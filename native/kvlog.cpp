// kvlog — append-only key/value log storage engine with crash recovery.
//
// The native storage core under the node's persistence layer (the role H2 +
// JDBCHashMap play in the reference: node/utilities/JDBCHashMap.kt,
// DBCheckpointStorage, DBTransactionStorage). Design:
//
//   - One append-only data file. Records: [u32 crc][u32 klen][u32 vlen]
//     [key][value]; vlen == 0xFFFFFFFF marks a tombstone (delete).
//   - The in-memory index (key -> offset,len) is owned by the Python side;
//     this engine exposes sequential scan for recovery plus append/read.
//   - Appends are synced (fdatasync) before returning — a record returned as
//     written survives a crash; torn tail records are detected by CRC and
//     truncated on recovery (the WAL discipline the reference gets from H2).
//
// C ABI for ctypes (no pybind11 dependency).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>

#include <fcntl.h>
#include <unistd.h>
#include <sys/stat.h>

namespace {

uint32_t crc32_table[256];
bool crc_ready = false;

void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc32_table[i] = c;
    }
    crc_ready = true;
}

uint32_t crc32(const uint8_t* data, size_t n, uint32_t seed = 0) {
    if (!crc_ready) crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
    for (size_t i = 0; i < n; i++)
        c = crc32_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

struct KvLog {
    int fd = -1;
    uint64_t size = 0;   // logical end (past last valid record)
};

constexpr uint32_t TOMBSTONE = 0xFFFFFFFFu;

void put_u32(uint8_t* p, uint32_t v) {
    p[0] = uint8_t(v >> 24); p[1] = uint8_t(v >> 16);
    p[2] = uint8_t(v >> 8);  p[3] = uint8_t(v);
}

uint32_t get_u32(const uint8_t* p) {
    return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
           (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

}  // namespace

extern "C" {

// Open (creating if needed). Returns handle or null.
KvLog* kvlog_open(const char* path) {
    int fd = ::open(path, O_RDWR | O_CREAT, 0644);
    if (fd < 0) return nullptr;
    auto* log = new KvLog();
    log->fd = fd;
    struct stat st{};
    if (fstat(fd, &st) == 0) log->size = uint64_t(st.st_size);
    return log;
}

void kvlog_close(KvLog* log) {
    if (!log) return;
    if (log->fd >= 0) ::close(log->fd);
    delete log;
}

// Append one record; returns the record's offset, or -1 on error.
// vlen == TOMBSTONE (pass tombstone=1, value ignored) marks deletion.
int64_t kvlog_append(KvLog* log, const uint8_t* key, uint32_t klen,
                     const uint8_t* value, uint32_t vlen, int tombstone) {
    if (!log || log->fd < 0) return -1;
    if (tombstone) vlen = TOMBSTONE;
    const uint32_t body_vlen = tombstone ? 0 : vlen;
    const uint64_t total = 12ull + klen + body_vlen;
    uint8_t* buf = static_cast<uint8_t*>(malloc(total));
    if (!buf) return -1;
    put_u32(buf + 4, klen);
    put_u32(buf + 8, vlen);
    memcpy(buf + 12, key, klen);
    if (body_vlen) memcpy(buf + 12 + klen, value, body_vlen);
    uint32_t crc = crc32(buf + 4, total - 4);
    put_u32(buf, crc);

    const int64_t offset = int64_t(log->size);
    uint64_t written = 0;
    while (written < total) {
        ssize_t n = pwrite(log->fd, buf + written, total - written,
                           off_t(log->size + written));
        if (n <= 0) { free(buf); return -1; }
        written += uint64_t(n);
    }
    free(buf);
    // Advance size BEFORE the sync: if the sync fails the record may or may
    // not be durable, so the offset must never be reused (a later append
    // overwriting it could resurrect-or-destroy ambiguously). -2 signals
    // "written but durability unknown" — callers must fail stop.
    log->size += total;
#if defined(__APPLE__)
    if (fsync(log->fd) != 0) return -2;
#else
    if (fdatasync(log->fd) != 0) return -2;
#endif
    return offset;
}

// Read the record at `offset`. Fills key/value lengths and copies up to the
// provided capacities. Returns: 1 = value record, 2 = tombstone, 0 = end/
// truncated-or-corrupt tail, -1 = error. `next_offset` receives the offset
// of the following record on success.
int kvlog_read_at(KvLog* log, int64_t offset,
                  uint8_t* key_buf, uint32_t key_cap, uint32_t* klen_out,
                  uint8_t* val_buf, uint32_t val_cap, uint32_t* vlen_out,
                  int64_t* next_offset) {
    if (!log || log->fd < 0 || offset < 0) return -1;
    if (uint64_t(offset) + 12 > log->size) return 0;
    uint8_t header[12];
    if (pread(log->fd, header, 12, off_t(offset)) != 12) return 0;
    const uint32_t crc = get_u32(header);
    const uint32_t klen = get_u32(header + 4);
    const uint32_t vlen = get_u32(header + 8);
    const bool tomb = (vlen == TOMBSTONE);
    const uint32_t body_vlen = tomb ? 0 : vlen;
    if (klen > (64u << 20) || body_vlen > (1u << 30)) return 0;
    const uint64_t total = 12ull + klen + body_vlen;
    if (uint64_t(offset) + total > log->size) return 0;

    uint8_t* body = static_cast<uint8_t*>(malloc(8 + klen + body_vlen));
    if (!body) return -1;
    memcpy(body, header + 4, 8);
    if (pread(log->fd, body + 8, klen + body_vlen,
              off_t(offset) + 12) != ssize_t(klen + body_vlen)) {
        free(body); return 0;
    }
    if (crc32(body, 8 + klen + body_vlen) != crc) { free(body); return 0; }

    *klen_out = klen;
    *vlen_out = body_vlen;
    if (key_cap < klen || (!tomb && val_cap < body_vlen)) {
        free(body);
        return -3;  // caller's buffers too small — never silently truncate
    }
    memcpy(key_buf, body + 8, klen);
    if (!tomb) memcpy(val_buf, body + 8 + klen, body_vlen);
    free(body);
    if (next_offset) *next_offset = offset + int64_t(total);
    return tomb ? 2 : 1;
}

// Truncate any torn tail found at `offset` (first invalid record position).
int kvlog_truncate(KvLog* log, int64_t offset) {
    if (!log || log->fd < 0 || offset < 0) return -1;
    if (ftruncate(log->fd, off_t(offset)) != 0) return -1;
    log->size = uint64_t(offset);
    return 0;
}

int64_t kvlog_size(KvLog* log) {
    return log ? int64_t(log->size) : -1;
}

}  // extern "C"
