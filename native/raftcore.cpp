// raftcore — the Raft protocol state machine as a native library.
//
// Reference parity: the role Copycat's core plays for the replicated notary
// commit log (RaftUniquenessProvider.kt:41,101-155). SURVEY.md §2's native
// plan calls for a C++ Raft; this is it: elections, log replication, the
// commit rule, and in-order apply are decided HERE, behind a C ABI. The
// Python layer (corda_tpu/consensus/raftcore.py) does transport and state-
// machine application, draining a typed action queue after every call.
//
// Log entries are opaque byte blobs (the canonical-codec bytes of the
// client triple), so the core is wire-compatible with the pure-Python
// RaftNode: mixed clusters replicate the same messages.
//
// Concurrency contract: calls are NOT thread-safe; the Python wrapper holds
// one lock around every entry point (matching RaftNode's coarse lock).

#include <cstdint>
#include <cstring>
#include <deque>
#include <string>
#include <vector>

namespace {

enum Role { FOLLOWER = 0, CANDIDATE = 1, LEADER = 2 };

enum ActionKind {
  ACT_NONE = 0,
  ACT_SEND_REQUEST_VOTE = 1,   // peer, a=term, b=last_idx, c=last_term
  ACT_SEND_VOTE_RESPONSE = 2,  // peer, a=term, flag=granted
  ACT_SEND_APPEND = 3,         // peer, a=term, b=prev_idx, c=prev_term,
                               // flag=leader_commit(lo32? no) -> c2 via data2
  ACT_SEND_APPEND_RESPONSE = 4,// peer, a=term, flag=success, b=match_index
  ACT_APPLY = 5,               // a=log index, data=blob
  ACT_BECAME_LEADER = 6,       // a=term
};

struct Entry {
  int64_t term;
  std::string blob;
};

struct Action {
  int32_t kind = ACT_NONE;
  int32_t peer = -1;
  int32_t flag = 0;
  int64_t a = 0, b = 0, c = 0, d = 0;
  std::string data;  // packed entries for APPEND, blob for APPLY
};

// Packed entry buffer: [u32 count] then per entry [i64 term][u32 len][bytes],
// all little-endian. Shared with the Python wrapper.
static std::string pack_entries(const std::vector<Entry>& log, size_t from) {
  std::string out;
  uint32_t count = static_cast<uint32_t>(log.size() - from);
  out.append(reinterpret_cast<const char*>(&count), 4);
  for (size_t i = from; i < log.size(); i++) {
    int64_t t = log[i].term;
    uint32_t len = static_cast<uint32_t>(log[i].blob.size());
    out.append(reinterpret_cast<const char*>(&t), 8);
    out.append(reinterpret_cast<const char*>(&len), 4);
    out.append(log[i].blob);
  }
  return out;
}

static bool unpack_entries(const uint8_t* buf, uint32_t len,
                           std::vector<Entry>* out) {
  if (len < 4) return false;
  uint32_t count;
  std::memcpy(&count, buf, 4);
  size_t off = 4;
  for (uint32_t i = 0; i < count; i++) {
    if (off + 12 > len) return false;
    Entry e;
    std::memcpy(&e.term, buf + off, 8);
    uint32_t blen;
    std::memcpy(&blen, buf + off + 8, 4);
    off += 12;
    if (off + blen > len) return false;
    e.blob.assign(reinterpret_cast<const char*>(buf + off), blen);
    off += blen;
    out->push_back(std::move(e));
  }
  return off == len;
}

struct Core {
  // configuration
  int32_t self;
  int32_t n;
  int32_t elec_min, elec_max, heartbeat;
  uint64_t rng;

  // persistent-equivalent state
  int64_t current_term = 0;
  int32_t voted_for = -1;
  std::vector<Entry> log;  // 1-based indexing via helpers

  // volatile state
  int32_t role = FOLLOWER;
  int32_t leader = -1;
  int64_t commit_index = 0;
  int64_t last_applied = 0;
  int64_t ticks = 0;
  int64_t election_deadline = 0;
  uint32_t votes = 0;  // bitmask of granted voters (n <= 32 replicas)
  std::vector<int64_t> next_index;
  std::vector<int64_t> match_index;

  std::deque<Action> outbox;
  Action current;  // storage for the action handed to the caller

  int64_t last_index() const { return static_cast<int64_t>(log.size()); }
  int64_t term_at(int64_t idx) const {
    return idx == 0 ? 0 : log[static_cast<size_t>(idx) - 1].term;
  }

  int64_t rand_range(int64_t lo, int64_t hi) {
    // xorshift64* — deterministic per seed, good enough for timeouts
    rng ^= rng >> 12; rng ^= rng << 25; rng ^= rng >> 27;
    uint64_t r = rng * 2685821657736338717ULL;
    return lo + static_cast<int64_t>(r % static_cast<uint64_t>(hi - lo + 1));
  }

  void reset_election_deadline() {
    election_deadline = rand_range(elec_min, elec_max);
  }

  void emit(Action a) { outbox.push_back(std::move(a)); }

  void observe_term(int64_t term) {
    if (term > current_term) {
      current_term = term;
      voted_for = -1;
      role = FOLLOWER;
      leader = -1;
    }
  }

  void send_append(int32_t peer) {
    int64_t next_i = next_index[peer];
    int64_t prev = next_i - 1;
    Action a;
    a.kind = ACT_SEND_APPEND;
    a.peer = peer;
    a.a = current_term;
    a.b = prev;
    a.c = term_at(prev);
    a.d = commit_index;
    a.data = pack_entries(log, static_cast<size_t>(prev));
    emit(std::move(a));
  }

  void broadcast_append() {
    for (int32_t p = 0; p < n; p++)
      if (p != self) send_append(p);
  }

  void start_election() {
    current_term += 1;
    role = CANDIDATE;
    voted_for = self;
    votes = 1u << self;
    reset_election_deadline();
    for (int32_t p = 0; p < n; p++) {
      if (p == self) continue;
      Action a;
      a.kind = ACT_SEND_REQUEST_VOTE;
      a.peer = p;
      a.a = current_term;
      a.b = last_index();
      a.c = term_at(last_index());
      emit(std::move(a));
    }
    maybe_win();
  }

  void maybe_win() {
    if (role != CANDIDATE) return;
    if (__builtin_popcount(votes) <= n / 2) return;
    role = LEADER;
    leader = self;
    next_index.assign(n, last_index() + 1);
    match_index.assign(n, 0);
    // current-term no-op (empty blob) lets the commit rule advance over
    // entries replicated in previous terms (Raft §5.4.2 liveness)
    log.push_back(Entry{current_term, std::string()});
    Action a;
    a.kind = ACT_BECAME_LEADER;
    a.a = current_term;
    emit(std::move(a));
    broadcast_append();
    maybe_commit();
  }

  void maybe_commit() {
    for (int64_t idx = last_index(); idx > commit_index; idx--) {
      if (term_at(idx) != current_term) break;  // §5.4.2 current-term rule
      int replicated = 1;
      for (int32_t p = 0; p < n; p++)
        if (p != self && match_index[p] >= idx) replicated++;
      if (replicated > n / 2) {
        commit_index = idx;
        break;
      }
    }
    apply_committed();
  }

  void apply_committed() {
    while (last_applied < commit_index) {
      last_applied += 1;
      const Entry& e = log[static_cast<size_t>(last_applied) - 1];
      if (e.blob.empty()) continue;  // leadership no-op
      Action a;
      a.kind = ACT_APPLY;
      a.a = last_applied;
      a.data = e.blob;
      emit(std::move(a));
    }
  }

  // -- entry points --------------------------------------------------------
  void tick() {
    ticks += 1;
    if (role == LEADER) {
      if (ticks % heartbeat == 0) broadcast_append();
      return;
    }
    election_deadline -= 1;
    if (election_deadline <= 0) start_election();
  }

  void submit(const uint8_t* blob, uint32_t len) {
    // leader-only (the wrapper checks role and forwards otherwise)
    if (role != LEADER) return;
    log.push_back(Entry{current_term,
                        std::string(reinterpret_cast<const char*>(blob), len)});
    broadcast_append();
    maybe_commit();  // single-node cluster commits immediately
  }

  void on_request_vote(int64_t term, int32_t candidate, int64_t last_idx,
                       int64_t last_term) {
    observe_term(term);
    bool up_to_date =
        last_term > term_at(last_index()) ||
        (last_term == term_at(last_index()) && last_idx >= last_index());
    bool grant = term == current_term && up_to_date &&
                 (voted_for == -1 || voted_for == candidate);
    if (grant) {
      voted_for = candidate;
      reset_election_deadline();
    }
    Action a;
    a.kind = ACT_SEND_VOTE_RESPONSE;
    a.peer = candidate;
    a.a = current_term;
    a.flag = grant ? 1 : 0;
    emit(std::move(a));
  }

  void on_vote_response(int64_t term, int32_t voter, int32_t granted) {
    observe_term(term);
    if (role == CANDIDATE && term == current_term && granted) {
      votes |= 1u << voter;
      maybe_win();
    }
  }

  void on_append(int64_t term, int32_t from_leader, int64_t prev_idx,
                 int64_t prev_term, const uint8_t* packed, uint32_t packed_len,
                 int64_t leader_commit) {
    observe_term(term);
    if (term < current_term) {
      Action a;
      a.kind = ACT_SEND_APPEND_RESPONSE;
      a.peer = from_leader;
      a.a = current_term;
      a.flag = 0;
      emit(std::move(a));
      return;
    }
    role = FOLLOWER;
    leader = from_leader;
    reset_election_deadline();
    // prev_idx < 0 never occurs from a correct leader; without the check a
    // hostile/malformed AppendEntries passes `prev_idx > last_index()` and
    // term_at() indexes the log out of bounds (ADVICE r1).
    bool fail = prev_idx < 0 || prev_idx > last_index() ||
                term_at(prev_idx) != prev_term;
    std::vector<Entry> entries;
    if (!fail) fail = !unpack_entries(packed, packed_len, &entries);
    if (fail) {
      Action a;
      a.kind = ACT_SEND_APPEND_RESPONSE;
      a.peer = from_leader;
      a.a = current_term;
      a.flag = 0;
      emit(std::move(a));
      return;
    }
    // Raft §5.3: truncate only from the first entry whose term conflicts
    // with the incoming one — a stale or duplicated append whose entries
    // all match the existing suffix must not discard later entries the
    // leader has already replicated past.
    size_t i = 0;
    int64_t idx = prev_idx + 1;
    for (; i < entries.size() && idx <= last_index(); i++, idx++) {
      if (term_at(idx) != entries[i].term) {
        log.resize(static_cast<size_t>(idx) - 1);
        break;
      }
    }
    for (; i < entries.size(); i++) log.push_back(std::move(entries[i]));
    if (leader_commit > commit_index) {
      // Raft: clamp to the last entry THIS append covered — with
      // conflict-only truncation an uncommitted divergent suffix may
      // extend past prev_idx + entries, and a stale/forged append must
      // not commit it
      int64_t covered = prev_idx + static_cast<int64_t>(entries.size());
      commit_index = std::min(leader_commit, covered);
    }
    apply_committed();
    Action a;
    a.kind = ACT_SEND_APPEND_RESPONSE;
    a.peer = from_leader;
    a.a = current_term;
    a.flag = 1;
    // match index = last entry THIS append verified, not last_index():
    // with conflict-only truncation the local log can extend past the
    // verified entries, and last_index() would let a batching leader
    // commit entries this follower does not hold (ADVICE r2)
    a.b = prev_idx + static_cast<int64_t>(entries.size());
    emit(std::move(a));
  }

  void on_append_response(int64_t term, int32_t follower, int32_t success,
                          int64_t match) {
    observe_term(term);
    if (role != LEADER || term != current_term) return;
    if (success) {
      // clamp: a forged/corrupt response with a huge match would drive
      // next_index past the log end and send_append's term_at(prev) out of
      // bounds — same hostile-input posture as on_append's prev_idx check
      if (match > last_index()) match = last_index();
      if (match < 0) match = 0;
      match_index[follower] = match;
      next_index[follower] = match + 1;
      maybe_commit();
    } else {
      next_index[follower] = std::max<int64_t>(1, next_index[follower] - 1);
      send_append(follower);
    }
  }
};

}  // namespace

extern "C" {

struct RaftActionView {
  int32_t kind;
  int32_t peer;
  int32_t flag;
  int64_t a, b, c, d;
  const uint8_t* data;
  uint32_t data_len;
};

void* raft_create(int32_t self, int32_t n, int32_t elec_min, int32_t elec_max,
                  int32_t heartbeat, uint64_t seed) {
  if (n <= 0 || n > 32 || self < 0 || self >= n) return nullptr;
  Core* c = new Core();
  c->self = self;
  c->n = n;
  c->elec_min = elec_min;
  c->elec_max = elec_max;
  c->heartbeat = heartbeat;
  c->rng = seed ? seed : 0x9E3779B97F4A7C15ULL + static_cast<uint64_t>(self);
  c->next_index.assign(n, 1);
  c->match_index.assign(n, 0);
  c->reset_election_deadline();
  return c;
}

void raft_destroy(void* h) { delete static_cast<Core*>(h); }
void raft_tick(void* h) { static_cast<Core*>(h)->tick(); }

void raft_submit(void* h, const uint8_t* blob, uint32_t len) {
  static_cast<Core*>(h)->submit(blob, len);
}

void raft_request_vote(void* h, int64_t term, int32_t candidate,
                       int64_t last_idx, int64_t last_term) {
  static_cast<Core*>(h)->on_request_vote(term, candidate, last_idx, last_term);
}

void raft_vote_response(void* h, int64_t term, int32_t voter,
                        int32_t granted) {
  static_cast<Core*>(h)->on_vote_response(term, voter, granted);
}

void raft_append_entries(void* h, int64_t term, int32_t leader,
                         int64_t prev_idx, int64_t prev_term,
                         const uint8_t* packed, uint32_t packed_len,
                         int64_t leader_commit) {
  static_cast<Core*>(h)->on_append(term, leader, prev_idx, prev_term, packed,
                                   packed_len, leader_commit);
}

void raft_append_response(void* h, int64_t term, int32_t follower,
                          int32_t success, int64_t match) {
  static_cast<Core*>(h)->on_append_response(term, follower, success, match);
}

int32_t raft_role(void* h) { return static_cast<Core*>(h)->role; }
int32_t raft_leader(void* h) { return static_cast<Core*>(h)->leader; }
int64_t raft_term(void* h) { return static_cast<Core*>(h)->current_term; }
int64_t raft_commit_index(void* h) {
  return static_cast<Core*>(h)->commit_index;
}
int64_t raft_last_index(void* h) { return static_cast<Core*>(h)->last_index(); }

// Drain one action; returns 0 when the outbox is empty. The view's data
// pointer stays valid until the NEXT raft_* call on this handle.
int32_t raft_next_action(void* h, RaftActionView* out) {
  Core* c = static_cast<Core*>(h);
  if (c->outbox.empty()) return 0;
  c->current = std::move(c->outbox.front());
  c->outbox.pop_front();
  out->kind = c->current.kind;
  out->peer = c->current.peer;
  out->flag = c->current.flag;
  out->a = c->current.a;
  out->b = c->current.b;
  out->c = c->current.c;
  out->d = c->current.d;
  out->data = reinterpret_cast<const uint8_t*>(c->current.data.data());
  out->data_len = static_cast<uint32_t>(c->current.data.size());
  return 1;
}

}  // extern "C"
