// Batch scalar preparation for the signature-verification kernels.
//
// The TPU device kernels (corda_tpu/ops/{weierstrass,ed25519}.py) consume
// pre-derived scalars/window indices/limb arrays; deriving them per item in
// Python bigints was the service path's ceiling (~0.9s per 32k secp256k1
// batch, ~1.9s for Ed25519 — BASELINE.md round-4 close-out).  This module
// does the whole scalar layer in one C pass per batch:
//   - Barrett modular arithmetic over the fixed curve moduli
//   - Montgomery batch inversion (one Fermat modpow per BATCH)
//   - secp256k1 GLV decomposition (Babai rounding, exact quotients)
//   - window/digit extraction and u16 limb packing in the kernels' wire
//     layout (16 little-endian 16-bit limbs per 256-bit value)
//
// Reference seams covered: Crypto.kt:473-496 (per-signature doVerify host
// work), OutOfProcessTransactionVerifierService.kt:18-71 (the service
// batching path this feeds).  No reference code is used here: the reference
// delegates scalar math to BouncyCastle/i2p; this is a from-scratch
// implementation of SEC1 §4.1.4 / RFC 8032 host-side scalar derivation.
//
// All multi-word values are little-endian arrays of u64.  Build:
//   g++ -O2 -fPIC -std=c++17 -shared -o libscalarmath.so scalarmath.cpp
// Loaded via ctypes (corda_tpu/ops/scalarprep.py) with a pure-Python
// fallback when the .so is absent.

#include <cstdint>
#include <cstring>

typedef unsigned __int128 u128;
typedef uint64_t u64;
typedef uint32_t u32;
typedef uint16_t u16;
typedef uint8_t u8;

namespace {

// ---------------------------------------------------------------------------
// Generic little-endian multiword helpers
// ---------------------------------------------------------------------------

inline void mp_zero(u64* x, int n) { std::memset(x, 0, 8 * n); }

inline void mp_copy(u64* d, const u64* s, int n) { std::memcpy(d, s, 8 * n); }

inline int mp_cmp(const u64* a, const u64* b, int n) {
    for (int i = n - 1; i >= 0; --i) {
        if (a[i] != b[i]) return a[i] < b[i] ? -1 : 1;
    }
    return 0;
}

inline bool mp_is_zero(const u64* a, int n) {
    for (int i = 0; i < n; ++i) if (a[i]) return false;
    return true;
}

inline u64 mp_add(u64* out, const u64* a, const u64* b, int n) {
    u128 c = 0;
    for (int i = 0; i < n; ++i) {
        c += (u128)a[i] + b[i];
        out[i] = (u64)c;
        c >>= 64;
    }
    return (u64)c;
}

inline u64 mp_sub(u64* out, const u64* a, const u64* b, int n) {
    u128 borrow = 0;
    for (int i = 0; i < n; ++i) {
        u128 d = (u128)a[i] - b[i] - borrow;
        out[i] = (u64)d;
        borrow = (d >> 64) ? 1 : 0;
    }
    return (u64)borrow;
}

// out[na+nb] = a * b (schoolbook; out must not alias inputs)
inline void mp_mul(const u64* a, int na, const u64* b, int nb, u64* out) {
    mp_zero(out, na + nb);
    for (int i = 0; i < na; ++i) {
        u128 carry = 0;
        u64 ai = a[i];
        for (int j = 0; j < nb; ++j) {
            u128 t = (u128)ai * b[j] + out[i + j] + carry;
            out[i + j] = (u64)t;
            carry = t >> 64;
        }
        out[i + nb] = (u64)carry;
    }
}

// ---------------------------------------------------------------------------
// Barrett reduction context for a fixed 256-bit modulus (HAC 14.42, b=2^64,
// k=4).  mu = floor(2^512 / m) fits 5 words for every modulus here
// (all are >= 2^252 > 2^192).
// ---------------------------------------------------------------------------

struct Mod {
    u64 m[4];
    u64 m5[5];     // m zero-extended to 5 words (for the k+1-word compare)
    u64 mu[5];
    u64 half[4];   // floor(m / 2) (the ECDSA low-s bound)
};

// mu = floor(2^512 / m) by restoring bitwise division (one-time per modulus).
void mod_init(Mod* M, const u64 m[4]) {
    mp_copy(M->m, m, 4);
    mp_copy(M->m5, m, 4);
    M->m5[4] = 0;
    u64 rem[5] = {0, 0, 0, 0, 0};
    u64 q[5] = {0, 0, 0, 0, 0};
    for (int bit = 512; bit >= 0; --bit) {
        // rem = rem << 1 | (bit == 512)
        u64 carry = (bit == 512) ? 1 : 0;
        for (int i = 0; i < 5; ++i) {
            u64 nc = rem[i] >> 63;
            rem[i] = (rem[i] << 1) | carry;
            carry = nc;
        }
        if (mp_cmp(rem, M->m5, 5) >= 0) {
            mp_sub(rem, rem, M->m5, 5);
            if (bit < 320) q[bit / 64] |= 1ull << (bit % 64);
        }
    }
    mp_copy(M->mu, q, 5);
    for (int i = 3; i >= 0; --i) {
        M->half[i] = (m[i] >> 1) | (i < 3 ? (m[i + 1] & 1) << 63 : 0);
    }
}

// r = x mod m for x < 2^512 (8 words); optionally returns the exact
// quotient's low 4 words in q_out (caller guarantees quotient < 2^256).
void bar_divmod(const Mod* M, const u64 x[8], u64 r[4], u64 q_out[4]) {
    // q1 = floor(x / b^3): 5 words x[3..7]
    const u64* q1 = x + 3;
    u64 q2[10];
    mp_mul(q1, 5, M->mu, 5, q2);           // q1 * mu
    u64* q3 = q2 + 5;                       // floor(q2 / b^5): 5 words
    // r1 = x mod b^5
    u64 r1[5];
    mp_copy(r1, x, 5);
    // r2 = (q3 * m) mod b^5
    u64 r2full[9];
    mp_mul(q3, 5, M->m, 4, r2full);
    // r = (r1 - r2) mod b^5  (fixed-width wraparound is the HAC "+ b^{k+1}")
    u64 rr[5];
    mp_sub(rr, r1, r2full, 5);
    u64 extra = 0;
    while (mp_cmp(rr, M->m5, 5) >= 0) {
        mp_sub(rr, rr, M->m5, 5);
        ++extra;
    }
    mp_copy(r, rr, 4);
    if (q_out) {
        u64 ext[5] = {extra, 0, 0, 0, 0};
        u64 q5[5];
        mp_add(q5, q3, ext, 5);
        mp_copy(q_out, q5, 4);
    }
}

inline void mod_red(const Mod* M, const u64 x[8], u64 r[4]) {
    bar_divmod(M, x, r, nullptr);
}

inline void mod_mul(const Mod* M, const u64 a[4], const u64 b[4], u64 r[4]) {
    u64 t[8];
    mp_mul(a, 4, b, 4, t);
    mod_red(M, t, r);
}

// r = base^exp mod m (binary ladder over a 256-bit exponent; ~20us — used
// once per BATCH by the Montgomery inversion, never per item).
void mod_pow(const Mod* M, const u64 base[4], const u64 exp[4], u64 r[4]) {
    u64 acc[4] = {1, 0, 0, 0};
    u64 sq[4];
    mp_copy(sq, base, 4);
    for (int i = 0; i < 256; ++i) {
        if ((exp[i / 64] >> (i % 64)) & 1) mod_mul(M, acc, sq, acc);
        if (i < 255) mod_mul(M, sq, sq, sq);
    }
    mp_copy(r, acc, 4);
}

// In-place Montgomery batch inversion of n nonzero values mod M
// (exp = m - 2: Fermat).  scratch: n*4 words.
void batch_inv(const Mod* M, u64* vals, int64_t n, u64* scratch) {
    if (n == 0) return;
    u64 acc[4] = {1, 0, 0, 0};
    for (int64_t i = 0; i < n; ++i) {
        mod_mul(M, acc, vals + 4 * i, acc);
        mp_copy(scratch + 4 * i, acc, 4);
    }
    u64 exp[4], two[4] = {2, 0, 0, 0};
    mp_sub(exp, M->m, two, 4);
    u64 inv[4];
    mod_pow(M, acc, exp, inv);
    for (int64_t i = n - 1; i > 0; --i) {
        u64 vi[4];
        mp_copy(vi, vals + 4 * i, 4);
        mod_mul(M, inv, scratch + 4 * (i - 1), vals + 4 * i);
        mod_mul(M, inv, vi, inv);
    }
    mp_copy(vals, inv, 4);
}

// ---------------------------------------------------------------------------
// Curve constants
// ---------------------------------------------------------------------------

const u64 K1_P[4] = {0xFFFFFFFEFFFFFC2Full, 0xFFFFFFFFFFFFFFFFull,
                     0xFFFFFFFFFFFFFFFFull, 0xFFFFFFFFFFFFFFFFull};
const u64 K1_N[4] = {0xBFD25E8CD0364141ull, 0xBAAEDCE6AF48A03Bull,
                     0xFFFFFFFFFFFFFFFEull, 0xFFFFFFFFFFFFFFFFull};
const u64 K1_B[4] = {7, 0, 0, 0};
// GLV basis (ecmath.py:371-386): beta, a1, |b1|, a2, b2 = a1
const u64 K1_BETA[4] = {0xC1396C28719501EEull, 0x9CF0497512F58995ull,
                        0x6E64479EAC3434E9ull, 0x7AE96A2B657C0710ull};
const u64 GLV_A1[2] = {0xE86C90E49284EB15ull, 0x3086D221A7D46BCDull};
const u64 GLV_AB1[2] = {0x6F547FA90ABFE4C3ull, 0xE4437ED6010E8828ull};
const u64 GLV_A2[3] = {0x57C1108D9D44CFD8ull, 0x14CA50F7A8E2F3F6ull, 1};
// b2 = a1

const u64 R1_P[4] = {0xFFFFFFFFFFFFFFFFull, 0x00000000FFFFFFFFull,
                     0x0000000000000000ull, 0xFFFFFFFF00000001ull};
const u64 R1_N[4] = {0xF3B9CAC2FC632551ull, 0xBCE6FAADA7179E84ull,
                     0xFFFFFFFFFFFFFFFFull, 0xFFFFFFFF00000000ull};
const u64 R1_B[4] = {0x3BCE3C3E27D2604Bull, 0x651D06B0CC53B0F6ull,
                     0xB3EBBD55769886BCull, 0x5AC635D8AA3A93E7ull};

const u64 ED_P[4] = {0xFFFFFFFFFFFFFFEDull, 0xFFFFFFFFFFFFFFFFull,
                     0xFFFFFFFFFFFFFFFFull, 0x7FFFFFFFFFFFFFFFull};
const u64 ED_L[4] = {0x5812631A5CF5D3EDull, 0x14DEF9DEA2F79CD6ull,
                     0x0000000000000000ull, 0x1000000000000000ull};

struct Ctx {
    Mod k1n, k1p, r1n, r1p, edl, edp;
    Ctx() {
        mod_init(&k1n, K1_N);
        mod_init(&k1p, K1_P);
        mod_init(&r1n, R1_N);
        mod_init(&r1p, R1_P);
        mod_init(&edl, ED_L);
        mod_init(&edp, ED_P);
    }
};

// C++11 magic static: thread-safe one-time construction (the batcher and
// OOP verifier call in from worker threads concurrently).
Ctx& ctx() {
    static Ctx c;
    return c;
}

// ---------------------------------------------------------------------------
// Per-curve helpers
// ---------------------------------------------------------------------------

inline void mod_neg(const Mod* P, const u64 y[4], u64 out[4]) {
    if (mp_is_zero(y, 4)) { mp_zero(out, 4); return; }
    mp_sub(out, P->m, y, 4);
}

// y^2 == x^3 + a*x + b (mod p) with a = 0 (k1) or a = -3 (r1).  The sum
// x^3 + (-3x mod p) + b runs in 5-word arithmetic (it can exceed 2^256)
// with trailing conditional subtractions — no Barrett needed.
bool on_curve(const Mod* P, const u64 x[4], const u64 y[4], const u64 b[4],
              bool a_minus3) {
    if (mp_cmp(x, P->m, 4) >= 0 || mp_cmp(y, P->m, 4) >= 0) return false;
    u64 y2[4], x2[4], x3[4];
    mod_mul(P, y, y, y2);
    mod_mul(P, x, x, x2);
    mod_mul(P, x2, x, x3);
    u64 acc[5], t5[5];
    mp_copy(acc, x3, 4);
    acc[4] = 0;
    mp_copy(t5, b, 4);
    t5[4] = 0;
    mp_add(acc, acc, t5, 5);
    if (a_minus3) {
        // acc += (p - (3x mod p))
        u64 three = 3, tx[5];
        mp_mul(x, 4, &three, 1, tx);
        while (mp_cmp(tx, P->m5, 5) >= 0) mp_sub(tx, tx, P->m5, 5);
        u64 negt[4];
        mod_neg(P, tx, negt);
        mp_copy(t5, negt, 4);
        t5[4] = 0;
        mp_add(acc, acc, t5, 5);
    }
    while (mp_cmp(acc, P->m5, 5) >= 0) mp_sub(acc, acc, P->m5, 5);
    return mp_cmp(y2, acc, 4) == 0;
}

// Signed GLV decomposition of k (mod n): k = k1 + k2*lambda, |k1|,|k2|<2^128.
// Mirrors ecmath.glv_decompose exactly (Babai rounding with n/2 bias).
// Returns false if a half ever exceeds 128 bits (mathematically impossible
// for k < n — a false return means an arithmetic bug, not bad input).
bool glv_split(const Ctx& C, const u64 k[4],
               bool* neg1, u64 abs1[2], bool* neg2, u64 abs2[2]) {
    const Mod* N = &C.k1n;
    // c1 = floor((b2*k + n/2) / n); b2 = a1 (2 words)
    u64 t6[6], t8[8];
    mp_mul(GLV_A1, 2, k, 4, t6);
    mp_copy(t8, t6, 6);
    t8[6] = t8[7] = 0;
    u64 nh5[8];
    mp_copy(nh5, N->half, 4);
    nh5[4] = nh5[5] = nh5[6] = nh5[7] = 0;
    mp_add(t8, t8, nh5, 8);
    u64 c1[4], rdump[4];
    bar_divmod(N, t8, rdump, c1);
    // c2 = floor((|b1|*k + n/2) / n)
    mp_mul(GLV_AB1, 2, k, 4, t6);
    mp_copy(t8, t6, 6);
    t8[6] = t8[7] = 0;
    mp_add(t8, t8, nh5, 8);
    u64 c2[4];
    bar_divmod(N, t8, rdump, c2);
    // k1 = k - c1*a1 - c2*a2  (plain integers; |k1| < 2^128)
    u64 s1[6], s2[6], S[6];
    mp_mul(c1, 2, GLV_A1, 2, s1);            // 4 words
    s1[4] = s1[5] = 0;
    mp_mul(c2, 2, GLV_A2, 3, s2);            // 5 words
    s2[5] = 0;
    mp_add(S, s1, s2, 6);
    u64 k6[6];
    mp_copy(k6, k, 4);
    k6[4] = k6[5] = 0;
    u64 d[6];
    if (mp_cmp(k6, S, 6) >= 0) {
        mp_sub(d, k6, S, 6);
        *neg1 = false;
    } else {
        mp_sub(d, S, k6, 6);
        *neg1 = true;
    }
    abs1[0] = d[0];
    abs1[1] = d[1];
    bool fit = !(d[2] | d[3] | d[4] | d[5]);
    // k2 = c1*|b1| - c2*b2 ; b2 = a1
    u64 p1[4], p2[4];
    mp_mul(c1, 2, GLV_AB1, 2, p1);
    mp_mul(c2, 2, GLV_A1, 2, p2);
    u64 d2[4];
    if (mp_cmp(p1, p2, 4) >= 0) {
        mp_sub(d2, p1, p2, 4);
        *neg2 = false;
    } else {
        mp_sub(d2, p2, p1, 4);
        *neg2 = true;
    }
    abs2[0] = d2[0];
    abs2[1] = d2[1];
    return fit && !(d2[2] | d2[3]);
}

// u64[4] LE value -> 16 LE u16 limbs (the kernels' wire limb format).
inline void write_limbs(u16* out, const u64 v[4]) {
    std::memcpy(out, v, 32);      // little-endian host: exact reinterpret
}

// ---------------------------------------------------------------------------
// secp256r1 half-gcd split (Antipa et al., "Accelerated Verification of
// ECDSA Signatures", SAC 2005): extended Euclid on (n, k) stopped at the
// first remainder below 2^128, giving k = v1/v2 (mod n) with both legs
// under 128 bits.  P-256 has no GLV endomorphism, so this is its only
// route to a half-length ladder.
// ---------------------------------------------------------------------------

inline int mp_bits(const u64* a, int n) {
    for (int i = n - 1; i >= 0; --i) {
        if (a[i]) return 64 * i + 64 - __builtin_clzll(a[i]);
    }
    return 0;
}

// out[nw] = a[na] << sh (caller guarantees the result fits nw words)
inline void mp_shl(u64* out, int nw, const u64* a, int na, int sh) {
    mp_zero(out, nw);
    int w = sh / 64, b = sh % 64;
    for (int i = na - 1; i >= 0; --i) {
        if (i + w < nw) out[i + w] |= a[i] << b;
        if (b && i + w + 1 < nw) out[i + w + 1] |= a[i] >> (64 - b);
    }
}

inline void mp_shr1(u64* a, int n) {
    for (int i = 0; i < n; ++i) {
        a[i] = (a[i] >> 1) | (i + 1 < n ? a[i + 1] << 63 : 0);
    }
}

// k (0 < k < n) → (neg1, v1, v2) with k*v2 ≡ (neg1 ? -v1 : v1) (mod n),
// 0 <= v1 < 2^128 and 0 < v2 < 2^128.  Signs in the EEA t-sequence strictly
// alternate, so only magnitudes are tracked (m_new = m0 + q*m1) with one
// parity bit; the invariant |t_i| <= n / r_{i-1} and the stop condition
// r_{i-1} >= 2^128 bound every magnitude strictly below 2^128 (a leg of
// exactly 2^128 is impossible).  A false return means the split degenerated
// (k = 0 / k >= n, or a defensive overflow check fired) — the caller routes
// such items to the host-oracle fallback.
bool r1_halfgcd(const u64 k[4], bool* neg1, u64 v1[2], u64 v2[2]) {
    if (mp_is_zero(k, 4) || mp_cmp(k, R1_N, 4) >= 0) return false;
    u64 r0[4], r1v[4], m0[4] = {0, 0, 0, 0}, m1[4] = {1, 0, 0, 0};
    mp_copy(r0, R1_N, 4);
    mp_copy(r1v, k, 4);
    bool s_pos = true;               // sign of the t attached to r1v
    while (r1v[2] | r1v[3]) {        // r1 >= 2^128
        // q = r0 / r1v, rem = r0 % r1v by shift-subtract: EEA quotients are
        // log-distributed, so total shift work across the loop is O(256)
        int d = mp_bits(r0, 4) - mp_bits(r1v, 4);
        u64 q[4] = {0, 0, 0, 0};
        u64 sh[5], rem[5];
        mp_shl(sh, 5, r1v, 4, d);
        mp_copy(rem, r0, 4);
        rem[4] = 0;
        for (int b = d; b >= 0; --b) {
            if (mp_cmp(rem, sh, 5) >= 0) {
                mp_sub(rem, rem, sh, 5);
                q[b / 64] |= 1ull << (b % 64);
            }
            mp_shr1(sh, 5);
        }
        mp_copy(r0, r1v, 4);
        mp_copy(r1v, rem, 4);
        u64 t8[8], m_new[4];
        mp_mul(q, 4, m1, 4, t8);
        u64 carry = mp_add(m_new, m0, t8, 4);
        if (carry || t8[4] | t8[5] | t8[6] | t8[7]) return false;
        mp_copy(m0, m1, 4);
        mp_copy(m1, m_new, 4);
        s_pos = !s_pos;
    }
    if (mp_is_zero(r1v, 4) || mp_is_zero(m1, 4)) return false;
    if (m1[2] | m1[3]) return false;
    v1[0] = r1v[0];
    v1[1] = r1v[1];
    v2[0] = m1[0];
    v2[1] = m1[1];
    // normalize v2 > 0: when t1 < 0, negate both legs and push the sign
    // onto v1 (applied to Q's y host-side)
    *neg1 = !s_pos;
    return true;
}

// ---------------------------------------------------------------------------
// Fast P-256 field arithmetic (FIPS 186-4 D.2.3 Solinas reduction) for the
// host-side [v2]R Jacobian ladder: the half-gcd prep runs ~1600 field mults
// per item here, where Barrett would triple the cost.
// ---------------------------------------------------------------------------

// r = t mod p256 for t < p^2 (8 words viewed as 16 u32 digits c0..c15).
void r1p_red(const u64 t[8], u64 r[4]) {
    u32 c[16];
    for (int i = 0; i < 8; ++i) {
        c[2 * i] = (u32)t[i];
        c[2 * i + 1] = (u32)(t[i] >> 32);
    }
    int64_t d[8];
    d[0] = (int64_t)c[0] + c[8] + c[9] - c[11] - c[12] - c[13] - c[14];
    d[1] = (int64_t)c[1] + c[9] + c[10] - c[12] - c[13] - c[14] - c[15];
    d[2] = (int64_t)c[2] + c[10] + c[11] - c[13] - c[14] - c[15];
    d[3] = (int64_t)c[3] + 2 * (int64_t)c[11] + 2 * (int64_t)c[12] + c[13]
         - c[15] - c[8] - c[9];
    d[4] = (int64_t)c[4] + 2 * (int64_t)c[12] + 2 * (int64_t)c[13] + c[14]
         - c[9] - c[10];
    d[5] = (int64_t)c[5] + 2 * (int64_t)c[13] + 2 * (int64_t)c[14] + c[15]
         - c[10] - c[11];
    d[6] = (int64_t)c[6] + c[13] + 3 * (int64_t)c[14] + 2 * (int64_t)c[15]
         - c[8] - c[9];
    d[7] = (int64_t)c[7] + c[8] + 3 * (int64_t)c[15] - c[10] - c[11]
         - c[12] - c[13];
    int64_t carry = 0;
    u32 out[8];
    for (int i = 0; i < 8; ++i) {
        int64_t v = d[i] + carry;
        out[i] = (u32)(v & 0xFFFFFFFFll);
        carry = v >> 32;             // arithmetic shift: floor division
    }
    u64 lo[4];
    for (int i = 0; i < 4; ++i) {
        lo[i] = (u64)out[2 * i] | ((u64)out[2 * i + 1] << 32);
    }
    // fold the signed end carry: value = lo + carry*2^256 and
    // 2^256 ≡ D (mod p) with D = 2^256 - p = 2^224 - 2^192 - 2^96 + 1;
    // each step trades one unit of carry for one add/sub of D (the loop
    // terminates within a few steps — |carry| <= 8 and wraps feed back
    // at most one unit)
    static const u64 D[4] = {0x0000000000000001ull, 0xFFFFFFFF00000000ull,
                             0xFFFFFFFFFFFFFFFFull, 0x00000000FFFFFFFEull};
    int guard = 0;
    while (carry != 0 && ++guard < 64) {
        if (carry > 0) {
            u64 ovf = mp_add(lo, lo, D, 4);
            carry += (int64_t)ovf - 1;
        } else {
            u64 brw = mp_sub(lo, lo, D, 4);
            carry += 1 - (int64_t)brw;
        }
    }
    while (mp_cmp(lo, R1_P, 4) >= 0) mp_sub(lo, lo, R1_P, 4);
    mp_copy(r, lo, 4);
}

// alias-safe (r may be a or b): the full product lands in t first
inline void r1p_mul(const u64 a[4], const u64 b[4], u64 r[4]) {
    u64 t[8];
    mp_mul(a, 4, b, 4, t);
    r1p_red(t, r);
}

inline void r1p_add(const u64 a[4], const u64 b[4], u64 r[4]) {
    u64 c = mp_add(r, a, b, 4);
    if (c || mp_cmp(r, R1_P, 4) >= 0) mp_sub(r, r, R1_P, 4);
}

inline void r1p_sub(const u64 a[4], const u64 b[4], u64 r[4]) {
    if (mp_sub(r, a, b, 4)) mp_add(r, r, R1_P, 4);
}

struct Jac { u64 X[4], Y[4], Z[4]; };

// o ← 2a, a = -3 (dbl-2001-b, 3M+5S); a must not be the identity.
// Alias-safe for o == a (every a-field is consumed before o is written).
void r1_jdbl(Jac* o, const Jac* a) {
    u64 delta[4], gamma[4], beta[4], alpha[4], t1[4], t2[4], m[4], yz[4];
    r1p_mul(a->Z, a->Z, delta);
    r1p_mul(a->Y, a->Y, gamma);
    r1p_mul(a->X, gamma, beta);
    r1p_sub(a->X, delta, t1);
    r1p_add(a->X, delta, t2);
    r1p_mul(t1, t2, m);
    r1p_add(m, m, alpha);
    r1p_add(alpha, m, alpha);        // alpha = 3(X-delta)(X+delta)
    r1p_add(a->Y, a->Z, yz);
    r1p_mul(yz, yz, yz);
    r1p_sub(yz, gamma, o->Z);        // Z3 = (Y+Z)^2 - gamma - delta
    r1p_sub(o->Z, delta, o->Z);
    u64 b8[4];
    r1p_add(beta, beta, b8);
    r1p_add(b8, b8, b8);
    r1p_add(b8, b8, b8);
    r1p_mul(alpha, alpha, t1);
    r1p_sub(t1, b8, o->X);           // X3 = alpha^2 - 8 beta
    u64 b4[4], g2[4];
    r1p_add(beta, beta, b4);
    r1p_add(b4, b4, b4);
    r1p_sub(b4, o->X, t2);
    r1p_mul(alpha, t2, t1);
    r1p_mul(gamma, gamma, g2);
    r1p_add(g2, g2, g2);
    r1p_add(g2, g2, g2);
    r1p_add(g2, g2, g2);
    r1p_sub(t1, g2, o->Y);           // Y3 = alpha(4 beta - X3) - 8 gamma^2
}

// o ← a + b (add-2007-bl, 11M+5S); both non-identity and a != ±b — the
// [v2]R ladder proves this structurally (see r1_mul_point).  Alias-safe
// for o == a.
void r1_jadd(Jac* o, const Jac* a, const Jac* b) {
    u64 z1z1[4], z2z2[4], u1[4], u2[4], s1[4], s2[4], t[4];
    r1p_mul(a->Z, a->Z, z1z1);
    r1p_mul(b->Z, b->Z, z2z2);
    r1p_mul(a->X, z2z2, u1);
    r1p_mul(b->X, z1z1, u2);
    r1p_mul(a->Y, b->Z, t);
    r1p_mul(t, z2z2, s1);
    r1p_mul(b->Y, a->Z, t);
    r1p_mul(t, z1z1, s2);
    u64 h[4], i_[4], j[4], rr_[4], v[4], zs[4];
    r1p_sub(u2, u1, h);
    r1p_add(h, h, t);
    r1p_mul(t, t, i_);               // I = (2H)^2
    r1p_mul(h, i_, j);
    r1p_sub(s2, s1, t);
    r1p_add(t, t, rr_);              // r = 2(S2 - S1)
    r1p_mul(u1, i_, v);
    r1p_add(a->Z, b->Z, zs);
    r1p_mul(zs, zs, zs);
    r1p_sub(zs, z1z1, zs);
    r1p_sub(zs, z2z2, zs);
    r1p_mul(zs, h, o->Z);            // Z3 = ((Z1+Z2)^2 - Z1Z1 - Z2Z2) H
    u64 x3[4], sj[4];
    r1p_mul(rr_, rr_, x3);
    r1p_sub(x3, j, x3);
    r1p_sub(x3, v, x3);
    r1p_sub(x3, v, x3);              // X3 = r^2 - J - 2V
    r1p_sub(v, x3, t);
    r1p_mul(rr_, t, t);
    r1p_mul(s1, j, sj);
    r1p_add(sj, sj, sj);
    r1p_sub(t, sj, o->Y);            // Y3 = r(V - X3) - 2 S1 J
    mp_copy(o->X, x3, 4);
}

// D = [v2]R for affine R = (rx, ry), 0 < v2 < 2^128, via 4-bit fixed
// windows (124 dbl + ~29 add).  Writes Jacobian (X, Z) only — the caller
// does an x-only projective compare, so Y is never needed.  Exception-free:
// before every add the accumulator is [16·prefix]R with 0 < 16·prefix <
// 2^128 ≪ n and the table entry is [d]R with d <= 15 < 16·prefix, so the
// add operands can never be equal or inverse.
void r1_mul_point(const u64 rx[4], const u64 ry[4], const u64 v2[2],
                  u64 outX[4], u64 outZ[4]) {
    Jac T[16];
    mp_copy(T[1].X, rx, 4);
    mp_copy(T[1].Y, ry, 4);
    mp_zero(T[1].Z, 4);
    T[1].Z[0] = 1;
    r1_jdbl(&T[2], &T[1]);
    for (int i = 3; i < 16; ++i) {
        if (i & 1) r1_jadd(&T[i], &T[i - 1], &T[1]);
        else r1_jdbl(&T[i], &T[i / 2]);
    }
    Jac acc;
    bool started = false;
    for (int t = 0; t < 32; ++t) {
        int shift = 4 * (31 - t);
        int dig = (int)((v2[shift / 64] >> (shift % 64)) & 0xF);
        if (started) {
            r1_jdbl(&acc, &acc);
            r1_jdbl(&acc, &acc);
            r1_jdbl(&acc, &acc);
            r1_jdbl(&acc, &acc);
            if (dig) r1_jadd(&acc, &acc, &T[dig]);
        } else if (dig) {
            acc = T[dig];
            started = true;
        }
    }
    mp_copy(outX, acc.X, 4);         // v2 >= 1 ⇒ started
    mp_copy(outZ, acc.Z, 4);
}

// y = sqrt(z) mod p256 via z^((p+1)/4) (p ≡ 3 mod 4); false when z is a
// non-residue (r is then not a valid x-coordinate).
bool r1p_sqrt(const u64 z[4], u64 y[4]) {
    // (p+1)/4 = 2^254 - 2^222 + 2^190 + 2^94
    static const u64 EXP[4] = {0x0000000000000000ull, 0x0000000040000000ull,
                               0x4000000000000000ull, 0x3FFFFFFFC0000000ull};
    u64 acc[4] = {1, 0, 0, 0}, sq[4], chk[4];
    mp_copy(sq, z, 4);
    for (int i = 0; i < 256; ++i) {
        if ((EXP[i / 64] >> (i % 64)) & 1) r1p_mul(acc, sq, acc);
        if (i < 255) r1p_mul(sq, sq, sq);
    }
    r1p_mul(acc, acc, chk);
    if (mp_cmp(chk, z, 4) != 0) return false;
    mp_copy(y, acc, 4);
    return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// Exports
// ---------------------------------------------------------------------------

extern "C" {

int sm_version() { return 3; }

// Differential-test seam: r = a*b mod m for mod_id in
// {0: k1 n, 1: k1 p, 2: r1 n, 3: r1 p, 4: ed L, 5: ed P}.
int sm_mulmod(int mod_id, const u64* a, const u64* b, u64* r) {
    const Ctx& C = ctx();
    const Mod* tbl[6] = {&C.k1n, &C.k1p, &C.r1n, &C.r1p, &C.edl, &C.edp};
    if (mod_id < 0 || mod_id > 5) return -1;
    mod_mul(tbl[mod_id], a, b, r);
    return 0;
}

// Differential-test seam: r = x mod m for a 512-bit x (8 words).
int sm_mod512(int mod_id, const u64* x, u64* r) {
    const Ctx& C = ctx();
    const Mod* tbl[6] = {&C.k1n, &C.k1p, &C.r1n, &C.r1p, &C.edl, &C.edp};
    if (mod_id < 0 || mod_id > 5) return -1;
    mod_red(tbl[mod_id], x, r);
    return 0;
}

// Differential-test seam for the GLV split.
int sm_glv(const u64* k, u8* negs, u64* abs1, u64* abs2) {
    bool n1, n2;
    bool fit = glv_split(ctx(), k, &n1, abs1, &n2, abs2);
    negs[0] = n1;
    negs[1] = n2;
    return fit ? 0 : -2;
}

// secp256k1 hybrid-GLV prep (mirrors weierstrass.prepare_batch_hybrid_wide
// + _precheck_and_scalars for g_w = 8).  Inputs: e (raw SHA-256 as LE
// words), r, s, pub (x,y) — all (n, ...) row-major.  Outputs in the
// kernel's wire layout; returns 0.
int sm_k1_prep(int64_t n,
               const u64* e, const u64* rr, const u64* ss, const u64* pub,
               int32_t* g_idx,      // (16, n)
               u8* q_packed,        // (64, n)
               u16* qc_x, u16* qc_y, u16* qd_x, u16* qd_y,   // (n,16) each
               u16* r_limbs,        // (n, 16)
               u8* rn_ok, u8* precheck,
               u64* work)           // scratch: 3*n*4 words
{
    const Ctx& C = ctx();
    const Mod* N = &C.k1n;
    const Mod* P = &C.k1p;
    u64* sw = work;              // (n,4) s-values for batch inversion
    u64* scratch = work + 4 * n; // (n,4) prefix products
    u64* em = work + 8 * n;      // (n,4) e mod n
    // pass 1: validate + substitute
    for (int64_t i = 0; i < n; ++i) {
        const u64* r4 = rr + 4 * i;
        const u64* s4 = ss + 4 * i;
        const u64* x4 = pub + 8 * i;
        const u64* y4 = pub + 8 * i + 4;
        bool ok = !mp_is_zero(r4, 4) && mp_cmp(r4, N->m, 4) < 0
               && !mp_is_zero(s4, 4) && mp_cmp(s4, N->half, 4) <= 0
               && on_curve(P, x4, y4, K1_B, false);
        precheck[i] = ok ? 1 : 0;
        if (ok) {
            mp_copy(sw + 4 * i, s4, 4);
            // e mod n: e < 2^256 < 2n → one conditional subtract
            const u64* e4 = e + 4 * i;
            if (mp_cmp(e4, N->m, 4) >= 0) mp_sub(em + 4 * i, e4, N->m, 4);
            else mp_copy(em + 4 * i, e4, 4);
        } else {
            u64 one[4] = {1, 0, 0, 0};
            mp_copy(sw + 4 * i, one, 4);
            mp_zero(em + 4 * i, 4);
        }
    }
    batch_inv(N, sw, n, scratch);
    // pass 2: scalars, GLV, points, windows
    for (int64_t i = 0; i < n; ++i) {
        bool ok = precheck[i];
        u64 u1[4], u2[4];
        if (ok) {
            mod_mul(N, em + 4 * i, sw + 4 * i, u1);
            u64 rmod[4];
            mp_copy(rmod, rr + 4 * i, 4);   // valid ⇒ r < n already
            mod_mul(N, rmod, sw + 4 * i, u2);
        } else {
            mp_zero(u1, 4);
            mp_zero(u2, 4);
        }
        bool sa, sb, sc, sd;
        u64 aa[2], ab[2], ac[2], ad[2];
        if (!glv_split(C, u1, &sa, aa, &sb, ab)) return -2;
        if (!glv_split(C, u2, &sc, ac, &sd, ad)) return -2;
        // Q legs: Qc = (sign c applied to pub), Qd = (sign d applied to phi)
        u64 qx[4], qy[4], py[4], phix[4];
        if (ok) {
            mp_copy(qx, pub + 8 * i, 4);
            mp_copy(qy, pub + 8 * i + 4, 4);
        } else {
            // substitute G (matching the Python prep)
            const u64 GX[4] = {0x59F2815B16F81798ull, 0x029BFCDB2DCE28D9ull,
                               0x55A06295CE870B07ull, 0x79BE667EF9DCBBACull};
            const u64 GY[4] = {0x9C47D08FFB10D4B8ull, 0xFD17B448A6855419ull,
                               0x5DA4FBFC0E1108A8ull, 0x483ADA7726A3C465ull};
            mp_copy(qx, GX, 4);
            mp_copy(qy, GY, 4);
        }
        mod_mul(P, qx, K1_BETA, phix);
        // write Qc
        mp_copy(py, qy, 4);
        if (sc) mod_neg(P, qy, py);
        write_limbs(qc_x + 16 * i, qx);
        write_limbs(qc_y + 16 * i, py);
        // write Qd (phi point, sign d)
        mp_copy(py, qy, 4);
        if (sd) mod_neg(P, qy, py);
        write_limbs(qd_x + 16 * i, phix);
        write_limbs(qd_y + 16 * i, py);
        // G-leg gather indices: 16 outer windows of 8 bits, MSB-first
        u32 sbit = ((u32)(sa ? 1 : 0) << 16) | ((u32)(sb ? 1 : 0) << 17);
        for (int t = 0; t < 16; ++t) {
            int shift = 8 * (15 - t);
            u32 wa = (u32)((aa[shift / 64] >> (shift % 64)) & 0xFF);
            u32 wb = (u32)((ab[shift / 64] >> (shift % 64)) & 0xFF);
            g_idx[(int64_t)t * n + i] = (int32_t)(wa | (wb << 8) | sbit);
        }
        // Q-leg packed 2-bit joint digits, MSB-first (64 of them)
        for (int t = 0; t < 64; ++t) {
            int shift = 2 * (63 - t);
            u32 wc = (u32)((ac[shift / 64] >> (shift % 64)) & 3);
            u32 wd = (u32)((ad[shift / 64] >> (shift % 64)) & 3);
            q_packed[(int64_t)t * n + i] = (u8)(wc | (wd << 2));
        }
        // r candidates
        const u64* r4 = rr + 4 * i;
        u64 rw[4];
        if (ok) mp_copy(rw, r4, 4);
        else mp_zero(rw, 4);
        write_limbs(r_limbs + 16 * i, rw);
        u64 rn[4];
        u64 carry = mp_add(rn, rw, N->m, 4);
        rn_ok[i] = (!carry && mp_cmp(rn, P->m, 4) < 0) ? 1 : 0;
    }
    return 0;
}

// secp256r1 single-scalar windowed prep (mirrors
// weierstrass.prepare_batch_windowed_single for w = 16).
int sm_r1_prep(int64_t n,
               const u64* e, const u64* rr, const u64* ss, const u64* pub,
               int32_t* g_idx,      // (16, n): w=16 windows of u1
               u8* q_digits,        // (64, n): 4-bit digits of u2
               u16* q_x, u16* q_y,  // (n,16)
               u16* r_limbs, u8* rn_ok, u8* precheck,
               u64* work)           // scratch: 3*n*4 words
{
    const Ctx& C = ctx();
    const Mod* N = &C.r1n;
    const Mod* P = &C.r1p;
    u64* sw = work;
    u64* scratch = work + 4 * n;
    u64* em = work + 8 * n;
    for (int64_t i = 0; i < n; ++i) {
        const u64* r4 = rr + 4 * i;
        const u64* s4 = ss + 4 * i;
        const u64* x4 = pub + 8 * i;
        const u64* y4 = pub + 8 * i + 4;
        bool ok = !mp_is_zero(r4, 4) && mp_cmp(r4, N->m, 4) < 0
               && !mp_is_zero(s4, 4) && mp_cmp(s4, N->half, 4) <= 0
               && on_curve(P, x4, y4, R1_B, true);
        precheck[i] = ok ? 1 : 0;
        if (ok) {
            mp_copy(sw + 4 * i, s4, 4);
            const u64* e4 = e + 4 * i;
            if (mp_cmp(e4, N->m, 4) >= 0) mp_sub(em + 4 * i, e4, N->m, 4);
            else mp_copy(em + 4 * i, e4, 4);
        } else {
            u64 one[4] = {1, 0, 0, 0};
            mp_copy(sw + 4 * i, one, 4);
            mp_zero(em + 4 * i, 4);
        }
    }
    batch_inv(N, sw, n, scratch);
    const u64 R1GX[4] = {0xF4A13945D898C296ull, 0x77037D812DEB33A0ull,
                         0xF8BCE6E563A440F2ull, 0x6B17D1F2E12C4247ull};
    const u64 R1GY[4] = {0xCBB6406837BF51F5ull, 0x2BCE33576B315ECEull,
                         0x8EE7EB4A7C0F9E16ull, 0x4FE342E2FE1A7F9Bull};
    for (int64_t i = 0; i < n; ++i) {
        bool ok = precheck[i];
        u64 u1[4], u2[4];
        if (ok) {
            mod_mul(N, em + 4 * i, sw + 4 * i, u1);
            u64 rmod[4];
            mp_copy(rmod, rr + 4 * i, 4);
            mod_mul(N, rmod, sw + 4 * i, u2);
        } else {
            mp_zero(u1, 4);
            mp_zero(u2, 4);
        }
        u64 qx[4], qy[4];
        if (ok) {
            mp_copy(qx, pub + 8 * i, 4);
            mp_copy(qy, pub + 8 * i + 4, 4);
        } else {
            mp_copy(qx, R1GX, 4);
            mp_copy(qy, R1GY, 4);
        }
        write_limbs(q_x + 16 * i, qx);
        write_limbs(q_y + 16 * i, qy);
        for (int t = 0; t < 16; ++t) {
            int shift = 16 * (15 - t);
            g_idx[(int64_t)t * n + i] =
                (int32_t)((u1[shift / 64] >> (shift % 64)) & 0xFFFF);
        }
        for (int t = 0; t < 64; ++t) {
            int shift = 4 * (63 - t);
            q_digits[(int64_t)t * n + i] =
                (u8)((u2[shift / 64] >> (shift % 64)) & 0xF);
        }
        const u64* r4 = rr + 4 * i;
        u64 rw[4];
        if (ok) mp_copy(rw, r4, 4);
        else mp_zero(rw, 4);
        write_limbs(r_limbs + 16 * i, rw);
        u64 rn[4];
        u64 carry = mp_add(rn, rw, N->m, 4);
        rn_ok[i] = (!carry && mp_cmp(rn, P->m, 4) < 0) ? 1 : 0;
    }
    return 0;
}

// Differential-test seam for the half-gcd split: k (4 LE words, 0 < k < n)
// → neg1, v1, v2 (2 words each) with k*v2 ≡ (neg1 ? -v1 : v1) (mod n) and
// both legs < 2^128.  Returns -2 when the split degenerates.
int sm_r1_halfgcd(const u64* k, u8* neg1, u64* v1, u64* v2) {
    bool ng;
    if (!r1_halfgcd(k, &ng, v1, v2)) return -2;
    *neg1 = ng ? 1 : 0;
    return 0;
}

// Differential-test seam for the Solinas fast P-256 reduction used by the
// [v2]R ladder (vs sm_mulmod mod_id=3's Barrett path).  Inputs canonical.
int sm_r1p_mulfast(const u64* a, const u64* b, u64* r) {
    r1p_mul(a, b, r);
    return 0;
}

// secp256r1 half-gcd split prep (PR 3 fast path; mirrors
// weierstrass._prepare_r1_split_python bit-for-bit).  Per item:
//   u2 = v1/v2 (mod n), |v1|, v2 < 2^128  ⇒  the verify identity
//   [u1]G + [u2]Q = W  ⟺  [t]G + [v1']Q = [v2]W  with t = v2*u1 mod n.
// The device ladder computes W2 = [t_lo]G + [t_hi]G' + [v1']Q (G' =
// [2^128]G, 124 doublings) and accepts iff x(W2) == x([v2]R) projectively;
// x([v2]R) is computed HERE (decompress r — either parity works, x is
// parity-free — then a 4-bit Jacobian ladder, one batch inversion for the
// whole batch's affine x) and shipped as limbs.
//
// hg_ok[i] = 0 routes item i to the host-oracle fallback: r + n < p (the
// second x-candidate exists and the split compare can't see it), r not a
// valid x-coordinate (sqrt fails), or a defensive half-gcd bound check.
// Precheck failures keep hg_ok = 1: their verdict is already False and
// they get benign zero windows (W2 = identity ⇒ device False).
int sm_r1_prep_hg(int64_t n,
                  const u64* e, const u64* rr, const u64* ss, const u64* pub,
                  int32_t* g_idx,      // (16, n): row 2j = t_hi window j,
                                       //          row 2j+1 = t_lo window j
                  u8* q_digits,        // (32, n): 4-bit |v1| digits MSB-first
                  u16* q_x, u16* q_y,  // (n, 16) sign-adjusted Q
                  u16* xd_limbs,       // (n, 16) x([v2]R); 0 when hg_ok = 0
                  u8* hg_ok, u8* precheck,
                  u64* work)           // scratch: 5*n*4 words
{
    const Ctx& C = ctx();
    const Mod* N = &C.r1n;
    const Mod* P = &C.r1p;
    u64* sw = work;
    u64* scratch = work + 4 * n;
    u64* em = work + 8 * n;
    u64* Xd = work + 12 * n;
    u64* Zd = work + 16 * n;
    for (int64_t i = 0; i < n; ++i) {
        const u64* r4 = rr + 4 * i;
        const u64* s4 = ss + 4 * i;
        const u64* x4 = pub + 8 * i;
        const u64* y4 = pub + 8 * i + 4;
        bool ok = !mp_is_zero(r4, 4) && mp_cmp(r4, N->m, 4) < 0
               && !mp_is_zero(s4, 4) && mp_cmp(s4, N->half, 4) <= 0
               && on_curve(P, x4, y4, R1_B, true);
        precheck[i] = ok ? 1 : 0;
        if (ok) {
            mp_copy(sw + 4 * i, s4, 4);
            const u64* e4 = e + 4 * i;
            if (mp_cmp(e4, N->m, 4) >= 0) mp_sub(em + 4 * i, e4, N->m, 4);
            else mp_copy(em + 4 * i, e4, 4);
        } else {
            u64 one[4] = {1, 0, 0, 0};
            mp_copy(sw + 4 * i, one, 4);
            mp_zero(em + 4 * i, 4);
        }
    }
    batch_inv(N, sw, n, scratch);
    const u64 R1GX[4] = {0xF4A13945D898C296ull, 0x77037D812DEB33A0ull,
                         0xF8BCE6E563A440F2ull, 0x6B17D1F2E12C4247ull};
    const u64 R1GY[4] = {0xCBB6406837BF51F5ull, 0x2BCE33576B315ECEull,
                         0x8EE7EB4A7C0F9E16ull, 0x4FE342E2FE1A7F9Bull};
    for (int64_t i = 0; i < n; ++i) {
        bool ok = precheck[i];
        u64 u1[4], u2[4];
        if (ok) {
            mod_mul(N, em + 4 * i, sw + 4 * i, u1);
            u64 rmod[4];
            mp_copy(rmod, rr + 4 * i, 4);
            mod_mul(N, rmod, sw + 4 * i, u2);
        } else {
            mp_zero(u1, 4);
            mp_zero(u2, 4);
        }
        u64 qx[4], qy[4];
        if (ok) {
            mp_copy(qx, pub + 8 * i, 4);
            mp_copy(qy, pub + 8 * i + 4, 4);
        } else {
            mp_copy(qx, R1GX, 4);
            mp_copy(qy, R1GY, 4);
        }
        bool hg = true, neg1 = false;
        u64 v1[2] = {0, 0}, v2[2] = {0, 0}, tt[4] = {0, 0, 0, 0}, ry[4];
        if (ok) {
            hg = r1_halfgcd(u2, &neg1, v1, v2);
            if (hg) {
                u64 v24[4] = {v2[0], v2[1], 0, 0};
                mod_mul(N, v24, u1, tt);       // t = v2*u1 mod n
            }
            u64 rn[4];
            u64 carry = mp_add(rn, rr + 4 * i, N->m, 4);
            if (!carry && mp_cmp(rn, P->m, 4) < 0) hg = false;
            if (hg) {
                // decompress r: y^2 = r^3 - 3r + b (r < n < p is canonical)
                const u64* r4 = rr + 4 * i;
                u64 r2[4], r3[4], z[4];
                r1p_mul(r4, r4, r2);
                r1p_mul(r2, r4, r3);
                r1p_sub(r3, r4, z);
                r1p_sub(z, r4, z);
                r1p_sub(z, r4, z);
                r1p_add(z, R1_B, z);
                if (!r1p_sqrt(z, ry)) hg = false;
            }
        }
        bool emit = ok && hg;
        if (emit) {
            u64 xD[4], zD[4];
            r1_mul_point(rr + 4 * i, ry, v2, xD, zD);
            mp_copy(Xd + 4 * i, xD, 4);
            mp_copy(Zd + 4 * i, zD, 4);
        } else {
            mp_zero(Xd + 4 * i, 4);
            mp_zero(Zd + 4 * i, 4);
            Zd[4 * i] = 1;
        }
        hg_ok[i] = hg ? 1 : 0;
        for (int t = 0; t < 8; ++t) {
            int shift = 16 * (7 - t);
            u32 whi = emit
                ? (u32)((tt[2 + shift / 64] >> (shift % 64)) & 0xFFFF) : 0;
            u32 wlo = emit
                ? (u32)((tt[shift / 64] >> (shift % 64)) & 0xFFFF) : 0;
            g_idx[(int64_t)(2 * t) * n + i] = (int32_t)whi;
            g_idx[(int64_t)(2 * t + 1) * n + i] = (int32_t)wlo;
        }
        for (int t = 0; t < 32; ++t) {
            int shift = 4 * (31 - t);
            q_digits[(int64_t)t * n + i] = emit
                ? (u8)((v1[shift / 64] >> (shift % 64)) & 0xF) : 0;
        }
        u64 py[4];
        mp_copy(py, qy, 4);
        if (emit && neg1) mod_neg(P, qy, py);
        write_limbs(q_x + 16 * i, qx);
        write_limbs(q_y + 16 * i, py);
    }
    // one batch inversion for every item's affine x([v2]R)
    batch_inv(P, Zd, n, scratch);
    for (int64_t i = 0; i < n; ++i) {
        u64 zi2[4], xa[4];
        r1p_mul(Zd + 4 * i, Zd + 4 * i, zi2);
        r1p_mul(Xd + 4 * i, zi2, xa);
        write_limbs(xd_limbs + 16 * i, xa);
    }
    return 0;
}

// Ed25519 split-k scalar prep: s (wire LE), h (raw SHA-512 LE) →
// k = h mod L; windows for the split ladder (s_lo/s_hi w=16 constant-base
// windows, joint 2-bit (k_lo, k_hi) digits).  A-point handling (decompress,
// [2^128]A) stays in Python (per-signer cached).
int sm_ed_prep(int64_t n,
               const u64* h,        // (n, 8)
               const u64* ss,       // (n, 4)
               int32_t* b_idx,      // (8, n): w=16 windows of s_lo, MSB-first
               int32_t* b2_idx,     // (8, n): w=16 windows of s_hi
               u8* a_packed,        // (64, n): klo | khi<<2 2-bit digits
               u8* s_ok)            // (n,)
{
    const Mod* L = &ctx().edl;
    for (int64_t i = 0; i < n; ++i) {
        const u64* s4 = ss + 4 * i;
        bool ok = mp_cmp(s4, L->m, 4) < 0;
        s_ok[i] = ok ? 1 : 0;
        u64 s[4], k[4];
        if (ok) {
            mp_copy(s, s4, 4);
            mod_red(L, h + 8 * i, k);
        } else {
            mp_zero(s, 4);
            mp_zero(k, 4);
        }
        // s = s_lo + 2^128 s_hi; windows of 16 bits, MSB-first over 128 bits
        for (int t = 0; t < 8; ++t) {
            int shift = 16 * (7 - t);        // within the 128-bit half
            b_idx[(int64_t)t * n + i] =
                (int32_t)((s[shift / 64] >> (shift % 64)) & 0xFFFF);
            b2_idx[(int64_t)t * n + i] =
                (int32_t)((s[2 + shift / 64] >> (shift % 64)) & 0xFFFF);
        }
        for (int t = 0; t < 64; ++t) {
            int shift = 2 * (63 - t);
            u32 klo = (u32)((k[shift / 64] >> (shift % 64)) & 3);
            u32 khi = (u32)((k[2 + shift / 64] >> (shift % 64)) & 3);
            a_packed[(int64_t)t * n + i] = (u8)(klo | (khi << 2));
        }
    }
    return 0;
}

// Plain (non-split) Ed25519 prep for the legacy windowed kernel: w=16
// windows of full s, 2-bit digits of full k.
int sm_ed_prep_plain(int64_t n,
                     const u64* h, const u64* ss,
                     int32_t* b_idx,      // (16, n)
                     u8* a_digits,        // (128, n)
                     u8* s_ok)
{
    const Mod* L = &ctx().edl;
    for (int64_t i = 0; i < n; ++i) {
        const u64* s4 = ss + 4 * i;
        bool ok = mp_cmp(s4, L->m, 4) < 0;
        s_ok[i] = ok ? 1 : 0;
        u64 s[4], k[4];
        if (ok) {
            mp_copy(s, s4, 4);
            mod_red(L, h + 8 * i, k);
        } else {
            mp_zero(s, 4);
            mp_zero(k, 4);
        }
        for (int t = 0; t < 16; ++t) {
            int shift = 16 * (15 - t);
            b_idx[(int64_t)t * n + i] =
                (int32_t)((s[shift / 64] >> (shift % 64)) & 0xFFFF);
        }
        for (int t = 0; t < 128; ++t) {
            int shift = 2 * (127 - t);
            a_digits[(int64_t)t * n + i] =
                (u8)((k[shift / 64] >> (shift % 64)) & 3);
        }
    }
    return 0;
}

}  // extern "C"
