"""North-star benchmark: ECDSA-secp256k1 signature verifies/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is measured against single-threaded host-CPU verification via the
`cryptography` (OpenSSL) package — the stand-in for the reference's
single-threaded JVM `Crypto.doVerify` replay (BASELINE.md config 1; OpenSSL
is strictly faster than the JVM/BouncyCastle path, so this under-reports our
advantage rather than inflating it).
"""
from __future__ import annotations

import json
import pathlib
import time

import numpy as np

import jax

# Persistent compile cache: repeated driver runs skip the ladder compile.
jax.config.update("jax_compilation_cache_dir",
                  str(pathlib.Path(__file__).resolve().parent / ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from corda_tpu.core.crypto import ecmath
from corda_tpu.ops import weierstrass as wc_ops

BATCH = 32768  # throughput peaks near 32k (dispatch amortized; 64k regresses)
UNIQUE = 512    # distinct signatures (host signing is pure Python; tile up)
REPS = 3


def make_items(n: int):
    rng = np.random.default_rng(123)
    base = []
    for _ in range(min(n, UNIQUE)):
        priv = int.from_bytes(rng.bytes(32), "little") % (ecmath.SECP256K1.n - 1) + 1
        pub = ecmath.SECP256K1.mul(priv, ecmath.SECP256K1.g)
        msg = rng.bytes(64)
        r, s = ecmath.ecdsa_sign(ecmath.SECP256K1, priv, msg)
        base.append((priv, pub, msg, r, s))
    return (base * (n // len(base) + 1))[:n]


def host_baseline_rate(items) -> float:
    """Single-threaded OpenSSL ECDSA-secp256k1 verify rate (verifies/sec)."""
    try:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.hazmat.primitives.asymmetric.utils import (
            encode_dss_signature)
    except ImportError:
        return 2000.0  # documented JVM-order fallback (BASELINE.md)
    keys, sigs = [], []
    for priv, pub, msg, r, s in items:
        keys.append(ec.derive_private_key(priv, ec.SECP256K1()).public_key())
        sigs.append(encode_dss_signature(r, s))
    t0 = time.perf_counter()
    for (priv, pub, msg, r, s), key, der in zip(items, keys, sigs):
        key.verify(der, msg, ec.ECDSA(hashes.SHA256()))
    dt = time.perf_counter() - t0
    return len(items) / dt


def device_rate(items) -> float:
    import functools
    kitems = [(pub, msg, r, s) for _, pub, msg, r, s in items]
    *args, pre = wc_ops.prepare_batch_hybrid_wide(
        kitems, wc_ops.HYBRID_G_WINDOW)
    assert pre.all()
    fn = functools.partial(wc_ops._verify_kernel_hybrid_wide,
                           g_w=wc_ops.HYBRID_G_WINDOW)
    ok = np.asarray(fn(*args))  # compile + warm
    assert bool(ok.all()), "benchmark signatures must all verify"
    t0 = time.perf_counter()
    for _ in range(REPS):
        # the host copy is a hard sync: async dispatch through the device
        # tunnel makes block_until_ready alone under-measure
        ok = np.asarray(fn(*args))
    dt = time.perf_counter() - t0
    return len(items) * REPS / dt


def service_metrics(items):
    """The SERVICE-path numbers (VERDICT r2 #1b/c): verifies/s through the
    SignatureBatcher seam (host prep + device kernel + future resolution —
    what a node actually gets), and p50 latency @ batch=1 (the host-crossover
    path: a lone check must not pay the ~140 ms device dispatch floor)."""
    from corda_tpu.core.crypto.keys import PublicKey, sec1_compress
    from corda_tpu.core.crypto.schemes import ECDSA_SECP256K1_SHA256
    from corda_tpu.verifier.batcher import SignatureBatcher

    triples = [(PublicKey(ECDSA_SECP256K1_SHA256,
                          sec1_compress(ecmath.SECP256K1, pub)),
                ecmath.ecdsa_sig_to_der(r, s), msg)
               for _, pub, msg, r, s in items]
    batcher = SignatureBatcher()
    try:
        assert all(batcher.submit_group(triples).result(timeout=600))  # warm
        # continuous stream: all reps queued up front so the dispatcher's
        # pipeline overlaps batch N+1's host prep with batch N's device
        # round-trip (the service's steady-state shape)
        t0 = time.perf_counter()
        group_futures = [batcher.submit_group(triples) for _ in range(REPS)]
        for gf in group_futures:
            assert all(gf.result(timeout=600))
        service_rate = len(triples) * REPS / (time.perf_counter() - t0)
        latencies = []
        for i in range(41):
            key, der, msg = triples[i % len(triples)]
            t0 = time.perf_counter()
            assert batcher.submit(key, der, msg).result(timeout=60)
            latencies.append(time.perf_counter() - t0)
        p50_ms = sorted(latencies)[len(latencies) // 2] * 1000.0
        # mid-size-batch latency (VERDICT r3 weak #5): the band between the
        # host crossover (192) and dispatch-floor amortization (~8k) pays
        # the linger window plus the fixed device dispatch — report it so
        # the worst-case latency region is visible, not just batch=1
        # warm the 1k bucket first: its kernel compile must not pollute the
        # latency sample (nor trip the sample timeout on a cold cache)
        assert all(batcher.submit_group(triples[:1024]).result(timeout=900))
        mid = []
        for _ in range(9):
            t0 = time.perf_counter()
            assert all(batcher.submit_group(triples[:1024]).result(
                timeout=120))
            mid.append(time.perf_counter() - t0)
        p50_1k_ms = sorted(mid)[len(mid) // 2] * 1000.0
    finally:
        batcher.close()
    return service_rate, p50_ms, p50_1k_ms


def main() -> None:
    items = make_items(BATCH)
    dev = device_rate(items)
    service_rate, p50_ms, p50_1k_ms = service_metrics(items)
    host = host_baseline_rate(items[: min(128, BATCH)])
    print(json.dumps({
        "metric": "ecdsa_secp256k1_verifies_per_sec_per_chip",
        "value": round(dev, 1),
        "unit": "verifies/s",
        "vs_baseline": round(dev / host, 3),
        "service_path_verifies_per_sec": round(service_rate, 1),
        "tx_verify_p50_ms_batch1": round(p50_ms, 3),
        "tx_verify_p50_ms_batch1k": round(p50_1k_ms, 3),
        "host_baseline_verifies_per_sec": round(host, 1),
    }))


if __name__ == "__main__":
    main()
