"""North-star benchmark: signature verifies/sec/chip, all device schemes.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} plus
per-scheme keys.  The primary metric/value stays ECDSA-secp256k1 (the
driver's tracked series); the same artifact now carries the Ed25519 (the
reference's DEFAULT scheme, Crypto.kt:119,170) and secp256r1 kernel rates,
the Ed25519 and mixed-scheme service rates, and the p50 latencies —
VERDICT r4 asked that every scheme's number be driver-reproducible, not
BASELINE.md prose.

vs_baseline is measured against single-threaded host-CPU verification via
the `cryptography` (OpenSSL) package — the stand-in for the reference's
single-threaded JVM `Crypto.doVerify` replay (BASELINE.md config 1;
OpenSSL is strictly faster than the JVM/BouncyCastle path, so this
under-reports our advantage rather than inflating it).

Env knobs:
  CORDA_TPU_BENCH_N       batch size (default 32768; use 256 to smoke-test)
  CORDA_TPU_BENCH_UNIQUE  1 → sign a fully-unique batch (no tiling) for the
                          gather-locality A/B (VERDICT r4 weak #6); slow
                          (pure-Python signing), meant for one-off runs.
                          Covers every scheme incl. secp256r1 (make_items
                          takes the curve), so the half-gcd split path's
                          per-item windows/tables get the same A/B.

Flags:
  --smoke    tiny-batch wiring check: exercises the FULL service path
             (SignatureBatcher drain → per-scheme prep pool → resolve)
             on the host-crossover route only — every batch stays under
             ``host_crossover`` so no device kernel compiles, making it
             fast enough for a tier-1 CPU test (tests/test_bench_smoke.py).
             Kernel-rate fields are emitted as 0.0 and "smoke": true is
             added; every other JSON field keeps its shape.
  --ledger   end-to-end ledger scenario (observability/ledger_harness.py):
             open-loop finance flows (issue → pay → DvP settle) against a
             raft notary with the verifier service on the commit path;
             emits the LEDGER_r0*.json fields (committed_tx_per_sec,
             per-stage p50/p90/p99, SLO budget, chaos windows). The full
             shape arms the chaos windows; with --smoke it is the tiny
             CPU tier-1 shape, chaos off. Exactly-once / agreement /
             stitched-trace violations exit 1 as BENCH INVALID.
  --guard    regression gate (corda_tpu.tools.benchguard): after printing
             the artifact, check it against floors fit from the repo's
             BENCH_r*.json trajectory (best-so-far minus a documented
             tolerance) and exit 1 with a readable diff on a breach. With
             --smoke the gate degrades to a schema check (zeroed kernel
             rates carry no information), so `--smoke --guard` is CI-safe.
"""
from __future__ import annotations

import json
import os
import pathlib
import statistics
import sys
import time

import numpy as np

import jax

# Persistent compile cache: repeated driver runs skip the ladder compile.
jax.config.update("jax_compilation_cache_dir",
                  str(pathlib.Path(__file__).resolve().parent / ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from corda_tpu.core.crypto import ecmath
from corda_tpu.ops import ed25519 as ed_ops
from corda_tpu.ops import weierstrass as wc_ops

SMOKE = "--smoke" in sys.argv
GUARD = "--guard" in sys.argv
FLEET = "--fleet" in sys.argv
LEDGER = "--ledger" in sys.argv
SOAK = "--soak" in sys.argv
# smoke: small enough that every per-scheme drain stays below the batcher's
# host_crossover (192) even when REPS groups coalesce into one flush
BATCH = int(os.environ.get("CORDA_TPU_BENCH_N", 48 if SMOKE else 32768))
UNIQUE = (BATCH if os.environ.get("CORDA_TPU_BENCH_UNIQUE")
          else (16 if SMOKE else 512))
REPS = 1 if SMOKE else 3
SERVICE_RUNS = 1 if SMOKE else 3
                   # service numbers are medians of SERVICE_RUNS runs
                   # (tunnel variance is ±20%; BASELINE.md methodology note)


def _tile(base, n):
    return (base * (n // len(base) + 1))[:n]


def make_items(n: int, curve=None):
    """ECDSA items [(priv, pub, msg, r, s)]; UNIQUE distinct, tiled to n."""
    curve = curve or ecmath.SECP256K1
    rng = np.random.default_rng(123)
    base = []
    for _ in range(min(n, UNIQUE)):
        priv = int.from_bytes(rng.bytes(32), "little") % (curve.n - 1) + 1
        pub = curve.mul(priv, curve.g)
        msg = rng.bytes(64)
        r, s = ecmath.ecdsa_sign(curve, priv, msg)
        base.append((priv, pub, msg, r, s))
    return _tile(base, n)


def make_ed_items(n: int):
    """Ed25519 items [(pub32, sig64, msg)]."""
    rng = np.random.default_rng(321)
    base = []
    for _ in range(min(n, UNIQUE)):
        seed = rng.bytes(32)
        pub = ecmath.ed25519_public_key(seed)
        msg = rng.bytes(64)
        base.append((pub, ecmath.ed25519_sign(seed, msg), msg))
    return _tile(base, n)


def host_baseline_rate(items) -> float:
    """Single-threaded OpenSSL ECDSA-secp256k1 verify rate (verifies/sec)."""
    try:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.hazmat.primitives.asymmetric.utils import (
            encode_dss_signature)
    except ImportError:
        return 2000.0  # documented JVM-order fallback (BASELINE.md)
    keys, sigs = [], []
    for priv, pub, msg, r, s in items:
        keys.append(ec.derive_private_key(priv, ec.SECP256K1()).public_key())
        sigs.append(encode_dss_signature(r, s))
    t0 = time.perf_counter()
    for (priv, pub, msg, r, s), key, der in zip(items, keys, sigs):
        key.verify(der, msg, ec.ECDSA(hashes.SHA256()))
    dt = time.perf_counter() - t0
    return len(items) / dt


def _kernel_rate(prep_args, fn) -> float:
    ok = np.asarray(fn(*prep_args))  # compile + warm
    assert bool(ok.all()), "benchmark signatures must all verify"
    t0 = time.perf_counter()
    for _ in range(REPS):
        # the host copy is a hard sync: async dispatch through the device
        # tunnel makes block_until_ready alone under-measure
        ok = np.asarray(fn(*prep_args))
    dt = time.perf_counter() - t0
    return ok.shape[0] * REPS / dt


def device_rate(items) -> float:
    import functools
    kitems = [(pub, msg, r, s) for _, pub, msg, r, s in items]
    *args, pre = wc_ops.prepare_batch_hybrid_wide(
        kitems, wc_ops.HYBRID_G_WINDOW)
    assert np.asarray(pre).all()
    return _kernel_rate(args, functools.partial(
        wc_ops._verify_kernel_hybrid_wide, g_w=wc_ops.HYBRID_G_WINDOW))


#: Doublings per verify in the production r1 kernel: the half-gcd split
#: ladder runs 8 outer steps × 16 bits with step 0 peeled (128 − 4), vs
#: 252 for the retired full-width windowed ladder.
R1_DOUBLINGS_PER_OP = 124.0


def r1_device_rate(items) -> tuple[float, float]:
    """(verifies/s, halfgcd fallback %) for the r1 half-gcd split kernel.
    The benchmark corpus is honestly-signed, so the fallback rate should
    be 0.0 (r + n < p has ~2^-64 probability for honest r) — the field is
    emitted so a regression in the split prep shows up in the artifact."""
    import functools
    kitems = [(pub, msg, r, s) for _, pub, msg, r, s in items]
    wc_ops.r1_split_stats(reset=True)
    *args, pre, forced = wc_ops.prepare_batch_r1_split(
        ecmath.SECP256R1, kitems, wc_ops.R1_G_WINDOW)
    stats = wc_ops.r1_split_stats()
    fallback_pct = 100.0 * stats["fallback"] / max(1, stats["items"])
    assert np.asarray(pre).all() and not forced.any()
    rate = _kernel_rate(args, functools.partial(
        wc_ops._verify_kernel_r1_split, curve_name="secp256r1",
        w=wc_ops.R1_G_WINDOW))
    return rate, fallback_pct


def ed_device_rate(items) -> float:
    import functools
    *args, pre = ed_ops.prepare_batch_split(items, ed_ops.SPLIT_B_WINDOW)
    assert np.asarray(pre).all()
    return _kernel_rate(args, functools.partial(
        ed_ops._verify_kernel_split, w=ed_ops.SPLIT_B_WINDOW))


def _ecdsa_triples(items, curve, scheme):
    from corda_tpu.core.crypto.keys import PublicKey, sec1_compress
    return [(PublicKey(scheme, sec1_compress(curve, pub)),
             ecmath.ecdsa_sig_to_der(r, s), msg)
            for _, pub, msg, r, s in items]


def _k1_triples(items):
    from corda_tpu.core.crypto.schemes import ECDSA_SECP256K1_SHA256
    return _ecdsa_triples(items, ecmath.SECP256K1, ECDSA_SECP256K1_SHA256)


def _ed_triples(items):
    from corda_tpu.core.crypto.keys import PublicKey
    from corda_tpu.core.crypto.schemes import EDDSA_ED25519_SHA512
    return [(PublicKey(EDDSA_ED25519_SHA512, pub), sig, msg)
            for pub, sig, msg in items]


def _service_warm(batcher, triples) -> None:
    """Warm one stream at the SAME depth as the timed loop, plus every
    bucket-ladder rung the continuous planner can cut from it, so all
    shapes the timed loop will see compile HERE (fresh bucket kernels cost
    hundreds of seconds through the tunnel, persistent-cached afterwards).
    mark_warm() after all warms makes any later compile a counted
    regression (post_warmup_compiles)."""
    warm = [batcher.submit_group(triples) for _ in range(REPS)]
    for wf in warm:
        assert all(wf.result(timeout=3000))
    for rung in batcher._default_ladder:
        if rung >= len(triples):
            break
        assert all(batcher.submit_group(triples[:rung]).result(timeout=3000))


def _service_rate_for(batcher, triples) -> float:
    """Median continuous-stream rate over SERVICE_RUNS runs (all reps
    queued up front so batch N+1's host prep overlaps batch N's device
    round-trip — the service's steady-state shape). Streams must be warmed
    via _service_warm first."""
    rates = []
    for _ in range(SERVICE_RUNS):
        t0 = time.perf_counter()
        group_futures = [batcher.submit_group(triples) for _ in range(REPS)]
        for gf in group_futures:
            assert all(gf.result(timeout=600))
        rates.append(len(triples) * REPS / (time.perf_counter() - t0))
    return statistics.median(rates)


def _pctl(sorted_samples, q: float) -> float:
    """Nearest-rank percentile of an already-sorted sample list."""
    idx = min(len(sorted_samples) - 1, int(q * len(sorted_samples)))
    return sorted_samples[idx]


def service_metrics(k1_items, ed_items, r1_items) -> dict:
    """Service-path numbers through the SignatureBatcher seam (host prep +
    device kernel + future resolution — what a node actually gets): k1,
    ed25519, r1, and a mixed-scheme stream; p50 @ batch=1 and p50/p90/p99
    @ batch=1k (interactive class); the prep-overlap high-water mark; and
    the post-warmup compile count (zero when the bucket ladder kept the
    jit cache hot through the whole timed phase)."""
    from corda_tpu.core.crypto.schemes import ECDSA_SECP256R1_SHA256
    from corda_tpu.observability import get_profiler, stage_percentiles
    from corda_tpu.utils.metrics import MetricRegistry
    from corda_tpu.verifier.batcher import SignatureBatcher

    k1_triples = _k1_triples(k1_items)
    ed_triples = _ed_triples(ed_items)
    r1_full = _ecdsa_triples(r1_items, ecmath.SECP256R1,
                             ECDSA_SECP256R1_SHA256)
    n = len(k1_triples)
    # GeneratedLedger-style mix (BASELINE config 2 direction): the default
    # scheme dominates, k1 heavy, r1 present (VerifierTests.kt:37-100 uses
    # mixed generated ledgers as the verification corpus)
    mixed = (ed_triples[: int(0.45 * n)] + k1_triples[: int(0.45 * n)]
             + r1_full[: max(1, n - 2 * int(0.45 * n))])
    registry = MetricRegistry()
    # the kernel flight recorder's gauges/histograms ride the same snapshot
    prof = get_profiler()
    prof.publish(registry)
    batcher = SignatureBatcher(metrics=registry)
    sub = k1_triples[:1024]
    try:
        # warm EVERY stream (and the interactive 1k bucket + a single
        # submit) before the warmup boundary: after mark_warm() the timed
        # phase must run entirely on the hot jit cache — any compile past
        # this point counts in post_warmup_compiles
        for stream in (k1_triples, ed_triples, r1_full, mixed):
            _service_warm(batcher, stream)
        assert all(batcher.submit_group(
            sub, latency_class="interactive").result(timeout=900))
        key0, der0, msg0 = k1_triples[0]
        assert batcher.submit(key0, der0, msg0).result(timeout=900)
        prof.mark_warm()
        k1_rate = _service_rate_for(batcher, k1_triples)
        ed_rate = _service_rate_for(batcher, ed_triples)
        r1_rate = _service_rate_for(batcher, r1_full)
        mixed_rate = _service_rate_for(batcher, mixed)
        latencies = []
        for i in range(5 if SMOKE else 41):
            key, der, msg = k1_triples[i % len(k1_triples)]
            t0 = time.perf_counter()
            assert batcher.submit(key, der, msg).result(timeout=60)
            latencies.append(time.perf_counter() - t0)
        p50_ms = sorted(latencies)[len(latencies) // 2] * 1000.0
        # mid-size-batch latency (VERDICT r3 weak #5 / r4 #7): the band
        # between the host crossover (192) and dispatch-floor amortization
        # (~8k). Submitted as the INTERACTIVE class — the latency-bound
        # path a node's verify_signed actually rides — so these tails
        # measure the short-deadline flush, not the bulk linger.
        # (--smoke holds BATCH below the crossover, so `sub` stays on the
        # host route there — same submit shape, no kernel compile.)
        mid = []
        for _ in range(3 if SMOKE else 11):
            t0 = time.perf_counter()
            assert all(batcher.submit_group(
                sub, latency_class="interactive").result(timeout=120))
            mid.append(time.perf_counter() - t0)
        mid.sort()
        p50_1k_ms = mid[len(mid) // 2] * 1000.0
        p90_1k_ms = _pctl(mid, 0.90) * 1000.0
        p99_1k_ms = _pctl(mid, 0.99) * 1000.0
        # the numbers above are only device numbers if the device was
        # actually used: an open breaker means some batches silently took
        # the host path, which would corrupt the bench without failing it
        breakers = batcher.breaker_status()
        tripped = {s: st for s, st in breakers.items()
                   if st["state"] != "closed" or st["trips"]}
        if tripped:
            print(f"BENCH INVALID: device circuit breaker engaged during "
                  f"the run: {tripped}", file=sys.stderr)
            sys.exit(1)
    finally:
        batcher.close()
    # per-stage latency breakdown (prep / dispatch / finish percentiles)
    # from the batcher's histograms — where a verify's time actually went
    snap = registry.snapshot()
    stages = stage_percentiles(snap)
    overlap = snap.get("SigBatcher.PrepActive", {}).get("max", 0)
    return {
        "k1_rate": k1_rate, "ed_rate": ed_rate, "r1_rate": r1_rate,
        "mixed_rate": mixed_rate, "p50_ms": p50_ms, "p50_1k_ms": p50_1k_ms,
        "p90_1k_ms": p90_1k_ms, "p99_1k_ms": p99_1k_ms, "stages": stages,
        "overlap": overlap,
        "post_warmup_compiles": prof.compiles_since_warm(),
        "bucket_ladder": list(batcher._default_ladder),
        "interactive_latency_ms": batcher.interactive_latency_s * 1000.0,
        "interactive_batch": batcher.interactive_batch,
    }


def _fleet_http_probe() -> dict:
    """Smoke acceptance for the fleet observability plane, over REAL HTTP:
    serve a live 2-worker fleet through NodeWebServer and check that
    (a) /metrics carries at least one worker-labeled federated family,
    (b) /traces returns a stitched trace holding node-side AND worker-side
    spans for one request, and (c) /debug/requests has lifecycle timelines.
    Returns {"http_federated_families": int, "http_stitched_traces": int,
    "http_request_timelines": int}."""
    import urllib.request
    from corda_tpu.observability import Tracer, get_tracer, set_tracer
    from corda_tpu.tools.webserver import NodeWebServer
    from corda_tpu.verifier.fleet import InProcessFleet, make_sig_checks

    class FleetOps:
        """Minimal ops surface: just what the observability endpoints use."""
        def __init__(self, fleet):
            self._fleet = fleet

        def metrics_snapshot(self):
            return self._fleet.metrics.snapshot()

        def fleet_status(self):
            return self._fleet.service.fleet_status()

        def request_timelines(self, limit=None):
            return self._fleet.service.request_log.snapshot(limit=limit)

    prev_tracer = get_tracer()
    set_tracer(Tracer(capacity=4096))
    fleet = InProcessFleet(2, use_device=False)
    web = NodeWebServer(FleetOps(fleet)).start()
    try:
        checks = make_sig_checks(16)
        for f in [fleet.verify_signatures(checks) for _ in range(8)]:
            f.result(timeout=120)
        time.sleep(0.05)   # let the pump deliver the next load reports

        def fetch(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{web.port}{path}", timeout=10) as r:
                return r.read().decode()

        metrics_text = fetch("/metrics")
        federated = {line.split("{", 1)[0] for line in metrics_text.splitlines()
                     if 'worker="' in line and not line.startswith("#")}
        traces = json.loads(fetch("/traces")).get("traces", {})
        stitched = 0
        for spans in traces.values():
            names = [s.get("name", "") for s in spans]
            if ("verifier.oop_submit" in names
                    and any(n.startswith("worker.") for n in names)):
                stitched += 1
        timelines = json.loads(fetch("/debug/requests"))["requests"]
        return {"http_federated_families": len(federated),
                "http_stitched_traces": stitched,
                "http_request_timelines": len(timelines)}
    finally:
        web.stop()
        fleet.close()
        set_tracer(prev_tracer)


def fleet_main() -> None:
    """--fleet: the multi-worker topology bench (corda_tpu.verifier.fleet).
    Smoke: 2 in-process host-route workers, no kernel compiles — a tier-1
    wiring check that the router deals to BOTH workers, every future
    resolves, and (via a real HTTP probe) the observability plane
    federates worker metrics and stitches cross-process traces. Full: one
    device-pinned worker per local chip (the MULTICHIP stage runs the same
    thing through __graft_entry__.dryrun_multichip)."""
    from corda_tpu.verifier.fleet import fleet_bench, kill_storm_recovery
    if SMOKE:
        out = fleet_bench(2, groups=24, group_size=16, use_device=False)
        out["smoke"] = True
        out.update(_fleet_http_probe())
    else:
        import jax
        devices = jax.devices()
        n = min(8, len(devices))
        out = fleet_bench(n, groups=32 * n, group_size=256,
                          use_device=True, devices=devices[:n],
                          host_crossover=0)
        # full runs also prove self-healing: a seeded kill-storm (host
        # path — the controller seams are device-agnostic) whose measured
        # recovery time becomes the artifact's recovery_s
        storm = kill_storm_recovery(seed=7)
        out["kill_storm"] = storm
        out["recovery_s"] = storm["recovery_s"] or 0.0
        out["controller_actions"] = storm["controller_actions"]
    out["fleet"] = True
    problems = []
    if SMOKE:
        # an unstressed run must leave the controller idle: state steady,
        # zero actions, nothing to recover from (benchguard schema-locked)
        if out.get("controller_state") != "steady":
            problems.append(f"controller_state={out.get('controller_state')!r}"
                            f" on an unstressed run (want 'steady')")
        if out.get("controller_actions") != 0:
            problems.append(f"controller_actions={out.get('controller_actions')}"
                            f" on an unstressed run (want 0)")
    else:
        storm = out["kill_storm"]
        if storm["lost_futures"]:
            problems.append(f"kill-storm lost {storm['lost_futures']} futures")
        if not storm["recovered_within_bound"]:
            problems.append(
                f"kill-storm recovery {storm['recovery_s']}s exceeded the "
                f"error-budget bound {storm['recovery_bound_s']}s "
                f"(state {storm['controller_state']})")
    if out["n_workers"] != (2 if SMOKE else max(1, out["n_workers"])):
        problems.append(f"n_workers={out['n_workers']}: fleet did not spawn")
    idle = [w for w, c in out["per_worker_sigs"].items() if c <= 0]
    if idle:
        problems.append(f"workers {idle} processed nothing: the router "
                        f"never dealt to them")
    if out["stitched_trace_depth"] < 2:
        problems.append(f"stitched_trace_depth="
                        f"{out['stitched_trace_depth']}: no trace crossed "
                        f"the node/worker seam")
    if SMOKE:
        if out["http_federated_families"] < 1:
            problems.append("no worker-labeled federated family on /metrics")
        if out["http_stitched_traces"] < 1:
            problems.append("no stitched cross-process trace on /traces")
        if out["http_request_timelines"] < 1:
            problems.append("no request lifecycle timelines on "
                            "/debug/requests")
    print(json.dumps(out))
    if problems:
        for p in problems:
            print(f"BENCH INVALID: {p}", file=sys.stderr)
        sys.exit(1)
    if GUARD:
        from corda_tpu.tools.benchguard import guard_multichip
        failures = guard_multichip(out)
        if failures:
            print("BENCH REGRESSION: fleet metrics breached their "
                  "trajectory floors:", file=sys.stderr)
            for p in failures:
                print(f"  {p}", file=sys.stderr)
            sys.exit(1)
        print("benchguard: ok", file=sys.stderr)


def ledger_main() -> None:
    """--ledger: the end-to-end ledger scenario (ISSUE 10): open-loop
    finance flows against the raft notary with the TPU verifier on the
    commit path. Smoke: tiny workload, chaos off, every signature batch
    under the host crossover — CPU tier-1 safe. Full: the measured shape
    with the chaos windows armed. Emits the LEDGER_r0*.json fields; the
    exactly-once and replica-agreement invariants are validity probes
    (BENCH INVALID), not guarded floors — a run that double-spends is
    wrong, not slow."""
    from corda_tpu.observability.ledger_harness import (
        LedgerScenarioConfig, ShardSweepConfig, run_ledger_scenario,
        run_shard_sweep_point, shard_scaling_fields)

    # --shards [N[,M...]] — the shard counts to sweep for the scaling
    # curve (default 1,2 smoke / 1,2,4 full; bare --shards keeps the
    # default).
    shard_counts = [1, 2] if SMOKE else [1, 2, 4]
    if "--shards" in sys.argv:
        i = sys.argv.index("--shards")
        if i + 1 < len(sys.argv) and not sys.argv[i + 1].startswith("-"):
            shard_counts = sorted({int(x) for x in
                                   sys.argv[i + 1].split(",") if x})
    top_shards = max(shard_counts)
    if SMOKE:
        # 2-shard CPU shape: tier-1 exercises the sharded provider +
        # cross-shard 2PC on every run (ISSUE 15 satellite). Small
        # compaction thresholds so every smoke run also proves the
        # bounded-log sawtooth and CoordinatorLog GC (ISSUE 20).
        cfg = LedgerScenarioConfig(shards=min(2, top_shards),
                                   cross_shard_pct=0.25,
                                   raft_snapshot_entries=4,
                                   coordlog_compact_bytes=1024)
    else:
        # The full flows scenario stays UNSHARDED: its fields carry
        # best-so-far floors fitted from the r01..r03 single-group
        # trajectory, and a sharded topology is a different workload
        # (smaller per-shard batches raise appends/tx by construction) —
        # comparing it against those floors would be guarding apples with
        # orange floors. Sharded end-to-end flows coverage lives in the
        # smoke shape (every tier-1 run), the scenario-tool preset, and
        # tests/test_chaos_sharded_notary.py; the sweep below is the
        # measured scaling story.
        cfg = LedgerScenarioConfig.full(chaos=True)
    out = run_ledger_scenario(cfg)
    out.pop("trace_sample", None)   # test hook, not an artifact field
    out["ledger"] = True
    out["sharded"] = True
    if SMOKE:
        out["smoke"] = True

    # the measured tx/s-vs-shards curve: notary-tier saturation per count
    # (the flows number above stays the headline committed_tx_per_sec so
    # the LEDGER trajectory remains comparable across rounds)
    points = []
    for n in shard_counts:
        sweep_cfg = ShardSweepConfig(
            shards=n, operations=220 if SMOKE else 1600,
            rate_tx_per_sec=600.0 if SMOKE else 1500.0,
            cross_shard_pct=0.08, chaos=(not SMOKE),
            seed=cfg.seed)
        points.append(run_shard_sweep_point(sweep_cfg))
    out.update(shard_scaling_fields(points))
    print(json.dumps(out))
    problems = []
    if not out["exactly_once_ok"]:
        problems.append("exactly-once violated: an accepted transaction's "
                        "inputs are not all consumed by that transaction "
                        "on every replica")
    if not out["replicas_agree"]:
        problems.append("raft replicas diverged at quiescence")
    if not out["counter_invariant_ok"]:
        problems.append("commit counters do not reconcile: committed != "
                        "notarised + self-issue (a committed tx either "
                        "passed the notary or had no inputs to check)")
    if out["stitched_traces"] < 1:
        problems.append("no connected flow.run→vault.update trace "
                        "(commit-path span stitching broken)")
    if out["ops_committed"] <= 0:
        problems.append("no operation committed")
    # blame conservation: the critical-path decomposition must account
    # for each class's e2e (runs under smoke too — the smoke gate is the
    # only CPU-tier proof the extractor still covers the whole path)
    from corda_tpu.tools.benchguard import ledger_critpath_violations
    problems.extend(ledger_critpath_violations(out))
    if out["stitched_traces"] >= 1 and out.get("ledger_critpath_traces", 0) < 1:
        problems.append("stitched traces exist but the critical-path "
                        "extractor decomposed none of them")
    # shard-sweep validity: every point must hold the safety invariants
    # (a sharded notary that double-spends or leaks reservations is
    # wrong, not slow), and multi-shard points must actually have run
    # cross-shard transactions through the 2PC
    for p in out.get("shard_sweep", []):
        tag = f"shard_sweep[shards={p.get('shards')}]"
        if not p.get("exactly_once_ok"):
            problems.append(f"{tag}: exactly-once violated")
        if not p.get("replicas_agree"):
            problems.append(f"{tag}: replicas diverged")
        if p.get("reserved_leftover", 0) != 0:
            problems.append(f"{tag}: {p['reserved_leftover']} refs left "
                            "reserved after in-doubt recovery")
        if p.get("shards", 1) > 1 and p.get("cross_shard_committed", 0) < 1:
            problems.append(f"{tag}: no cross-shard transaction committed")
    if out.get("ledger_shard_count", 1) > 1:
        if out.get("ledger_shard_cross_committed", 0) < 1:
            problems.append("flows scenario: no cross-shard tx committed")
        if out.get("ledger_shard_reserved_leftover", 0) != 0:
            problems.append("flows scenario: refs left reserved")
    if out.get("ledger_shard_finalize_conflicts", 0) != 0:
        problems.append("cross-shard atomicity violated: a finalize verdict "
                        "conflicted after the durable commit decision "
                        f"({out['ledger_shard_finalize_conflicts']} tx left "
                        "in-doubt)")
    # consensus-observatory validity (ISSUE 16): the per-entry raft
    # attribution must exist, the retained time-series plane must hold
    # ≥ 2 downsampled resolutions of Raft.LogEntries, and the sweep must
    # report a skew index. The attribution-sum conservation probe — the
    # component sum's p50 within 10% of the measured round p50 — is
    # enforced on FULL runs (hundreds of samples); under --smoke the
    # nearest-rank p50 of ~15 bimodal samples quantizes too coarsely for
    # a ratio test, so smoke only requires the fields to be live.
    attrib_sum = out.get("ledger_raft_attrib_sum_ms_p50", 0.0)
    round_p50 = out.get("ledger_raft_round_ms_p50", 0.0)
    if out.get("ledger_raft_attrib_samples", 0) < 1 or attrib_sum <= 0.0:
        problems.append("no raft commit-path attribution samples (the "
                        "consensus observatory saw no committed entry)")
    if round_p50 <= 0.0:
        problems.append("no measured consensus-round samples "
                        "(GroupCommitter.round_samples is empty)")
    if not SMOKE and attrib_sum > 0.0 and round_p50 > 0.0:
        rel = abs(attrib_sum - round_p50) / round_p50
        if rel > 0.10:
            problems.append(
                "raft attribution broke conservation: component sum p50 "
                f"{attrib_sum:.3f} ms vs measured round p50 "
                f"{round_p50:.3f} ms ({rel:.1%} apart, tolerance 10%)")
    if out.get("ledger_timeseries_resolutions", 0) < 2:
        problems.append("retained time-series plane holds "
                        f"{out.get('ledger_timeseries_resolutions', 0)} "
                        "downsampled resolutions of Raft.LogEntries "
                        "(want >= 2)")
    if out.get("shard_sweep_skew_index", 0.0) <= 0.0:
        problems.append("shard sweep reported no skew index")
    # bounded-state consensus (ISSUE 20): with compaction armed, replicas
    # must actually have snapshotted, and the RETAINED log must sawtooth
    # strictly under 2× the threshold — a peak at/over that bound means
    # compaction is not keeping up and the log is unbounded in disguise.
    snap_thr = out.get("ledger_raft_snapshot_threshold", 0)
    if snap_thr > 0:
        if out.get("ledger_raft_snapshots_taken", 0) < 1:
            problems.append("compaction armed "
                            f"(threshold {snap_thr}) but no replica took "
                            "a snapshot")
        log_peak = out.get("ledger_raft_log_entries_peak", 0)
        if log_peak >= 2 * snap_thr:
            problems.append(f"retained raft log peaked at {log_peak} "
                            f"entries against a {snap_thr}-entry snapshot "
                            "threshold (bounded-sawtooth invariant broken)")
        # the full chaos shape must additionally show the recovery paths
        # the smoke run is too small to force deterministically
        if not SMOKE and cfg.chaos:
            if out.get("ledger_raft_installs_received", 0) < 1:
                problems.append("chaos run with compaction: no lagging "
                                "follower caught up via InstallSnapshot")
            if out.get("ledger_raft_restarts", 0) < 1:
                problems.append("chaos run with compaction: no replica "
                                "crash-restart was executed")
    if problems:
        for p in problems:
            print(f"BENCH INVALID: {p}", file=sys.stderr)
        sys.exit(1)
    if GUARD:
        from corda_tpu.tools.benchguard import guard_ledger, guard_shards
        failures = guard_ledger(out) + guard_shards(out)
        if failures:
            print("BENCH REGRESSION: ledger metrics breached their "
                  "trajectory floors:", file=sys.stderr)
            for p in failures:
                print(f"  {p}", file=sys.stderr)
            sys.exit(1)
        print("benchguard: ok", file=sys.stderr)


def soak_main() -> None:
    """--soak: the drift-gated endurance run (ISSUE 19). Smoke: ~20 s of
    real load with every soak cadence accelerated (5 s phases, recurring
    chaos every 6 s) so tier-1 proves the full artifact schema — phase
    series, per-structure leak verdicts, subsystem CPU shares, drift
    slopes, mid-run invariant re-checks — without the wall clock. Full:
    ≥10 minutes at steady offered load over the sharded notary with
    chaos recurring on its schedule; emits the SOAK_r0*.json fields.

    Validity probes (BENCH INVALID, any shape): a ``leaking`` verdict on
    any declared-bounded structure, a failed mid-run invariant re-check,
    a missing schema field. Full runs additionally enforce the drift
    gates (throughput/p99 slope vs the declared bounds) and the CPU
    attribution sanity band (shares sum 90–110% of busy samples, a named
    top commit-path consumer) — a ~20 s smoke window is far too noisy
    for slope fits, exactly the existing smoke-vs-full benchguard
    discipline."""
    from corda_tpu.observability.soak import SoakConfig, run_soak

    minutes = 10.0
    if "--minutes" in sys.argv:
        i = sys.argv.index("--minutes")
        if i + 1 < len(sys.argv):
            minutes = float(sys.argv[i + 1])
    cfg = SoakConfig.smoke() if SMOKE else SoakConfig(minutes=minutes)
    out = run_soak(cfg)
    out.pop("trace_sample", None)
    out["ledger"] = True
    out["soak"] = True
    if SMOKE:
        out["smoke"] = True
    print(json.dumps(out))

    problems = []
    from corda_tpu.tools.benchguard import SOAK_REQUIRED
    missing = [k for k in SOAK_REQUIRED if k not in out]
    if missing:
        problems.append(f"soak artifact missing fields: {missing}")
    if not out.get("exactly_once_ok"):
        problems.append("exactly-once violated at quiescence")
    if not out.get("replicas_agree"):
        problems.append("raft replicas diverged at quiescence")
    if not out.get("soak_invariant_ok"):
        bad = [c for c in out.get("soak_invariant_checks", [])
               if not c.get("ok")]
        problems.append(f"mid-run invariant re-check failed: {bad}")
    if out.get("soak_leaking"):
        for name in out["soak_leaking"]:
            v = out["soak_leak_verdicts"].get(name, {})
            problems.append(
                f"leak verdict on declared-bounded structure {name}: "
                f"slope {v.get('slope_per_s')}/s, projected doubling "
                f"{v.get('doubling_s')}s")
    missing_verdicts = [n for n, v in
                        out.get("soak_leak_verdicts", {}).items()
                        if v.get("verdict") not in
                        ("bounded", "growing", "leaking")]
    if missing_verdicts:
        problems.append(f"structures without a leak verdict: "
                        f"{missing_verdicts}")
    if out.get("soak_cpu_samples", 0) < 1:
        problems.append("CPU profiler took no samples")
    if len(out.get("soak_phases", [])) < 2:
        problems.append("fewer than 2 soak phases sealed")
    if out.get("soak_chaos_cycles", 0) < 1:
        problems.append("no recurring chaos window ran")
    if not SMOKE:
        cpu_sum = out.get("soak_cpu_share_sum_pct", 0.0)
        if not 90.0 <= cpu_sum <= 110.0:
            problems.append(f"CPU shares sum to {cpu_sum}% of sampled "
                            "busy time (want 90–110%)")
        if not out.get("soak_cpu_top_commit_path"):
            problems.append("no top commit-path CPU consumer attributed")
        if not out.get("soak_drift_ok"):
            problems.append(
                "drift gate breached: throughput slope "
                f"{out.get('soak_throughput_slope_pct_per_min')}%/min "
                f"(gate ≥ {out.get('soak_throughput_gate_pct_per_min')}), "
                f"p99 slope {out.get('soak_p99_slope_pct_per_min')}%/min "
                f"(gate ≤ {out.get('soak_p99_gate_pct_per_min')})")
    if problems:
        for p in problems:
            print(f"BENCH INVALID: {p}", file=sys.stderr)
        sys.exit(1)
    if GUARD:
        from corda_tpu.tools.benchguard import guard_soak
        failures = guard_soak(out)
        if failures:
            print("BENCH REGRESSION: soak metrics breached their "
                  "trajectory floors:", file=sys.stderr)
            for p in failures:
                print(f"  {p}", file=sys.stderr)
            sys.exit(1)
        print("benchguard: ok", file=sys.stderr)


def main() -> None:
    from corda_tpu.observability import get_profiler
    from corda_tpu.verifier.batcher import SignatureBatcher
    # fresh flight-recorder counters: this run's compiles/occupancy/overlap
    # only (the profiler is process-global and always on)
    get_profiler().reset()
    items = make_items(BATCH)
    ed_items = make_ed_items(BATCH)
    r1_items = make_items(BATCH, ecmath.SECP256R1)
    if SMOKE:
        # host-crossover route only: no device kernel compiles on the
        # wiring check; kernel-rate fields keep their slots at 0.0
        dev = ed_dev = r1_dev = r1_fallback_pct = 0.0
    else:
        dev = device_rate(items)
        ed_dev = ed_device_rate(ed_items)
        r1_dev, r1_fallback_pct = r1_device_rate(r1_items)
    svc = service_metrics(items, ed_items, r1_items)
    host = host_baseline_rate(items[: min(128, BATCH)])

    def _ratio(service, kernel):
        # service throughput as a fraction of the raw kernel rate — the
        # continuous-batching headline (≥0.9 target). 0.0 in smoke (kernel
        # rates aren't measured there) so benchguard skips it.
        return round(service / kernel, 4) if kernel > 0 else 0.0

    out = {
        "metric": "ecdsa_secp256k1_verifies_per_sec_per_chip",
        "value": round(dev, 1),
        "unit": "verifies/s",
        "vs_baseline": round(dev / host, 3),
        "ed25519_verifies_per_sec_per_chip": round(ed_dev, 1),
        "secp256r1_verifies_per_sec_per_chip": round(r1_dev, 1),
        "r1_halfgcd_fallback_pct": round(r1_fallback_pct, 4),
        "r1_doublings_per_op": R1_DOUBLINGS_PER_OP,
        "service_path_verifies_per_sec": round(svc["k1_rate"], 1),
        "ed25519_service_path_verifies_per_sec": round(svc["ed_rate"], 1),
        "secp256r1_service_path_verifies_per_sec": round(svc["r1_rate"], 1),
        "mixed_service_path_verifies_per_sec": round(svc["mixed_rate"], 1),
        "service_to_kernel_ratio_k1": _ratio(svc["k1_rate"], dev),
        "service_to_kernel_ratio_ed25519": _ratio(svc["ed_rate"], ed_dev),
        "service_to_kernel_ratio_r1": _ratio(svc["r1_rate"], r1_dev),
        "tx_verify_p50_ms_batch1": round(svc["p50_ms"], 3),
        "tx_verify_p50_ms_batch1k": round(svc["p50_1k_ms"], 3),
        "tx_verify_p90_ms_batch1k": round(svc["p90_1k_ms"], 3),
        "tx_verify_p99_ms_batch1k": round(svc["p99_1k_ms"], 3),
        "host_baseline_verifies_per_sec": round(host, 1),
        "unique_signatures": UNIQUE,
        "prep_workers": SignatureBatcher.PREP_WORKERS,
        "prep_inflight_depth": SignatureBatcher.MAX_IN_FLIGHT,
        "prep_overlap_max": svc["overlap"],
        "post_warmup_compiles": svc["post_warmup_compiles"],
        "bucket_ladder": svc["bucket_ladder"],
        "interactive_latency_ms": svc["interactive_latency_ms"],
        "interactive_batch": svc["interactive_batch"],
        **svc["stages"],
    }
    # flight-recorder fields (corda_tpu.observability.profiling): where the
    # wall time went — XLA compiles vs cached dispatches, how full the
    # padded device batches ran, and how much host prep overlapped device
    # work. benchguard schema-locks these; the values are diagnostics.
    prof = get_profiler()
    totals = prof.compile_totals()
    out["compile_s_total"] = round(totals["compile_s_total"], 3)
    out["compile_cache_hits"] = totals["compile_cache_hits"]
    out["occupancy_pct_per_scheme"] = prof.occupancy_pct_per_scheme()
    out["prep_overlap_pct"] = round(prof.overlap.snapshot()["overlap_pct"], 2)
    if SMOKE:
        out["smoke"] = True
        # pipeline-serialization tripwires, cheap enough for tier-1: the
        # smoke run stays on the host route (no device intervals, so
        # overlap_pct is 0 by construction) — concurrent flushes on the
        # prep pool (PrepActive high-water ≥ 2) are its overlap signal,
        # and the hot-cache discipline must show ZERO compiles after
        # mark_warm(). A full bench run asserts the real overlap_pct via
        # benchguard instead.
        problems = []
        if out["prep_overlap_max"] < 2:
            problems.append(
                f"prep_overlap_max={out['prep_overlap_max']} < 2: scheme "
                f"flushes serialized — continuous planner not overlapping")
        if out["post_warmup_compiles"] != 0:
            problems.append(
                f"post_warmup_compiles={out['post_warmup_compiles']} != 0: "
                f"steady state recompiled after warmup")
        if problems:
            print(json.dumps(out))
            for p in problems:
                print(f"BENCH INVALID: {p}", file=sys.stderr)
            sys.exit(1)
    print(json.dumps(out))
    if GUARD:
        from corda_tpu.tools.benchguard import guard_current
        problems = guard_current(out)
        if problems:
            print("BENCH REGRESSION: guarded metrics breached their "
                  "trajectory floors:", file=sys.stderr)
            for p in problems:
                print(f"  {p}", file=sys.stderr)
            sys.exit(1)
        print("benchguard: ok", file=sys.stderr)


if __name__ == "__main__":
    if FLEET:
        fleet_main()
    elif SOAK:
        soak_main()
    elif LEDGER:
        ledger_main()
    else:
        main()
