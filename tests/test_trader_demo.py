"""Trader-demo end-to-end (TraderDemoTest / TwoPartyTradeFlowTests analogs):
full DvP over MockNetwork — cash issuance, paper issuance, atomic swap."""
import pytest

from corda_tpu.finance.cash import CashState
from corda_tpu.finance.commercial_paper import CommercialPaperState
from corda_tpu.flows import FlowException
from corda_tpu.samples.trader_demo import dollars, run_demo


def test_trader_demo_settles():
    out = run_demo(price_dollars=1000, face_dollars=1100)
    final = out["final"]
    buyer, seller, notary = out["buyer"], out["seller"], out["notary"]

    # three signatures: buyer (cash), seller (paper), notary
    assert {s.by for s in final.sigs} == {
        buyer.party.owning_key, seller.party.owning_key,
        notary.party.owning_key}
    final.verify_signatures()

    # buyer owns the paper now
    papers = out["buyer_paper"]
    assert len(papers) == 1
    assert papers[0].state.data.owner == buyer.party.owning_key

    # seller received exactly the price
    assert sum(s.state.data.amount.quantity
               for s in out["seller_cash"]) == dollars(1000).quantity
    # buyer kept the change
    assert sum(s.state.data.amount.quantity
               for s in out["buyer_cash"]) == dollars(200).quantity

    # both sides recorded the same final transaction
    assert buyer.services.storage.get_transaction(final.id) is not None
    assert seller.services.storage.get_transaction(final.id) is not None

    # seller saw its paper consumed
    assert seller.services.vault.query(CommercialPaperState, status="consumed")

    # the notary's commit log prevents re-selling the same (consumed) paper:
    # a second SellerFlow over the stale StateAndRef must die with a conflict
    from corda_tpu.finance.trade import SellerFlow
    from corda_tpu.flows.library import NotaryException
    network = out["network"]
    stale_ref = [s for s in
                 seller.services.vault.query(CommercialPaperState,
                                             status="consumed")][0]
    fsm = seller.start_flow(SellerFlow(buyer.party, stale_ref, dollars(100)))
    network.run_network()
    with pytest.raises(NotaryException, match="already consumed"):
        fsm.result_future.result(timeout=5)


def test_buyer_rejects_unaffordable_offer():
    out = run_demo(price_dollars=1000, face_dollars=1100)
    network, buyer, seller = out["network"], out["buyer"], out["seller"]
    # seller (now holding cash, no paper) offers a bogus trade the buyer
    # cannot pay for: buyer has only $200 left
    from corda_tpu.finance.trade import SellerFlow
    paper = out["buyer_paper"][0]  # owned by buyer, seller doesn't own it
    fsm = seller.start_flow(SellerFlow(buyer.party, paper, dollars(5000)))
    network.run_network()
    with pytest.raises(FlowException, match="Insufficient cash"):
        fsm.result_future.result(timeout=5)
