"""Sharded-notary chaos tests: cross-shard 2PC safety under conflict
races and coordinator crashes.

The ShardedUniquenessProvider partitions the uniqueness domain over N
raft groups and runs a two-phase provisional commit for transactions
whose inputs straddle shards. The properties under test:

- a SAME-SHARD conflicting pair rides the untouched group-commit fast
  path and resolves exactly-once on the home shard;
- a CROSS-SHARD conflicting pair racing with their input lists in
  opposite orders still contends at the canonical (lowest common) shard
  — exactly one wins, the loser's reservations on other shards are
  released, and an honest retry of the released ref succeeds;
- a COORDINATOR KILLED between prepare and finalize leaves reservations
  in-doubt; replaying the durable decision record into a fresh
  coordinator resolves them — finalized when the decision reached
  "commit", released otherwise — so no ref stays permanently reserved
  and every replica of every shard converges.
"""
import threading
import time

import pytest

from corda_tpu.consensus.raft import LEADER
from corda_tpu.consensus.raft_uniqueness import (DistributedImmutableMap,
                                                 RaftUniquenessProvider)
from corda_tpu.consensus.sharded_uniqueness import (CoordinatorLog,
                                                    CrossShardAtomicityError,
                                                    ShardedUniquenessProvider,
                                                    shard_of)
from corda_tpu.core.contracts.structures import StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.node.notary import UniquenessException
from corda_tpu.testing.faults import FaultError, FaultRule, inject

pytestmark = pytest.mark.chaos

SEEDS = [7, 101, 9001]
N_SHARDS = 2


class _ShardedCluster:
    """N shards x 3 replicas on one in-memory bus, pumped from a
    background thread (committers and the 2PC pool block on futures, so
    synchronous pumping deadlocks)."""

    def __init__(self, seed: int, n_shards: int = N_SHARDS,
                 replicas: int = 3):
        self.bus = InMemoryMessagingNetwork()
        self.n_shards = n_shards
        self.names = [[f"s{s}r{i}" for i in range(replicas)]
                      for s in range(n_shards)]
        self.maps = [[DistributedImmutableMap() for _ in range(replicas)]
                     for _ in range(n_shards)]
        self.providers = [
            [RaftUniquenessProvider.build(
                name, list(self.names[s]), self.bus.create_node(name),
                state_machine=self.maps[s][i], seed=seed + 31 * s + i,
                native=False)
             for i, name in enumerate(self.names[s])]
            for s in range(n_shards)]
        self.nodes = [[p.raft for p in group] for group in self.providers]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="chaos-shard-pump")
        self._thread.start()

    def _pump(self):
        while not self._stop.is_set():
            for group in self.nodes:
                for rn in group:
                    rn.tick()
            for group in self.names:
                for name in group:
                    while self.bus.pump_receive(name) is not None:
                        pass
            time.sleep(0.002)

    def wait_leaders(self, timeout=20.0):
        """One entry provider per shard, each backed by its shard's
        elected leader."""
        entries = []
        deadline = time.monotonic() + timeout
        for s in range(self.n_shards):
            while time.monotonic() < deadline:
                leaders = [i for i, n in enumerate(self.nodes[s])
                           if n.role == LEADER]
                if len(leaders) == 1:
                    entries.append(self.providers[s][leaders[0]])
                    break
                time.sleep(0.01)
            else:
                raise AssertionError(f"shard {s}: no leader elected")
        return entries

    def build_provider(self, log_path=None, timeout_s=10.0):
        return ShardedUniquenessProvider(
            self.wait_leaders(), timeout_s=timeout_s,
            decision_log=CoordinatorLog(log_path))

    def reserved_total(self) -> int:
        return sum(len(m._reserved)
                   for group in self.maps for m in group)

    def wait_shards_converged(self, timeout=10.0):
        """Every replica of every shard agrees ref-for-ref with its
        group AND carries zero leftover reservations."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ok = all(m._map == group[0]._map for group in self.maps
                     for m in group)
            if ok and self.reserved_total() == 0:
                return
            time.sleep(0.01)
        raise AssertionError(
            "shards did not converge reservation-free: "
            f"sizes={[[len(m) for m in g] for g in self.maps]} "
            f"reserved={self.reserved_total()}")

    def owner_of(self, ref):
        """The consuming tx recorded on the ref's home shard (leader's
        map), or None."""
        s = shard_of(ref, self.n_shards)
        held = self.maps[s][0]._map.get(ref)
        return held.consuming_tx if held is not None else None

    def close(self):
        self._stop.set()
        self._thread.join(timeout=2.0)


def _ref_on(shard: int, tag: str, n_shards: int = N_SHARDS) -> StateRef:
    """Rejection-sample a StateRef whose shard_of bucket is `shard`."""
    i = 0
    while True:
        ref = StateRef(SecureHash.sha256(f"{tag}:{i}".encode()), 0)
        if shard_of(ref, n_shards) == shard:
            return ref
        i += 1


def _tx(tag: str):
    return SecureHash.sha256(b"tx:" + tag.encode())


@pytest.mark.parametrize("seed", SEEDS)
def test_same_shard_conflict_one_winner(seed):
    """Two spends of one ref whose inputs live entirely on shard 0: both
    take the single-shard group-commit fast path — exactly one wins, the
    loser's conflict names the winner, every replica of the home shard
    records the same owner, and shard 1 never hears about it."""
    cluster = _ShardedCluster(seed)
    provider = None
    try:
        provider = cluster.build_provider()
        ref = _ref_on(0, f"same-{seed}")
        f_a = provider.commit_async([ref], _tx("a"), "chaos")
        f_b = provider.commit_async([ref], _tx("b"), "chaos")
        outcomes = {}
        for name, fut in (("a", f_a), ("b", f_b)):
            try:
                fut.result(timeout=15)
                outcomes[name] = "committed"
            except UniquenessException as ei:
                assert ref in ei.conflicts
                outcomes[name] = "rejected"
        winners = [n for n, o in outcomes.items() if o == "committed"]
        assert len(winners) == 1, outcomes
        cluster.wait_shards_converged()
        assert cluster.owner_of(ref) == _tx(winners[0])
        assert all(len(m) == 0 for m in cluster.maps[1])
    finally:
        if provider is not None:
            provider.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_cross_shard_conflict_racing_both_orders(seed):
    """Two cross-shard transactions racing for one shared ref on shard 1,
    their input lists given in OPPOSITE orders. Canonical shard-order
    preparation makes both reserve their private shard-0 ref first, then
    contend at shard 1: exactly one wins, the loser's shard-0
    reservation is released (an honest retry of that ref succeeds), and
    no ref is left reserved anywhere."""
    cluster = _ShardedCluster(seed)
    provider = None
    try:
        provider = cluster.build_provider()
        a_only = _ref_on(0, f"xa-{seed}")
        b_only = _ref_on(0, f"xb-{seed}")
        shared = _ref_on(1, f"xs-{seed}")
        tx_a, tx_b = _tx(f"xa-{seed}"), _tx(f"xb-{seed}")
        # a lists low shard first, b lists high shard first — partition()
        # canonicalizes, so the race is order-independent by construction
        f_a = provider.commit_async([a_only, shared], tx_a, "chaos")
        f_b = provider.commit_async([shared, b_only], tx_b, "chaos")
        outcomes = {}
        for name, fut in (("a", f_a), ("b", f_b)):
            try:
                fut.result(timeout=20)
                outcomes[name] = "committed"
            except UniquenessException as ei:
                assert shared in ei.conflicts
                outcomes[name] = "rejected"
        winners = [n for n, o in outcomes.items() if o == "committed"]
        assert len(winners) == 1, outcomes
        win_tx = tx_a if winners[0] == "a" else tx_b
        loser_ref = b_only if winners[0] == "a" else a_only

        cluster.wait_shards_converged()
        assert cluster.owner_of(shared) == win_tx
        # the loser's private ref was reserved in phase 1 and must have
        # been RELEASED by the abort: an honest retry spends it cleanly
        assert cluster.owner_of(loser_ref) is None
        retry_tx = _tx(f"retry-{seed}")
        assert provider.commit_async([loser_ref], retry_tx,
                                     "chaos").result(timeout=15) is None
        cluster.wait_shards_converged()
        assert cluster.owner_of(loser_ref) == retry_tx
        snap = provider.metrics.snapshot()
        assert snap["CrossShard.Aborted"]["count"] >= 1
        assert snap["CrossShard.Committed"]["count"] == 1
    finally:
        if provider is not None:
            provider.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_coordinator_killed_after_decide_recovers_to_commit(seed, tmp_path):
    """Coordinator killed between the durable "commit" decision and
    finalize: the refs sit reserved (in-doubt) and NO inline cleanup
    runs — the process is "dead". Replaying the decision file into a
    fresh coordinator finalizes the transaction on every shard; the
    once-in-doubt refs end up consumed exactly-once, nothing stays
    reserved."""
    cluster = _ShardedCluster(seed)
    provider = recovered = None
    log_path = str(tmp_path / "decisions.log")
    try:
        provider = cluster.build_provider(log_path=log_path)
        refs = [_ref_on(0, f"kc-{seed}"), _ref_on(1, f"kc-{seed}")]
        tx = _tx(f"kc-{seed}")
        with inject(FaultRule("shard2pc.finalize", "raise", count=1),
                    seed=seed):
            with pytest.raises(FaultError):
                provider.commit(refs, tx, "chaos")
        # the crash left the tx in-doubt with a durable commit decision
        # (each shard's leader holds a reservation; followers follow)
        assert provider.log.status(tx) == "commit"
        assert cluster.reserved_total() >= len(refs)

        # "restart": a fresh coordinator replays the decision file
        recovered = ShardedUniquenessProvider(
            cluster.wait_leaders(), timeout_s=10.0,
            decision_log=CoordinatorLog(log_path))
        assert recovered.log.status(tx) == "commit"
        resolved = recovered.recover_in_doubt()
        assert resolved == [(tx, "committed")]

        cluster.wait_shards_converged()
        for ref in refs:
            assert cluster.owner_of(ref) == tx
        assert len(recovered.log) == 0
        # a double spend of a recovered ref still rejects exactly-once
        with pytest.raises(UniquenessException):
            recovered.commit([refs[0]], _tx(f"dup-{seed}"), "chaos")
    finally:
        if recovered is not None:
            recovered.close()
        if provider is not None:
            provider.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_abort_releases_shard_whose_prepare_timed_out(seed):
    """The late-commit race: the reserve round on shard 1 SUCCEEDS on the
    replicated state machine but the coordinator sees a timeout (the
    _RoundStuck scenario — the verdict never came back). The abort must
    release shard 1's reservation anyway, not just the shards whose
    reserve verdict it saw, or the ref stays reserved forever and every
    future spender gets a false double-spend conflict."""
    cluster = _ShardedCluster(seed)
    provider = None
    try:
        provider = cluster.build_provider()
        refs = [_ref_on(0, f"to-{seed}"), _ref_on(1, f"to-{seed}")]
        tx = _tx(f"to-{seed}")
        orig = provider._round
        fired = []

        def flaky(shard, command, trace_ctx, phase, n_states):
            out = orig(shard, command, trace_ctx, phase, n_states)
            if phase == "prepare" and shard == 1 and not fired:
                fired.append(shard)   # reservation IS taken; verdict lost
                raise TimeoutError("injected: prepare verdict lost")
            return out

        provider._round = flaky
        with pytest.raises(TimeoutError):
            provider.commit(refs, tx, "chaos")
        # abort released BOTH shards' reservations and retired the entry
        cluster.wait_shards_converged()
        assert len(provider.log) == 0
        for ref in refs:
            assert cluster.owner_of(ref) is None
        # an honest retry of the once-stranded refs commits cleanly
        retry_tx = _tx(f"to-retry-{seed}")
        provider.commit(refs, retry_tx, "chaos")
        cluster.wait_shards_converged()
        for ref in refs:
            assert cluster.owner_of(ref) == retry_tx
    finally:
        if provider is not None:
            provider.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS[:1])
def test_finalize_conflict_is_surfaced_and_left_in_doubt(seed):
    """A lost reservation (here: a zombie release plus a rival spend
    sneaking in between decide and finalize) makes finalize_all report a
    conflict verdict. The coordinator must NOT count the tx committed or
    complete its log entry — it surfaces CrossShardAtomicityError, marks
    the alert meter, and recovery keeps the entry in-doubt instead of
    resolving the violation silently."""
    cluster = _ShardedCluster(seed)
    provider = None
    try:
        provider = cluster.build_provider()
        r0, r1 = _ref_on(0, f"fc-{seed}"), _ref_on(1, f"fc-{seed}")
        tx, rival = _tx(f"fc-{seed}"), _tx(f"fc-rival-{seed}")
        orig = provider._round
        stolen = []

        def stealing(shard, command, trace_ctx, phase, n_states):
            if phase == "finalize" and shard == 1 and not stolen:
                stolen.append(shard)
                # zombie recovery releases tx's reservation, rival spends
                orig(1, ("release_all", (tx, [r1])), None, "release", 1)
                orig(1, ("put_all", (rival, [r1], "rival")), None,
                     "steal", 1)
            return orig(shard, command, trace_ctx, phase, n_states)

        provider._round = stealing
        with pytest.raises(CrossShardAtomicityError) as ei:
            provider.commit([r0, r1], tx, "chaos")
        assert r1 in ei.value.conflicts
        assert ei.value.conflicts[r1].consuming_tx == rival
        # the entry is still in-doubt with its durable commit decision —
        # NOT completed as if the tx had committed atomically
        assert provider.log.status(tx) == "commit"
        snap = provider.metrics.snapshot()
        assert snap["CrossShard.FinalizeConflict"]["count"] == 1
        assert (snap.get("CrossShard.Committed") or {}).get("count", 0) == 0
        # recovery does not silently resolve it either: the entry stays
        # in-doubt and the meter keeps alerting
        provider._round = orig
        assert provider.recover_in_doubt() == []
        assert provider.log.status(tx) == "commit"
        assert provider.metrics.snapshot()[
            "CrossShard.FinalizeConflict"]["count"] == 2
    finally:
        if provider is not None:
            provider.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_fast_path_spend_of_reserved_ref_defers_until_release(seed):
    """A single-shard spend of a ref provisionally reserved by an
    in-flight cross-shard 2PC must DEFER, not terminal-reject: the
    reservation is revocable, and when the holder aborts and releases,
    the parked spend gets its chance and commits — previously the client
    received a permanent double-spend error for an unspent state."""
    cluster = _ShardedCluster(seed)
    provider = None
    prepared, proceed = threading.Event(), threading.Event()
    try:
        provider = cluster.build_provider()
        shared = _ref_on(0, f"dv-{seed}")
        other = _ref_on(1, f"dv-{seed}")
        tx_a, tx_b = _tx(f"dv-a-{seed}"), _tx(f"dv-b-{seed}")
        orig = provider._round

        def holding(shard, command, trace_ctx, phase, n_states):
            out = orig(shard, command, trace_ctx, phase, n_states)
            if phase == "prepare" and shard == 1:
                prepared.set()          # both shards now hold reservations
                proceed.wait(timeout=15)
                raise TimeoutError("injected: coordinator gives up")
            return out

        provider._round = holding
        f_a = provider.commit_async([shared, other], tx_a, "chaos")
        assert prepared.wait(timeout=15)
        # B spends the reserved ref on the fast path: parked, not rejected
        f_b = provider.commit_async([shared], tx_b, "chaos")
        time.sleep(0.4)
        assert not f_b.done(), \
            "fast-path spend of a reserved ref must defer, not resolve"
        proceed.set()                   # A aborts and releases both shards
        with pytest.raises(TimeoutError):
            f_a.result(timeout=15)
        # the released ref's parked spender commits (ticker re-screen —
        # no further batch completion happens on that shard by itself)
        assert f_b.result(timeout=15) is None
        cluster.wait_shards_converged()
        assert cluster.owner_of(shared) == tx_b
        assert cluster.owner_of(other) is None
    finally:
        prepared.set()
        proceed.set()
        if provider is not None:
            provider.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS[:1])
def test_provisional_batch_verdict_reparks_instead_of_rejecting(seed):
    """If a reservation lands between the committer's prescreen and the
    replicated apply, the batch verdict comes back conflict-but-
    provisional. The committer must re-park the request (it is blocked
    by a revocable claim) rather than fail the future terminally."""
    cluster = _ShardedCluster(seed)
    provider = None
    try:
        provider = cluster.build_provider()
        ref = _ref_on(0, f"pv-{seed}")
        holder, spender = _tx(f"pv-hold-{seed}"), _tx(f"pv-spend-{seed}")
        shard0 = provider.shards[0]
        # take a real replicated reservation on shard 0
        out = shard0.raft.submit(
            ("reserve_all", (holder, [ref], "holder"))).result(timeout=15)
        assert out["committed"]
        # warm up the committer on an unrelated tx, then blind its
        # reservation prescreen so the spend reaches consensus and meets
        # the reservation at apply time (the mid-flight race)
        warm = _ref_on(0, f"pv-warm-{seed}")
        provider.commit_async(
            [warm], _tx(f"pv-warm-{seed}"), "chaos").result(timeout=15)
        committer = shard0.group_committer
        committer._reserved_view = lambda: {}
        fut = provider.commit_async([ref], spender, "chaos")
        time.sleep(0.4)
        assert not fut.done(), \
            "provisional conflict verdict must re-park, not reject"
        # holder releases: the parked spend must now commit
        out = shard0.raft.submit(
            ("release_all", (holder, [ref]))).result(timeout=15)
        assert out["committed"]
        assert fut.result(timeout=15) is None
        cluster.wait_shards_converged()
        assert cluster.owner_of(ref) == spender
    finally:
        if provider is not None:
            provider.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_coordinator_killed_before_decide_recovers_to_abort(seed, tmp_path):
    """Coordinator killed AFTER reserving on every shard but BEFORE the
    decision reached the record: recovery must abort — the reservations
    are released, the decision record drains, and an honest retry of the
    same refs by a new transaction succeeds."""
    cluster = _ShardedCluster(seed)
    provider = recovered = None
    log_path = str(tmp_path / "decisions.log")
    try:
        provider = cluster.build_provider(log_path=log_path)
        refs = [_ref_on(0, f"ka-{seed}"), _ref_on(1, f"ka-{seed}")]
        tx = _tx(f"ka-{seed}")
        with inject(FaultRule("shard2pc.decide", "raise", count=1),
                    seed=seed):
            with pytest.raises(FaultError):
                provider.commit(refs, tx, "chaos")
        assert provider.log.status(tx) == "prepare"
        assert cluster.reserved_total() >= len(refs)

        recovered = ShardedUniquenessProvider(
            cluster.wait_leaders(), timeout_s=10.0,
            decision_log=CoordinatorLog(log_path))
        resolved = recovered.recover_in_doubt()
        assert resolved == [(tx, "aborted")]
        cluster.wait_shards_converged()
        for ref in refs:
            assert cluster.owner_of(ref) is None
        assert len(recovered.log) == 0

        # honest retry: the released refs commit cleanly cross-shard
        retry_tx = _tx(f"ka-retry-{seed}")
        recovered.commit(refs, retry_tx, "chaos")
        cluster.wait_shards_converged()
        for ref in refs:
            assert cluster.owner_of(ref) == retry_tx
    finally:
        if recovered is not None:
            recovered.close()
        if provider is not None:
            provider.close()
        cluster.close()
