"""RequestLog bounded retention: terminal-aware eviction under churn.

The debug surface exists to answer "why did request N land on worker
W?" — so a long-lived in-flight request must keep its full timeline
while short-lived resolved requests churn through the ring around it.
"""
import logging

from corda_tpu.observability.lifecycle import RequestLog, TERMINAL_EVENTS

logging.getLogger("corda_tpu.observability.lifecycle").setLevel(
    logging.CRITICAL)


def _run(log, vid):
    log.append(vid, "submitted")
    log.append(vid, "routed", worker="w0", reason="least-loaded")
    log.append(vid, "resolved", ok=True)


def test_capacity_bound_and_whole_timeline_eviction():
    log = RequestLog(capacity=3)
    for vid in range(5):
        _run(log, vid)
    snap = log.snapshot()
    assert len(snap) == 3
    assert log.dropped == 2
    # evicted whole: the survivors carry their complete event trail
    for tl in snap.values():
        assert [e["event"] for e in tl] == ["submitted", "routed", "resolved"]


def test_resolved_timelines_evicted_before_in_flight():
    log = RequestLog(capacity=3)
    log.append(100, "submitted")            # long-lived, never resolves
    _run(log, 101)                          # resolved
    _run(log, 102)                          # resolved
    _run(log, 103)                          # forces one eviction
    snap = log.snapshot()
    # 101 (oldest RESOLVED) went, not 100 (oldest overall, in flight)
    assert "100" in snap and "101" not in snap
    assert "102" in snap and "103" in snap
    assert log.dropped == 1


def test_in_flight_survives_heavy_churn_with_full_history():
    cap = 8
    log = RequestLog(capacity=cap)
    pinned = [1000, 1001, 1002]
    for vid in pinned:
        log.append(vid, "submitted")
    for i in range(200):                    # 200 short-lived requests
        _run(log, i)
        if i % 50 == 0:                     # pinned requests stay active
            for vid in pinned:
                log.append(vid, "dispatched", worker=f"w{i % 3}", batch=i)
    for vid in pinned:
        log.append(vid, "resolved", ok=True)
    snap = log.snapshot()
    assert len(snap) <= cap
    for vid in pinned:
        events = [e["event"] for e in snap[str(vid)]]
        # one unbroken timeline: submitted + 4 dispatches + resolved
        assert events[0] == "submitted" and events[-1] == "resolved"
        assert events.count("dispatched") == 4
        assert log.terminal_count(vid) == 1
    # everything evicted was a whole resolved timeline
    assert log.dropped == 200 + len(pinned) - cap


def test_fifo_fallback_when_nothing_resolved():
    log = RequestLog(capacity=2)
    log.append(1, "submitted")
    log.append(2, "submitted")
    log.append(3, "submitted")              # all in flight: oldest goes
    snap = log.snapshot()
    assert sorted(snap) == ["2", "3"]
    assert log.dropped == 1


def test_terminal_count_tracks_terminal_events():
    log = RequestLog(capacity=4)
    _run(log, 7)
    assert log.terminal_count(7) == 1
    assert TERMINAL_EVENTS  # the invariant the chaos suites key off
    assert log.terminal_count(999) == 0
