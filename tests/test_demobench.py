"""DemoBench / cordform network-spec tests.

Reference analogs: cordformation's deployNodes config generation and
DemoBench's launch/stop lifecycle (tools/demobench) — config expansion is
unit-tested; the full launch is a slow integration test over real node
processes like the driver tier.
"""
import json
import urllib.request

import pytest

from corda_tpu.node.node import NodeConfiguration
from corda_tpu.tools.demobench import (DemoBench, MAP_NAME,
                                       generate_node_configs)


def spec_for(tmp_path, **extra):
    return {
        "base_directory": str(tmp_path / "net"),
        "nodes": [
            {"name": "O=Notary, L=Zurich, C=CH", "notary": "simple"},
            {"name": "O=Alice, L=London, C=GB", **extra},
        ],
    }


def test_generate_node_configs(tmp_path):
    spec = spec_for(tmp_path)
    spec["map_port"] = 10123
    paths = generate_node_configs(spec)
    assert len(paths) == 3                     # implicit map node first
    cfgs = [NodeConfiguration.load(p) for p in paths]
    assert cfgs[0].my_legal_name == MAP_NAME
    assert cfgs[0].port == 10123
    assert cfgs[1].notary == "simple"
    assert cfgs[2].network_map_address == "127.0.0.1:10123"
    assert cfgs[2].network_map_name == MAP_NAME
    # regenerating is idempotent (same paths, loadable configs)
    assert generate_node_configs(spec) == paths


@pytest.mark.slow
def test_demobench_launch_and_rest(tmp_path):
    spec = spec_for(tmp_path, web_port=0)
    bench = DemoBench(spec).launch()
    try:
        rows = bench.status()
        assert len(rows) == 3 and all(r["alive"] for r in rows)
        web = next(r["web"] for r in rows if "Alice" in r["name"])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{web}/api/status", timeout=10) as r:
            status = json.loads(r.read())
        assert "Alice" in status["identity"]["legal_identity"]["name"]
        assert bench.stop_node("Alice")
        assert any(not r["alive"] for r in bench.status())
    finally:
        bench.shutdown()
    assert all(not r.alive for r in bench.nodes) or bench.nodes == []
