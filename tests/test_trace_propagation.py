"""SpanContext propagation across the TCP messaging plane.

The wire frame carries an optional fifth [trace_id, span_id] element
(network/tcp.py); a receive handler parenting its span on ``Message.trace``
stitches both hosts' spans into ONE connected trace — the cross-host half
of the flight-recorder story (statemachine → batcher → notary spans already
connect in-process through explicit SpanContext passing).
"""
import time

import pytest

from corda_tpu.network.messaging import TopicSession
from corda_tpu.network.tcp import TcpMessagingService
from corda_tpu.observability import (disable_tracing, enable_tracing,
                                     get_tracer)


def _wait_for(pred, timeout=8.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def plane():
    """Two plaintext endpoints wired through a shared directory."""
    directory = {}
    resolve = directory.get
    a = TcpMessagingService("alice", "127.0.0.1", 0, resolve)
    b = TcpMessagingService("bob", "127.0.0.1", 0, resolve)
    directory["alice"] = ("127.0.0.1", a.port)
    directory["bob"] = ("127.0.0.1", b.port)
    yield a, b
    a.stop()
    b.stop()


def test_transport_advertises_trace_support(plane):
    a, _ = plane
    assert a.supports_trace is True


def test_trace_rides_the_frame(plane):
    a, b = plane
    got = []
    b.add_message_handler(TopicSession("t", 1), got.append)
    a.send(TopicSession("t", 1), b"traced", "bob",
           trace=("deadbeef01020304", "cafe050607080900"))
    a.send(TopicSession("t", 1), b"plain", "bob")
    assert _wait_for(lambda: len(got) == 2)
    assert got[0].trace == ("deadbeef01020304", "cafe050607080900")
    assert got[0].data == b"traced"
    # an untraced send must decode as a four-element frame: no trace
    assert got[1].trace is None


def _roundtrip_connected_trace(a, b):
    """Shared body: send a->b under a live tracer, parent the receive span
    on the wire trace, and assert BOTH spans land in one connected trace."""
    tracer = enable_tracing()
    try:
        got = []

        def on_message(msg):
            with get_tracer().span("session.receive", parent=msg.trace):
                got.append(msg)

        b.add_message_handler(TopicSession("t", 1), on_message)
        send_span = tracer.span("session.send", peer="bob")
        a.send(TopicSession("t", 1), b"hello", "bob",
               trace=send_span.context().as_tuple())
        send_span.finish()
        assert _wait_for(lambda: got)

        trace = tracer.trace(send_span.trace_id)
        assert sorted(s["name"] for s in trace) == \
            ["session.receive", "session.send"]
        receive = next(s for s in trace if s["name"] == "session.receive")
        assert receive["parent_id"] == send_span.span_id
    finally:
        disable_tracing()


def test_roundtrip_yields_one_connected_trace(plane):
    a, b = plane
    _roundtrip_connected_trace(a, b)


def test_mtls_roundtrip_yields_one_connected_trace(tmp_path):
    """The satellite's acceptance shape: a two-node mutual-TLS round-trip
    produces one connected trace — the trace element survives the TLS
    transport exactly as it does plaintext."""
    pytest.importorskip("cryptography")
    from corda_tpu.network.tls import TlsConfig

    directory = {}
    resolve = directory.get
    a = TcpMessagingService(
        "alice", "127.0.0.1", 0, resolve,
        tls=TlsConfig.dev(str(tmp_path / "alice"), "alice",
                          str(tmp_path / "ca")))
    b = TcpMessagingService(
        "bob", "127.0.0.1", 0, resolve,
        tls=TlsConfig.dev(str(tmp_path / "bob"), "bob",
                          str(tmp_path / "ca")))
    directory["alice"] = ("127.0.0.1", a.port)
    directory["bob"] = ("127.0.0.1", b.port)
    try:
        _roundtrip_connected_trace(a, b)
    finally:
        a.stop()
        b.stop()
