"""Canonical codec tests: determinism, whitelisting, round-trips.

(Reference analog: KryoTests + CordaClassResolver whitelist tests.)
"""
import datetime

import pytest

from corda_tpu.core.serialization import (
    serialize, deserialize, serialized_hash, SerializationError, serializable)
from corda_tpu.core.crypto import SecureHash, generate_keypair, CompositeKey, Crypto


def test_primitive_roundtrips():
    for v in [None, True, False, 0, -1, 2**62, 2**100, -(2**100), "héllo", b"bytes",
              [1, [2, 3], "x"], {"a": 1, "b": [2]}, frozenset({1, 2, 3}),
              datetime.datetime(2026, 7, 29, 12, 0, tzinfo=datetime.timezone.utc)]:
        assert deserialize(serialize(v)) == v, v


def test_determinism_of_maps_and_sets():
    a = serialize({"x": 1, "y": 2, "z": {1, 2, 3}})
    b = serialize({"z": {3, 2, 1}, "y": 2, "x": 1})
    assert a == b
    # bytes are stable across processes by construction (no ids/hash seeds)
    assert serialized_hash({"x": 1}).hex() == serialized_hash({"x": 1}).hex()


def test_floats_rejected():
    with pytest.raises(SerializationError):
        serialize(1.5)


def test_whitelist_enforced():
    class NotRegistered:
        pass

    with pytest.raises(SerializationError):
        serialize(NotRegistered())
    # Unknown type name on deserialize is rejected too.
    import msgpack
    from corda_tpu.core.serialization.codec import _MAGIC, _EXT_OBJ
    evil = _MAGIC + msgpack.packb(
        msgpack.ExtType(_EXT_OBJ, msgpack.packb(["EvilType", []], use_bin_type=True)),
        use_bin_type=True)
    with pytest.raises(SerializationError):
        deserialize(evil)


def test_bad_magic_and_version():
    with pytest.raises(SerializationError):
        deserialize(b"nope")
    good = serialize(1)
    with pytest.raises(SerializationError):
        deserialize(good[:3] + bytes([99]) + good[4:])


def test_crypto_types_roundtrip():
    kp = generate_keypair(entropy=b"\x09" * 32)
    assert deserialize(serialize(kp.public)) == kp.public
    h = SecureHash.sha256(b"x")
    assert deserialize(serialize(h)) == h
    sig = Crypto.sign_with_key(kp, b"msg")
    sig2 = deserialize(serialize(sig))
    assert sig2 == sig and sig2.is_valid(b"msg")
    # Composite keys travel as PublicKey wire shape.
    k2 = generate_keypair(entropy=b"\x0a" * 32)
    comp = CompositeKey.Builder().add_keys(kp.public, k2.public).build(threshold=2)
    assert deserialize(serialize(comp)) == comp


def test_fuzz_mutated_bytes_fail_typed():
    """Untrusted wire bytes: random mutations of valid canonical bytes must
    either deserialize (benign mutation) or raise SerializationError — never
    any other exception type (the deserialize() hardening contract)."""
    import numpy as np

    from corda_tpu.core.crypto.secure_hash import SecureHash
    from corda_tpu.core.serialization import (SerializationError, deserialize,
                                              serialize)

    base = serialize({
        "refs": [SecureHash.sha256(bytes([i])) for i in range(4)],
        "amounts": [10**20, -5, 0],
        "nested": {"a": (1, 2, b"\x00\xff"), "b": frozenset((1, 2, 3))},
    })
    rng = np.random.default_rng(99)
    survived, rejected = 0, 0
    for _ in range(500):
        mutated = bytearray(base)
        for _ in range(int(rng.integers(1, 4))):
            mutated[int(rng.integers(0, len(base)))] = int(rng.integers(256))
        try:
            deserialize(bytes(mutated))
            survived += 1
        except SerializationError:
            rejected += 1
    assert survived + rejected == 500
    assert rejected > 0           # sanity: mutations do get caught

    # truncations at every boundary fail typed too
    for cut in range(len(base)):
        try:
            deserialize(base[:cut])
        except SerializationError:
            pass


def test_fuzz_random_structures_roundtrip():
    """Property: generator-built random wire trees round-trip exactly."""
    import numpy as np

    from corda_tpu.core.serialization import deserialize, serialize

    rng = np.random.default_rng(17)

    def random_value(depth=0):
        kinds = ["int", "bigint", "str", "bytes", "bool", "none"]
        if depth < 3:
            kinds += ["list", "dict"] * 2
        kind = kinds[int(rng.integers(len(kinds)))]
        if kind == "int":
            return int(rng.integers(-2**62, 2**62))
        if kind == "bigint":
            return int(rng.integers(0, 2**62)) << int(rng.integers(64, 200))
        if kind == "str":
            return "".join(chr(0x20 + int(c) % 0x5F)
                           for c in rng.integers(0, 255, size=8))
        if kind == "bytes":
            return bytes(rng.integers(0, 255, size=int(rng.integers(0, 16)),
                                      dtype=np.uint8))
        if kind == "bool":
            return bool(rng.integers(2))
        if kind == "none":
            return None
        if kind == "list":
            return [random_value(depth + 1)
                    for _ in range(int(rng.integers(0, 4)))]
        return {f"k{i}": random_value(depth + 1)
                for i in range(int(rng.integers(0, 4)))}

    for _ in range(100):
        value = random_value()
        back = deserialize(serialize(value))
        norm = _normalize_tuples(value)
        assert back == norm, (value, back)


def _normalize_tuples(v):
    if isinstance(v, (list, tuple)):
        return [_normalize_tuples(x) for x in v]
    if isinstance(v, dict):
        return {k: _normalize_tuples(x) for k, x in v.items()}
    return v


def test_registered_dataclass_roundtrip():
    from corda_tpu.testing import DummyState
    kp = generate_keypair(entropy=b"\x0b" * 32)
    s = DummyState(magic_number=42, owners=(kp.public,))
    s2 = deserialize(serialize(s))
    assert s2 == s
    assert isinstance(s2.owners, tuple)


# ---------------------------------------------------------------------------
# Schema-carrying deserialization of unknown types (ClassCarpenter analog,
# reference ClassCarpenter.kt:30-447; VERDICT r3 missing #5)
# ---------------------------------------------------------------------------

def test_carpented_unknown_type_roundtrip():
    import dataclasses

    from corda_tpu.core.serialization import codec

    @dataclasses.dataclass(frozen=True)
    class ThirdPartyState:
        issuer: str
        quantity: int
        memo: bytes

    name = "test.carpenter.ThirdPartyState"
    codec.register_type(name, ThirdPartyState, carry_schema=True)
    try:
        blob = codec.serialize(ThirdPartyState("O=Issuer", 42, b"\x01\x02"))

        # simulate a receiver WITHOUT the defining module
        del codec._REGISTRY[name]
        del codec._BY_CLASS[ThirdPartyState]
        got = codec.deserialize(blob)
        assert type(got) is not ThirdPartyState
        assert getattr(type(got), "__corda_carpented__", None) == name
        assert (got.issuer, got.quantity, got.memo) == ("O=Issuer", 42,
                                                        b"\x01\x02")
        # the bag re-serializes BIT-EXACTLY (relay/storage round-trip)
        assert codec.serialize(got) == blob
        # same schema carpents once; a DIFFERENT schema unions (evolution —
        # see tests/test_schema_evolution.py), while hostile names still fail
        assert type(codec.deserialize(blob)) is type(got)
        union_cls = codec.carpented_class(name, ["issuer", "extra_field"])
        assert union_cls is not type(got)
        assert union_cls.__corda_carpented_fields__ == [
            "issuer", "quantity", "memo", "extra_field"]
        with pytest.raises(SerializationError):
            codec.carpented_class(name, ["__class__"])

        # once the real class IS registered, it wins for new decodes
        codec.register_type(name, ThirdPartyState, carry_schema=True)
        again = codec.deserialize(blob)
        assert type(again) is ThirdPartyState
    finally:
        codec._REGISTRY.pop(name, None)
        codec._BY_CLASS.pop(ThirdPartyState, None)
        codec._SCHEMA_NAMES.pop(name, None)
        cls_entry = codec._CARPENTED.pop(name, None)
        if cls_entry is not None:
            codec._CARPENTED_BY_CLASS.pop(cls_entry[0], None)


def test_carpenter_rejects_hostile_field_names():
    from corda_tpu.core.serialization import codec
    with pytest.raises(SerializationError):
        codec.carpented_class("evil.Type", ["__class__"])
    with pytest.raises(SerializationError):
        codec.carpented_class("evil.Type2", ["not an identifier!"])


def test_plain_unknown_type_still_rejected():
    """The whitelist stays authoritative for schema-LESS objects."""
    import msgpack

    from corda_tpu.core.serialization import codec
    wire = msgpack.ExtType(codec._EXT_OBJ,
                           codec._packb(["no.such.Type", [1, 2]]))
    blob = codec._MAGIC + codec._packb(wire)
    with pytest.raises(SerializationError):
        codec.deserialize(blob)


def test_carpenter_rejects_huge_field_count():
    """ADVICE r4 (medium): a hostile peer must not be able to force
    synthesis of an arbitrarily wide (then pinned-forever) class via one
    schema'd object — field count is bounded like the name count."""
    import msgpack

    from corda_tpu.core.serialization import codec
    names = [f"f{i}" for i in range(codec._CARPENTED_MAX_FIELDS + 1)]
    with pytest.raises(SerializationError):
        codec.carpented_class("evil.Wide", names)
    # and via the wire (the hostile-peer path)
    wire = msgpack.ExtType(
        codec._EXT_OBJ_SCHEMA,
        codec._packb(["evil.Wide2", names, [0] * len(names)]))
    blob = codec._MAGIC + codec._packb(wire)
    with pytest.raises(SerializationError):
        codec.deserialize(blob)
    # the boundary itself is fine
    ok = codec.carpented_class(
        "test.carpenter.ExactlyMax",
        [f"f{i}" for i in range(codec._CARPENTED_MAX_FIELDS)])
    codec._CARPENTED.pop("test.carpenter.ExactlyMax", None)
    codec._CARPENTED_BY_CLASS.pop(ok, None)


def test_schema_skew_binds_by_name_not_position():
    """ADVICE r4 (low): when the real class IS registered, carried field
    names from a peer with a different declaration ORDER must bind by
    name; disjoint field sets must be a SerializationError, not a
    positional misbind or raw TypeError."""
    import dataclasses

    import msgpack

    from corda_tpu.core.serialization import codec

    @dataclasses.dataclass(frozen=True)
    class SkewState:
        issuer: str
        quantity: int

    name = "test.skew.SkewState"
    codec.register_type(name, SkewState, carry_schema=True)
    try:
        # peer serialized under a REVERSED declaration order
        wire = msgpack.ExtType(
            codec._EXT_OBJ_SCHEMA,
            codec._packb([name, ["quantity", "issuer"], [42, "O=Issuer"]]))
        blob = codec._MAGIC + codec._packb(wire)
        got = codec.deserialize(blob)
        assert got == SkewState(issuer="O=Issuer", quantity=42)

        # disjoint field names: rejected, not positionally bound
        wire = msgpack.ExtType(
            codec._EXT_OBJ_SCHEMA,
            codec._packb([name, ["issuer", "totally_else"], ["O=X", 1]]))
        blob = codec._MAGIC + codec._packb(wire)
        with pytest.raises(SerializationError):
            codec.deserialize(blob)
    finally:
        codec._REGISTRY.pop(name, None)
        codec._BY_CLASS.pop(SkewState, None)
        codec._SCHEMA_NAMES.pop(name, None)
