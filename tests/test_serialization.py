"""Canonical codec tests: determinism, whitelisting, round-trips.

(Reference analog: KryoTests + CordaClassResolver whitelist tests.)
"""
import datetime

import pytest

from corda_tpu.core.serialization import (
    serialize, deserialize, serialized_hash, SerializationError, serializable)
from corda_tpu.core.crypto import SecureHash, generate_keypair, CompositeKey, Crypto


def test_primitive_roundtrips():
    for v in [None, True, False, 0, -1, 2**62, 2**100, -(2**100), "héllo", b"bytes",
              [1, [2, 3], "x"], {"a": 1, "b": [2]}, frozenset({1, 2, 3}),
              datetime.datetime(2026, 7, 29, 12, 0, tzinfo=datetime.timezone.utc)]:
        assert deserialize(serialize(v)) == v, v


def test_determinism_of_maps_and_sets():
    a = serialize({"x": 1, "y": 2, "z": {1, 2, 3}})
    b = serialize({"z": {3, 2, 1}, "y": 2, "x": 1})
    assert a == b
    # bytes are stable across processes by construction (no ids/hash seeds)
    assert serialized_hash({"x": 1}).hex() == serialized_hash({"x": 1}).hex()


def test_floats_rejected():
    with pytest.raises(SerializationError):
        serialize(1.5)


def test_whitelist_enforced():
    class NotRegistered:
        pass

    with pytest.raises(SerializationError):
        serialize(NotRegistered())
    # Unknown type name on deserialize is rejected too.
    import msgpack
    from corda_tpu.core.serialization.codec import _MAGIC, _EXT_OBJ
    evil = _MAGIC + msgpack.packb(
        msgpack.ExtType(_EXT_OBJ, msgpack.packb(["EvilType", []], use_bin_type=True)),
        use_bin_type=True)
    with pytest.raises(SerializationError):
        deserialize(evil)


def test_bad_magic_and_version():
    with pytest.raises(SerializationError):
        deserialize(b"nope")
    good = serialize(1)
    with pytest.raises(SerializationError):
        deserialize(good[:3] + bytes([99]) + good[4:])


def test_crypto_types_roundtrip():
    kp = generate_keypair(entropy=b"\x09" * 32)
    assert deserialize(serialize(kp.public)) == kp.public
    h = SecureHash.sha256(b"x")
    assert deserialize(serialize(h)) == h
    sig = Crypto.sign_with_key(kp, b"msg")
    sig2 = deserialize(serialize(sig))
    assert sig2 == sig and sig2.is_valid(b"msg")
    # Composite keys travel as PublicKey wire shape.
    k2 = generate_keypair(entropy=b"\x0a" * 32)
    comp = CompositeKey.Builder().add_keys(kp.public, k2.public).build(threshold=2)
    assert deserialize(serialize(comp)) == comp


def test_registered_dataclass_roundtrip():
    from corda_tpu.testing import DummyState
    kp = generate_keypair(entropy=b"\x0b" * 32)
    s = DummyState(magic_number=42, owners=(kp.public,))
    s2 = deserialize(serialize(s))
    assert s2 == s
    assert isinstance(s2.owners, tuple)
