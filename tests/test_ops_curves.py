"""Differential tests: device curve kernels vs the pure-Python host oracle.

Mirrors the reference's crypto unit tests (core/src/test/.../crypto/
CryptoUtilsTest: sign/verify roundtrip + malformed-input rejection per
scheme) as the bit-exactness oracle for the TPU kernels (SURVEY.md §4.1).
"""
import hashlib

import numpy as np
import pytest

from corda_tpu.core.crypto import ecmath
from corda_tpu.ops import ed25519 as ed_ops
from corda_tpu.ops import field as F
from corda_tpu.ops import weierstrass as wc_ops

RNG = np.random.default_rng(7)


def rand_scalar(n):
    return int.from_bytes(RNG.bytes(32), "little") % n


# ---------------------------------------------------------------------------
# Ed25519
# ---------------------------------------------------------------------------

def ed_rand_points(k):
    pts = []
    for _ in range(k):
        s = rand_scalar(ecmath.ED_L)
        pts.append(ecmath.ed_to_affine(
            ecmath.ed_scalar_mul(s, ecmath.ed_to_extended(ecmath.ED_B))))
    return pts


def test_ed_add_double_matches_host():
    pts = ed_rand_points(4)
    qts = ed_rand_points(4)
    Pb = ed_ops._pack_point_ext(pts)
    Qb = ed_ops._pack_point_ext(qts)
    got_add = ed_ops.add(Pb, Qb)
    got_dbl = ed_ops.double(Pb)
    for i, (pa, qa) in enumerate(zip(pts, qts)):
        want = ecmath.ed_to_affine(ecmath.ed_point_add(
            ecmath.ed_to_extended(pa), ecmath.ed_to_extended(qa)))
        x, y, z, _ = (F.from_limbs(c[i]) for c in got_add)
        zi = pow(z, ecmath.ED_P - 2, ecmath.ED_P)
        assert (x * zi % ecmath.ED_P, y * zi % ecmath.ED_P) == want
        want_d = ecmath.ed_to_affine(ecmath.ed_point_double(ecmath.ed_to_extended(pa)))
        x, y, z, _ = (F.from_limbs(c[i]) for c in got_dbl)
        zi = pow(z, ecmath.ED_P - 2, ecmath.ED_P)
        assert (x * zi % ecmath.ED_P, y * zi % ecmath.ED_P) == want_d


def test_ed25519_verify_batch():
    items, want = [], []
    for i in range(8):
        seed = RNG.bytes(32)
        pub = ecmath.ed25519_public_key(seed)
        msg = RNG.bytes(40 + i)
        sig = ecmath.ed25519_sign(seed, msg)
        if i % 4 == 1:  # corrupt signature
            sig = sig[:10] + bytes([sig[10] ^ 1]) + sig[11:]
        if i % 4 == 2:  # corrupt message
            msg = msg[:-1] + bytes([msg[-1] ^ 0xFF])
        if i % 4 == 3:  # wrong key
            pub = ecmath.ed25519_public_key(RNG.bytes(32))
        items.append((pub, sig, msg))
        want.append(ecmath.ed25519_verify(pub, msg, sig))
    got = ed_ops.verify_batch(items)
    assert list(got) == want
    assert want[0] and not all(want)  # sanity: mix of verdicts


def test_ed25519_malformed_inputs():
    seed = RNG.bytes(32)
    pub = ecmath.ed25519_public_key(seed)
    msg = b"hello"
    sig = ecmath.ed25519_sign(seed, msg)
    bad_s = sig[:32] + (ecmath.ED_L + 1).to_bytes(32, "little")  # s >= L
    items = [
        (b"\xff" * 32, sig, msg),        # non-decompressible key
        (pub, b"\x00" * 63, msg),        # short signature
        (pub, bad_s, msg),
        (pub, sig, msg),                 # control: valid
    ]
    got = ed_ops.verify_batch(items)
    assert list(got) == [False, False, False, True]


def test_ed25519_r_encoding_edge_cases():
    """The re-encoding acceptance's R-specific rejections, each checked
    against the host oracle: a flipped x-sign bit (same y, DIFFERENT
    point), a non-canonical y (>= p, must reject like a failed
    decompression), and an off-curve y."""
    seed = RNG.bytes(32)
    pub = ecmath.ed25519_public_key(seed)
    msg = b"sign-bit coverage"
    sig = ecmath.ed25519_sign(seed, msg)
    flipped_sign = sig[:31] + bytes([sig[31] ^ 0x80]) + sig[32:]
    non_canonical = (2**255 - 10).to_bytes(32, "little") + sig[32:]
    # y = 2 is not on the curve (no x satisfies the equation)
    off_curve = (2).to_bytes(32, "little") + sig[32:]
    items = [(pub, s, msg)
             for s in (sig, flipped_sign, non_canonical, off_curve)]
    want = [ecmath.ed25519_verify(pub, msg, s)
            for _, s, _ in items]
    assert want == [True, False, False, False]  # oracle sanity
    assert list(ed_ops.verify_batch(items)) == want


# ---------------------------------------------------------------------------
# ECDSA secp256k1 / secp256r1
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("curve", [ecmath.SECP256K1, ecmath.SECP256R1],
                         ids=lambda c: c.name)
def test_wc_add_matches_host(curve):
    pts = [curve.mul(rand_scalar(curve.n), curve.g) for _ in range(4)]
    qts = [curve.mul(rand_scalar(curve.n), curve.g) for _ in range(4)]
    qts[1] = pts[1]  # doubling case through the complete formula
    Pb = (F.to_limbs([p[0] for p in pts]), F.to_limbs([p[1] for p in pts]),
          F.to_limbs([1] * 4))
    Qb = (F.to_limbs([q[0] for q in qts]), F.to_limbs([q[1] for q in qts]),
          F.to_limbs([1] * 4))
    X, Y, Z = wc_ops.add(Pb, Qb, curve)
    for i, (pa, qa) in enumerate(zip(pts, qts)):
        want = curve.add(pa, qa)
        x, y, z = F.from_limbs(X[i]), F.from_limbs(Y[i]), F.from_limbs(Z[i])
        zi = pow(z, curve.p - 2, curve.p)
        assert (x * zi % curve.p, y * zi % curve.p) == want
    # dedicated doubling formula (incl. the identity edge case)
    Ib = tuple(np.asarray(c) for c in wc_ops.identity((1,)))
    Db = tuple(np.concatenate([np.asarray(c), i_c])
               for c, i_c in zip(Pb, Ib))
    X, Y, Z = wc_ops.dbl(Db, curve)
    for i, pa in enumerate(pts):
        want = curve.add(pa, pa)
        x, y, z = F.from_limbs(X[i]), F.from_limbs(Y[i]), F.from_limbs(Z[i])
        zi = pow(z, curve.p - 2, curve.p)
        assert (x * zi % curve.p, y * zi % curve.p) == want
    assert F.from_limbs(Z[len(pts)]) % curve.p == 0  # 2·identity = identity


@pytest.mark.parametrize(
    "curve,mode",
    [(ecmath.SECP256K1, "plain"),
     (ecmath.SECP256K1, "glv"),      # endomorphism all-select ladder
     (ecmath.SECP256K1, "hybrid"),   # endomorphism + constant-G gather table
     # r1 runs in the DEFAULT tier (VERDICT r3 #5): its 224-bit Solinas fold
     # constant makes the cold compile ~4min on CPU, but the persistent
     # .jax_cache (shared by CI/driver runs on this workspace) makes warm
     # runs seconds — an untested-by-default kernel is an unshipped kernel.
     (ecmath.SECP256R1, "plain"),
     # the r1 PRODUCTION path: constant-G windows + 2-bit Q windows
     (ecmath.SECP256R1, "windowed")],
    ids=lambda v: v if isinstance(v, str) else v.name)
def test_ecdsa_verify_batch(curve, mode):
    items, want = [], []
    for i in range(8):
        priv = rand_scalar(curve.n - 1) + 1
        pub = curve.mul(priv, curve.g)
        msg = RNG.bytes(30 + i)
        r, s = ecmath.ecdsa_sign(curve, priv, msg)
        if i % 4 == 1:
            r = (r + 1) % curve.n or 1
        if i % 4 == 2:
            msg = msg + b"!"
        if i % 4 == 3:
            pub = curve.mul(rand_scalar(curve.n - 1) + 1, curve.g)
        items.append((pub, msg, r, s))
        want.append(ecmath.ecdsa_verify(curve, pub, msg, r, s))
    got = wc_ops.verify_batch(curve, items, mode=mode)
    assert list(got) == want
    assert want[0] and not all(want)


def test_hybrid_wide_window_widths_agree():
    """The wide-G ladder must verify identically at every (even) window
    width ON THE SAME INPUTS — g_w only changes how many bits one
    constant-table gather consumes, never the result (regression lock on
    the digit packing)."""
    curve = ecmath.SECP256K1
    rng = np.random.default_rng(77)
    items, want = [], []
    for i in range(8):
        priv = int.from_bytes(rng.bytes(32), "little") % (curve.n - 1) + 1
        pub = curve.mul(priv, curve.g)
        msg = rng.bytes(24 + i)
        r, s = ecmath.ecdsa_sign(curve, priv, msg)
        if i % 3 == 1:
            msg = msg + b"?"
        items.append((pub, msg, r, s))
        want.append(ecmath.ecdsa_verify(curve, pub, msg, r, s))
    for g_w in (2, 4):
        *args, precheck = wc_ops.prepare_batch_hybrid_wide(items, g_w)
        ok = np.asarray(wc_ops._verify_kernel_hybrid_wide(*args, g_w=g_w))
        assert list(ok & precheck) == want, f"g_w={g_w}"
    with pytest.raises(ValueError, match="even"):
        wc_ops.prepare_batch_hybrid_wide(items, 3)


def test_ecdsa_rejects_high_s_and_off_curve():
    curve = ecmath.SECP256K1
    priv = rand_scalar(curve.n - 1) + 1
    pub = curve.mul(priv, curve.g)
    msg = b"m"
    r, s = ecmath.ecdsa_sign(curve, priv, msg)
    items = [
        (pub, msg, r, curve.n - s),            # malleated high-s twin
        ((pub[0], (pub[1] + 1) % curve.p), msg, r, s),  # off-curve key
        (None, msg, r, s),                      # missing key
        (pub, msg, r, s),                       # control
    ]
    got = wc_ops.verify_batch(curve, items)
    assert list(got) == [False, False, False, True]
