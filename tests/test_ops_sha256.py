"""Device SHA-256 / Merkle kernels: bit-exact differential tests vs hashlib and
the host MerkleTree (the reference-parity oracle)."""
import hashlib
import os

import numpy as np
import pytest

from corda_tpu.core.crypto import MerkleTree, SecureHash
from corda_tpu.ops import sha256 as dsha


def test_sha256_single_block_messages():
    msgs = [b"", b"abc", b"a" * 55]  # all pad to 1 block
    batch = dsha.pack_batch(msgs)
    out = dsha.digests_to_bytes(dsha.sha256_blocks(batch))
    for m, d in zip(msgs, out):
        assert d == hashlib.sha256(m).digest()


def test_sha256_multi_block_messages():
    msgs = [os.urandom(100) for _ in range(8)]  # 100B -> 2 blocks
    out = dsha.digests_to_bytes(dsha.sha256_blocks(dsha.pack_batch(msgs)))
    for m, d in zip(msgs, out):
        assert d == hashlib.sha256(m).digest()
    # longer: 1000B -> 16 blocks
    msgs = [os.urandom(1000) for _ in range(4)]
    out = dsha.digests_to_bytes(dsha.sha256_blocks(dsha.pack_batch(msgs)))
    for m, d in zip(msgs, out):
        assert d == hashlib.sha256(m).digest()


def test_hash_pairs_matches_hash_concat():
    rng = np.random.default_rng(0)
    left = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(16)]
    right = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(16)]
    pairs = np.concatenate([dsha.digests_from_bytes(left),
                            dsha.digests_from_bytes(right)], axis=1)
    out = dsha.digests_to_bytes(dsha.hash_pairs(pairs))
    for l, r, d in zip(left, right, out):
        assert d == SecureHash(l).hash_concat(SecureHash(r)).bytes


@pytest.mark.parametrize("n_leaves", [1, 2, 8, 64, 256])
def test_merkle_root_matches_host_tree(n_leaves):
    leaves = [SecureHash.sha256(bytes([i % 256, i // 256])) for i in range(n_leaves)]
    from corda_tpu.core.crypto.merkle import pad_to_power_of_two
    padded = pad_to_power_of_two(leaves)
    dev = dsha.merkle_root(dsha.digests_from_bytes([h.bytes for h in padded]))
    host = MerkleTree.get_merkle_tree(leaves).hash
    assert dsha.digests_to_bytes(dev[None])[0] == host.bytes


def test_merkle_root_rejects_non_pow2():
    with pytest.raises(ValueError):
        dsha.merkle_root(np.zeros((3, 8), dtype=np.uint32))
