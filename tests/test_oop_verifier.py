"""Out-of-process verifier tests — VerifierTests.kt parity:
"verification works with N out-of-process verifiers", work redistribution on
verifier death, failure propagation, no-worker warning path.
"""
import pytest

from corda_tpu.core.contracts import Command, TransactionState
from corda_tpu.core.contracts.exceptions import TransactionVerificationException
from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.identity import Party
from corda_tpu.core.transactions import WireTransaction
from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.testing import DummyContract, DummyState, DUMMY_NOTARY_NAME
from corda_tpu.verifier.out_of_process import (
    OutOfProcessTransactionVerifierService, VerifierWorker)

NOTARY = Party(DUMMY_NOTARY_NAME, generate_keypair(entropy=b"\x51" * 32).public)
ALICE_KP = generate_keypair(entropy=b"\x52" * 32)


def make_ltx(i, valid=True):
    from corda_tpu.core.contracts.structures import AuthenticatedObject
    from corda_tpu.core.transactions.ledger import LedgerTransaction
    wtx = WireTransaction(
        outputs=(TransactionState(DummyState(i, (ALICE_KP.public,)), NOTARY),),
        commands=(Command(DummyContract.Create(), (ALICE_KP.public,)),),
        notary=NOTARY, must_sign=(ALICE_KP.public,) if valid else ())
    return LedgerTransaction(
        inputs=(), outputs=wtx.outputs,
        commands=tuple(AuthenticatedObject(c.signers, (), c.value)
                       for c in wtx.commands),
        attachments=(), id=wtx.id, notary=wtx.notary, must_sign=wtx.must_sign,
        type=wtx.type, time_window=None)


@pytest.fixture
def bus():
    return InMemoryMessagingNetwork()


def test_single_worker_verifies(bus):
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    worker = VerifierWorker(bus.create_node("w1"), "node")
    bus.run_network()
    futures = [svc.verify(make_ltx(i)) for i in range(20)]
    bus.run_network()
    for f in futures:
        assert f.result(timeout=1) is None
    assert worker.verified_count == 20
    snap = svc.metrics.snapshot()
    assert snap["Verification.Success"]["count"] == 20


def test_work_is_shared_across_workers(bus):
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    workers = [VerifierWorker(bus.create_node(f"w{i}"), "node")
               for i in range(4)]
    bus.run_network()
    futures = [svc.verify(make_ltx(i)) for i in range(40)]
    bus.run_network()
    for f in futures:
        assert f.result(timeout=1) is None
    counts = [w.verified_count for w in workers]
    assert all(c == 10 for c in counts), counts  # round-robin deal


def test_redistribution_on_worker_death(bus):
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    w1 = VerifierWorker(bus.create_node("w1"), "node")
    w2 = VerifierWorker(bus.create_node("w2"), "node")
    bus.run_network()
    futures = [svc.verify(make_ltx(i)) for i in range(30)]
    # w1 dies BEFORE pumping: its dealt share is still in flight
    w1.stop(announce=False)
    svc.queue.detach_worker("w1")
    bus.run_network()
    for f in futures:
        assert f.result(timeout=1) is None
    assert w1.verified_count == 0
    assert w2.verified_count == 30


def test_failure_propagates(bus):
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    VerifierWorker(bus.create_node("w1"), "node")
    bus.run_network()
    fut = svc.verify(make_ltx(1, valid=False))  # required signer missing
    bus.run_network()
    with pytest.raises(TransactionVerificationException):
        fut.result(timeout=1)
    assert svc.metrics.snapshot()["Verification.Failure"]["count"] == 1


def _pump_until(bus, futures, timeout=90.0):
    """Pump the manual bus until every future resolves (the device path
    replies from worker threads, so replies land between pumps)."""
    import time
    deadline = time.monotonic() + timeout
    while not all(f.done() for f in futures):
        bus.run_network()
        time.sleep(0.005)
        assert time.monotonic() < deadline, "verifications did not complete"


def test_device_path_through_worker(bus):
    """VERDICT r2 #1a: requests carrying signatures run their EC math through
    the worker's device batcher — the out-of-process scale-out story with
    the TPU actually in the worker."""
    from corda_tpu.testing.generated_ledger import make_generated_ledger
    from corda_tpu.testing.services import MockServices
    from corda_tpu.verifier.batcher import SignatureBatcher

    ledger = make_generated_ledger(12, seed=7)
    services = MockServices()
    for stx in ledger.transactions:
        services.record_transactions(stx)
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    batcher = SignatureBatcher(use_device=True, host_crossover=0,
                               max_latency_s=0.01)
    worker = VerifierWorker(bus.create_node("w1"), "node", batcher=batcher)
    bus.run_network()
    futures = [svc.verify_signed(stx, services)
               for stx in ledger.transactions]
    _pump_until(bus, futures)
    for f in futures:
        assert f.result(timeout=1) is None
    snap = batcher.metrics.snapshot()
    assert snap["SigBatcher.DeviceBatches"]["count"] > 0
    assert snap["SigBatcher.DeviceChecked"]["count"] >= len(futures)
    worker.stop()


def test_device_path_rejects_bad_signature(bus):
    """A transaction whose signature does not match its id must fail through
    the worker device path with a signature error."""
    from corda_tpu.core.crypto.signatures import Crypto
    from corda_tpu.core.transactions.signed import SignedTransaction
    from corda_tpu.core.transactions.wire import WireTransaction
    from corda_tpu.testing.services import MockServices
    from corda_tpu.verifier.batcher import SignatureBatcher

    wtx = WireTransaction(
        outputs=(TransactionState(DummyState(1, (ALICE_KP.public,)), NOTARY),),
        commands=(Command(DummyContract.Create(), (ALICE_KP.public,)),),
        notary=NOTARY, must_sign=(ALICE_KP.public,))
    bad_sig = Crypto.sign_with_key(ALICE_KP, b"some other content")
    stx = SignedTransaction.of(wtx, [bad_sig])

    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    batcher = SignatureBatcher(use_device=True, host_crossover=0,
                               max_latency_s=0.01)
    worker = VerifierWorker(bus.create_node("w1"), "node", batcher=batcher)
    bus.run_network()
    fut = svc.verify_signed(stx, MockServices())
    _pump_until(bus, [fut])
    with pytest.raises(TransactionVerificationException,
                       match="did not verify"):
        fut.result(timeout=1)
    worker.stop()


def test_requests_queue_until_worker_attaches(bus):
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    futures = [svc.verify(make_ltx(i)) for i in range(5)]
    bus.run_network()
    assert not any(f.done() for f in futures)
    VerifierWorker(bus.create_node("late"), "node")
    bus.run_network()
    for f in futures:
        assert f.result(timeout=1) is None


def test_fleet_status_and_worker_gauges(bus):
    """Hello carries device shard + capacity; the node exposes them via
    fleet_status() (the /readyz payload) and per-worker Fleet.* gauges on
    the metrics registry (the /metrics payload)."""
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node, expected_workers=2)
    w1 = VerifierWorker(bus.create_node("w1"), "node",
                        device_shard=(0, 1), capacity=2)
    bus.run_network()

    status = svc.fleet_status()
    assert status["expected"] == 2
    assert status["attached"] == 1
    assert status["degraded"] is True          # 1 of 2 → degraded
    assert status["workers"]["w1"]["device_shard"] == [0, 1]
    assert status["workers"]["w1"]["capacity"] == 2

    snap = svc.metrics.snapshot()
    assert snap["Fleet.WorkersAttached"]["value"] == 1
    assert snap["Fleet.WorkerCapacity.w1"]["value"] == 2
    assert snap["Fleet.WorkerQueueDepth.w1"]["value"] == 0

    w2 = VerifierWorker(bus.create_node("w2"), "node")
    bus.run_network()
    status = svc.fleet_status()
    assert status["attached"] == 2 and status["degraded"] is False

    w2.stop()   # graceful goodbye detaches; gauges read 0, not KeyError
    bus.run_network()
    snap = svc.metrics.snapshot()
    assert svc.fleet_status()["degraded"] is True
    assert snap["Fleet.WorkerCapacity.w2"]["value"] == 0
    w1.stop()


def test_load_aware_routing_prefers_idle_worker(bus):
    """A worker reporting a deep backlog must stop receiving new deals
    while an idle worker is in the slack band."""
    from corda_tpu.verifier.out_of_process import WorkerLoadReport
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    busy = VerifierWorker(bus.create_node("busy"), "node")
    idle = VerifierWorker(bus.create_node("idle"), "node")
    bus.run_network()

    # hand-deliver the reports (deterministic: no worker threads involved)
    svc.queue._on_load_report(WorkerLoadReport("busy", pending=64,
                                               in_flight=12))
    svc.queue._on_load_report(WorkerLoadReport("idle", pending=0,
                                               in_flight=0))
    futures = [svc.verify(make_ltx(i)) for i in range(8)]
    bus.run_network()
    for f in futures:
        assert f.result(timeout=1) is None
    assert idle.verified_count == 8
    assert busy.verified_count == 0
    busy.stop()
    idle.stop()


def test_submit_spans_finish_exactly_once_across_crash_requeue(bus):
    """Regression: the node-side verifier.oop_submit span must finish
    EXACTLY once per request even when the dealt worker crashes and the
    share is requeued to a survivor — no leaked live spans in svc._spans,
    no duplicate finished spans in the ring."""
    from corda_tpu.observability import disable_tracing, enable_tracing
    tracer = enable_tracing()
    try:
        node = bus.create_node("node")
        svc = OutOfProcessTransactionVerifierService(node)
        w1 = VerifierWorker(bus.create_node("w1"), "node")
        w2 = VerifierWorker(bus.create_node("w2"), "node")
        bus.run_network()
        futures = [svc.verify(make_ltx(i)) for i in range(10)]
        # w1 dies BEFORE pumping: its dealt share is requeued to w2
        w1.stop(announce=False)
        svc.queue.detach_worker("w1")
        bus.run_network()
        for f in futures:
            assert f.result(timeout=1) is None
        # every submit span finished exactly once, none leaked live
        assert svc._spans == {}
        submits = [s for s in tracer.ring.snapshot()
                   if s["name"] == "verifier.oop_submit"]
        assert len(submits) == len(futures)
        assert all(s["duration_s"] > 0 for s in submits)
        # the requeue left a lifecycle breadcrumb for the moved requests
        moved = [vid for vid, tl in
                 ((int(k), v) for k, v in svc.request_log.snapshot().items())
                 if any(e["event"] == "requeued" for e in tl)]
        assert moved, "no request recorded the worker-detached requeue"
        for vid in moved:
            assert svc.request_log.terminal_count(vid) == 1
        w2.stop()
    finally:
        disable_tracing()


def test_stale_worker_flagged_degraded(bus):
    """A worker whose last load report is older than 3× the report
    interval is flagged stale in fleet_status() — attached but possibly
    wedged — and the fleet reads degraded (the /readyz surface)."""
    import time
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(
        node, expected_workers=1, load_report_interval_s=0.02)
    w1 = VerifierWorker(bus.create_node("w1"), "node")
    bus.run_network()
    w1.send_load_report()
    bus.run_network()

    status = svc.fleet_status()
    assert status["workers"]["w1"]["stale"] is False
    assert status["workers"]["w1"]["last_report_age_s"] is not None
    assert status["stale"] == [] and status["degraded"] is False

    time.sleep(0.08)   # > 3× the 0.02s interval with no further report
    status = svc.fleet_status()
    assert status["workers"]["w1"]["stale"] is True
    assert status["stale"] == ["w1"]
    assert status["degraded"] is True

    w1.send_load_report()   # a fresh report clears the flag
    bus.run_network()
    status = svc.fleet_status()
    assert status["workers"]["w1"]["stale"] is False
    assert status["degraded"] is False
    w1.stop()
