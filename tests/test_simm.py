"""SIMM valuation demo tests (SimmValuationTest analog)."""
import numpy as np
import pytest

from corda_tpu.flows import FlowException
from corda_tpu.samples.simm_valuation import (AGREEMENT_TOLERANCE_CENTS,
                                              RISK_WEIGHTS, SimmRevaluationFlow,
                                              compute_margin_cents,
                                              correlation_matrix,
                                              demo_portfolio)
from corda_tpu.testing import MockNetwork


def numpy_margin_cents(sens) -> int:
    ws = RISK_WEIGHTS * np.sum(np.asarray(sens, dtype=np.float32), axis=0)
    return int(round(float(np.sqrt(ws @ correlation_matrix() @ ws)) * 100))


def test_device_margin_matches_reference():
    book = demo_portfolio()
    got = compute_margin_cents(book)
    want = numpy_margin_cents(book)
    assert abs(got - want) <= 2      # float32 device vs host rounding
    assert got > 0
    # margin is subadditive in offsetting trades: netting reduces it
    offset = np.concatenate([book, -book])
    assert compute_margin_cents(offset) <= got


def test_two_party_agreement():
    network = MockNetwork()
    a = network.create_node("O=Dealer A, L=London, C=GB")
    b = network.create_node("O=Dealer B, L=New York, C=US")
    network.start_nodes()
    book = demo_portfolio()
    fsm = a.start_flow(SimmRevaluationFlow(b.party, book))
    network.run_network()
    out = fsm.result_future.result(timeout=10)
    assert abs(out["margin_cents"] - out["counterparty_margin"]) \
        <= AGREEMENT_TOLERANCE_CENTS
    assert out["signature"]          # counterparty signed the agreed figure


def test_disagreement_refused(monkeypatch):
    """A proposal outside the counterparty's tolerance gets no signature and
    the initiator fails with the disagreement (tolerance forced negative so
    even an exact match counts as out-of-tolerance)."""
    import corda_tpu.samples.simm_valuation as simm
    monkeypatch.setattr(simm, "AGREEMENT_TOLERANCE_CENTS", -1)
    network = MockNetwork()
    a = network.create_node("O=Dealer A, L=London, C=GB")
    b = network.create_node("O=Dealer B, L=New York, C=US")
    network.start_nodes()
    fsm = a.start_flow(SimmRevaluationFlow(b.party, demo_portfolio()))
    network.run_network()
    with pytest.raises(FlowException, match="disagrees"):
        fsm.result_future.result(timeout=10)
