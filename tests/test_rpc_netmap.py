"""RPC ops surface + network map service tests.

Reference analogs: CordaRPCOpsImpl tests, NetworkMapServiceTest (registration,
fetch, subscribe-push).
"""
import pytest

from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.finance import CashIssueFlow, CashState
from corda_tpu.network.netmap import NetworkMapClient, NetworkMapService
from corda_tpu.node.rpc import CordaRPCOps, FlowPermissionException
from corda_tpu.testing import MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank = network.create_node("O=Bank, L=London, C=GB")
    network.start_nodes()
    return network, notary, bank


def test_rpc_start_flow_and_feeds(net):
    network, notary, bank = net
    rpc = CordaRPCOps(bank.services, bank.smm)
    assert "CashIssueFlow" in str(rpc.registered_flows())
    events = []
    rpc.state_machines_feed().subscribe(events.append)
    vault_updates = []
    rpc.vault_feed().subscribe(vault_updates.append)

    fsm = rpc.start_flow_dynamic("CashIssueFlow", Amount(5000, USD), b"\x01",
                                 bank.party, notary.party)
    network.run_network()
    fsm.result_future.result(timeout=1)
    assert [s.state.data.amount.quantity for s in rpc.vault_snapshot(CashState)] \
        == [5000]
    assert rpc.verified_transactions_snapshot()
    assert any(e[0] == "add" for e in events)
    assert any(e[0] == "remove" for e in events)
    assert vault_updates and vault_updates[0].produced

    with pytest.raises(FlowPermissionException):
        rpc.start_flow_dynamic("NotAFlow")
    # a flow class without @StartableByRPC is refused
    from corda_tpu.flows.library import NotaryFlow
    with pytest.raises(FlowPermissionException):
        rpc.start_flow_dynamic(NotaryFlow, None)

    assert rpc.notary_identities() == [notary.party]
    assert rpc.parties_from_name("Bank") == {bank.party}
    att_id = rpc.upload_attachment(b"some jar bytes")
    assert rpc.attachment_exists(att_id)
    assert rpc.open_attachment(att_id).data == b"some jar bytes"


def test_network_map_register_fetch_push():
    network = MockNetwork()
    mapnode = network.create_node("O=Map Service, L=London, C=GB")
    a = network.create_node("O=Alpha, L=Oslo, C=NO")
    b = network.create_node("O=Beta, L=Rome, C=IT")
    network.start_nodes()
    NetworkMapService(mapnode.messaging)
    map_name = str(mapnode.party.name)

    # Alpha registers, Beta subscribes then fetches: Beta learns Alpha
    b.services.network_map_cache.remove_node(str(a.party.name))
    a_client = NetworkMapClient(a.services, map_name)
    b_client = NetworkMapClient(b.services, map_name)
    b_client.subscribe()
    network.run_network()
    a_client.register()
    network.run_network()
    assert b.services.network_map_cache.party_from_name(str(a.party.name)) \
        == a.party

    # fetch-from-scratch also works
    b.services.network_map_cache.remove_node(str(a.party.name))
    b_client.fetch()
    network.run_network()
    assert b.services.network_map_cache.party_from_name(str(a.party.name)) \
        == a.party

    # a forged registration (wrong signer) is ignored
    from corda_tpu.network.netmap import NodeRegistration, ADD
    from corda_tpu.core.serialization import serialize
    forged_info = serialize(a.info)
    sig = b.services.key_management.sign(
        forged_info + bytes([9]), b.party.owning_key)
    forged = NodeRegistration(forged_info, 9, ADD, sig)
    from corda_tpu.network.messaging import (TOPIC_NETWORK_MAP_REGISTER,
                                             TopicSession)
    b.messaging.send(TopicSession(TOPIC_NETWORK_MAP_REGISTER),
                     serialize(forged), map_name)
    network.run_network()
    # serial 9 must NOT have been accepted for Alpha (signature by Beta's key)
    # → a re-fetch still returns Alpha's original serial-1 registration
    b.services.network_map_cache.remove_node(str(a.party.name))
    b_client.fetch()
    network.run_network()
    assert b.services.network_map_cache.party_from_name(str(a.party.name)) \
        == a.party
