"""FleetController unit suite: a fake clock, a scripted SLO, and stub
actuators — every control-loop property pinned without threads or sleep.

The properties under test are the ones that make the controller safe to
leave unattended: it does NOTHING while the SLO is healthy, it escalates
capacity before concessions, the degradation ladder applies in order and
reverts in reverse order, reversal demands a SUSTAINED healthy streak
(no flapping on an oscillating signal), scale-down never runs while the
error budget is scorched, and stale workers are reaped in any state.
"""
import pytest

from corda_tpu.utils.metrics import MetricRegistry
from corda_tpu.verifier.controller import (ControllerConfig, FleetController,
                                           LadderStep, apply_degradations,
                                           batcher_ladder)


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


class FakeSLO:
    """Scripted burn state: tests flip ``alerting`` / ``budget_pct``."""

    def __init__(self):
        self.alerting = False
        self.budget_pct = 100.0
        self.objectives = ("availability",)

    def alerts(self):
        if not self.alerting:
            return []
        return [{"objective": "availability", "severity": "page",
                 "burn_rate": 20.0, "windows_s": (60, 3600)}]

    def error_budget_pct(self, obj):
        return self.budget_pct


class Rung:
    def __init__(self, name, trail):
        self.step = LadderStep(name,
                               apply=lambda: trail.append(f"+{name}"),
                               revert=lambda: trail.append(f"-{name}"))


def build(slo=None, workers=2, ladder=(), reaped=None, **cfg_kw):
    """A controller over stub seams. Returns (controller, clock, state)
    where ``state`` records worker count and the action trail."""
    clock = FakeClock()
    state = {"workers": workers, "depth": 0.0, "trail": [],
             "reaped": list(reaped or ()), "breakers": 0}

    def spawn():
        state["workers"] += 1
        state["trail"].append("spawn")
        return f"w{state['workers']}"

    def retire():
        state["workers"] -= 1
        state["trail"].append("retire")
        return f"w{state['workers'] + 1}"

    def reap():
        out, state["reaped"] = state["reaped"], []
        return out

    cfg_kw.setdefault("scale_cooldown_s", 1.0)
    cfg_kw.setdefault("step_cooldown_s", 1.0)
    cfg_kw.setdefault("healthy_ticks", 3)
    ctl = FleetController(
        slo=slo, worker_count=lambda: state["workers"],
        queue_depth=lambda: state["depth"],
        spawn=spawn, retire=retire, reap_stale=reap,
        breaker_open_count=lambda: state["breakers"],
        ladder=ladder, config=ControllerConfig(**cfg_kw),
        clock=clock, metrics=MetricRegistry())
    return ctl, clock, state


def tick_n(ctl, clock, n, dt=1.0):
    acts = []
    for _ in range(n):
        clock.advance(dt)
        acts.extend(ctl.tick())
    return acts


def test_healthy_slo_means_zero_actions():
    slo = FakeSLO()
    ctl, clock, state = build(slo=slo, workers=2, min_workers=1,
                              max_workers=8)
    acts = tick_n(ctl, clock, 20)
    assert acts == []
    assert ctl.actions_total == 0
    assert ctl.state == "steady"
    assert state["workers"] == 2
    assert state["trail"] == []


def test_scale_up_before_ladder_and_one_action_per_cooldown():
    slo = FakeSLO()
    trail = []
    ladder = (Rung("shed_bulk", trail).step,)
    ctl, clock, state = build(slo=slo, workers=1, max_workers=3,
                              ladder=ladder)
    slo.alerting = True
    tick_n(ctl, clock, 2)
    # capacity first: both scale-ups happen before any concession
    assert state["trail"] == ["spawn", "spawn"]
    assert trail == []
    assert ctl.state == "stressed"
    # at max_workers the ladder engages
    tick_n(ctl, clock, 1)
    assert trail == ["+shed_bulk"]
    assert ctl.state == "degraded"


def test_scale_cooldown_limits_spawn_rate():
    slo = FakeSLO()
    ctl, clock, state = build(slo=slo, workers=1, max_workers=8,
                              scale_cooldown_s=10.0)
    slo.alerting = True
    tick_n(ctl, clock, 5, dt=1.0)       # 5 s elapsed < cooldown
    assert state["trail"].count("spawn") == 1
    tick_n(ctl, clock, 6, dt=1.0)       # crosses the 10 s cooldown once
    assert state["trail"].count("spawn") == 2


def test_ladder_applies_in_order_and_reverts_in_reverse():
    slo = FakeSLO()
    trail = []
    ladder = tuple(Rung(n, trail).step for n in
                   ("shed_bulk", "shrink_ladder", "host_route"))
    ctl, clock, state = build(slo=slo, workers=2, max_workers=2,
                              ladder=ladder)
    slo.alerting = True
    tick_n(ctl, clock, 3)
    assert trail == ["+shed_bulk", "+shrink_ladder", "+host_route"]
    assert ctl.ladder_step == 3
    assert ctl.state == "degraded"
    # recovery: reverts walk back-to-front, one per healthy streak window
    slo.alerting = False
    trail.clear()
    tick_n(ctl, clock, 12)
    assert trail == ["-host_route", "-shrink_ladder", "-shed_bulk"]
    assert ctl.ladder_step == 0
    assert ctl.state == "steady"


def test_no_flap_reversal_requires_sustained_health():
    slo = FakeSLO()
    trail = []
    ladder = (Rung("shed_bulk", trail).step,)
    ctl, clock, state = build(slo=slo, workers=2, max_workers=2,
                              ladder=ladder, healthy_ticks=3)
    slo.alerting = True
    tick_n(ctl, clock, 1)
    assert trail == ["+shed_bulk"]
    # oscillate: 2 healthy ticks, then an alert blip, forever — the
    # healthy streak never reaches 3, so the rung must NEVER revert
    for _ in range(6):
        slo.alerting = False
        tick_n(ctl, clock, 2)
        slo.alerting = True
        tick_n(ctl, clock, 1)
    assert "-shed_bulk" not in trail
    assert ctl.ladder_step == 1


def test_scale_down_waits_for_budget_and_only_returns_spawned():
    slo = FakeSLO()
    ctl, clock, state = build(slo=slo, workers=2, min_workers=1,
                              max_workers=8, budget_scale_down_pct=50.0)
    # burn: the controller grows the fleet it will later shrink
    slo.alerting = True
    tick_n(ctl, clock, 2)
    assert state["workers"] == 4
    # alerts clear but the budget is still scorched: no give-back yet
    slo.alerting = False
    slo.budget_pct = 10.0
    tick_n(ctl, clock, 10)
    assert state["trail"].count("retire") == 0
    assert state["workers"] == 4
    # budget heals → the two SPAWNED workers return; the operator's
    # baseline two are never touched even though min_workers is 1
    slo.budget_pct = 90.0
    tick_n(ctl, clock, 30)
    assert state["workers"] == 2
    assert state["trail"].count("retire") == 2
    assert ctl.state == "steady"
    tick_n(ctl, clock, 10)
    assert state["workers"] == 2


def test_stale_reap_runs_in_any_state_and_counts_as_action():
    slo = FakeSLO()
    ctl, clock, state = build(slo=slo, workers=3,
                              reaped=["w1", "w2"])
    acts = tick_n(ctl, clock, 1)
    kinds = [a["action"] for a in acts]
    assert kinds == ["stale_detach", "stale_detach"]
    assert {a["worker"] for a in acts} == {"w1", "w2"}
    assert ctl.actions_total == 2
    # the detaches opened an episode; sustained health closes it
    tick_n(ctl, clock, 10)
    assert ctl.state == "steady"
    assert ctl.status()["recovery_s_last"] is not None


def test_queue_trend_alone_triggers_stress_without_slo():
    ctl, clock, state = build(slo=None, workers=1, max_workers=4,
                              queue_high=100.0, queue_low=10.0)
    state["depth"] = 100_000.0
    tick_n(ctl, clock, 3)
    assert state["trail"].count("spawn") >= 1
    assert ctl.state == "stressed"
    state["depth"] = 0.0
    tick_n(ctl, clock, 40)
    assert ctl.state == "steady"
    assert state["workers"] == 1


def test_status_shape():
    slo = FakeSLO()
    ctl, clock, state = build(slo=slo, workers=2)
    tick_n(ctl, clock, 1)
    st = ctl.status()
    for key in ("state", "workers", "queue_depth_trend", "ladder",
                "ladder_step", "actions_total", "recent_actions",
                "episodes", "recovery_s_last", "healthy_streak"):
        assert key in st, key
    assert st["state"] == "steady"
    assert st["workers"] == 2
    assert st["actions_total"] == 0


def test_batcher_ladder_tracks_live_batcher_list():
    class FakeBatcher:
        def __init__(self):
            self.calls = []

        def shed_bulk(self, on):
            self.calls.append(("shed_bulk", on))

        def shrink_ladder(self, on):
            self.calls.append(("shrink_ladder", on))

        def route_interactive_host(self, on):
            self.calls.append(("route_interactive_host", on))

    batchers = [FakeBatcher()]
    ladder = batcher_ladder(batchers)
    assert [s.name for s in ladder] == \
        ["shed_bulk", "shrink_ladder", "host_route_interactive"]
    ladder[0].apply()
    ladder[0].applied = True
    # a batcher appended AFTER the rung applied still gets the revert
    late = FakeBatcher()
    batchers.append(late)
    apply_degradations(ladder, late)    # spawned mid-episode: inherit
    assert late.calls == [("shed_bulk", True)]
    ladder[0].revert()
    assert ("shed_bulk", False) in late.calls
    assert batchers[0].calls == [("shed_bulk", True), ("shed_bulk", False)]


def test_breaker_open_counts_as_stress():
    ctl, clock, state = build(slo=None, workers=1, max_workers=2)
    state["breakers"] = 1
    tick_n(ctl, clock, 2)
    assert state["trail"].count("spawn") == 1
    assert ctl.state == "stressed"
