"""Vault QueryCriteria engine: composition, paging, sorting, time conditions.

Reference analog: VaultQueryTests.kt (2,065 LoC exercising QueryCriteria.kt
axes through vaultQueryBy) — here against the in-memory predicate engine
(corda_tpu/node/query.py) over a cash ledger built with real flows.
"""
import pytest

from corda_tpu.core.contracts.amount import Amount, GBP, USD
from corda_tpu.finance import CashIssueFlow, CashPaymentFlow, CashState
from corda_tpu.node.query import (FungibleAssetQueryCriteria,
                                  CustomQueryCriteria, PageSpecification,
                                  Sort, VaultQueryCriteria, VaultQueryError,
                                  between, equal, greater_than,
                                  greater_than_or_equal, less_than)
from corda_tpu.testing import MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank = network.create_node("O=Bank, L=London, C=GB")
    alice = network.create_node("O=Alice, L=Madrid, C=ES")
    network.start_nodes()
    for qty, ccy, ref in ((100, USD, b"\x01"), (250, USD, b"\x02"),
                          (40, GBP, b"\x03")):
        fsm = bank.start_flow(CashIssueFlow(Amount(qty * 100, ccy), ref,
                                            bank.party, notary.party))
        network.run_network()
        fsm.result_future.result(timeout=1)
    return network, notary, bank, alice


def test_status_and_type_axes(net):
    network, notary, bank, alice = net
    page = bank.services.vault.query_by(
        VaultQueryCriteria(contract_state_types=(CashState,)))
    assert page.total_states_available == 3
    # consume one by paying alice
    fsm = bank.start_flow(CashPaymentFlow(Amount(100 * 100, USD), alice.party))
    network.run_network()
    fsm.result_future.result(timeout=1)
    consumed = bank.services.vault.query_by(VaultQueryCriteria(status="consumed"))
    assert consumed.total_states_available >= 1
    everything = bank.services.vault.query_by(VaultQueryCriteria(status="all"))
    assert everything.total_states_available > consumed.total_states_available


def test_fungible_criteria_quantity_and_issuer(net):
    network, notary, bank, alice = net
    vault = bank.services.vault
    big = vault.query_by(FungibleAssetQueryCriteria(
        quantity=greater_than(100 * 100)))
    assert [s.state.data.amount.quantity
            for s in big.states] == [250 * 100]
    small = vault.query_by(FungibleAssetQueryCriteria(
        quantity=less_than(50 * 100)))
    assert [s.state.data.amount.quantity for s in small.states] == [40 * 100]
    ref2 = vault.query_by(FungibleAssetQueryCriteria(issuer_ref=(b"\x02",)))
    assert ref2.total_states_available == 1
    issuer = vault.query_by(FungibleAssetQueryCriteria(issuer=(bank.party,)))
    assert issuer.total_states_available == 3
    rng = vault.query_by(FungibleAssetQueryCriteria(
        quantity=between(40 * 100, 100 * 100)))
    assert rng.total_states_available == 2


def test_custom_criteria_and_composition(net):
    network, notary, bank, alice = net
    vault = bank.services.vault
    usd = CustomQueryCriteria(attribute="amount.token.product.code",
                              predicate=equal("USD"))
    big = FungibleAssetQueryCriteria(quantity=greater_than_or_equal(100 * 100))
    both = vault.query_by(usd & big)
    assert both.total_states_available == 2
    either = vault.query_by(
        CustomQueryCriteria(attribute="amount.token.product.code",
                            predicate=equal("GBP")) | big)
    assert either.total_states_available == 3


def test_sorting_and_paging(net):
    network, notary, bank, alice = net
    vault = bank.services.vault
    page = vault.query_by(
        VaultQueryCriteria(),
        sorting=Sort((("quantity", "DESC"),)))
    qtys = [s.state.data.amount.quantity for s in page.states]
    assert qtys == sorted(qtys, reverse=True)
    p1 = vault.query_by(VaultQueryCriteria(),
                        paging=PageSpecification(1, 2),
                        sorting=Sort((("quantity", "ASC"),)))
    p2 = vault.query_by(VaultQueryCriteria(),
                        paging=PageSpecification(2, 2),
                        sorting=Sort((("quantity", "ASC"),)))
    assert p1.total_states_available == 3 and p2.total_states_available == 3
    assert len(p1.states) == 2 and len(p2.states) == 1
    all_q = ([s.state.data.amount.quantity for s in p1.states]
             + [s.state.data.amount.quantity for s in p2.states])
    assert all_q == sorted(all_q)
    with pytest.raises(VaultQueryError):
        PageSpecification(0, 10)


def test_time_condition_and_soft_lock_axes(net):
    network, notary, bank, alice = net
    vault = bank.services.vault
    from corda_tpu.node.query import TimeCondition
    import datetime
    now = datetime.datetime.now(datetime.timezone.utc)
    past = vault.query_by(VaultQueryCriteria(time_condition=TimeCondition(
        "recorded", less_than(now + datetime.timedelta(minutes=1)))))
    assert past.total_states_available == 3
    future = vault.query_by(VaultQueryCriteria(time_condition=TimeCondition(
        "recorded", greater_than(now + datetime.timedelta(minutes=1)))))
    assert future.total_states_available == 0
    # soft-lock one state; locked/unlocked filters partition the vault
    sar = vault.unconsumed_states(CashState)[0]
    vault.soft_lock_reserve("flow-1", [sar.ref])
    locked = vault.query_by(VaultQueryCriteria(soft_locking="locked_only"))
    unlocked = vault.query_by(VaultQueryCriteria(soft_locking="unlocked_only"))
    assert locked.total_states_available == 1
    assert unlocked.total_states_available == 2
