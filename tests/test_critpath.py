"""Critical-path extractor (observability/critpath.py) on synthetic span
trees: blame conservation, overlapping children, pre-root admission
waits, orphans, zero-duration spans, and parent-pointer cycles (must
terminate, never hang). Plus the flat ledger_critpath_* artifact fields
and the critpath CLI renderer."""
import pytest

from corda_tpu.observability.critpath import (COMPONENTS, WAIT_KINDS,
                                              aggregate_critpaths,
                                              component_of, critical_path,
                                              critpath_report, flow_kind,
                                              ledger_critpath_fields)

PAY = "corda_tpu.finance.cash.CashPaymentFlow"


def _span(name, span_id, parent_id=None, start=0.0, dur=0.0, **tags):
    return {"name": name, "trace_id": "t1", "span_id": span_id,
            "parent_id": parent_id, "start_s": start, "duration_s": dur,
            "tags": tags}


def _commit_tree():
    """flow.run [0,10] with verify [1,4], a notary park [4,9], and a
    scheduler-admission wait [-2,0] that precedes the root (submit
    happens before launch)."""
    return [
        _span("flow.run", "r", start=0.0, dur=10.0, flow_type=PAY),
        _span("wait.scheduler_admission", "a", "r", start=-2.0, dur=2.0,
              wait_kind="scheduler.admission"),
        _span("tx.verify", "v", "r", start=1.0, dur=3.0),
        _span("wait.await_future", "n", "r", start=4.0, dur=5.0,
              wait_kind="notary.commit"),
    ]


def test_blame_conserves_e2e_and_extends_to_submit():
    cp = critical_path(_commit_tree())
    # e2e spans submit (-2) to resolution (10), not launch to resolution
    assert cp["e2e_ms"] == pytest.approx(12000.0)
    assert cp["flow_type"] == PAY
    assert sum(cp["blame_ms"].values()) == pytest.approx(cp["e2e_ms"])
    assert cp["blame_ms"] == {
        "scheduler.wait": pytest.approx(2000.0),
        "flow.compute": pytest.approx(2000.0),    # [0,1] + [9,10] self-time
        "verify": pytest.approx(3000.0),
        "notary.batch_wait": pytest.approx(5000.0),
    }
    assert cp["dominant"] == "notary.batch_wait"
    # chronological chain, annotated with wait kinds
    assert [s["name"] for s in cp["segments"]] == [
        "wait.scheduler_admission", "flow.run", "tx.verify",
        "wait.await_future", "flow.run"]
    assert cp["segments"][3]["wait_kind"] == "notary.commit"


def test_overlapping_children_charge_the_blocking_one():
    """Two verify children overlap [2,6); the blocking chain charges each
    instant to exactly one span (the last-finishing one wins the overlap),
    so blame still sums to e2e."""
    spans = [
        _span("flow.run", "r", start=0.0, dur=10.0, flow_type=PAY),
        _span("tx.verify", "v1", "r", start=1.0, dur=5.0),   # [1,6]
        _span("tx.verify", "v2", "r", start=2.0, dur=7.0),   # [2,9]
    ]
    cp = critical_path(spans)
    assert cp["e2e_ms"] == pytest.approx(10000.0)
    assert sum(cp["blame_ms"].values()) == pytest.approx(10000.0)
    # v2 owns [2,9], v1 only its unshadowed prefix [1,2], root [0,1]+[9,10]
    assert cp["blame_ms"] == {"flow.compute": pytest.approx(2000.0),
                              "verify": pytest.approx(8000.0)}


def test_orphan_and_foreign_spans_do_not_claim_time():
    spans = _commit_tree() + [
        _span("worker.device_dispatch", "o1", parent_id="never-arrived",
              start=0.0, dur=50.0),
        {"bogus": "not a span"},
        _span("", "z"),   # zero-duration, nameless
    ]
    cp = critical_path(spans)
    assert cp["root_name"] == "flow.run"   # orphan is longer but not root
    assert cp["e2e_ms"] == pytest.approx(12000.0)
    assert sum(cp["blame_ms"].values()) == pytest.approx(12000.0)


def test_foreign_admission_waits_cannot_inflate_the_chain():
    """Regression pin: a stitched trace carries the responder and notary
    flows' own wait.scheduler_admission spans too. Only the ROOT flow's
    admission wait (parented to the root) extends the chain to submit —
    counting the others stacked overlapping pre-root segments and blew
    blame past e2e on full ledger runs."""
    resp = _span("flow.run", "rr", "n", start=5.0, dur=2.0)
    spans = _commit_tree() + [
        resp,
        # responder's admission wait: parented to ITS flow.run, and it
        # started before the root's launch — must NOT be prepended
        _span("wait.scheduler_admission", "ra", "rr", start=-1.5, dur=6.5,
              wait_kind="scheduler.admission"),
        # stray parentless admission wait (its flow.run was evicted)
        _span("wait.scheduler_admission", "sa", None, start=-3.0, dur=2.5,
              wait_kind="scheduler.admission"),
    ]
    cp = critical_path(spans)
    assert cp["e2e_ms"] == pytest.approx(12000.0)
    assert sum(cp["blame_ms"].values()) == pytest.approx(cp["e2e_ms"])
    assert cp["blame_ms"]["scheduler.wait"] == pytest.approx(2000.0)


def test_child_starting_before_parent_is_clamped():
    """Regression pin: retroactive wait spans and stitched responder
    flows can START before their parent span. The walk clamps every
    child's window inside its parent's, so the early overhang cannot be
    charged twice (it blew pay blame to 4× e2e on full ledger runs)."""
    spans = [
        _span("flow.run", "r", start=0.0, dur=10.0, flow_type=PAY),
        _span("tx.verify", "a", "r", start=2.0, dur=4.0),     # [2,6]
        # recorded retroactively: starts 2s before its parent
        _span("wait.verify_park", "g", "a", start=0.0, dur=5.0,
              wait_kind="verify.park"),                        # [0,5]
    ]
    cp = critical_path(spans)
    assert cp["e2e_ms"] == pytest.approx(10000.0)
    assert sum(cp["blame_ms"].values()) == pytest.approx(10000.0)
    assert cp["blame_ms"] == {"flow.compute": pytest.approx(6000.0),
                              "verify": pytest.approx(4000.0)}


def test_zero_duration_children_are_safe():
    spans = [
        _span("flow.run", "r", start=0.0, dur=1.0, flow_type=PAY),
        _span("vault.update", "z", "r", start=0.5, dur=0.0),
    ]
    cp = critical_path(spans)
    assert cp["blame_ms"] == {"flow.compute": pytest.approx(1000.0)}


def test_parent_pointer_cycle_terminates():
    # x and y point at each other under a healthy root: the walk must not
    # hang, and the root's decomposition stays conserved
    spans = _commit_tree() + [
        _span("raft.append", "x", "y", start=3.0, dur=1.0),
        _span("raft.append", "y", "x", start=3.0, dur=1.0),
    ]
    cp = critical_path(spans)
    assert sum(cp["blame_ms"].values()) == pytest.approx(cp["e2e_ms"])
    # a PURE cycle has no root at all: None, not an infinite loop
    cycle_only = [_span("raft.append", "x", "y", start=0.0, dur=1.0),
                  _span("raft.append", "y", "x", start=0.0, dur=1.0)]
    assert critical_path(cycle_only) is None


def test_empty_and_rootless_traces_return_none():
    assert critical_path([]) is None
    assert critical_path([{"bogus": 1}]) is None
    # root with zero duration and no pre-root wait: nothing to decompose
    assert critical_path([_span("flow.run", "r")]) is None


def test_component_taxonomy():
    # every wait_kind maps into the fixed component set
    for kind, comp in WAIT_KINDS.items():
        assert comp in COMPONENTS
        assert component_of(_span("wait.x", "s", wait_kind=kind)) == comp
    assert component_of(_span("flow.run", "s")) == "flow.compute"
    assert component_of(_span("vault.update", "s")) == "vault"
    assert component_of(_span("session.send", "s")) == "network"
    assert component_of(_span("mystery.thing", "s")) == "other"


def test_flow_kind_classification():
    assert flow_kind("corda_tpu.finance.cash.CashIssueFlow") == "issue"
    assert flow_kind(PAY) == "pay"
    assert flow_kind("corda_tpu.finance.trade.SellerFlow") == "settle"
    assert flow_kind("x.CommercialPaperIssueFlow") == "settle"
    assert flow_kind("corda_tpu.flows.library.NotaryServiceFlow") is None
    assert flow_kind(None) is None


def _traces_of(kind_durations):
    """One single-span flow.run trace per (flow_type, duration)."""
    traces = {}
    for i, (ftype, dur) in enumerate(kind_durations):
        tid = f"t{i}"
        s = _span("flow.run", f"s{i}", start=0.0, dur=dur, flow_type=ftype)
        s["trace_id"] = tid
        traces[tid] = [s]
    return traces


def test_aggregate_per_class_percentile_vectors():
    issue = "corda_tpu.finance.cash.CashIssueFlow"
    traces = _traces_of([(PAY, d) for d in (1.0, 2.0, 3.0, 4.0, 5.0)]
                        + [(issue, 9.0)])
    agg = aggregate_critpaths(traces, top_k=2)
    assert agg["traces"] == 6
    pay = agg["per_class"]["pay"]
    assert pay["n"] == 5
    assert pay["e2e_ms_p50"] == pytest.approx(3000.0)
    assert pay["e2e_ms_p99"] == pytest.approx(5000.0)
    # the p50 VECTOR is the p50 transaction's own decomposition: conserved
    assert sum(pay["blame_p50"].values()) == pytest.approx(3000.0)
    assert agg["per_class"]["issue"]["dominant"] == "flow.compute"
    # top-K slowest first, capped
    assert [cp["e2e_ms"] for cp in agg["top"]] == [9000.0, 5000.0]


def test_ledger_fields_always_present_with_defaults():
    fields = ledger_critpath_fields({})
    assert fields["ledger_critpath_traces"] == 0
    assert fields["ledger_critpath_top"] == []
    for kind in ("issue", "pay", "settle"):
        assert fields[f"ledger_critpath_blame_p50_{kind}"] == {}
        assert fields[f"ledger_critpath_blame_p99_{kind}"] == {}
        assert fields[f"ledger_critpath_e2e_p50_ms_{kind}"] == 0.0
        assert fields[f"ledger_critpath_dominant_{kind}"] == "-"


def test_ledger_fields_populated_and_conserved():
    traces = _traces_of([(PAY, 2.0), (PAY, 4.0)])
    fields = ledger_critpath_fields(traces)
    assert fields["ledger_critpath_traces"] == 2
    e2e = fields["ledger_critpath_e2e_p50_ms_pay"]
    assert e2e > 0
    blame = fields["ledger_critpath_blame_p50_pay"]
    assert sum(blame.values()) == pytest.approx(e2e)
    assert fields["ledger_critpath_dominant_pay"] == "flow.compute"
    assert fields["ledger_critpath_blame_p50_settle"] == {}


def test_critpath_cli_render_is_pure_and_tolerant():
    from corda_tpu.tools.critpath import render
    report = critpath_report({"t1": _commit_tree()}, top_k=3)
    text = render(report)
    assert "critical paths over 1 traces" in text
    assert "pay" in text and "notary.batch_wait" in text
    assert "[notary.commit]" in text
    # malformed / empty payloads render, never raise
    assert "0 traces" in render({})
    assert render({"per_class": "junk", "top": [None, {"segments": "x"}]})


def test_critpath_cli_jsonl_replay(tmp_path):
    from corda_tpu.tools.critpath import report_from_jsonl
    import json
    p = tmp_path / "spans.jsonl"
    lines = [json.dumps(s) for s in _commit_tree()] + ["{not json", ""]
    p.write_text("\n".join(lines), encoding="utf-8")
    report = report_from_jsonl(str(p), top_k=5)
    assert report["traces"] == 1
    assert report["per_class"]["pay"]["dominant"] == "notary.batch_wait"