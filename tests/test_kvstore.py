"""Native KV storage engine tests: durability, recovery, torn-tail
truncation, tombstones, compaction (JDBCHashMap/WAL-discipline analogs)."""
import os

import pytest

from corda_tpu.storage import KvStore, NATIVE_AVAILABLE

ENGINES = [False] + ([True] if NATIVE_AVAILABLE else [])


@pytest.mark.parametrize("native", ENGINES, ids=lambda n: "native" if n else "py")
def test_roundtrip_and_recovery(tmp_path, native):
    path = str(tmp_path / "store.kv")
    kv = KvStore(path, use_native=native)
    kv[b"alpha"] = b"1"
    kv[b"beta"] = b"2" * 1000
    kv[b"alpha"] = b"updated"
    del kv[b"beta"]
    assert kv[b"alpha"] == b"updated"
    assert b"beta" not in kv
    kv.close()

    # reopen: the index rebuilds from the log
    kv2 = KvStore(path, use_native=native)
    assert kv2[b"alpha"] == b"updated"
    assert b"beta" not in kv2
    assert len(kv2) == 1
    kv2.close()


@pytest.mark.parametrize("native", ENGINES, ids=lambda n: "native" if n else "py")
def test_torn_tail_is_truncated(tmp_path, native):
    path = str(tmp_path / "store.kv")
    kv = KvStore(path, use_native=native)
    kv[b"k1"] = b"v1"
    kv[b"k2"] = b"v2"
    kv.close()
    # simulate a crash mid-append: garbage half-record at the tail
    size = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b"\x00\x01\x02\x03\x04\x05garbage")
    kv2 = KvStore(path, use_native=native)
    assert kv2[b"k1"] == b"v1" and kv2[b"k2"] == b"v2"
    assert len(kv2) == 2
    assert os.path.getsize(path) == size  # tail truncated on recovery
    kv2.close()


@pytest.mark.parametrize("native", ENGINES, ids=lambda n: "native" if n else "py")
def test_compaction_drops_dead_records(tmp_path, native):
    path = str(tmp_path / "store.kv")
    kv = KvStore(path, use_native=native)
    for i in range(50):
        kv[b"churn"] = b"x" * 100  # 50 versions of one key
    kv[b"keep"] = b"forever"
    before = os.path.getsize(path)
    kv.compact()
    after = os.path.getsize(path)
    assert after < before / 10
    assert kv[b"churn"] == b"x" * 100 and kv[b"keep"] == b"forever"
    kv.close()
    kv2 = KvStore(path, use_native=native)
    assert len(kv2) == 2
    kv2.close()


@pytest.mark.skipif(not NATIVE_AVAILABLE, reason="native engine not built")
def test_native_and_python_formats_interoperate(tmp_path):
    path = str(tmp_path / "store.kv")
    kv = KvStore(path, use_native=True)
    kv[b"written-by"] = b"native"
    kv.close()
    kv2 = KvStore(path, use_native=False)
    assert kv2[b"written-by"] == b"native"
    kv2[b"and-by"] = b"python"
    kv2.close()
    kv3 = KvStore(path, use_native=True)
    assert kv3[b"and-by"] == b"python"
    kv3.close()
