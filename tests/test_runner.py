"""Node-runner abstraction tests: local lifecycle, SSH command layer, and
the SSH lifecycle through a fake (bash -c) transport — no live remote
needed (LoadTest.kt / NodeConnection.kt parity surface)."""
import os
import sys
import time

import pytest

from corda_tpu.testing.runner import (LocalRunner, SSHRunner, _PID_MARKER)

_SLEEPER = [sys.executable, "-u", "-c",
            "import time\nprint('up', flush=True)\n"
            "time.sleep(60)"]


def _state(pid: int) -> str:
    with open(f"/proc/{pid}/stat") as f:
        return f.read().split(")")[-1].split()[0]


def test_local_runner_lifecycle():
    h = LocalRunner().spawn(_SLEEPER)
    try:
        assert h.stdout.readline().strip() == "up"
        assert h.poll() is None
        h.suspend()
        time.sleep(0.1)
        assert _state(h.pid) == "T"          # stopped
        h.resume()
        time.sleep(0.1)
        assert _state(h.pid) in ("S", "R")
    finally:
        h.kill()
        h.wait(timeout=10)
    assert h.poll() is not None


def test_ssh_command_layer_argv():
    r = SSHRunner("box1", user="corda")
    assert r.argv("echo hi") == ["ssh", "-o", "BatchMode=yes",
                                 "corda@box1", "echo hi"]
    cmd = r.remote_command(["python3", "-m", "corda_tpu.node",
                            "--name", "O=A, L=B, C=GB"],
                           env={"JAX_PLATFORMS": "cpu"}, cwd="/data/n1")
    # shape: cd <dir>; echo <marker> $$; exec ENV... argv... 2>&1
    assert cmd.startswith("cd /data/n1; ")
    assert f"echo {_PID_MARKER} $$" in cmd
    assert "exec env JAX_PLATFORMS=cpu python3 -m corda_tpu.node" in cmd
    # the multi-word name must be quoted for the remote shell
    assert "'O=A, L=B, C=GB'" in cmd


def test_ssh_runner_lifecycle_through_fake_transport(tmp_path):
    """The full spawn/suspend/kill cycle through the SSH command layer,
    with bash -c standing in for the remote shell: every signal is
    delivered by the runner's `kill` commands, not by local Popen calls —
    exactly what a real ssh transport would execute."""
    r = SSHRunner("fake", transport=["bash", "-c"])
    r.prepare_dir(str(tmp_path / "remote"))
    assert (tmp_path / "remote").is_dir()
    h = r.spawn(_SLEEPER, env={"X_MARKER": "1"}, cwd=str(tmp_path))
    try:
        assert h.pid > 0
        assert h.stdout.readline().strip() == "up"
        h.suspend()
        time.sleep(0.1)
        assert _state(h.pid) == "T"
        h.resume()
        time.sleep(0.1)
        assert _state(h.pid) in ("S", "R")
    finally:
        h.kill()
    # the remote process is gone (kill -KILL through the transport)
    with pytest.raises(OSError):
        os.kill(h.pid, 0)


def test_ssh_runner_one_shot_run():
    r = SSHRunner("fake", transport=["bash", "-c"])
    out = r.run("echo remote-ok")
    assert out.stdout.strip() == "remote-ok"
    with pytest.raises(RuntimeError):
        r.run("exit 3")
