"""Custom state schemas: typed projections + schema-column vault queries
(VERDICT r2 #6).

Reference analogs: PersistentTypes.kt (MappedSchema/QueryableState),
HibernateObserver (on-record projection), VaultQueryTests' custom-schema
cases, finance CashSchemaV1.
"""
from dataclasses import dataclass

import pytest

from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.finance import CashIssueFlow, CashPaymentFlow
from corda_tpu.finance.cash import CASH_SCHEMA_V1
from corda_tpu.node.query import greater_than, equal
from corda_tpu.node.schemas import (MappedSchema, PersistentRow,
                                    SchemaColumnCriteria, SchemaService)
from corda_tpu.testing import MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank = network.create_node("O=Bank, L=London, C=GB")
    peer = network.create_node("O=Peer, L=Oslo, C=NO")
    network.start_nodes()
    return network, notary, bank, peer


def _issue(network, notary, bank, quantity):
    fsm = bank.start_flow(CashIssueFlow(Amount(quantity, USD), b"\x01",
                                        bank.party, notary.party))
    network.run_network()
    fsm.result_future.result(timeout=1)


def test_cash_states_project_into_schema_table(net):
    network, notary, bank, peer = net
    _issue(network, notary, bank, 700)
    _issue(network, notary, bank, 300)
    svc: SchemaService = bank.services.schema_service
    rows = svc.rows(CASH_SCHEMA_V1)
    assert sorted(r.values[CASH_SCHEMA_V1.columns.index("pennies")]
                  for r in rows) == [300, 700]
    header, table = svc.export_table(CASH_SCHEMA_V1)
    assert header == ("transaction_id", "output_index", "owner_key",
                      "pennies", "ccy_code", "issuer_party", "issuer_ref")
    assert len(table) == 2
    assert all(row[4] == "USD" for row in table)


def test_consumed_states_leave_the_table(net):
    network, notary, bank, peer = net
    _issue(network, notary, bank, 1000)
    fsm = bank.start_flow(CashPaymentFlow(Amount(1000, USD), peer.party))
    network.run_network()
    fsm.result_future.result(timeout=1)
    # bank spent its whole holding: its table row moved to the PEER's table
    assert bank.services.schema_service.rows(CASH_SCHEMA_V1) == []
    peer_rows = peer.services.schema_service.rows(CASH_SCHEMA_V1)
    assert [r.values[CASH_SCHEMA_V1.columns.index("pennies")]
            for r in peer_rows] == [1000]


def test_vault_query_filters_on_schema_column(net):
    """The done-criterion: a vault query filters on a custom schema column."""
    network, notary, bank, peer = net
    for quantity in (100, 600, 900):
        _issue(network, notary, bank, quantity)
    page = bank.services.vault.query_by(SchemaColumnCriteria(
        schema=CASH_SCHEMA_V1, column="pennies",
        predicate=greater_than(500)))
    amounts = sorted(s.state.data.amount.quantity for s in page.states)
    assert amounts == [600, 900]
    page = bank.services.vault.query_by(SchemaColumnCriteria(
        schema=CASH_SCHEMA_V1, column="ccy_code", predicate=equal("USD")))
    assert len(page.states) == 3


def test_sample_state_defines_its_own_schema(net):
    """A cordapp-defined state + schema, never known to the framework."""
    from corda_tpu.core.contracts.structures import (StateRef,
                                                     TransactionState)
    from corda_tpu.core.crypto.secure_hash import SecureHash
    from corda_tpu.node.vault import VaultUpdate
    from corda_tpu.core.contracts.structures import StateAndRef

    network, notary, bank, peer = net
    TRADE_SCHEMA = MappedSchema("TradeSchema", 1, ("ticker", "qty"))

    @dataclass(frozen=True)
    class TradeState:
        ticker: str
        qty: int
        owner_keys: tuple

        @property
        def participants(self):
            return list(self.owner_keys)

        def supported_schemas(self):
            return (TRADE_SCHEMA,)

        def generate_mapped_object(self, schema):
            return {"ticker": self.ticker, "qty": self.qty}

    svc: SchemaService = bank.services.schema_service
    ref = StateRef(SecureHash.sha256(b"trade-tx"), 0)
    state = TradeState("TPU", 64, (bank.party.owning_key,))
    svc._on_vault_update(VaultUpdate((), (StateAndRef(
        TransactionState(state, notary.party), ref),)))
    assert TRADE_SCHEMA.name in {s.name for s in svc.schemas}
    assert svc.rows(TRADE_SCHEMA) == [PersistentRow(ref, ("TPU", 64))]
