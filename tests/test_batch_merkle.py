"""Batch device Merkle proof verification vs the host path (bit-exact) and
the oracle bulk-signing consumer.

Reference workload: NodeInterestRates.kt:149-180 oracle attestation over
FilteredTransactions (MerkleTransaction.kt:70-170) — BASELINE config 3.
"""
import numpy as np
import pytest

from corda_tpu.core.contracts import Command, TransactionState
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.core.transactions.batch_merkle import (batch_roots,
                                                      verify_filtered_batch)
from corda_tpu.core.transactions.filtered import FilteredTransaction
from corda_tpu.core.transactions import WireTransaction
from corda_tpu.testing import DummyContract, DummyState, MockNetwork


def _wtxs(n, alice, oracle_node, notary, fix_cls, fix_of):
    out = []
    for i in range(n):
        out.append(WireTransaction(
            outputs=(TransactionState(
                DummyState(i + 1, (alice.party.owning_key,)), notary.party),),
            commands=(
                Command(DummyContract.Create(), (alice.party.owning_key,)),
                Command(fix_cls(fix_of, 525),
                        (oracle_node.party.owning_key,)),
            ),
            notary=notary.party,
            must_sign=(alice.party.owning_key,
                       oracle_node.party.owning_key)))
    return out


def _fixture():
    from corda_tpu.samples.rates_oracle import Fix, FixOf, RatesOracle
    fix_of = FixOf("ICE LIBOR", "2016-03-16", "3M")
    network = MockNetwork()
    notary = network.create_notary_node()
    oracle_node = network.create_node("O=Rates Oracle, L=London, C=GB")
    alice = network.create_node("O=Alice, L=Madrid, C=ES")
    network.start_nodes()
    oracle = RatesOracle(oracle_node.services, {fix_of: 525})
    return network, notary, oracle_node, alice, oracle, Fix, fix_of


def test_batch_verify_matches_host_and_rejects_tampered():
    network, notary, oracle_node, alice, oracle, Fix, fix_of = _fixture()
    wtxs = _wtxs(8, alice, oracle_node, notary, Fix, fix_of)
    ftxs = [w.build_filtered_transaction(
        lambda c: isinstance(c, Command) and isinstance(c.value, Fix))
        for w in wtxs]
    # a reveal-all proof and a wider reveal exercise deeper rounds
    ftxs.append(wtxs[0].build_filtered_transaction(lambda c: True))
    # tampered root: proof must fail while others still verify
    bad = FilteredTransaction(SecureHash.sha256(b"wrong"),
                              ftxs[0].filtered_leaves,
                              ftxs[0].partial_merkle_tree)
    ftxs.append(bad)
    got = verify_filtered_batch(ftxs, device_crossover=2)   # force device
    want = []
    for ftx in ftxs:
        try:
            want.append(ftx.verify())
        except ValueError:
            want.append(False)
    assert got == want
    assert got[:-1] == [True] * (len(ftxs) - 1) and got[-1] is False
    # host-only routing must agree with the device routing
    assert verify_filtered_batch(ftxs, use_device=False) == got


def test_batch_roots_matches_host():
    from corda_tpu.core.crypto.merkle import MerkleTree
    rng = np.random.default_rng(9)
    lists = []
    for n in (1, 2, 3, 5, 8, 16):
        lists.append([SecureHash.sha256(rng.bytes(16)) for _ in range(n)])
    got = batch_roots(lists, device_crossover=1)            # force device
    want = [MerkleTree.root_hash(hs) for hs in lists]
    assert got == want
    assert batch_roots(lists, use_device=False) == want


def test_oracle_sign_batch():
    network, notary, oracle_node, alice, oracle, Fix, fix_of = _fixture()
    wtxs = _wtxs(4, alice, oracle_node, notary, Fix, fix_of)
    ftxs = [w.build_filtered_transaction(
        lambda c: isinstance(c, Command) and isinstance(c.value, Fix))
        for w in wtxs]
    # one bad proof + one over-revealed tx the oracle must refuse
    ftxs.append(FilteredTransaction(SecureHash.sha256(b"no"),
                                    ftxs[0].filtered_leaves,
                                    ftxs[0].partial_merkle_tree))
    ftxs.append(wtxs[0].build_filtered_transaction(lambda c: True))
    out = oracle.sign_batch(ftxs)
    for i, (ftx, res) in enumerate(zip(ftxs[:4], out[:4])):
        assert not isinstance(res, Exception), res
        res.verify(ftx.root_hash.bytes)
    assert isinstance(out[4], Exception) and "Merkle" in str(out[4])
    assert isinstance(out[5], Exception) and "refuses" in str(out[5])
    # batch results agree with the single-item path
    single = oracle.sign(ftxs[0])
    single.verify(ftxs[0].root_hash.bytes)
