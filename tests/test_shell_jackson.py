"""Shell + JSON bindings parity (VERDICT r2 #8).

Reference analogs: InteractiveShellTest (string→flow-constructor binding),
StringToMethodCallParserTest (named-argument parsing/conversion),
JacksonSupport serializer tests (Party/Amount/hash/key renderings).
"""
import io

import pytest

import corda_tpu.finance  # noqa: F401
from corda_tpu.client.jackson import (StringToMethodCallParser,
                                      UnparseableCallException, render_yaml,
                                      to_json, to_jsonable)
from corda_tpu.core.contracts.amount import Amount, USD, currency
from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.core.identity import Party
from corda_tpu.tools.shell import Shell

ALICE = Party("O=Alice, L=London, C=GB",
              generate_keypair(entropy=b"\x81" * 32).public)


# -- jackson renderings ------------------------------------------------------

def test_jsonable_renderings():
    assert to_jsonable(ALICE) == "O=Alice, L=London, C=GB"
    assert to_jsonable(ALICE.owning_key) == ALICE.owning_key.to_string_short()
    h = SecureHash.sha256(b"x")
    assert to_jsonable(h) == h.bytes.hex()
    assert to_jsonable(Amount(4200, USD)) == "4200 USD"
    assert to_jsonable(b"\x01\xff") == "0x01ff"
    assert to_jsonable({"a": (1, 2), "b": None}) == {"a": [1, 2], "b": None}
    import json
    json.loads(to_json({"party": ALICE, "amount": Amount(1, USD)}))


def test_yaml_rendering_nests():
    text = render_yaml({"top": {"inner": [1, "two"]}, "flat": 3})
    assert "top:" in text and "  inner:" in text and "- 1" in text
    assert 'flat: 3' in text


# -- StringToMethodCallParser ------------------------------------------------

class _Target:
    def __init__(self, amount: Amount, issuer_ref: bytes, recipient, note="x"):
        self.bound = (amount, issuer_ref, recipient, note)


def test_parser_binds_named_arguments():
    parser = StringToMethodCallParser(
        party_resolver=lambda name: ALICE if "Alice" in name else None)
    args = parser.parse_arguments(
        _Target, "amount: 100.50 USD, issuer_ref: 0x01, "
                 "recipient: O=Alice, L=London, C=GB")
    assert args == [Amount(10050, currency("USD")), b"\x01", ALICE, "x"]


def test_parser_handles_x500_commas_and_order():
    parser = StringToMethodCallParser(
        party_resolver=lambda name: ALICE if "Alice" in name else None)
    # out-of-declaration-order + commas inside the party name
    args = parser.parse_arguments(
        _Target, "recipient: O=Alice, L=London, C=GB, amount: 7.00 USD, "
                 "issuer_ref: 0xaa, note: hello")
    assert args == [Amount(700, currency("USD")), b"\xaa", ALICE, "hello"]


def test_parser_rejects_unknown_and_missing():
    parser = StringToMethodCallParser()
    with pytest.raises(UnparseableCallException, match="unknown parameter"):
        parser.parse_arguments(_Target, "amount: 1.00 USD, wrong: 1")
    with pytest.raises(UnparseableCallException, match="missing required"):
        parser.parse_arguments(_Target, "amount: 1.00 USD")
    with pytest.raises(UnparseableCallException, match="not an amount"):
        parser.parse_arguments(_Target,
                               "amount: banana, issuer_ref: 0x01, "
                               "recipient: x")


# -- the shell against a LIVE node -------------------------------------------

@pytest.fixture
def live_node(tmp_path):
    from corda_tpu.node.node import Node, NodeConfiguration
    config = NodeConfiguration(
        "O=Solo, L=London, C=GB", port=0,
        base_directory=str(tmp_path / "solo"), notary="simple")
    node = Node(config).start()
    yield node
    node.stop()


def test_shell_starts_flows_from_typed_strings_against_live_node(live_node):
    """The done-criterion: `flow start CashPaymentFlow amount: ..., recipient:
    <X.500>` runs against a real node over RPC."""
    from corda_tpu.client.rpc import CordaRPCClient

    client = CordaRPCClient("127.0.0.1", live_node.messaging.port)
    out = io.StringIO()
    shell = Shell(client, out=out)
    try:
        name = "O=Solo, L=London, C=GB"
        assert shell.execute(
            f"flow start CashIssueFlow amount: 42.00 USD, issuer_ref: 0x01, "
            f"recipient: {name}, notary: {name}")
        assert "error" not in out.getvalue().lower(), out.getvalue()
        assert shell.execute(
            f"flow start CashPaymentFlow amount: 12.00 USD, "
            f"recipient: {name}")
        assert "error" not in out.getvalue().lower(), out.getvalue()
        out.truncate(0)
        assert shell.execute("run get_cash_balances")
        assert "4200" in out.getvalue()
        # typed-string failures surface as bind errors, not tracebacks
        out.truncate(0)
        shell.execute("flow start CashPaymentFlow amount: nonsense")
        assert "cannot bind" in out.getvalue()
    finally:
        client.close()


def test_shell_flow_watch_renders_events(live_node):
    from corda_tpu.client.rpc import CordaRPCClient
    import threading

    client = CordaRPCClient("127.0.0.1", live_node.messaging.port)
    out = io.StringIO()
    shell = Shell(client, out=out)
    try:
        name = "O=Solo, L=London, C=GB"
        watcher = threading.Thread(
            target=lambda: shell.execute("flow watch 2"), daemon=True)
        watcher.start()
        import time
        time.sleep(1.0)   # let the watch subscribe
        client.start_flow_and_wait(
            "CashIssueFlow", Amount(100, USD), b"\x01",
            live_node.party, live_node.party, timeout_s=60)
        watcher.join(timeout=30)
        assert not watcher.is_alive()
        text = out.getvalue()
        assert "CashIssueFlow" in text
    finally:
        client.close()


def test_shell_output_json_mode(live_node):
    from corda_tpu.client.rpc import CordaRPCClient

    client = CordaRPCClient("127.0.0.1", live_node.messaging.port)
    out = io.StringIO()
    shell = Shell(client, out=out)
    try:
        shell.execute("output json")
        shell.execute("run node_identity")
        import json
        parsed = json.loads(out.getvalue())
        assert parsed["legal_identity"] == "O=Solo, L=London, C=GB"
    finally:
        client.close()
