"""Resource accounting plane + leak detector + subsystem CPU profiler
(observability/resprof.py) — all over synthetic series and injected
frames, no real sleeping (tier-1 discipline)."""
import pytest

from corda_tpu.observability.consensus_obs import GrowthWatch
from corda_tpu.observability.resprof import (
    COMMIT_PATH_COMPONENTS, CPU_COMPONENTS, ResourceRegistry,
    SubsystemProfiler, classify_stack, get_resources, is_wait_frame,
    leak_verdict, process_rss_bytes, set_resources, theil_sen_slope)
from corda_tpu.observability.timeseries import TimeSeriesStore


def rows(pts):
    """Synthetic retained-ring rows [t, n, min, max, mean, last]."""
    return [[t, 1, v, v, v, v] for t, v in pts]


# ---------------------------------------------------------------------------
# Theil–Sen trend fit
# ---------------------------------------------------------------------------

def test_theil_sen_exact_on_linear():
    pts = [(float(t), 3.0 + 2.0 * t) for t in range(10)]
    assert theil_sen_slope(pts) == pytest.approx(2.0)


def test_theil_sen_robust_to_outlier():
    # a single chaos-window spike barely moves the median of pairwise
    # slopes — the property a least-squares fit does not have
    pts = [(float(t), float(t)) for t in range(20)]
    pts[10] = (10.0, 500.0)
    assert theil_sen_slope(pts) == pytest.approx(1.0, abs=0.15)


def test_theil_sen_degenerate():
    assert theil_sen_slope([]) == 0.0
    assert theil_sen_slope([(1.0, 5.0)]) == 0.0
    assert theil_sen_slope([(1.0, 5.0), (1.0, 9.0)]) == 0.0  # same t


# ---------------------------------------------------------------------------
# leak_verdict over synthetic bounded / linear / step series
# ---------------------------------------------------------------------------

def test_verdict_flat_series_is_bounded():
    v = leak_verdict(rows((float(t), 100.0) for t in range(60)))
    assert v["verdict"] == "bounded"
    assert v["slope_per_s"] == pytest.approx(0.0)


def test_verdict_noisy_flat_series_is_bounded():
    # ±5% sawtooth around a constant level: noise, not growth
    v = leak_verdict(rows((float(t), 100.0 + 5.0 * (-1) ** t)
                          for t in range(60)))
    assert v["verdict"] == "bounded"


def test_verdict_linear_growth_leaks_when_declared_bounded():
    v = leak_verdict(rows((float(t), 10.0 + 2.0 * t) for t in range(60)),
                     kind="bounded")
    assert v["verdict"] == "leaking"
    assert v["slope_per_s"] == pytest.approx(2.0, rel=0.05)
    # doubling time is level / slope over the recent-half window
    assert v["doubling_s"] == pytest.approx(v["level"] / 2.0, rel=0.05)


def test_verdict_linear_growth_caps_at_growing_when_declared_grows():
    v = leak_verdict(rows((float(t), 10.0 + 2.0 * t) for t in range(60)),
                     kind="grows")
    assert v["verdict"] == "growing"
    assert v["doubling_s"] is not None and v["doubling_s"] > 0


def test_verdict_step_then_plateau_is_bounded():
    # the chaos-window signature: one step up, then flat — the recent-half
    # fit must NOT read the old step as a trend
    pts = [(float(t), 10.0 if t < 20 else 500.0) for t in range(80)]
    v = leak_verdict(rows(pts), kind="bounded")
    assert v["verdict"] == "bounded"


def test_verdict_declared_bound_growth_under_cap_is_filling():
    # a fresh span ring filling toward capacity is NOT a leak
    pts = [(float(t), 10.0 * t) for t in range(60)]     # level ≈ 450
    v = leak_verdict(rows(pts), kind="bounded", bound=100_000.0)
    assert v["verdict"] == "bounded"
    assert v.get("filling") is True
    assert v["slope_per_s"] > 0
    # ...but growth AT/ABOVE the declared cap has lost its bound
    v = leak_verdict(rows(pts), kind="bounded", bound=400.0)
    assert v["verdict"] == "leaking"
    assert "filling" not in v


def test_verdict_growth_that_drains_at_quiescence_is_backlog():
    # in-flight structures (checkpoint stores, reservation maps) grow
    # with open-loop backlog and empty at drain: a leak by definition
    # PERSISTS at quiescence, so a final level back near zero downgrades
    pts = [(float(t), 2.0 * t) for t in range(60)]
    v = leak_verdict(rows(pts), kind="bounded", final_level=0.0)
    assert v["verdict"] == "bounded"
    assert v.get("drained") is True
    # ...while growth still standing after drain keeps the leak verdict
    v = leak_verdict(rows(pts), kind="bounded", final_level=120.0)
    assert v["verdict"] == "leaking"
    assert "drained" not in v


def test_verdict_too_few_points_is_honest_bounded():
    v = leak_verdict(rows((float(t), 1000.0 * t) for t in range(3)))
    assert v["verdict"] == "bounded"
    assert v["points"] == 3


def test_verdict_tolerates_malformed_rows():
    bad = [None, [], [1.0], ["x", 1, 2, 3, "y", 5], [0.0, 1, 2, 3, 4.0, 5]]
    v = leak_verdict(bad)
    assert v["verdict"] == "bounded" and v["points"] == 1
    assert leak_verdict(None)["verdict"] == "bounded"


# ---------------------------------------------------------------------------
# ResourceRegistry
# ---------------------------------------------------------------------------

def test_registry_register_sample_and_introspect():
    reg = ResourceRegistry()
    items = [1, 2, 3]
    reg.register("Test.List", lambda: len(items), kind="bounded", bound=10)
    reg.register("Test.Counter", lambda: 100.0, kind="grows")
    assert reg.names() == ["Test.Counter", "Test.List"]
    assert reg.kinds() == {"Test.List": "bounded", "Test.Counter": "grows"}
    assert reg.bounds() == {"Test.List": 10}
    store = TimeSeriesStore(resolutions=((1.0, 8),))
    values = reg.sample(store=store, t=0.0)
    assert values == {"Resource.Test.List": 3.0,
                      "Resource.Test.Counter": 100.0}
    assert reg.sizes()["Test.List"] == 3.0
    store.flush()
    snap = store.snapshot()
    assert sorted(snap["series"]) == ["Resource.Test.Counter",
                                      "Resource.Test.List"]
    reg.unregister("Test.List")
    assert reg.names() == ["Test.Counter"]
    assert "Test.List" not in reg.sizes()


def test_registry_rejects_bad_registrations():
    reg = ResourceRegistry()
    with pytest.raises(ValueError):
        reg.register("x", lambda: 0, kind="unbounded")
    with pytest.raises(ValueError):
        reg.register("x", 42)


def test_registry_rate_probe_windowed_delta():
    reg = ResourceRegistry()
    cum = {"v": 100.0}
    reg.register("Drops", lambda: cum["v"], kind="grows", rate=True)
    first = reg.sample(t=0.0)
    assert "Resource.Drops.Rate" not in first    # no window yet
    cum["v"] = 150.0
    second = reg.sample(t=10.0)
    assert second["Resource.Drops.Rate"] == pytest.approx(5.0)
    # a counter reset (restart) clamps to zero, never a negative rate
    cum["v"] = 0.0
    third = reg.sample(t=20.0)
    assert third["Resource.Drops.Rate"] == 0.0


def test_registry_broken_probe_does_not_stall_sampling():
    reg = ResourceRegistry()
    reg.register("Broken", lambda: 1 / 0)
    reg.register("NotANumber", lambda: "many")
    reg.register("Fine", lambda: 7.0)
    values = reg.sample(t=0.0)
    assert values == {"Resource.Fine": 7.0}


def test_registry_feeds_growth_watch_doubling_for_free():
    """Satellite: ANY registered structure gets doubling warnings —
    GrowthWatch is no longer limited to its two hard-coded hazards."""
    reg = ResourceRegistry()
    size = {"v": 2000.0}
    reg.register("Anything.AtAll", lambda: size["v"], kind="grows")
    cum = {"v": 5000.0}
    reg.register("Some.Counter", lambda: cum["v"], kind="grows", rate=True)
    watch = GrowthWatch()
    reg.sample(watch=watch, t=0.0)               # baseline armed
    size["v"] = 5000.0                           # ≥ 2× the baseline
    cum["v"] = 5001.0
    reg.sample(watch=watch, t=1.0)
    assert watch.warnings == 1                   # .Rate series never fed


def test_global_registry_seam():
    mine = ResourceRegistry()
    prev = set_resources(mine)
    try:
        assert get_resources() is mine
    finally:
        set_resources(prev)
    assert get_resources() is not mine


def test_process_rss_probe_reads_something():
    assert process_rss_bytes() > 0


# ---------------------------------------------------------------------------
# stack classification + CPU profiler (injected frames, no timing)
# ---------------------------------------------------------------------------

def test_classify_stack_thread_rules_win():
    # a dedicated subsystem thread is that subsystem's time no matter
    # which helper it is inside
    frames = [("corda_tpu/core/serialization/codec.py", "encode")]
    assert classify_stack("ledger-raft-pump-0", frames) == "raft_pump"
    assert classify_stack("sig-batcher-prep-1", frames) == "batcher_prep"
    assert classify_stack("sig-batcher-0", frames) == "batcher_dispatch"
    assert classify_stack("tcp-messaging-3", frames) == "network"
    assert classify_stack("soak-cpu-profiler", frames) == "observability"


def test_classify_stack_innermost_frame_rule():
    assert classify_stack("worker", [
        ("corda_tpu/consensus/raft.py", "tick"),
        ("corda_tpu/flows/runner.py", "run"),
    ]) == "raft_pump"
    assert classify_stack("worker", [
        ("corda_tpu/observability/tracing.py", "span"),
        ("corda_tpu/consensus/raft.py", "tick"),
    ]) == "observability"
    assert classify_stack("worker", [
        ("corda_tpu/node/statemachine.py", "step")]) == "flow_scheduler"
    assert classify_stack("worker", [("mymodule.py", "f")]) == "other"
    assert classify_stack("", []) == "other"


def test_is_wait_frame_stdlib_and_linecache(tmp_path):
    assert is_wait_frame("/usr/lib/python3.11/threading.py", "wait")
    assert is_wait_frame("/usr/lib/python3.11/queue.py", "get")
    assert not is_wait_frame("corda_tpu/consensus/raft.py", "tick")
    # C-level blocks leave the CALLER's frame innermost: the source-line
    # peek catches them
    src = tmp_path / "caller.py"
    src.write_text("import time\ntime.sleep(0.5)\nx = 1 + 1\n")
    assert is_wait_frame(str(src), "body", 2)
    assert not is_wait_frame(str(src), "body", 3)


class _Frame:
    """Just enough of a frame for SubsystemProfiler.sample_once."""

    class _Code:
        def __init__(self, filename, name):
            self.co_filename = filename
            self.co_name = name

    def __init__(self, filename, func, lineno=0, back=None):
        self.f_code = self._Code(filename, func)
        self.f_lineno = lineno
        self.f_back = back


def test_profiler_shares_sum_to_100_of_busy_samples():
    prof = SubsystemProfiler()
    busy_raft = _Frame("corda_tpu/consensus/raft.py", "tick")
    busy_ser = _Frame("corda_tpu/core/serialization/codec.py", "encode")
    waiting = _Frame("/usr/lib/python3.11/threading.py", "wait")
    frames = {1: busy_raft, 2: busy_ser, 3: waiting}
    names = {1: "pump", 2: "worker", 3: "parked"}
    for _ in range(4):
        prof.sample_once(current_frames=frames, thread_names=names)
    snap = prof.snapshot()
    assert snap["ticks"] == 4
    assert snap["samples"] == 12
    assert snap["busy_samples"] == 8 and snap["idle_samples"] == 4
    assert snap["busy_frac"] == pytest.approx(8 / 12, abs=1e-3)
    assert snap["shares_pct"]["raft_pump"] == pytest.approx(50.0)
    assert snap["shares_pct"]["serialization"] == pytest.approx(50.0)
    assert snap["share_sum_pct"] == pytest.approx(100.0, abs=0.1)
    assert snap["top_commit_path"] in ("raft_pump", "serialization")
    assert set(snap["shares_pct"]) == set(CPU_COMPONENTS)


def test_profiler_thread_name_beats_frame_for_dedicated_threads():
    prof = SubsystemProfiler()
    frames = {1: _Frame("corda_tpu/core/serialization/codec.py", "encode")}
    prof.sample_once(current_frames=frames,
                     thread_names={1: "ledger-raft-pump"})
    assert prof.snapshot()["shares_pct"]["raft_pump"] == 100.0


def test_profiler_empty_snapshot_is_well_formed():
    snap = SubsystemProfiler().snapshot()
    assert snap["samples"] == 0 and snap["busy_frac"] == 0.0
    assert snap["share_sum_pct"] == 0.0
    assert snap["top_commit_path"] is None
    assert all(c in CPU_COMPONENTS for c in COMMIT_PATH_COMPONENTS)


def test_profiler_walks_caller_chain_for_classification():
    # innermost frame unmatched, but its caller sits in consensus/raft:
    # the innermost MATCHING frame decides
    inner = _Frame("helperlib.py", "crunch",
                   back=None)
    inner.f_back = _Frame("corda_tpu/consensus/raft.py", "tick")
    prof = SubsystemProfiler()
    prof.sample_once(current_frames={1: inner}, thread_names={1: "t"})
    assert prof.snapshot()["shares_pct"]["raft_pump"] == 100.0
