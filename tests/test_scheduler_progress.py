"""Scheduler + ProgressTracker tests (NodeSchedulerServiceTest /
ProgressTracker tests analogs)."""
import datetime

import pytest

from corda_tpu.core.contracts import Command, TransactionState
from corda_tpu.core.contracts.structures import (SchedulableState,
                                                 ScheduledActivity)
from corda_tpu.core.serialization.codec import exact_epoch_micros
from corda_tpu.core.transactions import WireTransaction
from corda_tpu.flows import FlowLogic, startable_by_rpc
from corda_tpu.node.scheduler import FlowLogicRefFactory, NodeSchedulerService
from corda_tpu.testing import DummyContract, MockNetwork
from corda_tpu.utils.progress import DONE, ProgressTracker, Step

T0 = datetime.datetime(2026, 7, 30, 12, 0, tzinfo=datetime.timezone.utc)


class FireFlow(FlowLogic):
    def __init__(self, note):
        self.note = note

    def call(self):
        return f"fired:{self.note}"


class TimerState(SchedulableState):
    def __init__(self, fire_at_micros: int, owners=()):
        self.fire_at_micros = fire_at_micros
        self.owners = tuple(owners)

    @property
    def contract(self):
        from corda_tpu.testing.dummy import _DUMMY_CONTRACT
        return _DUMMY_CONTRACT

    @property
    def participants(self):
        return list(self.owners)

    def next_scheduled_activity(self, ref, factory):
        return ScheduledActivity(factory.create(FireFlow, "timer"),
                                 self.fire_at_micros)

    def __eq__(self, other):
        return (isinstance(other, TimerState)
                and other.fire_at_micros == self.fire_at_micros)

    def __hash__(self):
        return hash(self.fire_at_micros)


from corda_tpu.core.serialization import register_type  # noqa: E402

register_type("test.TimerState", TimerState,
              to_fields=lambda s: [s.fire_at_micros, list(s.owners)],
              from_fields=lambda f: TimerState(f[0], tuple(f[1])))


def test_scheduler_fires_due_states():
    network = MockNetwork()
    notary = network.create_notary_node()
    node = network.create_node("O=Sched, L=Oslo, C=NO")
    network.start_nodes()
    scheduler = NodeSchedulerService(node.services,
                                     clock=lambda: T0)
    scheduler.start()

    fire_at = exact_epoch_micros(T0 + datetime.timedelta(minutes=10))
    wtx = WireTransaction(
        outputs=(TransactionState(
            TimerState(fire_at, (node.party.owning_key,)), notary.party),),
        commands=(Command(DummyContract.Create(), (node.party.owning_key,)),),
        notary=notary.party, must_sign=(node.party.owning_key,))
    stx = node.services.sign_initial_transaction(wtx)
    node.services.record_transactions(stx)

    assert scheduler.next_deadline_micros() == fire_at
    # not due yet
    assert scheduler.wake(T0) == []
    # due now
    started = scheduler.wake(T0 + datetime.timedelta(minutes=11))
    network.run_network()
    assert len(started) == 1
    assert started[0].result_future.result(timeout=1) == "fired:timer"
    assert scheduler.next_deadline_micros() is None


def test_progress_tracker_hierarchy_and_stream():
    FETCH = Step("Fetching")
    VERIFY = Step("Verifying")
    outer = ProgressTracker(FETCH, VERIFY)
    inner = ProgressTracker(Step("Downloading"))
    outer.set_child_progress_tracker(FETCH, inner)
    events = []
    outer.subscribe(events.append)

    outer.next_step()
    assert outer.current_step == FETCH
    inner.next_step()
    outer.current_step = VERIFY
    outer.next_step()
    assert outer.has_ended
    kinds = [e[0] for e in events]
    assert kinds.count("position") >= 4
    rendered = ProgressTracker(FETCH, VERIFY).render()
    assert "Fetching" in rendered and "Verifying" in rendered
