"""Raft chaos tests: partitions, leader kills, and message-drop storms
driven by the seeded fault injector over the deterministic bus.

The property under test is the notary's uniqueness SAFETY: across any
partition/re-election interleaving, conflicting put_all commands commit
at most once, and every replica's DistributedImmutableMap converges to
the same winner. Each scenario runs under several seeds — the injector
guarantees a given seed replays the identical fault schedule.
"""
import pytest

from corda_tpu.consensus.raft import FOLLOWER, LEADER, RaftNode
from corda_tpu.consensus.raft_uniqueness import DistributedImmutableMap
from corda_tpu.core.contracts.structures import StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.testing.faults import FaultRule, inject

pytestmark = pytest.mark.chaos

SEEDS = [7, 101, 9001]


def make_map_cluster(n=3):
    """RaftNode cluster where each replica applies into its own
    DistributedImmutableMap (the raft-notary state machine)."""
    bus = InMemoryMessagingNetwork()
    names = [f"raft{i}" for i in range(n)]
    maps = [DistributedImmutableMap() for _ in range(n)]
    nodes = [RaftNode(name, list(names), bus.create_node(name),
                      maps[i].apply, seed=i)
             for i, name in enumerate(names)]
    return bus, nodes, maps


def pump(bus, nodes, ticks=10):
    for _ in range(ticks):
        for node in nodes:
            node.tick()
        bus.run_network()


def run_until_leader(bus, nodes, exclude=(), max_ticks=400):
    live = [n for n in nodes if n not in exclude]
    for _ in range(max_ticks):
        pump(bus, nodes, 1)
        leaders = [n for n in live if n.role == LEADER]
        if len(leaders) == 1:
            pump(bus, nodes, 5)   # settle follower state
            final = [n for n in live if n.role == LEADER]
            if len(final) == 1:
                return final[0]
    raise AssertionError("no leader elected")


def partition_rules(name):
    """Drop every bus message to and from `name` — a full partition."""
    return (FaultRule("net.send", "drop", detail=f"{name}->*"),
            FaultRule("net.send", "drop", detail=f"*->{name}"))


def put_all(node, tx_id, refs, timeout_ticks, bus, nodes):
    """Submit a put_all and pump until its future resolves (or give up)."""
    fut = node.submit(("put_all", [tx_id, refs, "chaos-test"]))
    for _ in range(timeout_ticks):
        if fut.done():
            break
        pump(bus, nodes, 1)
    return fut


@pytest.mark.parametrize("seed", SEEDS)
def test_uniqueness_safety_across_partition(seed):
    """Partition the leader away; the majority elects a new leader and
    commits a spend. The old leader's conflicting submission must NEVER
    commit — after the heal every replica agrees on the one winner and a
    re-notarisation attempt reports the conflict."""
    bus, nodes, maps = make_map_cluster(3)
    old_leader = run_until_leader(bus, nodes)
    ref = StateRef(SecureHash.sha256(b"contended-state"), 0)

    with inject(*partition_rules(old_leader.node_id), seed=seed):
        # the doomed side: the isolated old leader accepts a client
        # submission it can never replicate to a majority
        doomed = old_leader.submit(("put_all", [["tx-doomed"], [ref],
                                               "chaos-test"]))
        new_leader = run_until_leader(bus, nodes, exclude=(old_leader,))
        assert new_leader is not old_leader
        # the winning side: the majority commits the conflicting spend
        won = put_all(new_leader, ["tx-winner"], [ref], 200, bus, nodes)
        assert won.result(timeout=1) == {"committed": True, "conflicts": {}}

    # heal: the old leader rejoins, observes the higher term, steps down,
    # and its uncommitted entry is overwritten by the winner's log
    pump(bus, nodes, 60)
    assert old_leader.role == FOLLOWER
    # SAFETY: the doomed submission never reported success
    assert not (doomed.done() and not doomed.exception()
                and doomed.result().get("committed"))
    # every replica converged on the same single owner for the ref
    for m in maps:
        assert len(m) == 1
    key = next(iter(maps[0]._map))
    assert all(m._map[key] == maps[0]._map[key] for m in maps)

    # a retry of the losing tx now reports the conflict on every path
    rerun = put_all(nodes[0], ["tx-doomed"], [ref], 200, bus, nodes)
    out = rerun.result(timeout=1)
    assert out["committed"] is False and out["conflicts"]


@pytest.mark.parametrize("seed", SEEDS)
def test_progress_after_leader_kill(seed):
    """Kill the leader outright (permanent full partition): the survivors
    re-elect and keep committing — liveness under a single node failure."""
    bus, nodes, maps = make_map_cluster(3)
    leader = run_until_leader(bus, nodes)

    with inject(*partition_rules(leader.node_id), seed=seed):
        successor = run_until_leader(bus, nodes, exclude=(leader,))
        refs = [StateRef(SecureHash.sha256(b"k%d" % i), 0) for i in range(3)]
        for i, ref in enumerate(refs):
            fut = put_all(successor, [f"tx{i}"], [ref], 200, bus, nodes)
            assert fut.result(timeout=1)["committed"] is True
        # commit-index propagation rides the next heartbeats; settle, then
        # both survivors must have applied all three commits
        pump(bus, nodes, 20)
        live_maps = [maps[i] for i, n in enumerate(nodes) if n is not leader]
        assert all(len(m) == 3 for m in live_maps)


@pytest.mark.parametrize("seed", SEEDS)
def test_leader_kill_records_one_election_episode(seed):
    """Consensus observatory: a leader-kill window produces EXACTLY one
    new election episode across the live nodes — split votes extend the
    same episode rather than inflating the count — and the episode's
    duration matches the observed re-election gap (the leaderless
    window an operator sees on /debug/raft)."""
    import time

    bus, nodes, maps = make_map_cluster(3)
    leader = run_until_leader(bus, nodes)
    live = [n for n in nodes if n is not leader]
    episodes_before = sum(n.stats()["elections_total"] for n in live)

    with inject(*partition_rules(leader.node_id), seed=seed):
        t0 = time.perf_counter()
        successor = run_until_leader(bus, nodes, exclude=(leader,))
        wall_gap = time.perf_counter() - t0

    episodes_after = sum(n.stats()["elections_total"] for n in live)
    assert episodes_after == episodes_before + 1
    episode = successor.stats()["elections"][-1]
    # the kill happened after term 0, so the cause is a timeout (the
    # votes can all exchange inside one tick's bus pump, so ticks may
    # legitimately be 0)
    assert episode["cause"] == "timeout"
    assert episode["ticks"] >= 0
    # the episode IS the re-election gap: it opened at the successor's
    # first candidacy inside the window and closed at leadership, so its
    # duration is positive and bounded by the measured wall gap
    assert 0 < episode["duration_s"] <= wall_gap
    # the observatory surfaces the same episode per group
    from corda_tpu.observability.consensus_obs import raft_report
    group = raft_report({"g0": live})["groups"]["g0"]
    assert group["elections_total"] == episodes_after
    assert group["leader"]["node"] == successor.node_id


@pytest.mark.parametrize("seed", SEEDS)
def test_commits_survive_append_drop_storm(seed):
    """30% of AppendEntries traffic dropped (seeded): the leader's tick
    resend loop must still drive every entry to commitment on every
    replica. Client submissions retry on leadership churn, so an entry
    may apply more than once — the invariant is replica AGREEMENT plus
    all entries present, which is exactly what the idempotent put_all
    command set relies on upstream."""
    applied = [[], [], []]
    bus = InMemoryMessagingNetwork()
    names = [f"raft{i}" for i in range(3)]
    nodes = [RaftNode(name, list(names), bus.create_node(name),
                      (lambda s: (lambda e: (s.append(e), len(s))[1]))(applied[i]),
                      seed=i)
             for i, name in enumerate(names)]
    run_until_leader(bus, nodes)

    with inject(FaultRule("raft.append", "drop", probability=0.3),
                seed=seed):
        for i in range(5):
            entry = f"entry-{i}"
            for _attempt in range(40):
                leader = next((n for n in nodes if n.role == LEADER), None)
                if leader is None:
                    pump(bus, nodes, 10)
                    continue
                fut = leader.submit(entry)
                for _ in range(60):
                    pump(bus, nodes, 1)
                    if fut.done():
                        break
                if fut.done() and not fut.exception():
                    break
            else:
                raise AssertionError(f"{entry} never committed under storm")
        pump(bus, nodes, 80)   # let stragglers catch up inside the storm

    assert applied[0] == applied[1] == applied[2]
    for i in range(5):
        assert f"entry-{i}" in applied[0]
