"""/traces endpoint + the end-to-end acceptance trace: one transaction
verified through TransactionVerifierService produces ONE trace whose spans
cover submit → batch flush → dispatch → resolve, retrievable over HTTP."""
import json
import urllib.error
import urllib.request

import pytest

import corda_tpu.finance  # noqa: F401
from corda_tpu.core.contracts import Command, TransactionState
from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.identity import Party
from corda_tpu.core.transactions import WireTransaction
from corda_tpu.node.rpc import CordaRPCOps
from corda_tpu.observability import disable_tracing, enable_tracing
from corda_tpu.testing import (DUMMY_NOTARY_NAME, DummyContract, DummyState,
                               MockNetwork, MockServices)
from corda_tpu.verifier import TpuTransactionVerifierService

NOTARY_KP = generate_keypair(entropy=b"\x20" * 32)
NOTARY = Party(DUMMY_NOTARY_NAME, NOTARY_KP.public)
ALICE_KP = generate_keypair(entropy=b"\x21" * 32)


@pytest.fixture(autouse=True)
def _noop_after():
    yield
    disable_tracing()


def _make_stx(services):
    wtx = WireTransaction(
        outputs=(TransactionState(DummyState(7, (ALICE_KP.public,)), NOTARY),),
        commands=(Command(DummyContract.Create(), (ALICE_KP.public,)),),
        notary=NOTARY, must_sign=(ALICE_KP.public,))
    return services.sign_transaction(wtx, ALICE_KP.public)


def _verify_one_stx():
    services = MockServices(key_pairs=[NOTARY_KP, ALICE_KP], parties=[NOTARY])
    svc = TpuTransactionVerifierService()
    try:
        assert svc.verify_signed(_make_stx(services),
                                 services).result(timeout=120) is None
    finally:
        svc.shutdown()


def test_single_tx_verify_produces_one_end_to_end_trace():
    tracer = enable_tracing()
    _verify_one_stx()
    traces = tracer.traces()
    # ONE trace: every span of the pipeline shares the root's trace id
    assert len(traces) == 1
    (spans,) = traces.values()
    names = {s["name"] for s in spans}
    assert {"tx.verify", "verifier.submit", "batcher.enqueue_wait",
            "batcher.flush", "batcher.dispatch", "batcher.resolve",
            "verifier.resolve", "verifier.run"} <= names
    roots = [s for s in spans if s["name"] == "tx.verify"]
    assert len(roots) == 1 and roots[0]["parent_id"] is None
    assert roots[0]["tags"]["n_sigs"] == 1
    dispatch = next(s for s in spans if s["name"] == "batcher.dispatch")
    assert dispatch["tags"]["route"] in ("host", "device")
    # parent links all resolve within the same trace
    ids = {s["span_id"] for s in spans}
    for s in spans:
        assert s["parent_id"] is None or s["parent_id"] in ids


@pytest.fixture
def web():
    network = MockNetwork()
    network.create_notary_node()
    alice = network.create_node("O=Alice, L=Madrid, C=ES")
    network.start_nodes()
    from corda_tpu.tools.webserver import NodeWebServer
    ops = CordaRPCOps(alice.services, alice.smm)
    server = NodeWebServer(ops, pump=network.run_network).start()
    yield server
    server.stop()


def _get_json(server, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}", timeout=10) as r:
        return json.loads(r.read())


def test_traces_endpoint_disabled_then_live(web):
    server = web
    # no-op tracer: well-formed empty answer, never an error
    out = _get_json(server, "/traces")
    assert out == {"enabled": False, "traces": {}}
    tracer = enable_tracing()
    _verify_one_stx()
    out = _get_json(server, "/traces")
    assert out["enabled"] is True and len(out["traces"]) == 1
    (trace_id,) = out["traces"]
    names = {s["name"] for s in out["traces"][trace_id]}
    assert {"tx.verify", "batcher.flush", "batcher.dispatch",
            "batcher.resolve"} <= names
    # filtered + limited view
    one = _get_json(server, f"/traces?trace_id={trace_id}&limit=2")
    assert one["trace_id"] == trace_id and len(one["spans"]) == 2
    assert _get_json(server, "/traces?trace_id=feedfacedeadbeef")["spans"] == []
    # JSONL export view: one JSON object per line, same span set
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/traces?format=jsonl",
            timeout=10) as r:
        assert r.headers["Content-Type"].startswith("application/x-ndjson")
        lines = [json.loads(l) for l in r.read().decode().splitlines()]
    assert {s["name"] for s in lines} == {s["name"] for s in tracer.spans()}


def test_metrics_endpoint_exposes_verifier_histograms():
    from corda_tpu.tools.webserver import prometheus_text
    from corda_tpu.utils.metrics import MetricRegistry
    reg = MetricRegistry()
    services = MockServices(key_pairs=[NOTARY_KP, ALICE_KP], parties=[NOTARY])
    svc = TpuTransactionVerifierService(metrics=reg)
    try:
        assert svc.verify_signed(_make_stx(services),
                                 services).result(timeout=120) is None
    finally:
        svc.shutdown()
    text = prometheus_text(reg.snapshot())
    for metric in ("verifier_batch_size", "verifier_dispatch_seconds",
                   "tx_verify_seconds"):
        for q in ("p50", "p90", "p99"):
            assert f"corda_tpu_{metric}_{q}" in text, (metric, q)


def test_traces_endpoint_stitches_cross_process_fleet_trace(web):
    """An out-of-process verification produces ONE trace whose spans come
    from BOTH sides of the process seam — the node's verifier.oop_submit
    and the worker's worker.* child spans — retrievable over /traces."""
    import time
    from corda_tpu.network.inmemory import InMemoryMessagingNetwork
    from corda_tpu.verifier.fleet import make_sig_checks
    from corda_tpu.verifier.out_of_process import (
        OutOfProcessTransactionVerifierService, VerifierWorker)

    enable_tracing()
    bus = InMemoryMessagingNetwork()
    svc = OutOfProcessTransactionVerifierService(bus.create_node("node"))
    worker = VerifierWorker(bus.create_node("w1"), "node")
    bus.run_network()
    fut = svc.verify_signatures(make_sig_checks(4))
    deadline = time.monotonic() + 60
    while not fut.done():
        bus.run_network()
        time.sleep(0.005)
        assert time.monotonic() < deadline, "verification did not resolve"
    assert fut.result(timeout=1) is None

    out = _get_json(web, "/traces")
    assert out["enabled"] is True
    stitched = [spans for spans in out["traces"].values()
                if {"verifier.oop_submit", "worker.device_dispatch"}
                <= {s["name"] for s in spans}]
    assert stitched, "no stitched cross-process trace on /traces"
    (spans,) = stitched
    submit = next(s for s in spans if s["name"] == "verifier.oop_submit")
    dispatch = next(s for s in spans if s["name"] == "worker.device_dispatch")
    assert dispatch["parent_id"] == submit["span_id"]
    assert dispatch["tags"]["worker"] == "w1"
    worker.stop()


def test_traces_endpoint_min_duration_filter(web):
    """?min_duration_ms= keeps only traces whose longest span clears the
    threshold — the tail-forensics entry point (find the slow ones)."""
    server = web
    tracer = enable_tracing()
    slow = tracer.record("flow.run", duration_s=2.0)
    tracer.record("tx.verify", parent=slow, duration_s=0.5)
    tracer.record("flow.run", duration_s=0.001)   # separate fast trace
    out = _get_json(server, "/traces")
    assert len(out["traces"]) == 2
    out = _get_json(server, "/traces?min_duration_ms=1000")
    assert len(out["traces"]) == 1
    (spans,) = out["traces"].values()
    assert {s["name"] for s in spans} == {"flow.run", "tx.verify"}
    # threshold above every trace: empty, not an error
    assert _get_json(server, "/traces?min_duration_ms=60000")["traces"] == {}
    # composes with trace_id (filtered single-trace view unaffected)
    assert _get_json(
        server,
        f"/traces?trace_id={slow.trace_id}&min_duration_ms=60000")["spans"]
    # malformed value is a 400, not a 500
    try:
        _get_json(server, "/traces?min_duration_ms=soon")
        assert False, "expected HTTP 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_debug_critpath_endpoint(web):
    """/debug/critpath returns the blame decomposition of live traces:
    per-class vectors that sum to the class e2e, and a top-K list of
    slowest transactions with annotated blocking chains."""
    server = web
    # tracing off: well-formed empty report
    out = _get_json(server, "/debug/critpath")
    assert out["traces"] == 0 and out["top"] == []
    tracer = enable_tracing()
    # synthetic commit path: flow.run with a verify child and a notary
    # wait — the decomposition must cover all 4s
    root = tracer.record("flow.run", start_s=100.0, duration_s=4.0,
                         flow_type="corda_tpu.finance.cash.CashPaymentFlow")
    tracer.record("tx.verify", parent=root, start_s=100.5, duration_s=1.0)
    tracer.record("wait.await_future", parent=root, start_s=101.5,
                  duration_s=2.0, wait_kind="notary.commit")
    out = _get_json(server, "/debug/critpath?top_k=3")
    assert out["traces"] == 1
    assert out["per_class"]["pay"]["n"] == 1
    blame = out["per_class"]["pay"]["blame_p50"]
    assert abs(sum(blame.values()) - 4000.0) < 1.0   # conservation
    assert blame["verify"] == pytest.approx(1000.0)
    assert blame["notary.batch_wait"] == pytest.approx(2000.0)
    (top,) = out["top"]
    assert top["e2e_ms"] == pytest.approx(4000.0)
    kinds = [s["wait_kind"] for s in top["segments"]]
    assert "notary.commit" in kinds
    # bad top_k is a 400
    try:
        _get_json(server, "/debug/critpath?top_k=many")
        assert False, "expected HTTP 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400
