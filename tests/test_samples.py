"""Sample-demo acceptance tests (the reference pattern: every sample has an
integration test doubling as an end-to-end acceptance test — SURVEY.md §4.4).
"""
import pytest

from corda_tpu.samples import attachment_demo, bank_of_corda, notary_demo


def test_bank_of_corda_issuance():
    from corda_tpu.finance import CashState
    out = bank_of_corda.run_demo(amount_dollars=500)
    holdings = out["requester"].services.vault.unconsumed_states(CashState)
    assert sum(s.state.data.amount.quantity for s in holdings) == 500 * 100
    # the issuer reference is the bank
    assert all(str(s.state.data.amount.token.issuer.party.name)
               == str(out["bank"].party.name) for s in holdings)


def test_bank_of_corda_refuses_over_cap():
    from corda_tpu.core.contracts.amount import Amount, USD
    from corda_tpu.flows import FlowException
    from corda_tpu.samples.bank_of_corda import IssuanceRequester, install_issuer
    from corda_tpu.testing import MockNetwork
    network = MockNetwork()
    network.create_notary_node()
    bank = network.create_node("O=BankOfCorda, L=London, C=GB")
    requester = network.create_node("O=Greedy, L=Nowhere, C=US")
    network.start_nodes()
    install_issuer(bank.smm)
    fsm = requester.start_flow(IssuanceRequester(
        bank.party, Amount(10_000_000_00, USD)))
    network.run_network()
    with pytest.raises(FlowException, match="cap"):
        fsm.result_future.result(timeout=5)


def test_notary_demo_simple_and_validating():
    out = notary_demo.run_demo(rounds=2)
    assert out["notarised"] == 2
    assert out["conflicts"] == 2
    out = notary_demo.run_demo(rounds=1, validating=True)
    assert out["notarised"] == 1
    assert out["conflicts"] == 1


def test_notary_demo_raft_cluster():
    out = notary_demo.run_raft_demo(rounds=2)
    assert out["notarised"] == 2
    assert out["replicas_agree"]
    assert out["commit_log_size"] == 2


def test_notary_demo_bft_cluster():
    out = notary_demo.run_bft_demo(rounds=2)
    assert out["notarised"] == 2
    assert out["replicas_agree"]
    assert out["commit_log_size"] == 2


def test_attachment_demo():
    out = attachment_demo.run_demo()
    assert out["attachment"].data == out["document"]
    assert out["attachment"].verify()
    # the receiver resolved + recorded the attachment-bearing transaction
    assert out["receiver"].services.storage.get_transaction(
        out["final"].id) is not None
