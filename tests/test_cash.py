"""Cash contract + flows tests.

Reference analogs: CashTests.kt (clause conservation rules) and the cash flow
tests (CashIssueFlowTests / CashPaymentFlowTests / CashExitFlowTests).
"""
import pytest

from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.core.contracts.structures import Issued, PartyAndReference
from corda_tpu.core.contracts.exceptions import TransactionVerificationException
from corda_tpu.finance import (Cash, CashExitFlow, CashIssueFlow,
                               CashPaymentFlow, CashState)
from corda_tpu.flows import FlowException
from corda_tpu.testing import MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank = network.create_node("O=Bank of Corda, L=London, C=GB")
    alice = network.create_node("O=Alice, L=Madrid, C=ES")
    network.start_nodes()
    return network, notary, bank, alice


def dollars(n):
    return Amount(n * 100, USD)  # cents


def test_issue_and_pay(net):
    network, notary, bank, alice = net
    fsm = bank.start_flow(CashIssueFlow(dollars(100), b"\x01", bank.party,
                                        notary.party))
    network.run_network()
    stx = fsm.result_future.result(timeout=1)
    issued = stx.tx.outputs[0].data
    assert isinstance(issued, CashState)
    assert issued.amount.quantity == 100 * 100
    assert bank.services.vault.unconsumed_states(CashState)

    # bank pays alice $30; change returns to bank
    fsm = bank.start_flow(CashPaymentFlow(dollars(30), alice.party))
    network.run_network()
    pay_stx = fsm.result_future.result(timeout=1)
    amounts = sorted(o.data.amount.quantity for o in pay_stx.tx.outputs)
    assert amounts == [30 * 100, 70 * 100]
    # alice's vault tracked her new cash
    alice_states = alice.services.vault.unconsumed_states(CashState)
    assert [s.state.data.amount.quantity for s in alice_states] == [30 * 100]
    # bank's spent coin is consumed, change unconsumed
    bank_states = bank.services.vault.unconsumed_states(CashState)
    assert [s.state.data.amount.quantity for s in bank_states] == [70 * 100]
    # double payment larger than balance fails cleanly
    fsm = bank.start_flow(CashPaymentFlow(dollars(75), alice.party))
    network.run_network()
    with pytest.raises(FlowException, match="Insufficient cash"):
        fsm.result_future.result(timeout=1)


def test_exit(net):
    network, notary, bank, alice = net
    bank.start_flow(CashIssueFlow(dollars(50), b"\x01", bank.party,
                                  notary.party))
    network.run_network()
    fsm = bank.start_flow(CashExitFlow(dollars(20), b"\x01"))
    network.run_network()
    stx = fsm.result_future.result(timeout=1)
    remaining = bank.services.vault.unconsumed_states(CashState)
    assert sum(s.state.data.amount.quantity for s in remaining) == 30 * 100


def test_cash_contract_conservation():
    """Direct contract-level checks (CashTests.kt style) without a network."""
    from corda_tpu.core.crypto import generate_keypair
    from corda_tpu.core.identity import Party
    from corda_tpu.core.transactions.ledger import TransactionForContract
    from corda_tpu.core.contracts.structures import AuthenticatedObject
    from corda_tpu.core.crypto.secure_hash import SecureHash

    bank_kp = generate_keypair(entropy=b"\x31" * 32)
    bank = Party("O=Bank, L=London, C=GB", bank_kp.public)
    alice_kp = generate_keypair(entropy=b"\x32" * 32)
    token = Issued(PartyAndReference(bank, b"\x01"), USD)

    def ctx(inputs, outputs, commands):
        return TransactionForContract(
            inputs=tuple(inputs), outputs=tuple(outputs), attachments=(),
            commands=tuple(commands), id=SecureHash.sha256(b"tx"),
            notary=None)

    cash = Cash()
    in_state = CashState(Amount(1000, token), bank_kp.public)
    out_state = CashState(Amount(1000, token), alice_kp.public)
    move = AuthenticatedObject((bank_kp.public,), (), Cash.Move())

    # conserved move passes
    cash.verify(ctx([in_state], [out_state], [move]))

    # non-conserved move fails
    bad_out = CashState(Amount(900, token), alice_kp.public)
    with pytest.raises(TransactionVerificationException, match="conserved"):
        cash.verify(ctx([in_state], [bad_out], [move]))

    # move without the owner's signature fails
    unsigned = AuthenticatedObject((alice_kp.public,), (), Cash.Move())
    with pytest.raises(TransactionVerificationException, match="owner"):
        cash.verify(ctx([in_state], [out_state], [unsigned]))

    # issue must be signed by the issuer
    issue_ok = AuthenticatedObject((bank_kp.public,), (), Cash.Issue())
    cash.verify(ctx([], [in_state], [issue_ok]))
    issue_bad = AuthenticatedObject((alice_kp.public,), (), Cash.Issue())
    with pytest.raises(TransactionVerificationException, match="issuer"):
        cash.verify(ctx([], [in_state], [issue_bad]))

    # exit-only transactions must also conserve: no minting via bare Exit
    exit_100 = AuthenticatedObject((bank_kp.public,), (),
                                   Cash.Exit(Amount(100, token)))
    out_900 = CashState(Amount(900, token), alice_kp.public)
    cash.verify(ctx([in_state], [out_900], [exit_100]))  # 1000 = 900 + 100 ok
    small_in = CashState(Amount(100, token), bank_kp.public)
    with pytest.raises(TransactionVerificationException, match="conserved"):
        cash.verify(ctx([small_in], [out_900], [exit_100]))  # mints 900
