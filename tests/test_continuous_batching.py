"""Continuous-batching pipeline tests (PR 6): shape-bucket compile
stability, latency classes, bulk admission control, and the staging-buffer
lease discipline.

The device seam is stubbed at ``_start_ed25519`` (the same seam the
breaker chaos tests pin) so these run in tier-1 without paying an XLA
compile: the stub routes a shape-faithful padded array through
``KernelProfiler.call`` — the profiler's novel-signature fallback then
counts a "compile" exactly when the batcher hands the kernel a shape it
has not seen, which is the property the bucket ladder exists to bound.
"""
import threading
import time

import numpy as np
import pytest

from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.schemes import EDDSA_ED25519_SHA512
from corda_tpu.core.crypto.signatures import Crypto
from corda_tpu.observability.profiling import (
    KernelProfiler, get_profiler, set_profiler)
from corda_tpu.ops import field as F
from corda_tpu.ops.staging import StagingPool
from corda_tpu.testing.faults import FaultRule, inject
from corda_tpu.utils.metrics import MetricRegistry
from corda_tpu.verifier.batcher import BULK, INTERACTIVE, SignatureBatcher

KP = generate_keypair(EDDSA_ED25519_SHA512, entropy=b"\x42" * 32)
CONTENT = b"continuous batching content"
SIG = Crypto.sign_with_key(KP, CONTENT).bytes
TRIPLE = (KP.public, SIG, CONTENT)


# -- bucket ladder ----------------------------------------------------------

def test_pow2_ladder_rungs():
    assert SignatureBatcher._pow2_ladder(256, 2048) == (256, 512, 1024, 2048)
    # a non-pow2 cap rides along as the one extra megabatch shape
    assert SignatureBatcher._pow2_ladder(256, 3000) == (
        256, 512, 1024, 2048, 3000)
    # cap below the floor collapses to a single rung
    assert SignatureBatcher._pow2_ladder(256, 128) == (128,)


def test_ladder_cut_prefers_largest_fitting_rung():
    b = SignatureBatcher(metrics=MetricRegistry(), use_device=False,
                         bucket_ladder=(8, 16, 32, 64), max_batch=64)
    try:
        assert b._ladder_cut("ed25519", 70) == 64
        assert b._ladder_cut("ed25519", 33) == 32
        assert b._ladder_cut("ed25519", 8) == 8
        # sub-floor tails dispatch at raw depth (the kernels pad them)
        assert b._ladder_cut("ed25519", 5) == 5
    finally:
        b.close()


def test_per_scheme_ladder_overrides_default():
    b = SignatureBatcher(metrics=MetricRegistry(), use_device=False,
                         bucket_ladder={"ed25519": (512, 1024)})
    try:
        assert b._ladder_for("ed25519") == (512, 1024)
        assert b._ladder_for("secp256k1") == b._default_ladder
    finally:
        b.close()


def test_ladder_from_occupancy_tunes_floor_per_scheme():
    prof = KernelProfiler()
    for _ in range(4):
        prof.record_occupancy("ed25519", 16384, 16384)   # megabatch-fed
        prof.record_occupancy("secp256r1", 300, 512)     # trickle-fed
    ladders = SignatureBatcher.ladder_from_occupancy(
        profiler=prof, max_batch=32768)
    # floor doubles toward the observed mean with one rung of headroom
    assert ladders["ed25519"] == SignatureBatcher._pow2_ladder(8192, 32768)
    assert ladders["secp256r1"][0] == SignatureBatcher.LADDER_FLOOR


# -- shape-bucket compile stability (satellite: zero post-warmup compiles) --

def test_steady_state_varying_batches_zero_new_compiles_after_warmup():
    """Mixed arrival sizes after warmup must land entirely inside the
    warmed shape set: ladder cuts recur on the rungs and sub-floor tails
    pad to power-of-two buckets, so the (stub) jit cache never grows."""
    prof = KernelProfiler()
    old = get_profiler()
    set_profiler(prof)
    b = SignatureBatcher(metrics=MetricRegistry(), host_crossover=0,
                         max_latency_s=0.01, interactive_latency_s=0.01,
                         bucket_ladder=(8, 16, 32, 64), max_batch=64)

    def stub_start(items):
        n = len(items)
        cap = F.bucket_size(n, floor=8)      # pad exactly like the kernels
        rows = np.zeros((cap,), dtype=np.uint8)
        out = prof.call("stub.ed25519", lambda a: a, rows,
                        live=n, capacity=cap, scheme="ed25519")
        return (out, n), (lambda pending: [True] * pending[1])

    b._start_ed25519 = stub_start
    try:
        # warm phase: one batch per ladder rung
        for rung in (8, 16, 32, 64):
            assert all(b.submit_group([TRIPLE] * rung,
                                      latency_class=BULK).result(timeout=60))
        prof.mark_warm()
        hits0 = prof.compile_totals()["compile_cache_hits"]
        # steady state: arrival sizes that hit no rung exactly — every cut
        # and every padded tail must re-see a warmed shape
        for n in (70, 23, 64, 41, 9, 128, 57):
            assert all(b.submit_group([TRIPLE] * n,
                                      latency_class=BULK).result(timeout=60))
        assert prof.compiles_since_warm() == 0
        assert prof.compile_totals()["compile_cache_hits"] > hits0
        # every dispatched batch fed the occupancy surface
        assert prof.snapshot()["occupancy"]["ed25519"]["batches"] >= 11
    finally:
        b.close()
        set_profiler(old)


# -- latency classes --------------------------------------------------------

def test_interactive_submit_meets_deadline_under_bulk_pressure():
    """An interactive submit behind a wall of queued bulk megabatches must
    resolve via its priority in-flight slot long before the bulk backlog
    drains — the whole point of the latency class split."""
    b = SignatureBatcher(metrics=MetricRegistry(), host_crossover=0,
                         max_latency_s=0.05, interactive_latency_s=0.001,
                         bucket_ladder=(8,), max_batch=8)

    def slow_start(items):
        n = len(items)

        def finish(pending):
            time.sleep(0.25)                 # a busy "device"
            return [True] * n
        return n, finish

    b._start_ed25519 = slow_start
    try:
        bulk_futs = [b.submit_group([TRIPLE] * 8, latency_class=BULK)
                     for _ in range(12)]     # ~1s of stubbed device work
        t0 = time.perf_counter()
        f = b.submit(KP.public, SIG, CONTENT)   # INTERACTIVE by default
        assert f.result(timeout=60) is True
        interactive_s = time.perf_counter() - t0
        # the backlog was still draining when the interactive check landed
        assert sum(1 for g in bulk_futs if g.done()) < len(bulk_futs)
        for g in bulk_futs:
            assert all(g.result(timeout=60))
        bulk_s = time.perf_counter() - t0
        assert interactive_s < bulk_s
        assert interactive_s < 1.5
    finally:
        b.close()


def test_bulk_admission_blocks_at_cap_interactive_always_admitted():
    """max_pending backpressure lands on bulk producers (their enqueue
    blocks at the cap) while interactive submissions are admitted
    instantly — bounded latency under bulk pressure by construction."""
    started = threading.Semaphore(0)
    release = threading.Event()
    b = SignatureBatcher(metrics=MetricRegistry(), use_device=False,
                         max_latency_s=0.001, max_pending=8)
    orig_host = SignatureBatcher._run_host

    def gated_host(items):
        started.release()
        release.wait(timeout=30)
        return orig_host(items)

    b._run_host = gated_host
    try:
        wedged = []
        # wedge the three prep workers one flush at a time (waiting for
        # each to START so consecutive submits cannot coalesce)
        for _ in range(3):
            wedged.append(b.submit_group([TRIPLE], latency_class=BULK))
            assert started.acquire(timeout=10)
        # a fourth plan claims the last host in-flight slot and queues
        # behind the wedged pool workers
        wedged.append(b.submit_group([TRIPLE], latency_class=BULK))
        deadline = time.time() + 10
        while time.time() < deadline and b._inflight_n["host"] < 4:
            time.sleep(0.01)
        assert b._inflight_n["host"] == 4
        # no slots left: this group stays queued, filling the bulk cap
        wedged.append(b.submit_group([TRIPLE] * 8, latency_class=BULK))

        blocked_done = threading.Event()
        extra = []

        def producer():
            extra.append(b.submit_group([TRIPLE], latency_class=BULK))
            blocked_done.set()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        assert not blocked_done.wait(timeout=0.5)   # admission blocked
        # interactive bypasses admission control entirely
        t0 = time.perf_counter()
        f_int = b.submit_many([TRIPLE], latency_class=INTERACTIVE)[0]
        assert time.perf_counter() - t0 < 1.0
        assert not blocked_done.is_set()

        release.set()
        assert blocked_done.wait(timeout=30)        # producer re-admitted
        t.join(timeout=30)
        assert f_int.result(timeout=30) is True
        for g in wedged + extra:
            assert all(g.result(timeout=30))
    finally:
        release.set()
        b.close()


# -- breaker trip mid-pipeline (chaos-seeded) -------------------------------

@pytest.mark.chaos
@pytest.mark.parametrize("seed", [7, 9001])
def test_breaker_trip_mid_pipeline_zero_lost_futures(seed):
    """A 100%-failing device dispatch under CONCURRENT in-flight batches
    (the double-buffered pipeline, not the sequential chaos test): every
    future still resolves, the breaker trips exactly once, and post-trip
    batches route to host."""
    b = SignatureBatcher(metrics=MetricRegistry(), host_crossover=1,
                         max_latency_s=0.001, breaker_threshold=3,
                         bucket_ladder=(4,), max_batch=4)
    try:
        with inject(FaultRule("batcher.device_dispatch", "raise",
                              detail="ed25519"), seed=seed):
            futs = [b.submit_group([TRIPLE] * 4, latency_class=BULK)
                    for _ in range(10)]
            results = [g.result(timeout=60) for g in futs]
        assert all(len(r) == 4 and all(r) for r in results)   # zero lost
        st = b.breaker_status()["ed25519"]
        assert st["state"] == "open"
        assert st["trips"] == 1
        snap = b.metrics.snapshot()
        assert snap["SigBatcher.InFlight"]["value"] == 0
        assert snap["SigBatcher.BatchFailure"]["count"] >= 3
        assert snap["SigBatcher.BreakerRouted"]["count"] > 0
    finally:
        b.close()


def test_breaker_open_host_route_keeps_occupancy_and_gauges_fresh():
    """Degraded mode must not freeze the observability surface: a
    breaker-routed batch still records occupancy (100% live — no padding)
    and the per-scheme gauges read current state."""
    prof = KernelProfiler()
    old = get_profiler()
    set_profiler(prof)
    reg = MetricRegistry()
    b = SignatureBatcher(metrics=reg, host_crossover=1, max_latency_s=0.001)
    try:
        for _ in range(3):
            b._breakers["ed25519"].record_failure()
        assert b.breaker_status()["ed25519"]["state"] == "open"
        assert all(b.submit_group([TRIPLE] * 4,
                                  latency_class=BULK).result(timeout=60))
        occ = prof.snapshot()["occupancy"]["ed25519"]
        assert occ["batches"] == 1
        assert occ["live_total"] == occ["capacity_total"] == 4
        assert occ["occupancy_pct"] == 100.0
        snap = reg.snapshot()
        assert snap["SigBatcher.BreakerRouted"]["count"] == 4
        assert snap["SigBatcher.ed25519.QueueDepth"]["value"] == 0
        assert snap["SigBatcher.ed25519.InFlight"]["value"] == 0
    finally:
        b.close()
        set_profiler(old)


# -- staging pool -----------------------------------------------------------

def test_staging_pool_reuses_released_buffers():
    pool = StagingPool()
    lease = pool.lease()
    a = lease.take("t.rows", (16, 4), np.uint16)
    assert a.shape == (16, 4) and a.dtype == np.uint16
    lease.release()
    lease.release()                       # idempotent
    lease2 = pool.lease()
    assert lease2.take("t.rows", (16, 4), np.uint16) is a   # recycled
    # a second concurrent take of the same key gets fresh memory
    assert lease2.take("t.rows", (16, 4), np.uint16) is not a
    # different shape/dtype never shares
    assert lease2.take("t.rows", (8, 4), np.uint16) is not a
    stats = pool.stats()
    assert stats["hits"] == 1 and stats["misses"] == 3


def test_staging_pool_release_via_pending_handle():
    pool = StagingPool()
    lease = pool.lease()
    arr = lease.take("t.x", (8,), np.uint8)
    handle = object()
    pool.attach(handle, lease)
    assert pool.stats()["attached"] == 1
    pool.release_for(handle)              # the finish_batch force point
    assert pool.stats()["attached"] == 0
    assert pool.lease().take("t.x", (8,), np.uint8) is arr
    pool.release_for(handle)              # unknown handle: no-op


def test_staging_pool_dropped_lease_is_never_recycled():
    """A lease abandoned mid-dispatch (failure path) must not return its
    possibly-device-aliased buffers to the free lists."""
    pool = StagingPool(max_attached=2)
    leases = [pool.lease() for _ in range(3)]
    arrays = [ls.take("t.y", (4,), np.uint8) for ls in leases]
    handles = [object() for _ in range(3)]   # kept alive: attach keys by id
    for handle, ls in zip(handles, leases):
        pool.attach(handle, ls)
    # the oldest lease was evicted (bounded table) — dropped, not reclaimed
    assert pool.stats()["attached"] == 2
    fresh = pool.lease().take("t.y", (4,), np.uint8)
    assert all(fresh is not a for a in arrays)
