"""Verifier-service tests: async SPI, device-batched signature checking.

Reference analogs: InMemoryTransactionVerifierService behavior, the
OutOfProcess service's metrics wiring (OutOfProcessTransactionVerifierService.kt:33-45),
and VerifierTests.kt's "all transactions verify / invalid one fails" cases.
"""
import pytest

from corda_tpu.core.contracts import (Command, StateRef, TransactionState)
from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.schemes import (ECDSA_SECP256K1_SHA256,
                                           EDDSA_ED25519_SHA512)
from corda_tpu.core.crypto.signatures import Crypto, SignatureException
from corda_tpu.core.identity import Party
from corda_tpu.core.transactions import (SignaturesMissingException,
                                         SignedTransaction, WireTransaction)
from corda_tpu.testing import (DUMMY_NOTARY_NAME, DummyContract, DummyState,
                               MockServices)
from corda_tpu.verifier import (SignatureBatcher,
                                InMemoryTransactionVerifierService,
                                TpuTransactionVerifierService,
                                make_verifier_service)

NOTARY_KP = generate_keypair(entropy=b"\x20" * 32)
NOTARY = Party(DUMMY_NOTARY_NAME, NOTARY_KP.public)
ALICE_KP = generate_keypair(entropy=b"\x21" * 32)
ALICE_K1_KP = generate_keypair(ECDSA_SECP256K1_SHA256, entropy=b"\x22" * 32)


def make_issue_stx(services, owner_kp=ALICE_KP):
    wtx = WireTransaction(
        outputs=(TransactionState(DummyState(7, (owner_kp.public,)), NOTARY),),
        commands=(Command(DummyContract.Create(), (owner_kp.public,)),),
        notary=NOTARY, must_sign=(owner_kp.public,))
    return services.sign_transaction(wtx, owner_kp.public)


@pytest.fixture
def services():
    return MockServices(key_pairs=[NOTARY_KP, ALICE_KP, ALICE_K1_KP],
                        parties=[NOTARY])


def test_in_memory_service_verifies(services):
    stx = make_issue_stx(services)
    svc = InMemoryTransactionVerifierService()
    fut = svc.verify(stx.to_ledger_transaction(services))
    assert fut.result(timeout=30) is None
    snap = svc.metrics.snapshot()
    assert snap["Verification.Success"]["count"] == 1
    svc.shutdown()


def test_in_memory_service_propagates_failure(services):
    from corda_tpu.core.contracts import SignersMissing
    wtx = WireTransaction(
        outputs=(TransactionState(DummyState(7, (ALICE_KP.public,)), NOTARY),),
        commands=(Command(DummyContract.Create(), (ALICE_KP.public,)),),
        notary=NOTARY, must_sign=())  # required signer missing
    stx = services.sign_transaction(wtx, ALICE_KP.public)
    svc = InMemoryTransactionVerifierService()
    fut = svc.verify(stx.to_ledger_transaction(services))
    with pytest.raises(SignersMissing):
        fut.result(timeout=30)
    assert svc.metrics.snapshot()["Verification.Failure"]["count"] == 1
    svc.shutdown()


def test_signature_batcher_mixed_schemes(services):
    batcher = SignatureBatcher(max_latency_s=0.01)
    content = b"batched content"
    futures, want = [], []
    for i in range(6):
        kp = [ALICE_KP, ALICE_K1_KP, NOTARY_KP][i % 3]
        sig = Crypto.sign_with_key(kp, content)
        sig_bytes = sig.bytes if i % 4 != 3 else sig.bytes[:-2] + b"\x00\x00"
        futures.append(batcher.submit(kp.public, sig_bytes, content))
        want.append(Crypto.is_valid(kp.public, sig_bytes, content))
    got = [f.result(timeout=120) for f in futures]
    assert got == want
    assert False in got and True in got
    assert batcher.metrics.snapshot()["SigBatcher.Checked"]["count"] == 6
    assert batcher.metrics.snapshot()["SigBatcher.InFlight"]["value"] == 0
    batcher.close()


def test_tpu_service_full_path(services):
    svc = TpuTransactionVerifierService()
    stx = make_issue_stx(services)
    assert svc.verify_signed(stx, services).result(timeout=120) is None

    # corrupted signature → SignatureException from the device verdict
    bad_sig = stx.sigs[0].__class__(
        stx.sigs[0].bytes[:-1] + bytes([stx.sigs[0].bytes[-1] ^ 1]),
        stx.sigs[0].by)
    bad_stx = SignedTransaction(stx.tx_bits, (bad_sig,))
    with pytest.raises(SignatureException):
        svc.verify_signed(bad_stx, services).result(timeout=120)

    # signature by the wrong key → coverage failure
    k1_stx_wtx = stx.tx
    other = SignedTransaction.of(
        k1_stx_wtx, [services.sign(k1_stx_wtx.id.bytes, ALICE_K1_KP.public)])
    with pytest.raises(SignaturesMissingException):
        svc.verify_signed(other, services).result(timeout=120)
    svc.shutdown()


def test_verify_signed_submits_one_group_per_tx(services):
    """Acceptance pin: the TPU service path rides submit_group — ONE future
    per transaction's signature set, never per-signature submit_many
    futures (~25µs of Future allocation each)."""
    svc = TpuTransactionVerifierService()
    calls = []
    orig = svc.batcher.submit_group

    def spy(checks, ctx=None, **kw):
        calls.append(len(checks))
        return orig(checks, ctx=ctx, **kw)

    def reject(*a, **k):
        raise AssertionError("verify_signed must not use submit_many")

    svc.batcher.submit_group = spy
    svc.batcher.submit_many = reject
    try:
        stx = make_issue_stx(services)
        assert svc.verify_signed(stx, services).result(timeout=120) is None
        assert calls == [len(stx.sigs)]
    finally:
        svc.shutdown()


def test_verify_signed_on_closed_batcher_returns_failed_future(services):
    """Span-leak fix: if the batcher rejects the submission (closed), the
    caller must get a FAILED FUTURE — verify_signed's contract is async —
    and the root tx.verify span must still be finished, not leaked."""
    from corda_tpu.observability import disable_tracing, enable_tracing
    tracer = enable_tracing()
    svc = TpuTransactionVerifierService()
    try:
        stx = make_issue_stx(services)
        svc.batcher.close()
        fut = svc.verify_signed(stx, services)
        assert fut.done()
        with pytest.raises(RuntimeError, match="closed"):
            fut.result(timeout=5)
        # an unfinished span never reaches the ring: its presence IS the
        # proof that root.finish() ran on the failure path
        assert "tx.verify" in {s["name"] for s in tracer.spans()}
    finally:
        disable_tracing()
        svc.shutdown()


def test_make_verifier_service_seam():
    assert isinstance(make_verifier_service("InMemory"),
                      InMemoryTransactionVerifierService)
    svc = make_verifier_service("Tpu")
    assert isinstance(svc, TpuTransactionVerifierService)
    svc.shutdown()
    with pytest.raises(ValueError):
        make_verifier_service("Bogus")


def test_flows_route_verification_through_the_service_seam():
    """VERDICT r2: flows call hub.verify_transaction — with a TPU backend
    installed, a normal payment's signature checks ride the node's device
    batcher (the service seam composed with the node, not just bare
    kernels)."""
    import corda_tpu.finance  # noqa: F401
    from corda_tpu.core.contracts.amount import Amount, USD
    from corda_tpu.finance import CashIssueFlow, CashPaymentFlow
    from corda_tpu.testing import MockNetwork

    network = MockNetwork()
    notary = network.create_notary_node()
    alice = network.create_node("O=Alice, L=London, C=GB")
    bob = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()
    batchers = {}
    for node in (notary, alice, bob):
        batcher = SignatureBatcher(host_crossover=0, max_latency_s=0.01)
        batchers[node] = batcher
        node.services.verifier_service = TpuTransactionVerifierService(
            batcher=batcher)
    try:
        fsm = alice.start_flow(CashIssueFlow(
            Amount(900, USD), b"\x01", alice.party, notary.party))
        network.run_network()
        fsm.result_future.result(timeout=5)
        fsm = alice.start_flow(CashPaymentFlow(Amount(400, USD), bob.party))
        deadline = __import__("time").monotonic() + 120
        while not fsm.result_future.done():
            network.run_network()
            __import__("time").sleep(0.01)
            assert __import__("time").monotonic() < deadline
        fsm.result_future.result(timeout=5)
        # bob's NotifyTransactionHandler verified the broadcast through HIS
        # device batcher (payment inputs -> his node resolves and verifies)
        snap = batchers[bob].metrics.snapshot()
        assert snap.get("SigBatcher.DeviceChecked", {}).get("count", 0) > 0
        assert [s.state.data.amount.quantity
                for s in bob.services.vault.unconsumed_states()] == [400]
    finally:
        for b in batchers.values():
            b.close()
