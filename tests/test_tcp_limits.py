"""Wire-level resource caps on the TCP plane (VERDICT r3 #6).

Reference parity: Artemis' 10 MiB maxMessageSize
(ArtemisMessagingServer.kt:95) — one peer must not be able to OOM a node
with a single giant frame, and a local producer gets a typed error instead
of a severed connection.
"""
import socket
import time

import pytest

from corda_tpu.network.messaging import TopicSession
from corda_tpu.network.tcp import (MAX_FRAME, MessageSizeExceededError,
                                   TcpMessagingService)


@pytest.fixture
def plane():
    services = {}

    def resolve(name):
        svc = services.get(name)
        return ("127.0.0.1", svc.port) if svc else None

    svc = TcpMessagingService("node", "127.0.0.1", 0, resolve,
                              max_frame=64 * 1024)
    services["node"] = svc
    yield svc
    svc.stop()


def test_default_cap_is_artemis_parity():
    assert MAX_FRAME == 10 * 1024 * 1024


def test_local_oversized_send_raises_typed_error(plane):
    with pytest.raises(MessageSizeExceededError):
        plane.send(TopicSession("t"), b"\x00" * (64 * 1024 + 1), "node")


def test_hostile_giant_header_closes_connection_node_survives(plane):
    got = []
    plane.add_message_handler(
        TopicSession("t"), lambda m: got.append(m.data))

    # hostile peer: claim a 1 GiB frame, then stream garbage
    raw = socket.create_connection(("127.0.0.1", plane.port), timeout=5)
    raw.sendall((1 << 30).to_bytes(4, "big"))
    raw.sendall(b"\xde\xad" * 1024)
    raw.settimeout(5)
    # the node must sever the connection instead of buffering
    deadline = time.monotonic() + 5
    closed = False
    while time.monotonic() < deadline:
        try:
            if raw.recv(4096) == b"":
                closed = True
                break
        except (ConnectionResetError, BrokenPipeError):
            closed = True
            break
        except socket.timeout:
            break
    raw.close()
    assert closed, "node kept the hostile connection open"

    # and the plane still serves legitimate traffic afterwards
    plane.send(TopicSession("t"), b"still-alive", "node")
    deadline = time.monotonic() + 10
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    assert got == [b"still-alive"]
