"""Audit service: typed event taxonomy + node recording sites.

Reference analog: services/api/AuditService.kt:14-93 (event hierarchy incl.
FlowPermissionAuditEvent) — here verified against real flow runs.
"""
import pytest

from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.finance import CashIssueFlow
from corda_tpu.node.audit import (FlowErrorAuditEvent, FlowPermissionAuditEvent,
                                  FlowStartEvent, InMemoryAuditService,
                                  SystemAuditEvent)
from corda_tpu.node.rpc import CordaRPCOps, FlowPermissionException
from corda_tpu.testing import MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank = network.create_node("O=Bank, L=London, C=GB")
    network.start_nodes()
    return network, notary, bank


def test_flow_lifecycle_and_permission_events(net):
    network, notary, bank = net
    audit = bank.services.audit
    rpc = CordaRPCOps(bank.services, bank.smm)
    seen = []
    audit.add_observer(seen.append)

    rpc.start_flow_dynamic("CashIssueFlow", Amount(5000, USD), b"\x01",
                           bank.party, notary.party)
    network.run_network()
    starts = audit.events(FlowStartEvent)
    assert any(e.flow_type.endswith("CashIssueFlow") for e in starts)
    perms = audit.events(FlowPermissionAuditEvent)
    assert perms and perms[0].permission_granted
    assert perms[0].permission_requested.startswith("StartFlow.")
    assert seen  # observer callback fired

    with pytest.raises(FlowPermissionException):
        rpc.start_flow_dynamic("NotAFlow")
    denied = [e for e in audit.events(FlowPermissionAuditEvent)
              if not e.permission_granted]
    assert denied and denied[0].flow_type == "NotAFlow"


def test_flow_error_event(net):
    network, notary, bank = net
    # a flow that fails: pay more cash than the vault holds
    from corda_tpu.finance import CashPaymentFlow
    fsm = bank.start_flow(CashPaymentFlow(Amount(10**9, USD), notary.party))
    network.run_network()
    with pytest.raises(Exception):
        fsm.result_future.result(timeout=1)
    errors = bank.services.audit.events(FlowErrorAuditEvent)
    assert errors and "Insufficient" in errors[-1].error


def test_capacity_bound():
    svc = InMemoryAuditService(capacity=5)
    for i in range(12):
        svc.record_audit_event(SystemAuditEvent(description=f"e{i}"))
    evs = svc.events()
    assert len(evs) == 5
    assert evs[-1].description == "e11"
