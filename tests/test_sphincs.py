"""SPHINCS-256 hash-based signatures — scheme #5 of the crypto layer.

Reference analog: CryptoUtilsTest's per-scheme sign/verify roundtrip +
malformed-input rejection for SPHINCS256_SHA512_256 (reference
Crypto.kt:139-156). Construction details in corda_tpu/core/crypto/sphincs.py.
"""
import pytest

from corda_tpu.core.crypto import sphincs
from corda_tpu.core.crypto import (Crypto, SPHINCS256_SHA256, generate_keypair)


@pytest.fixture(scope="module")
def keypair():
    return sphincs.keygen(b"\x2a" * 32)


@pytest.fixture(scope="module")
def signed(keypair):
    pub, priv = keypair
    msg = b"post-quantum ledger commitment"
    return pub, msg, sphincs.sign(priv, msg)


def test_roundtrip_and_tampering(signed):
    pub, msg, sig = signed
    assert len(sig) == sphincs.SIG_LEN
    assert sphincs.verify(pub, msg, sig)
    assert not sphincs.verify(pub, msg + b"!", sig)
    # corrupt one byte in each structural region: R, HORST, WOTS, auth path
    for off in (0, 40, sphincs.SIG_LEN - 40, sphincs.SIG_LEN // 2):
        bad = bytearray(sig)
        bad[off] ^= 1
        assert not sphincs.verify(pub, msg, bytes(bad)), f"offset {off}"
    assert not sphincs.verify(pub, msg, sig[:-1])        # truncated
    other_pub, _ = sphincs.keygen(b"\x2b" * 32)
    assert not sphincs.verify(other_pub, msg, sig)       # wrong key


def test_keygen_deterministic():
    assert sphincs.keygen(b"\x07" * 32) == sphincs.keygen(b"\x07" * 32)
    assert sphincs.keygen(b"\x07" * 32) != sphincs.keygen(b"\x08" * 32)


def test_crypto_facade_dispatch():
    kp = generate_keypair(SPHINCS256_SHA256, entropy=b"\x11" * 32)
    content = b"scheme dispatch through the Crypto facade"
    sig = Crypto.sign_with_key(kp, content)
    assert sig.verify(content)
    assert sig.is_valid(content)
    assert not sig.is_valid(content + b"x")
    assert kp.public.scheme is SPHINCS256_SHA256
