"""Focused tests for round-5 behaviors without direct coverage elsewhere:
the batcher's dispatch-on-crossover early flush, the consolidated-wire
g_w guard, and the Ed25519 split kernel's per-signer cache cold path.
"""
import time

import numpy as np
import pytest

from corda_tpu.core.crypto import ecmath


def _k1_triples(n):
    from corda_tpu.core.crypto.keys import generate_keypair
    from corda_tpu.core.crypto.schemes import ECDSA_SECP256K1_SHA256
    from corda_tpu.core.crypto.signatures import Crypto
    kp = generate_keypair(ECDSA_SECP256K1_SHA256, entropy=b"\x29" * 32)
    msgs = [bytes([i]) * 24 for i in range(n)]
    return [(kp.public, Crypto.sign_with_key(kp, m).bytes, m) for m in msgs]


def test_batcher_early_flush_on_stalled_queue():
    """An atomic burst above the host crossover must dispatch well before
    the full linger window expires (dispatch-on-crossover, VERDICT r4
    ask #7): with a 2s window, a stalled queue should still resolve in a
    fraction of it."""
    from corda_tpu.verifier.batcher import SignatureBatcher
    triples = _k1_triples(8)
    b = SignatureBatcher(max_latency_s=2.0, host_crossover=4,
                         use_device=False)
    try:
        # warm one round so dispatcher thread startup is out of the timing
        assert all(b.submit_group(triples).result(timeout=30))
        t0 = time.perf_counter()
        assert all(b.submit_group(triples).result(timeout=30))
        elapsed = time.perf_counter() - t0
    finally:
        b.close()
    # full linger would be >= 2s; the early flush fires after one stalled
    # tick (0.4s) plus host verification of 8 sigs (~10ms)
    assert elapsed < 1.5, f"burst waited the full linger window: {elapsed}"


def test_hybrid_prep_rejects_wide_windows():
    """The consolidated wire form packs rn_ok at g_idx bit 18; window
    widths whose indices would reach that bit must be rejected loudly,
    never silently corrupted."""
    from corda_tpu.ops import weierstrass as wc
    with pytest.raises(ValueError, match="packed-index budget"):
        wc.prepare_batch_hybrid_wide([], 10)


def test_ed_signer_row_cache_cold_and_warm():
    """_signer_row builds the (−A, −A') limb rows once per signer (the
    [2^128]A chain); a second batch with the same signers must hit the
    cache, and invalid keys return None and fall to the substitute row."""
    from corda_tpu.ops import ed25519 as ed
    rng = np.random.default_rng(11)
    seed = rng.bytes(32)
    pub = ecmath.ed25519_public_key(seed)
    row1 = ed._signer_row(bytes(pub))
    assert row1 is not None and row1.shape == (6, 16)
    assert ed._signer_row(bytes(pub)) is row1          # cached object
    # row contents: (−A, −A') with A' = [2^128]A, all canonical limbs
    A = ecmath.ed_point_decompress(pub)
    P = ecmath.ED_P
    from corda_tpu.ops import field as F
    nx = (P - A[0]) % P
    np.testing.assert_array_equal(row1[0],
                                  F.to_limbs(nx).astype(np.uint16))
    # non-canonical y (>= p): decompression fails, row is None, and
    # prepare_batch_split substitutes + masks instead of raising
    bad = b"\xff" * 31 + b"\x7f"
    assert ed._signer_row(bad) is None
    got = ed.prepare_batch_split([(bad, b"\x00" * 64, b"m")])
    assert got[-1].shape == (1,) and not got[-1][0]


def test_split_prep_consolidated_shapes():
    """The 4-array wire form carries exactly what the kernel unpacks."""
    from corda_tpu.ops import ed25519 as ed
    rng = np.random.default_rng(12)
    items = []
    for _ in range(3):
        seed = rng.bytes(32)
        msg = rng.bytes(16)
        items.append((ecmath.ed25519_public_key(seed),
                      ecmath.ed25519_sign(seed, msg), msg))
    bb_idx, a_digits, rows, r_packed, *tabs, pre = ed.prepare_batch_split(
        items)
    assert bb_idx.shape == (16, 3) and a_digits.shape == (8, 8, 3)
    assert rows.shape == (3, 6, 16) and r_packed.shape == (3, 16)
    assert len(tabs) == 6 and pre.all()
    # sign bit rides limb 15 bit 15 of r_packed
    signs = np.asarray(r_packed)[:, 15] >> 15
    want = [sig[31] >> 7 for _, sig, _ in items]
    np.testing.assert_array_equal(signs, want)
