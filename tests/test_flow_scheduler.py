"""FlowScheduler + AwaitFuture/VerifyMany suspension points (ISSUE 11).

The group-commit pipeline hangs off three framework pieces: a generic
park-on-a-future yield (AwaitFuture — the notary-wait suspension), a
wave verify yield (VerifyMany — one park for N verifier futures), and a
bounded-concurrency flow launcher (FlowScheduler). These tests pin their
contracts directly, with controllable futures instead of live services.
"""
import threading
from concurrent.futures import Future

import pytest

from corda_tpu.flows import FlowException, FlowLogic
from corda_tpu.flows.api import AwaitFuture, VerifyMany
from corda_tpu.flows.library import _topological_order, _topological_waves
from corda_tpu.node.statemachine import FlowScheduler
from corda_tpu.testing import MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    a = network.create_node("O=Alice, L=London, C=GB")
    network.start_nodes()
    return network, a


class AwaitFlow(FlowLogic):
    def __init__(self, producer):
        self.producer = producer

    def call(self):
        value = yield AwaitFuture(self.producer)
        return value


class CatchingAwaitFlow(FlowLogic):
    """The error must be thrown INTO the flow with its type preserved."""

    def __init__(self, producer):
        self.producer = producer

    def call(self):
        try:
            yield AwaitFuture(self.producer)
        except ValueError as e:
            return f"caught:{e}"
        return "no-error"


def test_await_future_none_producer_resumes_immediately(net):
    network, a = net
    fsm = a.start_flow(AwaitFlow(lambda: None))
    network.run_network()
    assert fsm.result_future.result(timeout=1) is None


def test_await_future_done_fast_path(net):
    network, a = net
    fut = Future()
    fut.set_result("ready")
    fsm = a.start_flow(AwaitFlow(lambda: fut))
    network.run_network()
    assert fsm.result_future.result(timeout=1) == "ready"


def test_await_future_parks_until_foreign_thread_resolves(net):
    network, a = net
    fut = Future()
    fsm = a.start_flow(AwaitFlow(lambda: fut))
    assert not fsm.done and a.smm.awaiting_external == 1
    threading.Timer(0.05, lambda: fut.set_result(42)).start()
    network.run_network()
    assert fsm.result_future.result(timeout=1) == 42
    assert a.smm.awaiting_external == 0


def test_await_future_error_type_preserved(net):
    network, a = net
    fut = Future()
    fsm = a.start_flow(CatchingAwaitFlow(lambda: fut))
    threading.Timer(0.05, lambda: fut.set_exception(ValueError("nope"))).start()
    network.run_network()
    assert fsm.result_future.result(timeout=1) == "caught:nope"


# ---------------------------------------------------------------------------
# VerifyMany
# ---------------------------------------------------------------------------

class FakeVerifier:
    """Async verifier double: hands back controllable futures per submit."""

    def __init__(self):
        self.submitted: list = []   # (stx, future)

    def verify_signed(self, stx, hub, check_sufficient_signatures=True):
        fut = Future()
        self.submitted.append((stx, fut))
        return fut


class WaveFlow(FlowLogic):
    def __init__(self, stxs):
        self.stxs = stxs

    def call(self):
        try:
            yield VerifyMany(tuple(self.stxs),
                             check_sufficient_signatures=False)
        except Exception as e:
            return f"failed:{type(e).__name__}:{e}"
        return "verified"


def test_verify_many_empty_wave_is_immediate(net):
    network, a = net
    fsm = a.start_flow(WaveFlow([]))
    network.run_network()
    assert fsm.result_future.result(timeout=1) == "verified"


def test_verify_many_submits_whole_wave_and_parks_once(net):
    network, a = net
    fake = FakeVerifier()
    a.services.verifier_service = fake
    fsm = a.start_flow(WaveFlow(["stx0", "stx1", "stx2"]))
    # the whole wave hits the verifier concurrently — no serialization
    assert [s for s, _ in fake.submitted] == ["stx0", "stx1", "stx2"]
    # ONE external-wait slot for the wave, resumed by the last arrival
    assert a.smm.awaiting_external == 1
    fake.submitted[0][1].set_result(None)
    fake.submitted[2][1].set_result(None)
    a.smm.drain_external()
    assert not fsm.done and a.smm.awaiting_external == 1
    fake.submitted[1][1].set_result(None)
    network.run_network()
    assert fsm.result_future.result(timeout=1) == "verified"
    assert a.smm.awaiting_external == 0


def test_verify_many_throws_first_error_in_submission_order(net):
    network, a = net
    fake = FakeVerifier()
    a.services.verifier_service = fake
    fsm = a.start_flow(WaveFlow(["stx0", "stx1", "stx2"]))
    # the LAST submission fails first in wall time; the FIRST submission's
    # failure is what the yield site must see (deterministic across runs)
    fake.submitted[2][1].set_exception(IndexError("later"))
    fake.submitted[0][1].set_exception(ValueError("first"))
    fake.submitted[1][1].set_result(None)
    network.run_network()
    assert fsm.result_future.result(timeout=1) == "failed:ValueError:first"


# ---------------------------------------------------------------------------
# FlowScheduler
# ---------------------------------------------------------------------------

def _drain(node):
    """Drain the node's external queue to quiescence (run_network would
    block while other flows stay deliberately parked on pending futures)."""
    while node.smm.drain_external():
        pass


def test_scheduler_bounds_concurrency_and_backfills(net):
    network, a = net
    sched = FlowScheduler(a.smm, max_concurrent=2)
    futs = [Future() for _ in range(5)]
    proxies = [sched.submit(lambda f=f: AwaitFlow(lambda: f)) for f in futs]
    assert sched.in_flight == 2 and sched.waiting == 3

    futs[0].set_result("r0")
    _drain(a)   # completion launches the next waiter
    assert proxies[0].result(timeout=1) == "r0"
    assert sched.in_flight == 2 and sched.waiting == 2

    for i, fut in enumerate(futs[1:], start=1):
        fut.set_result(f"r{i}")
        _drain(a)
    assert [p.result(timeout=1) for p in proxies] == \
        ["r0", "r1", "r2", "r3", "r4"]
    assert sched.in_flight == 0 and sched.waiting == 0
    assert sched.launched == 5
    # the bound held: never more than max_concurrent in flight
    assert sched.high_water == 2


def test_scheduler_propagates_flow_failure_to_proxy(net):
    network, a = net
    sched = FlowScheduler(a.smm, max_concurrent=2)
    fut = Future()
    proxy = sched.submit(lambda: AwaitFlow(lambda: fut))
    fut.set_exception(FlowException("flow blew up"))
    _drain(a)
    with pytest.raises(FlowException, match="blew up"):
        proxy.result(timeout=1)
    assert sched.in_flight == 0


def test_scheduler_factory_failure_does_not_leak_a_slot(net):
    network, a = net
    sched = FlowScheduler(a.smm, max_concurrent=1)

    def bad_factory():
        raise RuntimeError("cannot build")

    proxy = sched.submit(bad_factory)
    with pytest.raises(RuntimeError, match="cannot build"):
        proxy.result(timeout=1)
    # the slot was released; a follow-up flow still runs
    ok = sched.submit(lambda: AwaitFlow(lambda: None))
    _drain(a)
    assert ok.result(timeout=1) is None


# ---------------------------------------------------------------------------
# Wave-based dependency resolution
# ---------------------------------------------------------------------------

class _FakeStx:
    def __init__(self, tx_id, deps=()):
        self.id = tx_id
        self.inputs = [type("Ref", (), {"txhash": d})() for d in deps]


def test_topological_waves_diamond():
    txs = {s.id: s for s in [
        _FakeStx("a"),
        _FakeStx("b", deps=["a"]),
        _FakeStx("c", deps=["a"]),
        _FakeStx("d", deps=["b", "c"]),
    ]}
    waves = _topological_waves(txs)
    assert [sorted(s.id for s in w) for w in waves] == \
        [["a"], ["b", "c"], ["d"]]
    order = [s.id for s in _topological_order(txs)]
    assert order.index("a") < order.index("b") < order.index("d")
    assert order.index("a") < order.index("c") < order.index("d")


def test_topological_waves_external_deps_are_wave_zero():
    # inputs whose producers are NOT in the fetched set (already in the
    # vault) must not block the wave cut
    txs = {s.id: s for s in [_FakeStx("x", deps=["already-recorded"])]}
    assert [[s.id for s in w] for w in _topological_waves(txs)] == [["x"]]


def test_topological_waves_cycle_raises():
    txs = {s.id: s for s in [
        _FakeStx("a", deps=["b"]),
        _FakeStx("b", deps=["a"]),
    ]}
    with pytest.raises(FlowException, match="cycle"):
        _topological_waves(txs)
