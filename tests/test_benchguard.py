"""Bench regression gate (tools/benchguard.py): floors fit from the
trajectory must fail a synthetic regression, pass the repo's real
BENCH_r*.json history, and degrade to a schema check on smoke artifacts.
"""
import json

import pytest

from corda_tpu.tools import benchguard


def _artifact(**over):
    """A minimal full-run artifact satisfying the required-field schema."""
    base = {
        "metric": "ecdsa_secp256k1_verifies_per_sec_per_chip",
        "value": 100.0, "unit": "verifies/s", "vs_baseline": 10.0,
        "ed25519_verifies_per_sec_per_chip": 1000.0,
        "secp256r1_verifies_per_sec_per_chip": 50.0,
        "service_path_verifies_per_sec": 200.0,
        "ed25519_service_path_verifies_per_sec": 400.0,
        "secp256r1_service_path_verifies_per_sec": 80.0,
        "mixed_service_path_verifies_per_sec": 150.0,
        "tx_verify_p50_ms_batch1": 1.0,
        "tx_verify_p50_ms_batch1k": 20.0,
        "tx_verify_p90_ms_batch1k": 30.0,
        "tx_verify_p99_ms_batch1k": 45.0,
        "service_to_kernel_ratio_k1": 0.8,
        "service_to_kernel_ratio_ed25519": 0.7,
        "service_to_kernel_ratio_r1": 0.75,
        "post_warmup_compiles": 0,
        "bucket_ladder": [256, 512, 1024],
        "compile_s_total": 5.0, "compile_cache_hits": 7,
        "occupancy_pct_per_scheme": {"ed25519": 90.0},
        "prep_overlap_pct": 40.0,
    }
    base.update(over)
    return base


def test_synthetic_regressing_trajectory_fails():
    trajectory = [_artifact(), _artifact(value=120.0)]
    guards = benchguard.fit_guards(trajectory)
    # best=120, floor=120*0.85=102 — a drop to 90 must trip the gate
    regressed = _artifact(value=90.0)
    problems = benchguard.check(regressed, guards)
    assert problems, "regression not caught"
    assert any("value: 90" in p and "floor" in p for p in problems)


def test_latency_regression_fails_against_ceiling():
    guards = benchguard.fit_guards([_artifact(tx_verify_p50_ms_batch1=1.0)])
    slow = _artifact(tx_verify_p50_ms_batch1=1.5)   # ceiling = 1.35
    problems = benchguard.check(slow, guards)
    assert any("tx_verify_p50_ms_batch1" in p and "ceiling" in p
               for p in problems)


def test_within_tolerance_passes():
    guards = benchguard.fit_guards([_artifact(value=100.0)])
    assert benchguard.check(_artifact(value=90.0), guards) == []


def test_smoke_artifact_gets_schema_check_only():
    guards = benchguard.fit_guards([_artifact(value=1000.0)])
    # values way below the floors, but smoke => schema-only
    smoke = _artifact(value=0.0, smoke=True)
    assert benchguard.check(smoke, guards) == []
    # ... and the schema check still bites on a missing field
    broken = dict(smoke)
    del broken["prep_overlap_pct"]
    problems = benchguard.check(broken, guards)
    assert any("prep_overlap_pct" in p for p in problems)


def test_schema_rejects_wrong_shapes():
    bad = _artifact(occupancy_pct_per_scheme=[1, 2],
                    compile_s_total="fast")
    problems = benchguard.schema_violations(bad)
    assert any("occupancy_pct_per_scheme" in p and "dict" in p
               for p in problems)
    assert any("compile_s_total" in p for p in problems)


def test_smoke_and_zero_rounds_do_not_drag_floors():
    trajectory = [
        _artifact(value=0.0, smoke=True),    # smoke round: skipped outright
        _artifact(value=0.0),                # dead metric: not a floor of 0
        _artifact(value=100.0),
    ]
    guards = benchguard.fit_guards(trajectory)
    assert guards["value"]["best"] == 100.0


def test_real_trajectory_passes_self_replay():
    """Every recorded round must clear the guards fit from the rounds
    before it — the tolerances are calibrated to the repo's real noise."""
    paths = benchguard.default_trajectory_paths()
    if not paths:
        pytest.skip("no BENCH_r*.json artifacts in this checkout")
    trajectory = benchguard.load_trajectory(paths)
    for i, run in enumerate(trajectory):
        guards = benchguard.fit_guards(trajectory[:i])
        value_problems = [p for p in benchguard.check(run, guards)
                          if "<" in p or ">" in p]
        assert value_problems == [], f"round {paths[i]}: {value_problems}"


def test_cli_replays_trajectory(capsys):
    if not benchguard.default_trajectory_paths():
        pytest.skip("no BENCH_r*.json artifacts in this checkout")
    assert benchguard.main([]) == 0
    assert "ok" in capsys.readouterr().out


def test_guard_current_with_explicit_paths(tmp_path):
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps({"parsed": _artifact(value=200.0)}))
    problems = benchguard.guard_current(_artifact(value=100.0), [str(p)])
    assert any("value: 100" in x for x in problems)
    assert benchguard.guard_current(_artifact(value=190.0), [str(p)]) == []


# ---------------------------------------------------------------------------
# MULTICHIP (fleet) gate
# ---------------------------------------------------------------------------

def _fleet(**over):
    base = {
        "fleet_verifies_per_sec": 50000.0,
        "scaling_efficiency_pct": 92.0,
        "n_workers": 8, "n_devices": 8,
        "fleet_steals": 3, "fleet_stolen": 12,
        "worker_busy_skew_pct": 4.0, "steals_total": 3,
        "stitched_trace_depth": 4,
        "recovery_s": 0.0, "controller_actions": 0,
        "per_worker_sigs": {"w0": 4096, "w1": 4096},
    }
    base.update(over)
    return base


def test_multichip_tail_parsed_from_last_json_line():
    """The fleet stage prints its JSON LAST; earlier stdout lines (even
    JSON-looking ones without the fleet fields) must not win."""
    tail = ('some dry-run chatter\n{"not": "the fleet line"}\n'
            + json.dumps(_fleet(fleet_verifies_per_sec=1234.5)) + "\n")
    parsed = benchguard.parse_multichip_artifact(
        {"n_devices": 8, "rc": 0, "ok": True, "tail": tail})
    assert parsed is not None
    assert parsed["fleet_verifies_per_sec"] == 1234.5


def test_multichip_empty_tail_is_pre_fleet():
    assert benchguard.parse_multichip_artifact(
        {"n_devices": 8, "rc": 0, "ok": True, "tail": ""}) is None


def test_multichip_regression_fails_against_trajectory(tmp_path):
    p = tmp_path / "MULTICHIP_r06.json"
    p.write_text(json.dumps(
        {"n_devices": 8, "rc": 0, "ok": True,
         "tail": json.dumps(_fleet()) + "\n"}))
    # floors: 50000*0.85=42500 and 92*0.85=78.2
    bad_rate = benchguard.guard_multichip(
        _fleet(fleet_verifies_per_sec=40000.0), [str(p)])
    assert any("fleet_verifies_per_sec" in x and "floor" in x
               for x in bad_rate)
    bad_eff = benchguard.guard_multichip(
        _fleet(scaling_efficiency_pct=70.0), [str(p)])
    assert any("scaling_efficiency_pct" in x for x in bad_eff)
    assert benchguard.guard_multichip(_fleet(), [str(p)]) == []


def test_multichip_smoke_schema_only():
    smoke = _fleet(fleet_verifies_per_sec=3.0, smoke=True)
    assert benchguard.guard_multichip(smoke, []) == []
    broken = dict(smoke)
    del broken["scaling_efficiency_pct"]
    problems = benchguard.guard_multichip(broken, [])
    assert any("scaling_efficiency_pct" in p for p in problems)


def test_multichip_real_trajectory_accepts_historical_artifacts():
    """Pre-fleet rounds have empty tails: they contribute nothing to the
    guards and must not crash the fit."""
    paths = benchguard.multichip_trajectory_paths()
    if not paths:
        pytest.skip("no MULTICHIP_r*.json artifacts in this checkout")
    assert benchguard.guard_multichip(_fleet(), paths) == []


# ---------------------------------------------------------------------------
# LEDGER (end-to-end ledger scenario) gate


def _ledger(**over):
    base = {
        "metric": "committed_tx_per_sec", "value": 10.0, "unit": "tx/s",
        "committed_tx_per_sec": 10.0, "offered_tx_per_sec": 40.0,
        "parties": 24, "raft_replicas": 3,
        "ops_total": 240, "ops_committed": 230, "ops_failed": 10,
        "notarised_tx_count": 158, "duration_s": 24.5,
        "e2e_ms_p50": 8300.0, "e2e_ms_p90": 15000.0, "e2e_ms_p99": 18000.0,
        "ledger_stage_flow_run_ms_p99": 500.0,
        "ledger_stage_tx_verify_ms_p99": 20.0,
        "ledger_stage_notary_uniqueness_ms_p99": 100.0,
        "ledger_stage_raft_commit_ms_p99": 90.0,
        "ledger_stage_vault_update_ms_p99": 5.0,
        "notary_uniqueness_p99_ms": 100.0,
        "slo_error_budget_pct": 0.0,
        "chaos_enabled": True, "chaos_windows": [],
        "exactly_once_ok": True, "replicas_agree": True,
        "stitched_traces": 183,
        # group-commit pipeline fields (ISSUE 11)
        "committed_tx_count": 810, "self_issue_tx_count": 144,
        "notarised_input_tx_count": 522, "counter_invariant_ok": True,
        "node_concurrency": 4, "max_concurrent_flows_per_node": 4,
        "flows_launched": 810,
        "commit_batch_occupancy_mean": 4.76,
        "commit_batch_occupancy_p99": 22.0,
        "ledger_commit_batch_count": 140, "group_commit_raft_appends": 140,
        "group_commit_committed": 666, "group_commit_rejected": 0,
        "group_commit_prescreened": 0, "group_commit_deferred": 0,
        "raft_appends_per_committed_tx": 0.21,
        "e2e_ms_p50_issue": 100.0, "e2e_ms_p90_issue": 200.0,
        "e2e_ms_p99_issue": 300.0,
        "e2e_ms_p50_pay": 400.0, "e2e_ms_p90_pay": 800.0,
        "e2e_ms_p99_pay": 1200.0,
        "e2e_ms_p50_settle": 500.0, "e2e_ms_p90_settle": 1000.0,
        "e2e_ms_p99_settle": 1500.0,
        "flow_ms_p50_issue": 50.0, "flow_ms_p90_issue": 90.0,
        "flow_ms_p99_issue": 120.0,
        "flow_ms_p50_pay": 200.0, "flow_ms_p90_pay": 400.0,
        "flow_ms_p99_pay": 600.0,
        "flow_ms_p50_settle": 250.0, "flow_ms_p90_settle": 500.0,
        "flow_ms_p99_settle": 700.0,
        # tail-forensics critical-path fields (ISSUE 14): each p50 blame
        # vector sums exactly to its class's critpath e2e (conservation)
        "ledger_critpath_traces": 183,
        "ledger_critpath_top": [],
        "ledger_critpath_blame_p50_issue": {"flow.compute": 60.0,
                                            "raft.commit": 40.0},
        "ledger_critpath_blame_p99_issue": {"raft.commit": 300.0},
        "ledger_critpath_e2e_p50_ms_issue": 100.0,
        "ledger_critpath_dominant_issue": "flow.compute",
        "ledger_critpath_blame_p50_pay": {"scheduler.wait": 250.0,
                                          "notary.batch_wait": 150.0},
        "ledger_critpath_blame_p99_pay": {"scheduler.wait": 1200.0},
        "ledger_critpath_e2e_p50_ms_pay": 400.0,
        "ledger_critpath_dominant_pay": "scheduler.wait",
        "ledger_critpath_blame_p50_settle": {"notary.batch_wait": 500.0},
        "ledger_critpath_blame_p99_settle": {"notary.batch_wait": 1500.0},
        "ledger_critpath_e2e_p50_ms_settle": 500.0,
        "ledger_critpath_dominant_settle": "notary.batch_wait",
        # sharded-notary fields (ISSUE 15)
        "ledger_shard_count": 2,
        "ledger_shard_commit_counts": {"s0": 340, "s1": 326},
        "ledger_shard_cross_committed": 60,
        "ledger_shard_cross_aborted": 2,
        "ledger_shard_cross_recovered": 0,
        "ledger_shard_reserved_leftover": 0,
        "ledger_shard_recovered_in_doubt": 0,
        "ledger_shard_finalize_conflicts": 0,
        "cross_shard_abort_rate": 0.032,
        "cross_shard_pct": 0.15,
        # consensus-observatory fields (ISSUE 16): raft commit attribution
        # telescopes — append_wait+fsync+replicate+apply p50s sum to the
        # attribution-sum p50, which matches the measured round p50
        "ledger_raft_append_wait_ms_p50": 0.4,
        "ledger_raft_append_wait_ms_p99": 2.0,
        "ledger_raft_fsync_ms_p50": 1.1, "ledger_raft_fsync_ms_p99": 4.0,
        "ledger_raft_replicate_ms_p50": 6.0,
        "ledger_raft_replicate_ms_p99": 30.0,
        "ledger_raft_apply_ms_p50": 0.5, "ledger_raft_apply_ms_p99": 2.0,
        "ledger_raft_attrib_samples": 140,
        "ledger_raft_attrib_sum_ms_p50": 8.0,
        "ledger_raft_round_ms_p50": 8.3,
        "ledger_raft_elections_total": 2,
        "ledger_raft_pump_busy_frac": 0.12,
        "ledger_shard_skew_index": 1.05,
        "ledger_coordinator_log_bytes": 4096,
        "ledger_timeseries_resolutions": 3,
        "ledger_growth_warnings": 0,
        # bounded-state consensus fields (ISSUE 20): snapshot compaction,
        # InstallSnapshot catch-up, restart recovery, CoordinatorLog GC
        "ledger_raft_snapshot_index": 180,
        "ledger_raft_snapshots_taken": 4,
        "ledger_raft_installs_sent": 1,
        "ledger_raft_installs_received": 1,
        "ledger_raft_snapshot_bytes": 8192,
        "ledger_raft_snapshot_threshold": 192,
        "ledger_raft_log_entries_peak": 210,
        "ledger_raft_restarts": 1,
        "ledger_growth_compactions": 4,
        "ledger_coordinator_compactions": 1,
        "host_cpus": 8,
    }
    base.update(over)
    return base


def test_ledger_schema_locks_every_required_field():
    assert benchguard.ledger_schema_violations(_ledger()) == []
    for field in benchguard.LEDGER_REQUIRED:
        broken = _ledger()
        del broken[field]
        assert benchguard.ledger_schema_violations(broken), field


def test_ledger_schema_rejects_wrong_shapes():
    bad = _ledger(exactly_once_ok="yes", chaos_windows="none",
                  committed_tx_per_sec="fast")
    problems = benchguard.ledger_schema_violations(bad)
    assert len(problems) == 3


def test_ledger_regression_fails_against_trajectory(tmp_path):
    good = tmp_path / "LEDGER_r01.json"
    good.write_text(json.dumps(_ledger(committed_tx_per_sec=10.0)))
    # throughput collapse breaches the floor
    slow = _ledger(committed_tx_per_sec=10.0 * (1 - 0.16))
    problems = benchguard.guard_ledger(slow, [str(good)])
    assert any("committed_tx_per_sec" in p for p in problems)
    # uniqueness-tail blowup breaches the ceiling (tolerance 6.0 → 7x
    # best — one straddled re-election is a coin flip, not a regression;
    # see the LEDGER_GUARDED comment and the r04/r05/r06 rolls)
    tail = _ledger(notary_uniqueness_p99_ms=100.0 * 7.1)
    problems = benchguard.guard_ledger(tail, [str(good)])
    assert any("notary_uniqueness_p99_ms" in p for p in problems)
    # within tolerance passes
    assert benchguard.guard_ledger(
        _ledger(committed_tx_per_sec=9.0), [str(good)]) == []


def test_ledger_group_commit_guards(tmp_path):
    """The amortization locks: appends-per-tx sliding back toward 1.0
    (re-serialization) breaches its ceiling; an occupancy collapse
    breaches its floor; a per-class p99 blowup names its class."""
    good = tmp_path / "LEDGER_r01.json"
    good.write_text(json.dumps(_ledger()))
    problems = benchguard.guard_ledger(
        _ledger(raft_appends_per_committed_tx=0.21 * 1.6), [str(good)])
    assert any("raft_appends_per_committed_tx" in p for p in problems)
    problems = benchguard.guard_ledger(
        _ledger(commit_batch_occupancy_mean=4.76 * (1 - 0.16)), [str(good)])
    assert any("commit_batch_occupancy_mean" in p for p in problems)
    # class tails carry a metric-specific 2.0 tolerance (chaos-straddle
    # survivorship — see LEDGER_GUARDED): breach needs more than 3x best
    problems = benchguard.guard_ledger(
        _ledger(e2e_ms_p99_settle=1500.0 * 3.1), [str(good)])
    assert any("e2e_ms_p99_settle" in p for p in problems)
    assert benchguard.guard_ledger(
        _ledger(e2e_ms_p99_settle=1500.0 * 2.9), [str(good)]) == []
    # within tolerance passes clean
    assert benchguard.guard_ledger(
        _ledger(raft_appends_per_committed_tx=0.25,
                commit_batch_occupancy_mean=4.2), [str(good)]) == []


def test_ledger_smoke_gets_schema_check_only(tmp_path):
    fast = tmp_path / "LEDGER_r01.json"
    fast.write_text(json.dumps(_ledger(committed_tx_per_sec=1000.0)))
    smoke = _ledger(committed_tx_per_sec=0.5, smoke=True)
    assert benchguard.guard_ledger(smoke, [str(fast)]) == []


def test_ledger_floors_fit_within_host_class_only(tmp_path):
    """Floors recorded on a bigger box are not held against a smaller
    one: trajectory rounds with a different host_cpus contribute no
    floors, same-class rounds do, and rounds predating the field (both
    sides absent) keep guarding each other."""
    big = tmp_path / "LEDGER_r01.json"
    big.write_text(json.dumps(_ledger(committed_tx_per_sec=100.0,
                                      host_cpus=64)))
    # a 64-core round sets no floor for an 8-core run
    assert benchguard.guard_ledger(
        _ledger(committed_tx_per_sec=10.0), [str(big)]) == []
    # a same-class round still does
    peer = tmp_path / "LEDGER_r02.json"
    peer.write_text(json.dumps(_ledger(committed_tx_per_sec=20.0)))
    problems = benchguard.guard_ledger(
        _ledger(committed_tx_per_sec=10.0), [str(big), str(peer)])
    assert any("committed_tx_per_sec" in p for p in problems)
    # pre-field rounds (no host_cpus on either side) stay comparable
    legacy = _ledger(committed_tx_per_sec=20.0)
    legacy.pop("host_cpus")
    old = tmp_path / "LEDGER_r03.json"
    old.write_text(json.dumps(legacy))
    cur = _ledger(committed_tx_per_sec=10.0)
    cur.pop("host_cpus")
    problems = benchguard.guard_ledger(cur, [str(old)])
    assert any("committed_tx_per_sec" in p for p in problems)


def test_ledger_critpath_blame_conservation_probe(tmp_path):
    # the helper's vectors sum exactly to their e2e: clean
    assert benchguard.ledger_critpath_violations(_ledger()) == []
    # a vector that lost 20% of its e2e (dropped spans) is INVALID
    broken = _ledger(
        ledger_critpath_blame_p50_pay={"scheduler.wait": 320.0})
    problems = benchguard.ledger_critpath_violations(broken)
    assert len(problems) == 1 and "pay" in problems[0]
    # an empty class (never ran in this round) is skipped, not a breach
    assert benchguard.ledger_critpath_violations(
        _ledger(ledger_critpath_blame_p50_settle={},
                ledger_critpath_e2e_p50_ms_settle=0.0)) == []
    # non-smoke guard_ledger enforces it; smoke stays schema-only
    good = tmp_path / "LEDGER_r01.json"
    good.write_text(json.dumps(_ledger()))
    problems = benchguard.guard_ledger(broken, [str(good)])
    assert any("lost spans" in p for p in problems)
    assert benchguard.guard_ledger(dict(broken, smoke=True),
                                   [str(good)]) == []


def test_ledger_real_artifact_passes_self_replay():
    paths = benchguard.ledger_trajectory_paths()
    if not paths:
        pytest.skip("no LEDGER_r*.json artifacts in this checkout")
    with open(sorted(paths)[-1], encoding="utf-8") as f:
        latest = json.load(f)
    assert benchguard.guard_ledger(latest, paths) == []


# ---------------------------------------------------------------------------
# SHARD-SCALING gate


def _sweep_point(shards, rate, **over):
    base = {
        "shards": shards, "committed_tx_per_sec": rate,
        "exactly_once_ok": True, "replicas_agree": True,
        "reserved_leftover": 0,
        "cross_shard_committed": 0 if shards == 1 else 12,
        "cross_shard_aborted": 0 if shards == 1 else 1,
    }
    base.update(over)
    return base


def _sharded(**over):
    points = [_sweep_point(1, 700.0), _sweep_point(2, 1300.0),
              _sweep_point(4, 2300.0)]
    base = _ledger(
        shard_sweep=points,
        committed_tx_per_sec_shards_1=700.0,
        committed_tx_per_sec_shards_2=1300.0,
        committed_tx_per_sec_shards_4=2300.0,
        shard_scaling_x=2300.0 / 700.0,
        shard_scaling_efficiency_pct=100.0 * (2300.0 / 700.0) / 4,
        shard_sweep_abort_rate=0.032,
        shard_sweep_skew_index=1.05,
        shard_sweep_ok=True)
    base.update(over)
    return base


def test_shard_guard_schema_and_hard_invariants():
    assert benchguard.guard_shards(_sharded(), []) == []
    # every required scaling field is locked in
    for field in benchguard.SHARD_REQUIRED:
        broken = _sharded()
        del broken[field]
        assert benchguard.guard_shards(broken, []), field
    # safety invariants are HARD — smoke does not excuse them
    bad = _sharded(smoke=True)
    bad["shard_sweep"] = [_sweep_point(1, 700.0),
                          _sweep_point(2, 1300.0, exactly_once_ok=False)]
    assert any("exactly_once_ok" in p
               for p in benchguard.guard_shards(bad, []))
    leak = _sharded(smoke=True)
    leak["shard_sweep"][2]["reserved_leftover"] = 3
    assert any("reserved_leftover" in p
               for p in benchguard.guard_shards(leak, []))
    # a multi-shard sweep that never committed cross-shard is a breach
    no_cross = _sharded(smoke=True)
    for p in no_cross["shard_sweep"]:
        p["cross_shard_committed"] = 0
    assert any("cross-shard" in p
               for p in benchguard.guard_shards(no_cross, []))


def test_shard_guard_locks_scaling_floors(tmp_path):
    good = tmp_path / "LEDGER_r04.json"
    good.write_text(json.dumps(_sharded()))
    # scaling efficiency collapse breaches its floor (the whole curve
    # uses SWEEP_RATE_TOLERANCE=0.45 — see benchguard)
    worse = _sharded(shard_scaling_efficiency_pct=
                     100.0 * (2300.0 / 700.0) / 4 * (1 - 0.46))
    assert any("shard_scaling_efficiency_pct" in p
               for p in benchguard.guard_shards(worse, [str(good)]))
    # a per-shard-count committed-rate collapse names its count (the
    # sweep rates use SWEEP_RATE_TOLERANCE=0.45 — the measured 4-shard
    # noise band spans 544.9–361.6 tx/s across r04–r06; see benchguard)
    slow4 = _sharded(committed_tx_per_sec_shards_4=2300.0 * (1 - 0.46))
    assert any("committed_tx_per_sec_shards_4" in p
               for p in benchguard.guard_shards(slow4, [str(good)]))
    assert benchguard.guard_shards(
        _sharded(committed_tx_per_sec_shards_4=2300.0 * (1 - 0.44)),
        [str(good)]) == []
    # sweep abort-rate blowup breaches the ceiling (tail tolerance 0.5);
    # the guarded field is the SWEEP aggregate, not the flows scenario's
    # cross_shard_abort_rate (a different workload sharing the artifact)
    aborts = _sharded(shard_sweep_abort_rate=0.032 * 1.6)
    assert any("shard_sweep_abort_rate" in p
               for p in benchguard.guard_shards(aborts, [str(good)]))
    # within tolerance passes; smoke gets invariants only, no floors
    assert benchguard.guard_shards(
        _sharded(committed_tx_per_sec_shards_4=2100.0,
                 shard_scaling_x=3.0), [str(good)]) == []
    assert benchguard.guard_shards(
        _sharded(smoke=True, committed_tx_per_sec_shards_4=10.0),
        [str(good)]) == []
