"""benchtrend: trajectory tables over the *_r0N.json artifacts."""
import json

from corda_tpu.tools.benchtrend import (FAMILIES, load_rounds, render_table,
                                        trend_rows)


def test_trend_rows_delta_tracks_headline_metric():
    rounds = [
        ("r01", {"value": 100.0, "vs_baseline": 1.0}),
        ("r02", {"value": 150.0, "vs_baseline": 1.5}),
        ("r03", {"value": 120.0, "vs_baseline": 1.2}),
    ]
    rows = trend_rows(rounds, ("value", "vs_baseline"))
    assert rows[0]["delta_pct"] is None
    assert round(rows[1]["delta_pct"]) == 50
    assert round(rows[2]["delta_pct"]) == -20


def test_trend_rows_skips_missing_headline_for_delta():
    rounds = [
        ("r01", {"value": 100.0}),
        ("r02", {}),                       # skipped round: no headline
        ("r03", {"value": 110.0}),
    ]
    rows = trend_rows(rounds, ("value",))
    assert rows[1]["delta_pct"] is None
    assert round(rows[2]["delta_pct"]) == 10  # vs r01, not the gap


def test_render_table_formats_bools_and_missing():
    rounds = [("r01", {"committed_tx_per_sec": 10.16,
                       "exactly_once_ok": True, "smoke": True})]
    out = render_table("ledger", rounds,
                       ("committed_tx_per_sec", "exactly_once_ok",
                        "nonexistent"))
    assert "r01 (smoke)" in out
    assert "10.16" in out and "yes" in out
    line = [l for l in out.splitlines() if l.startswith("r01")][0]
    assert line.rstrip().endswith("-")     # missing metric renders as -


def test_render_table_empty():
    assert "(no artifacts)" in render_table("bench", [], ("value",))


def test_ledger_family_carries_critpath_columns():
    metrics = FAMILIES["ledger"][1]
    for kind in ("issue", "pay", "settle"):
        assert f"ledger_critpath_dominant_{kind}" in metrics
    # a pre-critpath round renders "-" in the new columns, a new round
    # shows the dominant blame component — side by side in one table
    rounds = [
        ("r02", {"committed_tx_per_sec": 19.2}),
        ("r03", {"committed_tx_per_sec": 21.0,
                 "ledger_critpath_dominant_issue": "flow.compute",
                 "ledger_critpath_dominant_pay": "scheduler.wait",
                 "ledger_critpath_dominant_settle": "notary.batch_wait"}),
    ]
    out = render_table("ledger", rounds, metrics)
    old = next(l for l in out.splitlines() if l.startswith("r02"))
    new = next(l for l in out.splitlines() if l.startswith("r03"))
    assert "-" in old.split() and "scheduler.wait" in new


def test_soak_family_tolerates_pre_soak_artifacts(tmp_path):
    metrics = FAMILIES["soak"][1]
    assert metrics[0] == "committed_tx_per_sec"
    for col in ("soak_leak_ok", "soak_drift_ok",
                "soak_cpu_top_commit_path", "soak_chaos_cycles"):
        assert col in metrics
    # a pre-soak LEDGER-shaped artifact mixed into the table renders "-"
    # in every soak column; a soak round fills them — side by side
    rounds = [
        ("r01", {"committed_tx_per_sec": 5.1}),
        ("r02", {"committed_tx_per_sec": 5.3, "soak_minutes": 10.0,
                 "soak_throughput_slope_pct_per_min": -0.4,
                 "soak_p99_slope_pct_per_min": 1.2, "soak_drift_ok": True,
                 "soak_leak_ok": True, "soak_invariant_ok": True,
                 "soak_cpu_top_commit_path": "batcher_prep",
                 "soak_cpu_share_sum_pct": 100.0, "soak_chaos_cycles": 7}),
    ]
    out = render_table("soak", rounds, metrics)
    old = next(l for l in out.splitlines() if l.startswith("r01"))
    new = next(l for l in out.splitlines() if l.startswith("r02"))
    assert old.split().count("-") >= 9
    assert "batcher_prep" in new and "yes" in new
    # the soak glob finds SOAK_r*.json artifacts only
    (tmp_path / "SOAK_r01.json").write_text(json.dumps(
        {"committed_tx_per_sec": 5.0}))
    (tmp_path / "LEDGER_r01.json").write_text(json.dumps({}))
    loaded = load_rounds("soak", root=str(tmp_path))
    assert [r[0] for r in loaded] == ["r01"]
    assert loaded[0][1]["committed_tx_per_sec"] == 5.0


def test_load_rounds_orders_and_unwraps(tmp_path):
    # BENCH artifacts wrap the metrics in "parsed"; LEDGER ones are flat
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"rc": 0, "parsed": {"value": 2.0}}))
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"rc": 0, "parsed": {"value": 1.0}}))
    (tmp_path / "BENCH_r03.json").write_text("not json {")
    rounds = load_rounds("bench", root=str(tmp_path))
    assert [r[0] for r in rounds] == ["r01", "r02"]   # corrupt one skipped
    assert rounds[0][1] == {"value": 1.0}


def test_every_family_has_glob_and_headline():
    for fam, (glob_fn, metrics) in FAMILIES.items():
        assert callable(glob_fn) and metrics, fam


def test_cli_runs_over_real_repo_artifacts(capsys):
    from corda_tpu.tools.benchtrend import main
    assert main(["--family", "ledger"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("ledger")
