"""Bounded-state consensus under chaos (ISSUE 20): log compaction,
InstallSnapshot catch-up, crash-restart recovery, and CoordinatorLog GC.

Four seeded properties:

* a crash DURING snapshot persistence (the ``raft.snapshot.persist``
  fault freezes the torn state: snapshot written, covered prefix NOT
  deleted) leaves a store every restart can load;
* a follower partitioned through a compaction, healing into a 30%
  append-drop storm, catches up via InstallSnapshot and agrees;
* a replica crash-restarted mid-load resumes from snapshot + log suffix
  (not genesis) and converges with exactly-once intact;
* CoordinatorLog GC preserves the in-doubt set — ``recover_in_doubt``
  sees the identical 2PC entries before the compaction, after it, and
  after a replay of the compacted file.
"""
import random

import pytest

from corda_tpu.consensus.raft import LEADER, RaftNode
from corda_tpu.consensus.raft_store import RaftLogStore
from corda_tpu.consensus.raft_uniqueness import DistributedImmutableMap
from corda_tpu.consensus.sharded_uniqueness import CoordinatorLog
from corda_tpu.core.contracts.structures import StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.testing.faults import FaultRule, inject

pytestmark = pytest.mark.chaos

SEEDS = [7, 101, 9001]

SNAPSHOT_EVERY = 4


def make_compacting_cluster(tmp_path, seed, n=3,
                            snapshot_entries=SNAPSHOT_EVERY):
    """Durable, compacting cluster: every replica snapshots its
    DistributedImmutableMap each ``snapshot_entries`` applied entries."""
    bus = InMemoryMessagingNetwork()
    names = [f"raft{i}" for i in range(n)]
    maps = [DistributedImmutableMap() for _ in range(n)]
    nodes = [RaftNode(name, list(names), bus.create_node(name),
                      maps[i].apply, seed=seed + i,
                      storage=RaftLogStore(str(tmp_path / f"{name}.kv")),
                      snapshot_fn=maps[i].snapshot,
                      restore_fn=maps[i].restore,
                      snapshot_entries=snapshot_entries)
             for i, name in enumerate(names)]
    return bus, names, nodes, maps


def pump(bus, nodes, ticks=10):
    for _ in range(ticks):
        for node in nodes:
            node.tick()
        bus.run_network()


def run_until_leader(bus, nodes, max_ticks=400):
    for _ in range(max_ticks):
        pump(bus, nodes, 1)
        leaders = [n for n in nodes if n.role == LEADER]
        if len(leaders) == 1:
            pump(bus, nodes, 5)
            final = [n for n in nodes if n.role == LEADER]
            if len(final) == 1:
                return final[0]
    raise AssertionError("no leader elected")


def ref_of(tag: str) -> StateRef:
    return StateRef(SecureHash.sha256(tag.encode()), 0)


def tx_of(tag: str):
    return SecureHash.sha256(b"tx:" + tag.encode())


def commit_spend(leader, bus, nodes, tag, timeout_ticks=200):
    """put_all one fresh ref through the cluster; assert it committed."""
    fut = leader.submit(("put_all",
                         [tx_of(tag), [ref_of(tag)], "chaos-snapshot"]))
    for _ in range(timeout_ticks):
        if fut.done():
            break
        pump(bus, nodes, 1)
    assert fut.done(), f"spend {tag} never committed"
    assert fut.result()["committed"], fut.result()
    return tag


def assert_exactly_once(maps, tags):
    """Every committed tag consumed by ITS tx on every replica, and the
    replicas' views are identical."""
    views = [{r: d.consuming_tx for r, d in m._map.items()} for m in maps]
    assert all(v == views[0] for v in views[1:]), "replicas diverged"
    for tag in tags:
        for m in maps:
            details = m._map.get(ref_of(tag))
            assert details is not None, f"{tag} lost"
            assert details.consuming_tx == tx_of(tag), f"{tag} stolen"


@pytest.mark.parametrize("seed", SEEDS)
def test_torn_snapshot_persist_store_stays_loadable(seed, tmp_path):
    """Every snapshot persist is torn (record written, prefix delete
    dropped) — a crash frozen at the worst instant. The store must load
    anyway: snapshot + redundant prefix, never corruption, and a replica
    rebuilt from it resumes from the snapshot, not genesis."""
    bus, names, nodes, maps = make_compacting_cluster(tmp_path, seed)
    leader = run_until_leader(bus, nodes)
    tags = []
    with inject(FaultRule("raft.snapshot.persist", "drop"), seed=seed) as inj:
        for k in range(3 * SNAPSHOT_EVERY):
            tags.append(commit_spend(leader, bus, nodes, f"torn-{seed}-{k}"))
        assert inj.fired("raft.snapshot.persist") >= 1
    assert leader.state.snapshot_index > 0   # compaction DID run in memory

    # crash one follower at the torn state and rebuild it from disk
    dead = next(n for n in nodes if n.role != LEADER)
    dead_name, dead_i = dead.node_id, nodes.index(dead)
    dead.stop()
    dead.storage.close()
    store = RaftLogStore(str(tmp_path / f"{dead_name}.kv"))
    _term, _vote, snap_index, _st, blob, suffix = store.load_state()
    assert snap_index > 0 and blob is not None   # loadable, snapshot intact
    assert all(e is not None for e in suffix)
    store.close()

    fresh = DistributedImmutableMap()
    revived = RaftNode(dead_name, list(names), bus.endpoint(dead_name),
                       fresh.apply, seed=seed + 17,
                       storage=RaftLogStore(str(tmp_path / f"{dead_name}.kv")),
                       snapshot_fn=fresh.snapshot,
                       restore_fn=fresh.restore,
                       snapshot_entries=SNAPSHOT_EVERY)
    assert revived.state.snapshot_index == snap_index   # not genesis
    nodes[dead_i], maps[dead_i] = revived, fresh
    pump(bus, nodes, 30)
    tags.append(commit_spend(leader, bus, nodes, f"torn-{seed}-post"))
    pump(bus, nodes, 20)        # let followers apply the final commit
    assert_exactly_once(maps, tags)


@pytest.mark.parametrize("seed", SEEDS)
def test_lagging_follower_catches_up_via_install_snapshot(seed, tmp_path):
    """Partition a follower, commit past a compaction so the leader's log
    no longer reaches back to it, then heal into a 30% append-drop storm.
    The follower must catch up via InstallSnapshot — replication alone
    cannot serve entries the leader already truncated — and agree."""
    bus, names, nodes, maps = make_compacting_cluster(tmp_path, seed)
    leader = run_until_leader(bus, nodes)
    lagger = next(n for n in nodes if n.role != LEADER)
    live = [n for n in nodes if n is not lagger]
    tags = [commit_spend(leader, bus, nodes, f"install-{seed}-pre")]

    with inject(FaultRule("net.send", "drop", detail=f"{lagger.node_id}->*"),
                FaultRule("net.send", "drop", detail=f"*->{lagger.node_id}"),
                seed=seed):
        for k in range(4 * SNAPSHOT_EVERY):
            tags.append(commit_spend(leader, bus, live,
                                     f"install-{seed}-{k}"))
    # the majority compacted past everything the lagger ever saw
    assert leader.state.snapshot_index > lagger.state.last_index()

    with inject(FaultRule("raft.append", "drop", probability=0.30),
                seed=seed):
        pump(bus, nodes, 120)
    pump(bus, nodes, 60)        # calm after the storm: full convergence
    assert lagger.stats()["installs_received"] >= 1, \
        "follower caught up without InstallSnapshot (log should be gone)"
    assert lagger.state.snapshot_index >= SNAPSHOT_EVERY
    tags.append(commit_spend(leader, bus, nodes, f"install-{seed}-post"))
    pump(bus, nodes, 20)        # let followers apply the final commit
    assert_exactly_once(maps, tags)


@pytest.mark.parametrize("seed", SEEDS)
def test_crash_restart_resumes_from_snapshot(seed, tmp_path):
    """Kill a follower mid-load, keep committing, restart it from its
    durable store: it must come back from snapshot + suffix (snapshot
    index > 0 at construction — not a genesis replay) and re-converge
    with every commitment consumed exactly once."""
    bus, names, nodes, maps = make_compacting_cluster(tmp_path, seed)
    leader = run_until_leader(bus, nodes)
    tags = []
    for k in range(2 * SNAPSHOT_EVERY):
        tags.append(commit_spend(leader, bus, nodes, f"crash-{seed}-{k}"))

    dead = next(n for n in nodes if n.role != LEADER)
    dead_name, dead_i = dead.node_id, nodes.index(dead)
    dead.stop()
    dead.storage.close()
    live = [n for n in nodes if n is not dead]
    for k in range(2 * SNAPSHOT_EVERY):
        tags.append(commit_spend(leader, bus, live,
                                 f"crash-{seed}-down-{k}"))

    fresh = DistributedImmutableMap()
    revived = RaftNode(dead_name, list(names), bus.endpoint(dead_name),
                       fresh.apply, seed=seed + 23,
                       storage=RaftLogStore(str(tmp_path / f"{dead_name}.kv")),
                       snapshot_fn=fresh.snapshot,
                       restore_fn=fresh.restore,
                       snapshot_entries=SNAPSHOT_EVERY)
    assert revived.state.snapshot_index > 0, "restarted from genesis"
    assert len(fresh._map) > 0, "snapshot restore left the map empty"
    nodes[dead_i], maps[dead_i] = revived, fresh
    pump(bus, nodes, 60)
    tags.append(commit_spend(leader, bus, nodes, f"crash-{seed}-post"))
    pump(bus, nodes, 20)        # let followers apply the final commit
    assert_exactly_once(maps, tags)


@pytest.mark.parametrize("seed", SEEDS)
def test_coordinator_log_gc_preserves_in_doubt(seed, tmp_path):
    """The 2PC recovery contract across GC: the in-doubt set (what
    ``recover_in_doubt`` resolves) is identical before the compaction,
    after it, and after replaying the compacted file — and an injected
    mid-GC abort leaves the original log byte-for-byte usable."""
    path = str(tmp_path / "coordinator.log")
    log = CoordinatorLog(path=path)
    rng = random.Random(seed)
    for k in range(40):
        tx = tx_of(f"coord-{seed}-{k}")
        log.begin(tx, {0: [ref_of(f"c{k}a")], 1: [ref_of(f"c{k}b")]})
        r = rng.random()
        if r < 0.55:                       # resolved and finalized: GC food
            log.decide(tx, "commit" if r < 0.3 else "abort")
            log.complete(tx)
        elif r < 0.75:                     # decided, never finalized
            log.decide(tx, "commit")
        # else: still in prepare — the classic in-doubt shape

    def in_doubt_view(coordinator):
        return sorted((tx, e["status"],
                       sorted((s, tuple(refs))
                              for s, refs in e["by_shard"].items()))
                      for tx, e in coordinator.in_doubt())

    before = in_doubt_view(log)
    assert before, "seeded mix produced no in-doubt entries"

    # an injected abort between fsync and rename must leave the ORIGINAL
    # log authoritative — same recovery view, nothing half-renamed
    with inject(FaultRule("coordlog.compact", "drop"), seed=seed) as inj:
        assert log.compact() == 0
        assert inj.fired("coordlog.compact") == 1
    assert in_doubt_view(CoordinatorLog(path=path)) == before

    reclaimed = log.compact()              # the real GC
    assert reclaimed > 0
    assert log.compactions == 1
    assert in_doubt_view(log) == before    # live view unchanged
    replay = CoordinatorLog(path=path)     # a restarted coordinator's view
    assert in_doubt_view(replay) == before
    assert replay.bytes_appended == log.bytes_appended
