"""SLOTracker: error budgets, multi-window burn rates, and alerts.

Deterministic — the tracker's clock is injected, so the sliding windows
are stepped by hand.
"""
import pytest

from corda_tpu.observability.slo import (DEFAULT_OBJECTIVES, SLObjective,
                                         SLOTracker)
from corda_tpu.utils.metrics import MetricRegistry


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def make(objectives=None, **kw):
    clock = Clock()
    kw.setdefault("windows_s", (10.0, 100.0))
    tracker = SLOTracker(objectives=objectives or DEFAULT_OBJECTIVES,
                         clock=clock, **kw)
    return tracker, clock


def test_untouched_budget_is_100():
    tracker, _ = make()
    for obj in tracker.objectives:
        assert tracker.error_budget_pct(obj) == 100.0
    assert tracker.alerts() == []
    assert tracker.status()["alerting"] is False


def test_availability_budget_burns_with_failures():
    avail = SLObjective("availability", 0.9)     # 10% budget
    tracker, clock = make(objectives=(avail,))
    for i in range(100):
        tracker.record(ok=(i % 10 != 0), latency_s=0.01)  # 10% failures
    # burning exactly at budget: burn rate 1.0, budget fully consumed
    assert tracker.burn_rates(avail)[100.0] == pytest.approx(1.0)
    assert tracker.error_budget_pct(avail) == pytest.approx(0.0)


def test_latency_objective_counts_slow_commits_as_bad():
    lat = SLObjective("latency_p99", 0.99, latency_ms=100.0)
    tracker, _ = make(objectives=(lat,))
    tracker.record(ok=True, latency_s=0.05)      # under the bound
    tracker.record(ok=True, latency_s=0.5)       # slow == bad
    tracker.record(ok=False, latency_s=None)     # failed == bad
    assert lat.is_bad(True, 0.5) and lat.is_bad(False, None)
    assert not lat.is_bad(True, 0.05)
    assert tracker.error_budget_pct(lat) < 100.0


def test_events_age_out_of_the_window():
    avail = SLObjective("availability", 0.9)
    tracker, clock = make(objectives=(avail,))
    tracker.record(ok=False)
    assert tracker.error_budget_pct(avail) < 100.0
    clock.t += 101.0                             # past the long window
    tracker.record(ok=True)
    assert tracker.error_budget_pct(avail) == 100.0


def test_page_needs_both_windows_burning():
    avail = SLObjective("availability", 0.999)   # tiny budget: easy burn
    tracker, clock = make(objectives=(avail,))
    # old bad events: long window burns, short window is clean
    for _ in range(20):
        tracker.record(ok=False)
    clock.t += 50.0
    for _ in range(20):
        tracker.record(ok=True, latency_s=0.001)
    alerts = tracker.alerts()
    assert [a["severity"] for a in alerts] == ["ticket"]
    # now the short window burns too → page
    for _ in range(20):
        tracker.record(ok=False)
    alerts = tracker.alerts()
    assert alerts and alerts[0]["severity"] == "page"
    assert tracker.status()["alerting"] is True


def test_publish_exports_gauges():
    tracker, _ = make()
    registry = MetricRegistry()
    tracker.publish(registry)
    tracker.record(ok=False)
    snap = registry.snapshot()
    assert "SLO.availability.ErrorBudgetPct" in snap
    assert "SLO.Alerting" in snap
    names = {n for n in snap if n.startswith("SLO.")}
    assert any("BurnRateShort" in n for n in names)
    assert any("BurnRateLong" in n for n in names)


def test_window_validation():
    with pytest.raises(ValueError):
        SLOTracker(windows_s=(60.0,))
    with pytest.raises(ValueError):
        SLOTracker(windows_s=(300.0, 60.0))
