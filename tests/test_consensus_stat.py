"""consensus_stat CLI: render() is a pure function of the /debug/raft and
/api/timeseries payloads — canned dicts, no HTTP."""
from corda_tpu.tools.consensus_stat import render


RAFT = {
    "groups": {
        "s0": {
            "leader": {"node": "raft0", "role": "leader", "term": 4,
                       "leader_tenure_s": 12.5,
                       "peer_lag": {"raft1": 0, "raft2": 3}},
            "log_entries": 42, "elections_total": 1,
            "attribution": {
                "append_wait": {"n": 9, "p50_ms": 0.1, "p99_ms": 0.4},
                "fsync": {"n": 9, "p50_ms": 0.2, "p99_ms": 1.4},
                "replicate": {"n": 9, "p50_ms": 0.8, "p99_ms": 2.1},
                "apply": {"n": 9, "p50_ms": 0.05, "p99_ms": 0.2},
                "total": {"n": 9, "p50_ms": 1.15, "p99_ms": 4.1}},
        },
        "s1": {"leader": None, "log_entries": 7, "elections_total": 2},
    },
    "shards": {
        "shards": [
            {"shard": "s0", "requests": 30, "refs": 45,
             "applied": 28, "reserved": 1},
            {"shard": "s1", "requests": 10, "refs": 12, "applied": 9},
        ],
        "touch_matrix": {"s0": 25, "s0+s1": 5, "s1": 10},
        "skew_index": 1.5,
        "coordinator_log_bytes": 2048,
        "coordinator_in_doubt": 0,
    },
}

TIMESERIES = {
    "columns": ["t", "n", "min", "max", "mean", "last"],
    "series": {
        'Raft.LogEntries{group="s0"}': [
            {"bucket_s": 0.5, "capacity": 240,
             "points": [[0.0, 2, 1.0, 2.0, 1.5, 2.0],
                        [0.5, 2, 3.0, 4.0, 3.5, 4.0]]},
            {"bucket_s": 5.0, "capacity": 240,
             "points": [[0.0, 4, 1.0, 4.0, 2.5, 4.0]]},
        ],
    },
    "dropped_series": 0,
}


def test_render_groups_and_attribution():
    screen = render(RAFT, TIMESERIES)
    lines = screen.splitlines()
    assert lines[0] == "consensus groups: 2"
    s0 = next(l for l in lines if l.startswith("s0"))
    assert "raft0" in s0 and "42" in s0
    assert "0.2/1.4" in s0        # fsync p50/p99
    assert "0.8/2.1" in s0        # replicate p50/p99
    s1 = next(l for l in lines if l.startswith("s1"))
    # no leader, no attribution: honest "-" cells, never zeros
    assert "-" in s1 and "7" in s1
    assert "skew=1.500" in screen
    assert "coordinator_log_bytes=2048" in screen
    assert "s0:req=30" in screen and "reserved=1" in screen
    # the shard without a reserved count renders "-", not 0
    assert "s1:req=10 applied=9 reserved=-" in screen
    # sparklines: one per resolution ring with points
    spark_line = next(l for l in lines if "Raft.LogEntries" in l)
    assert "|" in spark_line      # two resolutions rendered


def test_render_survives_empty_and_malformed():
    assert "(no raft groups)" in render({}, None)
    for junk in (None, "oops", 42, {"groups": "x"},
                 {"groups": {"s0": None}},
                 {"groups": {"s0": {"leader": "x"}},
                  "shards": {"shards": "x", "skew_index": None}}):
        assert render(junk if isinstance(junk, dict) else junk or {},
                      {"series": "garbage"})
    # a half-written timeseries payload never breaks the screen
    broken_ts = {"series": {"x": [{"points": [[1], "junk", None]},
                                  "garbage"]}}
    assert render(RAFT, broken_ts)


SOAK = {
    "resources": {
        "Vault.States": {"size": 120, "kind": "grows",
                         "verdict": "growing", "slope_per_s": 1.4},
        "Staging.Buffers": {"size": 8, "kind": "bounded",
                            "verdict": "bounded", "slope_per_s": 0.0},
        "Requests.Timelines": {"size": 512, "kind": "bounded",
                               "verdict": "leaking", "slope_per_s": 2.5},
    },
    "leaking": ["Requests.Timelines"],
    "cpu": {"shares_pct": {"raft_pump": 40.0, "serialization": 35.0,
                           "other": 25.0, "network": 0.0},
            "share_sum_pct": 100.0, "top_commit_path": "raft_pump"},
}


def test_render_soak_section():
    screen = render(RAFT, TIMESERIES, SOAK)
    assert "soak resources" in screen
    vault = next(l for l in screen.splitlines() if "Vault.States" in l)
    assert "grows" in vault and "growing" in vault and "+1.4/s" in vault
    # a leaking verdict is flagged loudly
    leak = next(l for l in screen.splitlines()
                if "Requests.Timelines" in l)
    assert "leaking" in leak and "!!" in leak
    # CPU shares render busiest-first with the commit-path headline
    cpu = next(l for l in screen.splitlines() if l.startswith("cpu shares"))
    assert "top commit-path: raft_pump" in cpu
    assert cpu.index("raft_pump=40.0%") < cpu.index("serialization=35.0%")
    assert "network=0.0%" not in cpu       # zero shares are noise


def test_render_soak_section_survives_garbage():
    base = render(RAFT, TIMESERIES)
    # absent / malformed payloads lose the section, never the screen
    for junk in (None, "oops", 42, {"resources": "x"},
                 {"resources": {"a": "junk", "b": {"verdict": None}},
                  "cpu": {"shares_pct": "x"}}):
        assert "consensus groups" in render(RAFT, TIMESERIES, junk)
    assert render(RAFT, TIMESERIES, None) == base
