"""Native (C++) Raft core tests — same scenarios as the Python RaftNode,
plus wire-compat in a MIXED cluster (native + Python replicas replicating
together). Skipped when libraftcore.so is not built (make -C native)."""
import pytest

from corda_tpu.consensus.raft import LEADER, RaftNode
from corda_tpu.consensus.raftcore import NATIVE_RAFT_AVAILABLE
from corda_tpu.network.inmemory import InMemoryMessagingNetwork

pytestmark = pytest.mark.skipif(not NATIVE_RAFT_AVAILABLE,
                                reason="native raft core not built")


def make_cluster(n=3, mixed=False):
    from corda_tpu.consensus.raftcore import NativeRaftNode
    bus = InMemoryMessagingNetwork()
    names = [f"raft{i}" for i in range(n)]
    applied = [[] for _ in range(n)]
    nodes = []
    for i, name in enumerate(names):
        cls = RaftNode if (mixed and i % 2 == 1) else NativeRaftNode
        nodes.append(cls(
            name, list(names), bus.create_node(name),
            (lambda s: (lambda e: (s.append(e), len(s))[1]))(applied[i]),
            seed=i))
    return bus, nodes, applied


def run_until_leader(bus, nodes, max_ticks=300):
    for _ in range(max_ticks):
        for node in nodes:
            node.tick()
        bus.run_network()
        if [n for n in nodes if n.role == LEADER]:
            for _ in range(5):
                for node in nodes:
                    node.tick()
                bus.run_network()
            final = [n for n in nodes if n.role == LEADER]
            if len(final) == 1:
                return final[0]
    raise AssertionError("no leader elected")


def pump(bus, nodes, ticks=10):
    for _ in range(ticks):
        for node in nodes:
            node.tick()
        bus.run_network()


def test_native_election_and_replication():
    bus, nodes, applied = make_cluster(3)
    leader = run_until_leader(bus, nodes)
    fut = leader.submit("entry-1")
    pump(bus, nodes)
    assert fut.result(timeout=1) == 1
    fut2 = leader.submit("entry-2")
    pump(bus, nodes)
    assert fut2.result(timeout=1) == 2
    assert all(log == ["entry-1", "entry-2"] for log in applied)


def test_native_follower_forwarding():
    bus, nodes, applied = make_cluster(3)
    leader = run_until_leader(bus, nodes)
    follower = next(n for n in nodes if n is not leader)
    fut = follower.submit("via-follower")
    pump(bus, nodes, ticks=15)
    assert fut.result(timeout=1) == 1
    assert all(log == ["via-follower"] for log in applied)


def test_native_leader_failure_reelection():
    bus, nodes, applied = make_cluster(3)
    leader = run_until_leader(bus, nodes)
    fut = leader.submit("before-crash")
    pump(bus, nodes)
    assert fut.result(timeout=1) == 1
    # crash the leader: cut all its traffic
    bus.transfer_filter = lambda t: leader.node_id not in (t.sender,
                                                           t.recipient)
    rest = [n for n in nodes if n is not leader]
    new_leader = run_until_leader(bus, rest)
    assert new_leader is not leader
    fut2 = new_leader.submit("after-crash")
    pump(bus, rest, ticks=15)
    assert fut2.result(timeout=1) == 2
    live = [applied[nodes.index(n)] for n in rest]
    assert all(log == ["before-crash", "after-crash"] for log in live)


def test_native_append_response_reports_verified_match_only():
    """ADVICE r2 (C++ side): a duplicate append covering a prefix of the
    local log must report match = prev + len(entries), not last_index()."""
    from corda_tpu.consensus.raft import (AppendEntries, AppendResponse,
                                          LogEntry, TOPIC_RAFT)
    from corda_tpu.consensus.raftcore import NativeRaftNode
    from corda_tpu.core.serialization import deserialize, serialize
    from corda_tpu.network.messaging import TopicSession

    bus = InMemoryMessagingNetwork()
    leader_ep = bus.create_node("raft0")
    responses = []
    leader_ep.add_message_handler(
        TopicSession(TOPIC_RAFT),
        lambda msg: responses.append(deserialize(msg.data)))
    follower = NativeRaftNode(
        "raft1", ["raft0", "raft1"], bus.create_node("raft1"),
        lambda e: None, seed=1)
    # build a 3-entry log on the follower
    leader_ep.send(TopicSession(TOPIC_RAFT), serialize(AppendEntries(
        1, "raft0", 0, 0,
        (LogEntry(1, "a"), LogEntry(1, "b"), LogEntry(1, "c")), 0)), "raft1")
    bus.run_network()
    full = [m for m in responses if isinstance(m, AppendResponse)]
    assert full and full[-1].success and full[-1].match_index == 3
    # duplicate append covering only the first entry
    leader_ep.send(TopicSession(TOPIC_RAFT), serialize(AppendEntries(
        1, "raft0", 0, 0, (LogEntry(1, "a"),), 0)), "raft1")
    bus.run_network()
    dup = [m for m in responses if isinstance(m, AppendResponse)][-1]
    assert dup.success and dup.match_index == 1  # prev(0) + entries(1)
    # log not truncated by the duplicate
    from corda_tpu.consensus import raftcore as rc
    assert rc._LIB.raft_last_index(follower._handle) == 3


def test_mixed_native_python_cluster():
    """Wire compatibility: native and pure-Python replicas in ONE cluster
    elect a leader and replicate identically."""
    bus, nodes, applied = make_cluster(3, mixed=True)
    leader = run_until_leader(bus, nodes)
    for i in range(3):
        fut = leader.submit(f"e{i}")
        pump(bus, nodes)
        assert fut.result(timeout=1) == i + 1
    assert all(log == ["e0", "e1", "e2"] for log in applied)
    # submit through a node of the OTHER implementation than the leader
    other = next(n for n in nodes if type(n) is not type(leader))
    fut = other.submit("cross-impl")
    pump(bus, nodes, ticks=15)
    assert fut.result(timeout=1) == 4
    assert all(log[-1] == "cross-impl" for log in applied)
