"""Differential tests of the device limb field arithmetic vs Python ints.

The field ops are lazily reduced (relaxed limbs < 1.5*2^16, any residue
mod p) — tests canonicalise with F.canon before comparing against Python
modular arithmetic, and separately check the relaxed-limb invariant.
"""
import numpy as np
import pytest

from corda_tpu.ops import field as F

RNG = np.random.default_rng(42)
PRIMES = [F.P25519, F.PSECP, F.PSECR1]


def rand_elems(p, n=64):
    vals = [int.from_bytes(RNG.bytes(32), "little") % p for _ in range(n)]
    # include edge cases
    vals[:6] = [0, 1, p - 1, p - 2, (1 << 255) % p, (p - 1) // 2]
    return vals


def canon_int(a, p):
    """Device array → canonical Python ints, asserting the lazy invariant:
    limbs 0..14 < LMAX, limb 15 < 2^18 (field.py module contract)."""
    arr = np.asarray(a, dtype=np.uint64)
    assert (arr[..., :15] < F.LMAX).all(), "INV violated: limb >= 1.5*2^16"
    assert (arr[..., 15] < F.LIMB15_MAX).all(), "INV violated: limb15 >= 2^18"
    return F.from_limbs(F.canon(a, p))


@pytest.mark.parametrize("p", PRIMES)
def test_limb_roundtrip(p):
    vals = rand_elems(p)
    assert F.from_limbs(F.to_limbs(vals)) == vals


@pytest.mark.parametrize("p", PRIMES)
def test_canon(p):
    # canon must reduce any 16-limb value (up to 2^256-1) below p.
    vals = [0, 1, p - 1, p, p + 1, 2 * p - 1, (1 << 256) - 1, (1 << 256) - 2]
    vals = [v for v in vals if v < (1 << 256)]
    out = F.from_limbs(F.canon(jnp_arr(vals), p))
    assert out == [v % p for v in vals]


def jnp_arr(vals):
    import jax.numpy as jnp
    return jnp.asarray(F.to_limbs(vals))


@pytest.mark.parametrize("p", PRIMES)
def test_mul(p):
    a, b = rand_elems(p), rand_elems(p)
    out = canon_int(F.mul(F.to_limbs(a), F.to_limbs(b), p), p)
    assert out == [(x * y) % p for x, y in zip(a, b)]


@pytest.mark.parametrize("p", PRIMES)
def test_mul_lazy_inputs(p):
    # inputs anywhere in [0, 2^256) must still multiply correctly mod p
    a = [(1 << 256) - 1 - i for i in range(8)] + rand_elems(p, 8)
    b = rand_elems(p, 8) + [(1 << 256) - 17 - i for i in range(8)]
    out = canon_int(F.mul(F.to_limbs(a), F.to_limbs(b), p), p)
    assert out == [(x * y) % p for x, y in zip(a, b)]


@pytest.mark.parametrize("p", PRIMES)
def test_add_sub_neg(p):
    a, b = rand_elems(p), rand_elems(p)
    la, lb = F.to_limbs(a), F.to_limbs(b)
    assert canon_int(F.add(la, lb, p), p) == [(x + y) % p for x, y in zip(a, b)]
    assert canon_int(F.sub(la, lb, p), p) == [(x - y) % p for x, y in zip(a, b)]
    assert canon_int(F.neg(la, p), p) == [(-x) % p for x in a]


@pytest.mark.parametrize("p", PRIMES)
def test_add_sub_lazy_inputs(p):
    top = (1 << 256) - 1
    a = [top, top, 0, top - 5]
    b = [top, 0, top, 17]
    la, lb = F.to_limbs(a), F.to_limbs(b)
    assert canon_int(F.add(la, lb, p), p) == [(x + y) % p for x, y in zip(a, b)]
    assert canon_int(F.sub(la, lb, p), p) == [(x - y) % p for x, y in zip(a, b)]


@pytest.mark.parametrize("p", PRIMES)
def test_mul_const(p):
    a = rand_elems(p)
    for c in [0, 1, 2, 8, 38, 977, 121666]:
        out = canon_int(F.mul_const(F.to_limbs(a), c, p), p)
        assert out == [(x * c) % p for x in a]


@pytest.mark.parametrize("p", PRIMES)
def test_predicates(p):
    a = rand_elems(p, 8)
    la = F.to_limbs(a)
    assert list(np.asarray(F.eq(la, la, p))) == [True] * 8
    assert list(np.asarray(F.is_zero(la, p))) == [v == 0 for v in a]
    lb = F.to_limbs(a[::-1])
    assert list(np.asarray(F.eq(la, lb, p))) == [x == y for x, y in zip(a, a[::-1])]
    # lazy congruence: v and v+p are equal mod p though limb-distinct
    small = [3, 9]
    shifted = [v + p for v in small]
    assert list(np.asarray(F.eq(F.to_limbs(small), F.to_limbs(shifted), p))) == [True, True]


@pytest.mark.parametrize("p", PRIMES)
def test_pow_small(p):
    a = rand_elems(p, 8)
    la = F.to_limbs(a)
    out = canon_int(F.pow_const(la, 65537, p), p)
    assert out == [pow(x, 65537, p) for x in a]


@pytest.mark.parametrize("p", PRIMES[:2])
def test_inv(p):
    a = [v or 1 for v in rand_elems(p, 8)]
    la = F.to_limbs(a)
    out = canon_int(F.inv(la, p), p)
    assert out == [pow(x, p - 2, p) for x in a]
