"""Differential tests of the device limb field arithmetic vs Python ints."""
import numpy as np
import pytest

from corda_tpu.ops import field as F

RNG = np.random.default_rng(42)
PRIMES = [F.P25519, F.PSECP]


def rand_elems(p, n=64):
    vals = [int.from_bytes(RNG.bytes(32), "little") % p for _ in range(n)]
    # include edge cases
    vals[:6] = [0, 1, p - 1, p - 2, (1 << 255) % p, (p - 1) // 2]
    return vals


@pytest.mark.parametrize("p", PRIMES)
def test_limb_roundtrip(p):
    vals = rand_elems(p)
    assert F.from_limbs(F.to_limbs(vals)) == vals


@pytest.mark.parametrize("p", PRIMES)
def test_mul(p):
    a, b = rand_elems(p), rand_elems(p)
    out = F.from_limbs(F.mul(F.to_limbs(a), F.to_limbs(b), p))
    assert out == [(x * y) % p for x, y in zip(a, b)]


@pytest.mark.parametrize("p", PRIMES)
def test_add_sub_neg(p):
    a, b = rand_elems(p), rand_elems(p)
    la, lb = F.to_limbs(a), F.to_limbs(b)
    assert F.from_limbs(F.add(la, lb, p)) == [(x + y) % p for x, y in zip(a, b)]
    assert F.from_limbs(F.sub(la, lb, p)) == [(x - y) % p for x, y in zip(a, b)]
    assert F.from_limbs(F.neg(la, p)) == [(-x) % p for x in a]


@pytest.mark.parametrize("p", PRIMES)
def test_mul_const(p):
    a = rand_elems(p)
    for c in [0, 1, 2, 8, 38, 977, 121666]:
        out = F.from_limbs(F.mul_const(F.to_limbs(a), c, p))
        assert out == [(x * c) % p for x in a]


@pytest.mark.parametrize("p", PRIMES)
def test_predicates(p):
    a = rand_elems(p, 8)
    la = F.to_limbs(a)
    assert list(np.asarray(F.eq(la, la))) == [True] * 8
    assert list(np.asarray(F.is_zero(la))) == [v == 0 for v in a]
    lb = F.to_limbs(a[::-1])
    assert list(np.asarray(F.eq(la, lb))) == [x == y for x, y in zip(a, a[::-1])]


@pytest.mark.parametrize("p", PRIMES)
def test_pow_small(p):
    a = rand_elems(p, 8)
    la = F.to_limbs(a)
    out = F.from_limbs(F.pow_const(la, 65537, p))
    assert out == [pow(x, 65537, p) for x in a]
