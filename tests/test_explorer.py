"""Explorer terminal dashboard tests (tools/explorer analog)."""
from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.finance import CashIssueFlow
from corda_tpu.node.rpc import CordaRPCOps
from corda_tpu.samples.simulation import Simulation
from corda_tpu.testing import MockNetwork
from corda_tpu.tools.explorer import Explorer


def test_render_dashboard():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank = network.create_node("O=Bank, L=London, C=GB")
    network.start_nodes()
    ops = CordaRPCOps(bank.services, bank.smm)
    fsm = bank.start_flow(CashIssueFlow(Amount(123400, USD), b"\x01",
                                        bank.party, notary.party))
    network.run_network()
    fsm.result_future.result(timeout=5)

    out = Explorer(ops).render()
    assert "O=Bank, L=London, C=GB" in out
    assert "2 nodes" in out and "1 notaries" in out
    assert "CashState" in out and "total 123400" in out
    assert "1 verified transactions" in out
    assert "flows started: 1" in out


def test_watch_renders_over_simulation(capsys):
    sim = Simulation(n_banks=2, seed=3, issue_cents=100_00).run(steps=2)
    ops = CordaRPCOps(sim.banks[0].services, sim.banks[0].smm)
    Explorer(ops).watch(interval_s=0.0, iterations=2)
    printed = capsys.readouterr().out
    assert printed.count("VAULT") == 2        # two live frames
    assert "Bank A" in printed
