"""Schema-evolution interop: v1 and v2 of a carried-schema state type must
round-trip between peers on different versions.

Reference direction: ClassCarpenter.kt:30-447 + amqp/SerializerFactory.kt
(the carpenter/AMQP subsystem is the beginning of versioned evolution);
VERDICT r4 ask #8.  The two-version MockNetwork test flips the process
registry between the SEND serialization and the DELIVERY deserialization
via the bus transfer observer — the wire bytes cross a real version
boundary inside one deterministic process.
"""
import dataclasses

import pytest

from corda_tpu.core.serialization import SerializationError, codec
from corda_tpu.flows import FlowLogic, Receive, Send, SendAndReceive
from corda_tpu.flows.api import initiated_by, initiating_flow
from corda_tpu.testing import MockNetwork

NAME = "evolution.DemoState"


@dataclasses.dataclass(frozen=True)
class DemoStateV1:
    amount: int
    legacy_note: str


@dataclasses.dataclass(frozen=True)
class DemoStateV2:
    """v2: ``legacy_note`` removed, ``memo`` added WITH a default."""

    amount: int
    memo: str = "v2-default"


@dataclasses.dataclass(frozen=True)
class DemoStateV2Strict:
    """An added field WITHOUT a default: incompatible with v1 senders."""

    amount: int
    required_new: str


def _register(cls):
    codec.register_type(NAME, cls, carry_schema=True)


def _unregister(cls):
    codec._REGISTRY.pop(NAME, None)
    codec._BY_CLASS.pop(cls, None)
    codec._SCHEMA_NAMES.pop(NAME, None)


@pytest.fixture(autouse=True)
def _clean_registry():
    yield
    for cls in (DemoStateV1, DemoStateV2, DemoStateV2Strict):
        _unregister(cls)
    entry = codec._CARPENTED.pop(NAME, None)
    if entry is not None:
        for cls, cname in list(codec._CARPENTED_BY_CLASS.items()):
            if cname == NAME:
                del codec._CARPENTED_BY_CLASS[cls]


def test_v1_wire_decodes_on_v2_with_default():
    _register(DemoStateV1)
    blob = codec.serialize(DemoStateV1(7, "old"))
    _unregister(DemoStateV1)
    _register(DemoStateV2)
    got = codec.deserialize(blob)
    assert got == DemoStateV2(amount=7, memo="v2-default")


def test_v2_wire_decodes_on_v1_dropping_added_field():
    _register(DemoStateV2)
    blob = codec.serialize(DemoStateV2(9, memo="note"))
    _unregister(DemoStateV2)
    _register(DemoStateV1)
    with pytest.raises(SerializationError):
        codec.deserialize(blob)   # v1's legacy_note has NO default


def test_two_way_round_trip_with_defaults():
    """v1 ⇄ v2 when every version-unique field has a default."""

    @dataclasses.dataclass(frozen=True)
    class V1:
        amount: int
        legacy_note: str = "none"

    codec.register_type(NAME, V1, carry_schema=True)
    blob_v1 = codec.serialize(V1(3, "hello"))
    _unregister(V1)
    _register(DemoStateV2)
    got_v2 = codec.deserialize(blob_v1)       # legacy dropped, memo default
    assert got_v2 == DemoStateV2(3, "v2-default")
    blob_v2 = codec.serialize(got_v2)
    _unregister(DemoStateV2)
    codec.register_type(NAME, V1, carry_schema=True)
    got_v1 = codec.deserialize(blob_v2)       # memo dropped, legacy default
    assert got_v1 == V1(3, "none")
    _unregister(V1)


def test_incompatible_added_field_fails_typed():
    _register(DemoStateV1)
    blob = codec.serialize(DemoStateV1(1, "x"))
    _unregister(DemoStateV1)
    _register(DemoStateV2Strict)
    with pytest.raises(SerializationError, match="no default"):
        codec.deserialize(blob)


def test_carpented_union_evolution():
    """A receiver WITHOUT the class sees two schema versions of one name:
    both materialize; the union bag re-serializes under the union schema;
    a pre-evolution bag stays bit-exact."""
    _register(DemoStateV1)
    blob_v1 = codec.serialize(DemoStateV1(5, "legacy"))
    _unregister(DemoStateV1)
    bag_v1 = codec.deserialize(blob_v1)                  # carpents v1 schema
    assert codec.serialize(bag_v1) == blob_v1            # bit-exact
    _register(DemoStateV2)
    blob_v2 = codec.serialize(DemoStateV2(6, "m"))
    _unregister(DemoStateV2)
    bag_v2 = codec.deserialize(blob_v2)                  # triggers the union
    assert type(bag_v2).__corda_carpented_fields__ == [
        "amount", "legacy_note", "memo"]
    assert (bag_v2.amount, bag_v2.legacy_note, bag_v2.memo) == (6, None, "m")
    # the union class now serves OLD wire forms too
    bag_v1_again = codec.deserialize(blob_v1)
    assert type(bag_v1_again) is type(bag_v2)
    assert (bag_v1_again.amount, bag_v1_again.legacy_note,
            bag_v1_again.memo) == (5, "legacy", None)
    # union bags round-trip under the union schema
    rt = codec.deserialize(codec.serialize(bag_v2))
    assert rt == bag_v2
    # the PRE-evolution bag still re-serializes bit-exactly
    assert codec.serialize(bag_v1) == blob_v1


# ---------------------------------------------------------------------------
# Two-version MockNetwork interop
# ---------------------------------------------------------------------------

@initiating_flow
class SendStateFlow(FlowLogic):
    def __init__(self, peer, state):
        self.peer = peer
        self.state = state

    def call(self):
        resp = yield SendAndReceive(self.peer, self.state, object)
        return resp.unwrap(lambda d: d)


@initiated_by(SendStateFlow)
class ReceiveStateFlow(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        msg = yield Receive(self.peer, object)
        got = msg.unwrap(lambda d: d)
        yield Send(self.peer, ("ack", got))
        return got


def test_two_version_mocknetwork_interop():
    """Node A serializes a v1 state onto the bus; the process 'upgrades' to
    v2 while the message is in flight (bus transfer observer = the version
    boundary); node B decodes and ACKS a v2 instance — and A (now also v2)
    decodes the echoed state."""
    network = MockNetwork()
    a = network.create_node("O=A, L=London, C=GB")
    b = network.create_node("O=B, L=Paris, C=FR")
    network.start_nodes()

    _register(DemoStateV1)
    fsm = a.start_flow(SendStateFlow(b.party, DemoStateV1(11, "pre")))

    upgraded = []

    def upgrade_once(transfer):
        # flip versions on the transfer CARRYING the v1 payload: it is
        # already serialized (v1 bytes in flight), not yet delivered —
        # exactly the cross-version wire boundary
        if not upgraded and NAME.encode() in transfer.message.data:
            _unregister(DemoStateV1)
            _register(DemoStateV2)
            upgraded.append(True)
        return True

    network.bus.transfer_filter = upgrade_once
    network.run_network()
    ack, got = fsm.result_future.result(timeout=5)
    assert upgraded, "version boundary never crossed"
    assert ack == "ack"
    assert got == DemoStateV2(amount=11, memo="v2-default")
