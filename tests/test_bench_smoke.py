"""bench.py --smoke: the tiny-batch mode must exercise the full service
path (host-crossover route) end-to-end and emit the complete JSON schema —
every field the full run emits, plus the smoke marker — so the benchmark
artifact's shape is locked by CI, not discovered broken on TPU hardware.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_emits_full_json_schema():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    # the full-run schema (device rates are 0.0 in smoke, but PRESENT)
    for field in (
            "metric", "value", "unit", "vs_baseline",
            "ed25519_verifies_per_sec_per_chip",
            "secp256r1_verifies_per_sec_per_chip",
            "r1_halfgcd_fallback_pct", "r1_doublings_per_op",
            "service_path_verifies_per_sec",
            "ed25519_service_path_verifies_per_sec",
            "secp256r1_service_path_verifies_per_sec",
            "mixed_service_path_verifies_per_sec",
            "tx_verify_p50_ms_batch1", "tx_verify_p50_ms_batch1k",
            "tx_verify_p90_ms_batch1k", "tx_verify_p99_ms_batch1k",
            "service_to_kernel_ratio_k1", "service_to_kernel_ratio_ed25519",
            "service_to_kernel_ratio_r1",
            "host_baseline_verifies_per_sec", "unique_signatures",
            "prep_workers", "prep_inflight_depth", "prep_overlap_max",
            "post_warmup_compiles", "bucket_ladder",
            "interactive_latency_ms", "interactive_batch",
            "stage_dispatch_ms_p50", "stage_dispatch_ms_p90",
            "stage_dispatch_ms_p99", "stage_finish_ms_p50",
            "verifier_batch_size_p50",
            # flight-recorder fields (observability/profiling.py)
            "compile_s_total", "compile_cache_hits",
            "occupancy_pct_per_scheme", "prep_overlap_pct"):
        assert field in out, f"missing JSON field: {field}"
    assert isinstance(out["occupancy_pct_per_scheme"], dict)
    assert isinstance(out["bucket_ladder"], list) and out["bucket_ladder"]
    assert out["smoke"] is True
    # the service path actually ran: every scheme produced a nonzero rate,
    # and the continuous planner overlapped flushes on the prep pool
    # (bench's own smoke gate enforces >= 2 + zero post-warmup compiles
    # before it even prints — this re-asserts from the artifact side)
    for rate in ("service_path_verifies_per_sec",
                 "ed25519_service_path_verifies_per_sec",
                 "secp256r1_service_path_verifies_per_sec",
                 "mixed_service_path_verifies_per_sec"):
        assert out[rate] > 0, rate
    assert out["prep_overlap_max"] >= 2
    assert out["post_warmup_compiles"] == 0


@pytest.mark.slow
def test_bench_smoke_guard_gate_passes_end_to_end():
    """`bench.py --smoke --guard` must exit 0: the regression gate degrades
    to the schema check on a smoke artifact (tools/benchguard.py), so this
    is the CI-safe wiring test for the whole measure-then-gate path."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--guard"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "benchguard: ok" in proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["smoke"] is True


def test_fleet_observability_fields_locked_in_guard_schema():
    """The fleet artifact's observability fields are schema-locked: a
    future bench.py edit that drops them must fail the guard, not just
    vanish silently from the JSON."""
    from corda_tpu.tools import benchguard
    for field in ("worker_busy_skew_pct", "steals_total",
                  "stitched_trace_depth"):
        assert field in benchguard.MULTICHIP_REQUIRED
        smoke = {"fleet_verifies_per_sec": 3.0, "smoke": True}
        problems = benchguard.guard_multichip(smoke, [])
        assert any(field in p for p in problems), field


def test_controller_fields_locked_in_guard_schema():
    """The self-driving-fleet fields are schema-locked the same way: a
    fleet artifact without the controller's recovery evidence fails the
    MULTICHIP guard instead of silently shrinking."""
    from corda_tpu.tools import benchguard
    for field in ("recovery_s", "controller_actions"):
        assert field in benchguard.MULTICHIP_REQUIRED
        smoke = {"fleet_verifies_per_sec": 3.0, "smoke": True}
        problems = benchguard.guard_multichip(smoke, [])
        assert any(field in p for p in problems), field


@pytest.mark.slow
def test_fleet_smoke_guard_gate_passes_end_to_end():
    """`bench.py --smoke --fleet --guard` must exit 0: smoke degrades the
    MULTICHIP gate to its schema check, which now demands the fleet
    observability fields."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--fleet", "--guard"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "benchguard: ok" in proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["smoke"] is True
    assert out["stitched_trace_depth"] >= 2
    # a healthy smoke fleet is invisible to the controller: steady state,
    # zero actions, no recovery episode (bench.py itself asserts this
    # before printing; re-pinned here from the artifact side)
    assert out["controller_state"] == "steady"
    assert out["controller_actions"] == 0
    assert out["recovery_s"] == 0.0


@pytest.mark.ledger
def test_ledger_smoke_guard_gate_passes_end_to_end():
    """`bench.py --smoke --ledger --guard` is the tier-1 CPU proof for the
    whole ledger measurement path: the open-loop scenario completes, the
    artifact carries every LEDGER_r0*.json field, the validity probes
    (exactly-once, replica agreement, stitched traces) hold, and the
    guard degrades to its schema check on the smoke artifact."""
    from corda_tpu.tools.benchguard import LEDGER_REQUIRED
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--ledger", "--guard"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "benchguard: ok" in proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    for field in LEDGER_REQUIRED:
        assert field in out, f"missing LEDGER field: {field}"
    assert out["smoke"] is True and out["ledger"] is True
    assert out["exactly_once_ok"] is True
    assert out["replicas_agree"] is True
    assert out["stitched_traces"] >= 1
    assert out["ops_failed"] == 0
    assert out["committed_tx_per_sec"] > 0
    assert out["chaos_enabled"] is False and out["chaos_windows"] == []
    assert "trace_sample" not in out      # test hook stays out of artifacts
