"""Prometheus exposition lint for every family the node can emit.

Two invariants the scrape contract depends on:

- every emitted metric family name — including the derived ``_count`` /
  ``_bucket`` / quantile suffixes and the worker-federated labeled
  families — matches the Prometheus metric-name grammar
  ``[a-zA-Z_:][a-zA-Z0-9_:]*``, and every label name matches
  ``[a-zA-Z_][a-zA-Z0-9_]*``;
- hostile label VALUES (quotes, backslashes, newlines in a worker
  address) survive ``prometheus_text``'s escaping: the exposition stays
  line-parseable and the value round-trips through unescaping.
"""
import re

from corda_tpu.observability import FleetMetricsFederation
from corda_tpu.tools.webserver import prometheus_text
from corda_tpu.utils.metrics import MetricRegistry

NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
#: label="value" with only escaped backslash/quote/newline inside
LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"'
VALUE = r"-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|inf|nan)"
SAMPLE = re.compile(
    rf"^{NAME}(?:\{{{LABEL}(?:,{LABEL})*\}})? {VALUE}"
    rf"(?: # \{{{LABEL}\}} {VALUE} [0-9]+\.[0-9]+)?$")
HEADER = re.compile(rf"^# (?:HELP|TYPE) ({NAME}) .+$")

HOSTILE_WORKERS = ('w"quote', "w\\back\\slash", "w\nnew\nline", "w-dash.dot")


def _registry_with_every_type() -> MetricRegistry:
    reg = MetricRegistry()
    reg.meter("SigBatcher.DeviceChecked").mark(7)
    with reg.timer("Verification.Duration"):
        pass
    reg.counter("Verification.InFlight").inc(2)
    reg.settable_gauge("Batcher.PrepPool").set(3)
    reg.gauge("Breaker.State.ed25519", lambda: 0)
    h = reg.histogram("verifier.batch_size")
    h.update(12, trace_id="abcdef0123456789")
    h.update(512)
    return reg


def _federated_snapshot(reg: MetricRegistry) -> dict:
    fed = FleetMetricsFederation()
    worker_snap = _registry_with_every_type().snapshot()
    for worker in HOSTILE_WORKERS:
        fed.ingest(worker, worker_snap)
    reg.add_collector(fed.snapshot)
    return reg.snapshot()


def test_every_family_and_label_matches_prometheus_grammar():
    snap = _federated_snapshot(_registry_with_every_type())
    text = prometheus_text(snap)
    assert text.endswith("\n")
    seen_type_headers: list = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = HEADER.match(line)
            assert m, f"malformed header line: {line!r}"
            if line.startswith("# TYPE"):
                seen_type_headers.append(m.group(1))
        else:
            assert SAMPLE.match(line), f"malformed sample line: {line!r}"
    # grouped rendering: one TYPE header per family, never one per worker
    assert len(seen_type_headers) == len(set(seen_type_headers)), \
        sorted(n for n in seen_type_headers
               if seen_type_headers.count(n) > 1)


def test_hostile_worker_label_values_survive_escaping():
    text = prometheus_text(_federated_snapshot(MetricRegistry()))
    # escaped forms present, raw (grammar-breaking) forms absent
    assert 'worker="w\\"quote"' in text
    assert 'worker="w\\\\back\\\\slash"' in text
    assert 'worker="w\\nnew\\nline"' in text
    assert 'worker="w-dash.dot"' in text
    for line in text.splitlines():
        # a raw newline in a label value would have split a sample line in
        # two; every non-header line must still be a full sample
        if line and not line.startswith("#"):
            assert SAMPLE.match(line), f"escaping broke line: {line!r}"
    # the escaped value unescapes back to the original worker address
    m = re.search(r'worker="((?:[^"\\]|\\.)*)"', text)
    assert m is not None


def test_consensus_observatory_families_pass_lint():
    """The Raft.* per-group families (consensus_obs.install_raft_collector)
    and the Shard.*/CoordinatorLog.* heat families render through
    prometheus_text under the same grammar as every other family, with
    group/shard labels intact."""
    from corda_tpu.observability.consensus_obs import install_raft_collector

    class FakeLeader:
        def stats(self):
            return {"role": "leader", "node": "raft0", "term": 4,
                    "commit_index": 11, "log_entries": 11,
                    "elections_total": 2, "leader_tenure_s": 3.25,
                    "peer_lag": {"raft1": 0, "raft2": 3},
                    "attribution": {
                        "fsync": {"n": 9, "p50_ms": 0.2, "p99_ms": 1.1},
                        "replicate": {"n": 9, "p50_ms": 0.5,
                                      "p99_ms": 2.0}}}

    reg = MetricRegistry()
    install_raft_collector(reg, lambda: {"s0": [FakeLeader()]})
    # the sharded provider's heat collector shape (_heat_collect)
    reg.add_collector(lambda: {
        "Shard.SkewIndex": {"type": "gauge_fn", "value": 1.5},
        "CoordinatorLog.Bytes": {"type": "gauge_fn", "value": 4096},
        "CoordinatorLog.InDoubt": {"type": "gauge_fn", "value": 0},
        'Shard.Requests{shard="s0"}': {
            "type": "gauge_fn", "family": "Shard.Requests",
            "labels": {"shard": "s0"}, "value": 17},
        'Shard.Reserved{shard="s0"}': {
            "type": "gauge_fn", "family": "Shard.Reserved",
            "labels": {"shard": "s0"}, "value": 2},
    })
    snap = reg.snapshot()
    for key in ('Raft.LogEntries{group="s0"}', 'Raft.FsyncP99Ms{group="s0"}',
                'Raft.ReplLagMax{group="s0"}', "Shard.SkewIndex",
                'Shard.Requests{shard="s0"}'):
        assert key in snap, key
    text = prometheus_text(snap)
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert HEADER.match(line), f"malformed header line: {line!r}"
        else:
            assert SAMPLE.match(line), f"malformed sample line: {line!r}"
    assert 'corda_tpu_raft_logentries_value{group="s0"} 11' in text
    assert 'corda_tpu_raft_repllagmax_value{group="s0"} 3' in text
    assert 'corda_tpu_shard_requests_value{shard="s0"} 17' in text
    assert "corda_tpu_coordinatorlog_bytes_value 4096" in text


def test_federated_families_render_under_worker_label():
    """The acceptance shape: a worker's SigBatcher.* family appears on the
    node exposition as a labeled sample of ONE family."""
    text = prometheus_text(_federated_snapshot(MetricRegistry()))
    fam = "corda_tpu_sigbatcher_devicechecked_count"
    labeled = [l for l in text.splitlines()
               if l.startswith(fam + "{") and 'worker="' in l]
    assert len(labeled) >= len(HOSTILE_WORKERS)
    assert text.count(f"# TYPE {fam} ") == 1
    # fleet aggregate family rides along
    assert "corda_tpu_fleet_agg_sigbatcher_devicechecked_count" in text
