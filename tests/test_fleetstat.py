"""fleetstat CLI: render() is a pure function of the two JSON payloads."""
from corda_tpu.tools.fleetstat import render


FLEET = {
    "expected": 2, "attached": 2, "degraded": False, "stale": [],
    "workers": {
        "w0": {"device_shard": [0], "capacity": 1, "queue_depth": 3,
               "last_report_age_s": 0.012, "stale": False},
        "w1": {"device_shard": [1], "capacity": 2, "queue_depth": 0,
               "last_report_age_s": 0.002, "stale": False},
    },
}

METRICS = {
    'SigBatcher.Checked{worker="w0"}': {
        "type": "meter", "count": 128, "mean_rate": 40.0,
        "family": "SigBatcher.Checked", "labels": {"worker": "w0"}},
    'SigBatcher.Checked{worker="w1"}': {
        "type": "meter", "count": 64, "mean_rate": 20.0,
        "family": "SigBatcher.Checked", "labels": {"worker": "w1"}},
    'SigBatcher.DeviceChecked{worker="w0"}': {
        "type": "meter", "count": 96, "mean_rate": 30.0,
        "family": "SigBatcher.DeviceChecked", "labels": {"worker": "w0"}},
    "Fleet.agg.SigBatcher.Checked": {
        "type": "meter", "count": 192, "mean_rate": 60.0},
}


def test_render_one_row_per_worker():
    screen = render(FLEET, METRICS)
    lines = screen.splitlines()
    assert "2/2 attached" in lines[0]
    assert "DEGRADED" not in lines[0]
    w0 = next(l for l in lines if l.startswith("w0"))
    w1 = next(l for l in lines if l.startswith("w1"))
    assert "128" in w0 and "96" in w0 and "ok" in w0   # counts + fresh state
    assert "64" in w1
    assert "fleet aggregate checked: 192" in screen


def test_render_flags_stale_and_degraded():
    fleet = dict(FLEET, degraded=True, stale=["w0"])
    screen = render(fleet, METRICS)
    assert "DEGRADED" in screen.splitlines()[0]
    w0 = next(l for l in screen.splitlines() if l.startswith("w0"))
    assert "stale" in w0


def test_render_survives_empty_payloads():
    screen = render({}, {})
    assert "no workers attached" in screen


def test_render_critpath_blame_line():
    critpath = {"per_class": {
        "pay": {"n": 4, "e2e_ms_p50": 400.0, "e2e_ms_p99": 900.0,
                "dominant": "scheduler.wait",
                "blame_p50": {"scheduler.wait": 300.0,
                              "flow.compute": 100.0}},
        "issue": {"n": 2, "e2e_ms_p50": 100.0, "e2e_ms_p99": 120.0,
                  "dominant": "flow.compute",
                  "blame_p50": {"flow.compute": 100.0}},
    }}
    screen = render(FLEET, METRICS, critpath)
    line = next(l for l in screen.splitlines()
                if l.startswith("critpath blame(p50):"))
    assert "pay=scheduler.wait 75%" in line
    assert "issue=flow.compute 100%" in line
    # no critpath payload (old node / tracing off): line simply absent
    assert "critpath" not in render(FLEET, METRICS)
    assert "critpath" not in render(FLEET, METRICS, {"traces": 0,
                                                     "per_class": {}})
    # malformed payloads never break the screen
    for junk in ("oops", {"per_class": "x"}, {"per_class": {"pay": 3}},
                 {"per_class": {"pay": {"dominant": None,
                                        "blame_p50": "x"}}}):
        assert render(FLEET, METRICS, junk)


def test_render_survives_non_dict_payloads():
    # a webserver mid-restart can serve error strings / partial bodies
    for fleet, metrics in (
            (None, None), ("oops", []), ([], "oops"), (42, {"x": 1})):
        screen = render(fleet, metrics)
        assert "no workers attached" in screen


def test_render_survives_malformed_worker_entries():
    fleet = {
        "expected": None, "attached": "soon", "stale": "not-a-list",
        "workers": {
            "w0": None,                       # crashed mid-report
            "w1": "garbage",
            "w2": {"queue_depth": None, "capacity": {"nested": 1},
                   "last_report_age_s": "n/a"},
            3: {"queue_depth": 1},            # non-string worker key
        },
    }
    screen = render(fleet, {"SigBatcher.Checked": "not-a-dict"})
    lines = screen.splitlines()
    # every worker still gets a row, defaults filled in
    for name in ("w0", "w1", "w2", "3"):
        assert any(l.startswith(name) for l in lines), name
    w2 = next(l for l in lines if l.startswith("w2"))
    assert "n/a" in w2            # string age passes through


def test_render_missing_metric_family_zeroes_columns():
    metrics = {  # only one family present for w0; none for w1
        'SigBatcher.Checked{worker="w0"}': {"type": "meter", "count": 7},
        'SigBatcher.DeviceChecked{worker="w0"}': "corrupt",
    }
    screen = render(FLEET, metrics)
    w0 = next(l for l in screen.splitlines() if l.startswith("w0"))
    w1 = next(l for l in screen.splitlines() if l.startswith("w1"))
    assert "7" in w0
    assert w1.split()[-4:] == ["0", "0", "0", "0"]


def test_render_controller_block():
    fleet = dict(FLEET, controller={
        "state": "degraded",
        "ladder": [{"name": "shed_bulk", "applied": True},
                   {"name": "shrink_ladder", "applied": True},
                   {"name": "host_route_interactive", "applied": False}],
        "actions_total": 5, "episodes": 1, "recovery_s_last": 2.75,
        "recent_actions": [
            {"action": "scale_up", "worker": "w2"},
            {"action": "apply_step", "step": "shed_bulk"},
            {"action": "apply_step", "step": "shrink_ladder"}]})
    screen = render(fleet, METRICS)
    assert "controller: degraded" in screen
    assert "ladder=shed_bulk+shrink_ladder" in screen
    assert "actions=5" in screen and "episodes=1" in screen
    assert "recovery_s=2.75" in screen
    assert "recent: scale_up(w2); apply_step(shed_bulk); " \
        "apply_step(shrink_ladder)" in screen


RAFT = {"groups": {
    "s0": {"leader": {"node": "raft0", "role": "leader",
                      "leader_tenure_s": 12.5,
                      "peer_lag": {"raft1": 0, "raft2": 3}},
           "log_entries": 42, "elections_total": 1,
           "attribution": {"fsync": {"n": 9, "p50_ms": 0.2,
                                     "p99_ms": 1.4}}},
    "s1": {"leader": None, "log_entries": 7, "elections_total": 2},
}}


def test_render_consensus_line():
    screen = render(FLEET, METRICS, raft=RAFT)
    line = next(l for l in screen.splitlines()
                if l.startswith("consensus:"))
    assert "s0:leader(raft0)" in line
    assert "tenure=12s" in line or "tenure=13s" in line
    assert "elections=1" in line
    assert "fsync_p99=1.4ms" in line
    assert "lag=3" in line and "log=42" in line
    # a group mid-election renders honestly: no leader, "-" cells
    assert "s1:no-leader(?)" in line
    assert "elections=2" in line and "log=7" in line
    # no observatory payload (old node): line simply absent
    assert "consensus:" not in render(FLEET, METRICS)


def test_render_consensus_line_survives_garbage():
    for junk in ("oops", 42, {"groups": "x"}, {"groups": {"s0": None}},
                 {"groups": {"s0": {"leader": "x", "attribution": 3,
                                    "log_entries": None}}}):
        screen = render(FLEET, METRICS, raft=junk)
        assert "w0" in screen      # worker table still renders


def test_render_controller_block_survives_garbage():
    for ctl in ("oops", 42, {"state": None, "ladder": "x",
                             "recent_actions": [None, "bad", {}]}):
        screen = render(dict(FLEET, controller=ctl), METRICS)
        assert "w0" in screen      # worker table still renders


def test_render_soak_line():
    soak = {
        "resources": {
            "Vault.States": {"size": 120, "kind": "grows",
                             "verdict": "growing"},
            "Staging.Buffers": {"size": 8, "kind": "bounded",
                                "verdict": "bounded"},
            "Requests.Timelines": {"size": 512, "kind": "bounded",
                                   "verdict": "leaking"},
        },
        "leaking": ["Requests.Timelines"],
        "cpu": {"shares_pct": {"raft_pump": 60.0},
                "top_commit_path": "raft_pump"},
    }
    screen = render(FLEET, METRICS, soak=soak)
    line = next(l for l in screen.splitlines() if l.startswith("soak:"))
    assert "3 structures" in line
    assert "leaking=1['Requests.Timelines']" in line
    assert "growing=1" in line
    assert "cpu_top=raft_pump" in line
    # no soak plane (old node): line simply absent
    assert "soak:" not in render(FLEET, METRICS)
    # malformed payloads lose the line, never the screen
    for junk in ("oops", 42, {"resources": "x"},
                 {"resources": {"a": None}, "leaking": 7, "cpu": "x"}):
        assert "w0" in render(FLEET, METRICS, soak=junk)
