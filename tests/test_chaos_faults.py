"""Chaos harness unit tests: the fault injector's scheduling semantics,
the retry module's backoff/deadline behavior, and the fail-stop seams
they gate (TCP startup, KvStore flush).

Everything here must be exactly reproducible from a seed — that is the
whole point of the harness (docs/ROBUSTNESS.md).
"""
import threading
import time

import pytest

from corda_tpu.testing.faults import (DROP, DUPLICATE, FaultError,
                                      FaultInjector, FaultRule, active,
                                      arm, disarm, fault_point, inject)
from corda_tpu.utils import retry

pytestmark = pytest.mark.chaos


# -- scheduling predicates ---------------------------------------------------

def test_disarmed_fault_point_is_inert():
    assert active() is None
    assert fault_point("tcp.send", detail="a->b") is None


def test_count_limits_fires():
    with inject(FaultRule("net.send", "drop", count=2), seed=1) as inj:
        outcomes = [fault_point("net.send", detail="a->b") for _ in range(5)]
    assert outcomes == [DROP, DROP, None, None, None]
    assert inj.fired("net.send") == 2


def test_after_skips_leading_hits():
    with inject(FaultRule("x", "drop", after=2), seed=1) as inj:
        outcomes = [fault_point("x") for _ in range(4)]
    assert outcomes == [None, None, DROP, DROP]
    assert inj.fired("x") == 2


def test_every_selects_kth_hit():
    with inject(FaultRule("x", "drop", every=3), seed=1):
        outcomes = [fault_point("x") for _ in range(7)]
    # fires on eligible hits 1, 4, 7 (every 3rd, starting at the first)
    assert outcomes == [DROP, None, None, DROP, None, None, DROP]


def test_detail_fnmatch_targets_one_peer():
    """Pattern rules on `detail` are how a test partitions one node."""
    rule = FaultRule("net.send", "drop", detail="alice->*")
    with inject(rule, seed=1) as inj:
        assert fault_point("net.send", detail="alice->bob") == DROP
        assert fault_point("net.send", detail="bob->alice") is None
        assert fault_point("net.send", detail="alice->carol") == DROP
    assert inj.fired("net.send") == 2


def test_point_fnmatch():
    with inject(FaultRule("tcp.*", "drop"), seed=1):
        assert fault_point("tcp.send") == DROP
        assert fault_point("tcp.connect") == DROP
        assert fault_point("net.send") is None


def test_raise_action_throws_connectionerror_subclass():
    """FaultError must be a ConnectionError so transport except-clauses
    catch injected faults exactly as they catch real socket failures."""
    with inject(FaultRule("oop.deliver")):  # action defaults to "raise"
        with pytest.raises(FaultError):
            fault_point("oop.deliver", detail="->w1")
    assert issubclass(FaultError, ConnectionError)
    assert issubclass(FaultError, OSError)


def test_raise_custom_exception_type():
    class Boom(RuntimeError):
        pass

    with inject(FaultRule("x", "raise", exc=Boom)):
        with pytest.raises(Boom):
            fault_point("x")


def test_duplicate_is_returned_to_call_site():
    with inject(FaultRule("net.send", "duplicate", count=1)):
        assert fault_point("net.send", detail="a->b") == DUPLICATE
        assert fault_point("net.send", detail="a->b") is None


def test_delay_action_sleeps_and_composes():
    """A delay rule slows the hit, then the scan continues — so it can
    stack with a drop rule on the same point."""
    with inject(FaultRule("x", "delay", delay_s=0.05),
                FaultRule("x", "drop")):
        t0 = time.monotonic()
        assert fault_point("x") == DROP
        assert time.monotonic() - t0 >= 0.05


def test_probability_deterministic_per_seed():
    def run(seed):
        with inject(FaultRule("x", "drop", probability=0.5), seed=seed):
            return [fault_point("x") for _ in range(32)]

    a, b = run(42), run(42)
    assert a == b                       # same seed ⇒ identical schedule
    assert run(43) != a                 # 1-in-2^32 flake odds; fine
    assert 0 < a.count(DROP) < 32       # the coin actually flips


def test_probability_independent_of_other_rules():
    """Per-rule RNGs: arming an extra rule must not shift which hits a
    probabilistic rule fires on."""
    def run(extra):
        rules = [FaultRule("x", "drop", probability=0.5)]
        if extra:
            rules.append(FaultRule("unrelated", "drop", probability=0.3))
        with inject(*rules, seed=7):
            # interleave hits on the unrelated point
            out = []
            for _ in range(16):
                fault_point("unrelated")
                out.append(fault_point("x"))
            return out

    assert run(extra=False) == run(extra=True)


def test_env_seed_pickup(monkeypatch):
    monkeypatch.setenv("CORDA_TPU_FAULT_SEED", "1234")
    assert FaultInjector().seed == 1234
    assert FaultInjector(seed=9).seed == 9   # explicit wins


def test_arm_disarm_and_active():
    inj = FaultInjector(seed=5)
    inj.add(FaultRule("x", "drop"))
    arm(inj)
    try:
        assert active() is inj
        assert fault_point("x") == DROP
    finally:
        disarm()
    assert active() is None
    assert fault_point("x") is None


def test_concurrent_hits_all_accounted():
    """The injector is hit from transport/dispatcher threads — counts must
    stay exact under concurrency."""
    with inject(FaultRule("x", "drop", count=50), seed=3) as inj:
        def worker():
            for _ in range(25):
                fault_point("x")
        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert inj.fired("x") == 50
    assert inj.rules[0].matches == 100


# -- retry/backoff -----------------------------------------------------------

def test_delays_bounded_and_jittered():
    policy = retry.RetryPolicy(base_s=0.05, cap_s=0.4)
    seq = [next(d) for d in [retry.delays(policy, seed=11)] for _ in range(20)]
    assert all(policy.base_s <= s <= policy.cap_s for s in seq)
    assert len(set(seq)) > 1            # jittered, not a fixed ladder
    # deterministic for a given seed
    d2 = retry.delays(policy, seed=11)
    assert [next(d2) for _ in range(20)] == seq


def test_retry_call_recovers_and_meters():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    before = retry.snapshot().get("Retry.Attempts.chaos_ut", {}).get("count", 0)
    out = retry.retry_call(flaky, site="chaos_ut",
                           policy=retry.RetryPolicy(base_s=0.001, cap_s=0.002),
                           retry_on=(ConnectionError,), sleep=lambda s: None)
    assert out == "ok" and calls["n"] == 3
    snap = retry.snapshot()
    assert snap["Retry.Attempts.chaos_ut"]["count"] - before == 3
    assert snap["Retry.Attempts"]["count"] >= 3


def test_retry_call_gives_up_after_max_attempts():
    def always():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        retry.retry_call(always, site="chaos_ut_giveup",
                         policy=retry.RetryPolicy(base_s=0.001, cap_s=0.002,
                                                  max_attempts=3),
                         sleep=lambda s: None)
    snap = retry.snapshot()
    assert snap["Retry.Attempts.chaos_ut_giveup"]["count"] == 3
    assert snap["Retry.GiveUps.chaos_ut_giveup"]["count"] == 1


def test_retry_call_respects_deadline_budget():
    """The deadline breaks the loop when the *projected* sleep would blow
    the budget — no attempt cap needed to stop it."""
    now = [0.0]

    def clock():
        return now[0]

    def sleep(s):
        now[0] += s

    attempts = {"n": 0}

    def always():
        attempts["n"] += 1
        raise TimeoutError("slow")

    with pytest.raises(TimeoutError):
        retry.retry_call(always, site="chaos_ut_deadline",
                         policy=retry.RetryPolicy(base_s=0.2, cap_s=0.3,
                                                  max_attempts=100,
                                                  deadline_s=0.5),
                         seed=1, sleep=sleep, clock=clock)
    assert attempts["n"] < 100          # deadline, not the cap, stopped it
    assert now[0] <= 0.5                # never slept past the budget


def test_retry_does_not_catch_unlisted_exceptions():
    def typo():
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry.retry_call(typo, site="chaos_ut_unlisted",
                         retry_on=(ConnectionError,), sleep=lambda s: None)
    # exactly one attempt: logic bugs must not be retried
    assert retry.snapshot()["Retry.Attempts.chaos_ut_unlisted"]["count"] == 1


# -- fail-stop seams ---------------------------------------------------------

def test_tcp_startup_bind_failure_raises():
    """Satellite: a failed bind must raise MessagingStartupError from the
    constructor, not park the node on a dead event loop."""
    import socket

    from corda_tpu.network.tcp import MessagingStartupError, TcpMessagingService

    blocker = socket.socket()
    try:
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        port = blocker.getsockname()[1]
        with pytest.raises(MessagingStartupError, match="failed to bind"):
            TcpMessagingService("dup", "127.0.0.1", port,
                                resolve_address=lambda name: None)
    finally:
        blocker.close()


def test_kvstore_flush_fault_fail_stops(tmp_path):
    """An injected SyncFailure at the kvstore.flush seam must fail-stop the
    store (no silent acceptance of unsynced writes) and leave previously
    committed data recoverable on reopen."""
    from corda_tpu.storage.kvstore import KvStore, SyncFailure

    path = str(tmp_path / "kv")
    kv = KvStore(path, use_native=False)
    kv[b"committed"] = b"v1"

    with inject(FaultRule("kvstore.flush", "raise", exc=SyncFailure,
                          count=1)):
        with pytest.raises(SyncFailure):
            kv[b"doomed"] = b"v2"
        # fail-stop: the store refuses further writes after a sync failure
        with pytest.raises(SyncFailure):
            kv[b"after"] = b"v3"
    kv.close()

    kv2 = KvStore(path, use_native=False)
    try:
        assert kv2[b"committed"] == b"v1"
        assert b"doomed" not in kv2
    finally:
        kv2.close()
