"""Test configuration: force a virtual 8-device CPU mesh before JAX initialises.

Mirrors the reference's deterministic in-process multi-node testing strategy
(MockNetwork, reference test-utils/.../node/MockNode.kt:41-66): we test multi-chip
sharding without real chips by asking XLA for 8 host-platform devices.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The environment may pre-register a hardware TPU backend from sitecustomize
# *before* this file runs, so env-var platform selection (JAX_PLATFORMS) is too
# late; jax.config.update after import is the reliable override. Without it the
# suite eagerly dispatches every op over the TPU tunnel (~20x slower than CPU).
import jax

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the curve-kernel scans cost tens of seconds
# to compile; caching makes repeated suite runs (and CI re-runs) near-instant.
import pathlib

jax.config.update("jax_compilation_cache_dir",
                  str(pathlib.Path(__file__).resolve().parent.parent / ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# Build the native engines (kvlog, raftcore) when a compiler is available so
# the suite exercises the C++ paths, not just the Python fallbacks. Import
# happens after this, so the ctypes loaders see fresh .so files.
import subprocess

_native_dir = pathlib.Path(__file__).resolve().parent.parent / "native"
try:
    _mk = subprocess.run(["make", "-C", str(_native_dir)],
                         capture_output=True, timeout=120, check=False)
    if _mk.returncode != 0:
        # a toolchain exists but the build BROKE: surface it loudly instead
        # of letting skipif markers turn native coverage into silent skips
        import sys
        print("NATIVE BUILD FAILED:\n" + _mk.stderr.decode(errors="replace"),
              file=sys.stderr)
except (OSError, subprocess.TimeoutExpired):
    pass  # no toolchain: fallbacks cover the formats

# Chaos reproducibility: when a fault-injection test fails, print the seed
# that drove its injector so the red run reproduces verbatim
# (CORDA_TPU_FAULT_SEED=<seed> pytest <nodeid>). The hookwrapper sees the
# report AFTER the test body ran but while the injector may still be armed
# (inject() disarms in its finally, which runs inside the call phase — so
# the test itself stashes the seed on the item via the chaos_seed fixture
# or we read the param).
import pytest


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    report = outcome.get_result()
    if report.when != "call" or not report.failed:
        return
    if item.get_closest_marker("chaos") is None:
        return
    from corda_tpu.utils import faults as _faults
    inj = _faults.active()
    seed = inj.seed if inj is not None else item.funcargs.get("seed")
    if seed is not None:
        report.sections.append((
            "chaos seed",
            f"fault seed {seed} — reproduce with "
            f"CORDA_TPU_FAULT_SEED={seed} pytest {item.nodeid!r}"))
