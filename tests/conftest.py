"""Test configuration: force a virtual 8-device CPU mesh before JAX initialises.

Mirrors the reference's deterministic in-process multi-node testing strategy
(MockNetwork, reference test-utils/.../node/MockNode.kt:41-66): we test multi-chip
sharding without real chips by asking XLA for 8 host-platform devices.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
