"""Raft consensus tests over the deterministic bus.

Reference analogs: RaftNotaryServiceTests / DistributedImmutableMapTests —
leader election, replicated commitment, leader-failure re-election,
double-spend conflict reporting through the replicated map.
"""
import pytest

from corda_tpu.consensus.raft import LEADER, FOLLOWER, RaftNode
from corda_tpu.consensus.raft_uniqueness import (DistributedImmutableMap,
                                                 RaftUniquenessProvider)
from corda_tpu.core.contracts.structures import StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.node.notary import UniquenessException


def make_cluster(n=3, applied=None):
    bus = InMemoryMessagingNetwork()
    names = [f"raft{i}" for i in range(n)]
    nodes = []
    for i, name in enumerate(names):
        ep = bus.create_node(name)
        store = [] if applied is None else applied[i]
        nodes.append(RaftNode(name, list(names), ep,
                              (lambda s: (lambda e: (s.append(e), len(s))[1]))(store),
                              seed=i))
    return bus, nodes


def run_until_leader(bus, nodes, max_ticks=200):
    for _ in range(max_ticks):
        for node in nodes:
            node.tick()
        bus.run_network()
        leaders = [n for n in nodes if n.role == LEADER]
        if leaders:
            # let heartbeats settle follower state
            for _ in range(5):
                for node in nodes:
                    node.tick()
                bus.run_network()
            final = [n for n in nodes if n.role == LEADER]
            if len(final) == 1:
                return final[0]
    raise AssertionError("no leader elected")


def pump(bus, nodes, ticks=10):
    for _ in range(ticks):
        for node in nodes:
            node.tick()
        bus.run_network()


def test_leader_election_and_replication():
    applied = [[], [], []]
    bus, nodes = make_cluster(3, applied)
    leader = run_until_leader(bus, nodes)
    fut = leader.submit("entry-1")
    pump(bus, nodes)
    assert fut.result(timeout=1) == 1
    fut2 = leader.submit("entry-2")
    pump(bus, nodes)
    assert fut2.result(timeout=1) == 2
    # every replica applied both entries in order
    assert applied[0] == applied[1] == applied[2] == ["entry-1", "entry-2"]


def test_follower_forwards_to_leader():
    applied = [[], [], []]
    bus, nodes = make_cluster(3, applied)
    leader = run_until_leader(bus, nodes)
    follower = next(n for n in nodes if n.role == FOLLOWER)
    fut = follower.submit("via-follower")
    pump(bus, nodes)
    assert fut.result(timeout=1) == 1
    assert all(a == ["via-follower"] for a in applied)


def test_reelection_after_leader_death():
    applied = [[], [], []]
    bus, nodes = make_cluster(3, applied)
    leader = run_until_leader(bus, nodes)
    fut = leader.submit("pre-crash")
    pump(bus, nodes)
    fut.result(timeout=1)
    # silence the leader: stop ticking it and drop its traffic
    dead = leader
    bus.transfer_filter = lambda t: t.sender != dead.node_id and \
        t.recipient != dead.node_id
    survivors = [n for n in nodes if n is not dead]
    new_leader = run_until_leader(bus, survivors)
    assert new_leader is not dead
    fut2 = new_leader.submit("post-crash")
    pump(bus, survivors)
    assert fut2.result(timeout=1) == 2
    surviving_logs = [applied[nodes.index(n)] for n in survivors]
    assert all(a == ["pre-crash", "post-crash"] for a in surviving_logs)


def test_duplicate_append_does_not_truncate_matching_suffix():
    """Raft §5.3 (review r2): a stale/duplicated AppendEntries whose entries
    all match the existing prefix must not discard later entries."""
    from corda_tpu.consensus.raft import AppendEntries, LogEntry

    bus, nodes = make_cluster(3)
    follower = nodes[0]
    follower.state.current_term = 2
    follower.state.log = [LogEntry(1, "a"), LogEntry(1, "b"), LogEntry(2, "c")]
    # duplicate of the first append (entry "a" only), as if delayed in flight
    follower._on_append(AppendEntries(2, "raft1", 0, 0,
                                      (LogEntry(1, "a"),), 0))
    assert [e.entry for e in follower.state.log] == ["a", "b", "c"]


def test_forged_empty_append_cannot_commit_divergent_suffix():
    """Review r2: leader_commit must clamp to prev + len(entries) — an
    empty append with a huge leader_commit must not apply an uncommitted
    divergent local suffix to the state machine."""
    from corda_tpu.consensus.raft import AppendEntries, LogEntry

    applied = [[], [], []]
    bus, nodes = make_cluster(3, applied=applied)
    follower = nodes[0]
    follower.state.current_term = 3
    # committed prefix (applied) + divergent uncommitted suffix
    follower.state.log = [LogEntry(1, "ok1"), LogEntry(2, "DIVERGENT")]
    follower.state.commit_index = 1
    follower._on_append(AppendEntries(3, "raft1", 1, 1, (), 2))
    assert follower.state.commit_index == 1          # clamped to prev+0
    assert "DIVERGENT" not in applied[0]
    # a real append covering the suffix still commits it
    follower._on_append(AppendEntries(3, "raft1", 1, 1,
                                      (LogEntry(3, "ok2"),), 2))
    assert follower.state.commit_index == 2
    assert applied[0] and applied[0][-1] == "ok2"


def test_append_response_match_index_clamped():
    """Review r2: a forged AppendResponse with a huge match_index must not
    drive next_index past the log end (out-of-range term_at on the next
    heartbeat)."""
    from corda_tpu.consensus.raft import AppendResponse, LEADER

    bus, nodes = make_cluster(3)
    leader = run_until_leader(bus, nodes)
    peer = [n for n in nodes if n is not leader][0].node_id
    leader._on_append_response(
        AppendResponse(leader.state.current_term, peer, True, 10 ** 9))
    assert leader._next_index[peer] == leader.state.last_index() + 1
    leader._send_append(peer)   # must not raise


def test_append_response_reports_verified_match_only():
    """ADVICE r2: a successful append must report match = prev + len(entries),
    not last_index() — with conflict-only truncation the local log can extend
    past the verified entries, and overstating match would let a batching
    leader commit entries the follower does not hold."""
    from corda_tpu.consensus.raft import AppendEntries, LogEntry

    bus, nodes = make_cluster(3)
    follower = nodes[0]
    follower.state.current_term = 2
    # local log extends past what the incoming (duplicate) append covers
    follower.state.log = [LogEntry(1, "a"), LogEntry(1, "b"), LogEntry(2, "c")]
    captured = []
    follower._post = lambda to, msg: captured.append((to, msg))
    follower._on_append(AppendEntries(2, "raft1", 0, 0,
                                      (LogEntry(1, "a"),), 0))
    to, resp = captured[-1]
    assert resp.success and resp.match_index == 1  # prev(0) + entries(1)


def test_raft_uniqueness_provider_conflicts():
    bus = InMemoryMessagingNetwork()
    names = [f"raft{i}" for i in range(3)]
    shared_machines = [DistributedImmutableMap() for _ in range(3)]
    providers = [RaftUniquenessProvider.build(
        name, list(names), bus.create_node(name),
        state_machine=shared_machines[i], seed=i)
        for i, name in enumerate(names)]
    nodes = [p.raft for p in providers]
    leader = run_until_leader(bus, nodes)
    leader_provider = providers[nodes.index(leader)]

    ref = StateRef(SecureHash.sha256(b"issue-tx"), 0)
    tx1 = SecureHash.sha256(b"spend-1")
    tx2 = SecureHash.sha256(b"spend-2")

    import threading
    import time
    results = {}

    def commit(key, tx_id):
        try:
            leader_provider.commit([ref], tx_id, "caller")
            results[key] = "ok"
        except UniquenessException as e:
            results[key] = e.conflicts

    def run_and_pump(key, tx_id):
        """Pump until the commit thread reports — a fixed pump count races
        thread scheduling on a loaded box."""
        t = threading.Thread(target=commit, args=(key, tx_id))
        t.start()
        deadline = time.monotonic() + 20
        while key not in results and time.monotonic() < deadline:
            pump(bus, nodes, 5)
            time.sleep(0.01)
        t.join(timeout=5)
        assert key in results, f"consensus for {key} did not complete"

    run_and_pump("first", tx1)
    assert results["first"] == "ok"

    run_and_pump("second", tx2)
    conflicts = results["second"]
    assert conflicts != "ok" and ref in conflicts
    assert conflicts[ref].consuming_tx == tx1
    # replicas hold identical committed maps
    assert all(len(m) == 1 for m in shared_machines)
