"""Oracle tear-off attestation + shell tests.

Reference analogs: NodeInterestRatesTest (oracle signs correct tear-offs,
refuses wrong/overshared ones) and InteractiveShell command tests.
"""
import io

import pytest

import corda_tpu.finance  # noqa: F401 — registers the @startable_by_rpc flows
from corda_tpu.core.contracts import Command, TransactionState
from corda_tpu.core.transactions import WireTransaction
from corda_tpu.flows import FlowException
from corda_tpu.samples.rates_oracle import (Fix, FixOf, RatesFixQueryFlow,
                                            RatesFixSignFlow, RatesOracle)
from corda_tpu.testing import DummyContract, DummyState, MockNetwork
from corda_tpu.tools.shell import Shell

LIBOR_3M = FixOf("LIBOR", "2026-07-30", "3M")


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    oracle_node = network.create_node("O=Rates Oracle, L=London, C=GB")
    alice = network.create_node("O=Alice, L=Madrid, C=ES")
    network.start_nodes()
    oracle = RatesOracle(oracle_node.services, {LIBOR_3M: 525})  # 5.25%
    oracle.install(oracle_node.smm)
    return network, notary, oracle_node, alice


def make_wtx_with_fix(alice, oracle_node, notary, value_bp):
    return WireTransaction(
        outputs=(TransactionState(DummyState(1, (alice.party.owning_key,)),
                                  notary.party),),
        commands=(
            Command(DummyContract.Create(), (alice.party.owning_key,)),
            Command(Fix(LIBOR_3M, value_bp), (oracle_node.party.owning_key,)),
        ),
        notary=notary.party,
        must_sign=(alice.party.owning_key, oracle_node.party.owning_key))


def test_oracle_query_and_tear_off_sign(net):
    network, notary, oracle_node, alice = net
    # query the fix
    fsm = alice.start_flow(RatesFixQueryFlow(oracle_node.party, LIBOR_3M))
    network.run_network()
    fix = fsm.result_future.result(timeout=1)
    assert fix.value_bp == 525

    # embed it, tear off everything except the oracle's command, get the sig
    wtx = make_wtx_with_fix(alice, oracle_node, notary, fix.value_bp)
    ftx = wtx.build_filtered_transaction(
        lambda c: isinstance(c, Command) and isinstance(c.value, Fix))
    assert ftx.verify()
    # the torn form reveals ONE component (privacy) but proves the same id
    assert len(ftx.filtered_leaves.available_components) == 1
    assert ftx.root_hash == wtx.id

    fsm = alice.start_flow(RatesFixSignFlow(oracle_node.party, ftx))
    network.run_network()
    sig = fsm.result_future.result(timeout=1)
    assert sig.by == oracle_node.party.owning_key
    sig.verify(wtx.id.bytes)  # the sig covers the FULL transaction id


def test_oracle_refuses_wrong_rate_and_overshare(net):
    network, notary, oracle_node, alice = net
    # wrong rate embedded
    wtx = make_wtx_with_fix(alice, oracle_node, notary, 999)
    ftx = wtx.build_filtered_transaction(
        lambda c: isinstance(c, Command) and isinstance(c.value, Fix))
    fsm = alice.start_flow(RatesFixSignFlow(oracle_node.party, ftx))
    network.run_network()
    with pytest.raises(FlowException, match="refuses"):
        fsm.result_future.result(timeout=1)

    # overshared tear-off (reveals a non-Fix component) also refused
    wtx2 = make_wtx_with_fix(alice, oracle_node, notary, 525)
    ftx2 = wtx2.build_filtered_transaction(lambda c: True)  # reveal all
    fsm = alice.start_flow(RatesFixSignFlow(oracle_node.party, ftx2))
    network.run_network()
    with pytest.raises(FlowException, match="refuses"):
        fsm.result_future.result(timeout=1)


def test_shell_commands(net):
    network, notary, oracle_node, alice = net
    from corda_tpu.node.rpc import CordaRPCOps
    ops = CordaRPCOps(alice.services, alice.smm)
    out = io.StringIO()
    shell = Shell(ops, out=out)
    assert shell.execute("flow list")
    assert "CashIssueFlow" in out.getvalue()
    assert shell.execute("run notary_identities")
    assert "Notary" in out.getvalue()
    assert shell.execute("run registered_flows")
    assert shell.execute("bogus command")
    assert "unknown command" in out.getvalue()
    assert shell.execute("run nonexistent_op")
    assert "error" in out.getvalue()
    # flow start via the shell: issue cash with parsed Amount + Party args
    assert shell.execute(
        'flow start CashIssueFlow "100 USD" 0x01 '
        '"O=Alice, L=Madrid, C=ES" "O=Notary Service, L=Zurich, C=CH"')
    network.run_network()
    assert "run_id" in out.getvalue()
    from corda_tpu.finance import CashState
    assert alice.services.vault.unconsumed_states(CashState)
    assert not shell.execute("exit")
