"""The Verify flow-suspension point (VERDICT r3 #2).

Reference semantics: flows await the TransactionVerifierService future by
parking the fiber (FlowStateMachineImpl.kt:379-393, Services.kt:544-550) —
the SMM resumes them when the (possibly out-of-process) result arrives.
Covers: N concurrent flows coalescing into ONE device batch, the
OutOfProcess backend reachable from the flow path, restart-mid-verify
replay, and original-exception-type delivery at the yield site.
"""
import time
from concurrent.futures import Future

import pytest

from corda_tpu.core.contracts import Command, TransactionState
from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.signatures import SignatureException
from corda_tpu.core.identity import Party
from corda_tpu.core.transactions import WireTransaction
from corda_tpu.flows.api import FlowLogic, Verify
from corda_tpu.testing import (DUMMY_NOTARY_NAME, DummyContract, DummyState,
                               MockNetwork, MockServices)
from corda_tpu.verifier import SignatureBatcher, TpuTransactionVerifierService
from corda_tpu.verifier.out_of_process import (
    OutOfProcessTransactionVerifierService, VerifierWorker)

NOTARY_KP = generate_keypair(entropy=b"\x31" * 32)
NOTARY = Party(DUMMY_NOTARY_NAME, NOTARY_KP.public)
ALICE_KP = generate_keypair(entropy=b"\x32" * 32)


def make_issue_stx(services, i=7):
    wtx = WireTransaction(
        outputs=(TransactionState(DummyState(i, (ALICE_KP.public,)), NOTARY),),
        commands=(Command(DummyContract.Create(), (ALICE_KP.public,)),),
        notary=NOTARY, must_sign=(ALICE_KP.public,))
    return services.sign_transaction(wtx, ALICE_KP.public)


class VerifyFlow(FlowLogic):
    """Minimal flow that suspends on transaction verification."""

    def __init__(self, stx):
        self.stx = stx

    def call(self):
        yield Verify(self.stx)
        return "verified"


class CatchingVerifyFlow(FlowLogic):
    def __init__(self, stx):
        self.stx = stx

    def call(self):
        try:
            yield Verify(self.stx)
        except SignatureException:
            return "caught-signature-exception"
        return "verified"


def make_network_node():
    network = MockNetwork()
    node = network.create_node("O=Alice, L=London, C=GB")
    network.start_nodes()
    return network, node


def seed_services(node):
    """Signing services for building the test transactions (the node's own
    hub resolves/verifies them — issue transactions have no inputs)."""
    return MockServices(key_pairs=[NOTARY_KP, ALICE_KP], parties=[NOTARY])


def test_n_flows_one_device_batch():
    """N concurrently-suspended flows' signatures coalesce into ONE device
    batch — the cross-flow batching the suspension point exists for
    (impossible while flows blocked the node thread one at a time)."""
    network, node = make_network_node()
    svcs = seed_services(node)
    # verify_signed submits on the INTERACTIVE class (PR 6), so the
    # cross-flow coalescing window is interactive_latency_s now
    batcher = SignatureBatcher(host_crossover=0, max_latency_s=0.25,
                               interactive_latency_s=0.25)
    node.services.verifier_service = TpuTransactionVerifierService(
        batcher=batcher)
    try:
        fsms = [node.start_flow(VerifyFlow(make_issue_stx(svcs, i)))
                for i in range(8)]
        # every flow parked on its verify future before any batch dispatched
        assert node.smm.awaiting_external == 8
        network.run_network()
        assert [f.result_future.result(timeout=60) for f in fsms] \
            == ["verified"] * 8
        snap = batcher.metrics.snapshot()
        assert snap["SigBatcher.DeviceBatches"]["count"] == 1
        assert snap["SigBatcher.DeviceChecked"]["count"] == 8
    finally:
        node.services.verifier_service.shutdown()


def test_oop_backend_reachable_from_flows():
    """A flow on an OutOfProcess-backed node parks on the worker round-trip
    and the verification demonstrably executes in the worker — the r3 gate
    (node/services.py) that kept flows off the OOP backend is gone."""
    network, node = make_network_node()
    svcs = seed_services(node)
    svc = OutOfProcessTransactionVerifierService(node.messaging)
    node.services.verifier_service = svc
    worker = VerifierWorker(
        network.bus.create_node("verifier-worker-1"),
        str(node.info.address))
    network.run_network()     # worker Hello handshake
    fsms = [node.start_flow(VerifyFlow(make_issue_stx(svcs, i)))
            for i in range(4)]
    assert node.smm.awaiting_external == 4
    network.run_network()
    assert [f.result_future.result(timeout=30) for f in fsms] \
        == ["verified"] * 4
    assert worker.verified_count == 4


def test_verify_failure_throws_original_type_at_yield_site():
    network, node = make_network_node()
    svcs = seed_services(node)
    node.services.verifier_service = TpuTransactionVerifierService(
        batcher=SignatureBatcher(host_crossover=0, max_latency_s=0.01))
    try:
        stx = make_issue_stx(svcs)
        bad_sig = stx.sigs[0].__class__(
            stx.sigs[0].bytes[:-1] + bytes([stx.sigs[0].bytes[-1] ^ 1]),
            stx.sigs[0].by)
        bad_stx = stx.__class__(stx.tx_bits, (bad_sig,))
        fsm = node.start_flow(CatchingVerifyFlow(bad_stx))
        network.run_network()
        assert fsm.result_future.result(timeout=60) \
            == "caught-signature-exception"
    finally:
        node.services.verifier_service.shutdown()


class ManualVerifierService:
    """Async-capable verifier whose futures the test completes by hand."""

    def __init__(self):
        self.futures = []

    def verify_signed(self, stx, services, check_sufficient_signatures=True):
        fut = Future()
        self.futures.append(fut)
        return fut


def test_restart_mid_verify_replays_and_resubmits():
    """Kill the node while a flow is parked on Verify: the restored flow
    replays to the suspension point and RE-SUBMITS the verification to the
    new node's service (re-verification is idempotent — the result never
    made it into the checkpoint)."""
    network, node = make_network_node()
    svcs = seed_services(node)
    manual = ManualVerifierService()
    node.services.verifier_service = manual
    stx = make_issue_stx(svcs)
    fsm = node.start_flow(VerifyFlow(stx))
    assert len(manual.futures) == 1 and not fsm.result_future.done()
    assert node.smm.checkpoints.get_all_checkpoints()  # parked → checkpointed

    node2 = node.restart()
    manual2 = ManualVerifierService()
    node2.services.verifier_service = manual2
    seed_services(node2)
    node2.start()             # restore → replay → re-park on Verify
    assert len(manual2.futures) == 1
    restored = list(node2.smm.flows.values())[0]
    manual2.futures[0].set_result(None)
    network.run_network()
    assert restored.result_future.result(timeout=30) == "verified"
    assert not node2.smm.checkpoints.get_all_checkpoints()


def test_sync_fallback_failure_also_lands_at_yield_site():
    """The no-service fallback must deliver verification failures INTO the
    flow with their original type, exactly like the async path — not kill
    the flow from outside its except clause."""
    network, node = make_network_node()
    svcs = seed_services(node)
    assert node.services.verifier_service is None
    stx = make_issue_stx(svcs)
    bad_sig = stx.sigs[0].__class__(
        stx.sigs[0].bytes[:-1] + bytes([stx.sigs[0].bytes[-1] ^ 1]),
        stx.sigs[0].by)
    bad_stx = stx.__class__(stx.tx_bits, (bad_sig,))
    fsm = node.start_flow(CatchingVerifyFlow(bad_stx))
    network.run_network()
    assert fsm.result_future.result(timeout=30) == "caught-signature-exception"


def test_sync_fallback_without_async_service():
    """No verifier service configured → Verify verifies synchronously on the
    node thread (the no-service fallback), flows still complete."""
    network, node = make_network_node()
    svcs = seed_services(node)
    assert node.services.verifier_service is None
    fsm = node.start_flow(VerifyFlow(make_issue_stx(svcs)))
    network.run_network()
    assert fsm.result_future.result(timeout=30) == "verified"
    assert node.smm.awaiting_external == 0


def test_mesh_devices_requires_tpu_verifier():
    """Config validation (VERDICT r3 #3 follow-up): the configuration must
    FAIL AT CONSTRUCTION when mesh_devices is set with a verifier type
    that would silently ignore it — before a misconfigured node binds
    sockets or writes its identity."""
    from corda_tpu.node.node import NodeConfiguration

    for vt in ("InMemory", "OutOfProcess"):
        with pytest.raises(ValueError, match="mesh_devices requires"):
            NodeConfiguration(my_legal_name="O=Bad, L=London, C=GB",
                              verifier_type=vt, mesh_devices=4)
    # and the valid combination constructs fine
    NodeConfiguration(my_legal_name="O=Good, L=London, C=GB",
                      verifier_type="Tpu", mesh_devices=4)


class VerifyThenSleepFlow(FlowLogic):
    """Parks on Verify, then parks AGAIN on a long Sleep — the second park
    is the target a stale verify completion must not wrongly resume."""

    def __init__(self, stx):
        self.stx = stx

    def call(self):
        from corda_tpu.flows.api import Sleep
        yield Verify(self.stx)
        yield Sleep(3600)
        return "woke"


def test_stale_verify_completion_does_not_resume_wrong_park():
    """ADVICE r4 (low): _on_verify_done must check the flow is still parked
    on the ORIGINATING Verify request (like wake_timers' identity check) —
    a duplicate/stale future completion after the flow moved on must not
    resume it at the wrong yield."""
    network, node = make_network_node()
    svcs = seed_services(node)
    manual = ManualVerifierService()
    node.services.verifier_service = manual
    fsm = node.start_flow(VerifyThenSleepFlow(make_issue_stx(svcs)))
    verify_request = fsm.parked_on
    assert isinstance(verify_request, Verify)
    manual.futures[0].set_result(None)
    network.run_network()        # verify resumes; flow re-parks on Sleep
    assert not fsm.done
    sleep_park = fsm.parked_on
    assert sleep_park is not None and sleep_park is not verify_request

    # a duplicate delivery of the SAME verify completion arrives late
    node.smm._awaiting_external += 1   # pair the handler's decrement
    node.smm._on_verify_done(fsm, manual.futures[0], verify_request)
    assert fsm.parked_on is sleep_park and not fsm.done


def test_rebuild_error_uses_whitelist_not_dynamic_import():
    """ADVICE r4 (low): checkpoint error payloads must reconstruct only
    whitelisted exception types — an arbitrary 'module:qualname' gadget
    (import side effects, arbitrary one-string-arg callables) degrades to
    FlowException instead of being imported and invoked."""
    from corda_tpu.flows.api import FlowException, FlowTimeoutException
    from corda_tpu.node.statemachine import _error_payload, _rebuild_error

    e = _rebuild_error(_error_payload(SignatureException("bad sig")))
    assert type(e) is SignatureException and str(e) == "bad sig"
    e = _rebuild_error(_error_payload(FlowTimeoutException("slow peer")))
    assert type(e) is FlowTimeoutException
    # legacy string payloads still work
    assert type(_rebuild_error("plain")) is FlowException

    for gadget in (["os.path:join", "x"], ["subprocess:Popen", "sleep 9"],
                   ["builtins:exec", "1+1"], ["no.such.module:X", "y"]):
        rebuilt = _rebuild_error(gadget)
        assert type(rebuilt) is FlowException, gadget
