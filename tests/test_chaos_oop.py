"""Out-of-process verifier chaos tests: crashed and lossy workers.

The broker invariant under test: EVERY submitted verification future
resolves — a worker crash costs at most one redelivery, never a lost or
hung future. Faults ride the seeded injector at the ``oop.deliver`` /
``oop.reply`` / ``net.send`` seams (docs/ROBUSTNESS.md).
"""
import time

import pytest

from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.testing.faults import FaultRule, inject
from corda_tpu.utils import retry
from corda_tpu.verifier.out_of_process import (
    OutOfProcessTransactionVerifierService, VerifierWorker)

from test_oop_verifier import make_ltx

pytestmark = pytest.mark.chaos

SEEDS = [7, 101, 9001]


@pytest.fixture
def bus():
    return InMemoryMessagingNetwork()


def test_send_failure_detaches_worker_immediately(bus):
    """A delivery send that RAISES is a live crash signal: the queue must
    detach the worker and redeal its share at once — one redelivery, not a
    redelivery-timeout wait (and with no timeout configured at all)."""
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    w1 = VerifierWorker(bus.create_node("w1"), "node")
    w2 = VerifierWorker(bus.create_node("w2"), "node")
    bus.run_network()
    assert svc.queue.worker_count == 2

    with inject(FaultRule("oop.deliver", "raise", detail="->w1")):
        futures = [svc.verify(make_ltx(i)) for i in range(10)]
        bus.run_network()
        for f in futures:
            assert f.result(timeout=1) is None

    assert svc.queue.worker_count == 1      # w1 detached on first failure
    assert w1.verified_count == 0
    assert w2.verified_count == 10


@pytest.mark.parametrize("seed", SEEDS)
def test_lost_delivery_recovered_by_redelivery_timeout(bus, seed):
    """A delivery that vanishes in flight (worker never sees it) leaves no
    crash signal — the redelivery-timeout scan is what recovers it."""
    node = bus.create_node("node")
    # timeout set on the queue directly: the scan is driven by hand below,
    # so the background scanner thread cannot race the manually pumped bus
    svc = OutOfProcessTransactionVerifierService(node)
    svc.queue.redelivery_timeout_s = 0.05
    try:
        VerifierWorker(bus.create_node("w1"), "node")
        w2 = VerifierWorker(bus.create_node("w2"), "node")
        bus.run_network()

        with inject(FaultRule("oop.deliver", "drop", detail="->w1",
                              count=1), seed=seed) as inj:
            fut = svc.verify(make_ltx(1))
            bus.run_network()
            if not fut.done():
                # the drop hit w1's deal: silence until the scan fires
                assert inj.fired("oop.deliver") == 1
                time.sleep(0.12)
                svc.queue.requeue_overdue()
                bus.run_network()
            assert fut.result(timeout=1) is None
        assert w2.verified_count >= svc.queue.worker_count - 1
    finally:
        svc.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_worker_crash_mid_batch_completes_every_future(bus, seed):
    """Worker crashes BETWEEN verifying and replying (all its replies are
    dropped): after the redelivery timeout its whole dealt share requeues
    onto the survivor and every one of the 20 futures resolves."""
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    svc.queue.redelivery_timeout_s = 0.05
    try:
        w1 = VerifierWorker(bus.create_node("w1"), "node")
        w2 = VerifierWorker(bus.create_node("w2"), "node")
        bus.run_network()

        with inject(FaultRule("oop.reply", "drop", detail="w1->*"),
                    seed=seed) as inj:
            futures = [svc.verify(make_ltx(i)) for i in range(20)]
            bus.run_network()
            # w1's ten replies all vanished (the drop fires before the
            # sent-reply counter, so its count stays 0 — a true crash)
            assert w1.verified_count == 0
            assert inj.fired("oop.reply") == 10
            assert sum(f.done() for f in futures) == 10
            w1.stop(announce=False)   # and now it is really gone

            time.sleep(0.12)
            svc.queue.requeue_overdue()
            bus.run_network()
            for f in futures:
                assert f.result(timeout=1) is None

        assert w2.verified_count == 20
        assert svc.queue.worker_count == 1
        assert svc.metrics.snapshot()["Verification.Success"]["count"] == 20
    finally:
        svc.shutdown()


def test_worker_hello_retries_through_transient_send_failure(bus):
    """The worker's attach handshake rides retry_call: two injected send
    failures must not keep it off the queue."""
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(node)
    before = retry.snapshot().get("Retry.Attempts.oop.hello",
                                  {}).get("count", 0)
    with inject(FaultRule("net.send", "raise", detail="w1->node", count=2)):
        worker = VerifierWorker(bus.create_node("w1"), "node")
        bus.run_network()
    assert svc.queue.worker_count == 1
    fut = svc.verify(make_ltx(1))
    bus.run_network()
    assert fut.result(timeout=1) is None
    assert worker.verified_count == 1
    snap = retry.snapshot()
    assert snap["Retry.Attempts.oop.hello"]["count"] - before == 3
