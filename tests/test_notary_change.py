"""Notary-change flow tests (NotaryChangeTests.kt analog): two-participant
state migrates from notary A to notary B with everyone's consent; tampered
proposals are refused."""
import pytest

from corda_tpu.core.contracts import Command, TransactionState
from corda_tpu.core.contracts.structures import StateAndRef, StateRef
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.flows.library import FinalityFlow
from corda_tpu.flows.state_replacement import (NotaryChangeFlow,
                                               StateReplacementException,
                                               install_notary_change_acceptor)
from corda_tpu.testing import DummyContract, DummyState, MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    notary_a = network.create_notary_node("O=Notary A, L=Zurich, C=CH")
    notary_b = network.create_notary_node("O=Notary B, L=Geneva, C=CH")
    alice = network.create_node("O=Alice, L=London, C=GB")
    bob = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()
    for node in (alice, bob):
        install_notary_change_acceptor(node.smm)
    return network, notary_a, notary_b, alice, bob


def issue_shared_state(network, alice, bob, notary):
    builder = TransactionBuilder(notary=notary.party)
    builder.add_output_state(DummyState(
        9, (alice.party.owning_key, bob.party.owning_key)))
    builder.add_command(DummyContract.Create(), alice.party.owning_key)
    wtx = builder.to_wire_transaction()
    stx = alice.services.sign_initial_transaction(wtx)
    fsm = alice.start_flow(FinalityFlow(stx))
    network.run_network()
    final = fsm.result_future.result(timeout=5)
    return StateAndRef(final.tx.outputs[0], StateRef(final.id, 0))


def test_notary_change_with_consent(net):
    network, notary_a, notary_b, alice, bob = net
    sref = issue_shared_state(network, alice, bob, notary_a)
    assert sref.state.notary == notary_a.party

    fsm = alice.start_flow(NotaryChangeFlow(sref, notary_b.party))
    network.run_network()
    new_ref = fsm.result_future.result(timeout=5)
    assert new_ref.state.notary == notary_b.party
    assert new_ref.state.data == sref.state.data
    # bob co-signed and got the final transaction
    final = alice.services.storage.get_transaction(new_ref.ref.txhash)
    assert bob.party.owning_key in {s.by for s in final.sigs}
    assert bob.services.storage.get_transaction(new_ref.ref.txhash) is not None
    # old notary consumed the old state: respending under A now conflicts
    from corda_tpu.flows.library import NotaryException, NotaryFlow
    builder = TransactionBuilder()
    builder.add_input_state(sref)
    builder.add_output_state(DummyState(9, (alice.party.owning_key,)))
    builder.add_command(DummyContract.Move(), alice.party.owning_key)
    stale = alice.services.sign_initial_transaction(builder.to_wire_transaction())
    fsm = alice.start_flow(NotaryFlow(stale))
    network.run_network()
    with pytest.raises(NotaryException):
        fsm.result_future.result(timeout=5)


def test_notary_change_to_same_notary_refused(net):
    network, notary_a, notary_b, alice, bob = net
    sref = issue_shared_state(network, alice, bob, notary_a)
    fsm = alice.start_flow(NotaryChangeFlow(sref, notary_a.party))
    network.run_network()
    with pytest.raises(StateReplacementException, match="same"):
        fsm.result_future.result(timeout=5)
