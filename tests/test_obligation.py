"""Obligation contract tests (ObligationTests.kt analogs), via the ledger
DSL and direct contract contexts: issue/move/settle/net/default rules."""
import datetime

import pytest

from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.core.contracts.exceptions import TransactionVerificationException
from corda_tpu.core.contracts.structures import (AuthenticatedObject, Issued,
                                                 PartyAndReference, TimeWindow)
from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.core.identity import Party
from corda_tpu.core.serialization import deserialize, serialize
from corda_tpu.core.serialization.codec import exact_epoch_micros
from corda_tpu.core.transactions.ledger import TransactionForContract
from corda_tpu.finance.cash import CashState
from corda_tpu.finance.cash import Move as CashMove
from corda_tpu.finance.obligation import (Lifecycle, Obligation,
                                          ObligationState, Terms)

BANK_KP = generate_keypair(entropy=b"\x81" * 32)
BANK = Party("O=Issuer Bank, L=London, C=GB", BANK_KP.public)
ALICE_KP = generate_keypair(entropy=b"\x82" * 32)
BOB_KP = generate_keypair(entropy=b"\x83" * 32)

NOW = datetime.datetime(2026, 7, 1, tzinfo=datetime.timezone.utc)
DUE = exact_epoch_micros(NOW + datetime.timedelta(days=10))
TOKEN = Issued(PartyAndReference(BANK, b"\x01"), USD)
TERMS = Terms(TOKEN, DUE)
OB = Obligation()


def ctx(inputs, outputs, commands, at=NOW):
    return TransactionForContract(
        inputs=tuple(inputs), outputs=tuple(outputs), attachments=(),
        commands=tuple(commands), id=SecureHash.sha256(b"ob-test"),
        notary=None,
        time_window=TimeWindow.with_tolerance(at, datetime.timedelta(seconds=5)))


def cmd(data, *keys):
    return AuthenticatedObject(tuple(keys), (), data)


def owe(obligor_kp, beneficiary_kp, qty, lifecycle=Lifecycle.NORMAL):
    return ObligationState(obligor_kp.public, TERMS, qty,
                           beneficiary_kp.public, lifecycle)


def test_issue_and_move():
    OB.verify(ctx([], [owe(ALICE_KP, BOB_KP, 1000)],
                  [cmd(Obligation.Issue(), ALICE_KP.public)]))
    # only the obligor can bind themself
    with pytest.raises(TransactionVerificationException, match="obligor"):
        OB.verify(ctx([], [owe(ALICE_KP, BOB_KP, 1000)],
                      [cmd(Obligation.Issue(), BOB_KP.public)]))
    # move to a new beneficiary needs the current one
    OB.verify(ctx([owe(ALICE_KP, BOB_KP, 1000)],
                  [owe(ALICE_KP, BANK_KP, 1000)],
                  [cmd(Obligation.Move(), BOB_KP.public)]))
    with pytest.raises(TransactionVerificationException, match="beneficiary"):
        OB.verify(ctx([owe(ALICE_KP, BOB_KP, 1000)],
                      [owe(ALICE_KP, BANK_KP, 1000)],
                      [cmd(Obligation.Move(), ALICE_KP.public)]))
    # a move may not change the obligor
    with pytest.raises(TransactionVerificationException, match="who owes"):
        OB.verify(ctx([owe(ALICE_KP, BOB_KP, 1000)],
                      [owe(BOB_KP, BOB_KP, 1000)],
                      [cmd(Obligation.Move(), BOB_KP.public)]))


def test_settlement_requires_payment():
    payment = CashState(Amount(400, TOKEN), BOB_KP.public)
    OB.verify(ctx([owe(ALICE_KP, BOB_KP, 1000)],
                  [owe(ALICE_KP, BOB_KP, 600), payment],
                  [cmd(Obligation.Settle(400), ALICE_KP.public),
                   cmd(CashMove(), ALICE_KP.public)]))
    # settling without the cash leg fails
    with pytest.raises(TransactionVerificationException, match="pay"):
        OB.verify(ctx([owe(ALICE_KP, BOB_KP, 1000)],
                      [owe(ALICE_KP, BOB_KP, 600)],
                      [cmd(Obligation.Settle(400), ALICE_KP.public)]))
    # amounts must balance
    with pytest.raises(TransactionVerificationException, match="balance"):
        OB.verify(ctx([owe(ALICE_KP, BOB_KP, 1000)],
                      [owe(ALICE_KP, BOB_KP, 700), payment],
                      [cmd(Obligation.Settle(400), ALICE_KP.public),
                       cmd(CashMove(), ALICE_KP.public)]))


def test_bilateral_netting():
    a_owes_b = owe(ALICE_KP, BOB_KP, 1000)
    b_owes_a = owe(BOB_KP, ALICE_KP, 700)
    netted = owe(ALICE_KP, BOB_KP, 300)
    OB.verify(ctx([a_owes_b, b_owes_a], [netted],
                  [cmd(Obligation.Net(), ALICE_KP.public, BOB_KP.public)]))
    # value-destroying net is rejected
    with pytest.raises(TransactionVerificationException, match="net position"):
        OB.verify(ctx([a_owes_b, b_owes_a], [owe(ALICE_KP, BOB_KP, 200)],
                      [cmd(Obligation.Net(), ALICE_KP.public, BOB_KP.public)]))
    # everyone involved must sign
    with pytest.raises(TransactionVerificationException, match="every party"):
        OB.verify(ctx([a_owes_b, b_owes_a], [netted],
                      [cmd(Obligation.Net(), ALICE_KP.public)]))


CHARLIE_KP = generate_keypair(entropy=b"\x84" * 32)
DAVE_KP = generate_keypair(entropy=b"\x85" * 32)


def test_issue_cannot_destroy_other_claims():
    """Attack: an Issue consuming someone else's claim while growing the
    aggregate — per-claim accounting must reject it."""
    with pytest.raises(TransactionVerificationException, match="reduce"):
        OB.verify(ctx([owe(ALICE_KP, BOB_KP, 1000)],
                      [owe(CHARLIE_KP, DAVE_KP, 1001)],
                      [cmd(Obligation.Issue(), CHARLIE_KP.public)]))


def test_net_cannot_fabricate_zero_sum_debt():
    """Attack: netting nothing into two offsetting fabricated obligations —
    the bound parties never signed."""
    with pytest.raises(TransactionVerificationException, match="every party"):
        OB.verify(ctx([], [owe(ALICE_KP, CHARLIE_KP, 500),
                           owe(CHARLIE_KP, ALICE_KP, 500)],
                      [cmd(Obligation.Net(), ALICE_KP.public)]))
    # with both signatures it is allowed (a legitimate bilateral setup)
    OB.verify(ctx([], [owe(ALICE_KP, CHARLIE_KP, 500),
                       owe(CHARLIE_KP, ALICE_KP, 500)],
                  [cmd(Obligation.Net(), ALICE_KP.public, CHARLIE_KP.public)]))


def test_settle_cannot_redirect_remainder():
    """Attack: settle 400 but replace the remaining 600 claim with an
    unrelated pair — outputs creating new claims are rejected."""
    payment = CashState(Amount(400, TOKEN), BOB_KP.public)
    # rejected by the global cash-adequacy check (Bob's claim dropped 1000,
    # only 400 paid) — and the per-claim clause would catch it after that
    with pytest.raises(TransactionVerificationException,
                       match="new claims|pay the beneficiary"):
        OB.verify(ctx([owe(ALICE_KP, BOB_KP, 1000)],
                      [owe(CHARLIE_KP, DAVE_KP, 600), payment],
                      [cmd(Obligation.Settle(400), ALICE_KP.public),
                       cmd(CashMove(), ALICE_KP.public)]))


def test_move_cannot_flip_lifecycle():
    """Attack: a Move that also flips to DEFAULTED before the due time."""
    with pytest.raises(TransactionVerificationException, match="lifecycle"):
        OB.verify(ctx([owe(ALICE_KP, BOB_KP, 1000)],
                      [owe(ALICE_KP, BOB_KP, 1000, Lifecycle.DEFAULTED)],
                      [cmd(Obligation.Move(), BOB_KP.public)]))


def test_settle_cash_not_double_counted_across_groups():
    """Attack: one 400 cash payment claimed against two obligation groups
    (same product, different due dates) — the global adequacy check catches
    the shortfall."""
    terms2 = Terms(TOKEN, DUE + 1)
    ob1 = owe(ALICE_KP, BOB_KP, 400)
    ob2 = ObligationState(ALICE_KP.public, terms2, 400, BOB_KP.public)
    payment = CashState(Amount(400, TOKEN), BOB_KP.public)
    with pytest.raises(TransactionVerificationException, match="paid"):
        OB.verify(ctx([ob1, ob2], [payment],
                      [cmd(Obligation.Settle(400), ALICE_KP.public),
                       cmd(Obligation.Settle(400), ALICE_KP.public),
                       cmd(CashMove(), ALICE_KP.public)]))


def test_multi_beneficiary_settlement_accepted():
    """Two creditors fully paid in one transaction must verify (the old
    per-input total check wrongly rejected this)."""
    ob_bob = owe(ALICE_KP, BOB_KP, 400)
    ob_carol = owe(ALICE_KP, CHARLIE_KP, 400)
    pay_bob = CashState(Amount(400, TOKEN), BOB_KP.public)
    pay_carol = CashState(Amount(400, TOKEN), CHARLIE_KP.public)
    OB.verify(ctx([ob_bob, ob_carol], [pay_bob, pay_carol],
                  [cmd(Obligation.Settle(800), ALICE_KP.public),
                   cmd(CashMove(), ALICE_KP.public)]))


def test_default_lifecycle():
    after_due = NOW + datetime.timedelta(days=11)
    normal = owe(ALICE_KP, BOB_KP, 1000)
    defaulted = owe(ALICE_KP, BOB_KP, 1000, Lifecycle.DEFAULTED)
    OB.verify(ctx([normal], [defaulted],
                  [cmd(Obligation.SetLifecycle(Lifecycle.DEFAULTED),
                       BOB_KP.public)], at=after_due))
    # cannot default early
    with pytest.raises(TransactionVerificationException, match="before"):
        OB.verify(ctx([normal], [defaulted],
                      [cmd(Obligation.SetLifecycle(Lifecycle.DEFAULTED),
                           BOB_KP.public)], at=NOW))
    # serialization roundtrip incl. the enum lifecycle
    assert deserialize(serialize(defaulted)) == defaulted
