"""Host crypto layer tests.

Mirrors the reference's crypto unit tests (CryptoUtilsTest, CompositeKeyTests,
PartialMerkleTreeTest — SURVEY.md §4 tier 1), using the `cryptography` library as an
independent interop oracle for Ed25519/ECDSA.
"""
import hashlib

import pytest

from corda_tpu.core.crypto import (
    SecureHash, b58encode, b58decode, generate_keypair, Crypto,
    EDDSA_ED25519_SHA512, ECDSA_SECP256K1_SHA256, ECDSA_SECP256R1_SHA256,
    CompositeKey, MerkleTree, PartialMerkleTree, MerkleTreeException,
)


def test_secure_hash_basics():
    h = SecureHash.sha256(b"abc")
    assert h.bytes == hashlib.sha256(b"abc").digest()
    assert SecureHash.sha256_twice(b"abc").bytes == hashlib.sha256(
        hashlib.sha256(b"abc").digest()).digest()
    assert SecureHash.parse(h.hex()) == h
    assert SecureHash.zero_hash().bytes == b"\x00" * 32
    with pytest.raises(ValueError):
        SecureHash(b"\x00" * 31)
    # hash_concat is a SINGLE sha256 of the concatenation (SecureHash.kt:36).
    a, b = SecureHash.sha256(b"a"), SecureHash.sha256(b"b")
    assert a.hash_concat(b).bytes == hashlib.sha256(a.bytes + b.bytes).digest()


def test_base58_roundtrip():
    for data in [b"", b"\x00", b"\x00\x00hello", b"corda-tpu", bytes(range(256))]:
        assert b58decode(b58encode(data)) == data
    assert b58encode(b"\x00\x01") == "12"
    with pytest.raises(ValueError):
        b58decode("0OIl")


@pytest.mark.parametrize("scheme", [EDDSA_ED25519_SHA512, ECDSA_SECP256K1_SHA256,
                                    ECDSA_SECP256R1_SHA256])
def test_sign_verify_roundtrip(scheme):
    kp = generate_keypair(scheme, entropy=bytes([7] * 32))
    msg = b"the quick brown fox"
    sig = Crypto.sign_with_key(kp, msg)
    assert sig.is_valid(msg)
    assert sig.verify(msg)
    assert not sig.is_valid(msg + b"!")
    # Tampered signature fails (flip a bit mid-signature).
    bad = bytearray(sig.bytes)
    bad[10] ^= 1
    from corda_tpu.core.crypto.signatures import DigitalSignatureWithKey
    assert not DigitalSignatureWithKey(bytes(bad), kp.public).is_valid(msg)


def test_ed25519_interop_with_cryptography():
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey, Ed25519PublicKey)
    from cryptography.hazmat.primitives import serialization
    seed = bytes(range(32))
    kp = generate_keypair(EDDSA_ED25519_SHA512, entropy=seed)
    oracle = Ed25519PrivateKey.from_private_bytes(seed)
    oracle_pub = oracle.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw)
    assert kp.public.encoded == oracle_pub
    msg = b"interop message"
    ours = Crypto.sign_with_key(kp, msg)
    # Ed25519 is deterministic: signatures must match byte-for-byte.
    assert ours.bytes == oracle.sign(msg)
    # And their signature verifies under our implementation.
    from corda_tpu.core.crypto.signatures import DigitalSignatureWithKey
    assert DigitalSignatureWithKey(oracle.sign(msg), kp.public).is_valid(msg)


@pytest.mark.parametrize("scheme,curve_name", [(ECDSA_SECP256K1_SHA256, "SECP256K1"),
                                               (ECDSA_SECP256R1_SHA256, "SECP256R1")])
def test_ecdsa_interop_with_cryptography(scheme, curve_name):
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives import hashes, serialization
    from corda_tpu.core.crypto.signatures import DigitalSignatureWithKey
    from corda_tpu.core.crypto.keys import sec1_decompress, curve_for_scheme

    msg = b"ecdsa interop"
    # Their key, their signature -> our verify.
    curve = {"SECP256K1": ec.SECP256K1(), "SECP256R1": ec.SECP256R1()}[curve_name]
    oracle = ec.generate_private_key(curve)
    der_sig = oracle.sign(msg, ec.ECDSA(hashes.SHA256()))
    # Our verifier enforces low-s canonical signatures; normalise the oracle's.
    from corda_tpu.core.crypto.ecmath import (ecdsa_sig_from_der, ecdsa_sig_to_der)
    from corda_tpu.core.crypto.keys import curve_for_scheme as _cfs
    _r, _s = ecdsa_sig_from_der(der_sig)
    _n = _cfs(scheme).n
    if _s > _n // 2:
        _s = _n - _s
    der_sig = ecdsa_sig_to_der(_r, _s)
    pub_compressed = oracle.public_key().public_bytes(
        serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint)
    from corda_tpu.core.crypto.keys import PublicKey
    our_view = PublicKey(scheme, pub_compressed)
    assert DigitalSignatureWithKey(der_sig, our_view).is_valid(msg)
    # Our key, our signature -> their verify.
    kp = generate_keypair(scheme, entropy=bytes([3] * 32))
    sig = Crypto.sign_with_key(kp, msg)
    pt = sec1_decompress(curve_for_scheme(scheme), kp.public.encoded)
    nums = ec.EllipticCurvePublicNumbers(pt[0], pt[1], curve)
    nums.public_key().verify(sig.bytes, msg, ec.ECDSA(hashes.SHA256()))  # raises if bad


def test_composite_key_thresholds():
    a = generate_keypair(EDDSA_ED25519_SHA512, entropy=bytes([1] * 32)).public
    b = generate_keypair(EDDSA_ED25519_SHA512, entropy=bytes([2] * 32)).public
    c = generate_keypair(ECDSA_SECP256K1_SHA256, entropy=bytes([3] * 32)).public
    # 2-of-3
    key = CompositeKey.Builder().add_keys(a, b, c).build(threshold=2)
    assert isinstance(key, CompositeKey)
    assert not key.is_fulfilled_by(a)
    assert key.is_fulfilled_by({a, b})
    assert key.is_fulfilled_by({a, c})
    assert key.keys == frozenset({a, b, c})
    # weighted: a has weight 2, alone reaches threshold 2
    wkey = CompositeKey.Builder().add_key(a, 2).add_key(b, 1).build(threshold=2)
    assert wkey.is_fulfilled_by(a)
    assert not wkey.is_fulfilled_by(b)
    # nested
    nested = CompositeKey.Builder().add_key(key, 1).add_key(c, 1).build(threshold=2)
    assert nested.is_fulfilled_by({a, b, c})
    assert not nested.is_fulfilled_by({a, b})  # key fulfilled but c missing
    # builder collapses single child
    assert CompositeKey.Builder().add_key(a).build() == a
    # duplicates rejected
    with pytest.raises(ValueError):
        CompositeKey.Builder().add_keys(a, a).build(threshold=1)
    # encode/decode roundtrip
    assert CompositeKey.decode(nested.encoded) == nested
    # plain-key fulfilment API
    assert a.is_fulfilled_by({a, b})
    assert not a.is_fulfilled_by({b})


def test_merkle_tree_reference_semantics():
    leaves = [SecureHash.sha256(bytes([i])) for i in range(5)]
    tree = MerkleTree.get_merkle_tree(leaves)
    # 5 leaves pad to 8: manual recomputation.
    import hashlib as H
    padded = [h.bytes for h in leaves] + [b"\x00" * 32] * 3

    def combine(xs):
        return [H.sha256(xs[i] + xs[i + 1]).digest() for i in range(0, len(xs), 2)]

    lvl = padded
    while len(lvl) > 1:
        lvl = combine(lvl)
    assert tree.hash.bytes == lvl[0]
    with pytest.raises(MerkleTreeException):
        MerkleTree.get_merkle_tree([])
    # single leaf -> root is the leaf
    single = MerkleTree.get_merkle_tree([leaves[0]])
    assert single.hash == leaves[0]


def test_partial_merkle_tree():
    leaves = [SecureHash.sha256(bytes([i])) for i in range(7)]
    tree = MerkleTree.get_merkle_tree(leaves)
    include = [leaves[1], leaves[4]]
    pmt = PartialMerkleTree.build(tree, include)
    assert pmt.verify(tree.hash, include)
    # wrong root fails
    assert not pmt.verify(SecureHash.sha256(b"x"), include)
    # claiming a non-included hash fails
    assert not pmt.verify(tree.hash, [leaves[0]])
    # subset claim fails (must match exactly)
    assert not pmt.verify(tree.hash, [leaves[1]])
    # building with a hash not in the tree fails
    with pytest.raises(MerkleTreeException):
        PartialMerkleTree.build(tree, [SecureHash.sha256(b"nope")])
