"""Finance completeness tail + CSR enrolment (VERDICT r2 #10):
CommodityContract, TwoPartyDealFlow, ManualFinalityFlow, doorman
registration.

Reference analogs: CommodityContract.kt (fungible commodity claims),
TwoPartyDealFlow.kt (generic deal entry), core ManualFinalityFlow,
NetworkRegistrationHelper.kt:1-148.
"""
import pytest

from corda_tpu.core.contracts.amount import Amount
from corda_tpu.core.contracts.exceptions import (
    TransactionVerificationException)
from corda_tpu.core.contracts.structures import PartyAndReference
from corda_tpu.core.transactions.builder import TransactionBuilder
from corda_tpu.finance.commodity import (Commodity, CommodityContract,
                                         CommodityState)
from corda_tpu.testing import MockNetwork

FCOJ = Commodity("FCOJ", "Frozen concentrated orange juice")


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    alice = network.create_node("O=Alice, L=London, C=GB")
    bob = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()
    return network, notary, alice, bob


# -- CommodityContract -------------------------------------------------------

def _issue_commodity(alice, notary, quantity=1000, owner=None):
    issuer = PartyAndReference(alice.party, b"\x01")
    builder = TransactionBuilder(notary=notary.party)
    CommodityContract.generate_issue(
        builder, Amount(quantity, FCOJ), issuer,
        (owner or alice.party).owning_key, notary.party)
    builder.sign_with(
        alice.services.key_management.key_pair(alice.party.owning_key))
    return builder.to_signed_transaction(check_sufficient_signatures=False)


def test_commodity_issue_and_move(net):
    network, notary, alice, bob = net
    stx = _issue_commodity(alice, notary)
    stx.to_ledger_transaction(alice.services).verify()
    alice.services.record_transactions(stx)
    sar = alice.services.vault.unconsumed_states(CommodityState)[0]
    assert sar.state.data.amount.quantity == 1000
    assert str(sar.state.data.amount.token.product) == "FCOJ"

    builder = TransactionBuilder(notary=notary.party)
    CommodityContract.generate_move(builder, sar, bob.party.owning_key)
    builder.sign_with(
        alice.services.key_management.key_pair(alice.party.owning_key))
    mv = builder.to_signed_transaction(check_sufficient_signatures=False)
    mv.to_ledger_transaction(alice.services).verify()


def test_commodity_conservation_enforced(net):
    network, notary, alice, bob = net
    stx = _issue_commodity(alice, notary)
    alice.services.record_transactions(stx)
    sar = alice.services.vault.unconsumed_states(CommodityState)[0]
    from corda_tpu.core.contracts.structures import Issued
    from corda_tpu.finance.commodity import Move
    from corda_tpu.core.contracts.structures import Command

    builder = TransactionBuilder(notary=notary.party)
    builder.add_input_state(sar)
    inflated = Amount(2000, sar.state.data.amount.token)
    builder.add_output_state(CommodityState(inflated, bob.party.owning_key),
                             notary.party)
    builder.add_command(Command(Move(), (alice.party.owning_key,)))
    ltx = builder.to_wire_transaction().to_ledger_transaction(alice.services)
    with pytest.raises(TransactionVerificationException,
                       match="not conserved"):
        ltx.verify()


def test_commodity_issue_requires_issuer_signature(net):
    network, notary, alice, bob = net
    from corda_tpu.core.contracts.structures import Command, Issued
    from corda_tpu.finance.commodity import Issue

    issuer = PartyAndReference(alice.party, b"\x01")
    builder = TransactionBuilder(notary=notary.party)
    issued = Amount(100, Issued(issuer, FCOJ))
    builder.add_output_state(CommodityState(issued, alice.party.owning_key),
                             notary.party)
    builder.add_command(Command(Issue(), (bob.party.owning_key,)))  # wrong
    ltx = builder.to_wire_transaction().to_ledger_transaction(alice.services)
    with pytest.raises(TransactionVerificationException,
                       match="signed by the issuer"):
        ltx.verify()


def test_mixed_cash_and_commodity_transaction(net):
    """Review r3: cash and commodity command types are INDEPENDENT — a
    delivery-vs-payment transaction mixing both assets must verify, with
    each contract seeing only its own commands."""
    from corda_tpu.core.contracts.structures import Command, Issued
    from corda_tpu.finance.cash import Cash
    from corda_tpu.finance.commodity import Issue as CommodityIssue

    network, notary, alice, bob = net
    issuer = PartyAndReference(alice.party, b"\x01")
    builder = TransactionBuilder(notary=notary.party)
    # leg 1: commodity issuance to bob
    CommodityContract.generate_issue(
        builder, Amount(500, FCOJ), issuer, bob.party.owning_key,
        notary.party)
    # leg 2: cash issuance to alice in the SAME transaction
    from corda_tpu.core.contracts.amount import USD
    Cash.generate_issue(builder, Amount(10000, USD), issuer,
                        alice.party.owning_key, notary.party)
    ltx = builder.to_wire_transaction().to_ledger_transaction(alice.services)
    ltx.verify()   # must not cross-contaminate conservation checks


# -- TwoPartyDealFlow --------------------------------------------------------

def test_two_party_deal_flow(net):
    """Generic deal entry: the acceptor assembles a commodity issuance deal
    requiring BOTH signatures; collect + finalise; the instigator gets the
    finalised tx after ledger commit."""
    from corda_tpu.core.contracts.structures import Command
    from corda_tpu.finance.commodity import Issue, Move
    from corda_tpu.finance.deal import Handshake, TwoPartyDealFlow
    from corda_tpu.flows.api import flow_name
    from corda_tpu.flows.library import SignTransactionFlow, CollectSignaturesFlow

    network, notary, alice, bob = net

    class SellCommodity(TwoPartyDealFlow.Secondary):
        def validate_handshake(self, handshake):
            if handshake.payload["qty"] > 5000:
                from corda_tpu.flows.api import FlowException
                raise FlowException("too big")

        def assemble_shared_tx(self, handshake):
            hub = self.service_hub
            me = hub.my_info.legal_identity
            issuer = PartyAndReference(me, b"\x02")
            builder = TransactionBuilder(notary=notary.party)
            from corda_tpu.core.contracts.structures import Issued
            issued = Amount(handshake.payload["qty"], Issued(issuer, FCOJ))
            builder.add_output_state(
                CommodityState(issued,
                               handshake.primary_identity.owning_key),
                notary.party)
            # the deal requires both parties' signatures
            builder.add_command(Command(
                Issue(), (me.owning_key,
                          handshake.primary_identity.owning_key)))
            builder.sign_with(hub.key_management.key_pair(me.owning_key))
            return builder.to_signed_transaction(
                check_sufficient_signatures=False)

    # registrations: bob answers the Primary's handshake; alice answers
    # bob's signature collection
    bob.smm.register_flow_factory(flow_name(TwoPartyDealFlow.Primary),
                                  SellCommodity)
    alice.smm.register_flow_factory(flow_name(CollectSignaturesFlow),
                                    SignTransactionFlow)

    fsm = alice.start_flow(TwoPartyDealFlow.Primary(bob.party, {"qty": 500}))
    network.run_network()
    stx = fsm.result_future.result(timeout=1)
    keys = {s.by for s in stx.sigs}
    assert alice.party.owning_key in keys and bob.party.owning_key in keys
    assert alice.services.vault.unconsumed_states(CommodityState)


# -- ManualFinalityFlow ------------------------------------------------------

def test_manual_finality_broadcasts_only_named_recipients(net):
    from corda_tpu.flows.library import ManualFinalityFlow

    network, notary, alice, bob = net
    stx = _issue_commodity(alice, notary, owner=bob.party)
    # participant derivation would broadcast to bob; Manual names NOBODY
    fsm = alice.start_flow(ManualFinalityFlow(stx, []))
    network.run_network()
    fsm.result_future.result(timeout=1)
    assert bob.services.storage.get_transaction(stx.id) is None
    # and with bob named explicitly, he receives it
    stx2 = _issue_commodity(alice, notary, quantity=700, owner=bob.party)
    fsm = alice.start_flow(ManualFinalityFlow(stx2, [bob.party]))
    network.run_network()
    fsm.result_future.result(timeout=1)
    assert bob.services.storage.get_transaction(stx2.id) is not None


# -- durable fresh keys (review r3) ------------------------------------------

def test_fresh_keys_survive_restart(tmp_path):
    """Confidential-identity keys persist: a KeyManagementService reloaded
    from its store still owns (and can sign for) pre-crash fresh keys, so
    vault replay keeps the states they own."""
    from corda_tpu.node.services import KeyManagementService

    store = str(tmp_path / "fresh-keys.jsonl")
    kms = KeyManagementService(store_path=store)
    kp = kms.fresh_key()
    sig = kms.sign(b"content", kp.public)
    reloaded = KeyManagementService(store_path=store)
    assert kp.public in reloaded.keys
    assert reloaded.sign(b"content", kp.public).bytes == sig.bytes


def test_broadcast_reaches_later_recipients_past_a_dead_one(net):
    """Review r3: one unreachable recipient must not starve the rest — all
    deliveries are attempted, then the undelivered set surfaces as one
    error naming the final transaction."""
    from corda_tpu.flows.api import FlowException
    from corda_tpu.flows.library import BroadcastTransactionFlow

    network, notary, alice, bob = net
    carol = network.create_node("O=Carol, L=Rome, C=IT")
    network.start_nodes()
    stx = _issue_commodity(alice, notary, owner=bob.party)
    alice.services.record_transactions(stx)
    # bob's endpoint drops everything (dead); carol is fine
    network.bus.transfer_filter = \
        lambda t: "Bob" not in t.recipient and "Bob" not in t.sender
    fsm = alice.start_flow(
        BroadcastTransactionFlow(stx, [bob.party, carol.party]))
    network.run_network()
    # the transport notices bob is gone (the TCP plane's on_send_failure →
    # smm.on_peer_unreachable); the broadcast moves on to carol
    alice.smm.on_peer_unreachable(str(bob.party.name))
    for _ in range(40):
        network.run_network()
        if fsm.result_future.done():
            break
    # carol received it even though bob never acked
    assert carol.services.storage.get_transaction(stx.id) is not None
    with pytest.raises(FlowException, match="FINAL but could not"):
        fsm.result_future.result(timeout=1)


# -- CSR enrolment -----------------------------------------------------------

def test_registration_auto_approval(tmp_path):
    from corda_tpu.network.registration import (DoormanService,
                                                NetworkRegistrationHelper)
    from corda_tpu.network.tls import TlsConfig

    doorman = DoormanService(str(tmp_path / "network-ca"))
    helper = NetworkRegistrationHelper(
        str(tmp_path / "node"), "O=Enrolled, L=Oslo, C=NO", doorman)
    cert_path, key_path = helper.register()
    import os
    assert os.path.exists(cert_path) and os.path.exists(key_path)
    # idempotent
    assert helper.register() == (cert_path, key_path)
    # the installed chain is usable by the transport exactly like dev certs
    from corda_tpu.network.tls import _context
    ca = str(tmp_path / "node" / "tls-ca.crt")
    _context("server", ca, cert_path, key_path)


def test_registration_manual_approval_and_rejections(tmp_path):
    import threading
    from corda_tpu.network.registration import (DoormanService,
                                                NetworkRegistrationHelper,
                                                RegistrationError, build_csr)

    doorman = DoormanService(str(tmp_path / "ca"), auto_approve=False)
    helper = NetworkRegistrationHelper(
        str(tmp_path / "node"), "O=Slow, L=Oslo, C=NO", doorman,
        poll_interval_s=0.05, max_polls=40)
    # approve from "the operator" while the helper polls
    def approve_soon():
        import time
        time.sleep(0.3)
        (request_id,) = list(doorman._pending)
        doorman.approve(request_id)
    threading.Thread(target=approve_soon, daemon=True).start()
    cert_path, _ = helper.register()
    import os
    assert os.path.exists(cert_path)

    # duplicate name refused
    from cryptography.hazmat.primitives.asymmetric import ec
    with pytest.raises(RegistrationError, match="already issued"):
        doorman.submit_request(build_csr(
            "O=Slow, L=Oslo, C=NO", ec.generate_private_key(ec.SECP256R1())))
    # garbage refused
    with pytest.raises(RegistrationError, match="malformed"):
        doorman.submit_request(b"not a csr")


def test_registration_survives_doorman_restart_and_crash_windows(tmp_path):
    """Review r3: a poll timeout, a crash between submit and persist, or a
    doorman restart must never strand the name — submission is idempotent
    per (name, key) and a stale request id restarts enrolment."""
    from corda_tpu.network.registration import (DoormanService,
                                                NetworkRegistrationHelper,
                                                RegistrationError)
    import os

    # timeout, then resume with the SAME pending request on a later call
    doorman = DoormanService(str(tmp_path / "ca"), auto_approve=False)
    helper = NetworkRegistrationHelper(
        str(tmp_path / "node"), "O=R, L=Oslo, C=NO", doorman,
        poll_interval_s=0.01, max_polls=2)
    with pytest.raises(RegistrationError, match="not signed"):
        helper.register()
    assert os.path.exists(str(tmp_path / "node" / "enrolment-request.json"))
    (request_id,) = list(doorman._pending)
    doorman.approve(request_id)              # late operator approval
    cert_path, _ = helper.register()         # resumes, installs
    assert os.path.exists(cert_path)
    assert not os.path.exists(
        str(tmp_path / "node" / "enrolment-request.json"))

    # doorman restart (in-memory state lost): a fresh helper re-enrols
    doorman2 = DoormanService(str(tmp_path / "ca"), auto_approve=False)
    helper2 = NetworkRegistrationHelper(
        str(tmp_path / "node2"), "O=R2, L=Oslo, C=NO", doorman2,
        poll_interval_s=0.01, max_polls=2)
    with pytest.raises(RegistrationError, match="not signed"):
        helper2.register()
    doorman3 = DoormanService(str(tmp_path / "ca"), auto_approve=True)
    helper2.doorman = doorman3               # the restarted doorman
    cert2, _ = helper2.register()            # stale id -> fresh enrolment
    assert os.path.exists(cert2)


def test_registration_timeout_when_never_approved(tmp_path):
    from corda_tpu.network.registration import (DoormanService,
                                                NetworkRegistrationHelper,
                                                RegistrationError)
    doorman = DoormanService(str(tmp_path / "ca"), auto_approve=False)
    helper = NetworkRegistrationHelper(
        str(tmp_path / "node"), "O=Never, L=Oslo, C=NO", doorman,
        poll_interval_s=0.01, max_polls=3)
    with pytest.raises(RegistrationError, match="not signed"):
        helper.register()
