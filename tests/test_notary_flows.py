"""End-to-end ledger flows over MockNetwork: finality, notarisation,
double-spend rejection, dependency resolution, signature collection.

Reference analogs: NotaryServiceTests / NotaryFlow tests, FinalityFlow usage
in TwoPartyTradeFlowTests, ResolveTransactionsFlowTest, CollectSignaturesFlowTests.
"""
import pytest

from corda_tpu.core.contracts import Command, StateAndRef, StateRef
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.flows import FlowException
from corda_tpu.flows.library import (CollectSignaturesFlow, FinalityFlow,
                                     NotaryException, NotaryFlow,
                                     SignTransactionFlow, install_core_flows)
from corda_tpu.flows.api import flow_name
from corda_tpu.testing import DummyContract, DummyState, MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    alice = network.create_node("O=Alice, L=London, C=GB")
    bob = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()
    return network, notary, alice, bob


def issue_state(network, node, notary, magic=1):
    """Self-issue a DummyState and finalise it (no inputs → no notarisation)."""
    builder = TransactionBuilder(notary=notary.party)
    builder.add_output_state(DummyState(magic, (node.party.owning_key,)))
    builder.add_command(DummyContract.Create(), node.party.owning_key)
    wtx = builder.to_wire_transaction()
    stx = node.services.sign_initial_transaction(wtx)
    fsm = node.start_flow(FinalityFlow(stx))
    network.run_network()
    final = fsm.result_future.result(timeout=1)
    return final, StateAndRef(final.tx.outputs[0], StateRef(final.id, 0))


def move_state(node, state_and_ref, new_owner_key):
    builder = TransactionBuilder()
    builder.add_input_state(state_and_ref)
    builder.add_output_state(DummyState(
        state_and_ref.state.data.magic_number, (new_owner_key,)))
    builder.add_command(DummyContract.Move(), node.party.owning_key)
    wtx = builder.to_wire_transaction()
    return node.services.sign_initial_transaction(wtx)


def test_issue_and_notarised_move(net):
    network, notary, alice, bob = net
    _, sref = issue_state(network, alice, notary)
    stx = move_state(alice, sref, bob.party.owning_key)
    fsm = alice.start_flow(FinalityFlow(stx))
    network.run_network()
    final = fsm.result_future.result(timeout=1)
    # notary signature attached
    assert any(s.by == notary.party.owning_key for s in final.sigs)
    final.verify_signatures()
    # Bob resolved the dependency chain and recorded both transactions
    assert bob.services.storage.get_transaction(final.id) is not None
    assert bob.services.storage.get_transaction(sref.ref.txhash) is not None


def test_double_spend_rejected(net):
    network, notary, alice, bob = net
    _, sref = issue_state(network, alice, notary)
    stx1 = move_state(alice, sref, bob.party.owning_key)
    fsm1 = alice.start_flow(FinalityFlow(stx1))
    network.run_network()
    fsm1.result_future.result(timeout=1)

    stx2 = move_state(alice, sref, alice.party.owning_key)
    fsm2 = alice.start_flow(NotaryFlow(stx2))
    network.run_network()
    with pytest.raises(NotaryException, match="already consumed"):
        fsm2.result_future.result(timeout=1)


def test_collect_signatures(net):
    network, notary, alice, bob = net
    # a transaction requiring BOTH alice's and bob's signatures
    builder = TransactionBuilder(notary=notary.party)
    builder.add_output_state(DummyState(
        5, (alice.party.owning_key, bob.party.owning_key)))
    builder.add_command(DummyContract.Create(),
                       alice.party.owning_key, bob.party.owning_key)
    wtx = builder.to_wire_transaction()
    stx = alice.services.sign_initial_transaction(wtx)
    # bob auto-signs (register the abstract responder with no extra checks)
    bob.smm.register_flow_factory(flow_name(CollectSignaturesFlow),
                                  SignTransactionFlow)
    fsm = alice.start_flow(CollectSignaturesFlow(stx))
    network.run_network()
    full = fsm.result_future.result(timeout=1)
    assert {s.by for s in full.sigs} == {alice.party.owning_key,
                                         bob.party.owning_key}
    full.verify_signatures()
