"""Observability subsystem: histogram percentile math, span tracer +
explicit context propagation across the batcher's threads, the disabled
(no-op) fast path, and bench.py's per-stage percentile flattening."""
import json
import threading

import pytest

from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.schemes import ECDSA_SECP256K1_SHA256
from corda_tpu.core.crypto.signatures import Crypto
from corda_tpu.observability import (NOOP_SPAN, NOOP_TRACER, SpanRing,
                                     Tracer, disable_tracing, enable_tracing,
                                     get_tracer, stage_percentiles)
from corda_tpu.utils.metrics import Histogram, MetricRegistry
from corda_tpu.verifier.batcher import SignatureBatcher

KP = generate_keypair(ECDSA_SECP256K1_SHA256, entropy=b"\x61" * 32)
CONTENT = b"observability content"
SIG = Crypto.sign_with_key(KP, CONTENT).bytes


@pytest.fixture(autouse=True)
def _noop_after():
    yield
    disable_tracing()


# ---------------------------------------------------------------------------
# Histogram
# ---------------------------------------------------------------------------

def test_histogram_percentiles_within_bucket_resolution():
    h = Histogram()
    values = [0.001 * i for i in range(1, 101)]   # 1ms .. 100ms
    for v in values:
        h.update(v)
    # fixed log buckets: estimate within one quarter-decade (x1.78) of truth
    for q, want in ((0.50, 0.050), (0.90, 0.090), (0.99, 0.099)):
        got = h.quantile(q)
        assert want / 1.79 <= got <= want * 1.79, (q, got, want)
    assert h.quantile(1.0) <= h.max_value
    fields = h.snapshot_fields()
    assert fields["count"] == 100
    assert fields["max"] == pytest.approx(0.1)
    assert fields["mean"] == pytest.approx(sum(values) / 100)
    assert fields["p50"] <= fields["p90"] <= fields["p99"] <= fields["max"]


def test_histogram_empty_and_single_sample():
    h = Histogram()
    assert h.quantile(0.5) == 0.0
    assert h.snapshot_fields()["count"] == 0
    h.update(0.25)
    # one sample: every quantile clamps to the observed max exactly
    assert h.quantile(0.5) == 0.25
    assert h.quantile(0.99) == 0.25


def test_histogram_interpolates_within_bucket_boundaries():
    """Regression pin: quantile() used to snap to the bucket's UPPER edge,
    so 95 identical 2.0s samples reported p50 = 3.162 (the quarter-decade
    bound above 2.0) — a +58% tail overstatement at every bucket boundary.
    Linear interpolation inside the bucket keeps the estimate near the
    mass."""
    h = Histogram()
    for _ in range(95):
        h.update(2.0)
    for _ in range(5):
        h.update(1000.0)
    p50 = h.quantile(0.50)
    # 2.0 lives in bucket (1.778, 3.162]; rank 50 of the 95 samples there
    # interpolates to ~2.51 — strictly inside, never the 3.162 edge
    assert 1.778 < p50 < 3.0
    assert p50 == pytest.approx(2.507, rel=0.01)
    # the tail quantile still never exceeds the observed max
    assert 500.0 < h.quantile(0.99) <= 1000.0
    assert h.quantile(0.50) <= h.quantile(0.90) <= h.quantile(0.99)


def test_histogram_in_registry_snapshot_and_prometheus():
    from corda_tpu.tools.webserver import prometheus_text
    reg = MetricRegistry()
    reg.histogram("tx_verify_seconds").update(0.005)
    snap = reg.snapshot()
    assert snap["tx_verify_seconds"]["count"] == 1
    assert set(snap["tx_verify_seconds"]) == {
        "type", "count", "sum", "max", "mean", "p50", "p90", "p99",
        "buckets"}
    assert snap["tx_verify_seconds"]["type"] == "histogram"
    text = prometheus_text(snap)
    assert "corda_tpu_tx_verify_seconds_count 1" in text
    assert "corda_tpu_tx_verify_seconds_p99" in text
    with pytest.raises(TypeError):
        reg.counter("tx_verify_seconds")   # name/type collision stays typed


# ---------------------------------------------------------------------------
# Tracer + ring
# ---------------------------------------------------------------------------

def test_tracer_parenting_and_ring_query():
    tracer = Tracer(capacity=64)
    with tracer.span("root", kind="test") as root:
        with tracer.span("child", parent=root.context()) as child:
            child.set_tag("n", 3)
    spans = tracer.trace(root.trace_id)
    assert [s["name"] for s in spans] == ["child", "root"]  # finish order
    by_name = {s["name"]: s for s in spans}
    assert by_name["child"]["parent_id"] == root.span_id
    assert by_name["child"]["tags"] == {"n": 3}
    assert by_name["root"]["parent_id"] is None
    assert tracer.traces() == {root.trace_id: spans}
    # wire-tuple parents (the messaging form) attach to the same trace
    ctx = tracer.record("retro", parent=(root.trace_id, root.span_id),
                        start_s=1.0, duration_s=0.5)
    assert ctx.trace_id == root.trace_id
    assert len(tracer.trace(root.trace_id)) == 3


def test_span_ring_caps_and_exports(tmp_path):
    ring = SpanRing(capacity=4)
    for i in range(7):
        ring.record({"name": f"s{i}", "trace_id": "t", "span_id": str(i)})
    assert len(ring) == 4 and ring.dropped == 3
    assert [s["name"] for s in ring.snapshot()] == ["s3", "s4", "s5", "s6"]
    assert [s["name"] for s in ring.snapshot(limit=2)] == ["s5", "s6"]
    path = tmp_path / "spans.jsonl"
    assert ring.export_jsonl(str(path)) == 4
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [s["name"] for s in lines] == ["s3", "s4", "s5", "s6"]


def test_span_ring_survives_concurrent_writers():
    """N threads hammering one ring: no exception, the ring holds exactly
    `capacity` spans, and drop accounting balances the total written."""
    ring = SpanRing(capacity=32)
    n_threads, per_thread = 8, 200

    def writer(t):
        for i in range(per_thread):
            ring.record({"name": f"w{t}-{i}", "trace_id": "t",
                         "span_id": f"{t}-{i}"})

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(ring) == 32
    assert ring.dropped == n_threads * per_thread - 32
    assert len(ring.snapshot()) == 32


def test_spans_dropped_surfaces_as_registry_gauge():
    """The ServiceHub monitoring registry exposes the ring's drop counter
    (Tracing.SpansDropped) so an overflowing flight recorder is visible on
    /metrics instead of silently losing history."""
    from corda_tpu.testing import MockNetwork
    tracer = enable_tracing(capacity=4)
    network = MockNetwork()
    node = network.create_node("O=Drops, L=Oslo, C=NO")
    network.start_nodes()
    for i in range(10):            # 10 spans into a 4-slot ring → 6 drops
        tracer.record(f"s{i}")
    snap = node.services.monitoring.snapshot()
    assert snap["Tracing.SpansDropped"]["value"] == 6
    assert snap["Tracing.SpansBuffered"]["value"] == 4
    disable_tracing()              # no-op tracer has no ring: gauges read 0
    snap = node.services.monitoring.snapshot()
    assert snap["Tracing.SpansDropped"]["value"] == 0
    assert snap["Tracing.SpansBuffered"]["value"] == 0


def test_error_inside_span_is_tagged():
    tracer = Tracer()
    with pytest.raises(ValueError):
        with tracer.span("boom"):
            raise ValueError("nope")
    (span,) = tracer.spans()
    assert span["tags"]["error"].startswith("ValueError")


# ---------------------------------------------------------------------------
# Disabled path (the default)
# ---------------------------------------------------------------------------

def test_disabled_tracing_is_inert_no_threads_no_metrics():
    assert get_tracer() is NOOP_TRACER
    before = threading.active_count()
    span = get_tracer().span("anything", parent=None, x=1)
    assert span is NOOP_SPAN and span.context() is None
    with span:
        span.set_tag("y", 2)
    assert get_tracer().record("retro") is None
    assert get_tracer().spans() == [] and get_tracer().traces() == {}
    # enabling installs NO background threads either — purely passive
    enable_tracing(capacity=16)
    assert threading.active_count() == before
    disable_tracing()
    assert get_tracer() is NOOP_TRACER


def test_disabled_tracing_batcher_adds_no_trace_metrics():
    """With the no-op tracer, the host verify path must not grow any
    trace-only artifacts: no spans anywhere, and the per-item enqueue
    stamps stay unset (near-free disabled path)."""
    batcher = SignatureBatcher(max_latency_s=0.01)
    try:
        assert batcher.submit(KP.public, SIG, CONTENT).result(timeout=120)
    finally:
        batcher.close()
    assert get_tracer().spans() == []
    snap = batcher.metrics.snapshot()
    # the stage histograms themselves still work (they're metrics, not
    # tracing): the host dispatch recorded a batch
    assert snap["verifier_batch_size"]["count"] == 1


# ---------------------------------------------------------------------------
# Propagation across the batcher's dispatcher/finisher threads
# ---------------------------------------------------------------------------

def test_trace_propagates_across_batcher_threads():
    tracer = enable_tracing()
    root = tracer.span("tx.verify", n_sigs=1)
    batcher = SignatureBatcher(max_latency_s=0.01)
    try:
        fut = batcher.submit(KP.public, SIG, CONTENT, ctx=root.context())
        assert fut.result(timeout=120)
    finally:
        batcher.close()
    root.finish()
    spans = tracer.trace(root.trace_id)
    names = {s["name"] for s in spans}
    # submit happened on this thread; flush + dispatch on the dispatcher
    # thread; resolve on whichever finished — one trace across all of them
    assert {"batcher.enqueue_wait", "batcher.flush", "batcher.dispatch",
            "batcher.resolve", "tx.verify"} <= names
    by_name = {s["name"]: s for s in spans}
    assert by_name["batcher.dispatch"]["tags"]["route"] == "host"
    assert by_name["batcher.flush"]["tags"]["batch_size"] == 1
    assert by_name["batcher.flush"]["tags"]["flush_reason"] in (
        "deadline", "stalled", "small_batch", "close")
    # every span carries the SAME trace id (no orphaned second trace)
    assert all(s["trace_id"] == root.trace_id for s in spans)


def test_batch_stage_histograms_populate():
    batcher = SignatureBatcher(max_latency_s=0.01)
    try:
        futs = batcher.submit_many(
            [(KP.public, SIG, CONTENT) for _ in range(5)])
        assert all(f.result(timeout=120) for f in futs)
    finally:
        batcher.close()
    snap = batcher.metrics.snapshot()
    assert snap["verifier_batch_size"]["count"] >= 1
    assert snap["verifier_batch_size"]["max"] >= 1
    assert snap["verifier_dispatch_seconds"]["count"] >= 1
    assert snap["verifier_finish_seconds"]["count"] >= 1
    stages = stage_percentiles(snap)
    assert "stage_dispatch_ms_p50" in stages
    assert "stage_finish_ms_p99" in stages
    assert "verifier_batch_size_p50" in stages
    # host-only run: no device prep happened, so the stage is ABSENT
    assert "stage_prep_ms_p50" not in stages


def test_stage_percentiles_ignores_empty_and_missing():
    assert stage_percentiles({}) == {}
    empty = Histogram().snapshot_fields()
    assert stage_percentiles({"verifier_prep_seconds": empty}) == {}
