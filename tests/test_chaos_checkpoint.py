"""Crash-consistent checkpointing chaos tests (satellite of the chaos
harness): kill a flow between sending its response and removing its
checkpoint, restart the node on the SAME checkpoint storage, and prove
the replay is idempotent — the flow completes again without re-sending
anything, and the stale checkpoint is cleaned up.

The crash window is the ``smm.checkpoint_remove`` fault point in
StateMachineManager._finalize: a "drop" rule skips the removal, which is
exactly the artifact a crash at that instant leaves on disk. Checkpoints
are written at suspension points, so the flows here park on a trailing
flow-timer ``Sleep`` AFTER their sends — that park persists the send in
the response log, and the timer re-arms deterministically on replay
(test_flow_timers' mid-sleep-restart semantics).
"""
import pytest

from corda_tpu.core.serialization import deserialize
from corda_tpu.flows.api import (FlowLogic, Receive, Send, SendAndReceive,
                                 Sleep, initiated_by, initiating_flow)
from corda_tpu.node.checkpoints import FileCheckpointStorage, KvCheckpointStorage
from corda_tpu.node.statemachine import SessionData, SessionInit
from corda_tpu.testing import MockNetwork
from corda_tpu.testing.faults import FaultRule, inject

pytestmark = pytest.mark.chaos

SEEDS = [7, 101, 9001]


@initiating_flow
class AskFlow(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        answer = yield SendAndReceive(self.peer, "question", str)
        return answer.unwrap(lambda d: d)


@initiated_by(AskFlow)
class AnswerThenPauseFlow(FlowLogic):
    """Responds immediately, then parks on a housekeeping Sleep — the park
    writes the checkpoint whose response log already holds the answer
    send, i.e. the artifact a crash-before-remove leaves behind."""

    def __init__(self, peer):
        self.peer = peer

    def call(self):
        _ = yield Receive(self.peer, str)
        yield Send(self.peer, "answer")
        yield Sleep(1.0)
        return "done"


@initiating_flow
class AskThenPauseFlow(FlowLogic):
    """Initiator variant: the answer is in the log before the final park."""

    def __init__(self, peer):
        self.peer = peer

    def call(self):
        answer = yield SendAndReceive(self.peer, "question", str)
        yield Sleep(1.0)
        return answer.unwrap(lambda d: d)


@initiated_by(AskThenPauseFlow)
class AnswerNowFlow(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        _ = yield Receive(self.peer, str)
        yield Send(self.peer, "answer")
        return "done"


def count_session_traffic(bus, recipient):
    """How many session payload-bearing messages (SessionInit/SessionData)
    were ever sent to `recipient` — the double-send detector for the
    idempotent-replay assertions."""
    n = 0
    for transfer in bus.sent_log:
        if transfer.recipient != recipient:
            continue
        try:
            if isinstance(deserialize(transfer.message.data),
                          (SessionInit, SessionData)):
                n += 1
        except Exception:
            pass
    return n


def make_storage(kind, tmp_path):
    if kind == "file":
        return FileCheckpointStorage(str(tmp_path / "ckpts"))
    return KvCheckpointStorage(str(tmp_path / "ckpts.kv"), use_native=False)


@pytest.mark.parametrize("kind", ["file", "kv"])
@pytest.mark.parametrize("seed", SEEDS)
def test_responder_replay_after_crash_between_send_and_remove(
        tmp_path, kind, seed):
    """Bob's responder sends its answer, then 'crashes' in _finalize before
    remove_checkpoint. On restart the stale checkpoint replays: the flow
    must finish WITHOUT re-sending the answer (Alice sees exactly the same
    session traffic before and after) and the checkpoint must be removed."""
    network = MockNetwork()
    a = network.create_node("O=Alice, L=London, C=GB")
    b = network.create_node(
        "O=Bob, L=Paris, C=FR",
        checkpoint_storage=make_storage(kind, tmp_path))
    network.start_nodes()

    fsm = a.start_flow(AskFlow(b.party))
    network.run_network()
    # Alice has her answer; Bob is parked on his Sleep with one checkpoint
    assert fsm.result_future.result(timeout=1) == "answer"
    assert len(b.smm.checkpoints.get_all_checkpoints()) == 1

    # Bob's timer fires and his flow completes — but the injected drop
    # skips remove_checkpoint: the crash window between send and remove
    with inject(FaultRule("smm.checkpoint_remove", "drop", count=1),
                seed=seed) as inj:
        network.advance_clock(2.0)
    assert inj.fired("smm.checkpoint_remove") == 1
    assert b.smm.flows == {}
    assert len(b.smm.checkpoints.get_all_checkpoints()) == 1   # the artifact

    alice_addr = str(a.party.name)
    sends_before = count_session_traffic(network.bus, alice_addr)

    # restart Bob on the same storage: the replay consumes the response
    # log (the answer send included — no wire IO) and re-parks on Sleep
    b2 = b.restart()
    b2.start()
    assert len(b2.smm.flows) == 1
    network.advance_clock(2.0)     # the re-armed timer fires; flow completes

    assert b2.smm.flows == {}
    assert b2.smm.checkpoints.get_all_checkpoints() == []
    # idempotent: no duplicate answer (or any session message) hit Alice
    assert count_session_traffic(network.bus, alice_addr) == sends_before
    assert a.smm.flows == {}


@pytest.mark.parametrize("seed", SEEDS)
def test_initiator_replay_after_crash_before_remove(tmp_path, seed):
    """Same crash window on the INITIATOR: Alice already received her
    answer (it is in the checkpointed response log); her restart must
    replay to completion without opening a duplicate session to Bob."""
    network = MockNetwork()
    a = network.create_node(
        "O=Alice, L=London, C=GB",
        checkpoint_storage=make_storage("file", tmp_path))
    b = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()

    fsm = a.start_flow(AskThenPauseFlow(b.party))
    network.run_network()
    assert not fsm.result_future.done()      # parked on the trailing Sleep
    assert len(a.smm.checkpoints.get_all_checkpoints()) == 1

    with inject(FaultRule("smm.checkpoint_remove", "drop", count=1),
                seed=seed) as inj:
        network.advance_clock(2.0)
    assert inj.fired("smm.checkpoint_remove") == 1
    assert fsm.result_future.result(timeout=1) == "answer"
    assert len(a.smm.checkpoints.get_all_checkpoints()) == 1   # the artifact

    bob_addr = str(b.party.name)
    traffic_before = count_session_traffic(network.bus, bob_addr)

    a2 = a.restart()
    a2.start()
    assert len(a2.smm.flows) == 1
    network.advance_clock(2.0)

    assert a2.smm.flows == {}
    assert a2.smm.checkpoints.get_all_checkpoints() == []
    # the replayed initiator never re-sent its question to Bob
    assert count_session_traffic(network.bus, bob_addr) == traffic_before
    assert b.smm.flows == {}
