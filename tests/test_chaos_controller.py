"""Controller chaos suite: the seeded kill-storm and the silently-hung
worker.

Two properties close ROADMAP item 3's loop:

- **Kill-storm recovery** (the tentpole's proof): crash half the fleet
  mid-load and the FleetController must crash-detach the corpses, spawn
  replacements, and restore SLO compliance within the error-budget
  bound — with every in-flight future resolving exactly once and the
  whole episode rendered as ONE annotated ``controller.episode``
  timeline on /traces.

- **Stale-detach** (the fleet_status staleness fix): a worker that hangs
  SILENTLY — fault-injected ``oop.reply`` drop, no Goodbye, no further
  liveness — is actually crash-detached after N stale windows, not just
  flagged, so its charged futures complete on the survivors.
"""
import time

import pytest

from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.testing.faults import FaultRule, inject
from corda_tpu.verifier.fleet import kill_storm_recovery
from corda_tpu.verifier.out_of_process import (
    OutOfProcessTransactionVerifierService, VerifierWorker)

from test_oop_verifier import make_ltx

pytestmark = pytest.mark.chaos

SEEDS = [7, 101, 9001]


@pytest.mark.parametrize("seed", SEEDS)
def test_kill_storm_controller_restores_slo(seed):
    """Seeded kill-storm: ~half the workers crash mid-load (no Goodbye).
    The controller must reap them, respawn capacity, and return the fleet
    to steady inside the error-budget-bounded window; zero futures lost,
    one annotated episode timeline."""
    out = kill_storm_recovery(seed=seed)
    assert out["killed_workers"], "the storm killed nobody"
    # exactly-once: every future resolved, none hung, none failed
    assert out["lost_futures"] == 0, out
    assert out["failed_futures"] == 0, out
    # the SLO was restored within the error-budget bound
    assert out["controller_state"] == "steady", out
    assert out["recovered_within_bound"], out
    assert out["recovery_s"] is not None
    assert 0.0 < out["recovery_s"] <= out["recovery_bound_s"]
    # the controller actually acted (detach + respawn at minimum)...
    assert out["controller_actions"] >= len(out["killed_workers"])
    # ...and the whole episode is ONE annotated timeline on /traces
    assert out["episode_spans"] == 1, out
    assert out["episode_action_spans"] >= len(out["killed_workers"])


@pytest.mark.parametrize("seed", SEEDS)
def test_silently_hung_worker_is_stale_detached_and_futures_complete(seed):
    """The fleet_status staleness fix: w1 hangs silently — its replies
    are fault-dropped and it never reports load — while w2 keeps
    reporting. After N stale windows ``reap_stale_workers`` must
    crash-detach w1 (today's behavior was only ``stale: true`` flagging),
    requeueing its charged share so every future completes exactly once
    on the survivor."""
    bus = InMemoryMessagingNetwork()
    node = bus.create_node("node")
    svc = OutOfProcessTransactionVerifierService(
        node, load_report_interval_s=0.02, stale_detach_intervals=2)
    try:
        w1 = VerifierWorker(bus.create_node("w1"), "node")
        w2 = VerifierWorker(bus.create_node("w2"), "node")
        bus.run_network()
        assert svc.queue.worker_count == 2

        with inject(FaultRule("oop.reply", "drop", detail="w1->*"),
                    seed=seed) as inj:
            futures = [svc.verify(make_ltx(i)) for i in range(20)]
            bus.run_network()
            # w1's share hangs: replies vanished, nothing resolved there
            assert inj.fired("oop.reply") == 10
            assert sum(f.done() for f in futures) == 10

            # w2 stays live (reports + acks); w1 goes silent past the
            # horizon (2 windows × 3 × 0.02 s = 0.12 s)
            deadline = time.monotonic() + 0.15
            while time.monotonic() < deadline:
                w2.send_load_report()
                bus.run_network()
                time.sleep(0.02)

            # the service's own redelivery scanner may have swept w1
            # already; either way the manual sweep must leave exactly the
            # silent worker detached and the survivor attached
            reaped = svc.reap_stale_workers()
            assert reaped in ([], ["w1"]), reaped
            assert svc.queue.worker_count == 1
            bus.run_network()
            # the detach requeued w1's charged work onto w2 — but w1's
            # replies still drop, so only a real redeal can finish them
            for f in futures:
                assert f.result(timeout=5) is None
        # exactly-once bookkeeping: nothing left charged or pending
        with svc.queue._lock:
            assert not svc.queue._pending
            assert not svc.queue._dealt_at
        snap = svc.metrics.snapshot()
        assert snap["Fleet.StaleDetached"]["count"] == 1
    finally:
        svc.shutdown()
