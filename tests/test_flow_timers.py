"""Flow timers (VERDICT r3 #7): Sleep + receive-with-timeout on the node's
injectable clock — the reference's fiber-aware ClockUtils.awaitWithDeadline
(node/utilities/ClockUtils.kt) semantics: a sleeping flow never blocks the
node thread, a TestClock advance wakes it deterministically, and a timed-out
receive throws FlowTimeoutException at the yield site.
"""
from corda_tpu.flows.api import (FlowLogic, FlowTimeoutException, Receive,
                                 Send, Sleep, initiating_flow)
from corda_tpu.testing import MockNetwork


class SleepingFlow(FlowLogic):
    def __init__(self, seconds):
        self.seconds = seconds

    def call(self):
        yield Sleep(self.seconds)
        return "woke"


@initiating_flow
class AskWithTimeoutFlow(FlowLogic):
    def __init__(self, peer, timeout_s):
        self.peer = peer
        self.timeout_s = timeout_s

    def call(self):
        yield Send(self.peer, "question")
        try:
            reply = yield Receive(self.peer, str, timeout_s=self.timeout_s)
        except FlowTimeoutException:
            return "timed-out"
        return reply.unwrap(lambda d: d)


def make_silent_responder():
    """Responder that reads the question and never answers."""
    class SilentResponder(FlowLogic):
        def __init__(self, peer):
            self.peer = peer

        def call(self):
            yield Receive(self.peer, str)
            yield Receive(self.peer, str)    # parks forever
    return SilentResponder


def make_prompt_responder():
    class PromptResponder(FlowLogic):
        def __init__(self, peer):
            self.peer = peer

        def call(self):
            yield Receive(self.peer, str)
            yield Send(self.peer, "answer")
    return PromptResponder


def two_nodes():
    network = MockNetwork()
    a = network.create_node("O=A, L=London, C=GB")
    b = network.create_node("O=B, L=Paris, C=FR")
    network.start_nodes()
    return network, a, b


def test_sleep_wakes_on_test_clock_only():
    network, a, _ = two_nodes()
    fsm = a.start_flow(SleepingFlow(10.0))
    network.run_network()
    assert not fsm.result_future.done()      # pumping alone must not wake it
    network.advance_clock(5.0)
    assert not fsm.result_future.done()
    assert network.advance_clock(5.1) == 1
    assert fsm.result_future.result(timeout=5) == "woke"


def test_receive_timeout_throws_at_yield_site():
    network, a, b = two_nodes()
    from corda_tpu.flows.api import flow_name
    b.smm.register_flow_factory(flow_name(AskWithTimeoutFlow),
                                make_silent_responder())
    fsm = a.start_flow(AskWithTimeoutFlow(b.party, timeout_s=20.0))
    network.run_network()
    assert not fsm.result_future.done()
    network.advance_clock(21.0)
    assert fsm.result_future.result(timeout=5) == "timed-out"


def test_reply_before_deadline_cancels_timer():
    network, a, b = two_nodes()
    from corda_tpu.flows.api import flow_name
    b.smm.register_flow_factory(flow_name(AskWithTimeoutFlow),
                                make_prompt_responder())
    fsm = a.start_flow(AskWithTimeoutFlow(b.party, timeout_s=20.0))
    network.run_network()
    assert fsm.result_future.result(timeout=5) == "answer"
    # the stale timer must not corrupt anything when it fires later
    assert network.advance_clock(30.0) == 0


def test_sleep_survives_restart():
    """Mid-sleep restart: the restored flow re-parks on its Sleep and the
    deadline re-arms in full on the restored clock (documented semantics)."""
    network, a, _ = two_nodes()
    fsm = a.start_flow(SleepingFlow(10.0))
    network.run_network()
    assert not fsm.result_future.done()
    a2 = a.restart()
    a2.start()
    restored = list(a2.smm.flows.values())[0]
    network.advance_clock(10.1)
    assert restored.result_future.result(timeout=5) == "woke"
