"""Multi-chip sharding tests on the virtual 8-device CPU mesh (conftest).

Mirrors the reference's verifier fan-out tests (VerifierTests.kt:53-71:
"verification works with N out-of-process verifiers") — here the fan-out is
SPMD over a Mesh instead of N worker JVMs.
"""
import hashlib

import jax
import numpy as np
import pytest

from corda_tpu.core.crypto import ecmath
from corda_tpu.ops import ed25519 as ed_ops
from corda_tpu.ops import sha256 as sha_ops
from corda_tpu.parallel import (make_mesh, sharded_ecdsa_verify_hybrid,
                                sharded_ed25519_verify, sharded_merkle_root,
                                tx_verify_step)

RNG = np.random.default_rng(11)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest should provide 8 virtual devices"
    return make_mesh(8)


def _ed_items(n):
    items, want = [], []
    for i in range(n):
        seed = RNG.bytes(32)
        pub = ecmath.ed25519_public_key(seed)
        msg = RNG.bytes(20 + i)
        sig = ecmath.ed25519_sign(seed, msg)
        if i % 3 == 1:
            msg = msg + b"x"  # invalidate
        items.append((pub, sig, msg))
        want.append(ecmath.ed25519_verify(pub, msg, sig))
    return items, want


def test_sharded_ed25519_matches_host(mesh):
    items, want = _ed_items(16)
    s_bits, k_bits, neg_a, r_affine, precheck = ed_ops.prepare_batch(items)
    fn = sharded_ed25519_verify(mesh)
    ok = np.asarray(fn(s_bits, k_bits, neg_a, r_affine)) & precheck
    assert list(ok) == want
    assert True in ok and False in list(ok)


def test_sharded_hybrid_ecdsa_matches_host(mesh):
    from corda_tpu.ops import weierstrass as wc_ops
    curve = ecmath.SECP256K1
    items, want = [], []
    for i in range(16):
        priv = int.from_bytes(RNG.bytes(32), "little") % (curve.n - 1) + 1
        pub = curve.mul(priv, curve.g)
        msg = RNG.bytes(24 + i)
        r, s = ecmath.ecdsa_sign(curve, priv, msg)
        if i % 3 == 1:
            msg = msg + b"x"
        items.append((pub, msg, r, s))
        want.append(ecmath.ecdsa_verify(curve, pub, msg, r, s))
    *args, precheck = wc_ops.prepare_batch_hybrid_wide(
        items, wc_ops.HYBRID_G_WINDOW)
    fn = sharded_ecdsa_verify_hybrid(mesh)
    ok = np.asarray(fn(*args)) & precheck
    assert list(ok) == want
    assert True in ok and False in list(ok)


def test_sharded_merkle_root_matches_host(mesh):
    leaves_bytes = [hashlib.sha256(bytes([i])).digest() for i in range(32)]
    leaves = sha_ops.digests_from_bytes(leaves_bytes)

    def host_root(hs):
        while len(hs) > 1:
            hs = [hashlib.sha256(hs[i] + hs[i + 1]).digest()
                  for i in range(0, len(hs), 2)]
        return hs[0]

    fn = sharded_merkle_root(mesh)
    got = sha_ops.digests_to_bytes(np.asarray(fn(leaves))[None])[0]
    assert got == host_root(leaves_bytes)


def test_tx_verify_step(mesh):
    items, want = _ed_items(8)
    s_bits, k_bits, neg_a, r_affine, precheck = ed_ops.prepare_batch(items)
    leaves_bytes = [hashlib.sha256(bytes([i, i])).digest() for i in range(16)]
    leaves = sha_ops.digests_from_bytes(leaves_bytes)
    step = tx_verify_step(mesh)
    ok, root = step(s_bits, k_bits, neg_a, r_affine, leaves)
    assert list(np.asarray(ok) & precheck) == want
    def host_root(hs):
        while len(hs) > 1:
            hs = [hashlib.sha256(hs[i] + hs[i + 1]).digest()
                  for i in range(0, len(hs), 2)]
        return hs[0]
    assert sha_ops.digests_to_bytes(np.asarray(root)[None])[0] == host_root(leaves_bytes)


def test_mesh_backed_batcher_matches_host(mesh):
    """VERDICT r2 #7: the SERVICE seam composed with the mesh — a
    SignatureBatcher(mesh=...) shards its device batches over every chip
    and returns the same verdicts as host verification."""
    from corda_tpu.core.crypto import generate_keypair
    from corda_tpu.core.crypto.schemes import (ECDSA_SECP256K1_SHA256,
                                               EDDSA_ED25519_SHA512)
    from corda_tpu.core.crypto.signatures import Crypto
    from corda_tpu.verifier.batcher import SignatureBatcher

    checks, want = [], []
    for i in range(12):
        scheme = (EDDSA_ED25519_SHA512 if i % 2 else ECDSA_SECP256K1_SHA256)
        kp = generate_keypair(scheme, entropy=bytes([0x30 + i]) * 32)
        content = bytes([i]) * 24
        sig = Crypto.sign_with_key(kp, content).bytes
        if i % 4 == 2:
            content = content + b"!"        # invalidate
        checks.append((kp.public, sig, content))
        want.append(Crypto.is_valid(kp.public, sig, content))
    b = SignatureBatcher(mesh=mesh, host_crossover=0, max_latency_s=0.02)
    try:
        futs = b.submit_many(checks)
        got = [f.result(timeout=300) for f in futs]
        assert got == want
        snap = b.metrics.snapshot()
        assert snap["SigBatcher.DeviceChecked"]["count"] >= len(checks)
    finally:
        b.close()
