"""Smoke-test tier: drive the INSTALLED node artifact as a black box.

Reference analog: smoke-test-utils NodeProcess.kt:68-147 — boot the
packaged corda.jar from outside, connect a standalone RPC client, do real
work, shut down cleanly. Here the artifact is the console entry point
(`corda-tpu-node`, pyproject [project.scripts]) when installed, falling
back to the equivalent `python -m corda_tpu.node` module form; the test
uses ONLY the public CLI + RPC client, no test fixtures.
"""
import shutil
import signal
import subprocess
import sys

import pytest

import corda_tpu.finance  # noqa: F401 — client-side wire types
from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.client.rpc import CordaRPCClient
from corda_tpu.testing.driver import await_node_ready


def _node_command() -> list[str]:
    exe = shutil.which("corda-tpu-node")
    if exe is not None:
        return [exe]
    return [sys.executable, "-m", "corda_tpu.node"]


@pytest.mark.slow
def test_black_box_node_smoke(tmp_path):
    proc = subprocess.Popen(
        _node_command() + ["--name", "O=Smoke, L=London, C=GB",
                           "--port", "0", "--base-dir", str(tmp_path),
                           "--notary", "simple", "--quiet"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        host, port = await_node_ready(proc, "smoke", timeout_s=120.0)
        client = CordaRPCClient(host, port)
        try:
            info = client.node_identity()
            assert str(info.legal_identity.name) == "O=Smoke, L=London, C=GB"
            me = info.legal_identity
            notary = client.notary_identities()[0]
            result = client.start_flow_and_wait(
                "CashIssueFlow", Amount(1234, USD), b"\x01", me, notary,
                timeout_s=120)
            assert result is not None
            assert client.get_cash_balances() == {"USD": 1234}
            assert "CashPaymentFlow" in str(client.registered_flows())
        finally:
            client.close()
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            pytest.fail("node did not shut down on SIGTERM")
        assert rc == 0, f"node exited with {rc}"
