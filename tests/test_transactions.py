"""Transaction layer tests: ids, signatures, platform rules, tear-offs.

(Reference analogs: WireTransaction/SignedTransaction tests, TransactionTypesTests,
PartialMerkleTreeTest's FilteredTransaction cases.)
"""
import pytest

from corda_tpu.core.contracts import (
    Command, StateAndRef, StateRef, TimeWindow, TransactionState, TransactionType,
    DuplicateInputStates, SignersMissing, MoreThanOneNotary, ContractRejection,
    TransactionVerificationException, InvalidNotaryChange,
)
from corda_tpu.core.crypto import generate_keypair, SecureHash
from corda_tpu.core.identity import Party
from corda_tpu.core.serialization import serialize, deserialize
from corda_tpu.core.transactions import (
    WireTransaction, SignedTransaction, SignaturesMissingException,
    TransactionBuilder, FilteredTransaction, LedgerTransaction,
)
from corda_tpu.testing import DummyContract, DummyState, DUMMY_NOTARY_NAME

NOTARY_KP = generate_keypair(entropy=b"\x10" * 32)
NOTARY = Party(DUMMY_NOTARY_NAME, NOTARY_KP.public)
ALICE_KP = generate_keypair(entropy=b"\x11" * 32)
ALICE = Party("O=Alice Corp, L=Madrid, C=ES", ALICE_KP.public)
BOB_KP = generate_keypair(entropy=b"\x12" * 32)
BOB = Party("O=Bob Plc, L=Rome, C=IT", BOB_KP.public)


def make_wtx(**kw):
    defaults = dict(
        inputs=(), attachments=(),
        outputs=(TransactionState(DummyState(1, (ALICE_KP.public,)), NOTARY),),
        commands=(Command(DummyContract.Create(), (ALICE_KP.public,)),),
        notary=NOTARY, must_sign=(ALICE_KP.public,),
        type=TransactionType.General, time_window=None)
    defaults.update(kw)
    return WireTransaction(**defaults)


def ltx_from(wtx, input_states=()):
    """Resolve without a ServiceHub (direct construction) for rule tests."""
    from corda_tpu.core.contracts.structures import AuthenticatedObject
    return LedgerTransaction(
        inputs=tuple(input_states), outputs=wtx.outputs,
        commands=tuple(AuthenticatedObject(c.signers, (), c.value) for c in wtx.commands),
        attachments=(), id=wtx.id, notary=wtx.notary, must_sign=wtx.must_sign,
        type=wtx.type, time_window=wtx.time_window)


def test_wire_transaction_id_is_component_merkle_root():
    wtx = make_wtx()
    from corda_tpu.core.crypto.merkle import MerkleTree
    assert wtx.id == MerkleTree.get_merkle_tree(wtx.available_component_hashes).hash
    # component order: inputs, attachments, outputs, commands, notary, signers, type
    comps = wtx.available_components
    assert comps[0] == wtx.outputs[0]
    assert comps[1] == wtx.commands[0]
    assert comps[2] == wtx.notary
    assert comps[3] == ALICE_KP.public
    assert comps[4] == TransactionType.General
    # deterministic across serialization round trip
    wtx2 = deserialize(serialize(wtx))
    assert wtx2.id == wtx.id
    # changing any component changes the id
    assert make_wtx(must_sign=(BOB_KP.public,)).id != wtx.id


def test_signed_transaction_signature_checking():
    wtx = make_wtx(must_sign=(ALICE_KP.public, BOB_KP.public))
    alice_sig = __import__("corda_tpu.core.crypto.signatures", fromlist=["Crypto"]) \
        .Crypto.sign_with_key(ALICE_KP, wtx.id.bytes)
    stx = SignedTransaction.of(wtx, (alice_sig,))
    stx.check_signatures_are_valid()
    with pytest.raises(SignaturesMissingException):
        stx.verify_signatures()
    # allowed-to-be-missing lets collection flows proceed
    assert stx.verify_signatures(BOB_KP.public) == {BOB_KP.public}
    # adding Bob's signature completes it
    from corda_tpu.core.crypto.signatures import Crypto
    stx2 = stx.plus(Crypto.sign_with_key(BOB_KP, wtx.id.bytes))
    assert stx2.verify_signatures() == set()
    # a wrong signature fails cryptographically
    bad = Crypto.sign_with_key(BOB_KP, b"other content")
    from corda_tpu.core.crypto.signatures import SignatureException
    with pytest.raises(SignatureException):
        SignedTransaction.of(wtx, (alice_sig, bad)).check_signatures_are_valid()


def test_platform_rule_duplicate_inputs():
    ref = StateRef(SecureHash.sha256(b"prev"), 0)
    state = TransactionState(DummyState(1, (ALICE_KP.public,)), NOTARY)
    wtx = make_wtx(inputs=(ref, ref), must_sign=(ALICE_KP.public, NOTARY_KP.public))
    ltx = ltx_from(wtx, [StateAndRef(state, ref), StateAndRef(state, ref)])
    with pytest.raises(DuplicateInputStates):
        ltx.verify()


def test_platform_rule_missing_signers():
    wtx = make_wtx(commands=(Command(DummyContract.Create(), (BOB_KP.public,)),),
                   must_sign=(ALICE_KP.public,))
    with pytest.raises(SignersMissing):
        ltx_from(wtx).verify()


def test_platform_rule_more_than_one_notary():
    other_notary = Party("O=Other Notary, L=Oslo, C=NO", BOB_KP.public)
    ref1 = StateRef(SecureHash.sha256(b"a"), 0)
    ref2 = StateRef(SecureHash.sha256(b"b"), 0)
    s1 = StateAndRef(TransactionState(DummyState(1), NOTARY), ref1)
    s2 = StateAndRef(TransactionState(DummyState(2), other_notary), ref2)
    wtx = make_wtx(inputs=(ref1, ref2),
                   must_sign=(ALICE_KP.public, NOTARY_KP.public, BOB_KP.public))
    with pytest.raises(MoreThanOneNotary):
        ltx_from(wtx, [s1, s2]).verify()


def test_platform_rule_time_window_requires_notary():
    import datetime
    tw = TimeWindow.from_only(datetime.datetime(2026, 1, 1))
    wtx = make_wtx(notary=None, time_window=tw,
                   outputs=(TransactionState(DummyState(1), NOTARY),))
    with pytest.raises(TransactionVerificationException):
        ltx_from(wtx).verify()


def test_contract_rejection():
    from corda_tpu.core.serialization import serializable

    class AngryContract(DummyContract):
        def verify(self, tx):
            raise ValueError("no thanks")

    @serializable("test.AngryState")
    class AngryState(DummyState):
        @property
        def contract(self):
            return AngryContract()

    wtx = make_wtx(outputs=(TransactionState(AngryState(1), NOTARY),))
    with pytest.raises(ContractRejection):
        ltx_from(wtx).verify()


def test_notary_change_rules():
    other_notary = Party("O=Other Notary, L=Oslo, C=NO",
                         generate_keypair(entropy=b"\x13" * 32).public)
    state = DummyState(7, (ALICE_KP.public,))
    ref = StateRef(SecureHash.sha256(b"x"), 0)
    inp = StateAndRef(TransactionState(state, NOTARY), ref)
    good = make_wtx(
        inputs=(ref,), outputs=(TransactionState(state, other_notary),), commands=(),
        type=TransactionType.NotaryChange,
        must_sign=(ALICE_KP.public, NOTARY_KP.public))
    ltx_from(good, [inp]).verify()
    # modifying the state data is invalid
    bad = make_wtx(
        inputs=(ref,), outputs=(TransactionState(DummyState(8), other_notary),),
        commands=(), type=TransactionType.NotaryChange,
        must_sign=(ALICE_KP.public, NOTARY_KP.public))
    with pytest.raises(InvalidNotaryChange):
        ltx_from(bad, [inp]).verify()


def test_transaction_builder_end_to_end():
    b = TransactionBuilder(notary=NOTARY)
    b.add_output_state(DummyState(5, (ALICE_KP.public,)))
    b.add_command(DummyContract.Create(), ALICE_KP.public)
    b.sign_with(ALICE_KP)
    with pytest.raises(ValueError):
        b.add_command(DummyContract.Move(), BOB_KP.public)  # locked after signing
    stx = b.to_signed_transaction()
    assert stx.verify_signatures() == set()
    assert stx.tx.notary == NOTARY


def test_filtered_transaction_tear_off():
    wtx = make_wtx()
    # Reveal only commands (the oracle pattern: NodeInterestRates.kt:149-180).
    ftx = wtx.build_filtered_transaction(lambda c: isinstance(c, Command))
    assert ftx.verify()
    assert ftx.filtered_leaves.commands == wtx.commands
    assert ftx.filtered_leaves.outputs == ()
    assert ftx.filtered_leaves.check_with_fun(lambda c: isinstance(c, Command))
    # Round-trips through the codec (notaries sign these remotely).
    ftx2 = deserialize(serialize(ftx))
    assert ftx2.verify() and ftx2.root_hash == wtx.id
    # Tamper: swap in a different command
    from corda_tpu.core.transactions.filtered import FilteredLeaves
    forged_leaves = FilteredLeaves(commands=(Command(DummyContract.Move(),
                                                    (ALICE_KP.public,)),))
    forged = FilteredTransaction(ftx.root_hash, forged_leaves, ftx.partial_merkle_tree)
    assert not forged.verify()
