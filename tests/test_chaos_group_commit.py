"""Group-commit chaos tests: double-spend pairs through the batched
uniqueness pipeline under the seeded fault injector.

The GroupCommitter coalesces many suspended flows' uniqueness commits
into one ``put_all_batch`` raft append; the property under test is that
batching never weakens the notary's SAFETY:

- a conflicting pair landing in the SAME batch resolves first-wins in
  list order, deterministically on every replica;
- a pair split across ADJACENT batches rejects the second against the
  replicated map (prescreen off — the consensus-side verdict itself is
  what's exercised);
- a pair straddling a LEADER KILL mid-batch commits at most once, and
  the survivors converge on the one winner.

Unlike test_chaos_raft's synchronous pumping, the committer runs real
threads (ticker + batch pool), so each scenario drives the cluster from
a background pump thread — the same discipline as the ledger harness.
"""
import threading
import time

import pytest

from corda_tpu.consensus.commit_pipeline import GroupCommitter
from corda_tpu.consensus.raft import LEADER
from corda_tpu.consensus.raft_uniqueness import (DistributedImmutableMap,
                                                 RaftUniquenessProvider)
from corda_tpu.core.contracts.structures import StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.node.notary import UniquenessException
from corda_tpu.testing.faults import FaultRule, inject

pytestmark = pytest.mark.chaos

SEEDS = [7, 101, 9001]


def partition_rules(name):
    return (FaultRule("net.send", "drop", detail=f"{name}->*"),
            FaultRule("net.send", "drop", detail=f"*->{name}"))


class _Cluster:
    """3-replica raft cluster pumped from a background thread (the
    GroupCommitter blocks on futures, so synchronous pumping deadlocks)."""

    def __init__(self, seed: int, n: int = 3):
        self.bus = InMemoryMessagingNetwork()
        self.names = [f"raft{i}" for i in range(n)]
        self.maps = [DistributedImmutableMap() for _ in range(n)]
        self.providers = [RaftUniquenessProvider.build(
            name, list(self.names), self.bus.create_node(name),
            state_machine=self.maps[i], seed=seed + i, native=False)
            for i, name in enumerate(self.names)]
        self.nodes = [p.raft for p in self.providers]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="chaos-gc-pump")
        self._thread.start()

    def _pump(self):
        while not self._stop.is_set():
            for rn in self.nodes:
                rn.tick()
            for name in self.names:
                while self.bus.pump_receive(name) is not None:
                    pass
            time.sleep(0.002)

    def wait_leader(self, exclude=(), timeout=15.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = [n for n in self.nodes
                       if n.role == LEADER and n not in exclude]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.01)
        raise AssertionError("no leader elected")

    def wait_converged(self, n_entries: int, timeout=10.0, exclude=()):
        """Poll until every (non-excluded) replica applied `n_entries` and
        all agree ref-for-ref."""
        live = [m for i, m in enumerate(self.maps)
                if self.nodes[i] not in exclude]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(len(m) == n_entries for m in live) \
                    and all(m._map == live[0]._map for m in live):
                return live
            time.sleep(0.01)
        raise AssertionError(
            f"replicas did not converge on {n_entries} entries: "
            f"{[len(m) for m in live]}")

    def close(self):
        self._stop.set()
        self._thread.join(timeout=2.0)


def _ref(tag: str) -> StateRef:
    return StateRef(SecureHash.sha256(tag.encode()), 0)


def _tx(tag: str):
    return SecureHash.sha256(b"tx:" + tag.encode())


@pytest.mark.parametrize("seed", SEEDS)
def test_double_spend_pair_same_batch(seed):
    """Two spends of one ref admitted into the SAME batch (prescreen off,
    max_batch cuts at 2): apply resolves first-wins in list order — one
    raft append, one winner, the loser rejected with the conflict, every
    replica recording the same owner."""
    cluster = _Cluster(seed)
    committer = None
    try:
        leader = cluster.wait_leader()
        committer = GroupCommitter(leader, timeout_s=10.0, max_batch=2,
                                   max_latency_s=0.5, prescreen=False)
        ref = _ref(f"same-batch-{seed}")
        f_win = committer.submit([ref], _tx("winner"), "chaos")
        f_lose = committer.submit([ref], _tx("loser"), "chaos")

        assert f_win.result(timeout=15) is None
        with pytest.raises(UniquenessException) as ei:
            f_lose.result(timeout=15)
        assert ref in ei.value.conflicts
        assert ei.value.conflicts[ref].consuming_tx == _tx("winner")

        maps = cluster.wait_converged(1)
        assert maps[0]._map[ref].consuming_tx == _tx("winner")
        snap = committer.metrics.snapshot()
        # the whole pair rode ONE consensus round
        assert snap["GroupCommit.RaftAppends"]["count"] == 1
        assert snap["GroupCommit.Committed"]["count"] == 1
        assert snap["GroupCommit.Rejected"]["count"] == 1
    finally:
        if committer is not None:
            committer.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_double_spend_pair_adjacent_batches(seed):
    """The pair split across ADJACENT batches (max_batch=1 — every submit
    is its own append): the second spend must be rejected by the
    replicated apply, not by any leader-local shortcut (prescreen off)."""
    cluster = _Cluster(seed)
    committer = None
    try:
        leader = cluster.wait_leader()
        committer = GroupCommitter(leader, timeout_s=10.0, max_batch=1,
                                   max_latency_s=0.005, prescreen=False)
        ref = _ref(f"adjacent-{seed}")
        assert committer.submit([ref], _tx("first"), "chaos") \
            .result(timeout=15) is None
        with pytest.raises(UniquenessException) as ei:
            committer.submit([ref], _tx("second"), "chaos").result(timeout=15)
        assert ei.value.conflicts[ref].consuming_tx == _tx("first")

        maps = cluster.wait_converged(1)
        assert maps[0]._map[ref].consuming_tx == _tx("first")
        snap = committer.metrics.snapshot()
        assert snap["GroupCommit.RaftAppends"]["count"] == 2
    finally:
        if committer is not None:
            committer.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_leader_kill_cut_batch_retries_on_group_commit_site(seed):
    """A leader killed while a cut batch is in flight: the committer's
    ``consensus_round`` attempts ride utils/retry.py under the
    GroupCommitter's OWN retry site
    (``Retry.Attempts.raft.submit.group_commit``), and the election
    window neither duplicates nor loses a verdict. A future that times
    out inside the partition is NOT a lost verdict — after the heal a
    probe spend of the same ref must get a definitive answer that
    matches the replicated map exactly-once: either the original commit
    landed (probe conflicts against it) or it never did (probe wins)."""
    from corda_tpu.utils import retry as retry_mod

    cluster = _Cluster(seed)
    committer = None
    site_meter = "Retry.Attempts.raft.submit.group_commit"
    before = retry_mod.snapshot().get(site_meter, {}).get("count", 0)
    try:
        leader = cluster.wait_leader()
        follower = next(n for n in cluster.nodes if n is not leader)
        committer = GroupCommitter(follower, timeout_s=6.0, max_batch=4,
                                   max_latency_s=0.01, prescreen=False)
        refs = [_ref(f"site-{seed}-{i}") for i in range(3)]
        txs = [_tx(f"site-{seed}-{i}") for i in range(3)]
        with inject(*partition_rules(leader.node_id), seed=seed):
            futures = [committer.submit([r], tx, "chaos")
                       for r, tx in zip(refs, txs)]
            cluster.wait_leader(exclude=(leader,))
            outcomes = []
            for f in futures:
                try:
                    f.result(timeout=20)
                    outcomes.append("committed")
                except UniquenessException:
                    pytest.fail("distinct refs can never conflict "
                                "with each other")
                except Exception:
                    outcomes.append("pending")   # timed out in the window
        # heal, then resolve every pending verdict with a probe spend
        cluster.wait_leader()
        for i, out in enumerate(outcomes):
            if out == "committed":
                continue
            probe = committer.submit([refs[i]], _tx(f"probe-{seed}-{i}"),
                                     "chaos")
            try:
                probe.result(timeout=20)
                outcomes[i] = "never_landed"   # probe won: original lost
            except UniquenessException as ei:
                # original landed despite the client timeout: the map
                # must hold exactly the original tx, not the probe
                assert ei.value.conflicts[refs[i]].consuming_tx == txs[i]
                outcomes[i] = "committed"
        # exactly-once on every replica that saw the final history
        for i, out in enumerate(outcomes):
            if out == "committed":
                want = txs[i]
            else:
                want = _tx(f"probe-{seed}-{i}")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                owners = {m._map[refs[i]].consuming_tx
                          for m in cluster.maps if refs[i] in m._map}
                if owners == {want}:
                    break
                time.sleep(0.01)
            else:
                raise AssertionError(
                    f"ref {i} owners never converged on the "
                    f"{out} verdict")
        # the cut batch's appends metered under the committer's own site
        after = retry_mod.snapshot().get(site_meter, {}).get("count", 0)
        assert after - before >= 1
    finally:
        if committer is not None:
            committer.close()
        cluster.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_double_spend_pair_across_leader_kill(seed):
    """First spend submitted just as the leader is partitioned away
    mid-batch; the second spend goes to the successor. SAFETY: at most one
    of the pair ever reports commit, and the surviving replicas converge
    on one owner that matches the reported winner. The committer's backend
    is a follower (it survives the kill and forwards to whichever leader
    exists), the shape the notary node sees during a real re-election."""
    cluster = _Cluster(seed)
    committer = None
    try:
        leader = cluster.wait_leader()
        follower = next(n for n in cluster.nodes if n is not leader)
        committer = GroupCommitter(follower, timeout_s=8.0, max_batch=4,
                                   max_latency_s=0.01, prescreen=False)
        ref = _ref(f"kill-{seed}")

        with inject(*partition_rules(leader.node_id), seed=seed):
            # submitted into the partition window: its append either dies
            # with the old leader or retries onto the successor
            f_a = committer.submit([ref], _tx("a"), "chaos")
            cluster.wait_leader(exclude=(leader,))
            f_b = committer.submit([ref], _tx("b"), "chaos")

            outcomes = {}
            for name, fut in (("a", f_a), ("b", f_b)):
                try:
                    fut.result(timeout=20)
                    outcomes[name] = "committed"
                except UniquenessException:
                    outcomes[name] = "conflict"
                except Exception:
                    outcomes[name] = "lost"   # timed out in the partition

            committed = [n for n, o in outcomes.items() if o == "committed"]
            # SAFETY: never both; LIVENESS: the successor commits one
            assert len(committed) == 1, outcomes

            live = cluster.wait_converged(1, exclude=(leader,))
            assert live[0]._map[ref].consuming_tx == _tx(committed[0])

        # heal: the old leader rejoins and converges on the same winner
        winner = _tx(committed[0])
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(m._map.get(ref) is not None
                   and m._map[ref].consuming_tx == winner
                   for m in cluster.maps):
                break
            time.sleep(0.01)
        else:
            raise AssertionError("old leader never converged after heal")
    finally:
        if committer is not None:
            committer.close()
        cluster.close()
