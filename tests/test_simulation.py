"""Simulation (network-visualiser analog) tests."""
from corda_tpu.samples.simulation import Simulation


def test_simulation_conserves_and_streams_events():
    sim = Simulation(n_banks=3, seed=5, issue_cents=500_00).run(steps=8)
    # money is conserved across every random payment
    assert sim.total_cents() == 3 * 500_00
    kinds = {e[1] for e in sim.events}
    assert "payment-start" in kinds and "flow-complete" in kinds
    # observer callbacks fire per event (the visualiser feed)
    seen = []
    sim.add_observer(seen.append)
    sim.iterate()
    assert seen and seen[-1][0] == 9


def test_simulation_deterministic_by_seed():
    a = Simulation(n_banks=3, seed=5, issue_cents=500_00).run(steps=6)
    b = Simulation(n_banks=3, seed=5, issue_cents=500_00).run(steps=6)
    assert a.events == b.events
    assert a.balances() == b.balances()
