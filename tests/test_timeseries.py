"""Retained time-series plane: cascade downsampling, memory bounds, and
the snapshot contract /api/timeseries serves."""
from corda_tpu.observability.timeseries import (
    COLUMNS, TimeSeries, TimeSeriesStore, get_timeseries, set_timeseries)


def test_fine_ring_closes_into_coarser():
    ts = TimeSeries(resolutions=((1.0, 4), (10.0, 4)))
    # 12 samples, one per second: fine ring (cap 4) evicts; coarse absorbs
    for i in range(12):
        ts.record(float(i), float(i))
    snap = ts.snapshot()
    fine, coarse = snap
    assert fine["bucket_s"] == 1.0 and coarse["bucket_s"] == 10.0
    # fine keeps only its newest buckets (closed cap 4 + the open one)
    assert len(fine["points"]) <= 5
    # the evicted fine buckets survive, downsampled, in the coarse ring
    assert coarse["points"], "cascade lost the evicted buckets"
    t0_bucket = next(p for p in coarse["points"] if p[0] == 0.0)
    # columns: t, n, min, max, mean, last — 0..9 landed in the first
    # coarse bucket (sample 10 opened the next one, closing this)
    assert t0_bucket[1] == 10
    assert t0_bucket[2] == 0.0 and t0_bucket[3] == 9.0
    assert t0_bucket[4] == 4.5 and t0_bucket[5] == 9.0


def test_flush_seals_every_resolution():
    ts = TimeSeries(resolutions=((0.5, 8), (5.0, 8), (60.0, 8)))
    ts.record(100.0, 7.0)
    # one sample: every ring holds only an OPEN bucket until flush
    assert all(not r.closed for r in ts.rings)
    ts.flush()
    snap = ts.snapshot()
    assert all(len(level["points"]) == 1 for level in snap)
    for level in snap:
        assert level["points"][0][1] >= 1     # n
        assert level["points"][0][5] == 7.0   # last


def test_old_data_loses_resolution_never_existence():
    ts = TimeSeries(resolutions=((1.0, 2), (10.0, 2), (100.0, 2)))
    for i in range(100):
        ts.record(float(i), 1.0)
    ts.flush()
    # the fine rings keep only their newest buckets, but the coarsest
    # ring (2 buckets × 100 s horizon) still accounts for every sample —
    # old data lost resolution, not existence
    coarsest = ts.snapshot()[-1]
    assert sum(p[1] for p in coarsest["points"]) == 100


def test_store_snapshot_contract():
    store = TimeSeriesStore(resolutions=((1.0, 4), (10.0, 4)))
    for i in range(6):
        store.record("a", i, t=float(i))
        store.record("b", 2 * i, t=float(i))
    store.record("junk", "not-a-number", t=0.0)   # ignored, no series
    store.record("junk", None, t=0.0)
    store.record("junk", True, t=0.0)             # bools are not samples
    snap = store.snapshot()
    assert snap["columns"] == list(COLUMNS)
    assert sorted(snap["series"]) == ["a", "b"]
    assert snap["dropped_series"] == 0
    # names filter: unknown names are absent, never an error
    only_a = store.snapshot(names=["a", "nope"])
    assert sorted(only_a["series"]) == ["a"]
    # limit caps points per resolution, newest kept
    limited = store.snapshot(limit=1)
    for levels in limited["series"].values():
        for level in levels:
            assert len(level["points"]) <= 1
    rows = limited["series"]["a"][0]["points"]
    assert rows[0][0] == 5.0    # the newest fine bucket survived the cap


def test_store_bounds_series_count():
    store = TimeSeriesStore(resolutions=((1.0, 2),), max_series=3)
    for i in range(10):
        store.record(f"s{i}", 1.0, t=0.0)
    assert len(store.names()) == 3
    assert store.dropped_series == 7
    assert store.snapshot()["dropped_series"] == 7
    # existing series still record after the cap is hit
    store.record("s0", 2.0, t=1.0)


def test_cascade_under_long_horizon_clock():
    """Hours of injected clock across every sealing boundary of the soak
    resolutions (0.5 s → 5 s → 60 s): the finest rings wrap many times
    over but the coarsest still accounts for every sample inside its
    horizon — the property the soak leak fit stands on."""
    ts = TimeSeries(resolutions=((0.5, 240), (5.0, 240), (60.0, 240)))
    # one sample per second for 3 injected hours (no real sleeping)
    n = 3 * 3600
    for i in range(n):
        ts.record(float(i), float(i % 7))
    ts.flush()
    fine, mid, coarse = ts.snapshot()
    assert len(fine["points"]) <= 240 and len(mid["points"]) <= 240
    # the 60 s ring holds the newest 240 minutes — 3 h fits entirely
    assert len(coarse["points"]) == n // 60
    assert sum(p[1] for p in coarse["points"]) == n
    # buckets stay time-ordered after hours of cascade churn
    starts = [p[0] for p in coarse["points"]]
    assert starts == sorted(starts)


def test_snapshot_since_filter_straddling_a_seal():
    """``since`` is the incremental-poller contract: only buckets
    starting at/after the cutoff return, and a bucket that was OPEN at
    the cutoff reappears (sealed) in the next poll — at-least-once,
    never silently dropped."""
    ts = TimeSeries(resolutions=((1.0, 8), (10.0, 8)))
    for i in range(6):
        ts.record(float(i), float(i))
    # the t=5 bucket is still open; a poller that saw through t=4 asks
    # with since=5 and gets the open bucket's current aggregate
    snap = ts.snapshot(since=5.0)
    fine = snap[0]["points"]
    assert [p[0] for p in fine] == [5.0]
    # more samples land in that same bucket after the poll, then it
    # seals: polling with the SAME cutoff re-delivers it, now final
    ts.record(5.5, 100.0)
    ts.record(6.0, 1.0)          # opens t=6, sealing the t=5 bucket
    fine = ts.snapshot(since=5.0)[0]["points"]
    assert [p[0] for p in fine] == [5.0, 6.0]
    assert fine[0][1] == 2 and fine[0][3] == 100.0   # n, max — resealed
    # a cutoff beyond everything is an empty (not missing) resolution
    assert ts.snapshot(since=1e9)[0]["points"] == []


def test_snapshot_resolution_filter():
    ts = TimeSeries(resolutions=((0.5, 8), (5.0, 8), (60.0, 8)))
    for i in range(20):
        ts.record(float(i), 1.0)
    ts.flush()
    only = ts.snapshot(resolution=5.0)
    assert len(only) == 1 and only[0]["bucket_s"] == 5.0
    assert only[0]["points"]
    # an unknown resolution matches nothing — empty list, not an error
    assert ts.snapshot(resolution=7.0) == []


def test_store_snapshot_since_and_resolution_passthrough():
    store = TimeSeriesStore(resolutions=((1.0, 8), (10.0, 8)))
    for i in range(12):
        store.record("Resource.X", float(i), t=float(i))
    store.flush()
    snap = store.snapshot(names=["Resource.X"], since=8.0, resolution=1.0)
    levels = snap["series"]["Resource.X"]
    assert len(levels) == 1 and levels[0]["bucket_s"] == 1.0
    assert all(p[0] >= 8.0 for p in levels[0]["points"])
    assert snap["columns"] == list(COLUMNS)


def test_global_store_seam():
    mine = TimeSeriesStore()
    prev = set_timeseries(mine)
    try:
        assert get_timeseries() is mine
        get_timeseries().record("x", 1.0, t=0.0)
        assert mine.names() == ["x"]
    finally:
        set_timeseries(prev)
    assert get_timeseries() is not mine
