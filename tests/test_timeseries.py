"""Retained time-series plane: cascade downsampling, memory bounds, and
the snapshot contract /api/timeseries serves."""
from corda_tpu.observability.timeseries import (
    COLUMNS, TimeSeries, TimeSeriesStore, get_timeseries, set_timeseries)


def test_fine_ring_closes_into_coarser():
    ts = TimeSeries(resolutions=((1.0, 4), (10.0, 4)))
    # 12 samples, one per second: fine ring (cap 4) evicts; coarse absorbs
    for i in range(12):
        ts.record(float(i), float(i))
    snap = ts.snapshot()
    fine, coarse = snap
    assert fine["bucket_s"] == 1.0 and coarse["bucket_s"] == 10.0
    # fine keeps only its newest buckets (closed cap 4 + the open one)
    assert len(fine["points"]) <= 5
    # the evicted fine buckets survive, downsampled, in the coarse ring
    assert coarse["points"], "cascade lost the evicted buckets"
    t0_bucket = next(p for p in coarse["points"] if p[0] == 0.0)
    # columns: t, n, min, max, mean, last — 0..9 landed in the first
    # coarse bucket (sample 10 opened the next one, closing this)
    assert t0_bucket[1] == 10
    assert t0_bucket[2] == 0.0 and t0_bucket[3] == 9.0
    assert t0_bucket[4] == 4.5 and t0_bucket[5] == 9.0


def test_flush_seals_every_resolution():
    ts = TimeSeries(resolutions=((0.5, 8), (5.0, 8), (60.0, 8)))
    ts.record(100.0, 7.0)
    # one sample: every ring holds only an OPEN bucket until flush
    assert all(not r.closed for r in ts.rings)
    ts.flush()
    snap = ts.snapshot()
    assert all(len(level["points"]) == 1 for level in snap)
    for level in snap:
        assert level["points"][0][1] >= 1     # n
        assert level["points"][0][5] == 7.0   # last


def test_old_data_loses_resolution_never_existence():
    ts = TimeSeries(resolutions=((1.0, 2), (10.0, 2), (100.0, 2)))
    for i in range(100):
        ts.record(float(i), 1.0)
    ts.flush()
    # the fine rings keep only their newest buckets, but the coarsest
    # ring (2 buckets × 100 s horizon) still accounts for every sample —
    # old data lost resolution, not existence
    coarsest = ts.snapshot()[-1]
    assert sum(p[1] for p in coarsest["points"]) == 100


def test_store_snapshot_contract():
    store = TimeSeriesStore(resolutions=((1.0, 4), (10.0, 4)))
    for i in range(6):
        store.record("a", i, t=float(i))
        store.record("b", 2 * i, t=float(i))
    store.record("junk", "not-a-number", t=0.0)   # ignored, no series
    store.record("junk", None, t=0.0)
    store.record("junk", True, t=0.0)             # bools are not samples
    snap = store.snapshot()
    assert snap["columns"] == list(COLUMNS)
    assert sorted(snap["series"]) == ["a", "b"]
    assert snap["dropped_series"] == 0
    # names filter: unknown names are absent, never an error
    only_a = store.snapshot(names=["a", "nope"])
    assert sorted(only_a["series"]) == ["a"]
    # limit caps points per resolution, newest kept
    limited = store.snapshot(limit=1)
    for levels in limited["series"].values():
        for level in levels:
            assert len(level["points"]) <= 1
    rows = limited["series"]["a"][0]["points"]
    assert rows[0][0] == 5.0    # the newest fine bucket survived the cap


def test_store_bounds_series_count():
    store = TimeSeriesStore(resolutions=((1.0, 2),), max_series=3)
    for i in range(10):
        store.record(f"s{i}", 1.0, t=0.0)
    assert len(store.names()) == 3
    assert store.dropped_series == 7
    assert store.snapshot()["dropped_series"] == 7
    # existing series still record after the cap is hit
    store.record("s0", 2.0, t=1.0)


def test_global_store_seam():
    mine = TimeSeriesStore()
    prev = set_timeseries(mine)
    try:
        assert get_timeseries() is mine
        get_timeseries().record("x", 1.0, t=0.0)
        assert mine.names() == ["x"]
    finally:
        set_timeseries(prev)
    assert get_timeseries() is not mine
