"""Device circuit-breaker chaos tests: a 100%-failing device dispatch path
must degrade that scheme to host verification with ZERO dropped or hung
futures, trip the breaker (gauges + trip meter), and recover through a
half-open probe once the device behaves again.

The storm is injected at the ``batcher.device_dispatch`` fault point with
``detail=<scheme>``, so only the targeted scheme degrades. The breaker
clock is injected so cooldown expiry is stepped, not slept.
"""
import pytest

from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.schemes import EDDSA_ED25519_SHA512
from corda_tpu.core.crypto.signatures import Crypto
from corda_tpu.testing.faults import FaultRule, inject
from corda_tpu.verifier.batcher import SignatureBatcher

pytestmark = pytest.mark.chaos

SEEDS = [7, 101, 9001]

KP = generate_keypair(EDDSA_ED25519_SHA512, entropy=b"\x71" * 32)
CONTENT = b"breaker chaos content"
SIG = Crypto.sign_with_key(KP, CONTENT).bytes


def make_batcher(clock):
    return SignatureBatcher(host_crossover=1, max_latency_s=0.001,
                            breaker_threshold=3, breaker_cooldown_s=5.0,
                            breaker_clock=lambda: clock[0])


def stub_device(b):
    """Replace the ed25519 device-start seam with an instant all-valid
    kernel: recovery-probe tests must not pay an XLA compile."""
    b._start_ed25519 = lambda items: (None, lambda pending: [True] * len(items))


@pytest.mark.parametrize("seed", SEEDS)
def test_storm_trips_breaker_zero_dropped_results(seed):
    """100% device-dispatch failure: every future still resolves (host
    fallback), the breaker opens after exactly `threshold` failures, and
    no further device dispatch is attempted while it is open."""
    clock = [0.0]
    b = make_batcher(clock)
    try:
        with inject(FaultRule("batcher.device_dispatch", "raise",
                              detail="ed25519"), seed=seed) as inj:
            for _ in range(8):
                # sequential: each submit is its own flush → own dispatch
                assert b.submit(KP.public, SIG, CONTENT).result(timeout=60) \
                    is True

            st = b.breaker_status()["ed25519"]
            assert st["state"] == "open"
            assert st["trips"] == 1
            # after the third failure the breaker stopped trying the device
            assert inj.fired("batcher.device_dispatch") == 3

        snap = b.metrics.snapshot()
        assert snap["Breaker.Trips"]["count"] == 1
        assert snap["Breaker.Trips.ed25519"]["count"] == 1
        assert snap["Breaker.State.ed25519"]["value"] == 1        # OPEN
        assert snap["Breaker.State.secp256k1"]["value"] == 0      # untouched
        assert snap["SigBatcher.BatchFailure"]["count"] == 3      # fallbacks
        assert snap["SigBatcher.BreakerRouted"]["count"] == 5     # open-gated
    finally:
        b.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_half_open_probe_reopens_then_restores(seed):
    """Cooldown expiry admits exactly one probe. While the device is still
    broken the probe re-opens the breaker WITHOUT a second trip; once the
    device works the probe closes it and the scheme leaves degradation."""
    clock = [0.0]
    b = make_batcher(clock)
    try:
        with inject(FaultRule("batcher.device_dispatch", "raise",
                              detail="ed25519"), seed=seed) as inj:
            for _ in range(3):
                assert b.submit(KP.public, SIG, CONTENT).result(timeout=60)
            assert b.breaker_status()["ed25519"]["state"] == "open"

            # cooldown elapses but the device is STILL broken: the probe
            # fails and re-opens — no new trip, cooldown restarts
            clock[0] += 6.0
            assert b.submit(KP.public, SIG, CONTENT).result(timeout=60)
            st = b.breaker_status()["ed25519"]
            assert st["state"] == "open"
            assert st["trips"] == 1
            assert inj.fired("batcher.device_dispatch") == 4   # the probe

        # fault gone, device healthy (stubbed: no XLA compile in the fast
        # gate), cooldown elapses again: the next probe closes the breaker
        stub_device(b)
        clock[0] += 6.0
        assert b.submit(KP.public, SIG, CONTENT).result(timeout=60) is True
        st = b.breaker_status()["ed25519"]
        assert st["state"] == "closed"
        assert st["trips"] == 1
        assert b.metrics.snapshot()["Breaker.State.ed25519"]["value"] == 0
    finally:
        b.close()


def test_breaker_trip_surfaces_degraded_in_health():
    """An open breaker rides /readyz as `degraded` (the node serves — host
    path — but ops can see the device is out) and clears on recovery."""
    from corda_tpu.node.rpc import CordaRPCOps
    from corda_tpu.testing import MockNetwork
    from corda_tpu.verifier.service import TpuTransactionVerifierService

    network = MockNetwork()
    network.create_notary_node()
    alice = network.create_node("O=Alice, L=Madrid, C=ES")
    network.start_nodes()
    ops = CordaRPCOps(alice.services, alice.smm)
    svc = TpuTransactionVerifierService(
        workers=1, batcher=SignatureBatcher(use_device=False))
    alice.services.verifier_service = svc
    try:
        health = ops.health()
        assert health["ready"] is True
        assert "degraded" not in health

        breaker = svc.batcher._breakers["ed25519"]
        for _ in range(3):
            breaker.record_failure()
        health = ops.health()
        assert health["ready"] is True        # degraded, NOT unready
        assert health["degraded"]["device_breakers"]["ed25519"]["state"] \
            == "open"

        breaker.clock = lambda: breaker._opened_at + 10.0
        assert breaker.allow()                # half-open probe admitted
        breaker.record_success()
        health = ops.health()
        assert "degraded" not in health
    finally:
        alice.services.verifier_service = None
        svc.shutdown()


@pytest.mark.slow
def test_storm_and_recovery_with_real_kernels():
    """The unstubbed variant: the recovery probe runs the real ed25519
    device kernel (XLA compile and all) — nightly-tier proof that the
    half-open path restores genuine device verification."""
    clock = [0.0]
    b = make_batcher(clock)
    try:
        with inject(FaultRule("batcher.device_dispatch", "raise",
                              detail="ed25519"), seed=7):
            for _ in range(4):
                assert b.submit(KP.public, SIG, CONTENT).result(timeout=60)
            assert b.breaker_status()["ed25519"]["state"] == "open"
        clock[0] += 6.0
        assert b.submit(KP.public, SIG, CONTENT).result(timeout=600) is True
        assert b.breaker_status()["ed25519"]["state"] == "closed"
        assert b.metrics.snapshot()["SigBatcher.DeviceBatches"]["count"] >= 1
    finally:
        b.close()
