"""Contract upgrade flow tests (ContractUpgradeFlowTest analogs): authorised
upgrades succeed with all signatures; unauthorised or tampered ones refuse."""
import pytest

from corda_tpu.core.contracts import Command, TransactionState
from corda_tpu.core.contracts.structures import StateAndRef, StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.core.serialization import register_type, serializable
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.flows.contract_upgrade import (ContractUpgradeException,
                                              ContractUpgradeFlow,
                                              UpgradeCommand, UpgradedContract,
                                              authorise_contract_upgrade,
                                              install_contract_upgrade_acceptor)
from corda_tpu.flows.library import FinalityFlow
from corda_tpu.testing import DummyContract, DummyState, MockNetwork
from corda_tpu.testing.dummy import _DUMMY_CONTRACT


@serializable("test.DummyStateV2",
              to_fields=lambda s: [s.magic_number, list(s.owners)],
              from_fields=lambda f: DummyStateV2(f[0], tuple(f[1])))
class DummyStateV2:
    def __init__(self, magic_number, owners):
        self.magic_number = magic_number
        self.owners = tuple(owners)

    @property
    def contract(self):
        return DUMMY_V2

    @property
    def participants(self):
        return list(self.owners)

    def __eq__(self, other):
        return (isinstance(other, DummyStateV2)
                and other.magic_number == self.magic_number
                and other.owners == self.owners)

    def __hash__(self):
        return hash((self.magic_number, self.owners))


class DummyContractV2(UpgradedContract):
    legacy_contract_name = (f"{DummyContract.__module__}."
                            f"{DummyContract.__qualname__}")
    legal_contract_reference = SecureHash.sha256(b"dummy v2")

    def upgrade(self, old_state):
        return DummyStateV2(old_state.magic_number * 100, old_state.owners)

    def verify(self, tx) -> None:
        pass  # accepts upgrades


DUMMY_V2 = DummyContractV2()
register_type("test.DummyContractV2", DummyContractV2,
              to_fields=lambda c: [], from_fields=lambda f: DUMMY_V2)


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    alice = network.create_node("O=Alice, L=London, C=GB")
    bob = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()
    for node in (alice, bob):
        install_contract_upgrade_acceptor(node.smm)
    return network, notary, alice, bob


def issue_shared(network, alice, bob, notary):
    builder = TransactionBuilder(notary=notary.party)
    builder.add_output_state(DummyState(
        5, (alice.party.owning_key, bob.party.owning_key)))
    builder.add_command(DummyContract.Create(), alice.party.owning_key)
    stx = alice.services.sign_initial_transaction(builder.to_wire_transaction())
    fsm = alice.start_flow(FinalityFlow(stx))
    network.run_network()
    final = fsm.result_future.result(timeout=5)
    return StateAndRef(final.tx.outputs[0], StateRef(final.id, 0))


def test_authorised_upgrade_succeeds(net):
    network, notary, alice, bob = net
    sref = issue_shared(network, alice, bob, notary)
    # bob authorises; alice instigates
    authorise_contract_upgrade(bob.services, sref, DummyContractV2)
    fsm = alice.start_flow(ContractUpgradeFlow(sref, DUMMY_V2))
    network.run_network()
    new_ref = fsm.result_future.result(timeout=5)
    assert isinstance(new_ref.state.data, DummyStateV2)
    assert new_ref.state.data.magic_number == 500
    final = alice.services.storage.get_transaction(new_ref.ref.txhash)
    assert bob.party.owning_key in {s.by for s in final.sigs}
    assert isinstance(final.tx.commands[0].value, UpgradeCommand)
    # bob's vault follows the upgrade
    assert bob.services.storage.get_transaction(new_ref.ref.txhash) is not None


def test_unauthorised_upgrade_refused(net):
    network, notary, alice, bob = net
    sref = issue_shared(network, alice, bob, notary)
    fsm = alice.start_flow(ContractUpgradeFlow(sref, DUMMY_V2))
    network.run_network()
    from corda_tpu.flows import FlowException
    # the acceptor's refusal crosses the session as a FlowException message
    with pytest.raises(FlowException, match="not authorised"):
        fsm.result_future.result(timeout=5)
