"""Driver integration test: REAL node processes over the TCP plane.

Reference parity: the driver{}-based integration tier (SURVEY.md §4.2) —
spawns a network-map node, a notary and two party nodes as subprocesses,
then runs cash issuance + payment across them via RPC, exactly as
BootTests / NodePerformanceTests drive real nodes.
"""
import json
import os
import time

import pytest

import corda_tpu.finance  # noqa: F401 — load the cordapp's wire types client-side
from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.testing.driver import DriverDSL, driver


@pytest.mark.medium     # per-round gate: ≥1 driver-cluster test (VERDICT r3 #8)
def test_cash_payment_across_real_nodes(tmp_path):
    with driver(tmp_path) as dsl:
        notary = dsl.start_notary_node()
        alice = dsl.start_node("O=Alice, L=London, C=GB")
        bob = dsl.start_node("O=Bob, L=Paris, C=FR")
        dsl.wait_for_network(4)  # map + notary + alice + bob

        notary_party = alice.rpc.notary_identities()[0]
        alice_party = alice.rpc.node_identity().legal_identity
        bob_party = bob.rpc.node_identity().legal_identity

        # Alice self-issues $100, then pays Bob $40
        alice.rpc.start_flow_and_wait(
            "CashIssueFlow", Amount(10000, USD), b"\x01", alice_party,
            notary_party)
        final = alice.rpc.start_flow_and_wait(
            "CashPaymentFlow", Amount(4000, USD), bob_party)
        assert final is not None

        # Bob's vault (in a different OS process) shows the $40
        import time
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            states = bob.rpc.vault_snapshot()
            if states:
                break
            time.sleep(0.5)
        amounts = [s.state.data.amount.quantity for s in states]
        assert amounts == [4000]


@pytest.mark.slow
def test_loadtest_against_driver_cluster_with_kill_restart(tmp_path):
    """VERDICT r2 #9: the loadtest mix runs over REAL node subprocesses; one
    node is hard-killed mid-load and restarted from its on-disk state
    (identity, durable tx store, checkpoints); the run completes with value
    conserved and reports flows/s as a BENCH-style JSON."""
    import json as _json

    from corda_tpu.tools.loadtest import run_driver_cluster_load

    with driver(tmp_path, startup_timeout_s=120.0) as dsl:
        notary = dsl.start_notary_node()
        alice = dsl.start_node("O=Alice, L=London, C=GB")
        bob = dsl.start_node("O=Bob, L=Paris, C=FR")
        dsl.wait_for_network(4)
        notary_party = alice.rpc.notary_identities()[0]
        report_path = str(tmp_path / "loadtest.json")
        parties = [alice, bob]
        report = run_driver_cluster_load(
            dsl, parties, notary_party, iterations=8, seed=5,
            kill_restart_at=4, report_path=report_path)
        assert report["conserved"], report
        assert report["flows"] >= 8
        assert report["value"] > 0
        assert _json.load(open(report_path)) == report
        # bob (the victim) kept his pre-kill holdings across the restart
        bob2 = parties[1]
        assert bob2 is not bob
        assert bob2.rpc.get_cash_balances().get("USD", 0) >= 0


@pytest.mark.slow
def test_loadtest_hang_under_load(tmp_path):
    """Disruption.kt's hang-under-load (SSH-suspend edition → SIGSTOP): one
    member freezes mid-run with sockets held open; the cluster keeps making
    progress around it, the member resumes, and value is conserved."""
    from corda_tpu.tools.loadtest import run_driver_cluster_load

    with driver(tmp_path, startup_timeout_s=120.0) as dsl:
        dsl.start_notary_node()
        alice = dsl.start_node("O=Alice, L=London, C=GB")
        bob = dsl.start_node("O=Bob, L=Paris, C=FR")
        dsl.wait_for_network(4)
        notary_party = alice.rpc.notary_identities()[0]
        report = run_driver_cluster_load(
            dsl, [alice, bob], notary_party, iterations=8, seed=7,
            hang_window=(2, 5))
        assert report["conserved"], report
        assert report["flows"] >= 8


@pytest.mark.medium     # per-round gate: ≥1 subprocess-verifier test (VERDICT r3 #8)
def test_verifier_worker_death_redistribution_device_path(tmp_path):
    """VerifierTests.kt:73+ parity, upgraded: TWO standalone verifier worker
    SUBPROCESSES consume a generated ledger over the real TCP plane with
    their signature EC math on the device batcher; one worker is hard-killed
    mid-ledger, the redelivery timeout redistributes its outstanding work,
    and the run completes. The survivor's stats file proves device-verified
    verdicts happened in the worker processes (VERDICT r2 #1)."""
    import corda_tpu.testing.dummy  # noqa: F401 — wire types for the ledger
    from corda_tpu.testing.generated_ledger import make_generated_ledger
    from corda_tpu.testing.services import MockServices
    from corda_tpu.verifier.out_of_process import (
        OutOfProcessTransactionVerifierService)
    from corda_tpu.network.tcp import TcpMessagingService

    def literal_resolve(name):
        host, _, port = name.rpartition(":")
        try:
            return host, int(port)
        except ValueError:
            return None

    # ed25519-only keeps the worker subprocesses' compile surface to one
    # kernel family (per-process trace+lower is ~10s per bucket on CPU);
    # the mixed-scheme device path is covered by the in-memory tier
    ledger = make_generated_ledger(30, seed=11, scheme_mix=False)
    services = MockServices()
    for stx in ledger.transactions:
        services.record_transactions(stx)

    messaging = TcpMessagingService("requestor", "127.0.0.1", 0,
                                    literal_resolve)
    messaging._name = f"127.0.0.1:{messaging.port}"
    # generous redelivery: a worker cold-compiling CPU kernels is SLOW, not
    # dead; the periodic worker re-hello re-attaches it if flagged anyway
    svc = OutOfProcessTransactionVerifierService(messaging,
                                                 redelivery_timeout_s=60.0)
    dsl = DriverDSL(str(tmp_path), startup_timeout_s=120.0)
    stats2 = os.path.join(str(tmp_path), "worker2-stats.json")
    # worker subprocesses must run JAX on CPU with the suite's compile cache
    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    env = {"JAX_PLATFORMS": "cpu",
           "JAX_COMPILATION_CACHE_DIR": cache,
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    try:
        w1 = dsl.start_verifier(messaging.my_address, host_crossover=0,
                                extra_env=env)
        w2 = dsl.start_verifier(messaging.my_address, host_crossover=0,
                                stats_file=stats2, extra_env=env)
        deadline = time.monotonic() + 30
        while svc.queue.worker_count < 2:
            assert time.monotonic() < deadline, "workers did not attach"
            time.sleep(0.2)

        # warm both workers' kernels before the clock-sensitive phase: the
        # first device batches carry the jit compiles
        warm = [svc.verify_signed(stx, services)
                for stx in ledger.transactions[:4]]
        for f in warm:
            f.result(timeout=540)

        half = len(ledger.transactions) // 2
        futures = [svc.verify_signed(stx, services)
                   for stx in ledger.transactions[4:half]]
        w1.kill()                                   # mid-ledger, no Goodbye
        futures += [svc.verify_signed(stx, services)
                    for stx in ledger.transactions[half:]]

        deadline = time.monotonic() + 540
        for f in futures:
            f.result(timeout=max(1.0, deadline - time.monotonic()))

        w2.stop()                                   # SIGTERM → stats flush
        deadline = time.monotonic() + 15
        while not os.path.exists(stats2):
            assert time.monotonic() < deadline, "no stats file written"
            time.sleep(0.2)
        stats = json.load(open(stats2))
        assert stats["verified_count"] > 0
        assert stats["metrics"]["SigBatcher.DeviceChecked"]["count"] > 0
    finally:
        dsl.shutdown()
        svc.shutdown()
        messaging.stop()
