"""Driver integration test: REAL node processes over the TCP plane.

Reference parity: the driver{}-based integration tier (SURVEY.md §4.2) —
spawns a network-map node, a notary and two party nodes as subprocesses,
then runs cash issuance + payment across them via RPC, exactly as
BootTests / NodePerformanceTests drive real nodes.
"""
import pytest

import corda_tpu.finance  # noqa: F401 — load the cordapp's wire types client-side
from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.testing.driver import driver


@pytest.mark.slow
def test_cash_payment_across_real_nodes(tmp_path):
    with driver(tmp_path) as dsl:
        notary = dsl.start_notary_node()
        alice = dsl.start_node("O=Alice, L=London, C=GB")
        bob = dsl.start_node("O=Bob, L=Paris, C=FR")
        dsl.wait_for_network(4)  # map + notary + alice + bob

        notary_party = alice.rpc.notary_identities()[0]
        alice_party = alice.rpc.node_identity().legal_identity
        bob_party = bob.rpc.node_identity().legal_identity

        # Alice self-issues $100, then pays Bob $40
        alice.rpc.start_flow_and_wait(
            "CashIssueFlow", Amount(10000, USD), b"\x01", alice_party,
            notary_party)
        final = alice.rpc.start_flow_and_wait(
            "CashPaymentFlow", Amount(4000, USD), bob_party)
        assert final is not None

        # Bob's vault (in a different OS process) shows the $40
        import time
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            states = bob.rpc.vault_snapshot()
            if states:
                break
            time.sleep(0.5)
        amounts = [s.state.data.amount.quantity for s in states]
        assert amounts == [4000]
