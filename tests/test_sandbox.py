"""Deterministic contract sandbox tests.

Reference analogs: experimental/sandbox's WhitelistClassLoaderTest (accept
whitelisted code, reject banned constructs) and the runtime cost-accounting
thresholds (runaway code terminates deterministically).
"""
import pytest

from corda_tpu.core.contracts.sandbox import (DeterministicSandbox,
                                              SandboxBudgetError,
                                              SandboxViolation, validate)

CONTRACT = """
class TokenContract:
    def verify(self, inputs, outputs):
        total_in = sum(v for v in inputs)
        total_out = sum(v for v in outputs)
        if total_in != total_out:
            raise ValueError("conservation violated")
        return "ok"
"""


def test_loads_and_runs_whitelisted_contract():
    sandbox = DeterministicSandbox()
    ns = sandbox.load(CONTRACT)
    contract = ns["TokenContract"]()
    assert sandbox.run(contract.verify, [5, 7], [12]) == "ok"
    with pytest.raises(ValueError, match="conservation"):
        sandbox.run(contract.verify, [5], [12])
    assert sandbox.spent > 0


@pytest.mark.parametrize("source,label", [
    ("import os", "import"),
    ("from os import path", "import"),
    ("x = {1, 2}", "set display"),
    ("x = {v for v in range(3)}", "set comprehension"),
    ("def f():\n    global x", "global"),
    ("async def f():\n    pass", "async"),
    ("x = obj._secret", "underscore attribute"),
    ("x = __import__", "dunder name"),
    ("with open('f') as f:\n    pass", "with"),
])
def test_banned_constructs_rejected(source, label):
    with pytest.raises(SandboxViolation):
        validate(source)


def test_unsafe_builtins_absent():
    sandbox = DeterministicSandbox()
    for expr in ("eval('1')", "exec('x=1')", "open('/etc/hostname')",
                 "getattr(int, 'bit_length')", "globals()", "hash('a')",
                 "id(1)", "print('hi')"):
        with pytest.raises((NameError, KeyError)):
            sandbox.load(f"result = {expr}")


def test_runaway_loop_hits_budget():
    sandbox = DeterministicSandbox(instruction_budget=1000)
    with pytest.raises(SandboxBudgetError):
        sandbox.load("while True:\n    x = 1\n")


def test_iteration_is_charged():
    src = "total = sum(i for i in range(10_000))"
    with pytest.raises(SandboxBudgetError):
        DeterministicSandbox(instruction_budget=100).load(src)
    ns = DeterministicSandbox(instruction_budget=100_000).load(src)
    assert ns["total"] == sum(range(10_000))


def test_budget_spans_later_calls():
    """Functions defined in the sandbox keep charging when called after
    load — the budget covers the contract's whole lifetime."""
    sandbox = DeterministicSandbox(instruction_budget=5_000)
    ns = sandbox.load("def burn(n):\n    for i in range(n):\n        x = i\n")
    sandbox.run(ns["burn"], 100)
    with pytest.raises(SandboxBudgetError):
        sandbox.run(ns["burn"], 100_000)


def test_hook_rebinding_rejected():
    """ADVICE r1: single-underscore names (incl. the injected cost hooks)
    must be unnameable from contract source."""
    for src in ("_sandbox_charge = len", "_sandbox_iter = iter",
                "_x = 1", "def _f():\n    pass",
                "def f(_a):\n    pass"):
        with pytest.raises(SandboxViolation, match="underscore"):
            validate(src)


def test_budget_kill_not_swallowed_by_except():
    """ADVICE r1: `while True: try: ... except Exception: pass` must not
    neutralize the budget — SandboxCostExceeded derives from BaseException."""
    src = ("while True:\n"
           "    try:\n"
           "        x = 1\n"
           "    except Exception:\n"
           "        x = 2\n")
    with pytest.raises(SandboxBudgetError):
        DeterministicSandbox(instruction_budget=1000).load(src)


def test_bare_except_rejected():
    with pytest.raises(SandboxViolation, match="bare except"):
        validate("try:\n    x = 1\nexcept:\n    pass\n")


def test_single_statement_blowups_capped():
    """ADVICE r1: one statement must not smuggle unbounded work past the
    per-statement accounting."""
    for src in ("x = 10 ** (10 ** 8)",
                "x = 2 ** 100_000_000",
                "x = 1 << 10 ** 9",
                "x = 'a' * (10 ** 12)",
                "x = pow(2, 10 ** 9)",
                "x = list(range(10 ** 10))",
                "y = 7\ny **= 10 ** 8",
                "x = bytes(10 ** 10)",
                # s = s + s doubling: '+' is priced by sequence size, so the
                # budget dies exponentially alongside the data (no OOM race)
                "s = 'a' * 1000\n" + "s = s + s\n" * 40,
                # repeated in-budget ranges must still charge proportionally
                "for i in range(100):\n    x = list(range(99_000))"):
        with pytest.raises(SandboxBudgetError):
            DeterministicSandbox(instruction_budget=100_000).load(src)


def test_guarded_ops_still_correct():
    ns = DeterministicSandbox().load(
        "a = 3 ** 5\n"
        "b = 'ab' * 3\n"
        "c = pow(7, 11, 13)\n"
        "d = 1 << 10\n"
        "e = 6 * 7\n"
        "f = 2\n"
        "f **= 3\n"
        "g = [0] * 4\n")
    assert ns["a"] == 243 and ns["b"] == "ababab" and ns["c"] == pow(7, 11, 13)
    assert ns["d"] == 1024 and ns["e"] == 42 and ns["f"] == 8
    assert ns["g"] == [0, 0, 0, 0]


def test_default_arg_blowup_guarded():
    """Review r2: default-argument expressions execute at def time and must
    route through the binop guards too."""
    with pytest.raises(SandboxBudgetError):
        DeterministicSandbox(instruction_budget=100_000).load(
            "def f(x=10 ** (10 ** 8)):\n    return x\n")


def test_augassign_preserves_aliasing():
    """Review r2: `b += [2]` must mutate an aliased list in place, exactly
    like Python — the guard uses the in-place operator."""
    ns = DeterministicSandbox().load(
        "a = [1]\nb = a\nb += [2]\nc = 'x'\nc += 'y'\n")
    assert ns["a"] == [1, 2] and ns["b"] is ns["a"]
    assert ns["c"] == "xy"


def test_trivial_base_powers_stay_cheap():
    """Review r2: |base| <= 1 powers are O(1); they must not charge by
    exponent size."""
    ns = DeterministicSandbox(instruction_budget=1000).load(
        "a = 1 ** (10 ** 8)\nb = 0 ** (10 ** 8)\nc = (-1) ** (10 ** 8)\n")
    assert ns["a"] == 1 and ns["b"] == 0 and ns["c"] == 1


def test_except_handler_name_cannot_shadow_hooks():
    with pytest.raises(SandboxViolation, match="underscore"):
        validate("try:\n    x = 1\nexcept ValueError as _sandbox_charge:\n"
                 "    x = 2\n")


def test_budget_error_is_plain_exception_at_host_boundary():
    """Review r2: the kill is a BaseException INSIDE the sandbox but a
    plain Exception at load()/run(), so host `except Exception` error paths
    treat it as an ordinary contract failure."""
    sandbox = DeterministicSandbox(instruction_budget=100)
    try:
        sandbox.load("while True:\n    x = 1\n")
    except Exception as e:
        assert isinstance(e, SandboxBudgetError)
    else:
        raise AssertionError("budget kill did not surface")


def test_match_statement_rejected():
    """ADVICE r2 (high): MatchAs/MatchStar/MatchMapping capture names are
    raw string attributes the ast.Name underscore ban never inspects —
    `case _sandbox_charge:` would rebind the charge hook. The whole match
    statement is banned."""
    for src in ("match int:\n    case _sandbox_charge:\n        pass\n",
                "match [1]:\n    case [*_sandbox_iter]:\n        pass\n",
                "match {}:\n    case {**_sandbox_binop}:\n        pass\n",
                "match 1:\n    case 1:\n        pass\n"):
        with pytest.raises(SandboxViolation, match="match statement"):
            validate(src)


def test_match_rebinding_cannot_neutralize_budget():
    """The r2 exploit end-to-end: without the Match ban, rebinding the
    charge hook lets a 50M-iteration loop run with spent==1."""
    with pytest.raises((SandboxViolation, SandboxBudgetError)):
        DeterministicSandbox(instruction_budget=1000).load(
            "match int:\n    case _sandbox_charge:\n        pass\n"
            "while True:\n    x = 1\n")


def test_format_width_blowups_capped():
    """ADVICE r2 (medium): string-formatting surfaces must not allocate
    hundreds of MB for ~2 charged units."""
    for src in ("x = format(1, '>200000000')",
                "x = '%0200000000d' % 1",
                "y = '%0200000000d'\ny %= 1",
                # review r3: '*' takes the width from the argument tuple and
                # can't be priced statically — refused outright
                "x = '%*d' % (50000000, 1)",
                "x = '%.*f' % (50000000, 1.0)",
                # review r3: mapping-key specs carry the same width surface
                "x = '%(k)050000000d' % {'k': 1}"):
        with pytest.raises(SandboxBudgetError):
            DeterministicSandbox(instruction_budget=100_000).load(src)


def test_huge_digit_runs_do_not_escape_as_valueerror():
    """Review r3: digit runs past CPython's int-to-str limit (4300, and
    per-process configurable) must surface as the sandbox's own exceptions,
    not a raw ValueError."""
    run = "9" * 5000
    with pytest.raises(SandboxViolation):
        validate(f"x = f'{{1:>{run}}}'")
    with pytest.raises(SandboxBudgetError):
        DeterministicSandbox().load(f"x = '%{run}d' % 1")
    with pytest.raises(SandboxBudgetError):
        DeterministicSandbox().load(f"x = format(1, '>{run}')")


def test_literal_digits_in_percent_template_are_free():
    """Review r3: only conversion-spec widths count — large numeric literals
    in the template text are not padding."""
    ns = DeterministicSandbox().load(
        "x = 'block 20260730123456: %d' % 7\n"
        "y = '100%% of %5d' % 42\n")
    assert ns["x"] == "block 20260730123456: 7"
    assert ns["y"] == "100% of    42"


def test_width_taking_str_methods_banned():
    for src in ("x = 'a'.ljust(200000000)",
                "x = 'a'.rjust(9)",
                "x = 'a'.center(9)",
                "x = '1'.zfill(9)",
                "x = '\\t'.expandtabs(200000000)",
                "x = '{:>200000000}'.format(1)",
                "x = '{v}'.format_map({'v': 1})"):
        with pytest.raises(SandboxViolation, match="formatting"):
            validate(src)


def test_fstring_width_rejected():
    with pytest.raises(SandboxViolation, match="width"):
        validate("x = f'{1:>200000000}'")
    with pytest.raises(SandboxViolation, match="dynamic"):
        validate("w = 9\nx = f'{1:>{w}}'")


def test_formatting_still_correct():
    ns = DeterministicSandbox().load(
        "a = format(255, '08x')\n"
        "b = '%05d' % 42\n"
        "c = f'{3.14159:.2f}'\n"
        "d = 17 % 5\n"
        "e = 17\n"
        "e %= 5\n")
    assert ns["a"] == "000000ff" and ns["b"] == "00042"
    assert ns["c"] == "3.14" and ns["d"] == 2 and ns["e"] == 2


def test_bindings_visible():
    sandbox = DeterministicSandbox()
    ns = sandbox.load("answer = helper(20)", bindings={"helper": lambda v: v * 2 + 2})
    assert ns["answer"] == 42
