"""Deterministic contract sandbox tests.

Reference analogs: experimental/sandbox's WhitelistClassLoaderTest (accept
whitelisted code, reject banned constructs) and the runtime cost-accounting
thresholds (runaway code terminates deterministically).
"""
import pytest

from corda_tpu.core.contracts.sandbox import (DeterministicSandbox,
                                              SandboxCostExceeded,
                                              SandboxViolation, validate)

CONTRACT = """
class TokenContract:
    def verify(self, inputs, outputs):
        total_in = sum(v for v in inputs)
        total_out = sum(v for v in outputs)
        if total_in != total_out:
            raise ValueError("conservation violated")
        return "ok"
"""


def test_loads_and_runs_whitelisted_contract():
    sandbox = DeterministicSandbox()
    ns = sandbox.load(CONTRACT)
    contract = ns["TokenContract"]()
    assert sandbox.run(contract.verify, [5, 7], [12]) == "ok"
    with pytest.raises(ValueError, match="conservation"):
        sandbox.run(contract.verify, [5], [12])
    assert sandbox.spent > 0


@pytest.mark.parametrize("source,label", [
    ("import os", "import"),
    ("from os import path", "import"),
    ("x = {1, 2}", "set display"),
    ("x = {v for v in range(3)}", "set comprehension"),
    ("def f():\n    global x", "global"),
    ("async def f():\n    pass", "async"),
    ("x = obj._secret", "underscore attribute"),
    ("x = __import__", "dunder name"),
    ("with open('f') as f:\n    pass", "with"),
])
def test_banned_constructs_rejected(source, label):
    with pytest.raises(SandboxViolation):
        validate(source)


def test_unsafe_builtins_absent():
    sandbox = DeterministicSandbox()
    for expr in ("eval('1')", "exec('x=1')", "open('/etc/hostname')",
                 "getattr(int, 'bit_length')", "globals()", "hash('a')",
                 "id(1)", "print('hi')"):
        with pytest.raises((NameError, KeyError)):
            sandbox.load(f"result = {expr}")


def test_runaway_loop_hits_budget():
    sandbox = DeterministicSandbox(instruction_budget=1000)
    with pytest.raises(SandboxCostExceeded):
        sandbox.load("while True:\n    x = 1\n")


def test_iteration_is_charged():
    src = "total = sum(i for i in range(10_000))"
    with pytest.raises(SandboxCostExceeded):
        DeterministicSandbox(instruction_budget=100).load(src)
    ns = DeterministicSandbox(instruction_budget=100_000).load(src)
    assert ns["total"] == sum(range(10_000))


def test_budget_spans_later_calls():
    """Functions defined in the sandbox keep charging when called after
    load — the budget covers the contract's whole lifetime."""
    sandbox = DeterministicSandbox(instruction_budget=5_000)
    ns = sandbox.load("def burn(n):\n    for i in range(n):\n        x = i\n")
    ns["burn"](100)
    with pytest.raises(SandboxCostExceeded):
        ns["burn"](100_000)


def test_bindings_visible():
    sandbox = DeterministicSandbox()
    ns = sandbox.load("answer = helper(20)", bindings={"helper": lambda v: v * 2 + 2})
    assert ns["answer"] == 42
