"""Amount arithmetic tests (reference analog: core AmountTests)."""
import pytest

from corda_tpu.core.contracts import Amount, USD, GBP
from corda_tpu.core.contracts.amount import sum_or_none, sum_or_throw, sum_or_zero


def test_amount_arithmetic():
    a, b = Amount(100, USD), Amount(250, USD)
    assert (a + b).quantity == 350
    assert (b - a).quantity == 150
    assert (a * 3).quantity == 300
    assert a < b and b >= a
    with pytest.raises(ValueError):
        a + Amount(1, GBP)
    with pytest.raises(ValueError):
        Amount(-1, USD)
    with pytest.raises(ValueError):
        a - b  # would go negative
    with pytest.raises(ValueError):
        a * 1.5  # non-int factor


def test_amount_splits_and_sums():
    a = Amount(10, USD)
    parts = a.splits(3)
    assert [p.quantity for p in parts] == [4, 3, 3]
    assert sum_or_throw(parts) == a
    assert sum_or_none([]) is None
    assert sum_or_zero([], USD) == Amount(0, USD)
    with pytest.raises(ValueError):
        sum_or_throw([])


def test_amount_roundtrip():
    from corda_tpu.core.serialization import serialize, deserialize
    a = Amount(12345, USD)
    assert deserialize(serialize(a)) == a
