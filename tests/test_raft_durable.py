"""Durable Raft state: a restarted replica rejoins with its log intact.

Reference analog: Copycat's durable storage under RaftUniquenessProvider —
the notary cluster must survive replica restarts without forgetting
commitments (Raft §5.1 persistent state)."""
import pytest

from corda_tpu.consensus.raft import LEADER, RaftNode
from corda_tpu.consensus.raft_store import RaftLogStore
from corda_tpu.network.inmemory import InMemoryMessagingNetwork


def make_cluster(tmp_path, n=3):
    bus = InMemoryMessagingNetwork()
    names = [f"raft{i}" for i in range(n)]
    applied = [[] for _ in range(n)]
    nodes = []
    for i, name in enumerate(names):
        nodes.append(RaftNode(
            name, list(names), bus.create_node(name),
            (lambda s: (lambda e: (s.append(e), len(s))[1]))(applied[i]),
            seed=i, storage=RaftLogStore(str(tmp_path / f"{name}.kv"))))
    return bus, names, nodes, applied


def run_until_leader(bus, nodes, max_ticks=300):
    for _ in range(max_ticks):
        for node in nodes:
            node.tick()
        bus.run_network()
        leaders = [n for n in nodes if n.role == LEADER]
        if len(leaders) == 1:
            return leaders[0]
    raise AssertionError("no leader elected")


def pump(bus, nodes, ticks=10):
    for _ in range(ticks):
        for node in nodes:
            node.tick()
        bus.run_network()


def test_replica_restart_recovers_log(tmp_path):
    bus, names, nodes, applied = make_cluster(tmp_path)
    leader = run_until_leader(bus, nodes)
    for i in range(3):
        fut = leader.submit(f"entry-{i}")
        pump(bus, nodes)
        assert fut.result(timeout=1) == i + 1

    # kill a FOLLOWER: detach it from the bus, forget the object entirely
    dead = next(n for n in nodes if n.role != LEADER)
    dead_name = dead.node_id
    dead.stop()
    dead.storage.close()
    bus.transfer_filter = lambda t: dead_name not in (t.sender, t.recipient)
    live = [n for n in nodes if n is not dead]
    fut = leader.submit("while-down")
    pump(bus, live)
    assert fut.result(timeout=1) == 4

    # restart from its durable state on a fresh endpoint object
    bus.transfer_filter = None
    replay = []
    revived = RaftNode(dead_name, list(names),
                       bus.endpoint(dead_name),
                       lambda e: (replay.append(e), len(replay))[1],
                       seed=7,
                       storage=RaftLogStore(str(tmp_path / f"{dead_name}.kv")))
    # recovered persistent state: everything committed before the crash
    assert [e.entry for e in revived.state.log
            if isinstance(e.entry, str) and e.entry.startswith("entry-")] \
        == ["entry-0", "entry-1", "entry-2"]
    all_nodes = live + [revived]
    pump(bus, all_nodes, ticks=20)
    fut = leader.submit("after-restart")
    pump(bus, all_nodes, ticks=20)
    assert fut.result(timeout=1) == 5
    # the revived replica replayed the full history in order
    assert replay == [f"entry-{i}" for i in range(3)] + ["while-down",
                                                         "after-restart"]


def test_vote_survives_restart(tmp_path):
    """A restarted replica must remember its vote for the term (§5.1 —
    forgetting it could elect two leaders in one term)."""
    store = RaftLogStore(str(tmp_path / "solo.kv"))
    bus = InMemoryMessagingNetwork()
    bus.create_node("other")   # vote responses need a live endpoint
    node = RaftNode("solo", ["solo", "other"], bus.create_node("solo"),
                    lambda e: e, seed=1, storage=store)
    from corda_tpu.consensus.raft import RequestVote
    node._on_message_locked(RequestVote(5, "other", 0, 0))
    assert node.state.voted_for == "other"
    store.close()

    node2 = RaftNode("solo2", ["solo2", "other"], bus.create_node("solo2"),
                     lambda e: e, seed=1,
                     storage=RaftLogStore(str(tmp_path / "solo.kv")))
    assert node2.state.current_term == 5
    assert node2.state.voted_for == "other"
