"""bench.py --smoke --fleet: the in-process fleet wiring check for tier-1.

Two host-route workers behind the out-of-process queue's load-aware
router must both receive and complete work, every future must resolve,
and the one-line JSON aggregate must carry the MULTICHIP artifact fields
(fleet_verifies_per_sec / scaling_efficiency_pct / n_workers) that
tools/benchguard.py locks on device runs.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fleet_smoke_two_workers_share_the_run():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--fleet"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    for field in ("fleet_verifies_per_sec", "scaling_efficiency_pct",
                  "n_workers", "n_devices", "fleet_steals", "fleet_stolen",
                  "worker_busy_skew_pct", "steals_total",
                  "stitched_trace_depth",
                  "groups", "group_size", "wall_s", "per_worker_sigs"):
        assert field in out, f"missing fleet JSON field: {field}"
    assert out["smoke"] is True and out["fleet"] is True
    assert out["n_workers"] == 2
    assert out["fleet_verifies_per_sec"] > 0
    assert 0 < out["scaling_efficiency_pct"] <= 100
    # the router dealt to BOTH workers and both did real work — a fleet
    # where one worker starves is the regression this test exists to catch
    sigs = out["per_worker_sigs"]
    assert len(sigs) == 2 and all(c > 0 for c in sigs.values()), sigs
    # timed groups + the warm-up group all landed somewhere
    assert sum(sigs.values()) == (out["groups"] + 1) * out["group_size"]
    # the observability plane saw the run: at least oop_submit →
    # device_dispatch crossed the process seam under one trace id
    assert out["stitched_trace_depth"] >= 2
    assert 0 <= out["worker_busy_skew_pct"] <= 100
    # smoke acceptance rode real HTTP: federated worker families on
    # /metrics, a stitched cross-process trace on /traces, lifecycle
    # timelines on /debug/requests
    assert out["http_federated_families"] >= 1
    assert out["http_stitched_traces"] >= 1
    assert out["http_request_timelines"] >= 1
