"""Remote RPC observable streaming + the widened op surface (VERDICT r2 #3).

Reference analogs: RPCServer/RPCApi observable-as-id streaming
(node-api RPCApi.kt:27-60), client demux (RPCClientProxyHandler.kt:1-421),
and the CordaRPCOps operation set (CordaRPCOps.kt:60-449).
"""
import pytest

import corda_tpu.finance  # noqa: F401
from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.finance import CashState
from corda_tpu.node.rpc import CordaRPCOps
from corda_tpu.testing import MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank = network.create_node("O=Bank, L=London, C=GB")
    network.start_nodes()
    return network, notary, bank


def _issue(network, notary, bank, rpc, quantity=1000):
    fsm = rpc.start_flow_dynamic("CashIssueFlow", Amount(quantity, USD),
                                 b"\x01", bank.party, notary.party)
    network.run_network()
    return fsm.result_future.result(timeout=1)


# -- in-process op surface ---------------------------------------------------

def test_tracked_flow_streams_progress_and_result(net):
    network, notary, bank = net
    rpc = CordaRPCOps(bank.services, bank.smm)
    fsm, feed = rpc.start_tracked_flow_dynamic(
        "CashIssueFlow", Amount(500, USD), b"\x01", bank.party, notary.party)
    events = []
    feed.subscribe(events.append)
    network.run_network()
    fsm.result_future.result(timeout=1)
    removed = [e for e in events if e[0] == "removed"]
    assert removed and removed[0][1][0] == "done"


def test_tracked_flow_terminal_event_survives_fast_completion(net):
    """A flow finishing before anyone subscribes must still deliver its
    terminal event (server-side buffering)."""
    network, notary, bank = net
    rpc = CordaRPCOps(bank.services, bank.smm)
    fsm, feed = rpc.start_tracked_flow_dynamic(
        "CashIssueFlow", Amount(500, USD), b"\x01", bank.party, notary.party)
    network.run_network()
    fsm.result_future.result(timeout=1)     # flow done, nobody subscribed
    events = []
    feed.subscribe(events.append)           # late subscriber
    assert any(e[0] == "removed" for e in events)


def test_tx_mapping_feed(net):
    network, notary, bank = net
    rpc = CordaRPCOps(bank.services, bank.smm)
    pushed = []
    rpc.state_machine_recorded_transaction_mapping_feed().subscribe(
        pushed.append)
    _issue(network, notary, bank, rpc)
    snapshot = rpc.state_machine_recorded_transaction_mapping_snapshot()
    assert snapshot and pushed
    tx_ids = {stx.id for stx in rpc.verified_transactions_snapshot()}
    assert all(tx_id in tx_ids for _run_id, tx_id in snapshot)
    assert all(isinstance(run_id, str) and run_id
               for run_id, _tx_id in snapshot)


def test_network_map_feed(net):
    network, notary, bank = net
    rpc = CordaRPCOps(bank.services, bank.smm)
    changes = []
    rpc.network_map_feed().subscribe(changes.append)
    from corda_tpu.node.services import NodeInfo
    from corda_tpu.core.identity import Party
    from corda_tpu.core.crypto import generate_keypair
    newcomer = NodeInfo(
        address="127.0.0.1:9", legal_identity=Party(
            "O=New, L=Oslo, C=NO", generate_keypair(entropy=b"\x77" * 32).public))
    bank.services.network_map_cache.add_node(newcomer)
    assert ("added", newcomer) in changes
    bank.services.network_map_cache.remove_node("O=New, L=Oslo, C=NO")
    assert any(c[0] == "removed" for c in changes)


def test_cash_balances_and_tx_notes(net):
    network, notary, bank = net
    rpc = CordaRPCOps(bank.services, bank.smm)
    _issue(network, notary, bank, rpc, 700)
    _issue(network, notary, bank, rpc, 300)
    assert rpc.get_cash_balances() == {"USD": 1000}
    tx_id = rpc.verified_transactions_snapshot()[0].id
    rpc.add_vault_transaction_note(tx_id, "hello")
    rpc.add_vault_transaction_note(tx_id, "world")
    assert rpc.get_vault_transaction_notes(tx_id) == ["hello", "world"]


def test_party_lookup_ops(net):
    network, notary, bank = net
    rpc = CordaRPCOps(bank.services, bank.smm)
    assert rpc.party_from_name("Bank") == bank.party
    assert rpc.party_from_name("o-no-such") is None
    info = rpc.node_identity_from_party(bank.party)
    assert info is not None and info.legal_identity == bank.party
    assert rpc.wait_until_registered_with_network_map()


def test_vault_track_by(net):
    network, notary, bank = net
    rpc = CordaRPCOps(bank.services, bank.smm)
    updates = []
    feed = rpc.vault_track_by()
    feed.subscribe(updates.append)
    _issue(network, notary, bank, rpc)
    assert updates and updates[0].produced
    page = rpc.vault_track_by().snapshot
    assert len(page.states) == 1


def test_upload_file(net):
    network, notary, bank = net
    rpc = CordaRPCOps(bank.services, bank.smm)
    att_id = rpc.upload_file("attachment", "x.jar", b"jar bytes")
    from corda_tpu.core.crypto.secure_hash import SecureHash
    assert rpc.attachment_exists(SecureHash(bytes.fromhex(att_id)))
    with pytest.raises(ValueError, match="no acceptor"):
        rpc.upload_file("mystery", None, b"?")


# -- remote streaming over real TCP ------------------------------------------

@pytest.fixture
def live_node(tmp_path):
    from corda_tpu.node.node import Node, NodeConfiguration
    config = NodeConfiguration(
        "O=Solo, L=London, C=GB", port=0,
        base_directory=str(tmp_path / "solo"), notary="simple")
    node = Node(config).start()
    yield node
    node.stop()


def test_remote_push_streaming(live_node):
    """explorer --watch's data path: vault observations arrive by PUSH over
    the wire (no polling), and the tracked-flow result arrives by push."""
    from corda_tpu.client.rpc import ClientDataFeed, CordaRPCClient

    client = CordaRPCClient("127.0.0.1", live_node.messaging.port)
    try:
        vault_feed = client.vault_feed()
        assert isinstance(vault_feed, ClientDataFeed)
        assert not vault_feed.snapshot      # codec rounds lists to tuples

        # guarantee no result polling happens: the poll op would explode
        client.flow_result = None
        result = client.start_flow_and_wait(
            "CashIssueFlow", Amount(4200, USD), b"\x01",
            live_node.party, live_node.party, timeout_s=60)
        assert result is not None

        update = vault_feed.next_event(timeout_s=30)
        assert update.produced and \
            update.produced[0].state.data.amount.quantity == 4200

        # server held exactly our subscriptions; closing the feed retires it
        assert vault_feed.feed_id in live_node._feeds
        vault_feed.close()
        assert vault_feed.feed_id not in live_node._feeds
    finally:
        client.close()


def test_remote_disconnect_cleans_up_feeds(live_node):
    """A client that vanishes without unsubscribing must not leak server-side
    subscriptions: the transport's send-failure hook drops its feeds."""
    import time
    from corda_tpu.client.rpc import CordaRPCClient

    client = CordaRPCClient("127.0.0.1", live_node.messaging.port)
    feed = client.vault_feed()
    feed_id = feed.feed_id
    assert feed_id in live_node._feeds
    client._messaging.stop()          # crash, no goodbye

    driver = CordaRPCClient("127.0.0.1", live_node.messaging.port)
    try:
        driver.flow_result = None
        driver.start_flow_and_wait(
            "CashIssueFlow", Amount(100, USD), b"\x01",
            live_node.party, live_node.party, timeout_s=60)
        deadline = time.monotonic() + 30
        while feed_id in live_node._feeds:
            assert time.monotonic() < deadline, \
                "dead client's feed was not cleaned up"
            time.sleep(0.5)
    finally:
        driver.close()


def test_wait_until_registered_future(live_node):
    """CordaRPCOps.kt:275 parity: the client-side registration wait is a
    genuine Future (push-driven off the network-map feed), not a poll
    loop the caller has to write."""
    from corda_tpu.client.rpc import CordaRPCClient

    client = CordaRPCClient("127.0.0.1", live_node.messaging.port)
    try:
        fut = client.wait_until_registered_with_network_map(timeout_s=30)
        assert fut.result(timeout=30) is True
    finally:
        client.close()
