"""CommercialPaper contract unit tests (CommercialPaperTests.kt analog):
issue/move/redeem clause rules exercised directly at the contract level."""
import datetime

import pytest

from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.core.contracts.exceptions import TransactionVerificationException
from corda_tpu.core.contracts.structures import (AuthenticatedObject, Issued,
                                                 PartyAndReference, TimeWindow)
from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.core.identity import Party
from corda_tpu.core.serialization.codec import exact_epoch_micros
from corda_tpu.core.transactions.ledger import TransactionForContract
from corda_tpu.finance.cash import CashState
from corda_tpu.finance.commercial_paper import (CommercialPaper,
                                                CommercialPaperState)

ISSUER_KP = generate_keypair(entropy=b"\x61" * 32)
ISSUER = Party("O=MegaCorp, L=London, C=GB", ISSUER_KP.public)
OWNER_KP = generate_keypair(entropy=b"\x62" * 32)

NOW = datetime.datetime(2026, 7, 1, tzinfo=datetime.timezone.utc)
NOW_MICROS = exact_epoch_micros(NOW)
LATER_MICROS = exact_epoch_micros(NOW + datetime.timedelta(days=30))

TOKEN = Issued(PartyAndReference(ISSUER, b"\x01"), USD)
CP = CommercialPaper()


def paper(owner=ISSUER_KP.public, maturity=LATER_MICROS, face=100_000):
    return CommercialPaperState(PartyAndReference(ISSUER, b"\x01"), owner,
                                Amount(face, TOKEN), maturity)


def ctx(inputs, outputs, commands, at=NOW):
    tw = TimeWindow.with_tolerance(at, datetime.timedelta(seconds=30))
    return TransactionForContract(
        inputs=tuple(inputs), outputs=tuple(outputs), attachments=(),
        commands=tuple(commands), id=SecureHash.sha256(b"cp-test"),
        notary=None, time_window=tw)


def cmd(data, *keys):
    return AuthenticatedObject(tuple(keys), (), data)


def test_issue_rules():
    CP.verify(ctx([], [paper()], [cmd(CP.Issue(), ISSUER_KP.public)]))
    # unsigned by issuer
    with pytest.raises(TransactionVerificationException, match="issuer"):
        CP.verify(ctx([], [paper()], [cmd(CP.Issue(), OWNER_KP.public)]))
    # already matured
    with pytest.raises(TransactionVerificationException, match="mature"):
        CP.verify(ctx([], [paper(maturity=NOW_MICROS - 1)],
                      [cmd(CP.Issue(), ISSUER_KP.public)]))


def test_move_rules():
    CP.verify(ctx([paper()], [paper(owner=OWNER_KP.public)],
                  [cmd(CP.Move(), ISSUER_KP.public)]))
    # terms must not change
    with pytest.raises(TransactionVerificationException, match="terms"):
        CP.verify(ctx([paper()], [paper(owner=OWNER_KP.public, face=1)],
                      [cmd(CP.Move(), ISSUER_KP.public)]))
    # owner must sign
    with pytest.raises(TransactionVerificationException, match="owner"):
        CP.verify(ctx([paper()], [paper(owner=OWNER_KP.public)],
                      [cmd(CP.Move(), OWNER_KP.public)]))


def test_redeem_rules():
    matured = paper(owner=OWNER_KP.public, maturity=NOW_MICROS - 1)
    payment = CashState(Amount(100_000, TOKEN), OWNER_KP.public)
    # redemption paying face value to the owner, after maturity
    CP.verify(ctx([matured], [payment], [cmd(CP.Redeem(), OWNER_KP.public)]))
    # before maturity
    with pytest.raises(TransactionVerificationException, match="matured"):
        CP.verify(ctx([paper(owner=OWNER_KP.public)], [payment],
                      [cmd(CP.Redeem(), OWNER_KP.public)]))
    # underpayment
    small = CashState(Amount(40_000, TOKEN), OWNER_KP.public)
    with pytest.raises(TransactionVerificationException, match="face value"):
        CP.verify(ctx([matured], [small], [cmd(CP.Redeem(), OWNER_KP.public)]))
