"""Differential tests: native (C) scalar prep vs the Python bigint path.

The native layer (native/scalarmath.cpp via ops/scalarprep.py) must be
BIT-IDENTICAL to the Python prep it replaces — these tests lock that for
the low-level arithmetic seams (Barrett mulmod/mod512, GLV split) and the
full batch preps (secp256k1 hybrid, secp256r1 windowed), over valid,
tampered, and structurally-malformed inputs.  Mirrors the reference's
approach of differential-testing Crypto.doVerify against test vectors
(core/src/test/kotlin/net/corda/core/crypto/CryptoUtilsTest.kt).
"""
import os
import random

import numpy as np
import pytest

from corda_tpu.core.crypto import ecmath
from corda_tpu.ops import scalarprep as sp
from corda_tpu.ops import weierstrass as wc

pytestmark = pytest.mark.skipif(not sp.available(),
                                reason="libscalarmath.so not built")


def test_mulmod_matches_python():
    rng = random.Random(11)
    mods = [ecmath.SECP256K1.n, ecmath.SECP256K1.p, ecmath.SECP256R1.n,
            ecmath.SECP256R1.p, ecmath.ED_L, ecmath.ED_P]
    for mid, m in enumerate(mods):
        for _ in range(50):
            a, b = rng.getrandbits(256) % m, rng.getrandbits(256) % m
            assert sp.mulmod(mid, a, b) == a * b % m
        for _ in range(50):
            x = rng.getrandbits(512)
            assert sp.mod512(mid, x) == x % m
        # boundary values
        for a in (0, 1, m - 1):
            assert sp.mulmod(mid, a, m - 1) == a * (m - 1) % m
        assert sp.mod512(mid, (1 << 512) - 1) == ((1 << 512) - 1) % m


def test_glv_matches_python():
    rng = random.Random(12)
    n = ecmath.SECP256K1.n
    cases = [0, 1, n - 1, n // 2, n // 2 + 1]
    cases += [rng.getrandbits(256) % n for _ in range(300)]
    for k in cases:
        assert sp.glv(k) == ecmath.glv_decompose(k), k


def _k1_items(n_valid: int):
    rng = np.random.default_rng(42)
    curve = ecmath.SECP256K1
    items = []
    for _ in range(n_valid):
        priv = int.from_bytes(rng.bytes(32), "little") % (curve.n - 1) + 1
        pub = curve.mul(priv, curve.g)
        msg = rng.bytes(48)
        r, s = ecmath.ecdsa_sign(curve, priv, msg)
        items.append((pub, msg, r, s))
    # malformed rows: None point, r = 0, s = 0, high-s, r >= n, off-curve,
    # oversized r (DER can carry > 2^256 ints)
    pub0 = items[0][0]
    items += [
        (None, b"x", 5, 7),
        (pub0, b"m", 0, 7),
        (pub0, b"m", 5, 0),
        (pub0, b"m", 5, curve.n - 1),           # violates low-s
        (pub0, b"m", curve.n, 7),
        ((pub0[0], (pub0[1] + 1) % curve.p), b"m", 5, 7),
        (pub0, b"m", 1 << 300, 7),
    ]
    return items


def test_k1_prep_native_matches_python():
    items = _k1_items(24)
    native = wc._prepare_hybrid_native(items, 8)
    python = wc._prepare_hybrid_python(items, 8)
    assert len(native) == len(python)
    names = ["g_idx", "q_bits", "Qc", "Qd", "r_limbs", "rn_ok",
             "tab_x", "tab_y", "tab_ok", "precheck"]
    for name, a, b in zip(names, native, python):
        if isinstance(a, tuple):
            for i, (ac, bc) in enumerate(zip(a, b)):
                np.testing.assert_array_equal(
                    np.asarray(ac), np.asarray(bc), err_msg=f"{name}[{i}]")
        else:
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=name)


def test_r1_prep_native_matches_python():
    rng = np.random.default_rng(43)
    curve = ecmath.SECP256R1
    items = []
    for _ in range(12):
        priv = int.from_bytes(rng.bytes(32), "little") % (curve.n - 1) + 1
        pub = curve.mul(priv, curve.g)
        msg = rng.bytes(40)
        r, s = ecmath.ecdsa_sign(curve, priv, msg)
        items.append((pub, msg, r, s))
    pub0 = items[0][0]
    items += [(None, b"x", 5, 7), (pub0, b"m", 0, 7),
              (pub0, b"m", curve.n + 5, 7),
              ((pub0[0], (pub0[1] + 1) % curve.p), b"m", 5, 7)]
    native = wc.prepare_batch_windowed_single(curve, items, 16)
    python = wc._prepare_windowed_single_python(curve, items, 16)
    names = ["g_idx", "q_digits", "Q", "r_limbs", "rn_ok",
             "tab_x", "tab_y", "tab_ok", "precheck"]
    for name, a, b in zip(names, native, python):
        if isinstance(a, tuple):
            for i, (ac, bc) in enumerate(zip(a, b)):
                np.testing.assert_array_equal(
                    np.asarray(ac), np.asarray(bc), err_msg=f"{name}[{i}]")
        else:
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=name)


def test_ed_split_windows_native_matches_python():
    import hashlib

    from corda_tpu.ops import ed25519 as ed
    rng = np.random.default_rng(44)
    digests, s_ints = [], []
    for _ in range(40):
        digests.append(hashlib.sha512(rng.bytes(32)).digest())
        s_ints.append(int.from_bytes(rng.bytes(32), "little"))
    # boundary s values: 0, L-1, L (invalid), max
    s_ints[:4] = [0, ecmath.ED_L - 1, ecmath.ED_L, (1 << 256) - 1]
    s_words = sp.ints_to_words(s_ints)
    h_words = sp.le_digests_to_words(digests, 8)
    native = sp.ed_prep(h_words, s_words)
    python = ed._split_windows_python(digests, s_words)
    for name, a, b in zip(["b_idx", "b2_idx", "a_packed", "s_ok"],
                          native, python):
        np.testing.assert_array_equal(a, b, err_msg=name)


def test_ed_plain_windows_native_matches_python():
    """ed_prep_plain (the legacy windowed kernel's window extraction) vs
    the pure-numpy bit path, over already-reduced scalars as
    prepare_batch_windowed feeds it."""
    import numpy as _np

    from corda_tpu.ops import field as F
    from corda_tpu.ops.weierstrass import (_bits_to_w_windows,
                                           _bits_to_windows)
    rng = np.random.default_rng(46)
    ss = [int.from_bytes(rng.bytes(32), "little") % ecmath.ED_L
          for _ in range(30)] + [0, ecmath.ED_L - 1]
    ks = [int.from_bytes(rng.bytes(32), "little") % ecmath.ED_L
          for _ in range(30)] + [ecmath.ED_L - 1, 0]
    h_words = _np.zeros((len(ks), 8), dtype=_np.uint64)
    h_words[:, :4] = sp.ints_to_words(ks)
    b_idx, a_digits, s_ok = sp.ed_prep_plain(h_words, sp.ints_to_words(ss))
    assert s_ok.all()
    want_b = _bits_to_w_windows(F.scalars_to_bits(ss), 16).astype(np.int32)
    want_a = _bits_to_windows(F.scalars_to_bits(ks)).astype(np.uint8)
    np.testing.assert_array_equal(b_idx, want_b)
    np.testing.assert_array_equal(a_digits, want_a)


def test_ed_split_kernel_matches_plain_windowed():
    """The split-k kernel and the plain windowed kernel must agree verdict-
    for-verdict over valid + tampered + edge-encoded signatures."""
    from corda_tpu.ops import ed25519 as ed
    rng = np.random.default_rng(45)
    items = []
    for i in range(6):
        seed = rng.bytes(32)
        pub = ecmath.ed25519_public_key(seed)
        msg = rng.bytes(24)
        sig = ecmath.ed25519_sign(seed, msg)
        items.append((pub, sig, msg))
    pub0, sig0, msg0 = items[0]
    items += [
        (pub0, sig0, b"tampered"),
        (pub0, sig0[:31] + bytes([sig0[31] ^ 0x80]) + sig0[32:], msg0),
        (pub0, sig0[:32] + (ecmath.ED_L + 5).to_bytes(32, "little"), msg0),
        (pub0, b"short", msg0),
    ]
    split = ed.verify_batch(items)   # routes through the split kernel
    plain_pending = ed.prepare_batch_windowed(items, ed.B_WINDOW)
    *args, pre = plain_pending
    plain = np.asarray(ed._verify_kernel_windowed(*args, w=ed.B_WINDOW)) & pre
    np.testing.assert_array_equal(split, plain)
    want = [ecmath.ed25519_verify(pub, msg, sig) for pub, sig, msg in items]
    np.testing.assert_array_equal(split, np.asarray(want))


def _der_corpus():
    """Valid DER signatures plus every malformed shape ecdsa_sig_from_der
    rejects: truncated, trailing bytes, wrong tags, zero-length ints,
    negative ints, non-minimal encodings, oversized ints."""
    rng = random.Random(47)
    curve = ecmath.SECP256K1
    sigs = []
    for _ in range(24):
        priv = rng.randrange(1, curve.n)
        r, s = ecmath.ecdsa_sign(curve, priv, rng.randbytes(40))
        sigs.append(ecmath.ecdsa_sig_to_der(r, s))
    good = sigs[0]
    sigs += [
        b"",                                     # empty
        b"\x30",                                 # sequence tag alone
        good[:-1],                               # truncated
        good + b"\x00",                          # trailing byte
        b"\x31" + good[1:],                      # wrong outer tag
        good[:2] + b"\x03" + good[3:],           # wrong INTEGER tag
        b"\x30\x04\x02\x00\x02\x00",             # zero-length ints
        b"\x30\x06\x02\x01\x81\x02\x01\x01",     # negative r (high bit)
        b"\x30\x07\x02\x02\x00\x01\x02\x01\x01",  # non-minimal r
        b"\x30\x26\x02\x21\x01" + b"\x00" * 32 + b"\x02\x01\x01",  # r > 2^256
        bytes([good[0], good[1] + 1]) + good[2:] + b"\x00",  # length lies
    ]
    return sigs


def test_ecdsa_sigs_to_words_matches_der_parser():
    """The batched DER parse vs the strict per-item parser
    (ecmath.ecdsa_sig_from_der + ints_to_words): identical accepted set and
    word rows for every signature whose ints fit 256 bits. Oversized ints
    (which the strict parser accepts and leaves to the range precheck) and
    outright malformations both get ok=False + zeroed rows — r = 0 forces
    the native range precheck to reject, so the VERDICT is identical."""
    sigs = _der_corpus()
    r_words, s_words, ok = sp.ecdsa_sigs_to_words(sigs)
    assert r_words.shape == (len(sigs), 4) and s_words.shape == (len(sigs), 4)
    for i, der in enumerate(sigs):
        try:
            r, s = ecmath.ecdsa_sig_from_der(der)
            accept = max(r, s) < 1 << 256
        except Exception:
            accept = False
        if not accept:
            assert not ok[i], f"sig {i}: batched parse accepted"
            assert not r_words[i].any() and not s_words[i].any()
            continue
        assert ok[i], f"sig {i}: batched parse rejected, strict accepted"
        np.testing.assert_array_equal(r_words[i], sp.ints_to_words([r])[0])
        np.testing.assert_array_equal(s_words[i], sp.ints_to_words([s])[0])
    assert ok[:24].all() and not ok[24:].any()


def test_pub_row_cache_matches_decompress():
    """keys.sec1_pub_row_cached vs the bigint decompress: same affine point
    as LE u64 words, None for undecodable encodings, and cache hits return
    the identical row."""
    from corda_tpu.core.crypto.keys import sec1_compress, sec1_pub_row_cached
    rng = random.Random(48)
    for curve in (ecmath.SECP256K1, ecmath.SECP256R1):
        for _ in range(8):
            pt = curve.mul(rng.randrange(1, curve.n), curve.g)
            enc = sec1_compress(curve, pt)
            row = sec1_pub_row_cached(curve, enc)
            want = np.frombuffer(pt[0].to_bytes(32, "little")
                                 + pt[1].to_bytes(32, "little"), dtype="<u8")
            np.testing.assert_array_equal(row, want)
            assert sec1_pub_row_cached(curve, enc) is row   # LRU hit
        assert sec1_pub_row_cached(curve, b"\x02" + b"\xff" * 32) is None
        assert sec1_pub_row_cached(curve, b"\x09" * 33) is None


def test_stale_so_falls_back_loudly(caplog):
    """ABI gate (sm_version): a stale .so must be REFUSED with a warning —
    the Python fallback is bit-identical (differential tests above), so a
    silent downgrade would masquerade as a performance regression."""
    import logging
    real = next(p for p in sp._CANDIDATES if os.path.exists(p))
    with caplog.at_level(logging.WARNING, logger="corda_tpu.ops.scalarprep"):
        assert sp._load(candidates=[real],
                        expected=sp.SM_VERSION + 1) is None
    assert any("stale libscalarmath.so" in rec.message
               and "make -C native libscalarmath.so" in rec.message
               for rec in caplog.records)
    # the matching version loads fine (the gate, not the loader, refused)
    assert sp._load(candidates=[real]) is not None
    # and a refused library means available() gates every native seam
    assert sp.SM_VERSION == 3  # bumped 2→3 with sm_r1_halfgcd/sm_r1_prep_hg


def test_k1_verify_through_native_prep():
    """End-to-end: verify_batch (which routes through the native prep when
    available) accepts valid signatures and rejects tampered ones."""
    items = _k1_items(6)
    kitems = [(pub, msg, r, s) for pub, msg, r, s in items]
    ok = wc.verify_batch(ecmath.SECP256K1, kitems)
    assert ok[:6].all()
    assert not ok[6:].any()
    # tamper: flip a message byte
    pub, msg, r, s = kitems[0]
    bad = bytes([msg[0] ^ 1]) + msg[1:]
    ok2 = wc.verify_batch(ecmath.SECP256K1, [(pub, bad, r, s)])
    assert not ok2.any()
