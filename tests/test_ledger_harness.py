"""Ledger scenario harness (tier-1 CPU smoke shapes).

The acceptance check ISSUE 10 cares about most: ONE connected trace per
committed transaction — flow.run → tx.verify → notary.uniqueness →
raft.commit → vault.update under a single trace id — including when the
device breaker is open and verification degrades to the host route.
"""
import time

import pytest

from corda_tpu.observability.ledger_harness import (
    COMMIT_PATH_SPANS, LedgerScenarioConfig, connected_commit_traces,
    run_ledger_scenario)


def _tiny(**kw) -> LedgerScenarioConfig:
    kw.setdefault("parties", 2)
    kw.setdefault("coins_per_party", 2)
    kw.setdefault("operations", 8)
    kw.setdefault("rate_tx_per_sec", 10.0)
    kw.setdefault("max_duration_s", 60.0)
    return LedgerScenarioConfig(**kw)


def test_connected_commit_traces_requires_all_stages():
    traces = {
        "full": [{"name": n} for n in COMMIT_PATH_SPANS],
        "partial": [{"name": "flow.run"}, {"name": "tx.verify"}],
        "other": [{"name": "batcher.flush"}],
    }
    assert connected_commit_traces(traces) == ["full"]


@pytest.mark.ledger
def test_smoke_scenario_stitches_one_commit_path_trace():
    report = run_ledger_scenario(_tiny())
    assert report["ops_failed"] == 0, report
    assert report["exactly_once_ok"] and report["replicas_agree"]
    assert report["stitched_traces"] >= 1
    # counter reconciliation (ISSUE 11): every committed tx either passed
    # the notary (it had inputs) or was a self-issue leg with nothing to
    # check — the counters must account for each other exactly
    assert report["counter_invariant_ok"], report
    assert report["committed_tx_count"] == \
        report["notarised_tx_count"] + report["self_issue_tx_count"]
    # the notary only ever sees input-bearing transactions
    assert report["notarised_tx_count"] >= report["notarised_input_tx_count"]
    # group-commit amortization self-report is present and consistent:
    # every notarised tx went through the GroupCommitter exactly once
    assert report["group_commit_committed"] == report["notarised_tx_count"]
    assert report["group_commit_raft_appends"] == \
        report["ledger_commit_batch_count"]
    assert 0.0 < report["raft_appends_per_committed_tx"] <= 1.0
    spans = report["trace_sample"]
    names = {s["name"] for s in spans}
    for required in COMMIT_PATH_SPANS:
        assert required in names, f"missing span {required}: {sorted(names)}"
    # one trace id across the whole tree
    assert len({s["trace_id"] for s in spans}) == 1
    by_id = {s["span_id"]: s for s in spans}
    # the vault write is REACHABLE from the flow.run root: walking parent
    # pointers from a vault.update span crosses the notary/raft boundary
    # and lands on flow.run — the cross-component stitching acceptance
    def walks_to_flow_run(span) -> bool:
        seen = 0
        while span is not None and seen < 64:
            if span["name"] == "flow.run":
                return True
            span = by_id.get(span["parent_id"])
            seen += 1
        return False

    vault_spans = [s for s in spans if s["name"] == "vault.update"]
    assert vault_spans and any(walks_to_flow_run(s) for s in vault_spans)
    raft_spans = [s for s in spans if s["name"] == "raft.commit"]
    assert raft_spans and any(walks_to_flow_run(s) for s in raft_spans)
    # stage latency attribution made it into the artifact fields
    for stage in ("flow_run", "tx_verify", "notary_uniqueness",
                  "raft_commit", "vault_update"):
        assert report[f"ledger_stage_{stage}_ms_p99"] >= 0.0
    # tail forensics (ISSUE 14): the critical-path extractor decomposed
    # the stitched traces and every emitted p50 blame vector conserves
    # its class's e2e — the property bench.py turns into BENCH INVALID
    assert report["ledger_critpath_traces"] >= 1
    decomposed = 0
    for kind in ("issue", "pay", "settle"):
        blame = report[f"ledger_critpath_blame_p50_{kind}"]
        e2e = report[f"ledger_critpath_e2e_p50_ms_{kind}"]
        if not blame:
            continue
        decomposed += 1
        assert e2e > 0.0
        assert abs(sum(blame.values()) - e2e) <= 0.10 * e2e, (kind, blame,
                                                              e2e)
        assert report[f"ledger_critpath_dominant_{kind}"] in blame
    assert decomposed >= 1, "no flow class got a blame vector"
    # the slow-transaction report is annotated with its blocking chain
    assert report["ledger_critpath_top"], report["ledger_critpath_traces"]
    top = report["ledger_critpath_top"][0]
    assert top["segments"] and top["e2e_ms"] > 0.0


@pytest.mark.ledger
def test_degraded_breaker_open_route_still_stitches():
    """Open every device breaker and drop the host crossover to zero: all
    signature batches take the breaker_open host-verify route, and the
    commit path must STILL stitch end-to-end (degradation, not blindness).
    """
    captured = {}

    def trip(verifier):
        b = verifier.batcher
        b.host_crossover = 0              # no small-batch bypass
        for br in b._breakers.values():
            br.state = br.OPEN
            br._opened_at = br.clock()
            br.cooldown_s = 1e9           # never half-opens
        captured["metrics"] = b.metrics

    report = run_ledger_scenario(_tiny(on_verifier=trip))
    assert report["ops_failed"] == 0, report
    assert report["exactly_once_ok"] and report["replicas_agree"]
    assert report["stitched_traces"] >= 1
    names = {s["name"] for s in report["trace_sample"]}
    for required in COMMIT_PATH_SPANS:
        assert required in names
    snap = captured["metrics"].snapshot()
    routed = snap.get("SigBatcher.BreakerRouted", {})
    assert routed.get("count", 0) > 0, sorted(snap)


@pytest.mark.ledger
def test_hot_state_preset_rejects_every_double_spend():
    """The hostile preset (scenario.py --hot-state): every payment races
    against ONE exchange-like party, then deliberate double-spend replays
    of already-consumed refs hit the uniqueness provider directly. The
    notary must reject all of them naming the original consumer, the hot
    vault must still commit real throughput, and the artifact must clear
    benchguard's hot-state gate."""
    from corda_tpu.observability.ledger_harness import _build_ops
    from corda_tpu.tools.benchguard import guard_hot_state

    cfg = LedgerScenarioConfig.hot_state()
    cfg.operations = 28          # trimmed for tier-1 wall clock
    cfg.double_spend_replays = 6
    # the shape itself: every post-issue op targets the hot party
    spends = [o for o in _build_ops(cfg) if o.kind != "issue"]
    assert spends and all(o.counterparty == cfg.hot_party for o in spends)
    assert all(o.initiator != cfg.hot_party for o in spends)

    report = run_ledger_scenario(cfg)
    assert report["hot_state"] is True
    assert report["ops_failed"] == 0, report
    assert report["exactly_once_ok"] and report["replicas_agree"]
    assert report["double_spend_attempts"] == 6
    assert report["double_spend_rejected"] == 6
    assert report["double_spend_rejection_rate"] == 1.0
    assert report["committed_tx_per_sec"] > 0
    assert guard_hot_state(report) == []
