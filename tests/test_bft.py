"""BFT notary cluster tests (BFTNotaryServiceTests analogs): total-order
commitment over 4 replicas, crash tolerance of f=1, primary-failure view
change, replicated double-spend conflicts."""
import pytest

from corda_tpu.consensus.bft import (BFTClient, BFTReplica,
                                     BFTUniquenessProvider)
from corda_tpu.consensus.raft_uniqueness import DistributedImmutableMap
from corda_tpu.core.contracts.structures import StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.node.notary import UniquenessException


def make_cluster(n=4):
    bus = InMemoryMessagingNetwork()
    names = [f"bft{i}" for i in range(n)]
    machines = [DistributedImmutableMap() for _ in range(n)]
    replicas = [BFTReplica(name, names, bus.create_node(name),
                           machines[i].apply)
                for i, name in enumerate(names)]
    client = BFTClient("client", names, bus.create_node("client"))
    return bus, replicas, machines, client


def ref(i):
    return StateRef(SecureHash.sha256(bytes([i])), 0)


def commit_entry(tx_label, refs):
    return ("put_all", [SecureHash.sha256(tx_label), list(refs), "caller"])


def pump(bus, replicas, ticks=1):
    for _ in range(ticks):
        for r in replicas:
            r.tick()
        bus.run_network()


def test_total_order_commitment():
    bus, replicas, machines, client = make_cluster()
    fut = client.submit(commit_entry(b"t1", [ref(1)]))
    pump(bus, replicas)
    assert fut.result(timeout=1)["committed"]
    fut2 = client.submit(commit_entry(b"t2", [ref(1)]))  # double spend
    pump(bus, replicas)
    assert not fut2.result(timeout=1)["committed"]
    # every replica applied both, in the same order, with identical state
    assert all(len(m) == 1 for m in machines)
    assert all(r.executed_through == 1 for r in replicas)


def test_tolerates_one_crashed_replica():
    bus, replicas, machines, client = make_cluster()
    # silence a NON-primary replica (f = 1)
    dead = replicas[3]
    bus.transfer_filter = lambda t: t.recipient != dead.replica_id
    fut = client.submit(commit_entry(b"t1", [ref(1)]))
    pump(bus, replicas[:3])
    assert fut.result(timeout=1)["committed"]
    assert all(len(machines[i]) == 1 for i in range(3))


def test_view_change_on_primary_failure():
    bus, replicas, machines, client = make_cluster()
    primary = replicas[0]
    assert primary.is_primary
    bus.transfer_filter = lambda t: primary.replica_id not in (t.sender,
                                                               t.recipient)
    live = replicas[1:]
    fut = client.submit(commit_entry(b"t1", [ref(1)]))
    pump(bus, live, ticks=60)   # past the view-change timeout
    assert fut.result(timeout=1)["committed"]
    assert all(r.view >= 1 for r in live)
    assert all(len(machines[i]) == 1 for i in range(1, 4))


def test_bft_uniqueness_provider():
    import threading
    bus, replicas, machines, client = make_cluster()
    provider = BFTUniquenessProvider(client)
    results = {}

    def commit(key, label):
        try:
            provider.commit([ref(9)], SecureHash.sha256(label), "me")
            results[key] = "ok"
        except UniquenessException as e:
            results[key] = e.conflicts

    for key, label in (("first", b"a"), ("second", b"b")):
        t = threading.Thread(target=commit, args=(key, label))
        t.start()
        for _ in range(50):
            pump(bus, replicas)
            if key in results:
                break
            import time
            time.sleep(0.01)
        t.join(timeout=5)
    assert results["first"] == "ok"
    assert ref(9) in results["second"]
