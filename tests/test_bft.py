"""BFT notary cluster tests (BFTNotaryServiceTests analogs): total-order
commitment over 4 replicas, crash tolerance of f=1, primary-failure view
change, replicated double-spend conflicts."""
import pytest

from corda_tpu.consensus.bft import (BFTClient, BFTReplica,
                                     BFTUniquenessProvider)
from corda_tpu.consensus.raft_uniqueness import DistributedImmutableMap
from corda_tpu.core.contracts.structures import StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.node.notary import UniquenessException


def make_cluster(n=4):
    bus = InMemoryMessagingNetwork()
    names = [f"bft{i}" for i in range(n)]
    machines = [DistributedImmutableMap() for _ in range(n)]
    replicas = [BFTReplica(name, names, bus.create_node(name),
                           machines[i].apply)
                for i, name in enumerate(names)]
    client = BFTClient("client", names, bus.create_node("client"))
    return bus, replicas, machines, client


def ref(i):
    return StateRef(SecureHash.sha256(bytes([i])), 0)


def commit_entry(tx_label, refs):
    return ("put_all", [SecureHash.sha256(tx_label), list(refs), "caller"])


def pump(bus, replicas, ticks=1):
    for _ in range(ticks):
        for r in replicas:
            r.tick()
        bus.run_network()


def test_total_order_commitment():
    bus, replicas, machines, client = make_cluster()
    fut = client.submit(commit_entry(b"t1", [ref(1)]))
    pump(bus, replicas)
    assert fut.result(timeout=1)["committed"]
    fut2 = client.submit(commit_entry(b"t2", [ref(1)]))  # double spend
    pump(bus, replicas)
    assert not fut2.result(timeout=1)["committed"]
    # every replica applied both, in the same order, with identical state
    assert all(len(m) == 1 for m in machines)
    assert all(r.executed_through == 1 for r in replicas)


def test_tolerates_one_crashed_replica():
    bus, replicas, machines, client = make_cluster()
    # silence a NON-primary replica (f = 1)
    dead = replicas[3]
    bus.transfer_filter = lambda t: t.recipient != dead.replica_id
    fut = client.submit(commit_entry(b"t1", [ref(1)]))
    pump(bus, replicas[:3])
    assert fut.result(timeout=1)["committed"]
    assert all(len(machines[i]) == 1 for i in range(3))


def test_view_change_on_primary_failure():
    bus, replicas, machines, client = make_cluster()
    primary = replicas[0]
    assert primary.is_primary
    bus.transfer_filter = lambda t: primary.replica_id not in (t.sender,
                                                               t.recipient)
    live = replicas[1:]
    fut = client.submit(commit_entry(b"t1", [ref(1)]))
    pump(bus, live, ticks=60)   # past the view-change timeout
    assert fut.result(timeout=1)["committed"]
    assert all(r.view >= 1 for r in live)
    assert all(len(machines[i]) == 1 for i in range(1, 4))


def test_view_change_carries_prepared_request():
    """A request that PREPARED under the old primary (but never committed —
    commits were lost) must survive the view change via the prepared
    certificates in the ViewChange quorum and execute exactly once."""
    from corda_tpu.core.serialization import deserialize
    from corda_tpu.consensus.bft import CommitMsg

    bus, replicas, machines, client = make_cluster()
    primary = replicas[0]

    def block_commits(t):
        try:
            return not isinstance(deserialize(t.message.data), CommitMsg)
        except Exception:
            return True

    bus.transfer_filter = block_commits
    fut = client.submit(commit_entry(b"t1", [ref(1)]))
    pump(bus, replicas, ticks=3)     # everyone prepares, nobody commits
    assert all(r._prepared for r in replicas)
    assert all(r.executed_through == -1 for r in replicas)

    # old primary dies; commits stay blocked for it, flow for the rest
    bus.transfer_filter = lambda t: primary.replica_id not in (t.sender,
                                                               t.recipient)
    live = replicas[1:]
    pump(bus, live, ticks=60)        # timeout → certified view change
    assert fut.result(timeout=1)["committed"]
    assert all(len(machines[i]) == 1 for i in range(1, 4))
    assert all(r.view >= 1 for r in live)


def test_forged_new_view_rejected():
    """A NewView whose re-proposal order does not follow from its embedded
    ViewChange quorum is rejected — the receiver votes the next view instead
    of adopting the forged order."""
    from corda_tpu.core.serialization import deserialize
    from corda_tpu.consensus.bft import NewView, Request, ViewChange

    bus, replicas, machines, client = make_cluster()
    target = replicas[2]
    vcs = tuple(ViewChange(1, r.replica_id, -1, ()) for r in replicas[:3])
    forged = Request(999, "client", ("put_all", [SecureHash.sha256(b"evil"),
                                                 [ref(5)], "x"]))
    target._handle(NewView(1, vcs, (forged,)))  # quorum implies (), not this
    assert target.view == 0           # forged view not adopted
    assert len(machines[2]) == 0      # forged request not applied
    # and the target pushed back with a vote for the view AFTER the forgery
    votes = [deserialize(t.message.data) for t in bus.sent_log
             if t.sender == target.replica_id]
    assert any(isinstance(v, ViewChange) and v.new_view == 2 for v in votes)


def test_state_transfer_beyond_cert_window():
    """A replica partitioned past the certificate-retention window catches
    up via state transfer at the next view change: the snapshot restores the
    requests no re-proposal certificate still carries."""
    bus = InMemoryMessagingNetwork()
    names = [f"bft{i}" for i in range(4)]
    machines = [DistributedImmutableMap() for _ in range(4)]
    replicas = [BFTReplica(name, names, bus.create_node(name),
                           machines[i].apply,
                           snapshot_fn=machines[i].snapshot,
                           restore_fn=machines[i].restore,
                           cert_retention=2)
                for i, name in enumerate(names)]
    client = BFTClient("client", names, bus.create_node("client"))

    # partition bft3 and commit well past its retention window
    bus.transfer_filter = lambda t: "bft3" not in (t.sender, t.recipient)
    for i in range(5):
        fut = client.submit(commit_entry(b"t%d" % i, [ref(i)]))
        pump(bus, replicas[:3], ticks=3)
        assert fut.result(timeout=1)["committed"]
    assert len(machines[3]) == 0 and all(len(machines[i]) == 5
                                         for i in range(3))

    # heal bft3, kill the old primary, and submit a fresh request so the
    # timeout drives a certified view change with bft3 in the quorum
    primary = replicas[0]
    bus.transfer_filter = lambda t: primary.replica_id not in (t.sender,
                                                               t.recipient)
    live = replicas[1:]
    fut = client.submit(commit_entry(b"t5", [ref(5)]))
    pump(bus, live, ticks=80)
    assert fut.result(timeout=1)["committed"]
    # the lagging replica restored the snapshot AND applied the new commit
    assert all(len(machines[i]) == 6 for i in range(1, 4))
    assert all(r.view >= 1 for r in live)


def test_state_transfer_rejects_single_byzantine_response():
    """ADVICE r1: a snapshot must only be installed once f+1 distinct
    replicas return byte-identical state — a lone Byzantine responder (even
    the new primary) cannot install fabricated notary state."""
    from corda_tpu.consensus.bft import StateResponse
    from corda_tpu.core.serialization import serialize as ser

    bus = InMemoryMessagingNetwork()
    names = [f"bft{i}" for i in range(4)]
    machines = [DistributedImmutableMap() for _ in range(4)]
    replicas = [BFTReplica(name, names, bus.create_node(name),
                           machines[i].apply,
                           snapshot_fn=machines[i].snapshot,
                           restore_fn=machines[i].restore,
                           cert_retention=2)
                for i, name in enumerate(names)]
    lagger = replicas[3]
    # put the lagger into a waiting-for-state posture
    lagger._maybe_request_state(old=-1, base=10)
    assert lagger._state_request_mark is not None
    lagger.executed_through = lagger._state_request_mark

    evil = DistributedImmutableMap()
    evil.apply(commit_entry(b"forged", [ref(42)]))
    forged = StateResponse("bft1", evil.snapshot(), 50, (999,))
    lagger._handle(forged)
    # one response (≤ f) installs nothing
    assert len(machines[3]) == 0 and lagger._state_request_mark is not None

    # a Byzantine peer cannot cast extra votes under other replicas' names:
    # the payload's replica field must match the TRANSPORT-authenticated
    # sender, so bft1 re-sending the same snapshot as "bft0"/"bft2" is
    # discarded rather than tallied
    lagger._handle(StateResponse("bft0", evil.snapshot(), 50, (999,)),
                   sender="bft1")
    lagger._handle(StateResponse("bft2", evil.snapshot(), 50, (999,)),
                   sender="bft1")
    assert len(machines[3]) == 0 and lagger._state_request_mark is not None

    # a second, HONEST-but-different response still doesn't reach f+1 on
    # either snapshot — no quorum, no install
    honest = DistributedImmutableMap()
    honest.apply(commit_entry(b"real", [ref(7)]))
    lagger._handle(StateResponse("bft2", honest.snapshot(), 50, (1000,)))
    assert len(machines[3]) == 0 and lagger._state_request_mark is not None

    # f+1 = 2 byte-identical responses from distinct replicas install
    lagger._handle(StateResponse("bft0", honest.snapshot(), 50, (1000,)))
    assert len(machines[3]) == 1 and ref(7) in machines[3]._map
    assert lagger._state_request_mark is None


def test_bft_uniqueness_provider():
    import threading
    bus, replicas, machines, client = make_cluster()
    provider = BFTUniquenessProvider(client)
    results = {}

    def commit(key, label):
        try:
            provider.commit([ref(9)], SecureHash.sha256(label), "me")
            results[key] = "ok"
        except UniquenessException as e:
            results[key] = e.conflicts

    for key, label in (("first", b"a"), ("second", b"b")):
        t = threading.Thread(target=commit, args=(key, label))
        t.start()
        for _ in range(50):
            pump(bus, replicas)
            if key in results:
                break
            import time
            time.sleep(0.01)
        t.join(timeout=5)
    assert results["first"] == "ok"
    assert ref(9) in results["second"]
