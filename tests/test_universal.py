"""Universal-contracts DSL tests (experimental UniversalContract analog).

A zero-coupon-bond-like agreement and an FX-barrier-like agreement built
from the arrangement algebra, verified through the ledger DSL: correct
transitions pass; early exercise, wrong actors, wrong continuations, and
missing fixings fail.
"""
import datetime

import pytest

from corda_tpu.core.contracts.structures import TimeWindow
from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.identity import Party
from corda_tpu.experimental.universal import (Action, Actions, All, Issue,
                                              Move, Transfer, UniversalState,
                                              Zero, after, const, fixing)
from corda_tpu.testing.ledger_dsl import ledger

NOTARY = Party("O=Notary, L=Zurich, C=CH",
               generate_keypair(entropy=b"\x81" * 32).public)
ACME_KP = generate_keypair(entropy=b"\x82" * 32)
OWNER_KP = generate_keypair(entropy=b"\x83" * 32)

T0 = datetime.datetime(2026, 7, 1, tzinfo=datetime.timezone.utc)
MATURITY = datetime.datetime(2026, 12, 1, tzinfo=datetime.timezone.utc)


def window(at):
    return TimeWindow.with_tolerance(at, datetime.timedelta(seconds=30))


def bond():
    """Zero-coupon bond: after maturity the owner may demand 100 USD from
    ACME, ending the agreement."""
    redemption = Transfer(const(100_00), "USD", ACME_KP.public,
                          OWNER_KP.public)
    return Actions({
        "redeem": Action(OWNER_KP.public, after(MATURITY),
                         All((redemption,))),
    })


def test_bond_lifecycle():
    state = UniversalState(bond(), (ACME_KP.public, OWNER_KP.public))
    paid = UniversalState(All((Transfer(const(100_00), "USD",
                                        ACME_KP.public, OWNER_KP.public),)),
                          (ACME_KP.public, OWNER_KP.public))
    with ledger(NOTARY) as l:
        with l.transaction() as tx:     # issuance signed by the liable party
            tx.output("bond", state)
            tx.command(Issue(), ACME_KP.public)
            tx.verifies()
        with l.transaction() as tx:     # early redemption fails
            tx.input("bond")
            tx.output(None, paid)
            tx.command(Move("redeem"), OWNER_KP.public)
            tx.time_window(window(T0))
            tx.fails_with("condition")
        with l.transaction() as tx:     # wrong actor fails
            tx.input("bond")
            tx.output(None, paid)
            tx.command(Move("redeem"), ACME_KP.public)
            tx.time_window(window(MATURITY + datetime.timedelta(days=1)))
            tx.fails_with("actor")
        with l.transaction() as tx:     # wrong continuation fails
            tx.input("bond")
            tx.output(None, UniversalState(Zero(), state.parties))
            tx.command(Move("redeem"), OWNER_KP.public)
            tx.time_window(window(MATURITY + datetime.timedelta(days=1)))
            tx.fails_with("continuation")
        with l.transaction() as tx:     # proper redemption verifies
            tx.input("bond")
            tx.output("obligation", paid)
            tx.command(Move("redeem"), OWNER_KP.public)
            tx.time_window(window(MATURITY + datetime.timedelta(days=1)))
            tx.verifies()


def test_issuance_needs_liable_signature():
    state = UniversalState(bond(), (ACME_KP.public, OWNER_KP.public))
    with ledger(NOTARY) as l:
        with l.transaction() as tx:
            tx.output(None, state)
            tx.command(Issue(), OWNER_KP.public)   # ACME (liable) didn't sign
            tx.fails_with("liable")


def test_fixing_condition():
    """Barrier-style action: exercisable only when the observed rate fixing
    clears the strike — and unexercisable without the fixing at all."""
    arrangement = Actions({
        "exercise": Action(
            OWNER_KP.public,
            fixing("EURUSD").ge(const(11000)),     # 1.1000 in pips
            Zero()),
    })
    state = UniversalState(arrangement, (ACME_KP.public, OWNER_KP.public))
    with ledger(NOTARY) as l:
        with l.transaction() as tx:
            tx.output("opt", state)
            tx.command(Issue(), ACME_KP.public, OWNER_KP.public)
            tx.verifies()
        with l.transaction() as tx:     # no fixing provided
            tx.input("opt")
            tx.command(Move("exercise"), OWNER_KP.public)
            tx.time_window(window(T0))
            tx.fails_with("fixing")
        with l.transaction() as tx:     # below the barrier
            tx.input("opt")
            tx.command(Move("exercise", {"EURUSD": 10500}), OWNER_KP.public)
            tx.time_window(window(T0))
            tx.fails_with("condition")
        with l.transaction() as tx:     # above the barrier: agreement ends
            tx.input("opt")
            tx.command(Move("exercise", {"EURUSD": 11250}), OWNER_KP.public)
            tx.time_window(window(T0))
            tx.verifies()


def test_perceivable_algebra():
    from corda_tpu.experimental.universal import ValuationContext
    ctx = ValuationContext(T0, {"r": 250})
    expr = (fixing("r") * const(2) + const(100)).ge(const(600))
    assert expr.value(ctx) is True
    assert (fixing("r").lt(const(100))).value(ctx) is False
    assert (after(MATURITY)).value(ctx) is False
    assert (after(T0)).value(ctx) is True


def test_arrangement_roundtrips_canonically():
    from corda_tpu.core.serialization import deserialize, serialize
    state = UniversalState(bond(), (ACME_KP.public, OWNER_KP.public))
    assert deserialize(serialize(state)) == state
