"""HTTP gateway tests (the webserver module's API surface)."""
import json
import urllib.request

import pytest

import corda_tpu.finance  # noqa: F401
from corda_tpu.node.rpc import CordaRPCOps
from corda_tpu.testing import MockNetwork
from corda_tpu.tools.webserver import NodeWebServer


@pytest.fixture
def web():
    network = MockNetwork()
    notary = network.create_notary_node()
    alice = network.create_node("O=Alice, L=Madrid, C=ES")
    network.start_nodes()
    ops = CordaRPCOps(alice.services, alice.smm)
    server = NodeWebServer(ops, pump=network.run_network).start()
    yield network, alice, server
    server.stop()


def _get(server, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}", timeout=10) as r:
        return json.loads(r.read())


def _post(server, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_rest_surface(web):
    network, alice, server = web
    status = _get(server, "/api/status")
    assert status["identity"]["legal_identity"]["name"] == \
        "O=Alice, L=Madrid, C=ES"
    assert len(_get(server, "/api/network")) == 2
    assert len(_get(server, "/api/notaries")) == 1
    assert "CashIssueFlow" in str(_get(server, "/api/flows"))
    assert _get(server, "/api/vault") == []

    # start a cash issuance through REST
    out = _post(server, "/api/flows/CashIssueFlow", [
        {"amount": 12300, "currency": "USD"},
        {"hex": "01"},
        {"party": "O=Alice, L=Madrid, C=ES"},
        {"party": "O=Notary Service, L=Zurich, C=CH"},
    ])
    assert out["done"] and "result" in out
    vault = _get(server, "/api/vault")
    assert vault and vault[0]["state"]["data"]["amount"]["quantity"] == 12300
    assert len(_get(server, "/api/transactions")) == 1

    # unknown endpoint → 404 error body
    with pytest.raises(urllib.error.HTTPError):
        _get(server, "/api/nope")

    # metrics: the flow above marked the SMM meters; JSON + Prometheus text
    metrics = _get(server, "/api/metrics")
    assert metrics["Flows.Started"]["count"] >= 1
    assert metrics["Flows.InFlight"]["value"] == 0
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=10) as r:
        text = r.read().decode()
        assert r.headers["Content-Type"].startswith("text/plain")
    assert "corda_tpu_flows_started_count" in text
    assert "corda_tpu_flows_inflight_value 0" in text
    # the exposition carries HELP/TYPE metadata per family
    assert "# TYPE corda_tpu_flows_started_count counter" in text
    assert "# HELP corda_tpu_flows_started_count" in text


def test_health_surface(web):
    network, alice, server = web

    # liveness: always 200 once the server answers at all
    assert _get(server, "/healthz") == {"status": "ok"}

    # a mock node carries no verifier batcher: readiness is vacuous but the
    # notary directory check still reports
    ready = _get(server, "/readyz")
    assert ready["ready"] is True

    # the profiler snapshot rides /debug/profile
    prof = _get(server, "/debug/profile")
    for key in ("kernels", "occupancy", "overlap", "compile_s_total",
                "compile_cache_hits"):
        assert key in prof


def test_readyz_tracks_batcher_dispatcher(web):
    """With a batching verifier installed, /readyz reflects the dispatcher
    thread's liveness: 200 while it runs, 503 once it is closed."""
    from corda_tpu.verifier.batcher import SignatureBatcher
    from corda_tpu.verifier.service import TpuTransactionVerifierService
    network, alice, server = web
    svc = TpuTransactionVerifierService(
        workers=1, batcher=SignatureBatcher(use_device=False))
    alice.services.verifier_service = svc
    try:
        ready = _get(server, "/readyz")
        assert ready["ready"] is True
        assert ready["checks"]["batcher_dispatcher_alive"] is True

        svc.batcher.close()
        try:
            _get(server, "/readyz")
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            body = json.loads(e.read())
            assert body["ready"] is False
            assert body["checks"]["batcher_dispatcher_alive"] is False
    finally:
        alice.services.verifier_service = None
        svc.shutdown()


def test_retry_and_breaker_metric_families(web):
    """Robustness counters ride both metric surfaces: the retry module's
    process-wide registry is merged into /api/metrics + /metrics, and a
    batcher wired to the node registry contributes its breaker families
    (state gauges per scheme, trip meter) even before anything trips."""
    from corda_tpu.utils import retry
    from corda_tpu.verifier.batcher import SignatureBatcher
    from corda_tpu.verifier.service import TpuTransactionVerifierService
    network, alice, server = web
    svc = TpuTransactionVerifierService(
        workers=1,
        batcher=SignatureBatcher(use_device=False,
                                 metrics=alice.services.monitoring))
    alice.services.verifier_service = svc
    try:
        # exercise one retry site so the per-site meter exists too
        retry.retry_call(lambda: None, site="webtest",
                         policy=retry.RetryPolicy(max_attempts=1))

        metrics = _get(server, "/api/metrics")
        assert "Retry.Attempts" in metrics          # always-present family
        assert "Retry.GiveUps" in metrics
        assert metrics["Retry.Attempts.webtest"]["count"] >= 1
        for scheme in ("ed25519", "secp256k1", "secp256r1"):
            assert metrics[f"Breaker.State.{scheme}"]["value"] == 0
        assert metrics["Breaker.Trips"]["count"] == 0

        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "corda_tpu_retry_attempts" in text
        assert "corda_tpu_breaker_state_ed25519" in text
        assert "corda_tpu_breaker_trips" in text

        # a trip moves the gauge and meter on the same surfaces
        for _ in range(3):
            svc.batcher._breakers["secp256r1"].record_failure()
        metrics = _get(server, "/api/metrics")
        assert metrics["Breaker.State.secp256r1"]["value"] == 1
        assert metrics["Breaker.Trips"]["count"] == 1
    finally:
        alice.services.verifier_service = None
        svc.shutdown()


def test_debug_requests_empty_for_in_process_verifier(web):
    network, alice, server = web
    # in-process verifier keeps no request log: well-formed empty payload
    assert _get(server, "/debug/requests") == {"requests": {}}


def test_debug_raft_empty_for_non_notary(web):
    network, alice, server = web
    # alice is not a notary: the observatory answers with empty groups
    assert _get(server, "/debug/raft") == {"groups": {}}


def test_debug_raft_serves_ops_report():
    class Ops:
        def raft_report(self):
            return {"groups": {"s0": {"nodes": [], "leader": None,
                                      "log_entries": 7,
                                      "elections_total": 1}}}

    server = NodeWebServer(Ops()).start()
    try:
        out = _get(server, "/debug/raft")
        assert out["groups"]["s0"]["log_entries"] == 7
    finally:
        server.stop()
    # an ops surface without the capability degrades to empty groups
    bare = NodeWebServer(object()).start()
    try:
        assert _get(bare, "/debug/raft") == {"groups": {}}
    finally:
        bare.stop()


def test_api_timeseries_routes_and_validation():
    from corda_tpu.observability.timeseries import (TimeSeriesStore,
                                                    set_timeseries)

    class Ops:
        def __init__(self, store):
            self.store = store

        def timeseries_snapshot(self, names=None, limit=None):
            return self.store.snapshot(names=names, limit=limit)

    store = TimeSeriesStore(resolutions=((1.0, 4), (10.0, 4)))
    for i in range(12):
        store.record("Raft.LogEntries", i, t=float(i))
        store.record("Shard.SkewIndex", 1.0, t=float(i))
    store.flush()
    server = NodeWebServer(Ops(store)).start()
    try:
        out = _get(server, "/api/timeseries")
        assert out["columns"] == ["t", "n", "min", "max", "mean", "last"]
        assert sorted(out["series"]) == ["Raft.LogEntries",
                                         "Shard.SkewIndex"]
        levels = out["series"]["Raft.LogEntries"]
        # ≥2 resolutions of downsampled history (the acceptance shape)
        assert sum(1 for lvl in levels if lvl["points"]) >= 2
        # names filter + per-resolution row cap
        out = _get(server, "/api/timeseries?names=Shard.SkewIndex&limit=2")
        assert list(out["series"]) == ["Shard.SkewIndex"]
        assert all(len(lvl["points"]) <= 2
                   for lvl in out["series"]["Shard.SkewIndex"])
        # unknown names are absent, never an error
        out = _get(server, "/api/timeseries?names=nope")
        assert out["series"] == {}
        # malformed queries are the client's fault
        for bad in ("/api/timeseries?limit=zap", "/api/timeseries?limit=0"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(server, bad)
            assert ei.value.code == 400
    finally:
        server.stop()
    # an ops surface without the capability reads the process-global store
    prev = set_timeseries(store)
    bare = NodeWebServer(object()).start()
    try:
        out = _get(bare, "/api/timeseries?names=Raft.LogEntries")
        assert list(out["series"]) == ["Raft.LogEntries"]
    finally:
        bare.stop()
        set_timeseries(prev)


def test_api_timeseries_since_and_resolution_filters():
    from corda_tpu.observability.timeseries import TimeSeriesStore

    class Ops:
        def __init__(self, store):
            self.store = store

        def timeseries_snapshot(self, names=None, limit=None, since=None,
                                resolution=None):
            return self.store.snapshot(names=names, limit=limit,
                                       since=since, resolution=resolution)

    store = TimeSeriesStore(resolutions=((1.0, 8), (10.0, 8)))
    for i in range(12):
        store.record("Resource.Vault.States", float(i), t=float(i))
    store.flush()
    server = NodeWebServer(Ops(store)).start()
    try:
        # an incremental poller asks only for buckets it has not seen
        out = _get(server, "/api/timeseries?since=8")
        pts = out["series"]["Resource.Vault.States"][0]["points"]
        assert pts and all(p[0] >= 8.0 for p in pts)
        # the soak leak fit asks for one ring by its bucket width
        out = _get(server, "/api/timeseries?resolution=10")
        levels = out["series"]["Resource.Vault.States"]
        assert len(levels) == 1 and levels[0]["bucket_s"] == 10.0
        # unknown resolution matches nothing — empty, never an error
        out = _get(server, "/api/timeseries?resolution=7")
        assert out["series"]["Resource.Vault.States"] == []
        # malformed filters are the client's fault
        for bad in ("/api/timeseries?resolution=0",
                    "/api/timeseries?resolution=zap",
                    "/api/timeseries?since=zap"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(server, bad)
            assert ei.value.code == 400
    finally:
        server.stop()

    # an ops surface predating the soak filters (2-arg snapshot) serves
    # the unfiltered snapshot rather than a 500
    class OldOps:
        def __init__(self, store):
            self.store = store

        def timeseries_snapshot(self, names=None, limit=None):
            return self.store.snapshot(names=names, limit=limit)

    old = NodeWebServer(OldOps(store)).start()
    try:
        out = _get(old, "/api/timeseries?since=8&resolution=10")
        assert "Resource.Vault.States" in out["series"]
    finally:
        old.stop()


def test_debug_soak_serves_ops_report_and_global_seam():
    from corda_tpu.observability.resprof import (ResourceRegistry,
                                                 set_resources)

    class Ops:
        def soak_report(self):
            return {"resources": {"X": {"size": 1, "kind": "bounded",
                                        "verdict": "bounded"}},
                    "leaking": [], "cpu": None}

    server = NodeWebServer(Ops()).start()
    try:
        out = _get(server, "/debug/soak")
        assert out["resources"]["X"]["verdict"] == "bounded"
        assert out["leaking"] == []
    finally:
        server.stop()
    # an ops surface without the capability reads the process globals —
    # well-formed and empty on a node with no registered probes
    prev = set_resources(ResourceRegistry())
    bare = NodeWebServer(object()).start()
    try:
        out = _get(bare, "/debug/soak")
        assert out == {"resources": {}, "leaking": [], "cpu": None}
    finally:
        bare.stop()
        set_resources(prev)


def test_debug_requests_serves_request_log():
    from corda_tpu.observability import RequestLog

    class Ops:
        def __init__(self):
            self.log = RequestLog()

        def request_timelines(self, limit=None):
            return self.log.snapshot(limit=limit)

    ops = Ops()
    ops.log.append(1, "submitted", n_sigs=4)
    ops.log.append(1, "routed", worker="w0", reason="least-loaded-rr",
                   est_load={"w0": 0.0})
    ops.log.append(1, "resolved", ok=True, worker="w0")
    ops.log.append(2, "submitted", n_sigs=2)
    server = NodeWebServer(ops).start()
    try:
        out = _get(server, "/debug/requests")
        assert [e["event"] for e in out["requests"]["1"]] == [
            "submitted", "routed", "resolved"]
        assert out["requests"]["1"][1]["worker"] == "w0"
        assert out["requests"]["1"][1]["reason"] == "least-loaded-rr"
        # newest request first; limit caps the REQUEST count
        limited = _get(server, "/debug/requests?limit=1")
        assert list(limited["requests"]) == ["2"]
        # malformed limit is the client's fault
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(server, "/debug/requests?limit=zap")
        assert ei.value.code == 400
    finally:
        server.stop()
