"""Flow framework tests over MockNetwork — session protocol semantics.

Reference analog: FlowFrameworkTests.kt (921 LoC: send/receive pairs, session
init/confirm/reject, error propagation as FlowException at the peer's receive,
restart-from-checkpoint mid-flow).
"""
import pytest

from corda_tpu.flows import (FlowException, FlowLogic, Receive, Send,
                             SendAndReceive, WaitForLedgerCommit,
                             initiated_by, initiating_flow)
from corda_tpu.node.checkpoints import FileCheckpointStorage
from corda_tpu.testing import MockNetwork


@initiating_flow
class PingFlow(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        answer = yield SendAndReceive(self.peer, "ping", str)
        return answer.unwrap(lambda d: d)


@initiated_by(PingFlow)
class PongFlow(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        msg = yield Receive(self.peer, str)
        assert msg.unwrap(lambda d: d) == "ping"
        yield Send(self.peer, "pong")
        return "done"


@initiating_flow
class AngryInitiator(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        answer = yield SendAndReceive(self.peer, "hello", str)
        return answer.unwrap(lambda d: d)


@initiated_by(AngryInitiator)
class AngryResponder(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        _ = yield Receive(self.peer, str)
        raise FlowException("I am grumpy today")


@initiating_flow
class UnregisteredInitiator(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        answer = yield SendAndReceive(self.peer, "anyone there?", str)
        return answer.unwrap(lambda d: d)


@initiating_flow
class MultiHopFlow(FlowLogic):
    """Exercises sub_flow composition (FlowLogic.kt:156-168)."""

    def __init__(self, peer):
        self.peer = peer

    def call(self):
        first = yield from self.sub_flow(PingFlow(self.peer))
        second = yield from self.sub_flow(PingFlow2(self.peer))
        return (first, second)


@initiating_flow
class PingFlow2(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        answer = yield SendAndReceive(self.peer, "ping", str)
        return answer.unwrap(lambda d: d)


@initiated_by(PingFlow2)
class PongFlow2(PongFlow):
    pass


@pytest.fixture
def net():
    network = MockNetwork()
    a = network.create_node("O=Alice, L=London, C=GB")
    b = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()
    return network, a, b


def test_ping_pong(net):
    network, a, b = net
    fsm = a.start_flow(PingFlow(b.party))
    network.run_network()
    assert fsm.result_future.result(timeout=1) == "pong"


def test_error_propagates_to_initiator(net):
    network, a, b = net
    fsm = a.start_flow(AngryInitiator(b.party))
    network.run_network()
    with pytest.raises(FlowException, match="grumpy"):
        fsm.result_future.result(timeout=1)


def test_session_init_rejected_when_unregistered(net):
    network, a, b = net
    fsm = a.start_flow(UnregisteredInitiator(b.party))
    network.run_network()
    with pytest.raises(FlowException, match="No initiated flow registered"):
        fsm.result_future.result(timeout=1)


def test_sub_flow_composition(net):
    network, a, b = net
    fsm = a.start_flow(MultiHopFlow(b.party))
    network.run_network()
    assert fsm.result_future.result(timeout=1) == ("pong", "pong")


def test_checkpoint_restart_mid_flow(tmp_path):
    """Kill the initiating node after its SessionInit is sent but before the
    response arrives; restart from checkpoints; the flow must complete
    (StateMachineManager.kt:257-305 restore semantics, TwoPartyTradeFlowTests
    mid-flow restart analog)."""
    network = MockNetwork()
    a = network.create_node(
        "O=Alice, L=London, C=GB",
        checkpoint_storage=FileCheckpointStorage(str(tmp_path / "a_ckpts")))
    b = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()

    fsm = a.start_flow(PingFlow(b.party))
    assert len(a.smm.checkpoints.get_all_checkpoints()) == 1
    # deliver only the SessionInit to Bob; Bob replies; do NOT deliver to Alice
    network.bus.pump_receive(str(b.party.name))
    a2 = a.restart()  # Alice dies and comes back
    a2.start()
    restored = list(a2.smm.flows.values())
    assert len(restored) == 1
    network.run_network()
    assert restored[0].result_future.result(timeout=1) == "pong"
    assert a2.smm.checkpoints.get_all_checkpoints() == []


@initiating_flow
class DoubleReceiveAfterError(FlowLogic):
    """Catches the peer's error then tries to receive again — must fail fast,
    not hang on the dead session."""

    def __init__(self, peer):
        self.peer = peer

    def call(self):
        try:
            yield SendAndReceive(self.peer, "hello", str)
        except FlowException:
            pass
        answer = yield Receive(self.peer, str)  # session is dead
        return answer


@initiated_by(DoubleReceiveAfterError)
class AngryResponder2(AngryResponder):
    pass


def test_receive_on_dead_session_fails_fast(net):
    network, a, b = net
    fsm = a.start_flow(DoubleReceiveAfterError(b.party))
    network.run_network()
    with pytest.raises(FlowException, match="ended"):
        fsm.result_future.result(timeout=1)


@initiating_flow
class RetryingFlow(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        answer = yield from self.send_and_receive_with_retry(self.peer, "ping",
                                                             str, attempts=3)
        return answer.unwrap(lambda d: d)


# grumpy twice, then answers — only a per-attempt FRESH session can succeed
_GRUMPY_COUNT = {"n": 0}


@initiated_by(RetryingFlow)
class EventuallyHelpful(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        msg = yield Receive(self.peer, str)
        _GRUMPY_COUNT["n"] += 1
        if _GRUMPY_COUNT["n"] < 3:
            raise FlowException("not yet")
        yield Send(self.peer, "pong")
        return None


def test_send_and_receive_with_retry(net):
    network, a, b = net
    _GRUMPY_COUNT["n"] = 0
    fsm = a.start_flow(RetryingFlow(b.party))
    network.run_network()
    assert fsm.result_future.result(timeout=1) == "pong"
    assert _GRUMPY_COUNT["n"] == 3


@initiating_flow
class RetryThenChatFlow(FlowLogic):
    """Retry exchange (peer fails once) followed by a second exchange on the
    same post-retry session — the restart-replay regression case."""

    def __init__(self, peer):
        self.peer = peer

    def call(self):
        first = yield from self.send_and_receive_with_retry(self.peer, "ping",
                                                            str, attempts=3)
        second = yield SendAndReceive(self.peer, "again", str)
        return (first.unwrap(lambda d: d), second.unwrap(lambda d: d))


_FLAKY_COUNT = {"n": 0}


@initiated_by(RetryThenChatFlow)
class FlakyThenChatty(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        msg = yield Receive(self.peer, str)
        _FLAKY_COUNT["n"] += 1
        if _FLAKY_COUNT["n"] < 2:
            raise FlowException("not yet")
        assert msg.unwrap(lambda d: d) == "ping"
        yield Send(self.peer, "pong")
        msg2 = yield Receive(self.peer, str)
        assert msg2.unwrap(lambda d: d) == "again"
        yield Send(self.peer, "pong2")
        return None


def test_retry_discard_not_replayed_on_restart(tmp_path):
    """Restart a flow that already survived a session-failure retry and is
    parked on a LATER exchange with the same party: replaying the logged
    error must not re-run discard_session against the restored live session
    (which would orphan the parked receive)."""
    network = MockNetwork()
    a = network.create_node(
        "O=Alice, L=London, C=GB",
        checkpoint_storage=FileCheckpointStorage(str(tmp_path / "a_ckpts")))
    b = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()
    _FLAKY_COUNT["n"] = 0

    fsm = a.start_flow(RetryThenChatFlow(b.party))
    alice, bob = str(a.party.name), str(b.party.name)
    # drive until the retry succeeded and Alice parked on the second receive
    # (the resume that logs the 'data' entry also sends "again" synchronously)
    for _ in range(50):
        if any(e[0] == "data" for e in fsm.response_log):
            break
        network.bus.pump_receive(bob)
        network.bus.pump_receive(alice)
    else:
        raise AssertionError("never reached the second exchange")
    network.run_network(exclude=(alice,))  # Bob answers "again" → stays queued

    a2 = a.restart()  # Alice dies and comes back mid-second-exchange
    a2.start()
    restored = list(a2.smm.flows.values())
    assert len(restored) == 1
    network.run_network()
    assert restored[0].result_future.result(timeout=1) == ("pong", "pong2")


def test_flow_completion_removes_checkpoints(net):
    network, a, b = net
    a.start_flow(PingFlow(b.party))
    network.run_network()
    assert a.smm.checkpoints.get_all_checkpoints() == []
    assert a.smm.flows == {}
    assert b.smm.flows == {}
