"""SignatureBatcher policy tests: host-crossover routing, per-item fault
isolation, bulk submission (VERDICT r2 #1b/c, weak #9).

Reference analog: the verifier thread-pool seam
(InMemoryTransactionVerifierService.kt:10-18) — here the policy layer in
front of the device kernels.
"""
import threading

import pytest

from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.keys import PublicKey
from corda_tpu.core.crypto.schemes import (ECDSA_SECP256K1_SHA256,
                                           ECDSA_SECP256R1_SHA256,
                                           EDDSA_ED25519_SHA512)
from corda_tpu.core.crypto.signatures import Crypto
from corda_tpu.verifier.batcher import SignatureBatcher, _Group, _Pending

KP = generate_keypair(ECDSA_SECP256K1_SHA256, entropy=b"\x61" * 32)
CONTENT = b"batcher policy test content"
SIG = Crypto.sign_with_key(KP, CONTENT).bytes


def test_small_batches_route_to_host():
    """Below the crossover the device dispatch floor (~140 ms) dwarfs host
    verification — small batches must run on host, and without the linger
    wait (the p50@batch=1 path)."""
    b = SignatureBatcher(host_crossover=64)
    try:
        futs = [b.submit(KP.public, SIG, CONTENT) for _ in range(3)]
        assert all(f.result(timeout=30) for f in futs)
        snap = b.metrics.snapshot()
        assert snap["SigBatcher.HostRouted"]["count"] == 3
        assert "SigBatcher.DeviceBatches" not in snap
    finally:
        b.close()


def test_crossover_zero_forces_device():
    b = SignatureBatcher(host_crossover=0, max_latency_s=0.01)
    try:
        futs = b.submit_many([(KP.public, SIG, CONTENT)] * 4)
        assert all(f.result(timeout=120) for f in futs)
        snap = b.metrics.snapshot()
        assert snap["SigBatcher.DeviceBatches"]["count"] >= 1
        assert snap["SigBatcher.DeviceChecked"]["count"] >= 4
    finally:
        b.close()


def test_malformed_member_does_not_poison_batch():
    """Weak #9: one malformed item (garbage key encoding / truncated DER)
    becomes a False verdict for that item alone — siblings still verify."""
    garbage_key = PublicKey(ECDSA_SECP256K1_SHA256, b"\xff" * 33)
    b = SignatureBatcher(host_crossover=0, max_latency_s=0.01)
    try:
        futs = b.submit_many([
            (KP.public, SIG, CONTENT),
            (garbage_key, SIG, CONTENT),          # undecodable point
            (KP.public, b"\x00\x01", CONTENT),     # truncated DER
            (KP.public, SIG, CONTENT),
        ])
        results = [f.result(timeout=120) for f in futs]
        assert results == [True, False, False, True]
    finally:
        b.close()


def test_p50_batch1_latency_skips_linger():
    """A lone submit must not pay max_latency_s linger: with the crossover
    active it dispatches immediately to host. Generous bound (CI boxes)."""
    import time
    b = SignatureBatcher(host_crossover=64, max_latency_s=0.5)
    try:
        b.submit(KP.public, SIG, CONTENT).result(timeout=30)  # warm path
        t0 = time.perf_counter()
        assert b.submit(KP.public, SIG, CONTENT).result(timeout=30)
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.4, f"lone submit lingered: {elapsed:.3f}s"
    finally:
        b.close()


def test_bulk_submit_verdicts_match_individual():
    wrong = Crypto.sign_with_key(KP, b"other").bytes
    b = SignatureBatcher(host_crossover=64)
    try:
        futs = b.submit_many([(KP.public, SIG, CONTENT),
                              (KP.public, wrong, CONTENT)])
        assert [f.result(timeout=30) for f in futs] == [True, False]
    finally:
        b.close()


def test_mixed_drain_preps_schemes_concurrently():
    """Tentpole pin: ONE drain holding ed25519 + k1 + r1 buckets routes each
    bucket to its own prep-pool worker — no serial per-bucket _flush loop on
    the dispatcher thread. With the ed25519 flush wedged on an event, the
    ECDSA buckets of the SAME drain still prep and resolve."""
    ed_kp = generate_keypair(EDDSA_ED25519_SHA512, entropy=b"\x71" * 32)
    r1_kp = generate_keypair(ECDSA_SECP256R1_SHA256, entropy=b"\x72" * 32)
    content = b"mixed drain"
    ed_sig = Crypto.sign_with_key(ed_kp, content).bytes
    k1_sig = Crypto.sign_with_key(KP, content).bytes
    r1_sig = Crypto.sign_with_key(r1_kp, content).bytes

    release, entered = threading.Event(), threading.Event()
    # huge crossover: every bucket takes the host route inside _flush — the
    # pipeline shape under test is identical, with no kernel compiles
    b = SignatureBatcher(host_crossover=10_000, max_latency_s=0.05)
    inner = b._run_host
    ed_id = EDDSA_ED25519_SHA512.scheme_number_id

    def gated_run_host(items):
        if items[0].key.scheme.scheme_number_id == ed_id:
            entered.set()
            assert release.wait(timeout=30)
        return inner(items)

    b._run_host = gated_run_host   # instance shadow of the staticmethod
    try:
        # one submit_many -> one notify -> the dispatcher drains all three
        # scheme buckets in a single pass
        ed_fut, k1_fut, r1_fut = b.submit_many([
            (ed_kp.public, ed_sig, content),
            (KP.public, k1_sig, content),
            (r1_kp.public, r1_sig, content),
        ])
        assert entered.wait(timeout=30)    # ed25519 prep is live and wedged
        assert k1_fut.result(timeout=30) is True
        assert r1_fut.result(timeout=30) is True
        assert not ed_fut.done()
        release.set()
        assert ed_fut.result(timeout=30) is True
        # the overlap gauge saw >= 2 preps in flight at once
        assert b.metrics.snapshot()["SigBatcher.PrepActive"]["max"] >= 2
    finally:
        release.set()
        b.close()


class _CountingLock:
    def __init__(self):
        self._lock = threading.Lock()
        self.acquisitions = 0

    def __enter__(self):
        self.acquisitions += 1
        return self._lock.__enter__()

    def __exit__(self, *exc):
        return self._lock.__exit__(*exc)


def test_group_resolve_single_lock_acquire_per_flush():
    """_resolve batches group fan-in: each group's lock is taken at most
    ONCE per flush, regardless of how many members the flush carries (it
    was once per item — 32k acquires for a 32k single-group flush)."""
    b = SignatureBatcher(use_device=False)
    try:
        g = _Group(6)
        g.lock = _CountingLock()
        items = [_Pending(KP.public, SIG, CONTENT, group=g, index=i)
                 for i in range(6)]
        b._resolve("host", items[:4], [True, False, True, True])
        assert g.lock.acquisitions == 1
        assert not g.future.done()
        b._resolve("host", items[4:], [True, True])
        assert g.lock.acquisitions == 2
        assert g.future.result(timeout=5) == [True, False, True, True,
                                              True, True]
    finally:
        b.close()


def test_group_mixed_schemes_order_and_isolation():
    """submit_group across all three schemes: verdicts return in submission
    order, and a malformed member fails ALONE — its group siblings (in
    other scheme buckets, resolved by other flushes) still verify."""
    ed_kp = generate_keypair(EDDSA_ED25519_SHA512, entropy=b"\x73" * 32)
    r1_kp = generate_keypair(ECDSA_SECP256R1_SHA256, entropy=b"\x74" * 32)
    content = b"group order"
    checks = [
        (ed_kp.public, Crypto.sign_with_key(ed_kp, content).bytes, content),
        (KP.public, b"\x30\x02\x02\x00", content),        # malformed DER
        (r1_kp.public, Crypto.sign_with_key(r1_kp, content).bytes, content),
        (KP.public, Crypto.sign_with_key(KP, content).bytes, content),
    ]
    b = SignatureBatcher(max_latency_s=0.01)
    try:
        assert b.submit_group(checks).result(timeout=120) == [
            True, False, True, True]
        assert b.submit_group([]).result(timeout=5) == []
    finally:
        b.close()


def test_cancelled_future_does_not_wedge_the_dispatcher():
    """Review r3: a caller cancelling its future must not crash the
    dispatcher/finisher — later submissions still resolve."""
    b = SignatureBatcher(host_crossover=0, max_latency_s=0.01)
    try:
        doomed = b.submit(KP.public, SIG, CONTENT)
        doomed.cancel()   # may or may not win the race; either is fine
        after = b.submit_many([(KP.public, SIG, CONTENT)] * 3)
        assert all(f.result(timeout=120) for f in after)
        assert all(b.submit_group([(KP.public, SIG, CONTENT)] * 2)
                   .result(timeout=120))
    finally:
        b.close()
