"""SignatureBatcher policy tests: host-crossover routing, per-item fault
isolation, bulk submission (VERDICT r2 #1b/c, weak #9).

Reference analog: the verifier thread-pool seam
(InMemoryTransactionVerifierService.kt:10-18) — here the policy layer in
front of the device kernels.
"""
import pytest

from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.crypto.keys import PublicKey
from corda_tpu.core.crypto.schemes import ECDSA_SECP256K1_SHA256
from corda_tpu.core.crypto.signatures import Crypto
from corda_tpu.verifier.batcher import SignatureBatcher

KP = generate_keypair(ECDSA_SECP256K1_SHA256, entropy=b"\x61" * 32)
CONTENT = b"batcher policy test content"
SIG = Crypto.sign_with_key(KP, CONTENT).bytes


def test_small_batches_route_to_host():
    """Below the crossover the device dispatch floor (~140 ms) dwarfs host
    verification — small batches must run on host, and without the linger
    wait (the p50@batch=1 path)."""
    b = SignatureBatcher(host_crossover=64)
    try:
        futs = [b.submit(KP.public, SIG, CONTENT) for _ in range(3)]
        assert all(f.result(timeout=30) for f in futs)
        snap = b.metrics.snapshot()
        assert snap["SigBatcher.HostRouted"]["count"] == 3
        assert "SigBatcher.DeviceBatches" not in snap
    finally:
        b.close()


def test_crossover_zero_forces_device():
    b = SignatureBatcher(host_crossover=0, max_latency_s=0.01)
    try:
        futs = b.submit_many([(KP.public, SIG, CONTENT)] * 4)
        assert all(f.result(timeout=120) for f in futs)
        snap = b.metrics.snapshot()
        assert snap["SigBatcher.DeviceBatches"]["count"] >= 1
        assert snap["SigBatcher.DeviceChecked"]["count"] >= 4
    finally:
        b.close()


def test_malformed_member_does_not_poison_batch():
    """Weak #9: one malformed item (garbage key encoding / truncated DER)
    becomes a False verdict for that item alone — siblings still verify."""
    garbage_key = PublicKey(ECDSA_SECP256K1_SHA256, b"\xff" * 33)
    b = SignatureBatcher(host_crossover=0, max_latency_s=0.01)
    try:
        futs = b.submit_many([
            (KP.public, SIG, CONTENT),
            (garbage_key, SIG, CONTENT),          # undecodable point
            (KP.public, b"\x00\x01", CONTENT),     # truncated DER
            (KP.public, SIG, CONTENT),
        ])
        results = [f.result(timeout=120) for f in futs]
        assert results == [True, False, False, True]
    finally:
        b.close()


def test_p50_batch1_latency_skips_linger():
    """A lone submit must not pay max_latency_s linger: with the crossover
    active it dispatches immediately to host. Generous bound (CI boxes)."""
    import time
    b = SignatureBatcher(host_crossover=64, max_latency_s=0.5)
    try:
        b.submit(KP.public, SIG, CONTENT).result(timeout=30)  # warm path
        t0 = time.perf_counter()
        assert b.submit(KP.public, SIG, CONTENT).result(timeout=30)
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.4, f"lone submit lingered: {elapsed:.3f}s"
    finally:
        b.close()


def test_bulk_submit_verdicts_match_individual():
    wrong = Crypto.sign_with_key(KP, b"other").bytes
    b = SignatureBatcher(host_crossover=64)
    try:
        futs = b.submit_many([(KP.public, SIG, CONTENT),
                              (KP.public, wrong, CONTENT)])
        assert [f.result(timeout=30) for f in futs] == [True, False]
    finally:
        b.close()


def test_cancelled_future_does_not_wedge_the_dispatcher():
    """Review r3: a caller cancelling its future must not crash the
    dispatcher/finisher — later submissions still resolve."""
    b = SignatureBatcher(host_crossover=0, max_latency_s=0.01)
    try:
        doomed = b.submit(KP.public, SIG, CONTENT)
        doomed.cancel()   # may or may not win the race; either is fine
        after = b.submit_many([(KP.public, SIG, CONTENT)] * 3)
        assert all(f.result(timeout=120) for f in after)
        assert all(b.submit_group([(KP.public, SIG, CONTENT)] * 2)
                   .result(timeout=120))
    finally:
        b.close()
