"""Attachment-delivered contract code through the sandbox (VERDICT r2 #5).

Reference analogs: AttachmentsClassLoaderTests (contract code loads from a
transaction's attachments; a peer without the code installed still
verifies) + the sandbox gating (hostile attachments rejected).
"""
import pytest

from corda_tpu.core.contracts.attachment_contract import (AttachmentContract,
                                                          SandboxedCommand,
                                                          SandboxedState)
from corda_tpu.core.contracts.exceptions import (
    TransactionVerificationException)
from corda_tpu.core.contracts.structures import Attachment, Command
from corda_tpu.core.transactions.builder import TransactionBuilder
from corda_tpu.testing import MockNetwork

# The token contract exists ONLY as this source string — no Python module
# anywhere defines it. Conservation-of-value semantics: issues need an
# "issue" command; moves conserve the total amount.
TOKEN_CONTRACT = """
class TokenContract:
    def verify(self, tx):
        total_in = sum(s["fields"]["amount"] for s in tx["inputs"])
        total_out = sum(s["fields"]["amount"] for s in tx["outputs"])
        names = [c["name"] for c in tx["commands"]]
        if "issue" in names:
            if tx["inputs"]:
                raise ValueError("an issue consumes nothing")
            if total_out <= 0:
                raise ValueError("issue a positive amount")
        elif "move" in names:
            if total_in != total_out:
                raise ValueError("conservation violated")
        else:
            raise ValueError("unknown command")
"""

HOSTILE_IMPORT = "import os\nclass TokenContract:\n    def verify(self, tx):\n        pass\n"
HOSTILE_LOOP = ("class TokenContract:\n"
                "    def verify(self, tx):\n"
                "        while True:\n"
                "            x = 1\n")


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    alice = network.create_node("O=Alice, L=London, C=GB")
    bob = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()
    return network, notary, alice, bob


def _issue_tx(alice, notary, source: bytes, amount=100, owner=None):
    """Build + sign an issue of a sandboxed token, attachment included."""
    att = Attachment.of(source)
    alice.services.attachments.import_attachment(source)
    state = SandboxedState(att.id, "TokenContract",
                           (("amount", amount),),
                           ((owner or alice.party).owning_key,))
    builder = TransactionBuilder(notary=notary.party)
    builder.add_output_state(state, notary.party)
    builder.add_attachment(att.id)
    builder.add_command(Command(SandboxedCommand("issue"),
                                (alice.party.owning_key,)))
    builder.sign_with(
        alice.services.key_management.key_pair(alice.party.owning_key))
    return builder.to_signed_transaction(check_sufficient_signatures=False)


def test_peer_verifies_contract_it_never_installed(net):
    """The done-criterion: Bob receives a transaction whose contract exists
    ONLY as an attachment; resolution pulls the blob; verification runs it
    in the sandbox; the state lands in his vault."""
    from corda_tpu.flows.library import FinalityFlow

    network, notary, alice, bob = net
    stx = _issue_tx(alice, notary, TOKEN_CONTRACT.encode(), owner=bob.party)
    assert not bob.services.attachments.has_attachment(stx.tx.attachments[0])
    fsm = alice.start_flow(FinalityFlow(stx, [bob.party]))
    network.run_network()
    fsm.result_future.result(timeout=1)

    # bob fetched the attachment during resolution and verified sandboxed
    assert bob.services.attachments.has_attachment(stx.tx.attachments[0])
    states = bob.services.vault.unconsumed_states(SandboxedState)
    assert len(states) == 1
    assert states[0].state.data.field("amount") == 100


def test_sandboxed_contract_enforces_its_rules(net):
    network, notary, alice, bob = net
    stx = _issue_tx(alice, notary, TOKEN_CONTRACT.encode(), amount=-5)
    ltx = stx.to_ledger_transaction(alice.services)
    with pytest.raises(TransactionVerificationException,
                       match="positive amount"):
        ltx.verify()


def test_missing_attachment_rejected(net):
    network, notary, alice, bob = net
    att = Attachment.of(TOKEN_CONTRACT.encode())
    state = SandboxedState(att.id, "TokenContract", (("amount", 1),),
                           (alice.party.owning_key,))
    builder = TransactionBuilder(notary=notary.party)
    builder.add_output_state(state, notary.party)
    # attachment id NOT added to the transaction
    builder.add_command(Command(SandboxedCommand("issue"),
                                (alice.party.owning_key,)))
    wtx = builder.to_wire_transaction()
    ltx = wtx.to_ledger_transaction(alice.services)
    with pytest.raises(TransactionVerificationException,
                       match="not attached"):
        ltx.verify()


@pytest.mark.parametrize("source,error", [
    (HOSTILE_IMPORT, "rejected by the sandbox"),
    (HOSTILE_LOOP, "budget"),
    (b"\xff\xfe binary junk", "not source text"),
    ("x = 1\n", "does not define contract class"),
])
def test_hostile_attachments_rejected(net, source, error):
    network, notary, alice, bob = net
    blob = source if isinstance(source, bytes) else source.encode()
    stx = _issue_tx(alice, notary, blob)
    ltx = stx.to_ledger_transaction(alice.services)
    with pytest.raises(TransactionVerificationException, match=error):
        ltx.verify()


def test_move_conserves_value(net):
    network, notary, alice, bob = net
    from corda_tpu.core.contracts.structures import StateAndRef, StateRef

    stx = _issue_tx(alice, notary, TOKEN_CONTRACT.encode())
    alice.services.record_transactions(stx)
    sar = alice.services.vault.unconsumed_states(SandboxedState)[0]
    att_id = stx.tx.attachments[0]

    def move(amount_out):
        state = sar.state.data
        builder = TransactionBuilder(notary=notary.party)
        builder.add_input_state(StateAndRef(sar.state, sar.ref))
        from dataclasses import replace
        builder.add_output_state(
            replace(state, fields=(("amount", amount_out),),
                    owners=(bob.party.owning_key,)), notary.party)
        builder.add_attachment(att_id)
        builder.add_command(Command(SandboxedCommand("move"),
                                    (alice.party.owning_key,)))
        wtx = builder.to_wire_transaction()
        return wtx.to_ledger_transaction(alice.services)

    move(100).verify()                     # conserved: ok
    with pytest.raises(TransactionVerificationException,
                       match="conservation"):
        move(150).verify()                 # minted from nothing
