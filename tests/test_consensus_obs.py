"""Consensus observatory: per-entry commit attribution (the telescoping
property the bench validity probe relies on), election episodes, the
pooled /debug/raft report, Raft.* metric families (absent-never-zero
native parity), growth watchdogs, shard heat/skew, and the flattened
ledger_raft_* artifact fields."""
import logging

import pytest

from corda_tpu.consensus.raft import LEADER, RaftNode
from corda_tpu.consensus.raft_uniqueness import DistributedImmutableMap
from corda_tpu.consensus.raftcore import NATIVE_RAFT_AVAILABLE
from corda_tpu.consensus.sharded_uniqueness import CoordinatorLog, skew_index
from corda_tpu.core.contracts.structures import StateRef
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.observability.consensus_obs import (
    ATTRIBUTION_COMPONENTS, GrowthWatch, install_raft_collector,
    ledger_raft_fields, pool_attribution, raft_report, sample_timeseries)
from corda_tpu.observability.timeseries import TimeSeriesStore
from corda_tpu.utils.metrics import MetricRegistry


def make_cluster(n=3):
    bus = InMemoryMessagingNetwork()
    names = [f"raft{i}" for i in range(n)]
    maps = [DistributedImmutableMap() for _ in range(n)]
    nodes = [RaftNode(name, list(names), bus.create_node(name),
                      maps[i].apply, seed=i)
             for i, name in enumerate(names)]
    return bus, nodes, maps


def pump(bus, nodes, ticks=10):
    for _ in range(ticks):
        for node in nodes:
            node.tick()
        bus.run_network()


def run_until_leader(bus, nodes, max_ticks=400):
    for _ in range(max_ticks):
        pump(bus, nodes, 1)
        leaders = [n for n in nodes if n.role == LEADER]
        if len(leaders) == 1:
            pump(bus, nodes, 5)
            return leaders[0]
    raise AssertionError("no leader elected")


def commit(leader, bus, nodes, tx, ref):
    fut = leader.submit(("put_all", [[tx], [ref], "obs-test"]))
    for _ in range(200):
        if fut.done():
            break
        pump(bus, nodes, 1)
    return fut.result(timeout=1)


def committed_cluster(n_commits=5):
    bus, nodes, _ = make_cluster(3)
    leader = run_until_leader(bus, nodes)
    for i in range(n_commits):
        ref = StateRef(SecureHash.sha256(b"obs%d" % i), 0)
        out = commit(leader, bus, nodes, f"tx{i}", ref)
        assert out["committed"] is True
    return bus, nodes, leader


def test_attribution_telescopes_to_total():
    """Per committed entry, append_wait + fsync + replicate + apply must
    sum exactly to the retained total — the contiguous-clock construction
    the bench conservation probe (sum vs measured round p50) leans on."""
    _, nodes, leader = committed_cluster()
    samples = leader.attribution_samples()
    assert samples["total"], "leader attributed no commits"
    n = len(samples["total"])
    for comp in ATTRIBUTION_COMPONENTS:
        assert len(samples[comp]) == n, comp
    for i in range(n):
        parts = sum(samples[comp][i] for comp in ATTRIBUTION_COMPONENTS)
        assert parts == pytest.approx(samples["total"][i], abs=1e-9)
        assert samples["total"][i] > 0


def test_forwarded_round_conserves_against_attribution():
    """A submit through a FOLLOWER forwards to the leader. The client's
    submit stamp rides the ClientRequest (forward hop → append_wait) and
    the leader's apply-end stamp rides the ClientResponse back (delivery
    hop cancels out of the round), so the leader's attributed total still
    equals the round the submitting node measures — the conservation
    probe broke 45% on full bench runs when post-election rounds forwarded
    and both hops went unattributed."""
    import time as _t

    bus, nodes, _ = make_cluster(3)
    leader = run_until_leader(bus, nodes)
    follower = next(n for n in nodes if n is not leader)
    before = len(leader.attribution_samples()["total"])

    ref = StateRef(SecureHash.sha256(b"fwd"), 0)
    t0 = _t.perf_counter()
    fut = follower.submit(("put_all", [["tx-fwd"], [ref], "obs-test"]))
    for _ in range(200):
        if fut.done():
            break
        pump(bus, nodes, 1)
    assert fut.result(timeout=1)["committed"] is True

    # the round resolves against the leader's apply-end stamp...
    resolved = fut.raft_resolved_perf
    assert isinstance(resolved, float) and resolved > t0
    samples = leader.attribution_samples()
    assert len(samples["total"]) == before + 1
    total = samples["total"][-1]
    # ...and the attributed total telescopes over the SAME interval: both
    # start at the client's submit stamp (t0 is taken a hair earlier on
    # this side of the submit() call) and end at apply-end
    round_s = resolved - t0
    assert total == pytest.approx(round_s, abs=1e-3)
    # the forward hop is real waiting and must land in append_wait, not
    # vanish: it spans at least the pump iteration that delivered it
    assert samples["append_wait"][-1] > 0


def test_stats_surface_and_election_episode():
    _, nodes, leader = committed_cluster(n_commits=2)
    stats = leader.stats()
    assert stats["impl"] == "python"
    assert stats["role"] == LEADER
    assert stats["elections_total"] >= 1
    episode = stats["elections"][0]
    assert episode["cause"] == "startup"       # term was 0 at candidacy
    assert episode["duration_s"] > 0
    # the startup election can win inside the first tick window
    assert episode["ticks"] >= 0
    assert stats["leader_tenure_s"] > 0
    assert stats["log_entries"] >= 2
    assert set(stats["peer_lag"]) == {n.node_id for n in nodes
                                      if n is not leader}
    attrib = stats["attribution"]
    for comp in ATTRIBUTION_COMPONENTS + ("total",):
        assert attrib[comp]["n"] >= 2
        assert attrib[comp]["p99_ms"] >= attrib[comp]["p50_ms"] >= 0
    # followers never attribute commits (clocks live on the submit node)
    follower = next(n for n in nodes if n is not leader)
    assert follower.stats()["attribution"] == {}


def test_raft_report_shape_and_pooling():
    _, nodes, leader = committed_cluster(n_commits=3)
    report = raft_report({"s0": nodes})
    group = report["groups"]["s0"]
    assert len(group["nodes"]) == 3
    assert group["leader"]["node"] == leader.node_id
    assert group["log_entries"] >= 3
    assert group["elections_total"] >= 1
    assert group["attribution"]["total"]["n"] >= 3
    assert "shards" not in report
    # pooling across replicas = union (followers contribute nothing here)
    pooled = pool_attribution(nodes)
    assert len(pooled["total"]) == len(
        leader.attribution_samples()["total"])


def test_raft_report_defensive():
    class Broken:
        def stats(self):
            raise RuntimeError("dead node")

    class NoSurface:
        pass

    report = raft_report({"g": [Broken(), NoSurface()]})
    group = report["groups"]["g"]
    assert group["nodes"] == [] and group["leader"] is None
    assert group["log_entries"] == 0 and group["elections_total"] == 0
    assert "attribution" not in group
    assert raft_report({}) == {"groups": {}}

    class BadShards:
        def heat_stats(self):
            raise RuntimeError("boom")

    assert raft_report({}, sharded=BadShards())["shards"] is None


def test_raft_collector_families_and_native_parity():
    """The Raft.* labeled families ride a registry snapshot; fields a
    node cannot attribute (the native core's stats carry no attribution
    or peer_lag) are ABSENT from the snapshot — never rendered as 0."""
    _, nodes, leader = committed_cluster(n_commits=2)

    class NativeLike:
        """stats() shaped like NativeRaftNode's: no attribution, no
        peer_lag, no election episode list."""

        def stats(self):
            return {"impl": "native", "node": "n0", "role": LEADER,
                    "term": 3, "leader_id": "n0", "commit_index": 9,
                    "log_entries": 9, "elections_total": 1,
                    "leader_tenure_s": 1.5, "leader_tenure_last_s": 0.0,
                    "pending_requests": 0}

    reg = MetricRegistry()
    install_raft_collector(
        reg, lambda: {"s0": nodes, "s1": [NativeLike()]})
    snap = reg.snapshot()
    for family in ("Raft.LogEntries", "Raft.Elections", "Raft.CommitIndex",
                   "Raft.Term", "Raft.LeaderTenureSeconds"):
        for label in ("s0", "s1"):
            assert f'{family}{{group="{label}"}}' in snap, (family, label)
    entries = snap['Raft.LogEntries{group="s0"}']
    # gauge_fn, not gauge: prometheus_text's gauge branch renders a max
    # sample that collector-emitted entries don't carry
    assert entries["type"] == "gauge_fn" and entries["value"] >= 2
    assert entries["labels"] == {"group": "s0"}
    # python leader attributes: fsync/replicate p99 + replication lag live
    assert 'Raft.FsyncP99Ms{group="s0"}' in snap
    assert 'Raft.ReplicateP99Ms{group="s0"}' in snap
    assert 'Raft.ReplLagMax{group="s0"}' in snap
    # native parity: the same fields are absent for s1, never zero
    assert 'Raft.FsyncP99Ms{group="s1"}' not in snap
    assert 'Raft.ReplicateP99Ms{group="s1"}' not in snap
    assert 'Raft.ReplLagMax{group="s1"}' not in snap


@pytest.mark.skipif(not NATIVE_RAFT_AVAILABLE,
                    reason="libraftcore.so not built")
def test_native_stats_absent_fields_parity():
    from corda_tpu.consensus.raftcore import NativeRaftNode
    bus = InMemoryMessagingNetwork()
    names = ["n0", "n1", "n2"]
    nodes = [NativeRaftNode(name, list(names), bus.create_node(name),
                            lambda e: None, seed=i)
             for i, name in enumerate(names)]
    run_until_leader(bus, nodes)
    for node in nodes:
        stats = node.stats()
        assert stats["impl"] == "native"
        # the core cannot attribute: the fields are absent, never 0
        for missing in ("attribution", "peer_lag", "elections"):
            assert missing not in stats
        for present in ("term", "commit_index", "log_entries",
                        "elections_total", "leader_tenure_s"):
            assert present in stats


def test_growth_watch_doubles(caplog):
    watch = GrowthWatch(logger=logging.getLogger(
        "test.consensus_obs.growth"), floor=100.0)
    caplog.set_level(logging.WARNING, "test.consensus_obs.growth")
    assert watch.observe("g", 50) is False        # under the floor
    assert watch.observe("g", 120) is False       # baseline
    assert watch.observe("g", 200) is False       # < 2× baseline
    assert watch.observe("g", 240) is True        # 2× → warn, re-arm @ 240
    assert watch.observe("g", 400) is False
    assert watch.observe("g", 480) is True        # 2× again (4× baseline)
    assert watch.warnings == 2
    # junk values never count or raise
    assert watch.observe("g", None) is False
    assert watch.observe("g", True) is False
    assert watch.observe_many({"g": 960, "h": 10}) == 1
    assert watch.warnings == 3
    # the doubling rides jlog as a WARNING event, not print/debug noise
    warned = [r for r in caplog.records
              if r.levelno == logging.WARNING
              and "consensus.growth.doubled" in r.getMessage()]
    assert len(warned) == 3


def test_ledger_raft_fields_always_present_with_defaults():
    out = ledger_raft_fields({})
    for comp in ATTRIBUTION_COMPONENTS:
        assert out[f"ledger_raft_{comp}_ms_p50"] == 0.0
        assert out[f"ledger_raft_{comp}_ms_p99"] == 0.0
    assert out["ledger_raft_attrib_samples"] == 0
    assert out["ledger_raft_attrib_sum_ms_p50"] == 0.0
    assert out["ledger_raft_round_ms_p50"] == 0.0
    assert out["ledger_raft_elections_total"] == 0


def test_ledger_raft_fields_from_live_cluster():
    _, nodes, leader = committed_cluster(n_commits=4)
    rounds = [t for t in leader.attribution_samples()["total"]]
    out = ledger_raft_fields({"s0": nodes}, round_samples=rounds)
    assert out["ledger_raft_attrib_samples"] >= 4
    assert out["ledger_raft_attrib_sum_ms_p50"] > 0
    # rounds fed straight from the attribution totals: the two p50s agree
    assert out["ledger_raft_round_ms_p50"] == pytest.approx(
        out["ledger_raft_attrib_sum_ms_p50"], rel=1e-6)
    assert out["ledger_raft_elections_total"] >= 1
    summed = sum(out[f"ledger_raft_{c}_ms_p50"]
                 for c in ATTRIBUTION_COMPONENTS)
    assert summed > 0


def test_sample_timeseries_records_and_flushes():
    _, nodes, leader = committed_cluster(n_commits=2)
    store = TimeSeriesStore(resolutions=((0.5, 16), (5.0, 16)))
    watch = GrowthWatch(floor=1.0)
    values = sample_timeseries(store, {"s0": nodes}, watch=watch, t=100.0)
    assert values['Raft.LogEntries{group="s0"}'] >= 2
    assert 'Raft.Elections{group="s0"}' in values
    sample_timeseries(store, {"s0": nodes}, watch=watch, t=101.0)
    store.flush()
    snap = store.snapshot()
    levels = snap["series"]['Raft.LogEntries{group="s0"}']
    assert sum(1 for lvl in levels if lvl["points"]) >= 2, \
        "flush must seal every resolution"


def test_sample_timeseries_with_resource_registry():
    """Satellite (ISSUE 19): the sampling tick takes the resource
    accounting plane — every registered structure lands as a
    ``Resource.*`` series and rides the SAME growth watchdog (doubling
    warnings for free), while the two historical hazards keep their
    exact jlog series names."""
    from corda_tpu.observability.resprof import ResourceRegistry

    _, nodes, leader = committed_cluster(n_commits=2)
    reg = ResourceRegistry()
    size = {"v": 200.0}
    reg.register("Some.Pool", lambda: size["v"], kind="bounded")
    store = TimeSeriesStore(resolutions=((0.5, 16),))
    watch = GrowthWatch(floor=1.0)
    values = sample_timeseries(store, {"s0": nodes}, watch=watch, t=100.0,
                               resources=reg)
    # byte-compat: the historical hazard series names are unchanged
    assert 'Raft.LogEntries{group="s0"}' in values
    assert values["Resource.Some.Pool"] == 200.0
    size["v"] = 500.0                             # ≥ 2× the armed baseline
    before = watch.warnings
    sample_timeseries(store, {"s0": nodes}, watch=watch, t=101.0,
                      resources=reg)
    assert watch.warnings == before + 1
    store.flush()
    assert "Resource.Some.Pool" in store.snapshot()["series"]

    # a registry whose sample() blows up loses only the Resource.* rows,
    # never the consensus gauges
    class Broken:
        def sample(self, **kw):
            raise RuntimeError("boom")

    values = sample_timeseries(store, {"s0": nodes}, t=102.0,
                               resources=Broken())
    assert 'Raft.LogEntries{group="s0"}' in values


def test_skew_index():
    assert skew_index([]) == 0.0
    assert skew_index([0, 0]) == 0.0
    assert skew_index([5, 5, 5]) == pytest.approx(1.0)
    assert skew_index([12, 0, 0]) == pytest.approx(3.0)
    assert skew_index([3, 1]) == pytest.approx(1.5)


def test_coordinator_log_bytes_counted_and_replayed(tmp_path):
    path = str(tmp_path / "decisions.log")
    ref = StateRef(SecureHash.sha256(b"xs"), 0)
    log = CoordinatorLog(path)
    assert log.bytes_appended == 0
    log.begin("tx1", {0: [ref], 1: [ref]})
    after_begin = log.bytes_appended
    assert after_begin > 0
    log.decide("tx1", "commit")
    log.complete("tx1")
    total = log.bytes_appended
    assert total > after_begin
    # replay reconstructs the byte count from the durable file
    replayed = CoordinatorLog(path)
    assert replayed.bytes_appended == total
    assert len(replayed) == 0                 # tx1 completed
    # an in-memory record still counts logical bytes (the soak gauge
    # must not read 0 just because durability is off)
    mem = CoordinatorLog()
    mem.begin("tx2", {0: [ref]})
    assert mem.bytes_appended > 0
