"""Ledger DSL self-tests, used to re-express Cash rules declaratively
(the TestDSL usage pattern of CashTests.kt)."""
import pytest

from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.core.contracts.structures import Issued, PartyAndReference
from corda_tpu.core.crypto import generate_keypair
from corda_tpu.core.identity import Party
from corda_tpu.finance.cash import Cash, CashState
from corda_tpu.testing import DummyContract, DummyState
from corda_tpu.testing.ledger_dsl import DSLFailure, ledger

NOTARY = Party("O=Notary, L=Zurich, C=CH",
               generate_keypair(entropy=b"\x71" * 32).public)
BANK_KP = generate_keypair(entropy=b"\x72" * 32)
BANK = Party("O=Bank, L=London, C=GB", BANK_KP.public)
ALICE_KP = generate_keypair(entropy=b"\x73" * 32)
TOKEN = Issued(PartyAndReference(BANK, b"\x01"), USD)


def test_cash_lifecycle_via_dsl():
    with ledger(NOTARY) as l:
        with l.transaction() as tx:
            tx.output("bank cash", CashState(Amount(10000, TOKEN),
                                             BANK_KP.public))
            tx.command(Cash.Issue(), BANK_KP.public)
            tx.verifies()
        with l.transaction() as tx:
            tx.input("bank cash")
            tx.output("alice cash", CashState(Amount(10000, TOKEN),
                                              ALICE_KP.public))
            tx.command(Cash.Move(), BANK_KP.public)
            tx.verifies()
        # a non-conserving move is rejected with the clause's message
        with l.transaction() as tx:
            tx.input("alice cash")
            tx.output(None, CashState(Amount(900, TOKEN), BANK_KP.public))
            tx.command(Cash.Move(), ALICE_KP.public)
            tx.fails_with("conserved")
    assert len(l.transactions) == 2


def test_dsl_asserts_on_wrong_expectation():
    with ledger(NOTARY) as l:
        with pytest.raises(DSLFailure, match="but it passed"):
            with l.transaction() as tx:
                tx.output(None, DummyState(1, (ALICE_KP.public,)))
                tx.command(DummyContract.Create(), ALICE_KP.public)
                tx.fails_with("anything")


def test_unasserted_transaction_is_auto_verified():
    with pytest.raises(Exception):  # missing signer caught at block exit
        with ledger(NOTARY) as l:
            with l.transaction() as tx:
                tx.input("nope")  # unknown label
