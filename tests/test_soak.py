"""Soak observatory (observability/soak.py): the tier-1 accelerated
smoke soak asserts the SAME artifact schema as a full endurance run —
phases, per-structure leak verdicts, CPU attribution, drift slopes,
mid-run invariant re-checks — without the multi-minute wall clock, plus
pure-function tests for the drift fit, ring selection and the
guard_soak gate."""
import copy

import pytest

import corda_tpu.finance  # noqa: F401  (contract registration)
from corda_tpu.observability.resprof import ResourceRegistry, set_resources
from corda_tpu.observability.soak import (SoakConfig, run_soak, soak_report,
                                          soak_drift_fields, verdict_rows)
from corda_tpu.observability.timeseries import TimeSeriesStore, set_timeseries
from corda_tpu.tools.benchguard import SOAK_REQUIRED, guard_soak

pytestmark = [pytest.mark.soak, pytest.mark.ledger]


@pytest.fixture(scope="module")
def smoke_report():
    """One accelerated soak for the whole module (~20 s of real load:
    5 s phases, 6 s chaos period, 4 s invariant cadence)."""
    return run_soak(SoakConfig.smoke(seed=7))


# ---------------------------------------------------------------------------
# the tier-1 smoke soak: full schema, green gate
# ---------------------------------------------------------------------------

def test_smoke_soak_carries_full_schema(smoke_report):
    for field in SOAK_REQUIRED:
        assert field in smoke_report, field
    assert smoke_report["mode"] == "soak-smoke"


def test_smoke_soak_passes_guard(smoke_report):
    assert guard_soak(smoke_report, trajectory_paths=[]) == []


def test_smoke_soak_invariants_and_phases(smoke_report):
    r = smoke_report
    assert r["exactly_once_ok"] and r["replicas_agree"]
    assert r["soak_leak_ok"] and r["soak_leaking"] == []
    assert r["soak_invariant_ok"]
    assert r["soak_invariant_recheck_count"] >= 2
    for check in r["soak_invariant_checks"]:
        assert check["ok"] and check["conflicts"] == 0
        assert check["checked"] >= 0
    assert len(r["soak_phases"]) >= 2
    # the phase ledger accounts for the committed work (a sub-0.5 s tail
    # after the last sealed phase may legitimately fall outside it)
    assert 0 < sum(p["committed"] for p in r["soak_phases"]) \
        <= r["committed_tx_count"]
    for p in r["soak_phases"]:
        assert p["duration_s"] > 0
        assert p["committed_tx_per_sec"] >= 0


def test_smoke_soak_chaos_recurred(smoke_report):
    r = smoke_report
    assert r["soak_chaos_cycles"] >= 1
    for w in r["soak_chaos_windows"]:
        assert w["kind"] in ("partition_follower", "leader_kill",
                             "append_drop")
        assert w["end_s"] >= w["start_s"]


def test_smoke_soak_resource_verdicts(smoke_report):
    """Every registered structure carries a leak verdict; the topology's
    core hazards are all registered."""
    verdicts = smoke_report["soak_leak_verdicts"]
    for expected in ("CoordinatorLog.Bytes", "Tracing.SpanRing",
                     "Tracing.SpansDropped", "Vault.States",
                     "Checkpoints.Stored", "Shard.ReservedRefs",
                     "Process.RSSBytes", "Timeseries.Buckets"):
        assert expected in verdicts, expected
    assert any(n.startswith("RaftLog.") for n in verdicts)
    for name, v in verdicts.items():
        assert v["verdict"] in ("bounded", "growing"), name
        assert "slope_per_s" in v and "points" in v
    assert set(smoke_report["soak_resources"]) == set(verdicts)
    # churn accounting (satellite): the windowed drop/eviction rates are
    # numbers, not cumulative-only counters
    assert smoke_report["soak_spans_dropped_rate_per_s"] >= 0.0
    assert smoke_report["soak_timeline_evictions_rate_per_s"] >= 0.0


def test_smoke_soak_cpu_attribution(smoke_report):
    r = smoke_report
    assert r["soak_cpu_samples"] >= 1
    shares = r["soak_cpu_shares_pct"]
    assert r["soak_cpu_share_sum_pct"] == pytest.approx(100.0, abs=0.5)
    assert sum(shares.values()) == pytest.approx(100.0, abs=0.5)
    assert r["soak_cpu_top_commit_path"] in shares
    assert 0.0 < r["soak_cpu_busy_frac"] <= 1.0


def test_smoke_soak_drift_fields_recorded(smoke_report):
    """Smoke records the drift slopes (the fit runs) but the gate does
    not enforce them — a 20 s window is too noisy for slope floors."""
    r = smoke_report
    for f in ("soak_throughput_slope_pct_per_min",
              "soak_p99_slope_pct_per_min"):
        assert isinstance(r[f], (int, float))
    assert r["soak_throughput_gate_pct_per_min"] == -3.0
    assert r["soak_p99_gate_pct_per_min"] == 6.0


# ---------------------------------------------------------------------------
# guard_soak on doctored artifacts
# ---------------------------------------------------------------------------

def _full(report):
    """A doctored copy that reads as a FULL (non-smoke) run."""
    r = copy.deepcopy(report)
    r["mode"] = "soak"
    r.pop("smoke", None)
    return r


def test_guard_flags_missing_and_mistyped_fields(smoke_report):
    r = copy.deepcopy(smoke_report)
    del r["soak_phases"]
    assert any("missing required soak field 'soak_phases'" in p
               for p in guard_soak(r, trajectory_paths=[]))
    r = copy.deepcopy(smoke_report)
    r["soak_leak_verdicts"] = "nope"
    assert any("soak_leak_verdicts" in p
               for p in guard_soak(r, trajectory_paths=[]))


def test_guard_flags_leaking_structure(smoke_report):
    r = copy.deepcopy(smoke_report)
    r["soak_leaking"] = ["Staging.Buffers"]
    r["soak_leak_ok"] = False
    problems = guard_soak(r, trajectory_paths=[])
    assert any("Staging.Buffers" in p for p in problems)


def test_guard_flags_failed_invariant_recheck(smoke_report):
    r = copy.deepcopy(smoke_report)
    r["soak_invariant_ok"] = False
    assert any("invariant re-check failed" in p
               for p in guard_soak(r, trajectory_paths=[]))
    r = copy.deepcopy(smoke_report)
    r["soak_invariant_checks"] = []
    r["soak_invariant_recheck_count"] = 0
    assert any("no mid-run invariant re-check" in p
               for p in guard_soak(r, trajectory_paths=[]))


def test_guard_flags_malformed_verdicts_and_no_chaos(smoke_report):
    r = copy.deepcopy(smoke_report)
    r["soak_leak_verdicts"] = {"X": {"verdict": "maybe"}}
    assert any("well-formed leak verdict" in p
               for p in guard_soak(r, trajectory_paths=[]))
    r = copy.deepcopy(smoke_report)
    r["soak_chaos_cycles"] = 0
    assert any("no recurring chaos cycle" in p
               for p in guard_soak(r, trajectory_paths=[]))


def test_guard_full_run_enforces_cpu_band_and_drift(smoke_report):
    # the same numbers pass as smoke but a FULL run enforces the CPU
    # sanity band and the self-declared drift gates
    r = _full(smoke_report)
    r["soak_cpu_share_sum_pct"] = 55.0
    assert any("90–110%" in p for p in guard_soak(r, trajectory_paths=[]))
    r = _full(smoke_report)
    r["soak_drift_ok"] = False
    assert any("drift gate breached" in p
               for p in guard_soak(r, trajectory_paths=[]))
    r = _full(smoke_report)
    r["soak_cpu_top_commit_path"] = ""
    assert any("top commit-path" in p
               for p in guard_soak(r, trajectory_paths=[]))


# ---------------------------------------------------------------------------
# drift fit + ring selection (pure functions)
# ---------------------------------------------------------------------------

def _phases(rates, p99s, phase_s=60.0):
    return [{"t_s": i * phase_s, "committed_tx_per_sec": r,
             "e2e_ms_p99": p} for i, (r, p) in enumerate(zip(rates, p99s))]


def test_drift_fields_stable_run_passes():
    out = soak_drift_fields(_phases([6.0] * 8, [40.0] * 8), -3.0, 6.0)
    assert out["soak_drift_ok"] is True
    assert out["soak_throughput_slope_pct_per_min"] == pytest.approx(0.0)
    assert out["soak_p99_slope_pct_per_min"] == pytest.approx(0.0)


def test_drift_fields_degrading_throughput_breaches_gate():
    # committed rate sagging ~5%/min against a -3%/min floor
    rates = [6.0 - 0.3 * i for i in range(8)]
    out = soak_drift_fields(_phases(rates, [40.0] * 8), -3.0, 6.0)
    assert out["soak_throughput_slope_pct_per_min"] < -3.0
    assert out["soak_drift_ok"] is False


def test_drift_fields_rising_p99_breaches_gate():
    p99s = [40.0 * (1.0 + 0.15 * i) for i in range(8)]
    out = soak_drift_fields(_phases([6.0] * 8, p99s), -3.0, 6.0)
    assert out["soak_p99_slope_pct_per_min"] > 6.0
    assert out["soak_drift_ok"] is False


def test_drift_fields_too_few_phases_is_zero_drift():
    out = soak_drift_fields(_phases([6.0, 1.0], [40.0, 900.0]), -3.0, 6.0)
    assert out["soak_throughput_slope_pct_per_min"] == 0.0
    assert out["soak_drift_ok"] is True
    # zero-latency phases (nothing committed) drop out of the p99 fit
    out = soak_drift_fields(_phases([6.0] * 5, [0.0] * 5), -3.0, 6.0)
    assert out["soak_p99_slope_pct_per_min"] == 0.0


def test_verdict_rows_prefers_coarsest_populated_ring():
    fine = [[float(t), 1, 0, 0, float(t), 0] for t in range(100)]
    coarse = [[60.0 * t, 10, 0, 0, float(t), 0] for t in range(8)]
    rings = [{"bucket_s": 0.5, "points": fine},
             {"bucket_s": 60.0, "points": coarse}]
    assert verdict_rows(rings) == coarse       # coarsest with ≥5 points
    # a smoke run never fills the 60 s ring: fall back to the fine one
    rings = [{"bucket_s": 0.5, "points": fine},
             {"bucket_s": 60.0, "points": coarse[:2]}]
    assert verdict_rows(rings) == fine
    assert verdict_rows([]) == []
    assert verdict_rows([{"bucket_s": 0.5}, "junk", None]) == []


# ---------------------------------------------------------------------------
# the live /debug/soak payload
# ---------------------------------------------------------------------------

def test_soak_report_composes_live_registry_and_retained_series():
    reg = ResourceRegistry()
    size = {"v": 5.0}
    reg.register("Live.Thing", lambda: size["v"], kind="bounded")
    store = TimeSeriesStore(resolutions=((1.0, 16),))
    prev_reg, prev_store = set_resources(reg), set_timeseries(store)
    try:
        for t in range(10):
            reg.sample(store=store, t=float(t))
        store.flush()
        out = soak_report()
        assert list(out["resources"]) == ["Live.Thing"]
        r = out["resources"]["Live.Thing"]
        assert r["size"] == 5.0 and r["kind"] == "bounded"
        assert r["verdict"] == "bounded"
        assert out["leaking"] == []
        assert out["cpu"] is None              # no profiler running
    finally:
        set_resources(prev_reg)
        set_timeseries(prev_store)


def test_soak_report_empty_node_is_well_formed():
    prev_reg = set_resources(ResourceRegistry())
    try:
        out = soak_report()
        assert out == {"resources": {}, "leaking": [], "cpu": None}
    finally:
        set_resources(prev_reg)
