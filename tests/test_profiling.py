"""Kernel flight recorder (observability/profiling.py) unit coverage:
compile-cache accounting, batch-occupancy math, prep/device overlap
intervals, exemplar attach/expose, and registry publication.
"""
import numpy as np
import pytest

from corda_tpu.observability import (KernelProfiler, OverlapTracker,
                                     disable_tracing, enable_tracing)
from corda_tpu.utils.metrics import Histogram, MetricRegistry


# -- OverlapTracker ----------------------------------------------------------

def test_overlap_hand_fed_intervals():
    t = OverlapTracker()
    t.add_prep(0.0, 10.0)
    t.add_device(5.0, 15.0)
    snap = t.snapshot()
    assert snap["prep_busy_s"] == pytest.approx(10.0)
    assert snap["device_busy_s"] == pytest.approx(10.0)
    assert snap["overlap_s"] == pytest.approx(5.0)
    assert snap["overlap_pct"] == pytest.approx(50.0)


def test_overlap_merges_overlapping_intervals():
    t = OverlapTracker()
    # two prep intervals that merge into [0, 4]; device [1, 3] is fully
    # covered — overlap must not double-count the merged region
    t.add_prep(0.0, 2.5)
    t.add_prep(2.0, 4.0)
    t.add_device(1.0, 3.0)
    snap = t.snapshot()
    assert snap["prep_busy_s"] == pytest.approx(4.0)
    assert snap["overlap_s"] == pytest.approx(2.0)
    assert snap["overlap_pct"] == pytest.approx(100.0)


def test_overlap_no_device_time_is_zero_pct():
    t = OverlapTracker()
    t.add_prep(0.0, 1.0)
    assert t.overlap_pct() == 0.0
    t.add_prep(1.0, 0.5)        # inverted interval is dropped
    assert t.snapshot()["prep_busy_s"] == pytest.approx(1.0)


# -- compile accounting ------------------------------------------------------

def test_jit_compile_then_cache_hit():
    jax = pytest.importorskip("jax")
    prof = KernelProfiler()
    fn = jax.jit(lambda x: x + 1)
    x = np.zeros(4, np.int32)
    prof.call("k", fn, x)                       # first shape: compiles
    prof.call("k", fn, np.ones(4, np.int32))    # same shape: cache hit
    prof.call("k", fn, np.zeros(8, np.int32))   # new shape: compiles again
    totals = prof.compile_totals()
    assert totals["compiles"] == 2
    assert totals["compile_cache_hits"] == 1
    assert totals["compile_s_total"] > 0
    st = prof.snapshot()["kernels"]["k"]
    assert st["dispatches"] == 3
    # compile wall time was booked to the compile bucket, not dispatch
    assert prof.compile_hist.count == 2
    assert prof.dispatch_hist.count == 1


def test_signature_fallback_for_plain_callables():
    prof = KernelProfiler()
    calls = []

    def fn(a):
        calls.append(a.shape)
        return a

    prof.call("plain", fn, np.zeros((4, 2)))
    prof.call("plain", fn, np.ones((4, 2)))     # same shape/dtype: hit
    prof.call("plain", fn, np.zeros((8, 2)))    # novel shape: "compile"
    totals = prof.compile_totals()
    assert totals["compiles"] == 2
    assert totals["compile_cache_hits"] == 1
    assert len(calls) == 3


def test_compile_emits_span_when_tracing():
    tracer = enable_tracing()
    try:
        prof = KernelProfiler()
        prof.call("spanned", lambda a: a, np.zeros(3), capacity=8)
        spans = [s for s in tracer.spans() if s["name"] == "kernel.compile"]
        assert len(spans) == 1
        assert spans[0]["tags"]["kernel"] == "spanned"
        assert spans[0]["tags"]["batch_capacity"] == 8
    finally:
        disable_tracing()


# -- occupancy ---------------------------------------------------------------

def test_occupancy_math_matches_hand_computed_padding():
    prof = KernelProfiler()
    prof.record_occupancy("ed25519", live=3, capacity=8)
    assert prof.occupancy_pct_per_scheme() == {"ed25519": 37.5}
    prof.record_occupancy("ed25519", live=5, capacity=8)
    # aggregate: (3 + 5) / (8 + 8) = 50%
    assert prof.occupancy_pct_per_scheme() == {"ed25519": 50.0}
    occ = prof.snapshot()["occupancy"]["ed25519"]
    assert occ["live_total"] == 8 and occ["capacity_total"] == 16
    assert occ["last_batch_pct"] == 62.5


def test_occupancy_recorded_through_call():
    prof = KernelProfiler()
    prof.call("k1", lambda a: a, np.zeros(3), live=3, capacity=4,
              scheme="secp256r1")
    assert prof.occupancy_pct_per_scheme() == {"secp256r1": 75.0}


# -- device-wait attribution -------------------------------------------------

def test_pending_handle_attribution():
    prof = KernelProfiler()
    out = prof.call("kern", lambda a: a + 1, np.zeros(3))
    assert prof.pending_name(out) == "kern"
    assert prof.pending_name(out) == "unknown"   # popped on first lookup
    prof.device_wait("kern", 0.25)
    st = prof.snapshot()["kernels"]["kern"]
    assert st["device_waits"] == 1
    assert st["device_wait_s"] == pytest.approx(0.25)


# -- exemplars ---------------------------------------------------------------

def test_exemplar_attach_and_expose():
    h = Histogram()
    h.update(0.01, trace_id="aaaa000011112222")
    h.update(0.01)                               # untraced: keeps exemplar
    h.update(0.01, trace_id="bbbb000011112222")  # last-wins per bucket
    h.update(3.0, trace_id="cccc000011112222")
    ex = h.exemplars()
    assert set(ex) == {"0.01", "3.16228"}
    assert ex["0.01"]["trace_id"] == "bbbb000011112222"
    assert ex["3.16228"]["trace_id"] == "cccc000011112222"
    assert ex["0.01"]["value"] == pytest.approx(0.01)
    fields = h.snapshot_fields()
    assert fields["exemplars"] == ex             # the /metrics JSON surface
    # untraced-only histograms carry no exemplars key at all
    assert "exemplars" not in Histogram().snapshot_fields()


def test_exemplar_resolves_in_prometheus_text():
    from corda_tpu.tools.webserver import prometheus_text
    reg = MetricRegistry()
    reg.histogram("verifier_dispatch_seconds").update(
        0.02, trace_id="feedface00000001")
    text = prometheus_text(reg.snapshot())
    assert '# {trace_id="feedface00000001"} 0.02' in text


# -- registry publication ----------------------------------------------------

def test_publish_mirrors_into_registry():
    prof = KernelProfiler()
    prof.record_occupancy("ed25519", live=6, capacity=8)
    prof.overlap.add_prep(0.0, 2.0)
    prof.overlap.add_device(1.0, 3.0)
    reg = MetricRegistry()
    prof.publish(reg)
    snap = reg.snapshot()
    assert snap["Profiler.ed25519.OccupancyPct"]["value"] == 75.0
    assert snap["Profiler.PrepOverlapPct"]["value"] == pytest.approx(50.0)
    assert snap["Profiler.CompileSecondsTotal"]["value"] == 0
    # the registry shares the profiler's histogram OBJECT, not a copy
    assert reg.get_metric("kernel_dispatch_seconds") is prof.dispatch_hist
