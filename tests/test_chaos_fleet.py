"""Fleet chaos tests: routing, stealing, and membership churn under faults.

The invariant inherited from test_chaos_oop and extended to the fleet
machinery: EVERY submitted verification future resolves EXACTLY ONCE —
a worker killed mid-batch, a worker joining into a bulk backlog, or a
steal racing the overdue-redelivery scan must never lose a future or
double-resolve one (Verification.Success marks only when the response
finds a live handle, so the success count IS the exactly-once witness).
"""
import time

import pytest

from corda_tpu.network.inmemory import InMemoryMessagingNetwork
from corda_tpu.testing.faults import FaultRule, inject
from corda_tpu.verifier.fleet import make_sig_checks
from corda_tpu.verifier.out_of_process import (
    OutOfProcessTransactionVerifierService, VerifierWorker)

pytestmark = pytest.mark.chaos

SEEDS = [7, 101, 9001]

GROUPS = 12
GROUP_SIZE = 4


@pytest.fixture
def bus():
    return InMemoryMessagingNetwork()


def _host_worker(bus, name, max_inflight_groups=1):
    """A fleet worker on the host route (no kernels — chaos tests exercise
    protocol, not device math) with a finite in-flight window so a deep
    backlog stays parked and stealable."""
    from corda_tpu.verifier.batcher import SignatureBatcher
    return VerifierWorker(
        bus.create_node(name), "node",
        batcher=SignatureBatcher(use_device=False, max_latency_s=0.002),
        use_device=False, capacity=1,
        max_inflight_groups=max_inflight_groups)


def _pump_until(bus, futures, workers=(), timeout=60.0):
    """Pump the bus (and the workers' load reports, so routing and steal
    decisions keep flowing) until every future resolves."""
    deadline = time.monotonic() + timeout
    last_report = 0.0
    while not all(f.done() for f in futures):
        bus.run_network()
        now = time.monotonic()
        if now - last_report > 0.01:
            last_report = now
            for w in workers:
                if w._alive:
                    w.send_load_report()
        time.sleep(0.002)
        assert time.monotonic() < deadline, \
            "fleet verifications did not complete"


def _assert_exactly_once(svc, futures):
    for f in futures:
        assert f.result(timeout=1) is None
    snap = svc.metrics.snapshot()
    assert snap["Verification.Success"]["count"] == len(futures)
    assert snap.get("Verification.Failure", {}).get("count", 0) == 0


@pytest.mark.parametrize("seed", SEEDS)
def test_worker_killed_mid_batch_fleet(bus, seed):
    """A worker dies mid-batch with signature groups split between its
    batcher window and its stealable backlog: every reply it would have
    sent is dropped, it is killed, and the redelivery scan must move its
    WHOLE dealt share — admitted and parked alike — to the survivor."""
    svc = OutOfProcessTransactionVerifierService(bus.create_node("node"))
    svc.queue.redelivery_timeout_s = 0.1
    w1 = w2 = None
    try:
        w1 = _host_worker(bus, "w1")
        w2 = _host_worker(bus, "w2")
        bus.run_network()
        assert svc.queue.worker_count == 2

        checks = make_sig_checks(GROUP_SIZE, seed=seed)
        with inject(FaultRule("oop.reply", "drop", detail="w1->*"),
                    seed=seed):
            futures = [svc.verify_signatures(checks) for _ in range(GROUPS)]
            bus.run_network()
            w1.stop(announce=False)   # crash: no Goodbye, replies black-holed

            # keep pumping while the timeout elapses: the SURVIVOR's
            # trickling replies refresh its activity (the dual-condition
            # scan must flag only the silent dead worker, never a busy one)
            end = time.monotonic() + 0.25
            while time.monotonic() < end:
                bus.run_network()
                time.sleep(0.01)
            svc.queue.requeue_overdue()
            _pump_until(bus, futures, workers=[w2])

        _assert_exactly_once(svc, futures)
        assert svc.queue.worker_count == 1
        assert w2.processed_sig_count >= GROUPS * GROUP_SIZE // 2
    finally:
        for w in (w1, w2):
            if w is not None and w._alive:
                w.stop(announce=False)
        svc.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_worker_join_steals_from_bulk_backlog(bus, seed):
    """A worker joining while the only other worker holds a deep bulk
    backlog must receive work via a steal — including when the first
    StealRequest is LOST (the in-flight steal marker expires and the next
    idle report retries). Every future still resolves exactly once."""
    svc = OutOfProcessTransactionVerifierService(bus.create_node("node"))
    try:
        w1 = _host_worker(bus, "w1")
        bus.run_network()

        checks = make_sig_checks(GROUP_SIZE, seed=seed)
        futures = [svc.verify_signatures(checks) for _ in range(GROUPS)]
        bus.run_network()          # all dealt to the only worker
        w1.send_load_report()
        bus.run_network()          # node sees the deep backlog

        w2 = _host_worker(bus, "w2")
        bus.run_network()
        svc.queue.STEAL_TIMEOUT_S = 0.01   # lost-steal retry, test-speed
        with inject(FaultRule("oop.deliver", "drop", detail="->w1",
                              count=1), seed=seed) as inj:
            w2.send_load_report()  # idle report → steal → injected drop
            bus.run_network()
            assert inj.fired("oop.deliver") == 1
        time.sleep(0.02)           # expire the lost steal's marker
        _pump_until(bus, futures, workers=[w1, w2])

        _assert_exactly_once(svc, futures)
        assert svc.metrics.meter("Fleet.Steals").count >= 1
        # the joiner got work one way or the other: stolen-and-redealt, or
        # routed to it once the router saw the load imbalance
        assert w2.processed_sig_count > 0
        w1.stop(announce=False)
        w2.stop(announce=False)
    finally:
        svc.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_steal_racing_requeue_resolves_exactly_once(bus, seed):
    """The nastiest interleaving: a WorkReturned is in flight when the
    overdue scan declares the victim dead and requeues its whole share.
    The returned requests are no longer charged to the victim, so the
    node must IGNORE the stale return (no double-deal), and duplicated
    victim replies must not double-resolve any future."""
    svc = OutOfProcessTransactionVerifierService(bus.create_node("node"))
    try:
        w1 = _host_worker(bus, "w1")
        bus.run_network()
        checks = make_sig_checks(GROUP_SIZE, seed=seed)
        futures = [svc.verify_signatures(checks) for _ in range(GROUPS)]
        bus.run_network()
        w1.send_load_report()
        bus.run_network()

        w2 = _host_worker(bus, "w2")
        bus.run_network()
        # drain any queued w1 replies so the next node pump is the report
        bus.run_network()
        with inject(FaultRule("net.send", "duplicate", detail="w1->node"),
                    seed=seed):
            w2.send_load_report()
            # deliver ONLY the report to the node: the StealRequest goes
            # out to w1 but its WorkReturned must NOT be pumped yet
            while True:
                t = bus.pump_receive("node")
                assert t is not None, "load report never reached the node"
                if t.sender == "w2":
                    break
            assert bus.pump_receive("w1") is not None   # w1 sends the return
            # ... and NOW the victim goes overdue before the return lands
            svc.queue.redelivery_timeout_s = 0.05
            time.sleep(0.12)
            svc.queue.requeue_overdue()
            assert svc.queue.worker_count == 1   # w1 presumed dead
            _pump_until(bus, futures, workers=[w2])

        _assert_exactly_once(svc, futures)
        # the stale WorkReturned was ignored: nothing it carried was
        # re-dealt through the steal path after the requeue took them
        assert svc.metrics.meter("Fleet.Stolen").count == 0
        w1.stop(announce=False)
        w2.stop(announce=False)
    finally:
        svc.shutdown()


@pytest.mark.parametrize("seed", SEEDS)
def test_stolen_request_yields_one_stitched_trace(bus, seed):
    """Observability under churn: a request stolen mid-flight must still
    yield ONE stitched trace (node submit span + worker-side spans under
    the same trace id) and its lifecycle timeline must carry exactly one
    terminal resolution event — the steal hop adds events and spans, never
    duplicates or orphans them."""
    from corda_tpu.observability import Tracer, get_tracer, set_tracer
    prev_tracer = get_tracer()
    tracer = Tracer()
    set_tracer(tracer)
    svc = OutOfProcessTransactionVerifierService(bus.create_node("node"))
    try:
        w1 = _host_worker(bus, "w1")
        bus.run_network()
        checks = make_sig_checks(GROUP_SIZE, seed=seed)
        futures = [svc.verify_signatures(checks) for _ in range(GROUPS)]
        bus.run_network()          # all dealt to the only worker
        w1.send_load_report()
        bus.run_network()          # node sees the deep backlog

        w2 = _host_worker(bus, "w2")
        bus.run_network()
        w2.send_load_report()      # idle report → steal from w1's backlog
        bus.run_network()
        _pump_until(bus, futures, workers=[w1, w2])
        for f in futures:
            assert f.result(timeout=1) is None
        assert svc.metrics.meter("Fleet.Stolen").count >= 1
        # flush the victim's worker.stolen span outbox onto a load report
        w1.send_load_report()
        bus.run_network()

        timelines = svc.request_log.snapshot()
        assert len(timelines) == len(futures)
        stolen_vids = [int(vid) for vid, tl in timelines.items()
                       if any(e["event"] == "stolen" for e in tl)]
        assert stolen_vids, "no request recorded a steal hop"
        for vid in (int(v) for v in timelines):
            assert svc.request_log.terminal_count(vid) == 1, vid
        # no leaked live submit spans either
        assert svc._spans == {}
        for vid in stolen_vids:
            tl = timelines[str(vid)]
            stolen_ev = next(e for e in tl if e["event"] == "stolen")
            assert stolen_ev["victim"] == "w1"
            trace_id = next(e["trace_id"] for e in tl if "trace_id" in e)
            spans = tracer.trace(trace_id)
            names = [s["name"] for s in spans]
            assert names.count("verifier.oop_submit") == 1, names
            assert any(n.startswith("worker.") for n in names), names
            assert "worker.stolen" in names, names
            # every span of the stolen request is stitched into ONE trace
            assert {s["trace_id"] for s in spans} == {trace_id}
    finally:
        try:
            w1.stop(announce=False)
            w2.stop(announce=False)
        except Exception:
            pass
        svc.shutdown()
        set_tracer(prev_tracer)
