"""Mutual TLS on the TCP messaging plane.

Reference analog: ArtemisTcpTransport's TLS mutual-auth transport +
dev-certificate autogeneration (MQSecurityTest's transport-level slice:
peers without CA-signed certificates cannot join the plane).
"""
import time

import pytest

from corda_tpu.network.messaging import TopicSession
from corda_tpu.network.tcp import TcpMessagingService
from corda_tpu.network.tls import TlsConfig, ensure_dev_ca


def _wait_for(pred, timeout=8.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def _endpoint(tmp_path, name, resolve, ca="ca", node_dir=None):
    tls = TlsConfig.dev(str(tmp_path / (node_dir or name)), name,
                        str(tmp_path / ca))
    return TcpMessagingService(name, "127.0.0.1", 0, resolve, tls=tls)


def test_mutual_tls_roundtrip(tmp_path):
    directory = {}
    resolve = directory.get
    a = _endpoint(tmp_path, "alice", resolve)
    b = _endpoint(tmp_path, "bob", resolve)
    directory["alice"] = ("127.0.0.1", a.port)
    directory["bob"] = ("127.0.0.1", b.port)
    try:
        got_a, got_b = [], []
        a.add_message_handler(TopicSession("t", 1), lambda m: got_a.append(m))
        b.add_message_handler(TopicSession("t", 1), lambda m: got_b.append(m))
        a.send(TopicSession("t", 1), b"from-alice", "bob")
        b.send(TopicSession("t", 1), b"from-bob", "alice")
        assert _wait_for(lambda: got_a and got_b)
        assert got_b[0].data == b"from-alice" and got_b[0].sender == "alice"
        assert got_a[0].data == b"from-bob"
    finally:
        a.stop()
        b.stop()


def test_untrusted_peer_rejected(tmp_path):
    """A peer whose certificate chains to a DIFFERENT CA must not be able to
    deliver messages (the transport's whole point)."""
    directory = {}
    resolve = directory.get
    server = _endpoint(tmp_path, "server", resolve, ca="ca-real")
    rogue = _endpoint(tmp_path, "rogue", resolve, ca="ca-rogue")
    directory["server"] = ("127.0.0.1", server.port)
    try:
        got = []
        server.add_message_handler(TopicSession("t", 1), got.append)
        rogue.send(TopicSession("t", 1), b"evil", "server")
        assert not _wait_for(lambda: got, timeout=2.5)
    finally:
        server.stop()
        rogue.stop()


def test_plaintext_client_rejected(tmp_path):
    directory = {}
    resolve = directory.get
    server = _endpoint(tmp_path, "server", resolve)
    plain = TcpMessagingService("plain", "127.0.0.1", 0, resolve)
    directory["server"] = ("127.0.0.1", server.port)
    try:
        got = []
        server.add_message_handler(TopicSession("t", 1), got.append)
        plain.send(TopicSession("t", 1), b"hello?", "server")
        assert not _wait_for(lambda: got, timeout=2.5)
    finally:
        server.stop()
        plain.stop()


def test_cn_less_cert_refused(tmp_path, monkeypatch):
    """ADVICE r2: a verified certificate WITHOUT a CN (e.g. SAN-only) must
    not silently downgrade to the frame's self-declared sender — the
    connection is refused instead."""
    from corda_tpu.network import tls as tls_mod
    directory = {}
    resolve = directory.get
    server = _endpoint(tmp_path, "server", resolve)
    client = _endpoint(tmp_path, "client", resolve)
    directory["server"] = ("127.0.0.1", server.port)
    monkeypatch.setattr(tls_mod, "peer_common_name", lambda ssl_obj: None)
    try:
        got = []
        server.add_message_handler(TopicSession("t", 1), got.append)
        client.send(TopicSession("t", 1), b"anonymous", "server")
        assert not _wait_for(lambda: got, timeout=2.5)
    finally:
        server.stop()
        client.stop()


def test_dev_ca_created_once(tmp_path):
    c1 = ensure_dev_ca(str(tmp_path / "shared"))
    with open(c1[0], "rb") as f:
        first = f.read()
    c2 = ensure_dev_ca(str(tmp_path / "shared"))
    with open(c2[0], "rb") as f:
        assert f.read() == first
