"""Native build tooling: the .so and the Python-side ABI gate move together.

Rebuilds libscalarmath.so from source (into a tmpdir — the committed .so
is never touched) when a C++ compiler is present and asserts sm_version()
matches scalarprep.SM_VERSION, so a version bump that forgets one side of
the gate fails in tier-1 instead of silently falling back to the Python
prep on every deployment.  Skips LOUDLY (with the rebuild recipe) when no
compiler is available.
"""
import ctypes
import os
import shutil
import subprocess

import pytest

from corda_tpu.ops import scalarprep as sp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "native", "scalarmath.cpp")

RECIPE = ("rebuild with: make -C native libscalarmath.so "
          f"(needs sm_version() == {sp.SM_VERSION}, "
          "the gate in corda_tpu/ops/scalarprep.py)")


def _version_of(path: str) -> int:
    lib = ctypes.CDLL(path)
    lib.sm_version.restype = ctypes.c_int
    return int(lib.sm_version())


def test_rebuilt_so_version_matches_python_gate(tmp_path):
    cxx = (shutil.which(os.environ.get("CXX", "g++"))
           or shutil.which("c++") or shutil.which("clang++"))
    if cxx is None:
        pytest.skip(f"no C++ compiler on PATH — cannot rebuild; {RECIPE}")
    out = tmp_path / "libscalarmath.so"
    # -O0: this is an ABI check, not a perf build — keeps the test seconds
    proc = subprocess.run(
        [cxx, "-O0", "-fPIC", "-shared", "-std=c++17", SRC, "-o", str(out)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert _version_of(str(out)) == sp.SM_VERSION, RECIPE


def test_committed_so_version_matches_python_gate():
    built = [p for p in sp._CANDIDATES if os.path.exists(p)]
    if not built:
        pytest.skip(f"libscalarmath.so not built in this checkout; {RECIPE}")
    for path in built:
        assert _version_of(path) == sp.SM_VERSION, (path, RECIPE)
    # and the loader actually accepted it (no silent Python fallback)
    assert sp.available(), RECIPE
