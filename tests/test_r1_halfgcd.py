"""Half-gcd split path (secp256r1): decomposition contract, native vs
Python differentials, and fallback parity against the host oracle.

The Antipa split rewrites u2 into v1/v2 (both < 2^128); the device then
checks [t_lo]G + [t_hi]G' + [|v1|](±Q) == [v2]R with a 124-doubling
ladder.  These tests pin:

- the decomposition contract (u2·v2 ≡ ±v1 (mod n), STRICT 2^128 bounds —
  a leg exactly 2^128 is impossible: |t_i| ≤ n/r_{i-1} with r_{i-1} ≥
  2^128 at the stopping step);
- bit-identical native (sm_r1_halfgcd / sm_r1_prep_hg) vs pure-Python
  outputs, 10k random scalars + adversarial edges;
- verdict parity with ecmath.ecdsa_verify on mixed valid/invalid/
  malformed/fallback batches, BOTH with and without the native library
  (the acceptance criterion's with/without matrix).
"""
import hashlib
import random

import numpy as np
import pytest

from corda_tpu.core.crypto import ecmath
from corda_tpu.ops import scalarprep as sp
from corda_tpu.ops import weierstrass as wc

CURVE = ecmath.SECP256R1
N = CURVE.n

needs_native = pytest.mark.skipif(not sp.available(),
                                  reason="libscalarmath.so not built")


def _check_contract(u2: int, dec) -> None:
    assert dec is not None, u2
    neg1, v1, v2 = dec
    assert 0 <= v1 < (1 << 128), (u2, v1)       # strict: never == 2^128
    assert 0 < v2 < (1 << 128), (u2, v2)
    want = (N - v1) % N if neg1 else v1
    assert u2 * v2 % N == want, u2


def _edge_scalars():
    return [1, 2, 3, N - 1, N - 2, (1 << 128) - 1, 1 << 128,
            (1 << 128) + 1, N >> 128, 3 << 127, N - (1 << 128), N // 3]


def test_halfgcd_python_contract():
    rng = random.Random(501)
    for u2 in _edge_scalars() + [rng.randrange(1, N) for _ in range(2000)]:
        _check_contract(u2, sp.r1_halfgcd_py(u2))
    # u2 < 2^128 short-circuits to (False, u2, 1)
    assert sp.r1_halfgcd_py(12345) == (False, 12345, 1)
    # degenerate inputs are refused, not mangled
    for bad in (0, N, N + 5):
        assert sp.r1_halfgcd_py(bad) is None


@needs_native
def test_halfgcd_native_matches_python_10k():
    rng = random.Random(502)
    cases = _edge_scalars() + [rng.randrange(1, N) for _ in range(10_000)]
    for u2 in cases:
        native = sp.r1_halfgcd(u2)
        python = sp.r1_halfgcd_py(u2)
        assert native == python, u2
    for bad in (0, N, N + 5):
        assert sp.r1_halfgcd(bad) is None
        assert sp.r1_halfgcd_py(bad) is None


@needs_native
def test_r1p_mulfast_matches_python():
    rng = random.Random(503)
    p = CURVE.p
    ops = [(0, 0), (1, p - 1), (p - 1, p - 1), (1 << 128, 1 << 128)]
    ops += [(rng.randrange(p), rng.randrange(p)) for _ in range(2000)]
    for a, b in ops:
        assert sp.r1p_mulfast(a, b) == a * b % p, (a, b)


def _mixed_items():
    """Valid + tampered + malformed + split-degenerate items.  13 items →
    one 16-bucket, so every e2e test below shares one kernel compile."""
    rng = np.random.default_rng(504)
    items = []
    for _ in range(6):
        priv = int.from_bytes(rng.bytes(32), "little") % (N - 1) + 1
        pub = CURVE.mul(priv, CURVE.g)
        msg = rng.bytes(36)
        r, s = ecmath.ecdsa_sign(CURVE, priv, msg)
        items.append((pub, msg, r, s))
    pub0, msg0, r0, s0 = items[0]
    items += [
        (pub0, msg0 + b"!", r0, s0),                    # tampered message
        (pub0, msg0, (r0 + 1) % N or 1, s0),            # tampered r
        (pub0, msg0, 0, s0),                            # r = 0 (DER clamp)
        (pub0, msg0, N + 5, s0),                        # r >= n
        (pub0, msg0, r0, N - s0),                       # high-s twin
        ((pub0[0], (pub0[1] + 1) % CURVE.p), msg0, r0, s0),  # off-curve
        (None, msg0, r0, s0),                           # missing key
    ]
    return items


def _fallback_items():
    """Items that PASS the structural precheck but degenerate the split
    (r + n < p ⇒ the r+n x-candidate exists ⇒ hg_ok = 0): tiny r values —
    unreachable by honest signing (~2^-64), craftable by an adversary."""
    rng = np.random.default_rng(505)
    priv = int.from_bytes(rng.bytes(32), "little") % (N - 1) + 1
    pub = CURVE.mul(priv, CURVE.g)
    msg = rng.bytes(30)
    _, s = ecmath.ecdsa_sign(CURVE, priv, msg)
    return [(pub, msg, r, s) for r in (1, 2, 5, 1000, 1 << 64)]


def _oracle(items):
    return np.asarray([ecmath.ecdsa_verify(CURVE, pub, msg, r, s)
                       for pub, msg, r, s in items])


@needs_native
def test_r1_prep_hg_native_matches_python():
    items = _mixed_items() + _fallback_items()[:3]
    native = wc._prepare_r1_split_native_words(*wc._items_to_words(items), 16)
    python = wc._prepare_r1_split_python(CURVE, items, 16)
    names = ["g_idx", "q_digits", "Q", "xd_limbs", "lo_x", "lo_y", "lo_ok",
             "hi_x", "hi_y", "hi_ok", "precheck", "forced"]
    assert len(native) == len(python) == len(names)
    for name, a, b in zip(names, native, python):
        if isinstance(a, tuple):
            for i, (ac, bc) in enumerate(zip(a, b)):
                np.testing.assert_array_equal(
                    np.asarray(ac), np.asarray(bc), err_msg=f"{name}[{i}]")
        else:
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=name)


def test_fallback_items_marked_and_forced():
    """hg_ok=0 items must be masked OUT of precheck_eff and carry the host
    oracle's verdict in `forced` — through whichever prep is loaded."""
    items = _fallback_items() + _mixed_items()[:3]
    *_, precheck_eff, forced = wc.prepare_batch_r1_split(CURVE, items, 16)
    n_fb = len(_fallback_items())
    assert not precheck_eff[:n_fb].any()      # every tiny-r item fell back
    np.testing.assert_array_equal(forced[:n_fb], _oracle(items)[:n_fb])
    assert not forced[n_fb:].any()            # non-fallback rows untouched


# The e2e tests below share ONE 16-bucket kernel compile (cold ~minutes on
# CPU, then persistent-cached in .jax_cache — same deal as the r1 kernels
# already in the default tier, see tests/test_ops_curves.py).

@needs_native
def test_split_verdicts_match_oracle_native():
    items = _mixed_items()
    got = wc.verify_batch(CURVE, items, mode="halfgcd")
    np.testing.assert_array_equal(got, _oracle(items))


def test_split_verdicts_match_oracle_python(monkeypatch):
    monkeypatch.setattr(sp, "_LIB", None)
    assert not sp.available()
    items = _mixed_items()
    got = wc.verify_batch(CURVE, items, mode="halfgcd")
    np.testing.assert_array_equal(got, _oracle(items))


def test_fallback_parity_end_to_end():
    """rn_ok=False (hg_ok=0) items return verdicts identical to the host
    oracle through the FULL verify path — fallbacks mixed into a batch of
    valid and invalid members, plus the async words seam."""
    items = _mixed_items()[:6] + _fallback_items()[:3]
    want = _oracle(items)
    got = wc.verify_batch(CURVE, items, mode="halfgcd")
    np.testing.assert_array_equal(got, want)
    if sp.available():
        pend = wc.verify_batch_async_words(CURVE, *wc._items_to_words(items))
        assert len(pend) == 4                 # (dev, precheck, n, forced)
        np.testing.assert_array_equal(wc.finish_batch(pend), want)


def test_host_verify_scalars_matches_oracle():
    for pub, msg, r, s in _mixed_items():
        e_raw = int.from_bytes(hashlib.sha256(msg).digest(), "big")
        assert (wc._r1_host_verify_scalars(CURVE, pub, e_raw, r, s)
                == ecmath.ecdsa_verify(CURVE, pub, msg, r, s)), (r, s)


def test_split_python_prep_handles_empty_and_all_invalid():
    (g_idx, q_digits, Q, xd, *_tabs, precheck,
     forced) = wc._prepare_r1_split_python(
        CURVE, [(None, b"m", 5, 7), (None, b"n", 0, 0)], 16)
    assert not precheck.any() and not forced.any()
    assert not np.asarray(g_idx).any() and not np.asarray(q_digits).any()
