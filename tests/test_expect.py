"""Expect DSL tests (Expect.kt analog) over real vault/state-machine events."""
import pytest

from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.finance import CashIssueFlow, CashPaymentFlow
from corda_tpu.node.vault import VaultUpdate
from corda_tpu.testing import MockNetwork
from corda_tpu.testing.expect import (ExpectationFailed, expect, parallel,
                                      repeat, run_expectations, sequence)


def test_sequence_and_parallel_matching():
    events = ["start", 1, 2, "mid", 3, "end"]
    run_expectations(events, sequence(
        expect(str, lambda s: s == "start"),
        parallel(expect(int, lambda i: i == 2), expect(int, lambda i: i == 1)),
        expect(str, lambda s: s == "end")), strict=False)
    with pytest.raises(ExpectationFailed):
        run_expectations(events, sequence(
            expect(str, lambda s: s == "end"),
            expect(str, lambda s: s == "start")), strict=False)  # wrong order
    run_expectations([7, 7, 7], repeat(3, expect(int, lambda i: i == 7)))
    with pytest.raises(ExpectationFailed):
        run_expectations([7, 7], repeat(3, expect(int, lambda i: i == 7)))
    # strict mode flags unexpected events; backtracking finds the valid
    # assignment when an unconstrained leaf could shadow a constrained one
    with pytest.raises(ExpectationFailed, match="unexpected|satisfies"):
        run_expectations(["extra", 7], sequence(expect(int)))
    run_expectations([1, 2], parallel(expect(int),
                                      expect(int, lambda i: i == 1)))
    # vacuous expectations pass on empty streams
    run_expectations([], repeat(0, expect(int)))
    run_expectations([], sequence())


def test_expect_over_vault_updates():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank = network.create_node("O=Bank, L=London, C=GB")
    alice = network.create_node("O=Alice, L=Madrid, C=ES")
    network.start_nodes()

    events = []
    bank.services.vault.add_update_observer(events.append)
    fsm = bank.start_flow(CashIssueFlow(Amount(10000, USD), b"\x01",
                                        bank.party, notary.party))
    network.run_network()
    fsm.result_future.result(timeout=5)
    fsm = bank.start_flow(CashPaymentFlow(Amount(4000, USD), alice.party))
    network.run_network()
    fsm.result_future.result(timeout=5)

    run_expectations(events, sequence(
        expect(VaultUpdate, lambda u: len(u.produced) == 1 and not u.consumed),
        expect(VaultUpdate,
               lambda u: len(u.consumed) == 1 and len(u.produced) == 1)))
