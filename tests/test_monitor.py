"""NodeMonitorModel + webserver static serving tests (client/jfx +
staticServeDirs analogs)."""
import json
import urllib.request

import pytest

from corda_tpu.client.monitor import NodeMonitorModel
from corda_tpu.core.contracts.amount import Amount, USD
from corda_tpu.finance import CashIssueFlow, CashPaymentFlow
from corda_tpu.node.rpc import CordaRPCOps
from corda_tpu.testing import MockNetwork
from corda_tpu.tools.webserver import NodeWebServer


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank = network.create_node("O=Bank, L=London, C=GB")
    network.start_nodes()
    return network, notary, bank


def test_monitor_model_tracks_feeds(net):
    network, notary, bank = net
    ops = CordaRPCOps(bank.services, bank.smm)
    model = NodeMonitorModel().register(ops)
    assert model.tx_count.value == 0

    counts = []
    model.tx_count.observe(counts.append)
    fsm = bank.start_flow(CashIssueFlow(Amount(5000, USD), b"\x01",
                                        bank.party, notary.party))
    network.run_network()
    fsm.result_future.result(timeout=5)

    assert model.tx_count.value == 1 and counts[-1] == 1
    assert len(model.transactions) == 1
    assert model.vault_updates.snapshot()[0].produced
    kinds = [k for k, _ in model.state_machine_events.snapshot()]
    assert "add" in kinds and "remove" in kinds
    assert model.in_flight_flows.value == 0

    # late registration folds existing state in: the snapshot becomes an
    # initial vault update and transactions seed exactly once (deduped)
    late = NodeMonitorModel().register(ops)
    assert late.tx_count.value == 1
    assert late.vault_updates.snapshot()[0].produced
    assert late.in_flight_flows.value == 0


def test_webserver_static_dirs(tmp_path, net):
    network, notary, bank = net
    app = tmp_path / "webapp"
    app.mkdir()
    (app / "index.html").write_text("<h1>corda-tpu</h1>")
    (app / "app.js").write_text("console.log('hi')")
    ops = CordaRPCOps(bank.services, bank.smm)
    server = NodeWebServer(ops, static_dirs={"demo": str(app)}).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/web/demo/", timeout=10) as r:
            assert b"corda-tpu" in r.read()
            assert r.headers["Content-Type"].startswith("text/html")
        with urllib.request.urlopen(f"{base}/web/demo/app.js", timeout=10) as r:
            assert b"console" in r.read()
        # query strings (cache busting) and percent escapes resolve
        with urllib.request.urlopen(f"{base}/web/demo/app.js?v=123",
                                    timeout=10) as r:
            assert b"console" in r.read()
        with urllib.request.urlopen(f"{base}/web/demo/app%2Ejs",
                                    timeout=10) as r:
            assert b"console" in r.read()
        # a symlink escaping the app dir is refused (realpath containment)
        import os
        os.symlink("/etc", str(app / "esc"))
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/web/demo/esc/hostname", timeout=10)
        # traversal out of the app dir is refused
        for bad in ("/web/demo/../secret", "/web/demo/%2e%2e/x",
                    "/web/nope/index.html"):
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}{bad}", timeout=10)
    finally:
        server.stop()
