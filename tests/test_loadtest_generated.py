"""GeneratedLedger + loadtest harness tests.

Reference analogs: GeneratedLedger's use in VerifierTests (bulk valid
ledgers), SelfIssueTest/CrossCashTest invariants, Disruption injection.
"""
import pytest

from corda_tpu.testing import MockNetwork
from corda_tpu.testing.generated_ledger import (make_generated_ledger,
                                                signature_triples)
from corda_tpu.tools.loadtest import (DropMessages, KillRestartNode,
                                      cross_cash_test, run_load_test,
                                      self_issue_test)


def test_generated_ledger_is_valid_and_verifiable():
    ledger = make_generated_ledger(60, seed=7)
    assert len(ledger.transactions) == 60
    # every generated transaction's signatures check out and platform rules
    # hold when resolved against the generated chain
    from corda_tpu.testing.services import MockServices
    services = MockServices()
    for stx in ledger.transactions:
        stx.check_signatures_are_valid()
        services.record_transactions(stx)
    for stx in ledger.transactions:
        stx.to_ledger_transaction(services).verify()
    # signature triples feed the batcher: all verify via the host oracle
    triples = signature_triples(ledger)
    assert len(triples) >= 60
    from corda_tpu.core.crypto.signatures import Crypto
    for key, sig, content in triples[:20]:
        assert Crypto.is_valid(key, sig, content)


def test_generated_ledger_batch_verifies_on_device():
    """The parity harness: the generated ledger's signatures go through the
    scheme-bucketed device batcher and all verify (VerifierTests bulk case)."""
    from corda_tpu.verifier.batcher import SignatureBatcher
    ledger = make_generated_ledger(30, seed=11)
    batcher = SignatureBatcher(max_latency_s=0.01)
    futures = [batcher.submit(k, s, c)
               for k, s, c in signature_triples(ledger)]
    assert all(f.result(timeout=240) for f in futures)
    batcher.close()


@pytest.fixture
def cluster():
    network = MockNetwork()
    notary = network.create_notary_node()
    nodes = [network.create_node(f"O=Load {i}, L=City, C=GB")
             for i in range(3)]
    network.start_nodes()
    return {"network": network, "notary": notary, "party_nodes": nodes,
            "nodes": network.nodes}


def test_self_issue_load(cluster):
    run_load_test(self_issue_test(), cluster, iterations=20, seed=3)
    observed = self_issue_test().gather(cluster)
    assert observed == cluster["model_issued"]
    for fsm in cluster["flows"]:
        assert fsm.result_future.result(timeout=1)


def test_cross_cash_conservation_under_disruption(cluster):
    test = cross_cash_test()
    disruptions = [
        (5, 8, DropMessages(0.2, seed=1)),
        (12, 12, KillRestartNode(lambda ctx: ctx["party_nodes"][1])),
    ]
    run_load_test(test, cluster, iterations=18, seed=9,
                  disruptions=disruptions)
    # drain: dropped messages mean some flows need redelivery-free retries;
    # pump until quiescent then check conservation over COMPLETED payments
    cluster["network"].run_network()
    observed = test.gather(cluster)
    # conservation: no cash created or destroyed beyond what was issued
    assert observed <= cluster.get("total_issued", 0)
    done = sum(1 for f in cluster["flows"] if f.result_future.done())
    assert done > 0