"""IRS demo lifecycle: scheduler-driven fixings through the oracle with
tear-offs (VERDICT r2 #4).

Reference analogs: samples/irs-demo IRSDemoTest / NodeInterestRatesTest —
deal entry, then ≥2 scheduler-fired fixings, each applying an oracle-signed
Fix to the swap; the oracle signs only a filtered tear-off.
"""
import datetime

import pytest

from corda_tpu.flows.api import flow_name
from corda_tpu.node.scheduler import NodeSchedulerService
from corda_tpu.samples.irs_demo import (AgreeSwapFlow, FixedLeg, FixingFlow,
                                        FloatingLeg, InterestRateSwapState,
                                        install_irs_demo)
from corda_tpu.samples.rates_oracle import FixOf, RatesOracle
from corda_tpu.testing import MockNetwork

T0 = datetime.datetime(2026, 3, 1, tzinfo=datetime.timezone.utc)


@pytest.fixture
def net():
    network = MockNetwork()
    notary = network.create_notary_node()
    bank_a = network.create_node("O=Bank A, L=London, C=GB")     # fixed
    bank_b = network.create_node("O=Bank B, L=Paris, C=FR")      # floating
    oracle_node = network.create_node("O=Rates Oracle, L=London, C=GB")
    network.start_nodes()
    oracle = RatesOracle(oracle_node.services, {
        FixOf("LIBOR", "2026-03-10", "3M"): 525,
        FixOf("LIBOR", "2026-06-10", "3M"): 550,
        FixOf("LIBOR", "2026-09-10", "3M"): 575,
    })
    oracle.install(oracle_node.smm)
    install_irs_demo(bank_a)
    install_irs_demo(bank_b)
    return network, notary, bank_a, bank_b, oracle_node


def make_swap(bank_a, bank_b, oracle_node, dates=("2026-03-10", "2026-06-10")):
    return InterestRateSwapState(
        fixed_leg=FixedLeg(bank_a.party, rate_bp=450),
        floating_leg=FloatingLeg(bank_b.party, "LIBOR", "3M"),
        notional=10_000_000,
        oracle=oracle_node.party,
        fixing_dates=tuple(dates))


def _agree(network, notary, bank_a, bank_b, oracle_node, **kw):
    swap = make_swap(bank_a, bank_b, oracle_node, **kw)
    fsm = bank_a.start_flow(AgreeSwapFlow(swap, notary.party))
    network.run_network()
    return fsm.result_future.result(timeout=1)


def test_agreement_records_swap_on_both_nodes(net):
    network, notary, bank_a, bank_b, oracle_node = net
    stx = _agree(network, notary, bank_a, bank_b, oracle_node)
    for node in (bank_a, bank_b):
        states = node.services.vault.unconsumed_states(InterestRateSwapState)
        assert len(states) == 1
        assert states[0].state.data.notional == 10_000_000


def test_two_fixings_through_the_scheduler(net):
    """The done-criterion: ≥2 fixings run end-to-end through
    NodeSchedulerService on MockNetwork, each consuming the swap and
    producing it with one more oracle-signed fix applied."""
    network, notary, bank_a, bank_b, oracle_node = net
    # schedulers on BOTH parties, driven by a virtual clock
    clocks = {}
    schedulers = []
    for node in (bank_a, bank_b):
        sched = NodeSchedulerService(node.services, clock=lambda: clocks["t"])
        sched.start()
        schedulers.append(sched)
    clocks["t"] = T0

    _agree(network, notary, bank_a, bank_b, oracle_node)
    assert all(s.next_deadline_micros() is not None for s in schedulers)

    # advance past the first fixing date: both schedulers fire; only the
    # floating payer (bank_b) builds the fixing transaction
    clocks["t"] = T0 + datetime.timedelta(days=15)
    started = [fsm for s in schedulers for fsm in s.wake()]
    assert started
    network.run_network()
    for fsm in started:
        fsm.result_future.result(timeout=1)

    for node in (bank_a, bank_b):
        states = node.services.vault.unconsumed_states(InterestRateSwapState)
        assert len(states) == 1
        swap = states[0].state.data
        assert len(swap.applied_fixes) == 1
        assert swap.applied_fixes[0].value_bp == 525

    # the new output state reschedules the SECOND fixing automatically
    assert all(s.next_deadline_micros() is not None for s in schedulers)
    clocks["t"] = T0 + datetime.timedelta(days=120)
    started = [fsm for s in schedulers for fsm in s.wake()]
    network.run_network()
    for fsm in started:
        fsm.result_future.result(timeout=1)

    for node in (bank_a, bank_b):
        swap = node.services.vault.unconsumed_states(
            InterestRateSwapState)[0].state.data
        assert [f.value_bp for f in swap.applied_fixes] == [525, 550]
        assert swap.next_fix_of() is None       # calendar exhausted
    assert all(s.next_deadline_micros() is None for s in schedulers)


def test_fixing_transaction_carries_oracle_signature(net):
    network, notary, bank_a, bank_b, oracle_node = net
    _agree(network, notary, bank_a, bank_b, oracle_node)
    ref = bank_b.services.vault.unconsumed_states(
        InterestRateSwapState)[0].ref
    fsm = bank_b.start_flow(FixingFlow(ref))
    network.run_network()
    stx = fsm.result_future.result(timeout=1)
    assert oracle_node.party.owning_key in {s.by for s in stx.sigs}
    assert bank_a.party.owning_key in {s.by for s in stx.sigs}
    # full host verification passes (oracle sig covers the Merkle root)
    stx.verify(bank_b.services)


def test_wrong_fix_rejected_by_contract(net):
    """A fixing that skips ahead in the calendar fails contract verify."""
    from corda_tpu.core.contracts.exceptions import (
        TransactionVerificationException)
    from corda_tpu.core.contracts.structures import Command, StateAndRef
    from corda_tpu.core.transactions.builder import TransactionBuilder
    from corda_tpu.samples.irs_demo import FixCommand
    from corda_tpu.samples.rates_oracle import Fix

    network, notary, bank_a, bank_b, oracle_node = net
    _agree(network, notary, bank_a, bank_b, oracle_node)
    sar = bank_b.services.vault.unconsumed_states(InterestRateSwapState)[0]
    swap = sar.state.data
    wrong = Fix(FixOf("LIBOR", "2026-06-10", "3M"), 550)  # skips 03-10
    builder = TransactionBuilder(notary=notary.party)
    builder.add_input_state(StateAndRef(sar.state, sar.ref))
    builder.add_output_state(swap.with_fix(wrong), notary.party)
    builder.add_command(Command(wrong, (oracle_node.party.owning_key,)))
    builder.add_command(Command(FixCommand(), tuple(swap.participants)))
    wtx = builder.to_wire_transaction()
    ltx = wtx.to_ledger_transaction(bank_b.services)
    with pytest.raises(TransactionVerificationException,
                       match="next expected fixing"):
        ltx.verify()
