"""Chaos on the ledger commit path: the exactly-once invariant holds.

Three seeded storms drive the smoke workload through all three fault
windows (follower partition, leader kill, probabilistic append drops).
Whatever the faults do to latency and availability, every ACCEPTED
transaction must consume its inputs exactly once on every replica, the
replicas must agree at quiescence, and the damage must show up in the
SLO accounting instead of disappearing.
"""
import pytest

from corda_tpu.observability.ledger_harness import (LedgerScenarioConfig,
                                                    run_ledger_scenario)


@pytest.mark.chaos
@pytest.mark.ledger
@pytest.mark.parametrize("seed", [7, 101, 9001])
def test_chaos_run_commits_exactly_once_and_burns_slo(seed):
    cfg = LedgerScenarioConfig(seed=seed, chaos=True,
                               chaos_partition_s=1.0,
                               provider_timeout_s=3.0,
                               max_duration_s=90.0)
    report = run_ledger_scenario(cfg)
    # the invariant: no double spends, no lost accepted commits, replicas
    # converge — regardless of what the windows did
    assert report["exactly_once_ok"], report
    assert report["replicas_agree"], report
    assert report["ops_committed"] > 0
    # all three windows armed and were annotated with what fired
    kinds = [w["kind"] for w in report["chaos_windows"]]
    assert kinds == ["partition_follower", "leader_kill", "append_drop"]
    for w in report["chaos_windows"]:
        assert w["end_s"] > w["start_s"]
        assert w["faults_fired"] >= 0
    # SLO burn reflects the damage: any failed op, or any commit slower
    # than the 1s latency objective, must have eaten budget
    slow = report["e2e_ms_p99"] > 1000.0
    if report["ops_failed"] > 0 or slow:
        assert report["slo_error_budget_pct"] < 100.0, report["slo"]
    # and the tracing stayed stitched through the storm
    assert report["stitched_traces"] >= 1
