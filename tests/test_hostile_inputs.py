"""Hostile/malformed wire inputs: duplicate schema field names, evolved
defaults that must freeze, and adversarial partial-Merkle trees (deep
chains, junk nodes) that must mark only themselves False in a batch."""
import dataclasses
import hashlib
from types import SimpleNamespace

import msgpack
import pytest

from corda_tpu.core.crypto.merkle import _IncludedLeaf, _Leaf, _Node
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.core.serialization import SerializationError, codec
from corda_tpu.core.transactions.batch_merkle import (MAX_PROOF_DEPTH,
                                                      verify_filtered_batch)

NAME = "hostile.DemoState"


def _schema_blob(name, field_names, fields):
    """Hand-forge a schema'd-object wire message (what a hostile peer can
    put on the wire directly — the codec itself never emits duplicates)."""
    return codec._MAGIC + codec._packb(msgpack.ExtType(
        codec._EXT_OBJ_SCHEMA, codec._packb([name, list(field_names),
                                             list(fields)])))


def _unregister(cls):
    codec._REGISTRY.pop(NAME, None)
    codec._BY_CLASS.pop(cls, None)
    codec._SCHEMA_NAMES.pop(NAME, None)


@pytest.fixture(autouse=True)
def _clean_registry():
    yield
    for cls in (DemoV1, DemoV2):
        _unregister(cls)
    entry = codec._CARPENTED.pop(NAME, None)
    if entry is not None:
        for cls, cname in list(codec._CARPENTED_BY_CLASS.items()):
            if cname == NAME:
                del codec._CARPENTED_BY_CLASS[cls]


@dataclasses.dataclass(frozen=True)
class DemoV1:
    amount: int


@dataclasses.dataclass(frozen=True)
class DemoV2:
    """v2 adds a collection field with a list-producing default_factory."""

    amount: int
    tags: tuple = dataclasses.field(default_factory=lambda: [1, 2])


# ---------------------------------------------------------------------------
# codec: duplicate field names
# ---------------------------------------------------------------------------

def test_duplicate_field_names_rejected_for_carpented_type():
    blob = _schema_blob(NAME, ["amount", "amount"], [1, 2])
    with pytest.raises(SerializationError, match="duplicate field names"):
        codec.deserialize(blob)
    # the hostile name must NOT have been carpented as a side effect
    assert NAME not in codec._CARPENTED


def test_duplicate_field_names_rejected_for_registered_type():
    codec.register_type(NAME, DemoV1, carry_schema=True)
    blob = _schema_blob(NAME, ["amount", "amount"], [1, 2])
    with pytest.raises(SerializationError, match="duplicate field names"):
        codec.deserialize(blob)


def test_unique_field_names_still_roundtrip():
    codec.register_type(NAME, DemoV1, carry_schema=True)
    assert codec.deserialize(codec.serialize(DemoV1(5))) == DemoV1(5)


# ---------------------------------------------------------------------------
# codec: evolved defaults freeze like carried values
# ---------------------------------------------------------------------------

def test_evolved_default_factory_value_is_frozen():
    codec.register_type(NAME, DemoV1, carry_schema=True)
    blob = codec.serialize(DemoV1(7))
    _unregister(DemoV1)
    codec.register_type(NAME, DemoV2, carry_schema=True)
    got = codec.deserialize(blob)
    # the factory returns a LIST; the evolved instance must carry the
    # frozen (tuple) form so it hashes/compares like a native decode
    assert got.tags == (1, 2)
    assert isinstance(got.tags, tuple)
    hash(got)   # frozen dataclass with tuple fields is hashable


# ---------------------------------------------------------------------------
# batch_merkle: hostile trees mark only themselves False
# ---------------------------------------------------------------------------

def _good_ftx():
    la, lb = SecureHash.sha256(b"a"), SecureHash.sha256(b"b")
    root = _Node(_IncludedLeaf(la), _IncludedLeaf(lb))
    root_hash = SecureHash(hashlib.sha256(la.bytes + lb.bytes).digest())
    return SimpleNamespace(
        partial_merkle_tree=SimpleNamespace(root=root),
        filtered_leaves=SimpleNamespace(
            available_component_hashes=[la, lb]),
        root_hash=root_hash)


def _ftx_with_root(root):
    h = SecureHash.sha256(b"x")
    return SimpleNamespace(
        partial_merkle_tree=SimpleNamespace(root=root),
        filtered_leaves=SimpleNamespace(available_component_hashes=[h]),
        root_hash=h)


def test_deep_chain_marks_only_itself_false():
    chain = _IncludedLeaf(SecureHash.sha256(b"x"))
    filler = _Leaf(SecureHash.sha256(b"pad"))
    for _ in range(MAX_PROOF_DEPTH + 200):
        chain = _Node(chain, filler)
    # iterative walk: no RecursionError, and only the hostile member fails
    got = verify_filtered_batch(
        [_good_ftx(), _ftx_with_root(chain), _good_ftx()])
    assert got == [True, False, True]


def test_junk_node_and_broken_ftx_mark_only_themselves_false():
    got = verify_filtered_batch([
        _good_ftx(),
        _ftx_with_root("not a tree node"),
        SimpleNamespace(),              # no partial_merkle_tree at all
        _good_ftx()])
    assert got == [True, False, False, True]


def test_depth_within_cap_still_verifies():
    # a legitimate (small) unbalanced shape well inside the cap
    la, lb = SecureHash.sha256(b"a"), SecureHash.sha256(b"b")
    inner = _Node(_IncludedLeaf(la), _IncludedLeaf(lb))
    inner_h = hashlib.sha256(la.bytes + lb.bytes).digest()
    lc = SecureHash.sha256(b"c")
    root = _Node(inner, _Leaf(lc))
    root_hash = SecureHash(hashlib.sha256(inner_h + lc.bytes).digest())
    ftx = SimpleNamespace(
        partial_merkle_tree=SimpleNamespace(root=root),
        filtered_leaves=SimpleNamespace(
            available_component_hashes=[la, lb]),
        root_hash=root_hash)
    assert verify_filtered_batch([ftx]) == [True]
