"""Confidential identities: TransactionKeyFlow exchange tests.

Reference analog: TransactionKeyFlow + IdentityService registerAnonymous
(anonymous keys swap with ownership attestations; forged attestations are
refused)."""
import pytest

from corda_tpu.core.identity import AnonymousParty
from corda_tpu.flows import FlowException, TransactionKeyFlow
from corda_tpu.testing import MockNetwork


@pytest.fixture
def net():
    network = MockNetwork()
    a = network.create_node("O=Alice, L=London, C=GB")
    b = network.create_node("O=Bob, L=Paris, C=FR")
    network.start_nodes()
    return network, a, b


def test_transaction_key_exchange(net):
    network, a, b = net
    fsm = a.start_flow(TransactionKeyFlow(b.party))
    network.run_network()
    identities = fsm.result_future.result(timeout=1)

    anon_a = identities[a.party]
    anon_b = identities[b.party]
    assert isinstance(anon_a, AnonymousParty) and isinstance(anon_b,
                                                             AnonymousParty)
    # fresh one-time keys, not the well-known ones
    assert anon_a.owning_key != a.party.owning_key
    assert anon_b.owning_key != b.party.owning_key
    # each side can resolve the PEER's anonymous identity to the well-known
    assert (a.services.identity_service.well_known_party_from_anonymous(anon_b)
            == b.party)
    assert (b.services.identity_service.well_known_party_from_anonymous(anon_a)
            == a.party)
    # and can sign with its own fresh key (it is in the KMS)
    assert a.services.key_management.sign(b"x", anon_a.owning_key)


def test_forged_attestation_refused(net):
    network, a, b = net
    # Alice claims an anonymous key with a signature from the WRONG identity
    fresh = a.services.key_management.fresh_key()
    anon = AnonymousParty(fresh.public)
    content = a.services.identity_service.ownership_content(
        fresh.public, b.party.name)
    forged = a.services.sign(content).bytes   # signed by Alice, claims Bob
    with pytest.raises(Exception):
        b.services.identity_service.verify_and_register_anonymous(
            anon, b.party, forged)
    assert (b.services.identity_service.well_known_party_from_anonymous(anon)
            is None)
