"""Ledger DSL — the declarative contract-unit-test language.

Reference parity: test-utils {LedgerDSLInterpreter, TransactionDSLInterpreter,
TestDSL}.kt — `ledger { transaction { input(...) output(...) command(...)
verifies() / fails_with("...") } }`, with labelled outputs resolvable as
later inputs and all built transactions resolved against the same in-memory
ledger. Pythonic form:

    with ledger(notary=NOTARY) as l:
        with l.transaction() as tx:
            tx.output("cash", CashState(...))
            tx.command(Cash.Issue(), issuer_key)
            tx.verifies()
        with l.transaction() as tx:
            tx.input("cash")
            tx.output("moved", CashState(...))
            tx.command(Cash.Move(), owner_key)
            tx.fails_with("owner")
"""
from __future__ import annotations

from ..core.contracts.exceptions import TransactionVerificationException
from ..core.contracts.structures import (Command, StateAndRef, StateRef,
                                         TransactionState)
from ..core.identity import Party
from ..core.transactions.wire import WireTransaction
from .services import MockServices


class DSLFailure(AssertionError):
    pass


class TransactionDSL:
    def __init__(self, ledger: "LedgerDSL"):
        self.ledger = ledger
        self._inputs: list[StateRef] = []
        self._outputs: list[tuple[str | None, TransactionState]] = []
        self._commands: list[Command] = []
        self._time_window = None
        self._attachments: list = []
        self._checked = False

    # -- components ----------------------------------------------------------
    def input(self, label_or_sar) -> "TransactionDSL":
        if isinstance(label_or_sar, str):
            sar = self.ledger.labelled(label_or_sar)
        else:
            sar = label_or_sar
        self._inputs.append(sar.ref)
        return self

    def output(self, label, state, notary: Party | None = None,
               encumbrance: int | None = None) -> "TransactionDSL":
        if not isinstance(state, TransactionState):
            state = TransactionState(state, notary or self.ledger.notary,
                                     encumbrance)
        self._outputs.append((label, state))
        return self

    def command(self, data, *keys) -> "TransactionDSL":
        self._commands.append(Command(data, tuple(keys)))
        return self

    def time_window(self, tw) -> "TransactionDSL":
        self._time_window = tw
        return self

    def attachment(self, att_id) -> "TransactionDSL":
        self._attachments.append(att_id)
        return self

    # -- building / checking -------------------------------------------------
    def _build(self) -> WireTransaction:
        signers = sorted({k for c in self._commands for k in c.signers}
                         | ({self.ledger.notary.owning_key}
                            if self._inputs else set()))
        return WireTransaction(
            inputs=tuple(self._inputs),
            attachments=tuple(self._attachments),
            outputs=tuple(s for _, s in self._outputs),
            commands=tuple(self._commands),
            notary=self.ledger.notary,
            must_sign=tuple(signers),
            time_window=self._time_window)

    def verifies(self) -> WireTransaction:
        """Assert the transaction passes platform + contract verification and
        record it on the ledger (its outputs become spendable)."""
        wtx = self._build()
        ltx = wtx.to_ledger_transaction(self.ledger.services)
        ltx.verify()
        self._checked = True
        self.ledger._record(wtx, [lbl for lbl, _ in self._outputs])
        return wtx

    def fails_with(self, message_fragment: str) -> None:
        """Assert verification fails with the fragment in the error
        (TestDSL `fails with`). Only VERIFICATION failures count — a crash of
        any other type (AttributeError in a broken clause, say) propagates,
        so a broken contract can't masquerade as a correctly-rejecting one."""
        wtx = self._build()
        self._checked = True
        try:
            wtx.to_ledger_transaction(self.ledger.services).verify()
        except TransactionVerificationException as e:
            if message_fragment.lower() not in str(e).lower():
                raise DSLFailure(
                    f"Expected failure containing {message_fragment!r}, got: "
                    f"{type(e).__name__}: {e}") from e
            return
        raise DSLFailure(
            f"Expected verification to fail with {message_fragment!r}, "
            f"but it passed")

    def fails(self) -> None:
        wtx = self._build()
        self._checked = True
        try:
            wtx.to_ledger_transaction(self.ledger.services).verify()
        except TransactionVerificationException:
            return
        raise DSLFailure("Expected verification to fail, but it passed")

    # -- context manager -----------------------------------------------------
    def __enter__(self) -> "TransactionDSL":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None and not self._checked:
            self.verifies()  # un-asserted transactions must at least verify
        return False


class LedgerDSL:
    def __init__(self, notary: Party, services: MockServices | None = None):
        self.notary = notary
        self.services = services if services is not None else MockServices()
        self._labels: dict[str, StateAndRef] = {}
        self.transactions: list[WireTransaction] = []

    def transaction(self) -> TransactionDSL:
        return TransactionDSL(self)

    def labelled(self, label: str) -> StateAndRef:
        if label not in self._labels:
            raise KeyError(f"No output labelled {label!r} on this ledger")
        return self._labels[label]

    def _record(self, wtx: WireTransaction, labels) -> None:
        self.transactions.append(wtx)
        for i, out in enumerate(wtx.outputs):
            ref = StateRef(wtx.id, i)
            self.services.add_state(ref, out)
            if labels[i] is not None:
                self._labels[labels[i]] = StateAndRef(out, ref)

    def __enter__(self) -> "LedgerDSL":
        return self

    def __exit__(self, *exc) -> bool:
        return False


def ledger(notary: Party, services: MockServices | None = None) -> LedgerDSL:
    return LedgerDSL(notary, services)
