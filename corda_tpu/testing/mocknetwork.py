"""MockNetwork: N in-process nodes over the deterministic in-memory bus.

Reference parity: MockNetwork/MockNode (test-utils/.../node/MockNode.kt:41-66)
— nodes share one InMemoryMessagingNetwork; `run_network()` pumps messages
manually so protocol interleavings are reproducible single-threaded.
"""
from __future__ import annotations

from ..core.crypto.keys import KeyPair, generate_keypair
from ..core.identity import Party
from ..network.inmemory import InMemoryMessagingNetwork
from ..node.checkpoints import CheckpointStorage
from ..node.services import NodeInfo, ServiceHub, ServiceInfo
from ..node.statemachine import StateMachineManager


class TestClock:
    """Deterministic flow-timer clock (reference TestClock semantics): flows
    sleeping or receiving-with-timeout wake only when a test advances it
    (MockNetwork.advance_clock)."""

    def __init__(self, start: float = 1_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += seconds


class MockNode:
    def __init__(self, mock_net: "MockNetwork", name: str, key_pair: KeyPair,
                 advertised_services: tuple[ServiceInfo, ...] = (),
                 checkpoint_storage: CheckpointStorage | None = None,
                 messaging=None, storage=None):
        self.mock_net = mock_net
        self.key_pair = key_pair
        self.messaging = messaging if messaging is not None \
            else mock_net.bus.create_node(name)
        self.info = NodeInfo(address=name,
                             legal_identity=Party(name, key_pair.public),
                             advertised_services=tuple(advertised_services))
        self.services = ServiceHub(self.info, self.messaging,
                                   key_pairs=[key_pair])
        if storage is not None:
            # restart path: the transaction DB survives; rebuild the vault's
            # in-memory view from it (the persistent-vault analog)
            self.services.storage = storage
            self.services.vault.notify_all(storage.transactions)
        self.smm = StateMachineManager(self.services, checkpoint_storage)
        self.smm.clock = mock_net.clock.now   # flow timers on the test clock
        self.services.smm = self.smm
        self.notary_service = None
        from ..flows.library import install_core_flows
        install_core_flows(self.smm)

    def install_notary(self, notary_service_cls, **kwargs) -> None:
        """Install a NotaryService (SimpleNotaryService/ValidatingNotaryService)."""
        self.notary_service = notary_service_cls(self.services, **kwargs)
        self.notary_service.install(self.smm)

    def start(self) -> None:
        self.smm.start()

    def start_flow(self, flow):
        return self.smm.add(flow)

    @property
    def party(self) -> Party:
        return self.info.legal_identity

    def stop(self) -> None:
        """Simulate node death: drop off the bus handlers (checkpoints stay)."""
        self.smm.stop()
        self.smm.flows.clear()

    def restart(self) -> "MockNode":
        """Simulate restart-with-checkpoints: a fresh node reusing this node's
        checkpoint storage, transaction DB, bus endpoint and identity
        (TwoPartyTradeFlowTests mid-flow-restart analog). Core flows are
        reinstalled and an installed notary service is re-installed, exactly
        as a real node boot would (AbstractNode.start)."""
        self.stop()
        node = MockNode(self.mock_net, str(self.info.legal_identity.name),
                        self.key_pair,
                        advertised_services=self.info.advertised_services,
                        checkpoint_storage=self.smm.checkpoints,
                        messaging=self.messaging,
                        storage=self.services.storage)
        if self.notary_service is not None:
            node.install_notary(type(self.notary_service),
                                uniqueness=self.notary_service.uniqueness)
        self.mock_net.nodes[self.mock_net.nodes.index(self)] = node
        for other in self.mock_net.nodes:
            node.services.network_map_cache.add_node(other.info)
        return node


class MockNetwork:
    def __init__(self):
        self.bus = InMemoryMessagingNetwork()
        self.nodes: list[MockNode] = []
        self._counter = 0
        self.clock = TestClock()

    def advance_clock(self, seconds: float) -> int:
        """Advance the shared test clock, fire every due flow timer, then
        pump the network to quiescence. Returns fired timer count."""
        self.clock.advance(seconds)
        fired = sum(n.smm.wake_timers() for n in self.nodes)
        self.run_network()
        return fired

    def create_node(self, name: str | None = None,
                    advertised_services: tuple[ServiceInfo, ...] = (),
                    key_pair: KeyPair | None = None,
                    checkpoint_storage: CheckpointStorage | None = None
                    ) -> MockNode:
        self._counter += 1
        if name is None:
            name = f"O=Mock Company {self._counter}, L=London, C=GB"
        if key_pair is None:
            key_pair = generate_keypair(
                entropy=self._counter.to_bytes(32, "big"))
        node = MockNode(self, name, key_pair, advertised_services,
                        checkpoint_storage)
        self.nodes.append(node)
        # full-mesh directory (the network-map push analog for tests)
        for a in self.nodes:
            for b in self.nodes:
                a.services.network_map_cache.add_node(b.info)
        return node

    def create_notary_node(self, name: str | None = None, validating: bool = False,
                           **kwargs) -> MockNode:
        from ..node.notary import SimpleNotaryService, ValidatingNotaryService
        from ..node.services import ServiceInfo
        cls = ValidatingNotaryService if validating else SimpleNotaryService
        node = self.create_node(
            name or "O=Notary Service, L=Zurich, C=CH",
            advertised_services=(ServiceInfo(cls.type_id),), **kwargs)
        node.install_notary(cls)
        return node

    def start_nodes(self) -> None:
        for node in self.nodes:
            node.start()

    def run_network(self, rounds: int = -1, exclude=(),
                    idle_timeout: float = 120.0) -> int:
        """Pump until quiescent. Beyond message delivery, this also drains
        each node's async verify completions (the Verify suspension point:
        device/pool futures resolve on foreign threads and re-enter the flow
        on this driving thread via smm.drain_external), waiting — bounded by
        ``idle_timeout`` — while any flow is parked on such a future.
        The default is generous because a parked flow's batch may be paying
        a first jit-compile (tens of seconds on CPU, minutes through a cold
        device tunnel) — that is progress the driving thread cannot see."""
        total = self.bus.run_network(rounds, exclude=exclude)
        if rounds != -1:
            return total
        import time as _time
        excluded = set(exclude)
        deadline = _time.monotonic() + idle_timeout
        while True:
            live = [n for n in self.nodes
                    if str(n.info.address) not in excluded]
            drained = False
            for n in live:
                drained |= n.smm.drain_external()
            pumped = self.bus.run_network(-1, exclude=exclude)
            total += pumped
            if drained or pumped:
                deadline = _time.monotonic() + idle_timeout
                continue
            if not any(n.smm.awaiting_external for n in live):
                return total
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    "flows awaiting async verification made no progress "
                    f"for {idle_timeout}s")
            _time.sleep(0.002)
