"""Dummy contract/state fixtures (reference: test-utils DummyContract/DummyState)."""
from __future__ import annotations

from dataclasses import dataclass, field

from ..core.contracts import (Contract, ContractState, TypeOnlyCommandData)
from ..core.crypto.keys import PublicKey
from ..core.crypto.secure_hash import SecureHash
from ..core.serialization import serializable

DUMMY_NOTARY_NAME = "O=Notary Service, L=Zurich, C=CH"


@serializable("DummyContract.Create")
@dataclass(frozen=True)
class Create(TypeOnlyCommandData):
    pass


@serializable("DummyContract.Move")
@dataclass(frozen=True)
class Move(TypeOnlyCommandData):
    pass


class DummyContract(Contract):
    legal_contract_reference = SecureHash.sha256(b"corda_tpu.testing.DummyContract")

    Create = Create
    Move = Move

    def verify(self, tx) -> None:
        pass  # always accepts


_DUMMY_CONTRACT = DummyContract()

from ..core.serialization import register_type as _register_type  # noqa: E402

_register_type("DummyContract", DummyContract,
               to_fields=lambda c: [], from_fields=lambda f: _DUMMY_CONTRACT)


@serializable("DummyState")
@dataclass(frozen=True)
class DummyState(ContractState):
    magic_number: int = 0
    owners: tuple[PublicKey, ...] = ()

    @property
    def contract(self) -> Contract:
        return _DUMMY_CONTRACT

    @property
    def participants(self) -> list[PublicKey]:
        return list(self.owners)
