"""Generator — composable random-data generation (a monad over an RNG).

Reference parity: client/mock Generator (client/mock/.../Generator.kt:1-225)
+ Generators.kt: pure/map/flat_map/combine composition, choice/frequency,
collection generators — the substrate under GeneratedLedger and the loadtest
scenarios.
"""
from __future__ import annotations

import random
from typing import Any, Callable


class Generator:
    def __init__(self, fn: Callable[[random.Random], Any]):
        self._fn = fn

    def generate(self, rng: random.Random):
        return self._fn(rng)

    # -- composition ---------------------------------------------------------
    @staticmethod
    def pure(value) -> "Generator":
        return Generator(lambda rng: value)

    def map(self, f: Callable) -> "Generator":
        return Generator(lambda rng: f(self._fn(rng)))

    def flat_map(self, f: Callable[[Any], "Generator"]) -> "Generator":
        return Generator(lambda rng: f(self._fn(rng)).generate(rng))

    @staticmethod
    def combine(*gens: "Generator", with_fn: Callable = lambda *a: a
                ) -> "Generator":
        return Generator(lambda rng: with_fn(*[g.generate(rng) for g in gens]))

    # -- primitives ----------------------------------------------------------
    @staticmethod
    def int_range(lo: int, hi: int) -> "Generator":
        return Generator(lambda rng: rng.randint(lo, hi))

    @staticmethod
    def bytes_of(n: int) -> "Generator":
        return Generator(lambda rng: rng.randbytes(n))

    @staticmethod
    def choice(items) -> "Generator":
        items = list(items)
        return Generator(lambda rng: rng.choice(items))

    @staticmethod
    def frequency(*weighted: tuple[float, "Generator"]) -> "Generator":
        weights = [w for w, _ in weighted]
        gens = [g for _, g in weighted]

        def gen(rng):
            return rng.choices(gens, weights=weights, k=1)[0].generate(rng)

        return Generator(gen)

    def list_of(self, size_gen: "Generator") -> "Generator":
        return Generator(lambda rng: [self._fn(rng) for _ in
                                      range(size_gen.generate(rng))])

    @staticmethod
    def shuffled(items) -> "Generator":
        def gen(rng):
            out = list(items)
            rng.shuffle(out)
            return out
        return Generator(gen)

    @staticmethod
    def poisson_size(mean: float, cap: int = 50) -> "Generator":
        """Poisson-ish sized collections (GeneratedLedger's component lists)."""
        def gen(rng):
            n, p = 0, rng.random()
            import math
            threshold = math.exp(-mean)
            while p > threshold and n < cap:
                p *= rng.random()
                n += 1
            return n
        return Generator(gen)
