"""Pluggable node-process runners: local subprocesses or SSH-managed hosts.

Reference parity: the loadtest drives a REMOTE cluster over SSH with
disruption injection (LoadTest.kt:1-211 connectToNodes, NodeConnection.kt's
ssh session + process control, Disruption.kt:17-105 kill/hang via remote
shell commands).  Here process control (spawn / terminate / kill / SIGSTOP
/ SIGCONT / log capture) is abstracted behind :class:`NodeRunner`, so the
driver DSL, the disruption library and the conservation checks run
UNCHANGED over either runner:

- :class:`LocalRunner` — subprocess.Popen (the default; what CI runs).
- :class:`SSHRunner` — the same lifecycle over an SSH transport: the
  remote command is wrapped so its PID is reported on the first stdout
  line, stdout/stderr stream back over the SSH channel (log capture), and
  signals are delivered by follow-up ``kill`` commands through the same
  transport.  The transport argv is injectable, which makes the command
  layer unit-testable without a live remote (tests/test_runner.py runs it
  through ``bash -c``) — live multi-host execution needs only real
  ``ssh`` in PATH and key-based auth (docs/DEPLOYMENT.md).
"""
from __future__ import annotations

import os
import shlex
import signal
import subprocess
import time


class ProcessHandle:
    """Uniform process-control surface over a spawned node (duck-compatible
    with the subset of subprocess.Popen the driver/loadtest already used,
    plus suspend/resume for the hang disruption)."""

    pid: int | None
    stdout = None

    def poll(self):  # pragma: no cover - interface
        raise NotImplementedError

    def wait(self, timeout: float | None = None):  # pragma: no cover
        raise NotImplementedError

    def terminate(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def kill(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def suspend(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def resume(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class LocalProcessHandle(ProcessHandle):
    """A subprocess.Popen with suspend/resume (SIGSTOP/SIGCONT)."""

    def __init__(self, proc: subprocess.Popen):
        self._proc = proc
        self.pid = proc.pid
        self.stdout = proc.stdout

    def poll(self):
        return self._proc.poll()

    def wait(self, timeout: float | None = None):
        return self._proc.wait(timeout=timeout)

    def terminate(self) -> None:
        self._proc.terminate()

    def kill(self) -> None:
        self._proc.kill()

    def suspend(self) -> None:
        os.kill(self.pid, signal.SIGSTOP)

    def resume(self) -> None:
        os.kill(self.pid, signal.SIGCONT)


class NodeRunner:
    """Spawns node/verifier processes somewhere and hands back handles."""

    def spawn(self, cmd: list[str], env: dict | None = None,
              cwd: str | None = None) -> ProcessHandle:  # pragma: no cover
        raise NotImplementedError

    def prepare_dir(self, path: str) -> None:  # pragma: no cover
        raise NotImplementedError


class LocalRunner(NodeRunner):
    def spawn(self, cmd: list[str], env: dict | None = None,
              cwd: str | None = None) -> LocalProcessHandle:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                env=env, cwd=cwd)
        return LocalProcessHandle(proc)

    def prepare_dir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)


_PID_MARKER = "__CORDA_TPU_PID__"


class SSHProcessHandle(ProcessHandle):
    """A remote process: the local ssh client streams its output; signals
    travel as separate ``kill`` invocations over the same transport."""

    def __init__(self, runner: "SSHRunner", proc: subprocess.Popen,
                 pid_timeout_s: float = 30.0):
        self._runner = runner
        self._proc = proc
        self.pid = self._read_pid(pid_timeout_s)
        self.stdout = proc.stdout

    def _read_pid(self, timeout_s: float) -> int:
        """The wrapper prints '<marker> <pid>' as its first line; consume
        lines until it appears (sshd banners may precede it). select(2)
        gates each read so a transport that connects but never produces
        output (hung sshd, half-open firewall) trips the timeout instead
        of blocking readline forever."""
        import select
        deadline = time.monotonic() + timeout_s
        fd = self._proc.stdout.fileno()
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._proc.kill()
                raise TimeoutError("remote process did not report its PID")
            ready, _, _ = select.select([fd], [], [], min(remaining, 1.0))
            if not ready:
                continue
            line = self._proc.stdout.readline()
            if not line:
                raise RuntimeError("remote process exited before "
                                   "reporting its PID")
            if line.startswith(_PID_MARKER):
                return int(line.split()[1])

    def poll(self):
        return self._proc.poll()

    def wait(self, timeout: float | None = None):
        return self._proc.wait(timeout=timeout)

    def _signal(self, sig: str) -> None:
        self._runner.run(f"kill -{sig} {self.pid}", check=False)

    def terminate(self) -> None:
        self._signal("TERM")

    def kill(self) -> None:
        self._signal("KILL")
        # reap the local ssh client once the remote side dies (EOF)
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._proc.kill()

    def suspend(self) -> None:
        self._signal("STOP")

    def resume(self) -> None:
        self._signal("CONT")


class SSHRunner(NodeRunner):
    """Runs node processes on a remote host over SSH.

    ``transport`` is the argv prefix that executes one shell command
    string on the remote (default: ``ssh -o BatchMode=yes <host>``);
    injecting ``["bash", "-c"]`` turns the whole command layer into a
    locally-testable fake remote."""

    def __init__(self, host: str, user: str | None = None,
                 transport: list[str] | None = None):
        self.host = host
        self.user = user
        target = f"{user}@{host}" if user else host
        self.transport = (list(transport) if transport is not None
                          else ["ssh", "-o", "BatchMode=yes", target])

    # -- command layer -------------------------------------------------------
    def remote_command(self, cmd: list[str], env: dict | None = None,
                       cwd: str | None = None) -> str:
        """The exact shell string executed on the remote for ``spawn``:
        report the shell's PID (which ``exec`` then BECOMES — signals hit
        the node itself, not a wrapper), then exec the node under its env."""
        parts = []
        if cwd:
            parts.append(f"cd {shlex.quote(cwd)}")
        parts.append(f"echo {_PID_MARKER} $$")
        envs = "".join(f"{k}={shlex.quote(str(v))} "
                       for k, v in sorted((env or {}).items()))
        # `exec env K=V argv...`: exec replaces the PID-reporting shell (so
        # signals hit the node itself) and env(1) carries the assignments
        parts.append("exec " + ("env " + envs if envs else "")
                     + " ".join(shlex.quote(c) for c in cmd) + " 2>&1")
        return "; ".join(parts)

    def argv(self, shell_command: str) -> list[str]:
        return self.transport + [shell_command]

    def run(self, shell_command: str, check: bool = True,
            timeout: float = 30.0) -> subprocess.CompletedProcess:
        """One-shot remote command (mkdir, kill, pgrep...)."""
        out = subprocess.run(self.argv(shell_command), capture_output=True,
                             text=True, timeout=timeout)
        if check and out.returncode != 0:
            raise RuntimeError(
                f"remote command failed ({out.returncode}): "
                f"{shell_command}\n{out.stdout}{out.stderr}")
        return out

    # -- runner surface ------------------------------------------------------
    def spawn(self, cmd: list[str], env: dict | None = None,
              cwd: str | None = None) -> SSHProcessHandle:
        shell_command = self.remote_command(cmd, env, cwd)
        proc = subprocess.Popen(self.argv(shell_command),
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        return SSHProcessHandle(self, proc)

    def prepare_dir(self, path: str) -> None:
        self.run(f"mkdir -p {shlex.quote(path)}")
