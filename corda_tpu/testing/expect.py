"""Expect — declarative assertions over event streams.

Reference parity: test-utils Expect.kt:1-303 — compose `expect` leaves with
`sequence` (ordered), `parallel` (any interleaving), and `repeat`, then run
the compiled expectation against a recorded event list. Used for asserting
vault updates, state-machine changes and message transfers in tests.

    run_expectations(events, sequence(
        expect(VaultUpdate, lambda u: len(u.produced) == 1),
        parallel(expect(str, lambda s: s == "a"), expect(str, lambda s: s == "b")),
    ))
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable


class ExpectationFailed(AssertionError):
    pass


@dataclass
class _Leaf:
    match_type: type
    predicate: Callable[[Any], bool]

    def describe(self) -> str:
        return f"expect({self.match_type.__name__})"


@dataclass
class _Sequence:
    children: tuple


@dataclass
class _Parallel:
    children: tuple


def expect(match_type: type = object,
           predicate: Callable[[Any], bool] = lambda e: True) -> _Leaf:
    return _Leaf(match_type, predicate)


def sequence(*children) -> _Sequence:
    return _Sequence(tuple(children))


def parallel(*children) -> _Parallel:
    return _Parallel(tuple(children))


def repeat(n: int, child) -> _Sequence:
    return _Sequence(tuple(child for _ in range(n)))


def _simplify(node):
    """Collapse vacuously-satisfied nodes (empty sequence/parallel) to None."""
    if node is None or isinstance(node, _Leaf):
        return node
    children = tuple(c for c in (_simplify(c) for c in node.children)
                     if c is not None)
    if not children:
        return None
    return type(node)(children)


def _next_leaves(node) -> list:
    """The set of leaves that may legally match the next event."""
    if isinstance(node, _Leaf):
        return [node]
    if isinstance(node, _Sequence):
        return _next_leaves(node.children[0])
    if isinstance(node, _Parallel):
        out = []
        for c in node.children:
            out.extend(_next_leaves(c))
        return out
    raise TypeError(node)


def _consume(node, leaf):
    """Return the expectation tree with `leaf` satisfied, or None if empty."""
    if isinstance(node, _Leaf):
        return None if node is leaf else node
    if isinstance(node, _Sequence):
        head = _consume(node.children[0], leaf)
        rest = node.children[1:]
        children = ((head,) if head is not None else ()) + rest
        return _Sequence(children) if children else None
    if isinstance(node, _Parallel):
        children = []
        consumed = False
        for c in node.children:
            if not consumed and leaf in _next_leaves(c):
                reduced = _consume(c, leaf)
                consumed = True
                if reduced is not None:
                    children.append(reduced)
            else:
                children.append(c)
        return _Parallel(tuple(children)) if children else None
    raise TypeError(node)


def run_expectations(events, expectation, strict: bool = True) -> None:
    """Match the expectation tree against the event list with full
    backtracking over ambiguous parallel branches.

    ``strict`` (the reference's default, Expect.kt isStrict): every event
    must match some expectation — an unexpected event fails the run.
    Non-strict skips events no leaf wants. Predicate exceptions propagate
    (a broken predicate is a broken test, not a non-match)."""
    events = list(events)

    def attempt(node, idx) -> bool:
        if node is None:
            # all expectations satisfied; strict additionally requires no
            # trailing unexpected events
            return idx == len(events) if strict else True
        if idx == len(events):
            return False
        event = events[idx]
        for leaf in _next_leaves(node):
            if isinstance(event, leaf.match_type) and leaf.predicate(event):
                if attempt(_simplify(_consume(node, leaf)), idx + 1):
                    return True
        if not strict:
            return attempt(node, idx + 1)
        return False

    node = _simplify(expectation)
    if node is None:
        if strict and events:
            raise ExpectationFailed(
                f"Strict mode: {len(events)} unexpected event(s), first: "
                f"{events[0]!r}")
        return
    if not attempt(node, 0):
        raise ExpectationFailed(
            f"No assignment of {len(events)} events satisfies the "
            f"expectations (strict={strict}); remaining shape: {node}")
