"""MockServices — an in-memory ServiceHub stand-in for unit tests.

Reference parity: node/MockServices.kt:1-199 (state/attachment/identity/key
stubs backing `toLedgerTransaction` resolution and signing in tests). The
attachment/identity implementations are the node's in-memory services
(corda_tpu.node.services) re-exported under their Mock names.
"""
from __future__ import annotations

from ..core.contracts.structures import StateRef, TransactionState
from ..core.crypto.keys import KeyPair, PublicKey
from ..core.crypto.signatures import Crypto, DigitalSignatureWithKey
from ..core.identity import Party
from ..node.services import (InMemoryAttachmentStorage as MockAttachmentStorage,
                             InMemoryIdentityService as MockIdentityService)

__all__ = ["MockAttachmentStorage", "MockIdentityService", "MockServices"]


class MockServices:
    """Minimal ServiceHub: state resolution, attachments, identity, signing."""

    def __init__(self, key_pairs: list[KeyPair] = (), parties: list[Party] = ()):
        self.key_pairs = {kp.public: kp for kp in key_pairs}
        self.attachments = MockAttachmentStorage()
        self.identity_service = MockIdentityService(parties)
        self._states: dict[StateRef, TransactionState] = {}
        self.recorded: list = []

    # -- state resolution (WireTransaction.toLedgerTransaction seam) --------
    def load_state(self, ref: StateRef) -> TransactionState | None:
        return self._states.get(ref)

    def record_transactions(self, *stxs) -> None:
        """Make each transaction's outputs resolvable as future inputs."""
        for stx in stxs:
            self.recorded.append(stx)
            wtx = stx.tx if hasattr(stx, "tx") else stx
            for i, out in enumerate(wtx.outputs):
                self._states[StateRef(wtx.id, i)] = out

    def add_state(self, ref: StateRef, state: TransactionState) -> None:
        self._states[ref] = state

    # -- signing ------------------------------------------------------------
    def sign(self, content: bytes, key: PublicKey) -> DigitalSignatureWithKey:
        kp = self.key_pairs[key]
        return Crypto.sign_with_key(kp, content)

    def sign_transaction(self, wtx_or_stx, *keys: PublicKey):
        """WireTransaction → SignedTransaction (or add sigs to an existing one)."""
        from ..core.transactions.signed import SignedTransaction

        if isinstance(wtx_or_stx, SignedTransaction):
            sigs = [self.sign(wtx_or_stx.id.bytes, k) for k in keys]
            return wtx_or_stx.plus(*sigs)
        wtx = wtx_or_stx
        sigs = [self.sign(wtx.id.bytes, k) for k in keys]
        return SignedTransaction.of(wtx, sigs)
