"""Driver DSL — boot REAL node processes for integration tests.

Reference parity: test-utils driver{} (Driver.kt:89-239): start a network-map
node, then nodes/notaries as subprocesses, hand back handles with RPC
clients, and tear everything down (ShutdownManager) on exit.

    with driver(tmp_path) as dsl:
        notary = dsl.start_node("O=Notary, L=Zurich, C=CH", notary="simple")
        alice = dsl.start_node("O=Alice, L=London, C=GB")
        alice.rpc.start_flow_and_wait("CashIssueFlow", ...)
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from dataclasses import dataclass

from ..client.rpc import CordaRPCClient


@dataclass
class NodeHandle:
    name: str
    host: str
    port: int
    process: "object"            # testing.runner.ProcessHandle
    rpc: CordaRPCClient
    # spawn configuration, so restart_node restores the SAME role
    notary: str | None = None
    verifier_type: str = "InMemory"

    def stop(self) -> None:
        if self.rpc is not None:
            self.rpc.close()
        self.process.terminate()
        try:
            self.process.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.process.kill()


@dataclass
class VerifierHandle:
    """A standalone verifier worker subprocess (VerifierDriver.startVerifier
    analog, verifier/src/integration-test/.../VerifierDriver.kt:50-68)."""

    host: str
    port: int
    process: "object"            # testing.runner.ProcessHandle
    stats_file: str | None = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def stop(self) -> None:
        """Graceful: SIGTERM lets the worker flush its stats file."""
        self.process.terminate()
        try:
            self.process.wait(timeout=15)
        except subprocess.TimeoutExpired:
            self.process.kill()

    def kill(self) -> None:
        """Hard kill — the death-redistribution scenario."""
        self.process.kill()
        self.process.wait(timeout=10)


class DriverDSL:
    def __init__(self, base_dir: str, startup_timeout_s: float = 60.0,
                 runner=None):
        """``runner``: a testing.runner.NodeRunner — LocalRunner (default)
        spawns subprocesses on this machine; SSHRunner places the same
        processes on a remote host with identical lifecycle/disruption
        semantics (LoadTest.kt's ssh-managed cluster)."""
        from .runner import LocalRunner
        self.base_dir = str(base_dir)
        self.startup_timeout_s = startup_timeout_s
        self.runner = runner if runner is not None else LocalRunner()
        self.nodes: list[NodeHandle] = []
        self.verifiers: list[VerifierHandle] = []
        self.map_handle: NodeHandle | None = None
        self.map_name = "O=Network Map, L=London, C=GB"

    def __enter__(self) -> "DriverDSL":
        self.map_handle = self._spawn(self.map_name, is_map=True)
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- the DSL -------------------------------------------------------------
    def start_node(self, name: str, notary: str | None = None,
                   verifier_type: str = "InMemory") -> NodeHandle:
        return self._spawn(name, notary=notary, verifier_type=verifier_type)

    def start_notary_node(self, name: str = "O=Notary Service, L=Zurich, C=CH",
                          validating: bool = False) -> NodeHandle:
        return self.start_node(name,
                               notary="validating" if validating else "simple")

    def wait_for_network(self, min_nodes: int, timeout_s: float = 30.0) -> None:
        """Block until every started node sees >= min_nodes in its map cache
        (the driver's networkMapStartStrategy readiness wait)."""
        deadline = time.monotonic() + timeout_s
        for handle in self.nodes:
            while True:
                if len(handle.rpc.network_map_snapshot()) >= min_nodes:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"{handle.name} sees fewer than {min_nodes} nodes")
                time.sleep(0.3)

    def restart_node(self, handle: NodeHandle) -> NodeHandle:
        """Restart a (possibly killed) node subprocess on the SAME base
        directory and with the SAME role (notary/verifier config recorded
        at spawn): identity key, durable transaction store and checkpoints
        are reloaded from disk; the node re-registers its new address with
        the network map (the loadtest kill/restart disruption,
        Disruption.kt:17-105)."""
        if handle.process.poll() is None:
            handle.stop()
        elif handle.rpc is not None:
            handle.rpc.close()
        if handle in self.nodes:
            self.nodes.remove(handle)
        return self._spawn(handle.name, notary=handle.notary,
                           verifier_type=handle.verifier_type)

    def start_verifier(self, queue_address: str, use_device: bool = True,
                       host_crossover: int | None = None,
                       stats_file: str | None = None,
                       extra_env: dict | None = None) -> VerifierHandle:
        """Spawn a standalone verifier worker subprocess attached to
        ``queue_address`` ("host:port" of the requesting endpoint)."""
        cmd = [sys.executable, "-m", "corda_tpu.verifier",
               "--queue-address", queue_address, "--port", "0"]
        if not use_device:
            cmd.append("--no-device")
        if host_crossover is not None:
            cmd += ["--host-crossover", str(host_crossover)]
        if stats_file is not None:
            cmd += ["--stats-file", stats_file]
        env = dict(os.environ)
        env.setdefault("PYTHONPATH", os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env.update(extra_env or {})
        proc = self.runner.spawn(cmd, env=env)
        host, port = await_node_ready(proc, "verifier",
                                      self.startup_timeout_s,
                                      ready_prefix="VERIFIER READY")
        handle = VerifierHandle(host, port, proc, stats_file)
        self.verifiers.append(handle)
        return handle

    def shutdown(self) -> None:
        for handle in reversed(self.verifiers):
            handle.stop()
        self.verifiers.clear()
        for handle in reversed(self.nodes):
            handle.stop()
        self.nodes.clear()

    # -- process management --------------------------------------------------
    def _spawn(self, name: str, is_map: bool = False, notary: str | None = None,
               verifier_type: str = "InMemory") -> NodeHandle:
        node_dir = os.path.join(self.base_dir,
                                name.replace("=", "_").replace(", ", "_"))
        self.runner.prepare_dir(node_dir)
        cmd = [sys.executable, "-m", "corda_tpu.node", "--name", name,
               "--port", "0", "--base-dir", node_dir, "--quiet",
               "--verifier-type", verifier_type]
        if not is_map:
            assert self.map_handle is not None, "driver not entered"
            cmd += ["--network-map-name", self.map_name,
                    "--network-map-address",
                    f"{self.map_handle.host}:{self.map_handle.port}"]
        if notary:
            cmd += ["--notary", notary]
        env = dict(os.environ)
        env.setdefault("PYTHONPATH", os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        proc = self.runner.spawn(cmd, env=env)
        # await_node_ready's reader thread keeps draining stdout for the
        # process lifetime, so the node never blocks on a full pipe
        host, port = await_node_ready(proc, name, self.startup_timeout_s)
        rpc = CordaRPCClient(host, port)
        handle = NodeHandle(name, host, port, proc, rpc,
                            notary=notary, verifier_type=verifier_type)
        self.nodes.append(handle)
        return handle


def await_node_ready(proc, name: str,
                     timeout_s: float = 60.0,
                     ready_prefix: str = "NODE READY"):
    """Block until a node subprocess prints its READY line (driver
    futures); returns (host, port). Lines are read on a helper thread so a
    silently-hung child still trips the timeout instead of blocking readline
    forever. Shared by the driver DSL and the demobench launcher."""
    import queue as _queue
    import threading
    lines_q: "_queue.Queue" = _queue.Queue()

    def _reader():
        for line in proc.stdout:
            lines_q.put(line)
        lines_q.put(None)  # EOF

    threading.Thread(target=_reader, daemon=True).start()
    deadline = time.monotonic() + timeout_s
    lines = []
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            proc.kill()
            raise TimeoutError(
                f"node {name} did not start in time:\n" + "".join(lines))
        try:
            line = lines_q.get(timeout=min(remaining, 1.0))
        except _queue.Empty:
            continue
        if line is None:
            raise RuntimeError(
                f"node {name} exited during startup:\n" + "".join(lines))
        lines.append(line)
        if line.startswith(ready_prefix):
            addr = line.strip().rsplit(" ", 1)[-1]
            host, _, port = addr.rpartition(":")
            return host, int(port)


def driver(base_dir: str, **kwargs) -> DriverDSL:
    return DriverDSL(base_dir, **kwargs)
