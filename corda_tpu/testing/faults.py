"""Public face of the fault-injection harness (docs/ROBUSTNESS.md).

The implementation lives in ``corda_tpu.utils.faults`` so that production
modules (the TCP plane, the batcher, raft) can import ``fault_point``
without pulling in the ``corda_tpu.testing`` package — whose ``__init__``
imports MockNetwork and, transitively, most of the node — which would be
an import cycle. Tests import from here:

    from corda_tpu.testing.faults import FaultRule, inject

    with inject(FaultRule("tcp.send", "drop", count=3), seed=7) as inj:
        ...
        assert inj.fired("tcp.send") == 3
"""
from ..utils.faults import (DROP, DUPLICATE, FaultError, FaultInjector,
                            FaultRule, active, arm, disarm, fault_point,
                            inject)

__all__ = ["DROP", "DUPLICATE", "FaultError", "FaultInjector", "FaultRule",
           "active", "arm", "disarm", "fault_point", "inject"]
