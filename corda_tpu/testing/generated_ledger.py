"""GeneratedLedger — property-based generation of always-valid ledgers.

Reference parity: verifier/src/integration-test/.../GeneratedLedger.kt:25-190
— the key fixture for bulk verification benchmarking and the device-kernel
parity harness: arbitrarily long chains of issuance/move/exit transitions
over a pool of identities, every transaction correctly signed and
platform-rule-valid, with a notary attached so the chains notarise.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field

from ..core.contracts.structures import (Command, StateAndRef, StateRef,
                                         TransactionState)
from ..core.crypto.keys import KeyPair, generate_keypair
from ..core.crypto.schemes import (ECDSA_SECP256K1_SHA256,
                                   EDDSA_ED25519_SHA512)
from ..core.identity import Party
from ..core.transactions.signed import SignedTransaction
from ..core.transactions.wire import WireTransaction
from ..testing.dummy import DummyContract, DummyState
from .generator import Generator


@dataclass
class LedgerState:
    """Generation-time model of the unspent set."""

    parties: list[tuple[Party, KeyPair]]
    notary: Party
    notary_kp: KeyPair
    unspent: list[StateAndRef] = field(default_factory=list)
    transactions: list[SignedTransaction] = field(default_factory=list)
    owners: dict = field(default_factory=dict)   # StateRef -> KeyPair


def make_generated_ledger(n_transactions: int, seed: int = 0,
                          n_parties: int = 4,
                          scheme_mix: bool = True) -> LedgerState:
    """Generate `n_transactions` valid signed transactions: ~30% issuances,
    ~55% moves, ~15% exits (shifting to issuance when the unspent set runs
    dry). `scheme_mix` spreads party keys across Ed25519 and secp256k1
    (the mixed-scheme batch of BASELINE config 2)."""
    rng = random.Random(seed)
    schemes = ([EDDSA_ED25519_SHA512, ECDSA_SECP256K1_SHA256] if scheme_mix
               else [EDDSA_ED25519_SHA512])
    parties = []
    for i in range(n_parties):
        kp = generate_keypair(schemes[i % len(schemes)],
                              entropy=rng.randbytes(32))
        parties.append((Party(f"O=Gen Party {i}, L=City, C=GB", kp.public), kp))
    notary_kp = generate_keypair(entropy=rng.randbytes(32))
    notary = Party("O=Gen Notary, L=Zurich, C=CH", notary_kp.public)
    ledger = LedgerState(parties, notary, notary_kp)

    party_gen = Generator.choice(range(n_parties))
    magic_gen = Generator.int_range(1, 1 << 30)
    kind_gen = Generator.frequency(
        (0.30, Generator.pure("issue")),
        (0.55, Generator.pure("move")),
        (0.15, Generator.pure("exit")))

    def sign(wtx: WireTransaction, *kps: KeyPair) -> SignedTransaction:
        from ..core.crypto.signatures import Crypto
        sigs = [Crypto.sign_with_key(kp, wtx.id.bytes) for kp in kps]
        return SignedTransaction.of(wtx, sigs)

    def record(stx: SignedTransaction, owner_kps) -> None:
        ledger.transactions.append(stx)
        for i, out in enumerate(stx.tx.outputs):
            ref = StateRef(stx.id, i)
            ledger.unspent.append(StateAndRef(out, ref))
            ledger.owners[ref] = owner_kps[i]

    for _ in range(n_transactions):
        kind = kind_gen.generate(rng)
        if kind != "issue" and not ledger.unspent:
            kind = "issue"
        if kind == "issue":
            who = party_gen.generate(rng)
            party, kp = parties[who]
            n_out = max(1, Generator.poisson_size(1.5, 4).generate(rng))
            outputs = tuple(
                TransactionState(DummyState(magic_gen.generate(rng),
                                            (party.owning_key,)), notary)
                for _ in range(n_out))
            wtx = WireTransaction(
                outputs=outputs,
                commands=(Command(DummyContract.Create(), (party.owning_key,)),),
                notary=notary, must_sign=(party.owning_key,))
            record(sign(wtx, kp), [kp] * n_out)
        else:
            idx = rng.randrange(len(ledger.unspent))
            sar = ledger.unspent.pop(idx)
            owner_kp = ledger.owners[sar.ref]
            if kind == "move":
                who = party_gen.generate(rng)
                new_party, new_kp = parties[who]
                outputs = (TransactionState(
                    DummyState(sar.state.data.magic_number,
                               (new_party.owning_key,)), notary),)
                owner_kps = [new_kp]
            else:  # exit: consume with no outputs
                outputs = ()
                owner_kps = []
            wtx = WireTransaction(
                inputs=(sar.ref,), outputs=outputs,
                commands=(Command(DummyContract.Move(),
                                  (owner_kp.public,)),),
                notary=notary,
                must_sign=(owner_kp.public, notary.owning_key))
            record(sign(wtx, owner_kp, notary_kp), owner_kps)
    return ledger


def signature_triples(ledger: LedgerState):
    """Flatten the ledger into (key, signature, content) checks — the raw feed
    for the device signature batcher (the bulk-verification benchmark input)."""
    triples = []
    for stx in ledger.transactions:
        for sig in stx.sigs:
            triples.append((sig.by, sig.bytes, stx.id.bytes))
    return triples
