"""Test infrastructure — a first-class layer, as in the reference (SURVEY.md §4):
dummy contracts, mock services, the in-memory MockNetwork, ledger DSL and driver.
"""
from .dummy import DummyContract, DummyState, DUMMY_NOTARY_NAME
from .expect import expect, parallel, repeat, run_expectations, sequence
from .mocknetwork import MockNetwork, MockNode, TestClock
from .services import MockAttachmentStorage, MockIdentityService, MockServices

__all__ = ["DummyContract", "DummyState", "DUMMY_NOTARY_NAME",
           "expect", "parallel", "repeat", "run_expectations", "sequence",
           "MockAttachmentStorage", "MockIdentityService", "MockServices",
           "MockNetwork", "MockNode", "TestClock"]
