"""Universal contracts: a combinator DSL for generic financial agreements.

Reference parity: experimental (universal contracts) — UniversalContract.kt
(:1-327) and Perceivable.kt: instead of one bespoke contract class per
product, an *arrangement algebra* describes any cashflow agreement and ONE
contract verifies every transition of it:

- **Perceivables** — pure observations over a valuation context (time,
  oracle fixings): ``const``, arithmetic, comparisons, ``after(t)``,
  ``fixing(name)``. Deterministic: evaluation sees only the context.
- **Arrangements** — the agreement state machine: ``Zero`` (nothing owed),
  ``Transfer`` (an obligation to pay), ``All`` (conjunction), and
  ``Actions`` (named transitions, each with an authorized actor, a
  perceivable condition, and a continuation arrangement).
- **UniversalState/UniversalContract** — the single on-ledger state/contract
  pair: ``Issue`` requires every liable party's signature; ``Move(action)``
  requires the action's actor to sign, its condition to hold under the
  transaction's context (time-window midpoint + fixings carried by the
  command), and the outputs to equal the action's continuation.

The reference marks this experimental; the same caveat applies here.
"""
from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Any

from ..core.contracts.exceptions import TransactionVerificationException
from ..core.contracts.structures import Contract, ContractState
from ..core.crypto.keys import PublicKey
from ..core.serialization import register_type, serializable


# ---------------------------------------------------------------------------
# Perceivables
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ValuationContext:
    """What perceivables may see: the evaluation instant and oracle fixings
    (name → integer value; rates in basis points etc.)."""

    at: datetime.datetime
    fixings: dict = field(default_factory=dict)


class Perceivable:
    """A pure observation. Subclasses implement ``value(ctx)``."""

    def value(self, ctx: ValuationContext):
        raise NotImplementedError

    # arithmetic / comparison combinators
    def __add__(self, other):  return BinOp("+", self, lift(other))
    def __sub__(self, other):  return BinOp("-", self, lift(other))
    def __mul__(self, other):  return BinOp("*", self, lift(other))
    def gt(self, other):       return BinOp(">", self, lift(other))
    def ge(self, other):       return BinOp(">=", self, lift(other))
    def lt(self, other):       return BinOp("<", self, lift(other))
    def eq(self, other):       return BinOp("==", self, lift(other))
    def and_(self, other):     return BinOp("and", self, lift(other))
    def or_(self, other):      return BinOp("or", self, lift(other))


@serializable("universal.Const")
@dataclass(frozen=True)
class Const(Perceivable):
    v: Any

    def value(self, ctx):
        return self.v


@serializable("universal.BinOp")
@dataclass(frozen=True)
class BinOp(Perceivable):
    op: str
    left: Perceivable
    right: Perceivable

    def value(self, ctx):
        a, b = self.left.value(ctx), self.right.value(ctx)
        return {
            "+": lambda: a + b, "-": lambda: a - b, "*": lambda: a * b,
            ">": lambda: a > b, ">=": lambda: a >= b, "<": lambda: a < b,
            "==": lambda: a == b, "and": lambda: bool(a) and bool(b),
            "or": lambda: bool(a) or bool(b),
        }[self.op]()


@serializable("universal.After")
@dataclass(frozen=True)
class After(Perceivable):
    """True once the valuation instant reaches ``instant`` (epoch micros)."""

    instant_micros: int

    def value(self, ctx):
        from ..core.serialization.codec import exact_epoch_micros
        return exact_epoch_micros(ctx.at) >= self.instant_micros


@serializable("universal.Fixing")
@dataclass(frozen=True)
class Fixing(Perceivable):
    """An oracle-observed value (rate fixing) by name; evaluation fails the
    transition when the context lacks it."""

    name: str

    def value(self, ctx):
        if self.name not in ctx.fixings:
            raise TransactionVerificationException(
                None, f"fixing {self.name!r} not provided")
        return ctx.fixings[self.name]


def lift(v) -> Perceivable:
    return v if isinstance(v, Perceivable) else Const(v)


def const(v) -> Perceivable:
    return Const(v)


def after(t: datetime.datetime) -> Perceivable:
    from ..core.serialization.codec import exact_epoch_micros
    return After(exact_epoch_micros(t))


def fixing(name: str) -> Perceivable:
    return Fixing(name)


# ---------------------------------------------------------------------------
# Arrangements
# ---------------------------------------------------------------------------

class Arrangement:
    def liable_parties(self) -> frozenset[PublicKey]:
        """Keys with obligations anywhere in the arrangement (must sign
        issuance)."""
        return frozenset()


@serializable("universal.Zero")
@dataclass(frozen=True)
class Zero(Arrangement):
    """Nothing owed — the terminal arrangement."""


@serializable("universal.Transfer")
@dataclass(frozen=True)
class Transfer(Arrangement):
    """An obligation: ``frm`` owes ``amount`` (a perceivable or int, in
    integer token units) of ``token`` to ``to``."""

    amount: Any           # Perceivable | int
    token: str
    frm: PublicKey
    to: PublicKey

    def liable_parties(self):
        return frozenset((self.frm,))


@serializable("universal.All")
@dataclass(frozen=True)
class All(Arrangement):
    parts: tuple

    def liable_parties(self):
        out = frozenset()
        for p in self.parts:
            out |= p.liable_parties()
        return out


@serializable("universal.Action")
@dataclass(frozen=True)
class Action:
    """A named transition: ``actor`` may move the agreement to ``next`` when
    ``condition`` holds."""

    actor: PublicKey
    condition: Perceivable
    next: Arrangement


@serializable("universal.Actions", to_fields=lambda a: [sorted(a.table.items())],
              from_fields=lambda f: Actions(dict(f[0])))
@dataclass(frozen=True)
class Actions(Arrangement):
    table: dict   # name -> Action

    def liable_parties(self):
        out = frozenset()
        for act in self.table.values():
            out |= act.next.liable_parties()
        return out

    def __hash__(self):
        return hash(tuple(sorted(self.table)))


# ---------------------------------------------------------------------------
# The single state/contract pair
# ---------------------------------------------------------------------------

@serializable("universal.UniversalState")
@dataclass(frozen=True)
class UniversalState(ContractState):
    arrangement: Arrangement
    parties: tuple    # PublicKey... (everyone party to the agreement)

    @property
    def contract(self):
        return UniversalContract()

    @property
    def participants(self):
        return list(self.parties)

    def __hash__(self):
        return hash((type(self), self.parties))


@serializable("universal.Issue")
@dataclass(frozen=True)
class Issue:
    pass


@serializable("universal.Move",
              to_fields=lambda m: [m.action, sorted(m.fixings.items())],
              from_fields=lambda f: Move(f[0], dict(f[1])))
@dataclass(frozen=True)
class Move:
    """Exercise the named action; ``fixings`` carries the oracle context the
    condition may observe (attested upstream by the oracle flow)."""

    action: str
    fixings: dict = field(default_factory=dict)

    def __hash__(self):
        return hash((self.action, tuple(sorted(self.fixings.items()))))


class UniversalContract(Contract):
    """One verify() for every product expressible in the algebra
    (UniversalContract.kt verify semantics)."""

    def verify(self, tx) -> None:
        commands = [c for c in tx.commands
                    if isinstance(c.value, (Issue, Move))]
        if len(commands) != 1:
            raise TransactionVerificationException(
                tx.id, "exactly one universal-contract command required")
        cmd = commands[0]
        ins = [s for s in tx.inputs if isinstance(s, UniversalState)]
        outs = [s for s in tx.outputs if isinstance(s, UniversalState)]

        if isinstance(cmd.value, Issue):
            if ins or len(outs) != 1:
                raise TransactionVerificationException(
                    tx.id, "issuance: no universal inputs, one output")
            missing = outs[0].arrangement.liable_parties() - set(cmd.signers)
            if missing:
                raise TransactionVerificationException(
                    tx.id, "issuance must be signed by every liable party")
            return

        # Move
        if len(ins) != 1:
            raise TransactionVerificationException(
                tx.id, "move: exactly one universal input")
        arrangement = ins[0].arrangement
        if not isinstance(arrangement, Actions):
            raise TransactionVerificationException(
                tx.id, "input arrangement offers no actions")
        action = arrangement.table.get(cmd.value.action)
        if action is None:
            raise TransactionVerificationException(
                tx.id, f"no action {cmd.value.action!r} in the arrangement")
        if action.actor not in set(cmd.signers):
            raise TransactionVerificationException(
                tx.id, f"action {cmd.value.action!r} must be signed by its actor")
        if tx.time_window is None or tx.time_window.midpoint is None:
            raise TransactionVerificationException(
                tx.id, "move requires a time-window (the valuation instant)")
        ctx = ValuationContext(tx.time_window.midpoint,
                               dict(cmd.value.fixings))
        if not action.condition.value(ctx):
            raise TransactionVerificationException(
                tx.id, f"condition for {cmd.value.action!r} does not hold")
        expected = action.next
        if isinstance(expected, Zero):
            if outs:
                raise TransactionVerificationException(
                    tx.id, "continuation is Zero: no universal output allowed")
        else:
            if len(outs) != 1 or outs[0].arrangement != expected:
                raise TransactionVerificationException(
                    tx.id, "output must equal the action's continuation")
            if outs[0].parties != ins[0].parties:
                raise TransactionVerificationException(
                    tx.id, "parties to the agreement cannot change on a move")


register_type("universal.UniversalContract", UniversalContract,
              to_fields=lambda c: [], from_fields=lambda f: UniversalContract())
