"""Experimental subsystems (the reference's experimental/ tree)."""
