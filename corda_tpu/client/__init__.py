"""Client libraries (the reference's client/rpc, client/jackson layer)."""
from .rpc import CordaRPCClient, RPCException  # noqa: F401
