"""NodeMonitorModel — one subscription point turning RPC feeds into live
observable models.

Reference parity: client/jfx's NodeMonitorModel + the observable-value
utilities (client/jfx/.../Models.kt, NodeMonitorModel tracking vault,
transactions, state-machine progress per flow over RPC). JavaFX property
bindings become plain observable lists/values with callbacks — the same
aggregation layer the explorer/GUI consumed, usable from any Python UI,
notebook, or test.
"""
from __future__ import annotations

import threading
from typing import Any, Callable


class ObservableValue:
    """A value plus change callbacks (the Property binding analog)."""

    def __init__(self, initial: Any = None):
        self._lock = threading.Lock()
        self._value = initial
        self._observers: list[Callable] = []

    @property
    def value(self):
        with self._lock:
            return self._value

    def set(self, value) -> None:
        with self._lock:
            self._value = value
            observers = list(self._observers)
        for cb in observers:
            cb(value)

    def update(self, fn: Callable[[Any], Any]) -> None:
        """Atomic read-modify-write (concurrent feed callbacks must not lose
        increments)."""
        with self._lock:
            self._value = value = fn(self._value)
            observers = list(self._observers)
        for cb in observers:
            cb(value)

    def observe(self, cb: Callable) -> None:
        self._observers.append(cb)


class ObservableList:
    """An append-only observable list (the ObservableList utilities role)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items: list = []
        self._observers: list[Callable] = []

    def append(self, item) -> None:
        with self._lock:
            self._items.append(item)
            observers = list(self._observers)
        for cb in observers:
            cb(item)

    def snapshot(self) -> list:
        with self._lock:
            return list(self._items)

    def observe(self, cb: Callable) -> None:
        self._observers.append(cb)

    def __len__(self):
        return len(self._items)


class NodeMonitorModel:
    """Subscribe once, read live models: state-machine events, vault
    updates, verified transactions, and derived aggregates."""

    def __init__(self):
        self.state_machine_events = ObservableList()   # ("add"/"remove", info)
        self.vault_updates = ObservableList()          # VaultUpdate
        self.transactions = ObservableList()           # SignedTransaction
        self.in_flight_flows = ObservableValue(0)
        self.tx_count = ObservableValue(0)

    def register(self, ops) -> "NodeMonitorModel":
        """Wire every feed of a CordaRPCOps (in-process or remote proxy).
        Subscriptions attach BEFORE snapshot seeding and seeding dedupes by
        transaction id, so events landing in the snapshot/subscribe gap are
        neither lost nor double-counted."""
        self._seen_tx = set()
        self._seen_sm = set()
        sm_feed = ops.state_machines_feed()
        sm_feed.subscribe(self._on_sm_event)
        for info in list(sm_feed.snapshot):
            self._on_sm_event(("add", info))
            if info.done:
                self._on_sm_event(("remove", info))

        vault_feed = ops.vault_feed()
        vault_feed.subscribe(self.vault_updates.append)
        if vault_feed.snapshot:
            # fold the pre-existing holdings into one initial update
            # (the reference's initial Vault.Update from the snapshot)
            from ..node.vault import VaultUpdate
            self.vault_updates.append(
                VaultUpdate((), tuple(vault_feed.snapshot)))

        tx_feed = ops.verified_transactions_feed()
        tx_feed.subscribe(self._on_tx)
        for stx in list(tx_feed.snapshot):
            self._on_tx(stx)
        return self

    def _on_sm_event(self, event) -> None:
        kind, info = event
        key = (kind, info.run_id)
        if key in self._seen_sm:   # seeded AND delivered live: count once
            return
        self._seen_sm.add(key)
        self.state_machine_events.append((kind, info))
        delta = 1 if kind == "add" else -1
        self.in_flight_flows.update(lambda v: max(0, v + delta))

    def _on_tx(self, stx) -> None:
        if stx.id in self._seen_tx:
            return
        self._seen_tx.add(stx.id)
        self.transactions.append(stx)
        self.tx_count.update(lambda v: v + 1)
