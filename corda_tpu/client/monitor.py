"""NodeMonitorModel — one subscription point turning RPC feeds into live
observable models.

Reference parity: client/jfx's NodeMonitorModel + the observable-value
utilities (client/jfx/.../Models.kt, NodeMonitorModel tracking vault,
transactions, state-machine progress per flow over RPC). JavaFX property
bindings become plain observable lists/values with callbacks — the same
aggregation layer the explorer/GUI consumed, usable from any Python UI,
notebook, or test.
"""
from __future__ import annotations

import threading
from typing import Any, Callable


class ObservableValue:
    """A value plus change callbacks (the Property binding analog)."""

    def __init__(self, initial: Any = None):
        self._lock = threading.Lock()
        self._value = initial
        self._observers: list[Callable] = []

    @property
    def value(self):
        with self._lock:
            return self._value

    def set(self, value) -> None:
        with self._lock:
            self._value = value
            observers = list(self._observers)
        for cb in observers:
            cb(value)

    def observe(self, cb: Callable) -> None:
        self._observers.append(cb)


class ObservableList:
    """An append-only observable list (the ObservableList utilities role)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items: list = []
        self._observers: list[Callable] = []

    def append(self, item) -> None:
        with self._lock:
            self._items.append(item)
            observers = list(self._observers)
        for cb in observers:
            cb(item)

    def snapshot(self) -> list:
        with self._lock:
            return list(self._items)

    def observe(self, cb: Callable) -> None:
        self._observers.append(cb)

    def __len__(self):
        return len(self._items)


class NodeMonitorModel:
    """Subscribe once, read live models: state-machine events, vault
    updates, verified transactions, and derived aggregates."""

    def __init__(self):
        self.state_machine_events = ObservableList()   # ("add"/"remove", info)
        self.vault_updates = ObservableList()          # VaultUpdate
        self.transactions = ObservableList()           # SignedTransaction
        self.in_flight_flows = ObservableValue(0)
        self.tx_count = ObservableValue(0)

    def register(self, ops) -> "NodeMonitorModel":
        """Wire every feed of a CordaRPCOps (in-process or remote proxy) —
        NodeMonitorModel.register semantics: snapshots first, then deltas."""
        sm_feed = ops.state_machines_feed()
        for info in sm_feed.snapshot:
            self.state_machine_events.append(("add", info))
        self._recount(sm_feed.snapshot)
        sm_feed.subscribe(self._on_sm_event)

        vault_feed = ops.vault_feed()
        vault_feed.subscribe(self.vault_updates.append)

        tx_feed = ops.verified_transactions_feed()
        for stx in tx_feed.snapshot:
            self.transactions.append(stx)
        self.tx_count.set(len(tx_feed.snapshot))
        tx_feed.subscribe(self._on_tx)
        return self

    def _recount(self, infos) -> None:
        self.in_flight_flows.set(sum(1 for i in infos if not i.done))

    def _on_sm_event(self, event) -> None:
        kind, info = event
        self.state_machine_events.append((kind, info))
        delta = 1 if kind == "add" else -1
        self.in_flight_flows.set(max(0, self.in_flight_flows.value + delta))

    def _on_tx(self, stx) -> None:
        self.transactions.append(stx)
        self.tx_count.set(self.tx_count.value + 1)
