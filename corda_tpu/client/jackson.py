"""JSON/YAML bindings for framework types + string→constructor invocation.

Reference parity (VERDICT r2 #8):
- ``client/jackson JacksonSupport.kt:1-375``: custom JSON serializers for
  the platform types — Party as its X.500 name, keys in their short form,
  hashes as hex, Amount as "quantity TOKEN", byte strings as 0x-hex —
  applied recursively over dataclasses so any RPC result renders.
- ``client/jackson StringToMethodCallParser.kt:1-225``: invoke a
  constructor/method from text like ``amount: 100.00 USD, recipient:
  O=Bank A, L=London, C=GB`` by binding ``name: value`` pairs to the
  callable's parameter names, converting each value by parameter
  annotation or shape (the shell's ``flow start`` backbone).
"""
from __future__ import annotations

import dataclasses
import datetime
import inspect
import json
import re

from ..core.contracts.amount import Amount, currency


class UnparseableCallException(Exception):
    """The text does not bind to the target's parameters
    (StringToMethodCallParser.UnparseableCallException)."""


# ---------------------------------------------------------------------------
# Rendering: framework values → JSON-able primitives
# ---------------------------------------------------------------------------

def to_jsonable(value):
    """Recursively reduce a framework value to JSON-able primitives with the
    reference's canonical renderings."""
    from ..core.crypto.keys import PublicKey
    from ..core.crypto.secure_hash import SecureHash
    from ..core.identity import Party

    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, Party):
        return str(value.name)
    if isinstance(value, PublicKey):
        return value.to_string_short()
    if isinstance(value, SecureHash):
        return value.bytes.hex()
    if isinstance(value, Amount):
        return f"{value.quantity} {value.token}"
    if isinstance(value, (bytes, bytearray)):
        return "0x" + bytes(value).hex()
    if isinstance(value, datetime.datetime):
        return value.isoformat()
    if isinstance(value, dict):
        return {str(k): to_jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set, frozenset)):
        return [to_jsonable(v) for v in value]
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {f.name: to_jsonable(getattr(value, f.name))
                for f in dataclasses.fields(value)}
    # objects exposing their dataclass-ish shape (e.g. SignedTransaction)
    slots = getattr(value, "__slots__", None)
    if slots:
        return {name: to_jsonable(getattr(value, name)) for name in slots}
    if hasattr(value, "__dict__") and value.__dict__:
        return {k: to_jsonable(v) for k, v in value.__dict__.items()
                if not k.startswith("_")}
    return repr(value)


def to_json(value, indent: int = 2) -> str:
    return json.dumps(to_jsonable(value), indent=indent, sort_keys=False)


def render_yaml(value, indent: int = 0) -> str:
    """A YAML-ish rendering of the JSON-able reduction (the shell's default
    output mode, like the reference's Yaml emitter)."""
    value = to_jsonable(value) if indent == 0 else value
    pad = "  " * indent
    if isinstance(value, dict):
        if not value:
            return f"{pad}{{}}"
        lines = []
        for k, v in value.items():
            if isinstance(v, (dict, list)) and v:
                lines.append(f"{pad}{k}:")
                lines.append(render_yaml(v, indent + 1))
            else:
                lines.append(f"{pad}{k}: {render_yaml(v, -1)}")
        return "\n".join(lines)
    if isinstance(value, list):
        if not value:
            return f"{pad}[]"
        lines = []
        for v in value:
            if isinstance(v, (dict, list)) and v:
                lines.append(f"{pad}-")
                lines.append(render_yaml(v, indent + 1))
            else:
                lines.append(f"{pad}- {render_yaml(v, -1)}")
        return "\n".join(lines)
    if indent == -1:
        return json.dumps(value) if isinstance(value, str) else str(value)
    return f"{pad}{json.dumps(value) if isinstance(value, str) else value}"


# ---------------------------------------------------------------------------
# Parsing: "name: value, name: value" → bound arguments
# ---------------------------------------------------------------------------

_AMOUNT_RE = re.compile(r"^(\d+)(?:\.(\d{1,2}))?\s+([A-Z]{3})$")


def _parse_amount(text: str) -> Amount | None:
    """"100.50 USD" → Amount(10050, USD); None when the shape doesn't match
    (single source of truth for both the annotated and shape-inferred
    conversion paths)."""
    m = _AMOUNT_RE.match(text)
    if not m:
        return None
    whole, cents, code = m.groups()
    quantity = int(whole) * 100 + int((cents or "0").ljust(2, "0"))
    return Amount(quantity, currency(code))


class StringToMethodCallParser:
    """Bind ``name: value`` text to a callable's parameters
    (StringToMethodCallParser.kt:1-225). Values convert by the parameter's
    annotation when present, else by shape: ints, 0x-hex bytes, amounts
    ("100.00 USD"), X.500 names → Party (via the ``party_resolver``),
    quoted strings, bare words."""

    def __init__(self, party_resolver=None):
        self.party_resolver = party_resolver

    # -- value conversion ----------------------------------------------------
    def convert(self, text: str, annotation=None):
        text = text.strip()
        if annotation is not None:
            converted = self._convert_annotated(text, annotation)
            if converted is not None:
                return converted
        if text.lstrip("-").isdigit():
            return int(text)
        if text.startswith("0x"):
            return bytes.fromhex(text[2:])
        amount = _parse_amount(text)
        if amount is not None:
            return amount
        if "=" in text and self.party_resolver is not None:
            party = self.party_resolver(text)
            if party is not None:
                return party
        if text.startswith('"') and text.endswith('"') and len(text) >= 2:
            return text[1:-1]
        if text in ("true", "false"):
            return text == "true"
        return text

    def _convert_annotated(self, text: str, annotation):
        from ..core.identity import Party
        ann = annotation
        if isinstance(ann, str):            # from __future__ annotations
            ann = {"int": int, "str": str, "bytes": bytes,
                   "Amount": Amount, "Party": Party}.get(ann.split(".")[-1])
        if ann is int:
            return int(text)
        if ann is bytes:
            return bytes.fromhex(text[2:] if text.startswith("0x") else text)
        if ann is str:
            return text.strip('"')
        if ann is Amount:
            amount = _parse_amount(text)
            if amount is None:
                raise UnparseableCallException(
                    f"{text!r} is not an amount (want e.g. '100.00 USD')")
            return amount
        if ann is Party:
            party = (self.party_resolver(text)
                     if self.party_resolver is not None else None)
            if party is None:
                raise UnparseableCallException(
                    f"no well-known party named {text!r}")
            return party
        return None

    # -- argument binding ----------------------------------------------------
    @staticmethod
    def split_pairs(text: str) -> list[tuple[str, str]]:
        """Split ``a: 1, b: x, y`` into [(a, "1"), (b, "x, y")] — a comma
        only ends a value when the next chunk looks like ``name:`` (X.500
        names contain commas; the reference solves this with Yaml, we solve
        it with the same lookahead its shell grammar implies)."""
        pairs: list[tuple[str, str]] = []
        key = None
        buf: list[str] = []
        for chunk in text.split(","):
            m = re.match(r"^\s*([A-Za-z_][A-Za-z0-9_]*)\s*:\s*(.*)$", chunk)
            if m and key is not None:
                pairs.append((key, ",".join(buf).strip()))
                key, buf = m.group(1), [m.group(2)]
            elif m and key is None:
                key, buf = m.group(1), [m.group(2)]
            elif key is not None:
                buf.append(chunk)
            else:
                raise UnparseableCallException(
                    f"expected 'name: value' at {chunk.strip()!r}")
        if key is not None:
            pairs.append((key, ",".join(buf).strip()))
        return pairs

    def parse_arguments(self, target, text: str) -> list:
        """Bind the text's named values to ``target``'s constructor/call
        parameters, in declaration order; missing required parameters or
        unknown names raise UnparseableCallException."""
        fn = target.__init__ if inspect.isclass(target) else target
        params = [p for p in inspect.signature(fn).parameters.values()
                  if p.name not in ("self",)
                  and p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)]
        by_name = {p.name: p for p in params}
        given = dict(self.split_pairs(text)) if text.strip() else {}
        unknown = set(given) - set(by_name)
        if unknown:
            raise UnparseableCallException(
                f"unknown parameter(s) {sorted(unknown)}; "
                f"expected {[p.name for p in params]}")
        args = []
        for p in params:
            if p.name in given:
                args.append(self.convert(given[p.name],
                                         p.annotation
                                         if p.annotation is not p.empty
                                         else None))
            elif p.default is not p.empty:
                args.append(p.default)
            else:
                raise UnparseableCallException(
                    f"missing required parameter {p.name!r}; "
                    f"expected {[q.name for q in params]}")
        return args
