"""CordaRPCClient — connect to a node's RPC surface over the TCP plane.

Reference parity: client/rpc CordaRPCClient → proxy of CordaRPCOps
(RPCClient.kt / RPCClientProxyHandler.kt): the client opens its own transport
endpoint, sends framed requests carrying a reply address, correlates
responses by request id, and surfaces server-side exceptions. Flow results
are polled (`start_flow_and_wait`) — the reference's observable stream demux
maps to the feed/snapshot split on this wire.
"""
from __future__ import annotations

import threading
import time
import uuid

from ..core.serialization import deserialize, serialize
from ..network.messaging import TopicSession
from ..network.tcp import TcpMessagingService
from ..node.node import TOPIC_RPC, RpcRequest, RpcResponse


class RPCException(Exception):
    pass


class FlowFailedException(RPCException):
    pass


class CordaRPCClient:
    def __init__(self, host: str, port: int, client_host: str = "127.0.0.1",
                 timeout_s: float = 30.0, tls_ca_directory: str | None = None):
        """``tls_ca_directory``: enable mutual TLS against a node whose plane
        runs the dev CA in that directory (the client auto-provisions its own
        CA-signed certificate there, like any other peer)."""
        self.node_addr = (host, port)
        self.timeout_s = timeout_s
        self._pending: dict[str, object] = {}
        self._cond = threading.Condition()
        name = f"rpc-client-{uuid.uuid4().hex[:8]}"
        tls = None
        if tls_ca_directory is not None:
            import tempfile
            from ..network.tls import TlsConfig
            tls = TlsConfig.dev(tempfile.mkdtemp(prefix="rpc-tls-"), name,
                                tls_ca_directory)
        self._messaging = TcpMessagingService(
            name, client_host, 0, lambda name: self.node_addr, tls=tls)
        self._messaging.add_message_handler(TopicSession(TOPIC_RPC, 1),
                                            self._on_response)
        self.reply_to = f"{client_host}:{self._messaging.port}"

    # -- plumbing ------------------------------------------------------------
    def _on_response(self, msg) -> None:
        resp: RpcResponse = deserialize(msg.data)
        with self._cond:
            self._pending[resp.request_id] = resp
            self._cond.notify_all()

    def call(self, method: str, *args):
        rid = uuid.uuid4().hex
        req = RpcRequest(rid, method, list(args), self.reply_to)
        self._messaging.send(TopicSession(TOPIC_RPC), serialize(req), "node")
        deadline = time.monotonic() + self.timeout_s
        with self._cond:
            while rid not in self._pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RPCException(f"RPC {method} timed out")
                self._cond.wait(timeout=remaining)
            resp = self._pending.pop(rid)
        if resp.error is not None:
            raise RPCException(resp.error)
        return resp.result

    # -- the proxy surface ---------------------------------------------------
    def start_flow(self, flow_name: str, *args) -> str:
        return self.call("start_flow", flow_name, *args)

    def flow_result(self, run_id: str):
        return self.call("flow_result", run_id)

    def start_flow_and_wait(self, flow_name: str, *args,
                            timeout_s: float = 60.0, poll_s: float = 0.2):
        run_id = self.start_flow(flow_name, *args)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status, value = self.flow_result(run_id)
            if status == "done":
                return value
            if status == "failed":
                raise FlowFailedException(value)
            time.sleep(poll_s)
        raise RPCException(f"flow {run_id} did not finish in {timeout_s}s")

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda *args: self.call(name, *args)

    def close(self) -> None:
        self._messaging.stop()
