"""CordaRPCClient — connect to a node's RPC surface over the TCP plane.

Reference parity: client/rpc CordaRPCClient → proxy of CordaRPCOps
(RPCClient.kt / RPCClientProxyHandler.kt): the client opens its own transport
endpoint, sends framed requests carrying a reply address, correlates
responses by request id, and surfaces server-side exceptions.

Observable streaming (RPCClientProxyHandler.kt:1-421 / RPCApi.kt:27-60):
a server method returning a feed comes back as a FeedHandle (server-assigned
feed id + snapshot); subsequent observations are PUSHED to this client's
address and demuxed by id into ``ClientDataFeed`` callbacks/queues — no
polling. ``start_flow_and_wait`` rides a tracked-flow feed: progress steps
and the terminal result arrive as pushes.
"""
from __future__ import annotations

import queue as _queue
import threading
import time
import uuid

from ..core.serialization import deserialize, serialize
from ..network.messaging import TopicSession
from ..network.tcp import TcpMessagingService
from ..node.node import (TOPIC_RPC, FeedHandle, Observation, RpcRequest,
                         RpcResponse)


class RPCException(Exception):
    pass


class FlowFailedException(RPCException):
    pass


class ClientDataFeed:
    """Client half of a streamed feed: snapshot + pushed observations
    (demuxed by the server-assigned feed id)."""

    def __init__(self, client: "CordaRPCClient", feed_id: str, snapshot):
        self._client = client
        self.feed_id = feed_id
        self.snapshot = snapshot
        self.events: "_queue.Queue" = _queue.Queue()
        self._callbacks: list = []

    def subscribe(self, callback) -> None:
        self._callbacks.append(callback)

    def next_event(self, timeout_s: float = 30.0):
        """Block for the next pushed observation."""
        try:
            return self.events.get(timeout=timeout_s)
        except _queue.Empty:
            raise RPCException(
                f"no observation on feed {self.feed_id} in {timeout_s}s")

    def _on_observation(self, payload) -> None:
        self.events.put(payload)
        for cb in list(self._callbacks):
            try:
                cb(payload)
            except Exception:
                pass

    def close(self) -> None:
        self._client._close_feed(self)


class CordaRPCClient:
    def __init__(self, host: str, port: int, client_host: str = "127.0.0.1",
                 timeout_s: float = 30.0, tls_ca_directory: str | None = None):
        """``tls_ca_directory``: enable mutual TLS against a node whose plane
        runs the dev CA in that directory (the client auto-provisions its own
        CA-signed certificate there, like any other peer)."""
        self.node_addr = (host, port)
        self.timeout_s = timeout_s
        self._pending: dict[str, object] = {}
        self._cond = threading.Condition()
        name = f"rpc-client-{uuid.uuid4().hex[:8]}"
        tls = None
        if tls_ca_directory is not None:
            import tempfile
            from ..network.tls import TlsConfig
            tls = TlsConfig.dev(tempfile.mkdtemp(prefix="rpc-tls-"), name,
                                tls_ca_directory)
        self._messaging = TcpMessagingService(
            name, client_host, 0, lambda name: self.node_addr, tls=tls)
        self._messaging.add_message_handler(TopicSession(TOPIC_RPC, 1),
                                            self._on_response)
        self._feeds: dict[str, ClientDataFeed] = {}
        self._orphan_observations: dict[str, list] = {}
        self._messaging.add_message_handler(TopicSession(TOPIC_RPC, 2),
                                            self._on_observation)
        self.reply_to = f"{client_host}:{self._messaging.port}"

    # -- plumbing ------------------------------------------------------------
    def _on_response(self, msg) -> None:
        resp: RpcResponse = deserialize(msg.data)
        with self._cond:
            self._pending[resp.request_id] = resp
            self._cond.notify_all()

    def _on_observation(self, msg) -> None:
        obs: Observation = deserialize(msg.data)
        with self._cond:
            feed = self._feeds.get(obs.feed_id)
            if feed is None or obs.feed_id in self._orphan_observations:
                # observation raced ahead of the FeedHandle response (or a
                # replay of earlier parked observations is still running) —
                # park it so delivery order matches push order
                self._orphan_observations.setdefault(
                    obs.feed_id, []).append(obs.payload)
                return
        feed._on_observation(obs.payload)

    def call(self, method: str, *args):
        rid = uuid.uuid4().hex
        req = RpcRequest(rid, method, list(args), self.reply_to)
        self._messaging.send(TopicSession(TOPIC_RPC), serialize(req), "node")
        deadline = time.monotonic() + self.timeout_s
        with self._cond:
            while rid not in self._pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RPCException(f"RPC {method} timed out")
                self._cond.wait(timeout=remaining)
            resp = self._pending.pop(rid)
        if resp.error is not None:
            raise RPCException(resp.error)
        if isinstance(resp.result, FeedHandle):
            feed = ClientDataFeed(self, resp.result.feed_id,
                                  resp.result.snapshot)
            with self._cond:
                self._feeds[feed.feed_id] = feed
                had_orphans = feed.feed_id in self._orphan_observations
            # replay parked observations IN ORDER: new pushes keep parking
            # behind them (see _on_observation) until the list drains empty
            while had_orphans:
                with self._cond:
                    parked = self._orphan_observations.get(feed.feed_id, [])
                    if not parked:
                        self._orphan_observations.pop(feed.feed_id, None)
                        break
                    payload = parked.pop(0)
                feed._on_observation(payload)
            return feed
        return resp.result

    def _close_feed(self, feed: ClientDataFeed) -> None:
        self._feeds.pop(feed.feed_id, None)
        try:
            self.call("unsubscribe_feed", feed.feed_id)
        except RPCException:
            pass

    # -- the proxy surface ---------------------------------------------------
    def start_flow(self, flow_name: str, *args) -> str:
        return self.call("start_flow", flow_name, *args)

    def flow_result(self, run_id: str):
        return self.call("flow_result", run_id)

    def start_tracked_flow(self, flow_name: str, *args) -> ClientDataFeed:
        """startTrackedFlowDynamic: the returned feed's snapshot is the run
        id; pushed events are ("progress", step) and the terminal
        ("removed", [status, value])."""
        return self.call("start_flow_tracked", flow_name, *args)

    def start_flow_and_wait(self, flow_name: str, *args,
                            timeout_s: float = 60.0, poll_s: float = 0.2):
        """Start a flow and wait for its result — PUSHED over the tracked
        feed (no polling); falls back to result polling against servers
        without the streaming protocol."""
        try:
            feed = self.start_tracked_flow(flow_name, *args)
        except RPCException:
            feed = None
        if isinstance(feed, ClientDataFeed):
            deadline = time.monotonic() + timeout_s
            try:
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise RPCException(
                            f"flow {feed.snapshot} did not finish in "
                            f"{timeout_s}s")
                    event = feed.next_event(timeout_s=remaining)
                    if event[0] == "removed":
                        status, value = event[1]
                        if status == "failed":
                            raise FlowFailedException(value)
                        return value
            finally:
                feed.close()
        run_id = self.start_flow(flow_name, *args)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status, value = self.flow_result(run_id)
            if status == "done":
                return value
            if status == "failed":
                raise FlowFailedException(value)
            time.sleep(poll_s)
        raise RPCException(f"flow {run_id} did not finish in {timeout_s}s")

    def wait_until_registered_with_network_map(self,
                                               timeout_s: float = 60.0):
        """Genuine FUTURE semantics (CordaRPCOps.kt:275 returns a
        ListenableFuture): completes when the node reports itself
        registered, with the initial probe short-circuiting an
        already-registered node. Network-map pushes ACCELERATE a dedicated
        waiter thread, which does all the re-probing itself: an RPC from
        inside the feed callback would deadlock (callbacks run on the one
        messaging thread that also delivers RPC responses), and the single
        setter thread means no missed-event or set_result races."""
        from concurrent.futures import Future as _Future
        fut: _Future = _Future()
        if self.call("wait_until_registered_with_network_map"):
            fut.set_result(True)
            return fut
        feed = self.network_map_feed()
        kick = threading.Event()
        feed.subscribe(lambda _event: kick.set())

        def waiter():
            deadline = time.monotonic() + timeout_s
            try:
                while True:
                    if self.call("wait_until_registered_with_network_map"):
                        fut.set_result(True)
                        return
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        fut.set_exception(RPCException(
                            "not registered with the network map in "
                            f"{timeout_s}s"))
                        return
                    # push-accelerated, 1s-bounded poll: a change pushed
                    # BEFORE the subscription landed is still caught
                    kick.wait(timeout=min(remaining, 1.0))
                    kick.clear()
            finally:
                try:
                    feed.close()
                except Exception:
                    pass
        threading.Thread(target=waiter, daemon=True,
                         name="rpc-registration-wait").start()
        return fut

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda *args: self.call(name, *args)

    def close(self) -> None:
        for feed in list(self._feeds.values()):
            try:
                feed.close()
            except Exception:
                pass
        self._messaging.stop()
