"""Per-request lifecycle timelines for the verifier fleet.

Every out-of-process verification request leaves an append-only event
trail — submitted → routed{worker, reason, est-load vector} → parked →
stolen{victim} → dispatched{worker, batch} → resolved / requeued — kept in
a bounded structure (oldest REQUEST evicted whole, never a partial
timeline) and exposed two ways:

- ``GET /debug/requests`` (tools/webserver.py) returns the newest
  timelines as JSON, so "why did request 841 land on w3?" is answerable
  after the fact with the router's reason and the estimated-load vector it
  saw at decision time.
- every append also emits a ``request.<event>`` jlog line carrying the
  request's trace id (slog.py), so the timeline correlates with /traces
  and survives the ring's bounded retention in the log stream.

The log is always on: appends are O(1) dict/list work under one lock and
the jlog call is gated on the logger level, so the untraced hot path pays
a few dict writes per request, not serialization.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict

from .slog import _trace_ids, jlog

log = logging.getLogger(__name__)

#: Events that end a request's lifecycle — used by chaos tests to assert
#: exactly-once terminal resolution even across steals and crash-detaches.
TERMINAL_EVENTS = frozenset({"resolved"})


class RequestLog:
    """Bounded append-only map of verification_id → event list."""

    def __init__(self, capacity: int = 2048):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._timelines: "OrderedDict[int, list[dict]]" = OrderedDict()
        #: vids whose timeline already carries a TERMINAL_EVENTS event, in
        #: the order they resolved — the eviction queue of first resort.
        self._terminal: "OrderedDict[int, None]" = OrderedDict()
        self.dropped = 0   # whole timelines evicted by the bound

    def append(self, vid: int, event: str, trace=None, **fields) -> None:
        rec: dict = {"event": event, "t": round(time.time(), 6)}
        trace_id, _sid = _trace_ids(trace)
        if trace_id is not None:
            rec["trace_id"] = trace_id
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            tl = self._timelines.get(vid)
            if tl is None:
                while len(self._timelines) >= self.capacity:
                    self._evict_one_locked()
                tl = self._timelines[vid] = []
            tl.append(rec)
            if event in TERMINAL_EVENTS:
                self._terminal[vid] = None
        jlog(log, f"request.{event}", ctx=trace, vid=vid, **fields)

    def _evict_one_locked(self) -> None:
        """Evict one whole timeline, preferring requests that already
        resolved. Blind FIFO eviction could drop an in-flight request
        while resolved ones inserted later survive; its later events
        would then re-open a fresh partial timeline, leaking an extra
        entry per churn cycle and losing the routing history the debug
        surface exists for."""
        while self._terminal:
            vid, _ = self._terminal.popitem(last=False)
            if self._timelines.pop(vid, None) is not None:
                self.dropped += 1
                return
        self._timelines.popitem(last=False)
        self.dropped += 1

    def __len__(self) -> int:
        """Live timeline count — the bounded size the resource accounting
        plane probes (``Requests.Timelines``); ``dropped`` is the matching
        cumulative eviction counter it differentiates into a rate."""
        with self._lock:
            return len(self._timelines)

    def timeline(self, vid: int) -> list[dict]:
        with self._lock:
            return list(self._timelines.get(vid, ()))

    def events(self, vid: int) -> list[str]:
        return [e["event"] for e in self.timeline(vid)]

    def terminal_count(self, vid: int) -> int:
        """How many terminal (resolution) events this request has — the
        exactly-once invariant says 1 for every completed request."""
        return sum(1 for e in self.timeline(vid)
                   if e["event"] in TERMINAL_EVENTS)

    def snapshot(self, limit: int | None = None) -> dict:
        """Newest-first {vid: [events...]} — the /debug/requests payload.
        ``limit`` caps the number of REQUESTS returned (not events)."""
        with self._lock:
            items = list(self._timelines.items())
        items.reverse()
        if limit is not None:
            items = items[:max(0, limit)]
        return {str(vid): list(tl) for vid, tl in items}
