"""Service-level objectives over the ledger commit path.

The ledger harness (observability/ledger_harness.py) turns the commit
path into a stream of per-transaction outcomes: did it commit, and how
long from *intended* send to vault write. This module folds that stream
into the two SLO shapes operators actually page on (the SRE-workbook
model):

- an **availability** objective — the fraction of submitted transactions
  that commit must stay above ``target`` (e.g. 99.9%);
- a **latency** objective — the fraction of transactions finishing under
  ``latency_ms`` must stay above ``target`` (a p99-latency objective is
  ``target=0.99`` with ``latency_ms`` at the promised bound; a slow
  commit burns this budget exactly like a failed one burns availability).

Each objective keeps a sliding multi-window event ring and derives:

- ``error budget``: the allowed bad fraction is ``1 - target``; remaining
  budget is what's left of it over the LONGEST window, as a percentage
  (100 = untouched, 0 = fully burned).
- ``burn rate``: (observed bad fraction) / (allowed bad fraction) per
  window. 1.0 means burning exactly at budget; 14.4 means the whole
  budget would be gone in 1/14.4 of the period.
- **multi-window alerts**: a *page* fires when BOTH the short and long
  window burn at ``fast_burn`` or above (a real, ongoing fire — the short
  window keeps the alert fresh, the long window keeps it from flapping);
  a *ticket* fires when the long window alone burns at ``slow_burn`` or
  above (a slow leak that will exhaust the budget before anyone looks).

``publish()`` exports the gauges through a MetricRegistry; ``status()``
is the ``/readyz`` payload — the node surfaces it as ``degraded.slo``
when any alert is active (degraded, not unready: the node still serves,
but it is eating its error budget).

The clock is injectable so tests drive the windows deterministically.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass


@dataclass(frozen=True)
class SLObjective:
    """One objective: name, target fraction, optional latency bound.

    ``latency_ms is None`` → availability (bad = failed);
    otherwise → latency (bad = failed OR slower than ``latency_ms``).
    """

    name: str
    target: float               # e.g. 0.999 → 0.1% error budget
    latency_ms: float | None = None

    @property
    def budget_fraction(self) -> float:
        return max(1e-9, 1.0 - self.target)

    def is_bad(self, ok: bool, latency_s: float | None) -> bool:
        if not ok:
            return True
        if self.latency_ms is None:
            return False
        return latency_s is not None and latency_s * 1000.0 > self.latency_ms


#: Harness defaults: three nines of commit availability, and a p99-style
#: latency objective (99% under 1s end-to-end, measured from INTENDED send).
DEFAULT_OBJECTIVES = (
    SLObjective("availability", 0.999),
    SLObjective("latency_p99", 0.99, latency_ms=1000.0),
)


class SLOTracker:
    """Sliding-window error-budget accounting for a stream of outcomes."""

    def __init__(self, objectives=DEFAULT_OBJECTIVES,
                 windows_s: tuple = (60.0, 300.0),
                 clock=time.monotonic, capacity: int = 65536,
                 fast_burn: float = 14.4, slow_burn: float = 6.0):
        if len(windows_s) < 2 or sorted(windows_s) != list(windows_s):
            raise ValueError("windows_s must be ascending and have >= 2 "
                             "entries (short, ..., long)")
        self.objectives = tuple(objectives)
        self.windows_s = tuple(float(w) for w in windows_s)
        self.clock = clock
        self.capacity = capacity
        self.fast_burn = fast_burn
        self.slow_burn = slow_burn
        self._lock = threading.Lock()
        # (t, ok, latency_s) — bounded by capacity AND the longest window
        self._events: deque = deque(maxlen=capacity)

    # -- recording -----------------------------------------------------------
    def record(self, ok: bool, latency_s: float | None = None) -> None:
        now = self.clock()
        with self._lock:
            self._events.append((now, bool(ok), latency_s))
            horizon = now - self.windows_s[-1]
            while self._events and self._events[0][0] < horizon:
                self._events.popleft()

    # -- derived views -------------------------------------------------------
    def _window_counts(self, objective: SLObjective, now: float) -> dict:
        """{window_s: (total, bad)} under one objective's bad predicate."""
        with self._lock:
            events = list(self._events)
        out = {}
        for w in self.windows_s:
            cutoff = now - w
            total = bad = 0
            for t, ok, lat in events:
                if t < cutoff:
                    continue
                total += 1
                if objective.is_bad(ok, lat):
                    bad += 1
            out[w] = (total, bad)
        return out

    def burn_rates(self, objective: SLObjective, now: float | None = None
                   ) -> dict:
        """{window_s: burn_rate}; 0.0 with no traffic in the window."""
        now = self.clock() if now is None else now
        rates = {}
        for w, (total, bad) in self._window_counts(objective, now).items():
            frac = (bad / total) if total else 0.0
            rates[w] = frac / objective.budget_fraction
        return rates

    def error_budget_pct(self, objective: SLObjective,
                         now: float | None = None) -> float:
        """Remaining budget over the LONGEST window, 0..100."""
        now = self.clock() if now is None else now
        total, bad = self._window_counts(objective, now)[self.windows_s[-1]]
        if not total:
            return 100.0
        burned = (bad / total) / objective.budget_fraction
        return round(max(0.0, 1.0 - burned) * 100.0, 4)

    def alerts(self, now: float | None = None) -> list:
        """Active multi-window burn alerts, worst first."""
        now = self.clock() if now is None else now
        out = []
        short_w, long_w = self.windows_s[0], self.windows_s[-1]
        for obj in self.objectives:
            rates = self.burn_rates(obj, now)
            if min(rates[short_w], rates[long_w]) >= self.fast_burn:
                out.append({"objective": obj.name, "severity": "page",
                            "burn_rate": round(rates[short_w], 2),
                            "windows_s": [short_w, long_w]})
            elif rates[long_w] >= self.slow_burn:
                out.append({"objective": obj.name, "severity": "ticket",
                            "burn_rate": round(rates[long_w], 2),
                            "windows_s": [long_w]})
        out.sort(key=lambda a: -a["burn_rate"])
        return out

    def status(self, now: float | None = None) -> dict:
        """The /readyz ``degraded.slo`` payload (also /api surfaces)."""
        now = self.clock() if now is None else now
        alerts = self.alerts(now)
        objectives = {}
        for obj in self.objectives:
            rates = self.burn_rates(obj, now)
            objectives[obj.name] = {
                "target": obj.target,
                "latency_ms": obj.latency_ms,
                "error_budget_pct": self.error_budget_pct(obj, now),
                "burn_rates": {f"{int(w)}s": round(r, 3)
                               for w, r in rates.items()},
            }
        return {"alerting": bool(alerts), "alerts": alerts,
                "objectives": objectives}

    # -- metrics export ------------------------------------------------------
    def publish(self, registry) -> None:
        """Gauges on a MetricRegistry: per-objective remaining budget and
        short/long burn rates, plus an overall alerting flag — read lazily
        at snapshot time, so /metrics always shows the current windows."""
        short_w, long_w = self.windows_s[0], self.windows_s[-1]
        for obj in self.objectives:
            registry.gauge(
                f"SLO.{obj.name}.ErrorBudgetPct",
                lambda o=obj: self.error_budget_pct(o))
            registry.gauge(
                f"SLO.{obj.name}.BurnRateShort",
                lambda o=obj: round(self.burn_rates(o)[short_w], 4))
            registry.gauge(
                f"SLO.{obj.name}.BurnRateLong",
                lambda o=obj: round(self.burn_rates(o)[long_w], 4))
        registry.gauge("SLO.Alerting", lambda: int(bool(self.alerts())))
