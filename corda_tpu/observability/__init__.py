"""End-to-end observability for the TPU verification pipeline.

Three pieces (docs/OBSERVABILITY.md):

- tracing.py — span tracer with explicit SpanContext propagation across
  the flow state machine, verifier service, SignatureBatcher threads,
  messaging, notary, and raft. No-op by default (``NOOP_TRACER``);
  ``enable_tracing()`` turns it on.
- ring.py — the bounded in-memory span buffer behind a live tracer, with
  JSONL export and the /traces endpoint's query surface.
- stages.py — per-stage (prep/dispatch/finish) percentile flattening for
  bench.py's JSON artifact.

The Histogram metric type itself lives in utils/metrics.py with the rest
of the registry.
"""
from .ring import SpanRing
from .stages import STAGE_METRICS, stage_percentiles
from .tracing import (NOOP_SPAN, NOOP_TRACER, NoopTracer, Span, SpanContext,
                      Tracer, disable_tracing, enable_tracing, get_tracer,
                      set_tracer)

__all__ = [
    "NOOP_SPAN", "NOOP_TRACER", "NoopTracer", "Span", "SpanContext",
    "SpanRing", "STAGE_METRICS", "Tracer", "disable_tracing",
    "enable_tracing", "get_tracer", "set_tracer", "stage_percentiles",
]
