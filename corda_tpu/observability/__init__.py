"""End-to-end observability for the TPU verification pipeline.

Three pieces (docs/OBSERVABILITY.md):

- tracing.py — span tracer with explicit SpanContext propagation across
  the flow state machine, verifier service, SignatureBatcher threads,
  messaging, notary, and raft. No-op by default (``NOOP_TRACER``);
  ``enable_tracing()`` turns it on.
- ring.py — the bounded in-memory span buffer behind a live tracer, with
  JSONL export and the /traces endpoint's query surface.
- stages.py — per-stage (prep/dispatch/finish) percentile flattening for
  bench.py's JSON artifact.
- profiling.py — the kernel flight recorder: compile-cache accounting,
  device dispatch/wait wall time, batch occupancy, prep/device overlap;
  always-on, exported through /metrics and /debug/profile.
- slog.py — structured JSON log lines correlated by trace_id.
- federation.py — node-side accumulator for worker metric snapshots
  (per-worker labeled families + Fleet.agg.* merges on /metrics).
- lifecycle.py — bounded per-request event timelines (/debug/requests).
- slo.py — availability/latency objectives, error budgets, multi-window
  burn-rate alerts (surfaced on /readyz as ``degraded.slo``).
- ledger_harness.py — open-loop end-to-end commit-path load scenario
  (bench.py --ledger / tools/scenario.py).
- critpath.py — tail forensics: critical-path (blocking chain) extraction
  over stitched span trees, wait_kind blame attribution, the
  ``ledger_critpath_*`` artifact fields and /debug/critpath payload.
- timeseries.py — the retained time-series plane: memory-bounded,
  downsampled history (fine recent rings cascading into coarse older
  rings) behind /api/timeseries and the consensus_stat CLI.
- consensus_obs.py — the consensus observatory: raft stats pooling
  (/debug/raft), Raft.* metric families, growth watchdogs, and the
  ``ledger_raft_*`` artifact fields.
- resprof.py — the resource accounting plane (per-structure size probes
  → ``Resource.*`` series → ``bounded | growing | leaking`` verdicts)
  and the subsystem CPU sampling profiler.
- soak.py — drift-gated endurance runs: recurring chaos, per-phase
  committed-rate/tail/budget series, mid-run invariant re-checks, the
  ``soak_*`` artifact fields and /debug/soak payload.

The Histogram metric type itself lives in utils/metrics.py with the rest
of the registry.
"""
from .consensus_obs import (ATTRIBUTION_COMPONENTS, GrowthWatch,
                            install_raft_collector, ledger_raft_fields,
                            raft_report, sample_timeseries)
from .critpath import (COMPONENTS, WAIT_KINDS, aggregate_critpaths,
                       component_of, critical_path, critpath_report,
                       flow_kind, ledger_critpath_fields)
from .federation import FleetMetricsFederation
from .lifecycle import RequestLog
from .profiling import (KernelProfiler, OverlapTracker, get_profiler,
                        set_profiler)
from .resprof import (COMMIT_PATH_COMPONENTS, CPU_COMPONENTS,
                      ResourceRegistry, SubsystemProfiler, classify_stack,
                      get_resources, leak_verdict, process_rss_bytes,
                      set_resources, theil_sen_slope)
from .ring import SpanRing
from .slog import jlog
from .soak import SoakConfig, SoakObserver, run_soak, soak_report
from .slo import DEFAULT_OBJECTIVES, SLObjective, SLOTracker
from .stages import (LEDGER_STAGE_METRICS, STAGE_METRICS,
                     ledger_stage_percentiles, stage_percentiles)
from .timeseries import (TimeSeries, TimeSeriesStore, get_timeseries,
                         set_timeseries)
from .tracing import (NOOP_SPAN, NOOP_TRACER, NoopTracer, Span, SpanContext,
                      Tracer, disable_tracing, enable_tracing, get_tracer,
                      make_span_dict, set_tracer)

__all__ = [
    "ATTRIBUTION_COMPONENTS", "COMMIT_PATH_COMPONENTS", "COMPONENTS",
    "CPU_COMPONENTS", "DEFAULT_OBJECTIVES",
    "FleetMetricsFederation", "GrowthWatch",
    "KernelProfiler", "LEDGER_STAGE_METRICS", "NOOP_SPAN", "NOOP_TRACER",
    "NoopTracer", "OverlapTracker", "RequestLog", "ResourceRegistry",
    "SLObjective",
    "SLOTracker", "SoakConfig", "SoakObserver", "Span", "SpanContext",
    "SpanRing", "STAGE_METRICS", "SubsystemProfiler",
    "TimeSeries", "TimeSeriesStore",
    "Tracer", "WAIT_KINDS", "aggregate_critpaths", "classify_stack",
    "component_of",
    "critical_path", "critpath_report", "disable_tracing",
    "enable_tracing", "flow_kind", "get_profiler", "get_resources",
    "get_timeseries",
    "get_tracer", "install_raft_collector", "jlog", "leak_verdict",
    "ledger_critpath_fields", "ledger_raft_fields",
    "ledger_stage_percentiles", "make_span_dict", "process_rss_bytes",
    "raft_report", "run_soak",
    "sample_timeseries", "set_profiler", "set_resources",
    "set_timeseries", "set_tracer", "soak_report",
    "stage_percentiles", "theil_sen_slope",
]
