"""Consensus observatory: raft introspection pooling, shard heat rollup,
growth watchdogs, and the Raft.* metric families.

critpath blames ``raft.commit``/``raft.leaderless`` as the dominant tail
component (LEDGER_r03/r04) but nothing inside the consensus tier says
*why* — election churn vs per-append fsync vs replication RTT vs apply.
The raft nodes now self-attribute every committed entry
(``RaftNode.stats()`` / ``attribution_samples()``); this module is the
read side: it pools those per-node surfaces into one per-group report
(``raft_report`` → /debug/raft and fleetstat), flattens them into the
``ledger_raft_*`` bench artifact fields (benchguard-locked, with the
attribution-sum validity probe), installs the labeled ``Raft.*`` metric
families on a registry, feeds the retained time-series plane
(timeseries.py), and watches the two known growth hazards
(``Raft.LogEntries``, ``CoordinatorLog.Bytes``) for doubling within a
run. With compaction landed (ISSUE 20) those gauges are expected to
sawtooth: the watchdog resets its doubling baseline after each observed
shrink (``consensus.growth.compacted``) so a legitimate post-compaction
climb is measured from the new floor instead of warning spuriously.

Everything here is defensive: a node whose ``stats()`` is missing or
malformed contributes nothing rather than an exception — mixed
python/native fleets report whatever each implementation can attribute,
absent fields stay absent (never fabricated zeros).
"""
from __future__ import annotations

import logging
import math

from .slog import jlog

log = logging.getLogger("corda_tpu.consensus_obs")

__all__ = [
    "ATTRIBUTION_COMPONENTS", "GrowthWatch", "install_raft_collector",
    "ledger_raft_fields", "pool_attribution", "pooled_percentiles",
    "raft_report", "sample_timeseries",
]

#: Per-entry commit attribution components, pipeline order. Their sum
#: telescopes to submit→apply-end by construction (contiguous perf_counter
#: clocks in RaftNode._record_attribution) — the conservation property the
#: bench validity probe locks against raft_commit_seconds.
ATTRIBUTION_COMPONENTS = ("append_wait", "fsync", "replicate", "apply")


def _num(v):
    """float(v) for real numbers, else None (bools excluded)."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v) if math.isfinite(v) else None


def _pctl(sorted_samples, q: float) -> float:
    """Nearest-rank percentile over an ascending-sorted sample list."""
    if not sorted_samples:
        return 0.0
    rank = min(len(sorted_samples) - 1,
               max(0, int(math.ceil(q * len(sorted_samples))) - 1))
    return sorted_samples[rank]


def pool_attribution(nodes) -> dict:
    """Merge ``attribution_samples()`` across raft nodes (samples live on
    whichever node was leader when an entry committed, so a group's
    distribution is the union over its replicas). Nodes without the
    surface (native core) contribute nothing. Returns
    {component: [seconds, ...]} including "total"."""
    pooled: dict = {}
    for node in nodes:
        fn = getattr(node, "attribution_samples", None)
        if not callable(fn):
            continue
        try:
            samples = fn()
        except Exception:
            continue
        if not isinstance(samples, dict):
            continue
        for comp, values in samples.items():
            good = [v for v in (_num(x) for x in values) if v is not None]
            if good:
                pooled.setdefault(comp, []).extend(good)
    return pooled


def pooled_percentiles(pooled: dict) -> dict:
    """{component: {"n", "p50_ms", "p99_ms", "mean_ms"}} over pooled
    attribution samples; components with no samples are absent."""
    out = {}
    for comp, values in pooled.items():
        if not values:
            continue
        ordered = sorted(values)
        out[comp] = {
            "n": len(ordered),
            "p50_ms": _pctl(ordered, 0.50) * 1000.0,
            "p99_ms": _pctl(ordered, 0.99) * 1000.0,
            "mean_ms": sum(ordered) / len(ordered) * 1000.0,
        }
    return out


def _is_leader(stats: dict) -> bool:
    """Role match tolerant of case (raft.py uses "leader", an external
    payload may carry "LEADER")."""
    return str(stats.get("role", "")).lower() == "leader"


def _node_stats(node) -> dict | None:
    """One node's ``stats()``, or None when absent/malformed."""
    fn = getattr(node, "stats", None)
    if not callable(fn):
        return None
    try:
        stats = fn()
    except Exception:
        return None
    return stats if isinstance(stats, dict) else None


def raft_report(groups: dict, sharded=None) -> dict:
    """The /debug/raft payload. ``groups`` maps a group label (e.g. "s0")
    to its list of raft nodes (python or native, mixed is fine)::

        {"groups": {label: {"nodes": [stats...], "leader": stats|None,
                            "log_entries": int, "elections_total": int,
                            "attribution": {...}}},
         "shards": heat_stats()|None}

    Per group, ``leader`` is the stats dict of the node reporting
    role == "LEADER" (None during an election), ``log_entries`` is the
    max over replicas, and ``attribution`` pools every replica's exact
    samples (absent when no node can attribute — native parity rule).
    """
    out_groups = {}
    for label, nodes in sorted((groups or {}).items()):
        node_stats = [s for s in (_node_stats(n) for n in nodes)
                      if s is not None]
        leader = next((s for s in node_stats if _is_leader(s)), None)
        entry: dict = {
            "nodes": node_stats,
            "leader": leader,
            "log_entries": max(
                [v for v in (_num(s.get("log_entries"))
                             for s in node_stats) if v is not None],
                default=0),
            "elections_total": int(sum(
                v for v in (_num(s.get("elections_total"))
                            for s in node_stats) if v is not None)),
            # compaction surfaces (ISSUE 20): typed-default ints — a
            # native-only group reports zeros here (its per-NODE stats
            # stay honestly absent; the group rollup is an artifact
            # surface, so it keeps the always-present discipline)
            "snapshot_index": int(max(
                [v for v in (_num(s.get("snapshot_index"))
                             for s in node_stats) if v is not None],
                default=0)),
            "snapshots_taken": int(sum(
                v for v in (_num(s.get("snapshots_taken"))
                            for s in node_stats) if v is not None)),
            "installs_sent": int(sum(
                v for v in (_num(s.get("installs_sent"))
                            for s in node_stats) if v is not None)),
            "installs_received": int(sum(
                v for v in (_num(s.get("installs_received"))
                            for s in node_stats) if v is not None)),
            "snapshot_bytes": int(max(
                [v for v in (_num(s.get("snapshot_bytes"))
                             for s in node_stats) if v is not None],
                default=0)),
        }
        attribution = pooled_percentiles(pool_attribution(nodes))
        if attribution:
            entry["attribution"] = attribution
        out_groups[label] = entry
    report = {"groups": out_groups}
    if sharded is not None:
        try:
            report["shards"] = sharded.heat_stats()
        except Exception:
            report["shards"] = None
    return report


# -- Raft.* metric families ---------------------------------------------------

def install_raft_collector(metrics, groups_fn) -> None:
    """Register a collector on ``metrics`` emitting labeled ``Raft.*``
    gauge families per consensus group. ``groups_fn`` is a zero-arg
    callable returning the same {label: [nodes]} map raft_report takes
    (a callable so group membership may change under resharding). Fields
    a node cannot attribute are simply absent from the snapshot."""

    def collect() -> dict:
        out: dict = {}

        def emit(family: str, label: str, value) -> None:
            v = _num(value)
            if v is None:
                return
            # gauge_fn: the value-only gauge shape — prometheus_text
            # renders a plain ``_value`` sample (a full "gauge" snapshot
            # carries a high-water ``max`` field these collectors don't)
            out[f'{family}{{group="{label}"}}'] = {
                "type": "gauge_fn", "family": family,
                "labels": {"group": label}, "value": v}

        for label, nodes in (groups_fn() or {}).items():
            node_stats = [s for s in (_node_stats(n) for n in nodes)
                          if s is not None]
            if not node_stats:
                continue
            leader = next((s for s in node_stats if _is_leader(s)), None)
            emit("Raft.LogEntries", label,
                 max([v for v in (_num(s.get("log_entries"))
                                  for s in node_stats) if v is not None],
                     default=0))
            emit("Raft.Elections", label,
                 sum(v for v in (_num(s.get("elections_total"))
                                 for s in node_stats) if v is not None))
            # compaction family (ISSUE 20): absent-not-zero — emitted only
            # when at least one replica actually reports the field (the
            # native core does not)
            snap_idx = [v for v in (_num(s.get("snapshot_index"))
                                    for s in node_stats) if v is not None]
            if snap_idx:
                emit("Raft.SnapshotIndex", label, max(snap_idx))
            snaps = [v for v in (_num(s.get("snapshots_taken"))
                                 for s in node_stats) if v is not None]
            if snaps:
                emit("Raft.SnapshotsTaken", label, sum(snaps))
            installs = [v for v in (_num(s.get("installs_sent"))
                                    for s in node_stats) if v is not None]
            if installs:
                emit("Raft.InstallsSent", label, sum(installs))
            snap_bytes = [v for v in (_num(s.get("snapshot_bytes"))
                                      for s in node_stats) if v is not None]
            if snap_bytes:
                emit("Raft.SnapshotBytes", label, max(snap_bytes))
            if leader is not None:
                emit("Raft.CommitIndex", label, leader.get("commit_index"))
                emit("Raft.Term", label, leader.get("term"))
                emit("Raft.LeaderTenureSeconds", label,
                     leader.get("leader_tenure_s"))
                lag = leader.get("peer_lag")
                if isinstance(lag, dict) and lag:
                    vals = [v for v in (_num(x) for x in lag.values())
                            if v is not None]
                    if vals:
                        emit("Raft.ReplLagMax", label, max(vals))
                attrib = leader.get("attribution")
                if isinstance(attrib, dict):
                    fsync = attrib.get("fsync")
                    if isinstance(fsync, dict):
                        emit("Raft.FsyncP99Ms", label,
                             fsync.get("p99_ms"))
                    repl = attrib.get("replicate")
                    if isinstance(repl, dict):
                        emit("Raft.ReplicateP99Ms", label,
                             repl.get("p99_ms"))
        return out

    metrics.add_collector(collect)


# -- growth watchdogs ---------------------------------------------------------

class GrowthWatch:
    """Doubling detector for monotone soak gauges (Raft.LogEntries,
    CoordinatorLog.Bytes). The first observation of a series (above a
    noise floor) is its baseline; every time the value reaches 2× the
    last warned level it emits ONE jlog WARNING and re-arms at the new
    level — so a log growing without bound warns at 2×, 4×, 8×… instead
    of spamming every sample."""

    def __init__(self, logger=None, floor: float = 1024.0):
        self.floor = floor
        self.warnings = 0        # doubling warnings fired this run
        self.compactions = 0     # baseline resets after observed shrinks
        self._log = logger if logger is not None else log
        self._armed: dict = {}   # name -> level the next warning fires at 2×

    def observe(self, name: str, value) -> bool:
        """Feed one sample; returns True when a doubling warning fired.

        A sample well BELOW the armed level means the gauge was compacted
        (raft log truncation / CoordinatorLog GC): the doubling baseline
        resets to the post-compaction floor so the next legitimate 2× is
        measured from there — without this, a sawtoothing log would warn
        on every recovery climb (the ISSUE 20 false-alarm fix). The 0.9
        factor is hysteresis: leader churn can wobble a max-over-replicas
        gauge a few percent without any compaction happening."""
        v = _num(value)
        if v is None:
            return False
        level = self._armed.get(name)
        if level is not None and v < 0.9 * level:
            self.compactions += 1
            if v < self.floor:
                self._armed.pop(name, None)
            else:
                self._armed[name] = v
            jlog(self._log, "consensus.growth.compacted",
                 level=logging.INFO, gauge=name, value=v, previous=level,
                 reclaimed=round(level - v, 2))
            return False
        if v < self.floor:
            return False
        if level is None:
            self._armed[name] = v
            return False
        if v < 2.0 * level:
            return False
        self._armed[name] = v
        self.warnings += 1
        jlog(self._log, "consensus.growth.doubled",
             level=logging.WARNING, gauge=name, value=v, previous=level,
             factor=round(v / level, 2))
        return True

    def observe_many(self, values: dict) -> int:
        return sum(1 for name, v in (values or {}).items()
                   if self.observe(name, v))


# -- time-series + bench artifact flattening ----------------------------------

def sample_timeseries(store, groups: dict, sharded=None,
                      watch: GrowthWatch | None = None,
                      t: float | None = None, resources=None) -> dict:
    """One periodic sampling tick: record the soak-relevant consensus
    gauges into the retained time-series plane and (optionally) feed the
    growth watchdog. Returns {series name: value} for what was recorded.

    ``resources`` is an optional :class:`~.resprof.ResourceRegistry`:
    when given, every structure registered with the resource accounting
    plane is sampled in the same tick (``Resource.*`` series) and fed
    through the SAME watchdog — any registered probe gets doubling
    warnings for free, while the two historical hazards below keep their
    exact jlog series names (`Raft.LogEntries{...}`/`CoordinatorLog.Bytes`)
    so existing log pipelines stay byte-compatible."""
    values: dict = {}
    for label, nodes in (groups or {}).items():
        node_stats = [s for s in (_node_stats(n) for n in nodes)
                      if s is not None]
        if not node_stats:
            continue
        entries = max([v for v in (_num(s.get("log_entries"))
                                   for s in node_stats) if v is not None],
                      default=0)
        values[f'Raft.LogEntries{{group="{label}"}}'] = entries
        elections = sum(v for v in (_num(s.get("elections_total"))
                                    for s in node_stats) if v is not None)
        values[f'Raft.Elections{{group="{label}"}}'] = elections
    if sharded is not None:
        try:
            heat = sharded.heat_stats()
        except Exception:
            heat = None
        if isinstance(heat, dict):
            values["Shard.SkewIndex"] = heat.get("skew_index", 0.0)
            values["CoordinatorLog.Bytes"] = \
                heat.get("coordinator_log_bytes", 0)
    if store is not None:
        store.record_many(values, t=t)
    if watch is not None:
        watch.observe_many({k: v for k, v in values.items()
                            if k.startswith("Raft.LogEntries")
                            or k == "CoordinatorLog.Bytes"})
    if resources is not None:
        try:
            values.update(resources.sample(store=store, watch=watch, t=t))
        except Exception:
            pass   # a broken probe must not stall the consensus sampler
    return values


def ledger_raft_fields(groups: dict, round_samples=None) -> dict:
    """Flat ``ledger_raft_*`` artifact fields (benchguard-locked; always
    present with typed defaults — the group_commit_fields discipline).
    ``round_samples`` is the pooled list of exact per-batch consensus
    round durations (GroupCommitter.round_samples() across committers),
    the measured side of the attribution-sum validity probe."""
    pooled: dict = {}
    for nodes in (groups or {}).values():
        for comp, values in pool_attribution(nodes).items():
            pooled.setdefault(comp, []).extend(values)
    pct = pooled_percentiles(pooled)
    out: dict = {}
    for comp in ATTRIBUTION_COMPONENTS:
        stats = pct.get(comp) or {}
        out[f"ledger_raft_{comp}_ms_p50"] = round(
            float(stats.get("p50_ms", 0.0)), 4)
        out[f"ledger_raft_{comp}_ms_p99"] = round(
            float(stats.get("p99_ms", 0.0)), 4)
    total = pct.get("total") or {}
    out["ledger_raft_attrib_samples"] = int(total.get("n", 0))
    out["ledger_raft_attrib_sum_ms_p50"] = round(
        float(total.get("p50_ms", 0.0)), 4)
    rounds = [v for v in (_num(x) for x in (round_samples or ()))
              if v is not None]
    out["ledger_raft_round_ms_p50"] = round(
        _pctl(sorted(rounds), 0.50) * 1000.0, 4) if rounds else 0.0
    out["ledger_raft_elections_total"] = int(sum(
        v for g in (groups or {}).values()
        for v in (_num((_node_stats(n) or {}).get("elections_total"))
                  for n in g) if v is not None))
    # compaction rollup (ISSUE 20): typed-default ints over every replica
    # of every group — zeros on a native fleet (absent per-node stats),
    # real counts on compacting python replicas
    all_stats = [s for g in (groups or {}).values()
                 for s in (_node_stats(n) for n in g) if s is not None]

    def _agg(field, fn):
        vals = [v for v in (_num(s.get(field)) for s in all_stats)
                if v is not None]
        return int(fn(vals)) if vals else 0

    out["ledger_raft_snapshot_index"] = _agg("snapshot_index", max)
    out["ledger_raft_snapshots_taken"] = _agg("snapshots_taken", sum)
    out["ledger_raft_installs_sent"] = _agg("installs_sent", sum)
    out["ledger_raft_installs_received"] = _agg("installs_received", sum)
    out["ledger_raft_snapshot_bytes"] = _agg("snapshot_bytes", max)
    return out
