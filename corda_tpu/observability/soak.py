"""Soak observatory: drift-gated endurance runs over the ledger harness.

Every measurement plane so far observes seconds-to-minutes; the
production failure modes ROADMAP item 5 names — raft logs and
CoordinatorLogs growing unboundedly, span-ring/timeline eviction under
sustained churn, SLO budgets over multi-window horizons — only appear at
tens-of-minutes timescales. The soak mode runs the real open-loop ledger
scenario (observability/ledger_harness.py) for ``minutes`` at a steady
offered rate with **chaos windows recurring on a schedule** (not the
one-shot three-window script), and layers four soak-only instruments on
top via the harness's observer hook:

- **resource accounting** (resprof.ResourceRegistry): every
  bounded/growing structure in the topology registers a size probe —
  raft logs per group, CoordinatorLog bytes, the span ring + its drop
  counter (windowed rate), vault state sets, the staging pool,
  checkpoint stores, reservation maps, the time-series rings themselves,
  process RSS — sampled every second into the retained time-series plane
  and fed through the leak detector at the end: per-structure verdict
  ``bounded | growing | leaking`` with slope and projected doubling time;
- **subsystem CPU attribution** (resprof.SubsystemProfiler): wall-clock
  stack sampling mapped to the component taxonomy, so the artifact says
  where interpreter CPU went on the commit path (the ROADMAP's
  native-raft decision input);
- **phase segmentation**: per-minute committed-rate / tail-latency /
  error-budget rows (``soak_phases``), the series the drift gates fit;
- **mid-run invariant re-checks**: every ``invariant_check_s`` the
  exactly-once property is re-verified over everything committed so far
  (no replica may attribute a consumed ref to the wrong transaction) —
  a soak that only checks invariants at the end can run broken for 29
  of its 30 minutes.

**Drift gates**: robust (Theil–Sen) slopes over the per-phase committed
rate and e2e p99, expressed as %-of-mean per minute against declared
bounds, plus the leak verdicts and the invariant re-checks, become
BENCH-INVALID probes in ``bench.py --soak`` and the ``SOAK_REQUIRED`` /
``guard_soak`` gate in tools/benchguard.py. ``tools/scenario.py --soak
MINUTES`` runs the same thing interactively and exits 1 on any breach.

Surfaces: ``soak_report()`` behind ``/debug/soak`` + the rpc op, the
``Resource.*`` series on ``/api/timeseries``, and soak sections in
``consensus_stat`` / ``fleetstat``.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from .resprof import (ResourceRegistry, SubsystemProfiler, leak_verdict,
                      process_rss_bytes, set_resources, theil_sen_slope)

__all__ = [
    "SoakConfig", "SoakObserver", "run_soak", "soak_report",
    "soak_drift_fields", "verdict_rows", "get_cpu_profiler",
    "set_cpu_profiler",
]


def _pctl(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


@dataclass
class SoakConfig:
    """Knobs for one endurance run. The default is the measured shape —
    a ≥10-minute sharded-notary soak with recurring chaos; ``smoke()``
    is the ~20 s injected-everything tier-1 shape that asserts the same
    artifact schema without the wall clock."""

    minutes: float = 10.0
    parties: int = 6
    coins_per_party: int = 3
    #: steady offered load, held WELL below the flows-scenario capacity
    #: so a throughput drift reads as degradation, not saturation noise
    rate_tx_per_sec: float = 6.0
    node_concurrency: int = 2
    shards: int = 2
    cross_shard_pct: float = 0.25
    settle_fraction: float = 0.10
    seed: int = 7
    #: recurring chaos: one window (cycling partition → leader-kill →
    #: append-drop) every period, each ``chaos_window_s`` wide
    chaos: bool = True
    chaos_period_s: float = 75.0
    chaos_window_s: float = 2.5
    chaos_append_drop_p: float = 0.15
    #: bounded-state consensus (ISSUE 20): arm raft snapshot compaction
    #: and CoordinatorLog GC so the endurance run's log structures
    #: sawtooth instead of growing monotonically. With these set the
    #: RaftLog/CoordinatorLog probes are declared ``bounded`` with the
    #: 2×-threshold sawtooth cap as their bound — sustained growth past
    #: it is a LEAK verdict, which the soak gate fails on.
    raft_snapshot_entries: int | None = 64
    coordlog_compact_bytes: int | None = 65536
    #: phase (segment) length for the per-minute artifact series
    phase_s: float = 60.0
    #: resource-probe sampling cadence into the retained plane
    sample_interval_s: float = 1.0
    #: mid-run exactly-once re-check cadence
    invariant_check_s: float = 60.0
    cpu_sample_interval_s: float = 0.02
    provider_timeout_s: float = 5.0
    #: declared drift gates, %-of-mean per minute over the phase series:
    #: committed rate may not trend below the floor, e2e p99 not above
    #: the ceiling. Full runs enforce them; smoke records them only.
    throughput_gate_pct_per_min: float = -3.0
    p99_gate_pct_per_min: float = 6.0
    mode: str = "soak"

    @staticmethod
    def smoke(seed: int = 7) -> "SoakConfig":
        """Tier-1 shape: ~20 s of real load, everything else accelerated
        (5 s phases, 6 s chaos period, 4 s invariant cadence) so the
        artifact carries the full schema — phases, verdicts, CPU shares,
        drift slopes, re-checks — without the endurance wall clock."""
        return SoakConfig(
            minutes=0.35, parties=3, coins_per_party=2,
            rate_tx_per_sec=6.0, shards=2, cross_shard_pct=0.25,
            settle_fraction=0.0, seed=seed,
            chaos_period_s=6.0, chaos_window_s=0.8,
            phase_s=5.0, sample_interval_s=0.4, invariant_check_s=4.0,
            cpu_sample_interval_s=0.01,
            raft_snapshot_entries=8, coordlog_compact_bytes=4096,
            mode="soak-smoke")


class _RecurringChaos:
    """Chaos that recurs for as long as the run does: every
    ``period_s`` one window arms, cycling partition-follower →
    leader-kill → append-drop, each ``window_s`` wide. Same fault rules
    as the one-shot ledger schedule; annotations carry the cycle index
    so a drift in phase 7 reads against the window that caused it."""

    KINDS = ("partition_follower", "leader_kill", "append_drop")

    def __init__(self, cfg: SoakConfig, raft_nodes):
        self.cfg = cfg
        self.raft_nodes = raft_nodes
        self.cycle = 0
        self._active = None        # {"kind", "end_s", "detail", "start_s"}
        self.annotations: list[dict] = []
        #: first window waits one full period — phase 0 measures the
        #: undisturbed baseline the drift fit anchors on
        self._next_start = cfg.chaos_period_s

    def _rules(self, kind: str):
        from ..consensus.raft import LEADER
        from ..utils.faults import FaultRule
        if kind == "append_drop":
            return ([FaultRule("raft.append", "drop",
                               probability=self.cfg.chaos_append_drop_p)],
                    f"p={self.cfg.chaos_append_drop_p}")
        leaders = [rn.node_id for rn in self.raft_nodes
                   if getattr(rn, "role", None) == LEADER]
        followers = [rn.node_id for rn in self.raft_nodes
                     if rn.node_id not in leaders]
        if kind == "leader_kill" and leaders:
            target = leaders[0]
        else:
            target = (followers or [self.raft_nodes[-1].node_id])[0]
        return ([FaultRule("net.send", "drop", detail=f"{target}->*"),
                 FaultRule("net.send", "drop", detail=f"*->{target}")],
                target)

    def tick(self, now_s: float) -> None:
        from ..utils import faults
        if self._active is not None:
            if now_s >= self._active["end_s"]:
                inj = faults.active()
                faults.disarm()
                self._active["faults_fired"] = len(inj.log) if inj else 0
                self._active["end_s"] = round(now_s, 3)
                self.annotations.append(self._active)
                self._active = None
            return
        if now_s < self._next_start:
            return
        kind = self.KINDS[self.cycle % len(self.KINDS)]
        rules, detail = self._rules(kind)
        inj = faults.FaultInjector(seed=self.cfg.seed + self.cycle)
        for r in rules:
            inj.add(r)
        faults.arm(inj)
        self._active = {"kind": kind, "cycle": self.cycle,
                        "start_s": round(now_s, 3),
                        "end_s": now_s + self.cfg.chaos_window_s,
                        "detail": detail}
        self.cycle += 1
        self._next_start += self.cfg.chaos_period_s

    def close(self, now_s: float) -> None:
        from ..utils import faults
        if self._active is not None:
            inj = faults.active()
            faults.disarm()
            self._active["faults_fired"] = len(inj.log) if inj else 0
            self._active["end_s"] = round(now_s, 3)
            self.annotations.append(self._active)
            self._active = None


def verdict_rows(rings: list) -> list:
    """Pick the ring a leak fit should run over: the coarsest resolution
    holding at least 5 points (the 60 s ring on a real soak), falling
    back to the best-populated finer ring on short/smoke runs."""
    best: list = []
    for ring in rings or ():
        points = ring.get("points") if isinstance(ring, dict) else None
        if not isinstance(points, list):
            continue
        if len(points) >= 5:
            best = points          # rings come finest-first: keep coarsest
        elif not best and len(points) > len(best):
            best = points
    if not best:
        for ring in rings or ():
            points = ring.get("points") if isinstance(ring, dict) else None
            if isinstance(points, list) and len(points) > len(best):
                best = points
    return best


def soak_drift_fields(phases: list, throughput_gate: float,
                      p99_gate: float) -> dict:
    """Theil–Sen slopes over the per-phase committed rate and e2e p99,
    normalized to %-of-mean per minute, checked against the declared
    gates. Fewer than 3 complete phases is honest zero drift (a smoke
    run's 4×5 s phases still exercise the fit)."""
    rate_pts = [(p["t_s"], p["committed_tx_per_sec"]) for p in phases
                if isinstance(p.get("committed_tx_per_sec"), (int, float))]
    p99_pts = [(p["t_s"], p["e2e_ms_p99"]) for p in phases
               if isinstance(p.get("e2e_ms_p99"), (int, float))
               and p.get("e2e_ms_p99", 0) > 0]

    def pct_per_min(pts) -> float:
        if len(pts) < 3:
            return 0.0
        mean = sum(v for _t, v in pts) / len(pts)
        if mean <= 0:
            return 0.0
        return round(theil_sen_slope(pts) / mean * 100.0 * 60.0, 3)

    tp = pct_per_min(rate_pts)
    p99 = pct_per_min(p99_pts)
    return {
        "soak_throughput_slope_pct_per_min": tp,
        "soak_p99_slope_pct_per_min": p99,
        "soak_throughput_gate_pct_per_min": throughput_gate,
        "soak_p99_gate_pct_per_min": p99_gate,
        "soak_drift_ok": tp >= throughput_gate and p99 <= p99_gate,
    }


class SoakObserver:
    """The harness hook object (``LedgerScenarioConfig.observer``):
    ``on_start(ctx)`` registers the topology's resource probes and
    starts the CPU profiler, ``on_tick(now_rel)`` runs on every driver
    iteration (same thread as the workload bookkeeping — no locking
    against ``latencies``/``final_counts`` needed), ``finalize(report)``
    computes the verdicts/drift/CPU fields into the artifact, and
    ``close()`` is the finally-block teardown."""

    def __init__(self, cfg: SoakConfig):
        self.cfg = cfg
        self.resources = ResourceRegistry()
        self.profiler = SubsystemProfiler(
            interval_s=cfg.cpu_sample_interval_s)
        self.chaos: _RecurringChaos | None = None
        self.phases: list[dict] = []
        self.invariant_checks: list[dict] = []
        self._ctx: dict = {}
        self._prev_resources = None
        self._prev_profiler = None
        self._last_sample = 0.0
        self._last_invariant = 0.0
        self._phase_start = 0.0
        self._phase_committed = 0
        self._phase_lat_base = 0
        self._started_monotonic = 0.0

    # -- harness hooks -------------------------------------------------------
    def on_start(self, ctx: dict) -> None:
        self._ctx = ctx
        cfg = self.cfg
        if cfg.chaos:
            self.chaos = _RecurringChaos(cfg, ctx["raft_nodes"])
        self._register_probes(ctx)
        self._prev_resources = set_resources(self.resources)
        self._prev_profiler = set_cpu_profiler(self.profiler)
        self.profiler.start()
        self._started_monotonic = time.monotonic()
        # t=0 baseline sample so every probe's series exists immediately
        self.resources.sample(store=ctx.get("ts_store"),
                              watch=ctx.get("growth"))

    def on_tick(self, now_rel: float) -> None:
        if self.chaos is not None:
            self.chaos.tick(now_rel)
        if now_rel - self._last_sample >= self.cfg.sample_interval_s:
            self._last_sample = now_rel
            try:
                self.resources.sample(store=self._ctx.get("ts_store"),
                                      watch=self._ctx.get("growth"))
            except Exception:
                pass               # observability must never stall the run
        if now_rel - self._phase_start >= self.cfg.phase_s:
            self._seal_phase(now_rel)
        if now_rel - self._last_invariant >= self.cfg.invariant_check_s:
            self._last_invariant = now_rel
            self.invariant_checks.append(self._check_invariants(now_rel))

    def on_drain(self, end_rel: float) -> None:
        """Workload drained: stop recurring chaos and seal the partial
        phase so ``soak_phases`` accounts for every committed op."""
        if self.chaos is not None:
            self.chaos.close(end_rel)
        if end_rel - self._phase_start > 0.5:
            self._seal_phase(end_rel)
        self.invariant_checks.append(self._check_invariants(end_rel))

    def close(self) -> None:
        self.profiler.stop()
        set_resources(self._prev_resources)
        set_cpu_profiler(self._prev_profiler)

    # -- probes --------------------------------------------------------------
    def _register_probes(self, ctx: dict) -> None:
        """Wire every structure the topology owns into the accounting
        plane. Probes are defensive closures over live objects; a probe
        whose surface is absent simply never registers."""
        reg = self.resources
        cfg = ctx.get("cfg")
        # bounded-state consensus (ISSUE 20): with compaction armed the
        # raft log's contract flips from "grows until GC" to a bounded
        # sawtooth — declare it so, with 2× the snapshot threshold as
        # the cap, and the leak gate enforces the invariant the whole
        # soak long. Without compaction the honest declaration stays
        # "grows" (the pre-r06 unbounded-log hazard, named in ROADMAP).
        snap_thr = getattr(cfg, "raft_snapshot_entries", None)
        for label, nodes in (ctx.get("raft_groups") or {}).items():
            def probe(nodes=nodes):
                return max((len(getattr(rn.state, "log", ()))
                            for rn in nodes), default=0)
            if snap_thr:
                reg.register(f"RaftLog.{label}", probe, kind="bounded",
                             bound=2.0 * snap_thr)
            else:
                reg.register(f"RaftLog.{label}", probe, kind="grows")
        sharded = ctx.get("sharded")
        if sharded is not None:
            log = getattr(sharded, "log", None)
            if log is not None:
                probe = lambda log=log: getattr(log, "bytes_appended", 0)
                gc_thr = getattr(cfg, "coordlog_compact_bytes", None)
                if gc_thr:
                    reg.register("CoordinatorLog.Bytes", probe,
                                 kind="bounded", bound=2.0 * gc_thr)
                else:
                    reg.register("CoordinatorLog.Bytes", probe,
                                 kind="grows")
        from .tracing import get_tracer
        ring = getattr(get_tracer(), "ring", None)
        if ring is not None:
            reg.register("Tracing.SpanRing", lambda r=ring: len(r),
                         kind="bounded",
                         bound=getattr(ring, "capacity", None))
            reg.register("Tracing.SpansDropped",
                         lambda r=ring: getattr(r, "dropped", 0),
                         kind="grows", rate=True)
        verifier = ctx.get("verifier")
        rlog = getattr(verifier, "request_log", None)
        if rlog is not None:
            reg.register("Requests.Timelines", lambda rl=rlog: len(rl),
                         kind="bounded",
                         bound=getattr(rlog, "capacity", None))
            reg.register("Requests.TimelineEvictions",
                         lambda rl=rlog: getattr(rl, "dropped", 0),
                         kind="grows", rate=True)
        network = ctx.get("network")
        if network is not None:
            def vault_states(net=network):
                total = 0
                for node in getattr(net, "nodes", ()):
                    vault = getattr(node.services, "vault", None)
                    total += len(getattr(vault, "_unconsumed", ())) \
                        + len(getattr(vault, "_consumed", ()))
                return total
            reg.register("Vault.States", vault_states, kind="grows")

            def checkpoints(net=network):
                total = 0
                for node in getattr(net, "nodes", ()):
                    smm = getattr(node, "smm", None)
                    store = getattr(smm, "checkpoints", None)
                    total += len(getattr(store, "_checkpoints", ()))
                return total
            reg.register("Checkpoints.Stored", checkpoints, kind="bounded")
        machines = ctx.get("machines")
        if machines:
            reg.register(
                "Shard.ReservedRefs",
                lambda ms=machines: sum(len(getattr(m, "_reserved", ()))
                                        for m in ms),
                kind="bounded")
        try:
            from ..ops.staging import get_staging_pool
            pool = get_staging_pool()
            reg.register(
                "Staging.Buffers",
                lambda p=pool: sum(len(v)
                                   for v in getattr(p, "_free", {}).values())
                + len(getattr(p, "_attached", ())),
                kind="bounded")
        except Exception:
            pass
        store = ctx.get("ts_store")
        if store is not None:
            def ts_buckets(s=store):
                total = 0
                for series in getattr(s, "_series", {}).values():
                    for ring_ in series.rings:
                        total += len(ring_.closed)
                return total
            # bounded by construction at sum(ring capacities) × series,
            # but it FILLS over the first coarsest-horizon: grows
            reg.register("Timeseries.Buckets", ts_buckets, kind="grows")
        reg.register("Process.RSSBytes", process_rss_bytes, kind="grows")

    # -- phase segmentation --------------------------------------------------
    def _seal_phase(self, now_rel: float) -> None:
        ctx = self._ctx
        committed = ctx["final_counts"]["committed"]
        latencies = ctx["latencies"]
        window = sorted(latencies[self._phase_lat_base:])
        dt = max(1e-9, now_rel - self._phase_start)
        status = None
        slo = ctx.get("slo")
        if slo is not None:
            try:
                status = slo.status()
            except Exception:
                status = None
        budgets = [o.get("error_budget_pct")
                   for o in (status or {}).get("objectives", {}).values()
                   if isinstance(o, dict)]
        budgets = [b for b in budgets
                   if isinstance(b, (int, float)) and not isinstance(b, bool)]
        self.phases.append({
            "phase": len(self.phases),
            "t_s": round(self._phase_start, 3),
            "duration_s": round(dt, 3),
            "committed": committed - self._phase_committed,
            "committed_tx_per_sec":
                round((committed - self._phase_committed) / dt, 3),
            "e2e_ms_p50": round(_pctl(window, 0.50) * 1000, 3),
            "e2e_ms_p99": round(_pctl(window, 0.99) * 1000, 3),
            "slo_error_budget_pct":
                round(min(budgets), 3) if budgets else 100.0,
        })
        self._phase_start = now_rel
        self._phase_committed = committed
        self._phase_lat_base = len(latencies)

    # -- mid-run invariants --------------------------------------------------
    def _check_invariants(self, now_rel: float) -> dict:
        """Exactly-once over everything committed SO FAR: a replica that
        has applied a consumed ref must attribute it to the transaction
        that committed it (absence is fine mid-run — followers lag), and
        the reservation maps carry only in-flight work. Runs on the
        driver thread, so the committed list is stable underneath it."""
        from ..consensus.sharded_uniqueness import shard_of
        ctx = self._ctx
        shard_machines = ctx["shard_machines"]
        n_shards = len(shard_machines)
        conflicts = 0
        checked = 0
        for tx_id, refs in list(ctx["committed_notarised"]):
            for ref in refs:
                for m in shard_machines[shard_of(ref, n_shards)]:
                    details = getattr(m, "_map", {}).get(ref)
                    checked += 1
                    if details is not None and details.consuming_tx != tx_id:
                        conflicts += 1
        reserved = sum(len(getattr(m, "_reserved", ()))
                       for m in ctx.get("machines", ()))
        return {"t_s": round(now_rel, 3), "checked": checked,
                "conflicts": conflicts, "reserved_inflight": reserved,
                "ok": conflicts == 0}

    # -- artifact ------------------------------------------------------------
    def finalize(self, report: dict) -> None:
        cfg = self.cfg
        ctx = self._ctx
        store = ctx.get("ts_store")
        kinds = self.resources.kinds()
        bounds = self.resources.bounds()
        # one closing read AFTER the workload drained: it lands the
        # quiescent level in the retained series, carries the final
        # windowed ``.Rate`` values, and lets the verdict distinguish
        # in-flight backlog (drains to ~0) from a real leak (persists)
        last = self.resources.sample(store=store)
        verdicts: dict = {}
        if store is not None:
            snap = store.snapshot()
            for name, kind in sorted(kinds.items()):
                rings = snap["series"].get(f"Resource.{name}")
                verdicts[name] = leak_verdict(
                    verdict_rows(rings or []), kind=kind,
                    bound=bounds.get(name),
                    final_level=last.get(f"Resource.{name}"))
        leaking = sorted(n for n, v in verdicts.items()
                         if v["verdict"] == "leaking")
        cpu = self.profiler.snapshot()
        report["soak"] = True
        report["soak_minutes"] = cfg.minutes
        report["soak_phase_s"] = cfg.phase_s
        report["soak_phases"] = self.phases
        report["soak_chaos_cycles"] = \
            self.chaos.cycle if self.chaos is not None else 0
        report["soak_chaos_windows"] = \
            self.chaos.annotations if self.chaos is not None else []
        report["soak_resources"] = {
            n: round(v, 2) for n, v in sorted(
                self.resources.sizes().items())}
        report["soak_leak_verdicts"] = verdicts
        report["soak_leaking"] = leaking
        report["soak_leak_ok"] = not leaking
        report["soak_invariant_checks"] = self.invariant_checks
        report["soak_invariant_recheck_count"] = len(self.invariant_checks)
        report["soak_invariant_ok"] = bool(self.invariant_checks) and all(
            c["ok"] for c in self.invariant_checks)
        report["soak_cpu_shares_pct"] = cpu["shares_pct"]
        report["soak_cpu_share_sum_pct"] = cpu["share_sum_pct"]
        report["soak_cpu_samples"] = cpu["samples"]
        report["soak_cpu_busy_frac"] = cpu["busy_frac"]
        report["soak_cpu_top_commit_path"] = cpu["top_commit_path"] or ""
        # windowed churn rates (satellite: cumulative-only counters are
        # useless on a soak) — the most recent sampled Resource.*.Rate
        report["soak_spans_dropped_rate_per_s"] = round(
            last.get("Resource.Tracing.SpansDropped.Rate", 0.0), 3)
        report["soak_timeline_evictions_rate_per_s"] = round(
            last.get("Resource.Requests.TimelineEvictions.Rate", 0.0), 3)
        report.update(soak_drift_fields(
            self.phases[:-1] if len(self.phases) > 3 else self.phases,
            cfg.throughput_gate_pct_per_min, cfg.p99_gate_pct_per_min))
        report["mode"] = cfg.mode


def run_soak(cfg: SoakConfig | None = None) -> dict:
    """Build the endurance-shaped ledger scenario and run it under a
    :class:`SoakObserver`. The workload length IS the soak length:
    ``minutes × 60 × rate`` operations on the open-loop schedule."""
    from .ledger_harness import LedgerScenarioConfig, run_ledger_scenario

    cfg = cfg if cfg is not None else SoakConfig()
    operations = max(8, int(cfg.minutes * 60.0 * cfg.rate_tx_per_sec))
    lcfg = LedgerScenarioConfig(
        parties=cfg.parties, operations=operations,
        coins_per_party=cfg.coins_per_party,
        rate_tx_per_sec=cfg.rate_tx_per_sec,
        node_concurrency=cfg.node_concurrency,
        seed=cfg.seed, chaos=False,       # the observer drives recurrence
        settle_fraction=cfg.settle_fraction,
        shards=cfg.shards, cross_shard_pct=cfg.cross_shard_pct,
        raft_snapshot_entries=cfg.raft_snapshot_entries,
        coordlog_compact_bytes=cfg.coordlog_compact_bytes,
        provider_timeout_s=cfg.provider_timeout_s,
        max_duration_s=cfg.minutes * 60.0 + 120.0,
        mode=cfg.mode, observer=SoakObserver(cfg))
    return run_ledger_scenario(lcfg)


# ---------------------------------------------------------------------------
# live surface: /debug/soak + rpc soak_report
# ---------------------------------------------------------------------------

_prof_lock = threading.Lock()
_active_profiler: SubsystemProfiler | None = None


def get_cpu_profiler() -> "SubsystemProfiler | None":
    with _prof_lock:
        return _active_profiler


def set_cpu_profiler(profiler: "SubsystemProfiler | None"
                     ) -> "SubsystemProfiler | None":
    global _active_profiler
    with _prof_lock:
        prev, _active_profiler = _active_profiler, profiler
        return prev


def soak_report() -> dict:
    """The /debug/soak payload: every registered structure's live size,
    declared kind, and leak verdict over the retained ``Resource.*``
    series, plus the CPU-attribution snapshot when a profiler is
    running. Well-formed and empty on a node with no probes — scraping
    any node is safe."""
    from .resprof import get_resources
    from .timeseries import get_timeseries
    reg = get_resources()
    kinds = reg.kinds()
    sizes = reg.sizes()
    bounds = reg.bounds()
    snap = get_timeseries().snapshot(
        names=[f"Resource.{n}" for n in kinds]) if kinds else {"series": {}}
    resources = {}
    for name in sorted(kinds):
        rings = snap["series"].get(f"Resource.{name}")
        resources[name] = {
            "size": sizes.get(name),
            "kind": kinds[name],
            **leak_verdict(verdict_rows(rings or []), kind=kinds[name],
                           bound=bounds.get(name)),
        }
    prof = get_cpu_profiler()
    return {"resources": resources,
            "leaking": sorted(n for n, r in resources.items()
                              if r["verdict"] == "leaking"),
            "cpu": prof.snapshot() if prof is not None else None}
