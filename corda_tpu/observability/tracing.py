"""Span-based tracer with explicit context propagation.

Design (SURVEY.md §5 tracing; the reference's analog is YourKit/JMX on the
verifier JVM — this is the in-framework replacement):

- A *trace* is one logical operation end-to-end (a transaction's verify, a
  flow run) identified by a random ``trace_id``; a *span* is one timed step
  inside it (enqueue wait, batch flush, device dispatch, resolve).
- Context propagation is EXPLICIT: a ``SpanContext`` (or its wire-friendly
  ``(trace_id, span_id)`` tuple) is passed as an argument across threads
  and components — the flow state machine hands it to the verifier service,
  the service hands it to the SignatureBatcher, the batcher carries it from
  the dispatcher thread to the finisher thread. No thread-locals, so spans
  never mis-attach when work hops threads (the whole pipeline is
  cross-thread).
- The default tracer is a NO-OP singleton: every instrumentation site costs
  one module-global read plus a method call returning a shared singleton,
  no allocation, no locks, no threads. ``enable_tracing()`` swaps in a real
  ``Tracer`` backed by a bounded ``SpanRing`` (ring.py).

Zero-dependency, thread-safe, stdlib-only.
"""
from __future__ import annotations

import os
import time

from .ring import SpanRing


def _new_id() -> str:
    return os.urandom(8).hex()


class SpanContext:
    """Immutable (trace_id, span_id) pair — the unit that travels across
    threads, futures, and (in-memory) messages."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        object.__setattr__(self, "trace_id", trace_id)
        object.__setattr__(self, "span_id", span_id)

    def __setattr__(self, *a):
        raise AttributeError("SpanContext is immutable")

    def as_tuple(self) -> tuple:
        return (self.trace_id, self.span_id)

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id})"


def _parent_ids(parent) -> tuple[str | None, str | None]:
    """Accept a SpanContext, a Span, a (trace_id, span_id) tuple (the
    messaging wire form), or None."""
    if parent is None:
        return None, None
    if isinstance(parent, SpanContext):
        return parent.trace_id, parent.span_id
    if isinstance(parent, Span):
        return parent.trace_id, parent.span_id
    if isinstance(parent, (tuple, list)) and len(parent) == 2:
        return parent[0], parent[1]
    raise TypeError(f"Bad span parent: {parent!r}")


class Span:
    """One timed operation. Use as a context manager, or call ``finish()``
    explicitly for spans that outlive a lexical scope (a flow's run span,
    a raft submission awaiting commit). Recording happens at finish time —
    an unfinished span is never visible in the ring."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_s",
                 "duration_s", "tags", "_ring", "_t0", "_done")

    def __init__(self, ring: SpanRing, name: str, trace_id: str,
                 parent_id: str | None, tags: dict):
        self._ring = ring
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.tags = tags
        self.start_s = time.time()
        self._t0 = time.perf_counter()
        self.duration_s = 0.0
        self._done = False

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_tag(self, key: str, value) -> "Span":
        self.tags[key] = value
        return self

    def finish(self) -> None:
        if self._done:
            return
        self._done = True
        self.duration_s = time.perf_counter() - self._t0
        self._ring.record(self.to_dict())

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_s": self.start_s, "duration_s": self.duration_s,
                "tags": dict(self.tags)}

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.tags.setdefault("error", f"{exc_type.__name__}: {exc}")
        self.finish()
        return False


class _NoopSpan:
    """Shared do-nothing span: every method is a constant-time no-op and
    ``context()`` is None, so disabled tracing propagates nothing."""

    __slots__ = ()

    def context(self):
        return None

    def set_tag(self, key, value):
        return self

    def finish(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class NoopTracer:
    """The default: near-free when tracing is off. All span factories return
    the shared NOOP_SPAN; nothing is ever recorded."""

    enabled = False
    ring = None

    def span(self, name, parent=None, **tags):
        return NOOP_SPAN

    def record(self, name, parent=None, start_s=None, duration_s=0.0, **tags):
        return None

    def ingest(self, span_dict) -> None:
        return None

    def spans(self, trace_id=None, limit=None):
        return []

    def trace(self, trace_id):
        return []

    def traces(self, limit_spans=None):
        return {}


NOOP_TRACER = NoopTracer()


class Tracer:
    """Recording tracer over a bounded SpanRing."""

    enabled = True

    def __init__(self, capacity: int = 8192):
        self.ring = SpanRing(capacity)

    def span(self, name: str, parent=None, **tags) -> Span:
        """Open a live span. ``parent`` is a SpanContext / Span /
        (trace_id, span_id) tuple, or None to start a fresh trace."""
        trace_id, parent_id = _parent_ids(parent)
        if trace_id is None:
            trace_id = _new_id()
        return Span(self.ring, name, trace_id, parent_id, tags)

    def record(self, name: str, parent=None, start_s: float | None = None,
               duration_s: float = 0.0, **tags) -> SpanContext:
        """Record an already-completed span retroactively (e.g. enqueue
        waits, measured between timestamps taken under a lock). Returns its
        context so children can still be parented to it."""
        trace_id, parent_id = _parent_ids(parent)
        if trace_id is None:
            trace_id = _new_id()
        span_id = _new_id()
        self.ring.record({
            "name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent_id,
            "start_s": time.time() if start_s is None else start_s,
            "duration_s": duration_s, "tags": dict(tags)})
        return SpanContext(trace_id, span_id)

    def ingest(self, span_dict) -> None:
        """Record a FINISHED span produced in another process (a verifier
        worker's dict-built span, shipped back piggybacked on a reply or a
        load report). The dict is normalized defensively — a malformed or
        truncated span from an old worker is dropped, never raises."""
        if not isinstance(span_dict, dict):
            return
        d = dict(span_dict)
        if not d.get("trace_id") or not d.get("span_id"):
            return
        d.setdefault("name", "?")
        d.setdefault("parent_id", None)
        d.setdefault("start_s", 0.0)
        d.setdefault("duration_s", 0.0)
        if not isinstance(d.get("tags"), dict):
            d["tags"] = {}
        self.ring.record(d)

    def spans(self, trace_id=None, limit=None) -> list[dict]:
        return self.ring.snapshot(trace_id=trace_id, limit=limit)

    def trace(self, trace_id: str) -> list[dict]:
        return self.ring.snapshot(trace_id=trace_id)

    def traces(self, limit_spans=None) -> dict:
        return self.ring.traces(limit_spans=limit_spans)


def make_span_dict(name: str, parent, start_s: float, duration_s: float,
                   **tags) -> dict:
    """Build a finished span AS A DICT, bypassing the process tracer — the
    worker half of cross-process stitching. A worker process (whose own
    tracer is usually the no-op default) still produces real spans for any
    request that arrived carrying a trace context; they ship back over the
    wire and the node's tracer ``ingest``s them into its ring. ``parent``
    is the wire ``(trace_id, span_id)`` tuple from the request."""
    trace_id, parent_id = _parent_ids(parent)
    if trace_id is None:
        trace_id = _new_id()
    return {"name": name, "trace_id": trace_id, "span_id": _new_id(),
            "parent_id": parent_id, "start_s": start_s,
            "duration_s": duration_s,
            "tags": {k: v for k, v in tags.items() if v is not None}}


# ---------------------------------------------------------------------------
# Process-global tracer seam
# ---------------------------------------------------------------------------

_TRACER = NOOP_TRACER


def get_tracer():
    """The process tracer — instrumentation sites call this per operation
    (NOT at import time) so enable/disable takes effect immediately."""
    return _TRACER


def set_tracer(tracer) -> None:
    global _TRACER
    _TRACER = tracer


def enable_tracing(capacity: int = 8192) -> Tracer:
    """Install (and return) a recording tracer."""
    tracer = Tracer(capacity)
    set_tracer(tracer)
    return tracer


def disable_tracing() -> None:
    """Back to the no-op tracer; previously recorded spans are dropped with
    the old tracer's ring."""
    set_tracer(NOOP_TRACER)
